"""Sans-io consensus state machine: ``(event, now) -> [effects]``.

``CoreStateMachine`` wraps the REAL :class:`~hotstuff_tpu.consensus.core.Core`
handlers — the same dispatch table (``Core.HANDLERS``), the same voting
rules, the same certificate paths — behind deterministic IO adapters:

- the network seam is an :class:`Outbox` that records sends instead of
  opening sockets;
- the timer is the real :class:`~hotstuff_tpu.consensus.timer.Timer`
  over an injected :class:`~hotstuff_tpu.sim.clock.VirtualClock` (the
  scheduler reads ``timer.deadline`` and fires expiries as events);
- the QC-retry backoff (``Core._call_later``) becomes a ``sched``
  effect instead of a sleeping task;
- the synchronizer and proposer actors are replayed synchronously
  (:class:`SimSynchronizer` mirrors ``consensus/synchronizer.py``'s
  suspend/request/unwind algorithm; the proposer drains ``tx_proposer``
  in-step), because the sim plane has no task scheduler to run them on.

The sans-io contract: every handler invocation must RUN TO COMPLETION
without suspending — all awaits inside resolve synchronously (in-memory
store, inline crypto below ``INLINE_SIG_LIMIT``, non-full queues). The
trampoline (:func:`run_sync`) enforces this: a handler that actually
suspends raises :class:`SimSuspended`, which is a sim-plane bug, never
silently different behavior.

Effects are plain tuples (kept allocation-light — the sweep budget is
tens of microseconds per event):

- ``("send", address, data)`` — one unframed wire message to ``address``
  (exactly the bytes the real ``SimpleSender`` would frame and write);
- ``("sched", delay_s, event)`` — deliver ``event`` back to THIS node
  after ``delay_s`` of virtual time (loopback blocks, QC retries, sync
  re-request ticks);
- ``("commit", block)`` — a block left the core on ``tx_commit``.
"""

from __future__ import annotations

import logging
import os

from hotstuff_tpu import telemetry
from hotstuff_tpu.consensus.config import Committee
from hotstuff_tpu.consensus.core import Core
from hotstuff_tpu.consensus.helper import CHAIN_DEPTH
from hotstuff_tpu.consensus.leader import make_elector
from hotstuff_tpu.consensus.mempool_driver import MempoolDriver
from hotstuff_tpu.consensus.messages import (
    QC,
    Block,
    SeatTable,
    encode_propose,
    encode_state_response,
    encode_sync_request,
    sha512_digest,
)
from hotstuff_tpu.consensus.statesync import (
    SNAPSHOT_KEY,
    Compactor,
    SnapshotError,
    StateSync,
    peek_frontier,
)
from hotstuff_tpu.consensus.proposer import Cleanup as ProposerCleanup
from hotstuff_tpu.consensus.proposer import Make as ProposerMake
from hotstuff_tpu.consensus.timer import Timer
from hotstuff_tpu.crypto import PublicKey, SecretKey, SignatureService
from hotstuff_tpu.store import Store

log = logging.getLogger("sim")

__all__ = ["CoreStateMachine", "Outbox", "SimSuspended", "run_sync"]


class SimSuspended(RuntimeError):
    """A handler suspended on real IO inside the simulation — the sans-io
    contract is broken (e.g. a crypto batch above ``INLINE_SIG_LIMIT``
    went to the worker pool). Fix the seam; do not catch this."""


def run_sync(coro):
    """Drive ``coro`` to completion without an event loop, requiring that
    it never suspends on a pending awaitable."""
    try:
        coro.send(None)
    except StopIteration as e:
        return e.value
    coro.close()
    raise SimSuspended(f"coroutine suspended in simulation: {coro!r}")


class Outbox:
    """``SimpleSender``-shaped effect collector: the Core's network seam.

    ``send``/``broadcast`` append ``("send", address, data)`` effects to
    the machine's effect list; nothing is framed, queued, or written.
    """

    def __init__(self, effects: list) -> None:
        self._effects = effects

    def send(self, address, data: bytes) -> None:
        self._effects.append(("send", address, data))

    def broadcast(self, addresses, data: bytes) -> None:
        for address in addresses:
            self._effects.append(("send", address, data))

    def lucky_broadcast(self, addresses, data: bytes, nodes: int) -> None:
        # Deterministic superset of the real gossip primitive (random
        # sample): the sim favors reproducibility over send-count parity,
        # and no consensus-core path uses this today.
        self.broadcast(addresses, data)

    def shutdown(self) -> None:
        pass


class _SimChannel:
    """Minimal stand-in for the ``asyncio.Queue`` channels between the
    Core and its sibling actors: ``await put`` appends (never suspends),
    and the machine drains by list swap — no loop binding, no
    ``QueueEmpty`` exception per drained-empty check (four of those per
    step added up at sweep rates)."""

    __slots__ = ("_items",)

    def __init__(self) -> None:
        self._items: list = []

    async def put(self, item) -> None:
        self._items.append(item)

    def put_nowait(self, item) -> None:
        self._items.append(item)

    def drain(self):
        if not self._items:
            return ()
        items = self._items
        self._items = []
        return items


class _NotifyingStore(Store):
    """In-memory store that reports writes to the machine — the sim's
    replacement for ``Store.notify_read`` task obligations. The engine
    object survives crash/restart (it is the node's disk)."""

    def __init__(self, engine=None) -> None:
        super().__init__(engine=engine)
        self.on_write = None

    async def write(self, key: bytes, value: bytes) -> None:
        await super().write(key, value)
        if self.on_write is not None:
            self.on_write(key)


class _SimCore(Core):
    """The thin sim driver over the Core handlers: self-scheduling
    becomes an effect instead of a sleeping asyncio task."""

    sim_effects: list  # attached by CoreStateMachine right after init

    def _call_later(self, delay_s: float, item) -> None:
        self.sim_effects.append(("sched", delay_s, item))


class _SimMempoolDriver(MempoolDriver):
    """Payload gate without the PayloadWaiter task: the sim plane has no
    mempool, so blocks carry empty payloads and missing payloads (only
    fabricatable by byzantine traffic) simply fail availability instead
    of parking a waiter.

    ``twin_salts`` (installed by a ``SimWorld(twin_proposal_salt=True)``
    world) lists every instance's salt; a payload digest that matches
    the deterministic per-(instance, round) salt digest is treated as
    available without a store read. Twins runs model clients feeding
    DIFFERENT batches to the two copies of a seat — availability is
    universal by assumption there, digest divergence is the point — so
    the gate must not veto what the safety checker exists to judge."""

    twin_salts: tuple[bytes, ...] = ()

    async def verify(self, block) -> bool:
        for d in block.payload:
            if await self.store.read(d.data) is None and not self._twin_salt_ok(
                d, block.round
            ):
                return False
        return True

    def _twin_salt_ok(self, digest, round_) -> bool:
        if not self.twin_salts:
            return False
        rb = round_.to_bytes(8, "little")
        return any(
            digest == sha512_digest(b"twins-proposal-salt", salt, rb)
            for salt in self.twin_salts
        )


class SimSynchronizer:
    """Effect-based port of ``consensus.Synchronizer``: same suspend /
    solicited-request / chain-unwind algorithm, no tasks. Retries ride
    ``("sched", retry_delay, ("sync_retry", parent))`` effects and the
    ``notify_read`` unwind becomes a store write callback re-injecting
    the suspended blocks as loopback events."""

    _ANCESTOR_CACHE_CAP = 128

    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        effects: list,
        sync_retry_delay_s: float,
        clock,
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self._effects = effects
        self.sync_retry_delay = sync_retry_delay_s
        self._clock = clock
        self._pending = set()  # suspended block digests
        self._requests = {}  # parent Digest -> first-request virtual ts
        self._waiting: dict[bytes, list[Block]] = {}  # parent bytes -> blocks
        self._ancestor_cache: dict[bytes, Block] = {}
        self._floor = None  # truncation floor digest (Lazarus)
        self._floor_round = 0

    # -- Core-facing interface (mirrors consensus.Synchronizer) ----------

    def is_pending(self, digest) -> bool:
        return digest in self._pending

    def requested(self, digest) -> bool:
        return digest in self._requests

    def cache_block(self, block: Block) -> None:
        if len(self._ancestor_cache) >= self._ANCESTOR_CACHE_CAP:
            self._ancestor_cache.clear()
        self._ancestor_cache[block.digest().data] = block

    def note_floor(self, frontier: Block) -> None:
        """Mirror of ``Synchronizer.note_floor``: adopt the truncation
        floor and cancel any suspend/request aimed below it."""
        self._floor = frontier.digest()
        self._floor_round = frontier.round
        parent = frontier.parent()
        self._requests.pop(parent, None)
        self._waiting.pop(parent.data, None)
        self._pending.discard(frontier.digest())
        # Drop cached ancestors below the floor: compaction may have
        # truncated their stored parents (see consensus/synchronizer.py).
        for key in [
            k
            for k, b in self._ancestor_cache.items()
            if b.round < frontier.round
        ]:
            del self._ancestor_cache[key]

    def request_block(self, digest, address) -> None:
        """Mirror of ``Synchronizer.request_block`` (the state-sync
        frontier pull): solicited registration + retry tick; fulfillment
        is cleared by ``on_store_write``."""
        if digest in self._requests:
            return
        telemetry.counter("consensus.sync_requests").inc()
        self._requests[digest] = self._clock()
        if address is not None:
            self._effects.append(
                ("send", address, encode_sync_request(digest, self.name))
            )
        self._effects.append(
            ("sched", self.sync_retry_delay, ("sync_retry", digest))
        )

    def cancel_request(self, digest) -> None:
        """Mirror of ``Synchronizer.cancel_request``: withdraw a direct
        pull that will never be served. The pending ``sync_retry`` effect
        self-cancels (``retry`` checks ``_requests`` membership); blocks
        suspended on the digest (if any) stay registered — only the
        request driving the network retries is withdrawn."""
        self._requests.pop(digest, None)

    async def get_parent_block(self, block: Block):
        if block.qc == QC.genesis():
            return Block.genesis()
        if self._floor is not None and block.digest() == self._floor:
            # Truncation frontier: ancestry is truncated everywhere (see
            # consensus/synchronizer.py for the safety argument).
            return Block.genesis()
        if self._floor_round and block.round <= self._floor_round:
            # Stale delivery at or below the horizon — unservable
            # ancestry, placeholder (see consensus/synchronizer.py).
            return Block.genesis()
        parent_digest = block.parent().data
        cached = self._ancestor_cache.get(parent_digest)
        if cached is not None:
            return cached
        data = await self.store.read(parent_digest)
        if data is not None:
            parent = Block.deserialize(data)
            if len(self._ancestor_cache) >= self._ANCESTOR_CACHE_CAP:
                self._ancestor_cache.clear()
            self._ancestor_cache[parent_digest] = parent
            return parent
        self._suspend(block)
        return None

    async def get_ancestors(self, block: Block):
        b1 = await self.get_parent_block(block)
        if b1 is None:
            return None
        b0 = await self.get_parent_block(b1)
        assert b0 is not None, "we should have all ancestors of delivered blocks"
        return (b0, b1)

    def shutdown(self) -> None:
        pass

    # -- sim plumbing -----------------------------------------------------

    def _suspend(self, block: Block) -> None:
        digest = block.digest()
        if digest in self._pending:
            return
        self._pending.add(digest)
        parent = block.parent()
        self._waiting.setdefault(parent.data, []).append(block)
        if parent not in self._requests:
            telemetry.counter("consensus.sync_requests").inc()
            self._requests[parent] = self._clock()
            address = self.committee.address(block.author)
            if address is not None:
                self._effects.append(
                    ("send", address, encode_sync_request(parent, self.name))
                )
            self._effects.append(
                ("sched", self.sync_retry_delay, ("sync_retry", parent))
            )

    def on_store_write(self, key: bytes) -> None:
        blocks = self._waiting.pop(key, None)
        if blocks:
            for block in blocks:
                self._pending.discard(block.digest())
                self._effects.append(("sched", 0.0, ("loopback", block)))
        # The request (keyed by Digest) is fulfilled. Direct state-sync
        # frontier requests have no suspended waiter, so this runs even
        # when nothing was waiting.
        for parent in list(self._requests):
            if parent.data == key:
                del self._requests[parent]

    def retry(self, parent) -> None:
        """A ``sync_retry`` tick fired: if the request is still open,
        re-broadcast it to the whole committee (the real synchronizer's
        frontier retry) and re-arm."""
        if parent not in self._requests:
            return
        addresses = [a for _, a in self.committee.broadcast_addresses(self.name)]
        for address in addresses:
            self._effects.append(
                ("send", address, encode_sync_request(parent, self.name))
            )
        self._effects.append(
            ("sched", self.sync_retry_delay, ("sync_retry", parent))
        )


class CoreStateMachine:
    """One validator as a deterministic state machine.

    Inputs are ``step(event, now)`` calls — ``event`` is a tagged tuple
    exactly as the Core's merged queue carries them (``("propose",
    Block)``, ``("vote", Vote)``, ``("timer", round)``, ...) plus the
    sim-plane extras ``("sync_request", (digest, origin))`` (served by
    the helper logic inline) and ``("sync_retry", digest)``. Outputs are
    the effect tuples documented in the module docstring.

    ``store`` survives restart — passing the previous incarnation's
    store exercises the real ``_restore_state`` recovery path.
    """

    def __init__(
        self,
        name: PublicKey,
        secret: SecretKey,
        committee: Committee,
        *,
        clock,
        timeout_delay: int = 1_000,
        sync_retry_delay: int = 10_000,
        leader_elector: str = "",
        batch_vote_verification: bool = True,
        wire_v2: bool = True,
        store: _NotifyingStore | None = None,
        retention_rounds: int = 0,
        statesync_active: bool = False,
    ) -> None:
        self.clock = clock
        self.store = store if store is not None else _NotifyingStore()
        self._effects: list = []
        self.outbox = Outbox(self._effects)

        seats = SeatTable.for_committee(committee)
        # Same emission gate as Consensus.spawn: decode always accepts
        # both formats; only what we emit is selected here.
        wire_seats = (
            seats
            if wire_v2 and os.environ.get("HOTSTUFF_WIRE_V2", "1") != "0"
            else None
        )
        self.seats = seats
        self._wire_seats = wire_seats

        self.rx_message = _SimChannel()
        self.tx_proposer = _SimChannel()
        self.tx_commit = _SimChannel()
        self.tx_mempool = _SimChannel()

        elector = make_elector(committee, leader_elector)
        self.synchronizer = SimSynchronizer(
            name,
            committee,
            self.store,
            self._effects,
            sync_retry_delay / 1000.0,
            clock,
        )
        self.store.on_write = self.synchronizer.on_store_write
        mempool_driver = _SimMempoolDriver(
            self.store, self.tx_mempool, self.rx_message
        )
        # Handle for SimWorld: twin-salt worlds install the committee's
        # salt list on it (see _SimMempoolDriver.twin_salts).
        self.mempool_driver = mempool_driver
        self.core = _SimCore(
            name,
            committee,
            SignatureService(secret),
            self.store,
            elector,
            mempool_driver,
            self.synchronizer,
            timeout_delay,
            self.rx_message,
            self.rx_message,
            self.tx_proposer,
            self.tx_commit,
            batch_vote_verification=batch_vote_verification,
            wire_seats=wire_seats,
            network=self.outbox,
            timer=Timer(timeout_delay, clock=clock),
            # Lazarus parity: every sim node answers state probes and can
            # install verified snapshots; the ACTIVE probe loop is opt-in
            # (statesync_active) so committed sweep seeds keep their
            # byte-identical event streams; the compactor arms with a
            # retention depth exactly as on the real plane.
            statesync=StateSync(
                name,
                committee,
                sync_retry_delay,
                active=statesync_active,
            ),
            compactor=(
                Compactor(self.store, retention_rounds)
                if retention_rounds > 0
                else None
            ),
        )
        self.core.sim_effects = self._effects
        self._handlers = self.core.bound_handlers()
        self._payload_buffer: set = set()
        self._signature_service = self.core.signature_service
        # Oracle/Twins hooks, set post-construction by the world: a
        # virtual-clock trace sink (sim.streams.SimRoundTrace) and a
        # per-instance payload salt so a twin pair's same-round blocks
        # differ by digest (real twins act on different client payloads;
        # the sim has no clients, so the salt stands in).
        self.trace = None
        self.proposal_salt: bytes | None = None

    # -- scheduler-facing surface -----------------------------------------

    @property
    def timer_deadline(self) -> float:
        return self.core.timer.deadline

    @property
    def round(self) -> int:
        return self.core.round

    def init(self, now: float) -> list:
        """The ``Core.run()`` preamble: restore persisted voting state,
        arm the timer, and propose if this node leads its (restored)
        round."""
        self.clock.advance_to(now)
        run_sync(self.core._restore_state())
        # Same preamble order as Core.run(): floor restoration + probe
        # arming between state restore and the timer.
        run_sync(self.core._statesync.start(self.core))
        self.core.timer.reset()
        if self.core.name == self.core.leader_elector.get_leader(self.core.round):
            run_sync(self.core.generate_proposal(None))
        self._drain_queues()
        return self._take_effects()

    def step(self, event, now: float) -> list:
        self.clock.advance_to(now)
        kind, payload = event
        if kind == "timer":
            # Stale expiry guard, exactly as in Core.run(): the event
            # carries the round the timer fired in.
            if payload == self.core.round:
                run_sync(self.core._guarded(self.core.local_timeout_round()))
        elif kind == "sync_request":
            self._serve_sync_request(payload)
        elif kind == "sync_retry":
            self.synchronizer.retry(payload)
        else:
            handler = self._handlers.get(kind)
            if handler is None:
                log.error("unexpected protocol message kind %s", kind)
            else:
                run_sync(self.core._guarded(handler(payload)))
        self._drain_queues()
        return self._take_effects()

    # -- internals ---------------------------------------------------------

    def _take_effects(self) -> list:
        effects, self._effects[:] = list(self._effects), []
        return effects

    def _drain_queues(self) -> None:
        # Proposer actor, replayed synchronously: Make builds and signs
        # the block, broadcasts it, and loops it back (the loopback is an
        # event, not an inline call — same ordering as the real queue).
        for msg in self.tx_proposer.drain():
            if isinstance(msg, ProposerMake):
                self._make_block(msg)
            elif isinstance(msg, ProposerCleanup):
                for d in msg.digests:
                    self._payload_buffer.discard(d)
        for block in self.tx_commit.drain():
            self._effects.append(("commit", block))
        self.tx_mempool.drain()  # mempool Synchronize/Cleanup: no mempool here
        for item in self.rx_message.drain():  # self-queued: ride the heap
            self._effects.append(("sched", 0.0, item))

    def _make_block(self, make: ProposerMake) -> None:
        payload = sorted(self._payload_buffer, key=lambda d: d.data)
        self._payload_buffer.clear()
        if self.proposal_salt is not None:
            payload.append(
                sha512_digest(
                    b"twins-proposal-salt",
                    self.proposal_salt,
                    make.round.to_bytes(8, "little"),
                )
            )
        block = run_sync(
            Block.new(
                make.qc,
                make.tc,
                self.core.name,
                make.round,
                payload,
                self._signature_service,
            )
        )
        addresses = [
            a for _, a in self.core.committee.broadcast_addresses(self.core.name)
        ]
        if self.trace is not None:
            # The real plane's leader-side broadcast mark (Proposer emits
            # it via telemetry.trace_event): author + digest so stream
            # analyzers attribute the proposal and spot conflicts.
            self.trace.propose_send(
                make.round, f"{self.core.name!r}|{block.digest()!r}"
            )
        self.outbox.broadcast(addresses, encode_propose(block, self._wire_seats))
        self._effects.append(("sched", 0.0, ("loopback", block)))

    def _serve_sync_request(self, payload) -> None:
        """The Helper actor inline: answer with the requested block plus
        up to ``CHAIN_DEPTH - 1`` ancestors, newest first (see
        ``consensus/helper.py`` for why that order heals range gaps)."""
        digest, origin = payload
        address = self.core.committee.address(origin)
        if address is None:
            log.warning("received sync request from unknown node %s", origin)
            return
        try:
            data = run_sync(self.store.read(digest.data))
            if data is None:
                # Truncated-or-unknown digest: answer with the snapshot
                # record so the requester can establish a floor (mirror
                # of the real Helper's NACK path).
                snap = run_sync(self.store.read_meta(SNAPSHOT_KEY))
                if snap is not None:
                    try:
                        round_, frontier = peek_frontier(snap)
                    except SnapshotError as e:
                        log.error("corrupt snapshot record: %s", e)
                    else:
                        self.outbox.send(
                            address,
                            encode_state_response(round_, frontier, snap),
                        )
                return
            block = Block.deserialize(data)
            self.outbox.send(address, encode_propose(block))
            sent = 1
            while sent < CHAIN_DEPTH:
                pdata = run_sync(self.store.read(block.parent().data))
                if pdata is None:
                    break
                block = Block.deserialize(pdata)
                self.outbox.send(address, encode_propose(block))
                sent += 1
        except Exception as e:  # parity with Helper's guard
            log.error("failed to serve sync request for %s: %s", digest, e)
