"""Shrink a failing fault schedule to a minimal pinned reproducer.

When a sweep seed produces a checker violation, the raw chaos scenario
is a poor artifact: half its fault events are irrelevant, and "seed
84321" tells the next engineer nothing. ``shrink`` applies
delta-debugging-style reduction — entirely at the SCENARIO level, so
the output replays through either plane:

1. **drop fault entries** — greedy event removal to a fixpoint (each
   removal re-runs the sim and keeps the candidate only if the SAME
   violation class reproduces);
2. **shorten durations** — interval faults' heal times are pulled
   toward their activations, then the scenario's total duration is
   bisected down;
3. **interleaving** — the sim's ``jitter`` knob re-draws message
   latencies without touching the fault schedule; the shrinker records
   the jitter under which the minimal scenario reproduces, pinning one
   concrete interleaving.

The reproducer artifact (``write_reproducer``) is a single JSON file
carrying the scenario, the world configuration, the verdict, and the
canonical schedule trace — drop it in ``benchmark/scenarios/`` or feed
it back to ``run_sim``/``run_scenario`` to replay.
"""

from __future__ import annotations

import json
import os

from hotstuff_tpu.faultline.policy import Scenario

from .world import run_sim

__all__ = ["ShrinkResult", "shrink", "sim_failure_probe", "write_reproducer"]

REPRO_SCHEMA = "simulant-repro-v1"


def _violation_class(verdict: dict) -> str | None:
    """The coarse failure fingerprint shrinking preserves: safety
    violations and liveness violations are different bugs — a shrink
    step must not "simplify" one into the other."""
    if not verdict["safety"]["ok"]:
        return "safety"
    if not verdict["liveness"]["recovered"]:
        return "liveness"
    return None


def sim_failure_probe(n: int, **world_kwargs):
    """A ``probe(scenario) -> (violation_class | None, verdict)`` that
    runs the scenario on the sim plane with fixed world parameters."""

    def probe(scenario: Scenario):
        verdict = run_sim(scenario, n, **world_kwargs)["verdict"]
        return _violation_class(verdict), verdict

    return probe


class ShrinkResult:
    __slots__ = ("scenario", "verdict", "violation", "runs", "steps")

    def __init__(self, scenario, verdict, violation, runs, steps) -> None:
        self.scenario = scenario
        self.verdict = verdict
        self.violation = violation
        self.runs = runs
        self.steps = steps


def shrink(
    scenario: Scenario,
    probe,
    *,
    max_runs: int = 200,
) -> ShrinkResult:
    """Minimize ``scenario`` while ``probe`` keeps reporting the same
    violation class. ``probe(scenario) -> (violation | None, verdict)``;
    the initial scenario MUST fail (ValueError otherwise, so a flaky
    report can't silently shrink to nothing)."""
    violation, verdict = probe(scenario)
    runs = 1
    if violation is None:
        raise ValueError("shrink() requires a failing scenario")
    steps: list[str] = []
    current = scenario

    def attempt(candidate: Scenario, note: str):
        nonlocal current, verdict, runs
        if runs >= max_runs:
            return False
        got, v = probe(candidate)
        runs += 1
        if got == violation:
            current = candidate
            verdict = v
            steps.append(note)
            return True
        return False

    # Pass 1: greedy single-event drops to a fixpoint. Dropping never
    # re-rolls sibling events' seeded choices (policy.compile derives
    # one RNG stream per ORIGINAL template slot index — which shifts on
    # removal, so re-probe rather than assume).
    changed = True
    while changed and runs < max_runs:
        changed = False
        i = 0
        while i < len(current.events):
            events = current.events[:i] + current.events[i + 1 :]
            if not events:
                break
            candidate = Scenario(
                name=current.name,
                seed=current.seed,
                duration_s=current.duration_s,
                events=events,
            )
            if attempt(candidate, f"drop event {i}"):
                changed = True  # list shifted: retry same index
            else:
                i += 1

    # Pass 2: shorten interval faults (heal sooner).
    for i, ev in enumerate(list(current.events)):
        until = ev.get("until")
        if until is None:
            continue
        at = float(ev.get("at", 0.0))
        for frac in (0.25, 0.5):
            shorter = at + (float(until) - at) * frac
            if shorter >= float(until):
                continue
            events = [dict(e) for e in current.events]
            events[i]["until"] = round(shorter, 3)
            candidate = Scenario(
                name=current.name,
                seed=current.seed,
                duration_s=current.duration_s,
                events=events,
            )
            if attempt(candidate, f"shorten event {i} until -> {shorter:.3f}"):
                break

    # Pass 3: trim total duration (the recovery tail judges liveness, so
    # the scenario only needs to outlive its last event).
    last_event_t = max(
        (
            max(float(e.get("at", 0.0)), float(e.get("until") or 0.0))
            for e in current.events
        ),
        default=0.0,
    )
    for frac in (0.4, 0.6, 0.8):
        duration = max(last_event_t + 0.5, current.duration_s * frac)
        if duration >= current.duration_s:
            continue
        candidate = Scenario(
            name=current.name,
            seed=current.seed,
            duration_s=round(duration, 3),
            events=current.events,
        )
        if attempt(candidate, f"duration -> {duration:.3f}"):
            break

    return ShrinkResult(current, verdict, violation, runs, steps)


def write_reproducer(
    directory: str,
    scenario: Scenario,
    n: int,
    verdict: dict,
    *,
    trace: str | None = None,
    world: dict | None = None,
    steps: list[str] | None = None,
    tag: str = "repro",
) -> str:
    """Write a replayable reproducer artifact; returns its path."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(
        directory, f"{tag}-{scenario.name}-seed{scenario.seed}-n{n}.json"
    )
    with open(path, "w") as f:
        json.dump(
            {
                "schema": REPRO_SCHEMA,
                "scenario": scenario.to_json(),
                "n": n,
                "world": world or {},
                "verdict": verdict,
                "trace": trace,
                "shrink_steps": steps or [],
            },
            f,
            indent=2,
            sort_keys=True,
        )
        f.write("\n")
    return path
