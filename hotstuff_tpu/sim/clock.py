"""Virtual clock for deterministic simulation.

A ``VirtualClock`` is a float the scheduler advances. It is CALLABLE so
it drops into every ``clock=`` seam the real stack exposes
(``consensus.Timer``, ``consensus.Synchronizer``,
``faultline.FaultPlane``): code written against ``time.monotonic``
semantics reads simulated seconds instead, and nothing ever sleeps.

Monotonicity is enforced — an event heap that tried to move time
backwards has a scheduling bug, and silently accepting it would
desynchronize every timer deadline derived from the clock.
"""

from __future__ import annotations

__all__ = ["VirtualClock"]


class VirtualClock:
    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        if t < self._now:
            raise ValueError(
                f"virtual time cannot move backwards: {t} < {self._now}"
            )
        self._now = t

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"VirtualClock({self._now:.6f})"
