"""Simulant: deterministic simulation of the consensus protocol.

FoundationDB-style deterministic simulation testing for this codebase:
the sans-io :class:`CoreStateMachine` (the REAL ``Core`` handlers behind
effect-collecting IO seams) runs N-node committees on a single
virtual-time event heap (:class:`SimWorld`), enacting the existing
faultline scenario schema through the existing :class:`FaultPlane` and
judging with the existing checker — thousands of seeded fault schedules
per CI minute instead of wall-clock minutes per seed.

Entry points:

- :func:`run_sim` — one scenario, one verdict (harness-shaped result);
- :mod:`~hotstuff_tpu.sim.twins` — Twins-style systematic equivocation
  scenario generation (duplicate identity across partitions);
- :mod:`~hotstuff_tpu.sim.shrink` — minimize a failing schedule to a
  pinned reproducer;
- ``benchmark/sim_sweep.py`` — the checker-gated seed-range sweep.
"""

from .clock import VirtualClock
from .machine import CoreStateMachine, SimSuspended
from .world import EventHeap, SimWorld, run_sim

__all__ = [
    "CoreStateMachine",
    "EventHeap",
    "SimSuspended",
    "SimWorld",
    "VirtualClock",
    "run_sim",
]
