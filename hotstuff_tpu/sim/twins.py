"""Twins-style systematic Byzantine-scenario generation.

The Twins insight (Bano et al., "Twins: BFT Systems Made Robust"): most
Byzantine behaviors worth testing are EQUIVALENT to running two copies
of a correct validator with the same identity ("twins") and letting the
network schedule decide which copy each honest node hears — equivocation
falls out of duplicate identity + partitioning, with no hand-written
attack code.

Mapping onto this codebase:

- a twin pair is two :class:`~hotstuff_tpu.sim.machine.CoreStateMachine`
  instances sharing one committee seat (same keypair, same address,
  SEPARATE stores — so each signs whatever its own partition shows it,
  which is exactly how a real equivocator splits the committee);
- the Twins round-by-round partition schedule is approximated by
  virtual-time partition windows over node INSTANCES (the sim's
  schedules are time-indexed, not round-indexed; with default link
  latency a window of W seconds covers ~10·W rounds, and the generator
  enumerates window phases so leader/partition alignments vary);
- leader rotation comes from the deterministic round-robin elector
  cycling every seat through leadership inside each window, rather than
  the paper's explicit per-round leader assignment.

Every generated scenario heals before the end, so the checker judges
BOTH properties: safety across the whole run (the twin pair is the
byzantine fault — honest nodes must never commit conflicting blocks no
matter which twin they heard) and post-heal liveness.

``enumerate_twins`` is exhaustive over (twin seat × partition
arrangement × window phase) below the cap; ``twins_scenario`` draws one
configuration from a seed for sweep-style sampling.
"""

from __future__ import annotations

import itertools

from hotstuff_tpu.faultline.policy import Scenario, _seed_stream

from .world import SimWorld, _node_name

__all__ = ["TWIN_SUFFIX", "enumerate_twins", "run_twins", "twins_scenario"]

TWIN_SUFFIX = "+twin"


def _twin_name(base: str) -> str:
    return base + TWIN_SUFFIX


def _partition_arrangements(names: list[str], twin: str) -> list[list[list[str]]]:
    """All 2-way splits of the instance set where the twin pair is
    separated (one copy per side) — the arrangements that can actually
    produce equivocation — and each side can make progress at least when
    joined by the twin (size >= quorum - 1 honest members)."""
    twin_a, twin_b = twin, _twin_name(twin)
    honest = [n for n in names if n not in (twin_a, twin_b)]
    n_seats = len(honest) + 1  # committee size (the twin pair is one seat)
    quorum = 2 * ((n_seats - 1) // 3) + 1
    arrangements = []
    for r in range(1, len(honest)):
        for side in itertools.combinations(honest, r):
            group_a = sorted([twin_a, *side])
            group_b = sorted([twin_b, *(n for n in honest if n not in side)])
            # Keep splits where at least one side can quorum (with its
            # twin copy counted for the shared seat) — those are the
            # dangerous ones: commits can happen while the committee is
            # split, so safety genuinely rests on quorum intersection.
            if max(len(group_a), len(group_b)) >= quorum:
                arrangements.append([group_a, group_b])
    return arrangements


def enumerate_twins(
    n: int = 4,
    *,
    duration_s: float = 8.0,
    windows: int = 2,
    phases: int = 2,
    limit: int | None = None,
):
    """Yield ``(scenario, twins_map)`` pairs systematically covering
    (twin seat) x (partition arrangement) x (window phase). ``windows``
    partition windows tile the middle of the run; ``phases`` shifts the
    tiling so window edges land at different protocol rounds."""
    names = [_node_name(i) for i in range(n)]
    count = 0
    lo, hi = 0.15 * duration_s, 0.75 * duration_s
    for twin in names:
        instances = sorted([*names, _twin_name(twin)])
        for arrangement in _partition_arrangements(instances, twin):
            for phase in range(phases):
                span = (hi - lo) / windows
                offset = span * phase / phases
                events = []
                for w in range(windows):
                    at = lo + w * span + offset
                    until = min(at + span * 0.8, 0.85 * duration_s)
                    # Alternate which side the odd windows isolate by
                    # reversing group order (groups are symmetric for
                    # the partition filter; alternating is for trace
                    # readability only).
                    groups = arrangement if w % 2 == 0 else arrangement[::-1]
                    events.append(
                        {
                            "kind": "partition",
                            "groups": groups,
                            "at": round(at, 3),
                            "until": round(until, 3),
                        }
                    )
                scenario = Scenario(
                    name=f"twins-{twin}-a{len(arrangement[0])}-p{phase}",
                    seed=count,
                    duration_s=duration_s,
                    events=events,
                )
                yield scenario, {_twin_name(twin): twin}
                count += 1
                if limit is not None and count >= limit:
                    return


def twins_scenario(seed: int, n: int = 4, *, duration_s: float = 8.0):
    """One seed-drawn Twins configuration: ``(scenario, twins_map)``."""
    rng = _seed_stream(seed, "twins")
    names = [_node_name(i) for i in range(n)]
    twin = rng.choice(names)
    instances = sorted([*names, _twin_name(twin)])
    arrangements = _partition_arrangements(instances, twin)
    arrangement = rng.choice(arrangements)
    windows = rng.choice((1, 2, 3))
    lo, hi = 0.15 * duration_s, 0.75 * duration_s
    span = (hi - lo) / windows
    events = []
    for w in range(windows):
        at = lo + w * span + rng.uniform(0.0, 0.3) * span
        until = min(at + rng.uniform(0.5, 0.9) * span, 0.85 * duration_s)
        events.append(
            {
                "kind": "partition",
                "groups": arrangement,
                "at": round(at, 3),
                "until": round(until, 3),
            }
        )
    scenario = Scenario(
        name=f"twins-seed{seed}", seed=seed, duration_s=duration_s, events=events
    )
    return scenario, {_twin_name(twin): twin}


def run_twins(scenario: Scenario, twins_map: dict[str, str], n: int = 4, **kwargs):
    """Execute one Twins scenario on the sim plane. The verdict's
    ``safety`` section is the point: honest nodes must agree on every
    committed round even though the twinned seat signed on both sides of
    every partition."""
    return SimWorld(scenario, n, twins=twins_map, **kwargs).run()
