"""Twins-style systematic Byzantine-scenario generation.

The Twins insight (Bano et al., "Twins: BFT Systems Made Robust"): most
Byzantine behaviors worth testing are EQUIVALENT to running two copies
of a correct validator with the same identity ("twins") and letting the
network schedule decide which copy each honest node hears — equivocation
falls out of duplicate identity + partitioning, with no hand-written
attack code.

Mapping onto this codebase:

- a twin pair is two :class:`~hotstuff_tpu.sim.machine.CoreStateMachine`
  instances sharing one committee seat (same keypair, same address,
  SEPARATE stores — so each signs whatever its own partition shows it,
  which is exactly how a real equivocator splits the committee);
- the Twins round-by-round partition schedule is approximated by
  virtual-time partition windows over node INSTANCES (the sim's
  schedules are time-indexed, not round-indexed; with default link
  latency a window of W seconds covers ~10·W rounds, and the generator
  enumerates window phases so leader/partition alignments vary);
- leader rotation comes from the deterministic round-robin elector
  cycling every seat through leadership inside each window — OR, with
  the per-round API below, from the paper's explicit controls:
  ``SimWorld(leader_schedule=...)`` pins exactly who leads each round
  (a :class:`~hotstuff_tpu.consensus.leader.ScheduledLeaderElector`
  shared by every instance) and ``SimWorld(round_partitions=...)``
  decides per-message connectivity by the SENDER's current round, so a
  partition arrangement holds for protocol rounds rather than wall
  windows. ``twin_proposal_salt`` makes a twin pair's same-round blocks
  differ by digest (payloads are salted per instance), which is what
  lets two sides of a split certify CONFLICTING blocks instead of
  accidentally agreeing on identical empty ones.

The per-round controls make the Twins paper's boundary executable:
``dual_commit_config(pairs=2)`` scripts two twinned seats at n=4 —
faults strictly beyond the f=1 tolerance — into a split where BOTH
sides hold a full quorum of distinct seats, each side chains its own
QCs over salted twin proposals, and two honest nodes commit conflicting
blocks (the checker's safety verdict flags it). The same script with
``pairs=1`` (faults within tolerance) leaves one side short of quorum:
safety provably holds. ``tests/test_sim_twins.py`` pins both sides of
that boundary.

Every time-windowed scenario (``enumerate_twins`` / ``twins_scenario``)
heals before the end, so the checker judges BOTH properties: safety
across the whole run (the twin pair is the byzantine fault — honest
nodes must never commit conflicting blocks no matter which twin they
heard) and post-heal liveness.

``enumerate_twins`` is exhaustive over (twin seat × partition
arrangement × window phase) below the cap; ``twins_scenario`` draws one
configuration from a seed for sweep-style sampling.
"""

from __future__ import annotations

import itertools

from hotstuff_tpu.faultline.policy import Scenario, _seed_stream

from .world import SimWorld, _node_name

__all__ = [
    "TWIN_SUFFIX",
    "dual_commit_config",
    "enumerate_twins",
    "run_twins",
    "twins_round_scenario",
    "twins_scenario",
]

TWIN_SUFFIX = "+twin"


def _twin_name(base: str) -> str:
    return base + TWIN_SUFFIX


def _partition_arrangements(names: list[str], twin: str) -> list[list[list[str]]]:
    """All 2-way splits of the instance set where the twin pair is
    separated (one copy per side) — the arrangements that can actually
    produce equivocation — and each side can make progress at least when
    joined by the twin (size >= quorum - 1 honest members)."""
    twin_a, twin_b = twin, _twin_name(twin)
    honest = [n for n in names if n not in (twin_a, twin_b)]
    n_seats = len(honest) + 1  # committee size (the twin pair is one seat)
    quorum = 2 * ((n_seats - 1) // 3) + 1
    arrangements = []
    for r in range(1, len(honest)):
        for side in itertools.combinations(honest, r):
            group_a = sorted([twin_a, *side])
            group_b = sorted([twin_b, *(n for n in honest if n not in side)])
            # Keep splits where at least one side can quorum (with its
            # twin copy counted for the shared seat) — those are the
            # dangerous ones: commits can happen while the committee is
            # split, so safety genuinely rests on quorum intersection.
            if max(len(group_a), len(group_b)) >= quorum:
                arrangements.append([group_a, group_b])
    return arrangements


def enumerate_twins(
    n: int = 4,
    *,
    duration_s: float = 8.0,
    windows: int = 2,
    phases: int = 2,
    limit: int | None = None,
):
    """Yield ``(scenario, twins_map)`` pairs systematically covering
    (twin seat) x (partition arrangement) x (window phase). ``windows``
    partition windows tile the middle of the run; ``phases`` shifts the
    tiling so window edges land at different protocol rounds."""
    names = [_node_name(i) for i in range(n)]
    count = 0
    lo, hi = 0.15 * duration_s, 0.75 * duration_s
    for twin in names:
        instances = sorted([*names, _twin_name(twin)])
        for arrangement in _partition_arrangements(instances, twin):
            for phase in range(phases):
                span = (hi - lo) / windows
                offset = span * phase / phases
                events = []
                for w in range(windows):
                    at = lo + w * span + offset
                    until = min(at + span * 0.8, 0.85 * duration_s)
                    # Alternate which side the odd windows isolate by
                    # reversing group order (groups are symmetric for
                    # the partition filter; alternating is for trace
                    # readability only).
                    groups = arrangement if w % 2 == 0 else arrangement[::-1]
                    events.append(
                        {
                            "kind": "partition",
                            "groups": groups,
                            "at": round(at, 3),
                            "until": round(until, 3),
                        }
                    )
                scenario = Scenario(
                    name=f"twins-{twin}-a{len(arrangement[0])}-p{phase}",
                    seed=count,
                    duration_s=duration_s,
                    events=events,
                )
                yield scenario, {_twin_name(twin): twin}
                count += 1
                if limit is not None and count >= limit:
                    return


def twins_scenario(seed: int, n: int = 4, *, duration_s: float = 8.0):
    """One seed-drawn Twins configuration: ``(scenario, twins_map)``."""
    rng = _seed_stream(seed, "twins")
    names = [_node_name(i) for i in range(n)]
    twin = rng.choice(names)
    instances = sorted([*names, _twin_name(twin)])
    arrangements = _partition_arrangements(instances, twin)
    arrangement = rng.choice(arrangements)
    windows = rng.choice((1, 2, 3))
    lo, hi = 0.15 * duration_s, 0.75 * duration_s
    span = (hi - lo) / windows
    events = []
    for w in range(windows):
        at = lo + w * span + rng.uniform(0.0, 0.3) * span
        until = min(at + rng.uniform(0.5, 0.9) * span, 0.85 * duration_s)
        events.append(
            {
                "kind": "partition",
                "groups": arrangement,
                "at": round(at, 3),
                "until": round(until, 3),
            }
        )
    scenario = Scenario(
        name=f"twins-seed{seed}", seed=seed, duration_s=duration_s, events=events
    )
    return scenario, {_twin_name(twin): twin}


def dual_commit_config(n: int = 4, *, pairs: int = 2, rounds: int = 60):
    """The Twins tolerance boundary as an executable config: returns
    ``(scenario, twins_map, sim_kwargs)`` for :func:`run_twins`.

    With ``pairs=2`` at ``n=4`` (two twinned seats — faults strictly
    beyond the f=1 tolerance) the script separates the copies into two
    sides that EACH hold a quorum of distinct seats::

        side A: n000,  n001,  n002        side B: n000', n001', n003

    Every scripted round pins a twinned seat as leader, so both of its
    copies believe they lead and propose to their own side; the
    per-instance proposal salt makes those same-round blocks conflict
    by digest, each side certifies and 2-chains its own blocks, and the
    two honest observers (n002, n003) commit CONFLICTING blocks — the
    checker's safety verdict must flag it.

    With ``pairs=1`` (within tolerance) side B is one distinct seat
    short of quorum: it can never certify anything, so safety provably
    holds no matter the schedule — the unreachable side of the
    boundary, pinned by the same test that pins the violation.
    """
    if n != 4:
        raise ValueError("the scripted boundary is a committee-of-4 story")
    if pairs not in (1, 2):
        raise ValueError("pairs must be 1 (safe) or 2 (violating)")
    names = [_node_name(i) for i in range(n)]
    twinned = names[:pairs]
    twins_map = {_twin_name(b): b for b in twinned}
    side_a = sorted(names[:3])
    side_b = sorted([_twin_name(b) for b in twinned] + names[3:])
    # Leaders alternate over the twinned seats only: every scripted
    # round both sides have a copy of the leader, so neither waits on
    # rotation reaching an absent seat.
    leader_schedule = {r: twinned[r % len(twinned)] for r in range(rounds)}
    round_partitions = {r: [side_a, side_b] for r in range(rounds)}
    scenario = Scenario(
        name=f"twins-dual-commit-p{pairs}",
        seed=0,
        duration_s=8.0,
        events=[],
    )
    sim_kwargs = {
        "leader_schedule": leader_schedule,
        "round_partitions": round_partitions,
        "twin_proposal_salt": True,
    }
    return scenario, twins_map, sim_kwargs


def twins_round_scenario(
    seed: int,
    n: int = 4,
    *,
    rounds: int = 40,
    duration_s: float = 8.0,
):
    """One seed-drawn PER-ROUND Twins configuration — the paper's actual
    adversary space: each scripted round independently draws a leader
    (any seat) and a partition arrangement separating the twin pair.
    Returns ``(scenario, twins_map, sim_kwargs)``; rounds beyond the
    scripted range are fully connected with round-robin leaders. Safety
    is judged across the whole run regardless; post-heal liveness is
    only meaningful for runs that exhaust the scripted range in time —
    a schedule whose drawn leaders keep landing on the minority side
    grinds at timeout pace and may end mid-script, which the checker
    reports as ``recovered: false`` rather than a safety problem."""
    rng = _seed_stream(seed, "twins-rounds")
    names = [_node_name(i) for i in range(n)]
    twin = rng.choice(names)
    instances = sorted([*names, _twin_name(twin)])
    arrangements = _partition_arrangements(instances, twin)
    leader_schedule: dict[int, str] = {}
    round_partitions: dict[int, list] = {}
    for r in range(rounds):
        leader_schedule[r] = rng.choice(names)
        # ~1 round in 4 left fully connected: progress interleaves with
        # splits, which is where stale-QC / fork-choice bugs live.
        if rng.random() < 0.75:
            round_partitions[r] = rng.choice(arrangements)
    scenario = Scenario(
        name=f"twins-rounds-seed{seed}",
        seed=seed,
        duration_s=duration_s,
        events=[],
    )
    sim_kwargs = {
        "leader_schedule": leader_schedule,
        "round_partitions": round_partitions,
        "twin_proposal_salt": True,
    }
    return scenario, {_twin_name(twin): twin}, sim_kwargs


def run_twins(scenario: Scenario, twins_map: dict[str, str], n: int = 4, **kwargs):
    """Execute one Twins scenario on the sim plane. The verdict's
    ``safety`` section is the point: honest nodes must agree on every
    committed round even though the twinned seat signed on both sides of
    every partition."""
    return SimWorld(scenario, n, twins=twins_map, **kwargs).run()
