"""Super-batching backend wrapper: fuse concurrent batch-verification
requests into one crypto call.

BASELINE.json's north star calls for accumulating "all 2f+1 vote
signatures per round into a single TPU call ... (or per fused multi-round
super-batch)". Individual QC/TC verifications already batch their own
2f+1 signatures; this wrapper fuses REQUESTS that arrive concurrently —
multiple QCs from pipelined rounds, proposals being verified while votes
aggregate, or many in-process validators sharing one device — into one
device dispatch, amortizing the per-call round trip.

Mechanics: verification requests from the crypto worker threads join a
small collection window (first arrival opens it); the opener flushes the
merged batch through the inner backend. If the merged batch fails, each
request is re-verified separately so one byzantine QC cannot poison its
neighbors' verdicts (requests keep exact per-request acceptance).
Thread-safe; no asyncio dependency (it sits below the bridge).
"""

from __future__ import annotations

import threading

from . import BackendUnavailable, CryptoError, get_backend, set_backend


class _Request:
    __slots__ = ("msgs", "pubs", "sigs", "done", "error")

    def __init__(self, msgs, pubs, sigs) -> None:
        self.msgs = msgs
        self.pubs = pubs
        self.sigs = sigs
        self.done = threading.Event()
        self.error: CryptoError | None = None


class BatchingBackend:
    """Wraps any backend; fuses concurrent ``verify_batch`` calls."""

    def __init__(self, inner, window_ms: float = 2.0, max_sigs: int = 8192) -> None:
        self.inner = inner
        self.name = f"{inner.name}+superbatch"
        self.window = window_ms / 1000.0
        self.max_sigs = max_sigs
        self._lock = threading.Lock()
        self._pending: list[_Request] = []
        self._flusher_active = False
        # Observability: how many inner calls vs requests (exposed for
        # tests and diagnostics).
        self.fused_requests = 0
        self.inner_calls = 0

    def verify_batch(self, msgs, pubs, sigs) -> None:
        if not len(msgs) == len(pubs) == len(sigs):
            raise CryptoError("batch length mismatch")
        req = _Request(list(msgs), list(pubs), list(sigs))
        with self._lock:
            self._pending.append(req)
            i_flush = not self._flusher_active
            if i_flush:
                self._flusher_active = True
        if i_flush:
            # Collection window: let concurrent requests pile in.
            import time

            time.sleep(self.window)
            self._flush()
        req.done.wait()
        if req.error is not None:
            raise req.error

    def _flush(self) -> None:
        with self._lock:
            batch = self._pending
            self._pending = []
            self._flusher_active = False
        if not batch:
            return
        self.fused_requests += len(batch)
        fused_ok = False
        try:
            msgs = [m for r in batch for m in r.msgs]
            pubs = [p for r in batch for p in r.pubs]
            sigs = [s for r in batch for s in r.sigs]
            try:
                self.inner_calls += 1
                if len(msgs) <= self.max_sigs:
                    self.inner.verify_batch(msgs, pubs, sigs)
                    fused_ok = True
                else:
                    # Oversized fusion: verify per request (still one call
                    # per QC, the non-fused baseline).
                    raise CryptoError("fused batch too large")
            except Exception:
                # Isolate: one bad request must not fail its neighbors —
                # and a NON-crypto failure (JAX RuntimeError, device/tunnel
                # death) must fail loudly, not wedge every waiter.
                for r in batch:
                    try:
                        self.inner_calls += 1
                        self.inner.verify_batch(r.msgs, r.pubs, r.sigs)
                    except CryptoError as e:
                        r.error = e
                    except Exception as e:
                        # Distinguishable from an invalid signature: the
                        # request was NOT judged (transient infrastructure
                        # failure, e.g. device/tunnel death).
                        r.error = BackendUnavailable(
                            f"verification backend failure: {e!r}"
                        )
                    finally:
                        r.done.set()
        finally:
            # Nobody may be left waiting. A request released without having
            # been verified is REJECTED (error set), never accepted.
            for r in batch:
                if not r.done.is_set():
                    if not fused_ok and r.error is None:
                        r.error = BackendUnavailable(
                            "verification flush aborted"
                        )
                    r.done.set()


def enable_superbatching(window_ms: float = 2.0, max_sigs: int = 8192) -> BatchingBackend:
    """Wrap the currently-selected backend (idempotent)."""
    current = get_backend()
    if isinstance(current, BatchingBackend):
        return current
    wrapped = BatchingBackend(current, window_ms=window_ms, max_sigs=max_sigs)
    set_backend(wrapped)
    return wrapped
