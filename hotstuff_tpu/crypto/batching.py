"""Super-batching backend wrapper: fuse concurrent batch-verification
requests into one crypto call.

BASELINE.json's north star calls for accumulating "all 2f+1 vote
signatures per round into a single TPU call ... (or per fused multi-round
super-batch)". Individual QC/TC verifications already batch their own
2f+1 signatures; this wrapper fuses REQUESTS that arrive concurrently —
multiple QCs from pipelined rounds, proposals being verified while votes
aggregate, or many in-process validators sharing one device — into one
device dispatch, amortizing the per-call round trip.

Mechanics: back-pressure batching, no timer. A request arriving while
the device is IDLE flushes immediately — a lone QC pays zero added
latency (round 2 charged it a fixed 2 ms collection window). Requests
arriving while an inner call is IN FLIGHT pool up and are fused into one
call the moment the device frees, so fusion kicks in exactly under the
contention that needs it, sized by the device's own round-trip time. If
a merged batch fails, each request is re-verified separately so one
byzantine QC cannot poison its neighbors' verdicts (requests keep exact
per-request acceptance). Thread-safe; no asyncio dependency (it sits
below the bridge).
"""

from __future__ import annotations

import threading
import time

from hotstuff_tpu import telemetry

from . import (
    BackendUnavailable,
    CryptoError,
    _explode_cert,
    get_backend,
    set_backend,
)


class _Request:
    __slots__ = ("msgs", "pubs", "sigs", "cert", "done", "error")

    def __init__(self, msgs, pubs, sigs) -> None:
        self.msgs = msgs
        self.pubs = pubs
        self.sigs = sigs
        # Fused-cert requests carry (msgs, pubs, sig_buf, stride, key)
        # here and leave the triple lists empty.
        self.cert = None
        self.done = threading.Event()
        self.error: CryptoError | None = None

    def nsigs(self) -> int:
        return len(self.cert[1]) if self.cert is not None else len(self.msgs)


class BatchingBackend:
    """Wraps any backend; fuses concurrent ``verify_batch`` calls.

    ``window_ms`` is accepted for backward compatibility and ignored:
    collection is driven by device back-pressure (requests pool only
    while an inner call is in flight), not by a timer.
    """

    def __init__(
        self, inner, window_ms: float | None = None, max_sigs: int = 8192
    ) -> None:
        self.inner = inner
        self.name = f"{inner.name}+superbatch"
        self.max_sigs = max_sigs
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: list[_Request] = []
        self._thread: threading.Thread | None = None
        # Observability: how many inner calls vs requests, and how many
        # signatures the identical-triple dedup removed (exposed for
        # tests and diagnostics; mirrored into the telemetry registry).
        self.fused_requests = 0
        self.inner_calls = 0
        self.deduped_sigs = 0
        self.cert_requests = 0
        self.cert_deduped_sigs = 0
        self._m_requests = telemetry.counter("crypto.superbatch.requests")
        self._m_flushes = telemetry.counter("crypto.superbatch.flushes")
        self._m_deduped = telemetry.counter("crypto.superbatch.deduped_sigs")
        self._m_cert_requests = telemetry.counter(
            "crypto.superbatch.cert_requests"
        )
        self._m_cert_deduped = telemetry.counter(
            "crypto.superbatch.cert_deduped_sigs"
        )
        self._h_occupancy = telemetry.histogram(
            "crypto.superbatch.occupancy", telemetry.COUNT_BUCKETS
        )
        # Fine buckets: flushes at small occupancy finish in tens of µs
        # and the whole 22-26 µs/sig regime sat in DURATION_MS_BUCKETS'
        # first bucket, unreadable.
        self._h_flush_ms = telemetry.histogram(
            "crypto.superbatch.flush_ms", telemetry.FINE_DURATION_MS_BUCKETS
        )
        self._h_per_sig_ms = telemetry.histogram(
            "crypto.superbatch.per_sig_ms", telemetry.FINE_DURATION_MS_BUCKETS
        )

    def verify_batch(self, msgs, pubs, sigs) -> None:
        if not len(msgs) == len(pubs) == len(sigs):
            raise CryptoError("batch length mismatch")
        self._submit(_Request(list(msgs), list(pubs), list(sigs)))

    def verify_cert(self, msgs, pubs, sig_buf, stride: int = 64, key=None) -> None:
        """Fused certificate verification through the same back-pressure
        pool: concurrent verifies of the SAME cert (an in-process committee
        fans one proposal's QC to all N validators) dedup by cert identity
        to one inner MSM. ``key`` is the caller's canonical cert identity;
        without one, the full verify statement is the key."""
        sig_buf = bytes(sig_buf)
        if key is None:
            mk = (
                bytes(msgs)
                if isinstance(msgs, (bytes, bytearray, memoryview))
                else tuple(bytes(m) for m in msgs)
            )
            key = (mk, tuple(bytes(p) for p in pubs), sig_buf, stride)
        req = _Request((), (), ())
        req.cert = (msgs, pubs, sig_buf, stride, key)
        self._submit(req)

    def _submit(self, req: _Request) -> None:
        with self._cv:
            self._pending.append(req)
            # is_alive, not None: a forked child (engine groups) inherits
            # the parent's thread OBJECT but not the running thread — a
            # None check would leave every request waiting on a flusher
            # that does not exist in this process.
            if self._thread is None or not self._thread.is_alive():
                # Dedicated daemon flusher, started on first use. A
                # caller-thread flusher (the previous design) either
                # stalls its own caller for unbounded time under
                # sustained traffic (it must drain pools that keep
                # refilling) or strands the pool when it exits — a
                # dedicated thread has neither failure mode, and an idle
                # device still flushes a lone QC immediately (one
                # condition-variable wake away, ~tens of µs).
                self._thread = threading.Thread(
                    target=self._flusher_loop, daemon=True, name="superbatch"
                )
                self._thread.start()
            self._cv.notify()
        req.done.wait()
        if req.error is not None:
            raise req.error

    def _flusher_loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending:
                    self._cv.wait()
                batch = self._pending
                self._pending = []
            try:
                self._flush(batch)
            except BaseException:  # noqa: BLE001
                # _flush's own finally released every waiter (error set,
                # never silently accepted); the flusher must survive even
                # interpreter-level interrupts or all later requests
                # would wait forever.
                pass

    def _flush(self, batch: list[_Request]) -> None:
        certs = [r for r in batch if r.cert is not None]
        triples = [r for r in batch if r.cert is None]
        self.fused_requests += len(batch)
        self._m_requests.inc(len(triples))
        if certs:
            self.cert_requests += len(certs)
            self._m_cert_requests.inc(len(certs))
        self._m_flushes.inc()
        self._h_occupancy.observe(len(batch))
        t0 = time.perf_counter()
        fused_ok = False
        try:
            if certs:
                self._flush_certs(certs)
            # Dedup identical (msg, pub, sig) triples across the fused
            # requests: verifying the DISTINCT set decides the multiset —
            # every duplicate is the same mathematical statement, and the
            # RLC covers each distinct triple with its own random
            # coefficient, so soundness is unchanged. This is the big
            # win under contention: certificates are REBROADCAST (every
            # timeout in a view change carries the same high_qc; every
            # proposal fans the same QC to all N validators of an
            # in-process committee sharing this backend), so a fused
            # window routinely holds N copies of one QC — priced here at
            # one, not N. If the deduped batch fails, each request is
            # still re-verified separately below (exact per-request
            # verdicts, nothing poisoned).
            if not triples:
                return  # finally still prices the flush
            seen = set()
            msgs, pubs, sigs = [], [], []
            for r in triples:
                for m, p, s in zip(r.msgs, r.pubs, r.sigs):
                    key = (m, p, s)
                    if key in seen:
                        continue
                    seen.add(key)
                    msgs.append(m)
                    pubs.append(p)
                    sigs.append(s)
            removed = sum(len(r.msgs) for r in triples) - len(msgs)
            self.deduped_sigs += removed
            self._m_deduped.inc(removed)
            try:
                self.inner_calls += 1
                if len(msgs) <= self.max_sigs:
                    self.inner.verify_batch(msgs, pubs, sigs)
                    fused_ok = True
                else:
                    # Oversized fusion: verify per request (still one call
                    # per QC, the non-fused baseline).
                    raise CryptoError("fused batch too large")
            except Exception:
                # Isolate: one bad request must not fail its neighbors —
                # and a NON-crypto failure (JAX RuntimeError, device/tunnel
                # death) must fail loudly, not wedge every waiter.
                for r in triples:
                    try:
                        self.inner_calls += 1
                        self.inner.verify_batch(r.msgs, r.pubs, r.sigs)
                    except CryptoError as e:
                        r.error = e
                    except Exception as e:
                        # Distinguishable from an invalid signature: the
                        # request was NOT judged (transient infrastructure
                        # failure, e.g. device/tunnel death).
                        r.error = BackendUnavailable(
                            f"verification backend failure: {e!r}"
                        )
                    finally:
                        r.done.set()
        finally:
            # Nobody may be left waiting. A request released without having
            # been verified is REJECTED (error set), never accepted.
            for r in batch:
                if not r.done.is_set():
                    if not fused_ok and r.error is None:
                        r.error = BackendUnavailable(
                            "verification flush aborted"
                        )
                    r.done.set()
            elapsed_ms = (time.perf_counter() - t0) * 1e3
            self._h_flush_ms.observe(elapsed_ms)
            n_sigs = sum(r.nsigs() for r in batch)
            if n_sigs:
                # Amortized per-signature cost of the flush — directly
                # comparable with the bench corpus's µs/sig rows (the
                # 0.022-0.026 ms regime the fine buckets resolve).
                self._h_per_sig_ms.observe(elapsed_ms / n_sigs)

    def _flush_certs(self, certs: list[_Request]) -> None:
        """Verify the DISTINCT certs of a fused window, one inner MSM each.

        Certs dedup by identity, not per-triple: a cert's verify statement
        is atomic (one bitmap + one buffer), and concurrent requests for
        the same cert are the same statement — priced at one. Each request
        gets its own verdict object; a bad cert fails only its own waiters.
        """
        groups: dict = {}
        for r in certs:
            groups.setdefault(r.cert[4], []).append(r)
        removed = sum(
            len(rs[0].cert[1]) * (len(rs) - 1) for rs in groups.values()
        )
        self.cert_deduped_sigs += removed
        self._m_cert_deduped.inc(removed)
        fused = getattr(self.inner, "verify_cert", None)
        for rs in groups.values():
            msgs, pubs, sig_buf, stride, _key = rs[0].cert
            err_text = None
            unavailable = None
            try:
                self.inner_calls += 1
                if fused is not None:
                    fused(msgs, pubs, sig_buf, stride)
                else:
                    m, p, s = _explode_cert(
                        msgs, pubs, sig_buf, stride, len(pubs)
                    )
                    self.inner.verify_batch(m, p, s)
            except CryptoError as e:
                err_text = str(e)
            except Exception as e:
                unavailable = f"verification backend failure: {e!r}"
            for r in rs:
                # Fresh exception per waiter: one instance raised from
                # several threads would race on __traceback__.
                if err_text is not None:
                    r.error = CryptoError(err_text)
                elif unavailable is not None:
                    r.error = BackendUnavailable(unavailable)
                r.done.set()


def enable_superbatching(
    window_ms: float | None = None, max_sigs: int = 8192
) -> BatchingBackend:
    """Wrap the currently-selected backend (idempotent)."""
    current = get_backend()
    if isinstance(current, BatchingBackend):
        return current
    wrapped = BatchingBackend(current, window_ms=window_ms, max_sigs=max_sigs)
    set_backend(wrapped)
    return wrapped
