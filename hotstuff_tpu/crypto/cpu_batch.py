"""CPU batched Ed25519 verification: RLC + Pippenger multi-scalar mul.

The honest CPU bar from the reference: dalek's ``verify_batch``
(``/root/reference/crypto/src/lib.rs:206-219``) is not a serial loop — it
folds the whole batch into ONE multi-scalar multiplication

    8·[ (-sum z_i s_i mod L)·B + sum z_i·R_i + sum (z_i h_i mod L)·A_i ] == O

with random 128-bit z_i, evaluated by a Straus/Pippenger MSM. This module
implements the same equation with the same algorithm (bucketed Pippenger,
window size chosen by batch size) in pure Python over ``ed25519_ref``'s
extended coordinates, so ``bench.py`` can report the device speedup against
batch-verify *semantics and algorithm*, not just against a serial loop.

Pure Python big-int arithmetic is the limit here (~2 µs per point add); on
this box the native serial OpenSSL loop and this batched verifier land in
the same range, and bench.py reports both honestly.
"""

from __future__ import annotations

import hashlib
import secrets

from .ed25519_ref import (
    G,
    IDENTITY,
    L,
    compute_challenge,
    is_identity,
    point_add,
    point_double,
    point_decompress,
    point_mul,
)


def best_verify_batch():
    """The fastest CPU batch-verify implementation available on this host:
    the native C++ engine when its shared library is built, else the
    pure-Python Pippenger below. Both take ``(msgs, pubs, sigs, rng=...)``."""
    try:
        from .native_ed25519 import native_available, verify_batch_native

        if native_available():
            return verify_batch_native
    except ImportError:
        pass
    return verify_batch_rlc_pippenger


# -- fused aggregate-certificate verification ------------------------------
#
# A wire-v2 certificate arrives as a seat bitmap plus one packed signature
# buffer; the fused path verifies the whole cert as ONE RLC equation over
# that buffer without materializing per-signature objects. The RLC
# coefficients are DERANDOMIZED Fiat–Shamir style: z_i is derived by
# hashing the full verify statement (domain tag, message(s), every public
# key, the raw signature buffer), so they are (a) reproducible — the same
# cert always folds with the same coefficients, which the process-wide
# cert-verdict arena and cross-backend equivalence tests rely on — and
# (b) sound — an adversary choosing signatures cannot choose them
# independently of the coefficients, exactly the argument that makes
# deterministic-challenge batch verification as strong as random z_i
# (each z_i is still a full 128-bit value with the top bit pinned, the
# same distribution dalek's verify_batch samples).

_CERT_RLC_DOMAIN = b"hs-agg-qc-v1"


def _cert_msg_at(msgs, i: int) -> bytes:
    """Message for seat ``i``: certs over one statement (QC) pass a single
    bytes object; per-seat statements (TC high-qc rounds) pass a list."""
    if isinstance(msgs, (bytes, bytearray, memoryview)):
        return bytes(msgs)
    return msgs[i]


def cert_rlc_coefficients(msgs, pubs, sig_buf, stride: int, n: int) -> list[int]:
    """Deterministic 128-bit RLC coefficients for a fused cert verify.

    seed = SHA-512(domain || len-prefixed message(s) || pubs || sig_buf);
    the coefficient stream is SHAKE-256(seed), 16 bytes per seat, top bit
    pinned so every z_i is exactly 128 bits (matching the sampled-z path).
    """
    h = hashlib.sha512()
    h.update(_CERT_RLC_DOMAIN)
    if isinstance(msgs, (bytes, bytearray, memoryview)):
        h.update(len(msgs).to_bytes(8, "little"))
        h.update(bytes(msgs))
    else:
        for m in msgs:
            h.update(len(m).to_bytes(8, "little"))
            h.update(bytes(m))
    for pub in pubs:
        h.update(bytes(pub))
    h.update(bytes(sig_buf))
    stream = hashlib.shake_256(h.digest()).digest(16 * n)
    return [
        int.from_bytes(stream[16 * i : 16 * i + 16], "little") | (1 << 127)
        for i in range(n)
    ]


def verify_cert_rlc(msgs, pubs, sig_buf, stride: int = 64, c: int = 8) -> bool:
    """Pure-Python fused cert verification (reference for the native path).

    ``pubs``: n public keys; ``sig_buf``: packed signatures at ``stride``
    bytes per record (signature in the first 64); ``msgs``: one shared
    bytes statement or a per-seat list. One RLC + Pippenger MSM over the
    whole cert with deterministic coefficients; same canonicality
    rejections as ``verify_batch_rlc_pippenger``.
    """
    n = len(pubs)
    if n == 0:
        return True
    if len(sig_buf) < stride * (n - 1) + 64:
        return False
    zs = cert_rlc_coefficients(msgs, pubs, sig_buf, stride, n)
    scalars: list[int] = []
    points: list = []
    b_coeff = 0
    for i in range(n):
        pub = bytes(pubs[i])
        r_enc = bytes(sig_buf[stride * i : stride * i + 32])
        s = int.from_bytes(sig_buf[stride * i + 32 : stride * i + 64], "little")
        if len(pub) != 32 or s >= L:
            return False
        a_pt = point_decompress(pub)
        r_pt = point_decompress(r_enc)
        if a_pt is None or r_pt is None:
            return False
        z = zs[i]
        h = compute_challenge(r_enc, pub, _cert_msg_at(msgs, i))
        b_coeff = (b_coeff + z * s) % L
        scalars.append(z)
        points.append(r_pt)
        scalars.append(z * h % L)
        points.append(a_pt)
    scalars.append((-b_coeff) % L)
    points.append(G)
    acc = _pippenger(scalars, points, c)
    return is_identity(point_mul(8, acc))


def _pippenger(scalars: list[int], points: list, c: int) -> tuple:
    """Bucketed MSM: sum scalars[i] * points[i], window width ``c`` bits."""
    n_windows = (max(s.bit_length() for s in scalars) + c - 1) // c if scalars else 1
    acc = IDENTITY
    for w in range(n_windows - 1, -1, -1):
        if acc is not IDENTITY:
            for _ in range(c):
                acc = point_double(acc)
        buckets: dict[int, tuple] = {}
        shift = w * c
        mask = (1 << c) - 1
        for s, pt in zip(scalars, points):
            d = (s >> shift) & mask
            if d == 0:
                continue
            cur = buckets.get(d)
            buckets[d] = pt if cur is None else point_add(cur, pt)
        if not buckets:
            continue
        # Bucket sweep: sum_d d * bucket[d] via running suffix sums.
        running = IDENTITY
        window_sum = IDENTITY
        for d in range(max(buckets), 0, -1):
            pt = buckets.get(d)
            if pt is not None:
                running = point_add(running, pt)
            window_sum = point_add(window_sum, running)
        acc = point_add(acc, window_sum)
    return acc


def verify_batch_rlc_pippenger(msgs, pubs, sigs, rng=None, c: int = 8) -> bool:
    """Batch verification, dalek ``verify_batch`` algorithm on CPU.

    msgs/pubs/sigs: equal-length lists of bytes. True iff the whole batch
    verifies under cofactored semantics. Rejects non-canonical encodings
    host-side exactly like the device pipeline (``ops.verify``).
    """
    if not len(msgs) == len(pubs) == len(sigs):
        raise ValueError("batch length mismatch")
    randbits = rng.getrandbits if rng is not None else secrets.randbits

    scalars: list[int] = []
    points: list = []
    b_coeff = 0
    for msg, pub, sig in zip(msgs, pubs, sigs):
        if len(sig) != 64 or len(pub) != 32:
            return False
        a_pt = point_decompress(pub)
        r_pt = point_decompress(sig[:32])
        if a_pt is None or r_pt is None:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            return False
        z = randbits(128) | (1 << 127)
        h = compute_challenge(sig[:32], pub, msg)
        b_coeff = (b_coeff + z * s) % L
        scalars.append(z)
        points.append(r_pt)
        scalars.append(z * h % L)
        points.append(a_pt)
    scalars.append((-b_coeff) % L)
    points.append(G)

    acc = _pippenger(scalars, points, c)
    return is_identity(point_mul(8, acc))
