"""ctypes binding for the native C++ Ed25519 batch-verification engine.

Builds ``libhsed25519.so`` lazily with g++ on first use (same pattern as
the native store engine — plain ctypes over a C ABI). The C++ side
evaluates the random-linear-combination MSM; this module does the host
prep exactly like the device pipeline (``ops/verify.py``): strictness
checks (canonical s < L, canonical y), SHA-512 challenges, and the RLC
scalar arithmetic mod L.

This is the honest CPU bar for the benchmark — dalek ``verify_batch``
semantics AND algorithm (reference ``crypto/src/lib.rs:206-219``) at
native speed — and doubles as a fast batched CPU fallback backend for
nodes without a reachable device.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import secrets
import subprocess

from .ed25519_ref import G, L, P, point_compress

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SRC = os.path.join(_DIR, "ed25519.cpp")
_LIB = os.path.join(_DIR, "libhsed25519.so")

_B_ENC = point_compress(G)
_HALF_MASK = (1 << 255) - 1


def _is_built() -> bool:
    return os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)


def _ensure_built() -> str:
    if not _is_built():
        # Per-pid temp name: concurrent builders (bench + node + tests)
        # must not corrupt each other's output mid-os.replace.
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _LIB)
    return _LIB


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(_ensure_built())
        lib.hs_ed25519_msm_is_identity.restype = ctypes.c_int
        lib.hs_ed25519_msm_is_identity.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.hs_ed25519_decompress_check.restype = ctypes.c_int
        lib.hs_ed25519_decompress_check.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.hs_ed25519_msm_signed.restype = ctypes.c_int
        lib.hs_ed25519_msm_signed.argtypes = [
            ctypes.c_char_p,  # encodings (m*32)
            ctypes.c_char_p,  # pre_xy (m*64), may be None
            ctypes.c_char_p,  # flags (m), may be None
            ctypes.c_char_p,  # scalars (m*32)
            ctypes.c_uint64,
            ctypes.c_int,  # window width
            ctypes.c_int,  # cofactored (0 = strict/cofactorless sum)
        ]
        lib.hs_ed25519_scalarmult_base.restype = ctypes.c_int
        lib.hs_ed25519_scalarmult_base.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        lib.hs_ed25519_cert_challenges.restype = ctypes.c_int
        lib.hs_ed25519_cert_challenges.argtypes = [
            ctypes.c_char_p,  # shared message
            ctypes.c_uint64,  # message length
            ctypes.c_char_p,  # pubs (n*32)
            ctypes.c_char_p,  # packed signature buffer (n*stride)
            ctypes.c_uint64,  # stride (>= 64)
            ctypes.c_uint64,  # n
            ctypes.c_char_p,  # out (n*64 digests)
        ]
        lib.hs_ed25519_stats.restype = ctypes.c_int
        lib.hs_ed25519_stats.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int,
        ]
        _lib = lib
        # The engine's counters surface through the registry's single
        # snapshot call once the library is live.
        from hotstuff_tpu import telemetry
        from hotstuff_tpu.telemetry import profiler as _pyprof

        telemetry.register_collector("crypto.native", native_stats)
        # Instrumentable ctypes boundary: an active profiler session
        # counts calls + wall ns per entry point (the per-call GIL
        # release/reacquire toll); zero cost otherwise.
        _pyprof.register_ctypes_lib(
            lib,
            "hs_ed25519",
            [
                "hs_ed25519_msm_is_identity", "hs_ed25519_msm_signed",
                "hs_ed25519_decompress_check", "hs_ed25519_scalarmult_base",
                "hs_ed25519_cert_challenges",
            ],
        )
    return _lib


# hs_ed25519_stats field order (new fields append; indices never move).
ED25519_STATS_FIELDS = (
    "msm_calls", "msm_points", "scalarmult_calls", "decompress_calls",
    "cert_challenge_calls", "cert_challenge_sigs",
)


def native_stats() -> dict[str, int]:
    """Engine counter snapshot: verify-side MSM evaluations/lanes plus
    sign/derive basepoint multiplications — one call exports them all."""
    out = (ctypes.c_uint64 * len(ED25519_STATS_FIELDS))()
    n = _load().hs_ed25519_stats(out, len(ED25519_STATS_FIELDS))
    return {name: out[i] for i, name in enumerate(ED25519_STATS_FIELDS[:n])}


def native_available(build: bool = True) -> bool:
    """True if the shared library is loadable on this host.

    ``build=False`` only probes for an already-built library — callers on
    a latency-sensitive path (the consensus backend) must not block on a
    g++ compile; the library ships prebuilt and tests/bench rebuild it."""
    if not build and not _is_built():
        return False
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


def decompress_check(encoding: bytes) -> bool:
    """Native single-point decompression probe (test hook)."""
    return _load().hs_ed25519_decompress_check(encoding, None) == 1


def scalarmult_base_native(scalar: int) -> bytes:
    """Compressed encoding of ``scalar * B`` (``scalar`` already reduced
    mod L). Powers signing/public-key derivation when the ``cryptography``
    package is unavailable. Variable-time in the scalar (comb indexing) —
    fine for this research testbed, noted here for production readers."""
    out = ctypes.create_string_buffer(32)
    rc = _load().hs_ed25519_scalarmult_base(
        scalar.to_bytes(32, "little"), out
    )
    if rc != 1:
        raise ValueError("native scalarmult rejected arguments")
    return bytes(out.raw)


# Decompressed-point cache: committee public keys recur in every QC this
# process ever verifies, and decompression (a field sqrt) is ~35% of a
# 67-signature batch on this box. A real validator decompresses each
# committee key once per epoch (the CPU analog of the device
# DevicePointCache), so sharing this across in-process testbed nodes
# models per-epoch amortization, not skipped per-round work. R points are
# per-signature nonces and never hit the cache.
_XY_CACHE_CAP = 4096
_xy_cache: dict[bytes, bytes] = {}


def _cached_xy(pub: bytes):
    """64-byte affine x|y for a compressed key, or None if invalid."""
    xy = _xy_cache.get(pub)
    if xy is not None:
        return xy
    out = ctypes.create_string_buffer(64)
    if _load().hs_ed25519_decompress_check(pub, out) != 1:
        return None
    if len(_xy_cache) >= _XY_CACHE_CAP:
        _xy_cache.clear()  # epoch-scale working sets never get here
    xy = bytes(out.raw)
    _xy_cache[pub] = xy
    return xy


def verify_batch_native(msgs, pubs, sigs, rng=None) -> bool:
    """Batch verification on the native engine.

    msgs/pubs/sigs: equal-length lists of bytes. True iff the whole batch
    is valid under cofactored semantics — the same host-side prep and
    rejection rules as the device pipeline (``ops.verify.prepare_batch``).
    Public-key and basepoint decompressions are cached; the MSM runs the
    signed-digit kernel (halved bucket sweep).
    """
    if not len(msgs) == len(pubs) == len(sigs):
        raise ValueError("batch length mismatch")
    if len(msgs) == 0:
        return True
    randbits = rng.getrandbits if rng is not None else secrets.randbits

    n = len(msgs)
    m = 2 * n + 1
    encodings = bytearray()
    pre_xy = bytearray()
    flags = bytearray()
    scalars = bytearray()
    zero64 = bytes(64)
    b_coeff = 0
    for msg, pub, sig in zip(msgs, pubs, sigs):
        if len(sig) != 64 or len(pub) != 32:
            return False
        r_enc, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= L:  # non-canonical s: reject (RFC 8032 / dalek)
            return False
        if (int.from_bytes(pub, "little") & _HALF_MASK) >= P:
            return False
        if (int.from_bytes(r_enc, "little") & _HALF_MASK) >= P:
            return False
        z = randbits(128) | (1 << 127)
        h = int.from_bytes(hashlib.sha512(r_enc + pub + msg).digest(), "little") % L
        b_coeff = (b_coeff + z * s) % L
        encodings += r_enc
        pre_xy += zero64
        flags.append(0)
        scalars += z.to_bytes(32, "little")
        xy = _cached_xy(bytes(pub))
        if xy is None:
            return False  # invalid public key (same verdict as in-MSM)
        encodings += pub
        pre_xy += xy
        flags.append(1)
        scalars += (z * h % L).to_bytes(32, "little")
    encodings += _B_ENC
    pre_xy += _cached_xy(_B_ENC)
    flags.append(1)
    scalars += ((-b_coeff) % L).to_bytes(32, "little")

    rc = _load().hs_ed25519_msm_signed(
        bytes(encodings),
        bytes(pre_xy),
        bytes(flags),
        bytes(scalars),
        m,
        _signed_window(m),
        1,
    )
    if rc < 0:
        raise ValueError("native ed25519 engine rejected arguments")
    return rc == 1


def verify_cert_native(msgs, pubs, sig_buf, stride: int = 64) -> bool:
    """Fused aggregate-certificate verification on the native engine.

    ``pubs``: n public keys; ``sig_buf``: the cert's packed signature
    buffer at ``stride`` bytes per record (signature in the first 64);
    ``msgs``: one shared bytes statement (QC) or a per-seat list (TC).
    One RLC equation over the whole cert — the n challenge hashes run
    behind a single ctypes crossing when the message is shared, the RLC
    coefficients are the deterministic Fiat–Shamir stream from
    ``cpu_batch.cert_rlc_coefficients``, and the whole cert folds into
    one signed-digit MSM (m = 2n+1 lanes). Same canonicality rejections
    as ``verify_batch_native``.
    """
    n = len(pubs)
    if n == 0:
        return True
    sig_buf = bytes(sig_buf)
    if len(sig_buf) < stride * (n - 1) + 64:
        return False
    from .cpu_batch import cert_rlc_coefficients

    zs = cert_rlc_coefficients(msgs, pubs, sig_buf, stride, n)
    lib = _load()

    pubs_buf = b"".join(bytes(p) for p in pubs)
    if len(pubs_buf) != 32 * n:
        return False
    shared = isinstance(msgs, (bytes, bytearray, memoryview))
    if shared:
        msg = bytes(msgs)
        digests = ctypes.create_string_buffer(64 * n)
        rc = lib.hs_ed25519_cert_challenges(
            msg, len(msg), pubs_buf, sig_buf, stride, n, digests
        )
        if rc != 1:
            raise ValueError("native cert-challenge engine rejected arguments")
        digests = digests.raw
    else:
        digests = b"".join(
            hashlib.sha512(
                sig_buf[stride * i : stride * i + 32]
                + pubs_buf[32 * i : 32 * i + 32]
                + bytes(msgs[i])
            ).digest()
            for i in range(n)
        )

    m = 2 * n + 1
    encodings = bytearray()
    pre_xy = bytearray()
    flags = bytearray()
    scalars = bytearray()
    zero64 = bytes(64)
    b_coeff = 0
    for i in range(n):
        base = stride * i
        r_enc = sig_buf[base : base + 32]
        s = int.from_bytes(sig_buf[base + 32 : base + 64], "little")
        if s >= L:  # non-canonical s: reject (RFC 8032 / dalek)
            return False
        pub = pubs_buf[32 * i : 32 * i + 32]
        if (int.from_bytes(pub, "little") & _HALF_MASK) >= P:
            return False
        if (int.from_bytes(r_enc, "little") & _HALF_MASK) >= P:
            return False
        z = zs[i]
        h = int.from_bytes(digests[64 * i : 64 * i + 64], "little") % L
        b_coeff = (b_coeff + z * s) % L
        encodings += r_enc
        pre_xy += zero64
        flags.append(0)
        scalars += z.to_bytes(32, "little")
        xy = _cached_xy(pub)
        if xy is None:
            return False  # invalid public key (same verdict as in-MSM)
        encodings += pub
        pre_xy += xy
        flags.append(1)
        scalars += (z * h % L).to_bytes(32, "little")
    encodings += _B_ENC
    pre_xy += _cached_xy(_B_ENC)
    flags.append(1)
    scalars += ((-b_coeff) % L).to_bytes(32, "little")

    rc = lib.hs_ed25519_msm_signed(
        bytes(encodings),
        bytes(pre_xy),
        bytes(flags),
        bytes(scalars),
        m,
        _signed_window(m),
        1,
    )
    if rc < 0:
        raise ValueError("native ed25519 engine rejected arguments")
    return rc == 1


def verify_single_strict_native(msg: bytes, pub: bytes, sig: bytes) -> bool:
    """COFACTORLESS single verification: s B - R - h A == identity — the
    exact equation OpenSSL / dalek ``verify_strict`` check, evaluated as
    one 3-point MSM on the native engine. Used for ``Signature.verify``
    when the ``cryptography`` package is unavailable, so gated and
    non-gated processes share one strict acceptance set. The caller is
    responsible for the small-order/canonical-encoding rejections."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    r_enc, s_bytes = sig[:32], sig[32:]
    s = int.from_bytes(s_bytes, "little")
    if s >= L:  # non-canonical s: reject (RFC 8032 / dalek / OpenSSL)
        return False
    if (int.from_bytes(pub, "little") & _HALF_MASK) >= P:
        return False
    if (int.from_bytes(r_enc, "little") & _HALF_MASK) >= P:
        return False
    xy = _cached_xy(bytes(pub))
    if xy is None:
        return False
    h = int.from_bytes(hashlib.sha512(r_enc + pub + msg).digest(), "little") % L
    encodings = r_enc + pub + _B_ENC
    pre_xy = bytes(64) + xy + _cached_xy(_B_ENC)
    flags = bytes([0, 1, 1])
    scalars = (
        (L - 1).to_bytes(32, "little")  # -1 * R
        + ((L - h) % L).to_bytes(32, "little")  # -h * A
        + s.to_bytes(32, "little")  # s * B
    )
    rc = _load().hs_ed25519_msm_signed(
        encodings, pre_xy, flags, scalars, 3, _signed_window(3), 0
    )
    if rc < 0:
        raise ValueError("native ed25519 engine rejected arguments")
    return rc == 1


def _pippenger_window(m: int) -> int:
    """Window width minimizing (253/c) * (m + 2^(c+1)) point additions."""
    return min(range(1, 13), key=lambda c: (253 / c) * (m + (1 << (c + 1))))


def _signed_window(m: int) -> int:
    """Window width for the signed-digit kernel: the sweep costs two adds
    per bucket and buckets number 2^(c-1)."""
    return min(range(1, 13), key=lambda c: (253 / c) * (m + (1 << c)))
