"""ctypes binding for the native C++ Ed25519 batch-verification engine.

Builds ``libhsed25519.so`` lazily with g++ on first use (same pattern as
the native store engine — plain ctypes over a C ABI). The C++ side
evaluates the random-linear-combination MSM; this module does the host
prep exactly like the device pipeline (``ops/verify.py``): strictness
checks (canonical s < L, canonical y), SHA-512 challenges, and the RLC
scalar arithmetic mod L.

This is the honest CPU bar for the benchmark — dalek ``verify_batch``
semantics AND algorithm (reference ``crypto/src/lib.rs:206-219``) at
native speed — and doubles as a fast batched CPU fallback backend for
nodes without a reachable device.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import secrets
import subprocess

from .ed25519_ref import G, L, P, point_compress

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SRC = os.path.join(_DIR, "ed25519.cpp")
_LIB = os.path.join(_DIR, "libhsed25519.so")

_B_ENC = point_compress(G)
_HALF_MASK = (1 << 255) - 1


def _is_built() -> bool:
    return os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)


def _ensure_built() -> str:
    if not _is_built():
        # Per-pid temp name: concurrent builders (bench + node + tests)
        # must not corrupt each other's output mid-os.replace.
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _LIB)
    return _LIB


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(_ensure_built())
        lib.hs_ed25519_msm_is_identity.restype = ctypes.c_int
        lib.hs_ed25519_msm_is_identity.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.hs_ed25519_decompress_check.restype = ctypes.c_int
        lib.hs_ed25519_decompress_check.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        _lib = lib
    return _lib


def native_available(build: bool = True) -> bool:
    """True if the shared library is loadable on this host.

    ``build=False`` only probes for an already-built library — callers on
    a latency-sensitive path (the consensus backend) must not block on a
    g++ compile; the library ships prebuilt and tests/bench rebuild it."""
    if not build and not _is_built():
        return False
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


def decompress_check(encoding: bytes) -> bool:
    """Native single-point decompression probe (test hook)."""
    return _load().hs_ed25519_decompress_check(encoding, None) == 1


def verify_batch_native(msgs, pubs, sigs, rng=None) -> bool:
    """Batch verification on the native engine.

    msgs/pubs/sigs: equal-length lists of bytes. True iff the whole batch
    is valid under cofactored semantics — the same host-side prep and
    rejection rules as the device pipeline (``ops.verify.prepare_batch``).
    """
    if not len(msgs) == len(pubs) == len(sigs):
        raise ValueError("batch length mismatch")
    if len(msgs) == 0:
        return True
    randbits = rng.getrandbits if rng is not None else secrets.randbits

    encodings = bytearray()
    scalars = bytearray()
    b_coeff = 0
    for msg, pub, sig in zip(msgs, pubs, sigs):
        if len(sig) != 64 or len(pub) != 32:
            return False
        r_enc, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= L:  # non-canonical s: reject (RFC 8032 / dalek)
            return False
        if (int.from_bytes(pub, "little") & _HALF_MASK) >= P:
            return False
        if (int.from_bytes(r_enc, "little") & _HALF_MASK) >= P:
            return False
        z = randbits(128) | (1 << 127)
        h = int.from_bytes(hashlib.sha512(r_enc + pub + msg).digest(), "little") % L
        b_coeff = (b_coeff + z * s) % L
        encodings += r_enc
        scalars += z.to_bytes(32, "little")
        encodings += pub
        scalars += (z * h % L).to_bytes(32, "little")
    encodings += _B_ENC
    scalars += ((-b_coeff) % L).to_bytes(32, "little")

    m = len(encodings) // 32
    rc = _load().hs_ed25519_msm_is_identity(
        bytes(encodings), bytes(scalars), m, _pippenger_window(m)
    )
    if rc < 0:
        raise ValueError("native ed25519 engine rejected arguments")
    return rc == 1


def _pippenger_window(m: int) -> int:
    """Window width minimizing (253/c) * (m + 2^(c+1)) point additions."""
    return min(range(1, 13), key=lambda c: (253 / c) * (m + (1 << (c + 1))))
