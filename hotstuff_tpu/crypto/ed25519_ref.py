"""Pure-Python Ed25519 (RFC 8032) — the correctness oracle.

This is the bit-exact reference the TPU kernels (``hotstuff_tpu.ops``) are
property-tested against. It is written from the RFC 8032 specification: field
GF(2^255-19), twisted Edwards curve -x^2 + y^2 = 1 + d x^2 y^2, extended
homogeneous coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, x*y = T/Z.

Not used on the node hot path (the CPU production backend is OpenSSL via the
``cryptography`` package; the device backend is JAX). Mirrors the semantics of
the reference implementation's ed25519-dalek usage: sign/verify over 32-byte
digests (reference ``crypto/src/lib.rs:177-220``).
"""

from __future__ import annotations

import hashlib
import secrets

P = 2**255 - 19
# Group order L = 2^252 + delta.
L = 2**252 + 27742317777372353535851937790883648493
# Curve constant d = -121665/121666 mod p.
D = (-121665 * pow(121666, P - 2, P)) % P
# sqrt(-1) mod p, used in decompression.
SQRT_M1 = pow(2, (P - 1) // 4, P)


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def inv(x: int) -> int:
    return pow(x, P - 2, P)


# ---------------------------------------------------------------------------
# Point arithmetic in extended homogeneous coordinates.
# A point is a tuple (X, Y, Z, T). Neutral element: (0, 1, 1, 0).
# ---------------------------------------------------------------------------

IDENTITY = (0, 1, 1, 0)


def point_add(p, q):
    """Unified addition (RFC 8032 section 5.1.4)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_double(p):
    """Dedicated doubling (dbl-2008-hwcd); valid for a = -1 curves."""
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = (a + b) % P
    e = (h - (x1 + y1) * (x1 + y1)) % P
    g = (a - b) % P
    f = (c + g) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_mul(s: int, p):
    """Scalar multiplication by double-and-add (LSB-first)."""
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_double(p)
        s >>= 1
    return q


def point_equal(p, q) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def is_identity(p) -> bool:
    return point_equal(p, IDENTITY)


def recover_x(y: int, sign: int) -> int | None:
    """x from y via x^2 = (y^2-1)/(d y^2+1); None if not on curve."""
    if y >= P:
        return None
    x2 = (y * y - 1) * inv(D * y * y + 1) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    # Square root by exponentiation to (p+3)/8.
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


# Base point: y = 4/5, x recovered with even sign.
_BY = 4 * inv(5) % P
_BX = recover_x(_BY, 0)
G = (_BX, _BY, 1, _BX * _BY % P)


def point_compress(p) -> bytes:
    x, y, z, _ = p
    zi = inv(z)
    x, y = x * zi % P, y * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress(s: bytes):
    """Decompress 32 bytes to a point; None if invalid.

    Rejects non-canonical y (y >= p), matching dalek/RFC strictness on field
    element decoding.
    """
    if len(s) != 32:
        return None
    val = int.from_bytes(s, "little")
    sign = val >> 255
    y = val & ((1 << 255) - 1)
    if y >= P:
        return None
    x = recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def is_small_order(p) -> bool:
    """True if the point is in the 8-torsion subgroup."""
    return is_identity(point_mul(8, p))


def torsion_generator():
    """A point of exact order 8 (generator of the torsion subgroup).

    Found by clearing the prime-order component (L*Q) of deterministic
    pseudo-random curve points until one of full order 8 remains.
    """
    import random as _random

    rng = _random.Random(0xED25519)
    while True:
        y = rng.randrange(P)
        x = recover_x(y, 0)
        if x is None:
            continue
        t = point_mul(L, (x, y, 1, x * y % P))
        if not is_identity(point_mul(4, t)):
            return t


# ---------------------------------------------------------------------------
# Keys and signatures (RFC 8032 section 5.1.5-5.1.7).
# ---------------------------------------------------------------------------


def secret_expand(seed: bytes) -> tuple[int, bytes]:
    """Expand a 32-byte seed into the clamped scalar and the hash prefix."""
    h = _sha512(seed)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def secret_to_public(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return point_compress(point_mul(a, G))


def sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix = secret_expand(seed)
    pub = point_compress(point_mul(a, G))
    r = int.from_bytes(_sha512(prefix + msg), "little") % L
    big_r = point_compress(point_mul(r, G))
    h = int.from_bytes(_sha512(big_r + pub + msg), "little") % L
    s = (r + h * a) % L
    return big_r + int.to_bytes(s, 32, "little")


def compute_challenge(big_r: bytes, pub: bytes, msg: bytes) -> int:
    """h = SHA-512(R || A || M) mod L — the per-signature challenge scalar."""
    return int.from_bytes(_sha512(big_r + pub + msg), "little") % L


def verify(pub: bytes, msg: bytes, sig: bytes, *, strict: bool = True) -> bool:
    """Verify a signature.

    ``strict=True`` mirrors dalek's ``verify_strict`` (reference
    ``crypto/src/lib.rs:200-204``): canonical s, canonical point encodings,
    and neither A nor R of small order; checks the cofactorless equation
    s·B == R + h·A. ``strict=False`` checks the cofactored equation
    8s·B == 8R + 8h·A (RFC 8032 semantics, matching dalek's batch verifier).
    """
    if len(sig) != 64 or len(pub) != 32:
        return False
    a_pt = point_decompress(pub)
    if a_pt is None:
        return False
    r_pt = point_decompress(sig[:32])
    if r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    if strict and (is_small_order(a_pt) or is_small_order(r_pt)):
        return False
    h = compute_challenge(sig[:32], pub, msg)
    lhs = point_mul(s, G)
    rhs = point_add(r_pt, point_mul(h, a_pt))
    if strict:
        return point_equal(lhs, rhs)
    return point_equal(point_mul(8, lhs), point_mul(8, rhs))


def verify_batch_rlc(items, rng=None) -> bool:
    """Random-linear-combination batch verification (dalek-equivalent
    semantics of reference ``crypto/src/lib.rs:206-219``).

    ``items`` is a sequence of ``(pub32, msg, sig64)``. Checks

        8·[ (-sum z_i s_i mod L)·B + sum z_i·R_i + sum (z_i h_i mod L)·A_i ] == O

    with independent 128-bit random ``z_i``. This is the exact equation the
    TPU MSM kernel evaluates; kept here as the slow oracle.
    """
    terms = []  # (scalar, point) pairs of the MSM
    b_coeff = 0
    for pub, msg, sig in items:
        if len(sig) != 64:
            return False
        a_pt = point_decompress(pub)
        r_pt = point_decompress(sig[:32])
        if a_pt is None or r_pt is None:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            return False
        z = (rng.getrandbits(128) if rng else secrets.randbits(128)) | 1
        h = compute_challenge(sig[:32], pub, msg)
        b_coeff = (b_coeff + z * s) % L
        terms.append((z, r_pt))
        terms.append((z * h % L, a_pt))
    terms.append(((-b_coeff) % L, G))
    acc = IDENTITY
    for scalar, pt in terms:
        acc = point_add(acc, point_mul(scalar, pt))
    return is_identity(point_mul(8, acc))
