"""Crypto layer: digests, Ed25519 keys/signatures, signature service.

Same public surface as the reference crypto crate (``crypto/src/lib.rs:20-250``):
``Digest``, ``PublicKey``, ``SecretKey``, ``generate_keypair``, ``Signature``
(with ``new``/``verify``/``verify_batch``) and ``SignatureService``. All
protocol digests are SHA-512 truncated to 32 bytes and signatures sign the
32-byte digest, never the raw message (reference ``crypto/src/lib.rs:185``,
``consensus/src/messages.rs:79-90``).

Batch verification is a pluggable backend: ``cpu`` (OpenSSL per-signature
loop) or ``tpu`` (JAX random-linear-combination MSM on device), optionally
wrapped for multi-round super-batching (``cpu-batched``/``tpu-batched``) —
selected via ``set_backend()`` or the ``HOTSTUFF_CRYPTO_BACKEND`` env var. This is the
north-star offload site: QC verification calls ``Signature.verify_batch`` with
the 2f+1 vote signatures of a quorum certificate.
"""

from __future__ import annotations

import base64
import hashlib
import os
import secrets
import time

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    _HAVE_PYCA = True
except ImportError:  # gated: some deploy images ship no OpenSSL binding
    InvalidSignature = Ed25519PrivateKey = Ed25519PublicKey = None
    _HAVE_PYCA = False

from . import ed25519_ref


class CryptoError(Exception):
    """Signature or encoding verification failure."""


class BackendUnavailable(CryptoError):
    """The verification BACKEND failed (device/tunnel death, JAX runtime
    error) — the signatures were NOT judged. Callers must treat this as
    transient infrastructure failure, never as a byzantine signature:
    recording it in bad-signature caches would blacklist honest validators
    for the round."""


class Digest:
    """32-byte hash value; base64 display (reference ``crypto/src/lib.rs:20-62``)."""

    SIZE = 32
    __slots__ = ("data",)

    def __init__(self, data: bytes) -> None:
        if len(data) != self.SIZE:
            raise ValueError(f"digest must be {self.SIZE} bytes, got {len(data)}")
        # type-check without copying: wire decode hands us immutable
        # bytes already, and these run per decoded signature/key
        self.data = data if type(data) is bytes else bytes(data)

    @classmethod
    def default(cls) -> "Digest":
        return cls(bytes(cls.SIZE))

    def __bytes__(self) -> bytes:
        return self.data

    def __eq__(self, other) -> bool:
        return isinstance(other, Digest) and self.data == other.data

    def __lt__(self, other: "Digest") -> bool:
        return self.data < other.data

    def __hash__(self) -> int:
        return hash(self.data)

    def __repr__(self) -> str:
        return base64.standard_b64encode(self.data).decode()[:16]

    def __str__(self) -> str:
        return base64.standard_b64encode(self.data).decode()


def sha512_digest(*chunks: bytes) -> Digest:
    """SHA-512 truncated to 32 bytes over the concatenated chunks.

    The protocol-wide hash (reference uses ``ed25519_dalek::Sha512`` the same
    way, e.g. ``mempool/src/processor.rs:30``).
    """
    h = hashlib.sha512()
    for c in chunks:
        h.update(c)
    return Digest(h.digest()[:32])


# ---------------------------------------------------------------------------
# Gated Ed25519 signing/derivation: used when the ``cryptography`` package
# (the OpenSSL binding) is not installed. SHA-512 and mod-L scalar
# arithmetic run here; the fixed-base scalar multiplications go to the
# native C++ engine (``crypto/native/ed25519.cpp``), with the pure-Python
# RFC 8032 oracle as the last-resort fallback. RFC 8032 output is
# byte-identical to OpenSSL's, so signatures from gated and non-gated
# processes interoperate.
# ---------------------------------------------------------------------------

_NATIVE_SCALARMULT = None  # resolved lazily: callable, or False if absent


def _scalarmult_base(scalar: int) -> bytes:
    global _NATIVE_SCALARMULT
    if _NATIVE_SCALARMULT is None:
        try:
            from .native_ed25519 import native_available, scalarmult_base_native

            _NATIVE_SCALARMULT = (
                scalarmult_base_native if native_available() else False
            )
        except Exception:  # toolchain unavailable: pure-Python fallback
            _NATIVE_SCALARMULT = False
    if _NATIVE_SCALARMULT:
        return _NATIVE_SCALARMULT(scalar)
    return ed25519_ref.point_compress(ed25519_ref.point_mul(scalar, ed25519_ref.G))


class _GatedSigner:
    """Expanded Ed25519 key for one seed (cached: key expansion is one
    SHA-512 plus a scalar multiplication)."""

    __slots__ = ("a", "prefix", "pub")

    def __init__(self, seed: bytes) -> None:
        self.a, self.prefix = ed25519_ref.secret_expand(seed)
        self.pub = _scalarmult_base(self.a)

    def sign(self, msg: bytes) -> bytes:
        # RFC 8032 signing is deterministic in (key, msg): under the
        # opt-in crypto memo (the simulation plane) repeated signings of
        # byte-identical messages — a sweep's seeds share their
        # fault-free prefixes — skip the scalar multiplication.
        memo = _VERIFY_MEMO
        if memo is not None:
            key = (b"sign", self.prefix, msg)
            sig = memo.get(key)
            if sig is None:
                sig = self._sign_now(msg)
                _memo_put(memo, key, sig)
            return sig
        return self._sign_now(msg)

    def _sign_now(self, msg: bytes) -> bytes:
        r = (
            int.from_bytes(
                hashlib.sha512(self.prefix + msg).digest(), "little"
            )
            % ed25519_ref.L
        )
        big_r = _scalarmult_base(r)
        k = (
            int.from_bytes(
                hashlib.sha512(big_r + self.pub + msg).digest(), "little"
            )
            % ed25519_ref.L
        )
        s = (r + k * self.a) % ed25519_ref.L
        return big_r + s.to_bytes(32, "little")


_SIGNER_CACHE: dict[bytes, _GatedSigner] = {}


def _gated_signer(seed: bytes) -> _GatedSigner:
    signer = _SIGNER_CACHE.get(seed)
    if signer is None:
        if len(_SIGNER_CACHE) >= 4096:  # committees are far smaller
            _SIGNER_CACHE.clear()
        signer = _SIGNER_CACHE[seed] = _GatedSigner(seed)
    return signer


class _StrictSingleBackend:
    """Inner backend for the strict-single fuser: verifies each DISTINCT
    (msg, pub, sig) triple with the native cofactorless 3-point MSM.
    Strictness is per-triple (no RLC across items — a random linear
    combination without cofactor clearing could cancel torsion components
    with probability 1/8 per bad item, which is not a sound strict
    verdict), so the fuser's win is purely the identical-triple dedup:
    a proposal fanned to N in-process validators costs ONE strict MSM
    instead of N."""

    name = "cpu-strict-single"

    def verify_batch(self, msgs, pubs, sigs) -> None:
        from .native_ed25519 import verify_single_strict_native

        for msg, pub, sig in zip(msgs, pubs, sigs):
            if not verify_single_strict_native(msg, pub, sig):
                raise CryptoError("invalid signature")


_STRICT_FUSER = None  # BatchingBackend over _StrictSingleBackend, lazy


def _verify_single_gated(msg: bytes, pub: bytes, sig: bytes) -> bool:
    """Single-signature verification without OpenSSL: the COFACTORLESS
    equation on the native engine (one 3-point MSM), falling back to the
    pure-Python strict oracle — so gated and OpenSSL-backed processes
    share exactly one strict acceptance set (the
    ``test_cofactored_batch_semantics_unified`` contract). The
    small-order/canonicality rejections run in the caller.

    Concurrent strict singles route through a fusing wrapper so
    byte-identical requests (a proposal's author signature verified by
    every in-process validator at once) dedup to one MSM; verdicts stay
    exact per request (the wrapper re-verifies individually if a fused
    flush rejects)."""
    global _STRICT_FUSER
    if _STRICT_FUSER is None:
        try:
            from .native_ed25519 import native_available

            if native_available():
                from .batching import BatchingBackend

                _STRICT_FUSER = BatchingBackend(_StrictSingleBackend())
            else:
                _STRICT_FUSER = False
        except Exception:
            _STRICT_FUSER = False
    if _STRICT_FUSER is False:
        return ed25519_ref.verify(pub, msg, sig, strict=True)
    if _VERIFY_MEMO is not None:
        # Memo mode (the deterministic sim): the caller already dedups
        # byte-identical verifies across time, so the fuser's concurrent
        # dedup buys nothing and its cross-thread handoff (~0.2 ms per
        # request) would dominate a simulated round. One direct MSM.
        from .native_ed25519 import verify_single_strict_native

        return verify_single_strict_native(msg, pub, sig)
    try:
        _STRICT_FUSER.verify_batch([msg], [pub], [sig])
        return True
    except BackendUnavailable:
        raise
    except CryptoError:
        return False


# -- opt-in process-wide verification-verdict memo ---------------------------
#
# Signature verification is a PURE function of (message, key, signature)
# bytes, so memoizing verdicts is semantically invisible. It is still
# opt-in: when one process models a whole committee, a memo hit skips
# work every REAL node would have to perform itself, which would falsify
# the perf benchmarks (the live planes only fuse CONCURRENT duplicates —
# crypto/batching.py — which a real node's concurrent arrivals genuinely
# share). The deterministic simulation plane (hotstuff_tpu/sim) enables
# it: there the object of study is protocol behavior under fault
# schedules, not per-node CPU, and byte-identical re-verifies across
# simulated nodes and seeds are pure waste. Failure verdicts are cached
# too (byzantine resends stay cheap); BackendUnavailable never is.

_VERIFY_MEMO: dict | None = None
_VERIFY_MEMO_CAP = 1 << 16


def enable_verify_memo(enabled: bool = True) -> None:
    """Turn the process-wide verification memo on (idempotent — an
    existing memo is kept warm) or off (drops it)."""
    global _VERIFY_MEMO
    if enabled:
        if _VERIFY_MEMO is None:
            _VERIFY_MEMO = {}
    else:
        _VERIFY_MEMO = None


def verify_memo_enabled() -> bool:
    return _VERIFY_MEMO is not None


def _memo_put(memo: dict, key, verdict) -> None:
    if len(memo) >= _VERIFY_MEMO_CAP:
        memo.clear()  # coarse bound; sim working sets rarely get here
    memo[key] = verdict


def backend_verify_batch(msgs, pubs, sigs) -> None:
    """Dispatch a batch verification to the active backend through the
    (opt-in) process-wide verdict memo. All structured certificate paths
    (``Signature.verify_batch``/``verify_batch_multi`` and the wire-v2
    raw-slice path in consensus/messages.py) route here."""
    memo = _VERIFY_MEMO
    if memo is None:
        return get_backend().verify_batch(msgs, pubs, sigs)
    # Canonical (order-independent) key: a QC's signature set is verified
    # once by the assembling leader (aggregator arrival order) and again
    # off the wire (seat-sorted v2 order) — same set, same verdict, one
    # memo entry.
    key = tuple(sorted(zip(msgs, pubs, sigs)))
    hit = memo.get(key)
    if hit is not None:
        from hotstuff_tpu import telemetry

        telemetry.counter("crypto.verify_memo.hits").inc()
        if hit is True:
            return
        raise CryptoError(hit)
    try:
        get_backend().verify_batch(msgs, pubs, sigs)
    except CryptoError as e:
        _memo_put(memo, key, str(e))
        raise
    _memo_put(memo, key, True)


# -- fused aggregate-certificate dispatch ------------------------------------
#
# A wire-v2 certificate is a seat bitmap plus one packed signature buffer;
# the fused path hands the crypto plane ONE job per cert (buffer + stride,
# never 2f+1 sliced Signature objects) and verifies it as a single RLC MSM
# with deterministic coefficients (cpu_batch.cert_rlc_coefficients).
# ``HOTSTUFF_AGG_QC=0`` is the kill-switch: certs then explode into the
# pre-aggregate per-signature batch path, byte-identical behavior.


def agg_qc_enabled() -> bool:
    """True unless ``HOTSTUFF_AGG_QC=0`` disables fused cert verification
    (read per call so tests and operators can flip it live)."""
    return os.environ.get("HOTSTUFF_AGG_QC", "1") != "0"


def _explode_cert(msgs, pubs, sig_buf, stride, n):
    """Per-signature (msgs, pubs, sigs) lists for a packed cert — the
    fallback shape for backends/paths without a fused entry point."""
    sig_buf = bytes(sig_buf)
    if isinstance(msgs, (bytes, bytearray, memoryview)):
        msg_list = [bytes(msgs)] * n
    else:
        msg_list = [bytes(m) for m in msgs]
    pub_list = [bytes(p) for p in pubs]
    sig_list = [sig_buf[stride * i : stride * i + 64] for i in range(n)]
    return msg_list, pub_list, sig_list


def backend_verify_cert(msgs, pubs, sig_buf, stride: int = 64, key=None) -> None:
    """Dispatch one fused certificate verification to the active backend.

    ``pubs``: the cert's n public keys (bytes each); ``sig_buf``: its
    packed signature buffer at ``stride`` bytes per record (signature in
    the first 64); ``msgs``: one shared statement (QC) or a per-seat list
    (TC). ``key`` is an optional canonical cert identity the super-batching
    layer uses to dedup concurrent verifies of the same cert. Raises
    CryptoError on an invalid cert.

    Falls back to the exploded ``backend_verify_batch`` path when the
    verdict memo is active (sim plane: exploded triples keep ONE unified
    memo keyspace with the structured paths), when ``HOTSTUFF_AGG_QC=0``,
    or when the active backend has no fused entry point.
    """
    n = len(pubs)
    if n == 0:
        return
    if _VERIFY_MEMO is not None or not agg_qc_enabled():
        m, p, s = _explode_cert(msgs, pubs, sig_buf, stride, n)
        return backend_verify_batch(m, p, s)
    backend = get_backend()
    fused = getattr(backend, "verify_cert", None)
    if fused is None:
        m, p, s = _explode_cert(msgs, pubs, sig_buf, stride, n)
        return backend.verify_batch(m, p, s)
    return fused(msgs, pubs, sig_buf, stride, key=key)


class PublicKey:
    """Compressed Edwards point, 32 bytes; base64 serde; ordered (for
    round-robin leader election over sorted keys, reference
    ``consensus/src/leader.rs:16-20``)."""

    SIZE = 32
    __slots__ = ("data",)

    def __init__(self, data: bytes) -> None:
        if len(data) != self.SIZE:
            raise ValueError("public key must be 32 bytes")
        # type-check without copying: wire decode hands us immutable
        # bytes already, and these run per decoded signature/key
        self.data = data if type(data) is bytes else bytes(data)

    @classmethod
    def decode_base64(cls, s: str) -> "PublicKey":
        return cls(base64.standard_b64decode(s))

    def encode_base64(self) -> str:
        return base64.standard_b64encode(self.data).decode()

    def __bytes__(self) -> bytes:
        return self.data

    def __eq__(self, other) -> bool:
        return isinstance(other, PublicKey) and self.data == other.data

    def __lt__(self, other: "PublicKey") -> bool:
        return self.data < other.data

    def __hash__(self) -> int:
        return hash(self.data)

    def __repr__(self) -> str:
        return self.encode_base64()[:16]

    def __str__(self) -> str:
        return self.encode_base64()


class SecretKey:
    """Ed25519 seed (32 bytes). The reference stores the 64-byte expanded
    keypair (``crypto/src/lib.rs:64-175``) and zeroizes on drop; we keep the
    seed, from which the expanded key is derived on demand."""

    SIZE = 32
    __slots__ = ("seed",)

    def __init__(self, seed: bytes) -> None:
        if len(seed) != self.SIZE:
            raise ValueError("secret key seed must be 32 bytes")
        self.seed = bytes(seed)

    @classmethod
    def decode_base64(cls, s: str) -> "SecretKey":
        return cls(base64.standard_b64decode(s))

    def encode_base64(self) -> str:
        return base64.standard_b64encode(self.seed).decode()

    def public_key(self) -> PublicKey:
        if _HAVE_PYCA:
            sk = Ed25519PrivateKey.from_private_bytes(self.seed)
            return PublicKey(sk.public_key().public_bytes_raw())
        return PublicKey(_gated_signer(self.seed).pub)


def generate_keypair(rng: "secrets.SystemRandom | None" = None, *, seed: bytes | None = None):
    """Generate an Ed25519 keypair. ``seed`` pins determinism for tests,
    mirroring the reference's seeded-RNG fixtures
    (``consensus/src/tests/common.rs:17-20``)."""
    if seed is None:
        if rng is not None:
            seed = rng.randbytes(32)
        else:
            seed = secrets.token_bytes(32)
    sk = SecretKey(seed)
    return sk.public_key(), sk


class Signature:
    """Detached Ed25519 signature (64 bytes, R || s).

    The reference splits it into two 32-byte halves for serde
    (``crypto/src/lib.rs:177-220``); we keep the canonical 64 bytes.
    """

    SIZE = 64
    __slots__ = ("data",)

    def __init__(self, data: bytes) -> None:
        if len(data) != self.SIZE:
            raise ValueError("signature must be 64 bytes")
        # type-check without copying: wire decode hands us immutable
        # bytes already, and these run per decoded signature/key
        self.data = data if type(data) is bytes else bytes(data)

    @classmethod
    def default(cls) -> "Signature":
        return cls(bytes(cls.SIZE))

    @classmethod
    def new(cls, digest: Digest, secret: SecretKey) -> "Signature":
        """Sign a 32-byte digest (reference ``Signature::new``, ``:185``)."""
        if _HAVE_PYCA:
            sk = Ed25519PrivateKey.from_private_bytes(secret.seed)
            return cls(sk.sign(digest.data))
        return cls(_gated_signer(secret.seed).sign(digest.data))

    def __bytes__(self) -> bytes:
        return self.data

    def __eq__(self, other) -> bool:
        return isinstance(other, Signature) and self.data == other.data

    def __hash__(self) -> int:
        return hash(self.data)

    def part1(self) -> bytes:
        return self.data[:32]

    def part2(self) -> bytes:
        return self.data[32:]

    def verify(self, digest: Digest, public_key: PublicKey) -> None:
        """Strict single verification (reference ``verify`` → dalek
        ``verify_strict``, ``crypto/src/lib.rs:200-204``). Raises CryptoError."""
        memo = _VERIFY_MEMO
        if memo is None:
            return self._verify_now(digest, public_key)
        key = (digest.data, public_key.data, self.data)
        hit = memo.get(key)
        if hit is not None:
            if hit is True:
                return
            raise CryptoError(hit)
        try:
            self._verify_now(digest, public_key)
        except CryptoError as e:
            _memo_put(memo, key, str(e))
            raise
        _memo_put(memo, key, True)

    def _verify_now(self, digest: Digest, public_key: PublicKey) -> None:
        # OpenSSL's verify is cofactorless (sB == R + hA) and rejects
        # non-canonical s, matching verify_strict's equation; additionally
        # reject small-order R/A like dalek does.
        if not _strict_point_checks(public_key.data, self.data):
            raise CryptoError("small-order or non-canonical point in signature")
        if _HAVE_PYCA:
            try:
                Ed25519PublicKey.from_public_bytes(public_key.data).verify(
                    self.data, digest.data
                )
            except (InvalidSignature, ValueError) as e:
                raise CryptoError(f"invalid signature: {e}") from e
        elif not _verify_single_gated(
            digest.data, public_key.data, self.data
        ):
            raise CryptoError("invalid signature")

    @staticmethod
    def verify_batch(digest: Digest, votes) -> None:
        """Verify many signatures over the SAME digest — the QC path
        (reference ``verify_batch``, ``crypto/src/lib.rs:206-219``, called from
        ``QC::verify``, ``consensus/src/messages.rs:197``).

        ``votes``: iterable of ``(PublicKey, Signature)``. Raises CryptoError
        if any signature is invalid. Routed to the active backend.
        """
        votes = list(votes)
        backend_verify_batch(
            [digest.data] * len(votes),
            [pk.data for pk, _ in votes],
            [sig.data for _, sig in votes],
        )

    @staticmethod
    def verify_batch_multi(items) -> None:
        """General batch verification over per-item digests — used for
        TC verification (per-voter digests, reference
        ``consensus/src/messages.rs:303-314``) and for cross-round
        super-batching on device. ``items``: iterable of
        ``(Digest, PublicKey, Signature)``."""
        items = list(items)
        backend_verify_batch(
            [d.data for d, _, _ in items],
            [pk.data for _, pk, _ in items],
            [sig.data for _, _, sig in items],
        )


def _small_order_encodings() -> frozenset[bytes]:
    """Canonical encodings of the eight 8-torsion points, computed once."""
    t = ed25519_ref.torsion_generator()
    encs = set()
    acc = ed25519_ref.IDENTITY
    for _ in range(8):
        encs.add(ed25519_ref.point_compress(acc))
        acc = ed25519_ref.point_add(acc, t)
    return frozenset(encs)


_SMALL_ORDER = _small_order_encodings()
_P = ed25519_ref.P


def _canonical_y(enc: bytes) -> bool:
    return (int.from_bytes(enc, "little") & ((1 << 255) - 1)) < _P


def _strict_point_checks(pub: bytes, sig: bytes) -> bool:
    """Reject non-canonical or small-order A/R (dalek verify_strict
    semantics) using only integer compares against a precomputed table —
    no field arithmetic on the per-vote hot path."""
    r_enc = sig[:32]
    if not (_canonical_y(pub) and _canonical_y(r_enc)):
        return False
    # OpenSSL verification already proved both decode to on-curve points, so
    # a canonical encoding outside the 8-torsion table is not small-order.
    return pub not in _SMALL_ORDER and r_enc not in _SMALL_ORDER


# ---------------------------------------------------------------------------
# Pluggable batch-verification backend.
# ---------------------------------------------------------------------------


class CpuBackend:
    """CPU batch verification — the baseline the TPU backend is benchmarked
    against (dalek's CPU ``verify_batch``, reference
    ``crypto/src/lib.rs:206-219``).

    Acceptance semantics are COFACTORED (8sB == 8R + 8hA), identical to the
    TPU backend and to dalek's batch verifier, so a committee may mix
    backends without splitting on QC validity. Implementation: the native
    C++ RLC+Pippenger engine (``crypto/native/ed25519.cpp`` — dalek's
    algorithm, ~4.5x the serial loop at committee scale) when the toolchain
    can build it, else fast OpenSSL cofactorless per-signature verification
    (a strict subset of the cofactored set) with a slow cofactored re-check
    only for signatures OpenSSL rejects — honest inputs never hit the slow
    path. ``use_rlc=False`` forces the serial path (the benchmark's serial
    baseline).
    """

    name = "cpu"

    # The pure-Python cofactored re-check costs ~6.5 ms; it only ever runs on
    # signatures OpenSSL rejected, which honest RFC 8032 signers never produce
    # in the divergence region (their R = rB is torsion-free, so OpenSSL
    # rejection == cofactored rejection for them). A token bucket bounds the
    # CPU amplification a byzantine committee member could otherwise extract;
    # once exhausted, OpenSSL's verdict is final — this can only reject
    # byzantine-crafted torsioned signatures, never honest ones.
    SLOW_CHECK_BUDGET = 32
    SLOW_CHECK_REFILL_S = 10.0

    def __init__(self, use_rlc: bool = True) -> None:
        self._slow_tokens = float(self.SLOW_CHECK_BUDGET)
        self._last_refill = time.monotonic()
        self._rlc = None
        if use_rlc:
            try:
                from .native_ed25519 import native_available, verify_batch_native

                # build=False: never run a g++ compile on the consensus
                # path — only pick up an already-built library (it ships
                # prebuilt; tests and bench build it when stale).
                if native_available(build=False):
                    self._rlc = verify_batch_native
            except Exception:  # toolchain unavailable: serial fallback
                self._rlc = None

    def _take_slow_token(self) -> bool:
        now = time.monotonic()
        self._slow_tokens = min(
            float(self.SLOW_CHECK_BUDGET),
            self._slow_tokens
            + (now - self._last_refill) * self.SLOW_CHECK_BUDGET / self.SLOW_CHECK_REFILL_S,
        )
        self._last_refill = now
        if self._slow_tokens >= 1.0:
            self._slow_tokens -= 1.0
            return True
        return False

    def verify_batch(self, msgs, pubs, sigs) -> None:
        if not len(msgs) == len(pubs) == len(sigs):
            raise CryptoError("batch length mismatch")
        from hotstuff_tpu import telemetry

        telemetry.counter("crypto.dispatch.cpu").inc()
        telemetry.counter("crypto.dispatch.cpu_sigs").inc(len(msgs))
        # Without OpenSSL, even a batch of one routes to the native RLC
        # engine — the pure-Python serial loop below is milliseconds per
        # signature and only ever acceptable as the last-resort fallback.
        if self._rlc is not None and (len(msgs) >= 2 or not _HAVE_PYCA):
            if not self._rlc(msgs, pubs, sigs):
                raise CryptoError("invalid signature in batch")
            return
        for msg, pub, sig in zip(msgs, pubs, sigs):
            if not _HAVE_PYCA:
                if not ed25519_ref.verify(pub, msg, sig, strict=False):
                    raise CryptoError("invalid signature in batch")
                continue
            try:
                Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
            except (InvalidSignature, ValueError):
                if not self._take_slow_token():
                    raise CryptoError(
                        "invalid signature in batch (cofactored re-check "
                        "rate-limited; rejecting conservatively)"
                    ) from None
                if not ed25519_ref.verify(pub, msg, sig, strict=False):
                    raise CryptoError("invalid signature in batch") from None

    def verify_cert(self, msgs, pubs, sig_buf, stride: int = 64, key=None) -> None:
        """Fused aggregate-certificate verification: one RLC MSM over the
        cert's packed signature buffer (``native_ed25519.verify_cert_native``).
        Acceptance set identical to ``verify_batch`` over the exploded
        slices — the deterministic-coefficient RLC rejects any corrupted
        slice with the same cofactored semantics. Falls back to the
        exploded batch path when the native engine is unavailable."""
        n = len(pubs)
        from hotstuff_tpu import telemetry

        telemetry.counter("crypto.dispatch.cpu_cert").inc()
        telemetry.counter("crypto.dispatch.cpu_cert_sigs").inc(n)
        if self._rlc is not None:
            from .native_ed25519 import verify_cert_native

            if not verify_cert_native(msgs, pubs, sig_buf, stride):
                raise CryptoError("invalid signature in certificate")
            return
        m, p, s = _explode_cert(msgs, pubs, sig_buf, stride, n)
        self.verify_batch(m, p, s)


_BACKEND = None


def get_backend():
    global _BACKEND
    if _BACKEND is None:
        set_backend(os.environ.get("HOTSTUFF_CRYPTO_BACKEND", "cpu"))
    return _BACKEND


def set_backend(name_or_backend) -> None:
    """Select the batch-verify backend: "cpu", "tpu", their super-batching
    variants "cpu-batched"/"tpu-batched" (fuse concurrent verification
    requests into one call, see ``crypto/batching.py``), or a backend
    object."""
    global _BACKEND
    if not isinstance(name_or_backend, str):
        _BACKEND = name_or_backend
        return
    # Validate fully and construct into a local before touching the global:
    # a failed set_backend must leave the active backend unchanged.
    name = name_or_backend
    base, sep, variant = name.partition("-")
    if base not in ("cpu", "tpu"):
        raise ValueError(f"unknown crypto backend {name!r}")
    if sep and variant != "batched":
        raise ValueError(f"unknown crypto backend variant {name!r}")
    if base == "cpu":
        backend = CpuBackend()
    else:
        # Imported lazily: pulls in jax.
        from .tpu_backend import TpuBackend

        backend = TpuBackend()
    if variant == "batched":
        # Fuse concurrent verification requests into one device call
        # (multi-round super-batching, see crypto/batching.py).
        from .batching import BatchingBackend

        backend = BatchingBackend(backend)
    _BACKEND = backend


class SignatureService:
    """Holds the secret key and signs digests on request.

    The reference runs this as an actor answering mpsc requests with oneshot
    replies (``crypto/src/lib.rs:222-250``) so signing never blocks protocol
    tasks. OpenSSL signing is ~15µs, so we sign inline in the awaiting task;
    the async API is preserved so callers are identical.
    """

    def __init__(self, secret: SecretKey) -> None:
        if _HAVE_PYCA:
            self._sk = Ed25519PrivateKey.from_private_bytes(secret.seed)
            self._signer = None
        else:
            self._sk = None
            self._signer = _gated_signer(secret.seed)

    async def request_signature(self, digest: Digest) -> Signature:
        if self._sk is not None:
            return Signature(self._sk.sign(digest.data))
        return Signature(self._signer.sign(digest.data))


__all__ = [
    "BackendUnavailable",
    "CryptoError",
    "Digest",
    "sha512_digest",
    "PublicKey",
    "SecretKey",
    "generate_keypair",
    "Signature",
    "SignatureService",
    "get_backend",
    "set_backend",
    "CpuBackend",
]
