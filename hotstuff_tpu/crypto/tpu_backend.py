"""TPU batch-verification backend (JAX device kernels).

Routes ``Signature.verify_batch`` to the device random-linear-combination
verifier in ``hotstuff_tpu.ops`` — the north-star offload of the QC hot path
(reference ``crypto/src/lib.rs:206-219``). Acceptance semantics: cofactored
(dalek ``verify_batch``-equivalent), identical to ``CpuBackend``.
"""

from __future__ import annotations

from . import BackendUnavailable, CryptoError


class TpuBackend:
    name = "tpu"

    def __init__(self) -> None:
        try:
            from hotstuff_tpu.ops import verify as _ops_verify  # noqa: F401
        except ImportError as e:  # pragma: no cover
            raise NotImplementedError(
                "the TPU crypto backend requires hotstuff_tpu.ops.verify "
                "(jax device kernels); not available: %s" % e
            ) from e
        self._ops = _ops_verify

    def verify_batch(self, msgs, pubs, sigs) -> None:
        if not len(msgs) == len(pubs) == len(sigs):
            raise CryptoError("batch length mismatch")
        if not msgs:
            return
        try:
            ok = self._ops.verify_batch_device(msgs, pubs, sigs)
        except Exception as e:
            # Device/runtime failure: the batch was NOT judged.
            raise BackendUnavailable(f"device verification failed: {e!r}") from e
        if not ok:
            raise CryptoError("invalid signature in batch (device)")
