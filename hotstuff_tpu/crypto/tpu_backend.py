"""TPU batch-verification backend (JAX device kernels).

Routes ``Signature.verify_batch`` to the device random-linear-combination
verifier in ``hotstuff_tpu.ops`` — the north-star offload of the QC hot path
(reference ``crypto/src/lib.rs:206-219``). Acceptance semantics: cofactored
(dalek ``verify_batch``-equivalent), identical to ``CpuBackend``.

With more than one visible device the backend automatically shards the MSM
lanes over a ``jax.sharding.Mesh`` and combines per-device partial sums
over ICI (``parallel.mesh``) — the BASELINE config-5 path (4096-validator
vote sets across a v5e pod slice). Override with ``sharded=True/False`` or
``HOTSTUFF_TPU_SHARDED=1/0``.
"""

from __future__ import annotations

import os

from . import BackendUnavailable, CryptoError


class TpuBackend:
    name = "tpu"

    def __init__(self, sharded: bool | None = None) -> None:
        try:
            from hotstuff_tpu.ops import verify as _ops_verify  # noqa: F401
        except ImportError as e:  # pragma: no cover
            raise NotImplementedError(
                "the TPU crypto backend requires hotstuff_tpu.ops.verify "
                "(jax device kernels); not available: %s" % e
            ) from e
        self._ops = _ops_verify
        self._mesh = None
        if sharded is None:
            env = os.environ.get("HOTSTUFF_TPU_SHARDED", "auto")
            sharded = None if env == "auto" else env not in ("0", "false", "no")
        if sharded is not False:
            try:
                import jax

                n_dev = jax.device_count()
            except Exception:  # pragma: no cover - device init failure
                n_dev = 1
            if n_dev > 1:
                from hotstuff_tpu.parallel import mesh as _pmesh

                self._pmesh = _pmesh
                self._mesh = _pmesh.make_mesh()
        # Committee point cache: validator keys decompress once and stay
        # device-resident (committees are static per epoch); per-QC work is
        # then R-decompress + signed-digit MSM only. HOTSTUFF_TPU_CACHE=0
        # reverts to the full-decompress path. On a mesh the cache array is
        # replicated and the cached split shards across devices
        # (``parallel.mesh.verify_batch_device_cached_sharded``).
        self._cache = None
        if os.environ.get("HOTSTUFF_TPU_CACHE", "1") not in (
            "0",
            "false",
            "no",
        ):
            self._cache = _ops_verify.DevicePointCache()

    def verify_batch(self, msgs, pubs, sigs) -> None:
        if not len(msgs) == len(pubs) == len(sigs):
            raise CryptoError("batch length mismatch")
        if not msgs:
            return
        from hotstuff_tpu import telemetry

        telemetry.counter("crypto.dispatch.tpu").inc()
        telemetry.counter("crypto.dispatch.tpu_sigs").inc(len(msgs))
        try:
            if self._mesh is not None and self._cache is not None:
                try:
                    ok = self._pmesh.verify_batch_device_cached_sharded(
                        self._mesh, msgs, pubs, sigs, self._cache
                    )
                except self._ops.CacheFull:
                    self._cache = self._ops.DevicePointCache()
                    ok = self._pmesh.verify_batch_device_sharded(
                        self._mesh, msgs, pubs, sigs
                    )
            elif self._mesh is not None:
                ok = self._pmesh.verify_batch_device_sharded(
                    self._mesh, msgs, pubs, sigs
                )
            elif self._cache is not None:
                try:
                    ok = self._ops.verify_batch_device_cached(
                        msgs, pubs, sigs, self._cache
                    )
                except self._ops.CacheFull:
                    # Keys accumulate across epochs with no eviction; only
                    # the CURRENT epoch's committee is ever live, so start a
                    # fresh cache (repopulated by subsequent batches) rather
                    # than losing the cached path for the process lifetime.
                    self._cache = self._ops.DevicePointCache()
                    ok = self._ops.verify_batch_device(msgs, pubs, sigs)
            else:
                ok = self._ops.verify_batch_device(msgs, pubs, sigs)
        except Exception as e:
            # Device/runtime failure: the batch was NOT judged.
            raise BackendUnavailable(f"device verification failed: {e!r}") from e
        if not ok:
            raise CryptoError("invalid signature in batch (device)")
