// Native Ed25519 batch-verification engine (CPU plane).
//
// The reference's CPU hot path is dalek's verify_batch
// (crypto/src/lib.rs:206-219): fold the batch into one multi-scalar
// multiplication over a random linear combination and check
//     8 * sum(scalar_i * P_i) == identity.
// This engine evaluates exactly that MSM: batched point decompression and
// a bucketed Pippenger multi-scalar multiplication over the twisted
// Edwards curve, with GF(2^255-19) in radix-2^51 limbs on uint64
// (products via unsigned __int128). The Python side does the byte-level
// strictness checks, SHA-512 challenges and mod-L scalar arithmetic —
// same split as the device pipeline (ops/verify.py).
//
// Single-threaded by design: the box this serves is one core, and the
// caller (crypto backend) already parallelizes across batches if needed.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

// Engine-level telemetry counters, exported via hs_ed25519_stats.
// Relaxed atomics: callers run concurrently on the crypto worker pool
// and the superbatch flusher; exact cross-thread ordering is
// irrelevant for monotonic totals.
static std::atomic<uint64_t> g_msm_calls{0};       // batch-verify MSM evaluations
static std::atomic<uint64_t> g_msm_points{0};      // MSM lanes (points) processed
static std::atomic<uint64_t> g_scalarmult_calls{0};  // sign/derive basepoint mults
static std::atomic<uint64_t> g_decompress_calls{0};  // single-point decompressions
static std::atomic<uint64_t> g_cert_challenge_calls{0};  // fused-cert challenge batches
static std::atomic<uint64_t> g_cert_challenge_sigs{0};   // signatures hashed in them

typedef unsigned __int128 u128;

static const uint64_t MASK51 = ((uint64_t)1 << 51) - 1;

struct fe {
    uint64_t v[5];
};

// Per-limb 2p, large enough to keep a + 2p - b non-negative for
// carried operands (limbs < 2^52).
static const fe FE_SUB2P = {{0xfffffffffffdaULL, 0xffffffffffffeULL,
                             0xffffffffffffeULL, 0xffffffffffffeULL,
                             0xffffffffffffeULL}};
static const fe FE_D2 = {{0x69b9426b2f159ULL, 0x35050762add7aULL,
                          0x3cf44c0038052ULL, 0x6738cc7407977ULL,
                          0x2406d9dc56dffULL}};
static const fe FE_D = {{0x34dca135978a3ULL, 0x1a8283b156ebdULL,
                         0x5e7a26001c029ULL, 0x739c663a03cbbULL,
                         0x52036cee2b6ffULL}};
static const fe FE_SQRT_M1 = {{0x61b274a0ea0b0ULL, 0xd5a5fc8f189dULL,
                               0x7ef5e9cbd0c60ULL, 0x78595a6804c9eULL,
                               0x2b8324804fc1dULL}};
static const fe FE_ONE = {{1, 0, 0, 0, 0}};
static const fe FE_ZERO = {{0, 0, 0, 0, 0}};

static inline void fe_add(fe& r, const fe& a, const fe& b) {
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
}

static inline void fe_sub(fe& r, const fe& a, const fe& b) {
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + FE_SUB2P.v[i] - b.v[i];
}

// Weak carry: limbs back under ~2^52 (top folds by 19).
static inline void fe_carry(fe& r) {
    uint64_t c;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
    c = r.v[1] >> 51; r.v[1] &= MASK51; r.v[2] += c;
    c = r.v[2] >> 51; r.v[2] &= MASK51; r.v[3] += c;
    c = r.v[3] >> 51; r.v[3] &= MASK51; r.v[4] += c;
    c = r.v[4] >> 51; r.v[4] &= MASK51; r.v[0] += 19 * c;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
}

static void fe_mul(fe& r, const fe& a, const fe& b) {
    u128 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
    uint64_t b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3], b4 = b.v[4];
    uint64_t b1_19 = 19 * b1, b2_19 = 19 * b2, b3_19 = 19 * b3, b4_19 = 19 * b4;

    u128 t0 = a0 * b0 + a1 * b4_19 + a2 * b3_19 + a3 * b2_19 + a4 * b1_19;
    u128 t1 = a0 * b1 + a1 * b0 + a2 * b4_19 + a3 * b3_19 + a4 * b2_19;
    u128 t2 = a0 * b2 + a1 * b1 + a2 * b0 + a3 * b4_19 + a4 * b3_19;
    u128 t3 = a0 * b3 + a1 * b2 + a2 * b1 + a3 * b0 + a4 * b4_19;
    u128 t4 = a0 * b4 + a1 * b3 + a2 * b2 + a3 * b1 + a4 * b0;

    uint64_t c;
    uint64_t r0 = (uint64_t)t0 & MASK51; c = (uint64_t)(t0 >> 51);
    t1 += c;
    uint64_t r1 = (uint64_t)t1 & MASK51; c = (uint64_t)(t1 >> 51);
    t2 += c;
    uint64_t r2 = (uint64_t)t2 & MASK51; c = (uint64_t)(t2 >> 51);
    t3 += c;
    uint64_t r3 = (uint64_t)t3 & MASK51; c = (uint64_t)(t3 >> 51);
    t4 += c;
    uint64_t r4 = (uint64_t)t4 & MASK51; c = (uint64_t)(t4 >> 51);
    r0 += 19 * c;
    c = r0 >> 51; r0 &= MASK51; r1 += c;

    r.v[0] = r0; r.v[1] = r1; r.v[2] = r2; r.v[3] = r3; r.v[4] = r4;
}

static inline void fe_sq(fe& r, const fe& a) { fe_mul(r, a, a); }

// Canonical little-endian bytes of the fully reduced value.
static void fe_tobytes(uint8_t out[32], const fe& a) {
    fe t = a;
    fe_carry(t);
    fe_carry(t);
    // Canonicalize: q = floor((t + 19) / 2^255) (the "is t >= p" carry),
    // then t + 19*q with the bits >= 2^255 masked off subtracts q*p.
    uint64_t q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    uint64_t c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;  // drop bits >= 2^255
    uint64_t w0 = t.v[0] | (t.v[1] << 51);
    uint64_t w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    uint64_t w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    uint64_t w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    std::memcpy(out, &w0, 8);
    std::memcpy(out + 8, &w1, 8);
    std::memcpy(out + 16, &w2, 8);
    std::memcpy(out + 24, &w3, 8);
}

// Little-endian bytes -> limbs. Caller clears/handles the sign bit.
static void fe_frombytes(fe& r, const uint8_t in[32]) {
    uint64_t w0, w1, w2, w3;
    std::memcpy(&w0, in, 8);
    std::memcpy(&w1, in + 8, 8);
    std::memcpy(&w2, in + 16, 8);
    std::memcpy(&w3, in + 24, 8);
    r.v[0] = w0 & MASK51;
    r.v[1] = ((w0 >> 51) | (w1 << 13)) & MASK51;
    r.v[2] = ((w1 >> 38) | (w2 << 26)) & MASK51;
    r.v[3] = ((w2 >> 25) | (w3 << 39)) & MASK51;
    r.v[4] = (w3 >> 12) & MASK51;  // drops bit 255 (the sign bit)
}

static bool fe_iszero(const fe& a) {
    uint8_t b[32];
    fe_tobytes(b, a);
    uint8_t acc = 0;
    for (int i = 0; i < 32; i++) acc |= b[i];
    return acc == 0;
}

static bool fe_eq(const fe& a, const fe& b) {
    fe d;
    fe_sub(d, a, b);
    return fe_iszero(d);
}

static int fe_parity(const fe& a) {
    uint8_t b[32];
    fe_tobytes(b, a);
    return b[0] & 1;
}

// z^(2^k) by k squarings.
static void fe_sqk(fe& r, const fe& z, int k) {
    fe t = z;
    for (int i = 0; i < k; i++) fe_sq(t, t);
    r = t;
}

// z^(2^252 - 3): the (p-5)/8 exponent of the decompression square root.
// Addition chain on all-ones exponents: f(a+b) = f(a)^(2^b) * f(b)
// (same chain as the Pallas kernel's _pow_p58).
static void fe_pow_p58(fe& r, const fe& z) {
    fe f1 = z, f2, f4, f5, f10, f20, f40, f80, f160, f240, f250, t;
    fe_sqk(t, f1, 1); fe_mul(f2, t, f1);
    fe_sqk(t, f2, 2); fe_mul(f4, t, f2);
    fe_sqk(t, f4, 1); fe_mul(f5, t, f1);
    fe_sqk(t, f5, 5); fe_mul(f10, t, f5);
    fe_sqk(t, f10, 10); fe_mul(f20, t, f10);
    fe_sqk(t, f20, 20); fe_mul(f40, t, f20);
    fe_sqk(t, f40, 40); fe_mul(f80, t, f40);
    fe_sqk(t, f80, 80); fe_mul(f160, t, f80);
    fe_sqk(t, f160, 80); fe_mul(f240, t, f80);
    fe_sqk(t, f240, 10); fe_mul(f250, t, f10);
    fe_sqk(t, f250, 2); fe_mul(r, t, z);
}

// -- point arithmetic: extended homogeneous coordinates (X, Y, Z, T) -------

struct pt {
    fe x, y, z, t;
};

static const pt PT_IDENTITY = {FE_ZERO, FE_ONE, FE_ONE, FE_ZERO};

// Unified addition (add-2008-hwcd-3 for a=-1 twisted Edwards).
static void pt_add(pt& r, const pt& p, const pt& q) {
    fe a, b, c, d, e, f, g, h, t1, t2;
    fe_sub(t1, p.y, p.x);
    fe_sub(t2, q.y, q.x);
    fe_mul(a, t1, t2);
    fe_add(t1, p.y, p.x);
    fe_add(t2, q.y, q.x);
    fe_carry(t1);  // sums of carried limbs: keep under mul input bounds
    fe_carry(t2);
    fe_mul(b, t1, t2);
    fe_mul(c, p.t, FE_D2);
    fe_mul(c, c, q.t);
    fe_mul(d, p.z, q.z);
    fe_add(d, d, d);
    fe_carry(d);
    fe_sub(e, b, a);
    fe_sub(f, d, c);
    fe_add(g, d, c);
    fe_add(h, b, a);
    fe_carry(e); fe_carry(f); fe_carry(g); fe_carry(h);
    fe_mul(r.x, e, f);
    fe_mul(r.y, g, h);
    fe_mul(r.z, f, g);
    fe_mul(r.t, e, h);
}

// Dedicated doubling (dbl-2008-hwcd).
static void pt_double(pt& r, const pt& p) {
    fe a, b, c, e, f, g, h, t1;
    fe_sq(a, p.x);
    fe_sq(b, p.y);
    fe_sq(c, p.z);
    fe_add(c, c, c);
    fe_add(h, a, b);
    fe_add(t1, p.x, p.y);
    fe_carry(t1);
    fe_sq(t1, t1);
    fe_sub(e, h, t1);
    fe_sub(g, a, b);
    fe_add(f, c, g);
    fe_carry(e); fe_carry(f); fe_carry(g); fe_carry(h);
    fe_mul(r.x, e, f);
    fe_mul(r.y, g, h);
    fe_mul(r.z, f, g);
    fe_mul(r.t, e, h);
}

static void pt_neg(pt& r, const pt& p) {
    fe_sub(r.x, FE_ZERO, p.x);
    fe_carry(r.x);
    r.y = p.y;
    r.z = p.z;
    fe_sub(r.t, FE_ZERO, p.t);
    fe_carry(r.t);
}

static bool pt_is_identity(const pt& p) {
    if (!fe_iszero(p.x)) return false;
    // Y == Z != 0: a degenerate (0, 0, 0, *) value — only producible by an
    // exceptional unified-addition case, never by a valid point — must not
    // read as the identity.
    if (fe_iszero(p.y)) return false;
    return fe_eq(p.y, p.z);
}

// Decompress a 32-byte encoding. Rejects non-canonical y (y >= p) and
// off-curve values, matching RFC 8032 / dalek field-element strictness.
static bool pt_decompress(pt& r, const uint8_t enc[32]) {
    // Canonicality: the 255-bit y must be < p.
    uint8_t y_bytes[32];
    std::memcpy(y_bytes, enc, 32);
    int sign = y_bytes[31] >> 7;
    y_bytes[31] &= 0x7f;
    fe y;
    fe_frombytes(y, y_bytes);
    uint8_t canon[32];
    fe_tobytes(canon, y);
    if (std::memcmp(canon, y_bytes, 32) != 0) return false;  // y >= p

    // x^2 = (y^2 - 1) / (d y^2 + 1)
    fe y2, u, v, v3, v7, x, chk, t;
    fe_sq(y2, y);
    fe_sub(u, y2, FE_ONE);
    fe_mul(v, y2, FE_D);
    fe_add(v, v, FE_ONE);
    fe_carry(u); fe_carry(v);

    // x = u v^3 (u v^7)^((p-5)/8)
    fe_sq(t, v);
    fe_mul(v3, t, v);
    fe_sq(t, v3);
    fe_mul(v7, t, v);
    fe_mul(t, u, v7);
    fe_pow_p58(t, t);
    fe_mul(x, u, v3);
    fe_mul(x, x, t);

    fe_sq(chk, x);
    fe_mul(chk, chk, v);  // v x^2 in {u, -u} iff a root exists
    if (!fe_eq(chk, u)) {
        fe neg_u;
        fe_sub(neg_u, FE_ZERO, u);
        if (!fe_eq(chk, neg_u)) return false;
        fe_mul(x, x, FE_SQRT_M1);
    }
    if (fe_iszero(x)) {
        if (sign) return false;  // -0 is not a valid encoding
    } else if (fe_parity(x) != sign) {
        fe_sub(x, FE_ZERO, x);
        fe_carry(x);
    }
    r.x = x;
    r.y = y;
    r.z = FE_ONE;
    fe_mul(r.t, x, y);
    return true;
}

// 1/z = z^(p-2) = z^(2^255 - 21): the standard curve25519 addition chain.
static void fe_invert(fe& r, const fe& z) {
    fe z2, z9, z11, z_5_0, z_10_0, z_20_0, z_40_0, z_50_0, z_100_0, z_200_0, t;
    fe_sq(z2, z);
    fe_sqk(t, z2, 2);
    fe_mul(z9, t, z);
    fe_mul(z11, z9, z2);
    fe_sq(t, z11);
    fe_mul(z_5_0, t, z9);
    fe_sqk(t, z_5_0, 5);
    fe_mul(z_10_0, t, z_5_0);
    fe_sqk(t, z_10_0, 10);
    fe_mul(z_20_0, t, z_10_0);
    fe_sqk(t, z_20_0, 20);
    fe_mul(z_40_0, t, z_20_0);
    fe_sqk(t, z_40_0, 10);
    fe_mul(z_50_0, t, z_10_0);
    fe_sqk(t, z_50_0, 50);
    fe_mul(z_100_0, t, z_50_0);
    fe_sqk(t, z_100_0, 100);
    fe_mul(z_200_0, t, z_100_0);
    fe_sqk(t, z_200_0, 50);
    fe_mul(t, t, z_50_0);
    fe_sqk(t, t, 5);
    fe_mul(r, t, z11);
}

static void pt_compress(uint8_t out[32], const pt& p) {
    fe zinv, x, y;
    fe_invert(zinv, p.z);
    fe_mul(x, p.x, zinv);
    fe_mul(y, p.y, zinv);
    fe_tobytes(out, y);
    out[31] |= (uint8_t)(fe_parity(x) << 7);
}

// Fixed-base scalar multiplication: 4-bit radix-16 comb over a
// precomputed table of d * 16^w * B (w in [0, 64), d in [1, 15]), built
// once per process. Each call is then 63 unified additions and no
// doublings. Variable-time in the scalar (table indexing by digit) —
// acceptable for this research testbed; noted in the Python binding.
static const uint8_t B_ENC[32] = {
    0x58, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66};

static constexpr int BASE_WINDOWS = 64;  // ceil(256 / 4)
static pt g_base_table[BASE_WINDOWS * 15];
static std::once_flag g_base_table_once;

static void build_base_table() {
    pt window_base;
    pt_decompress(window_base, B_ENC);
    for (int w = 0; w < BASE_WINDOWS; w++) {
        pt acc = window_base;
        for (int d = 1; d <= 15; d++) {
            g_base_table[w * 15 + (d - 1)] = acc;
            if (d < 15) pt_add(acc, acc, window_base);
        }
        // next window base: 16^{w+1} B = 16 * (16^w B)
        for (int i = 0; i < 4; i++) pt_double(window_base, window_base);
    }
}

// -- compact SHA-512 (FIPS 180-4) ------------------------------------------
//
// Serves the fused aggregate-certificate path: a QC's n challenge hashes
// h_i = SHA-512(R_i || A_i || msg) share one message and differ only in
// the 64-byte signature/key prefix, so hashing all of them behind ONE
// ctypes crossing (hs_ed25519_cert_challenges) replaces n hashlib object
// constructions + GIL-held update/digest calls on the Python side.

static const uint64_t SHA512_K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL, 0xe9b5dba58189dbbcULL,
    0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL, 0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL,
    0xd807aa98a3030242ULL, 0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL, 0xc19bf174cf692694ULL,
    0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL, 0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL,
    0x2de92c6f592b0275ULL, 0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL, 0xbf597fc7beef0ee4ULL,
    0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL, 0x06ca6351e003826fULL, 0x142929670a0e6e70ULL,
    0x27b70a8546d22ffcULL, 0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL, 0x92722c851482353bULL,
    0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL, 0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL,
    0xd192e819d6ef5218ULL, 0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL, 0x34b0bcb5e19b48a8ULL,
    0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL, 0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL,
    0x748f82ee5defb2fcULL, 0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL, 0xc67178f2e372532bULL,
    0xca273eceea26619cULL, 0xd186b8c721c0c207ULL, 0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL,
    0x06f067aa72176fbaULL, 0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL, 0x431d67c49c100d4cULL,
    0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL, 0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};

static inline uint64_t rotr64(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

static void sha512_block(uint64_t h[8], const uint8_t* p) {
    uint64_t w[80];
    for (int i = 0; i < 16; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[i * 8 + j];
        w[i] = v;
    }
    for (int i = 16; i < 80; i++) {
        uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
        uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint64_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
        uint64_t s1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
        uint64_t ch = (e & f) ^ (~e & g);
        uint64_t t1 = hh + s1 + ch + SHA512_K[i] + w[i];
        uint64_t s0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
        uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint64_t t2 = s0 + maj;
        hh = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

// One-shot SHA-512 over the concatenation of ``nparts`` byte ranges.
static void sha512_oneshot(const uint8_t* const* parts, const uint64_t* lens,
                           int nparts, uint8_t out[64]) {
    uint64_t h[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
        0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
        0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
    };
    uint8_t buf[128];
    size_t fill = 0;
    uint64_t total = 0;
    for (int pi = 0; pi < nparts; pi++) {
        const uint8_t* d = parts[pi];
        uint64_t len = lens[pi];
        total += len;
        while (len) {
            size_t take = 128 - fill;
            if (take > len) take = (size_t)len;
            std::memcpy(buf + fill, d, take);
            fill += take;
            d += take;
            len -= take;
            if (fill == 128) {
                sha512_block(h, buf);
                fill = 0;
            }
        }
    }
    buf[fill++] = 0x80;
    if (fill > 112) {
        std::memset(buf + fill, 0, 128 - fill);
        sha512_block(h, buf);
        fill = 0;
    }
    std::memset(buf + fill, 0, 112 - fill);
    std::memset(buf + 112, 0, 8);  // messages here are < 2^61 bytes
    uint64_t bits = total * 8;
    for (int i = 0; i < 8; i++) buf[120 + i] = (uint8_t)(bits >> (56 - 8 * i));
    sha512_block(h, buf);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++)
            out[i * 8 + j] = (uint8_t)(h[i] >> (56 - 8 * j));
}

extern "C" {

// c-bit window starting at bit offset (byte-unaligned reads via memcpy).
static inline int scalar_window(const uint8_t* scalar, int bit, int c) {
    int byte = bit >> 3;
    if (byte > 24) byte = 24;
    uint64_t w;
    std::memcpy(&w, scalar + byte, 8);
    return (int)((w >> (bit - 8 * byte)) & (((uint64_t)1 << c) - 1));
}

// encodings: m*32 bytes of compressed points; scalars: m*32 bytes of
// little-endian scalars (< 2^253, already reduced mod L by the caller).
// Returns 1 if every point decompresses AND 8 * sum(s_i * P_i) is the
// identity; 0 if any point is invalid or the sum is nonzero; -1 on bad
// arguments. ``c`` is the Pippenger window width in bits (the caller
// picks it by batch size; clamped to [1, 12]). This is the whole device
// MSM contract on CPU.
int hs_ed25519_msm_is_identity(const uint8_t* encodings,
                               const uint8_t* scalars, uint64_t m, int c) {
    if (encodings == nullptr || scalars == nullptr || m == 0) return -1;
    g_msm_calls.fetch_add(1, std::memory_order_relaxed);
    g_msm_points.fetch_add(m, std::memory_order_relaxed);
    if (c < 1) c = 1;
    if (c > 12) c = 12;

    std::vector<pt> points(m);
    for (uint64_t i = 0; i < m; i++) {
        if (!pt_decompress(points[i], encodings + 32 * i)) return 0;
    }

    // Bucketed Pippenger, c-bit windows, MSB-first. Scalars are < 2^253.
    const int N_WINDOWS = (253 + c - 1) / c;
    const int N_BUCKETS = (1 << c) - 1;  // digit 0 skipped
    std::vector<pt> buckets(N_BUCKETS);
    std::vector<bool> used(N_BUCKETS);

    pt acc = PT_IDENTITY;
    bool acc_started = false;
    for (int w = N_WINDOWS - 1; w >= 0; w--) {
        if (acc_started) {
            for (int i = 0; i < c; i++) pt_double(acc, acc);
        }
        std::fill(used.begin(), used.end(), false);
        for (uint64_t i = 0; i < m; i++) {
            int digit = scalar_window(scalars + 32 * i, w * c, c);
            if (digit == 0) continue;
            if (!used[digit - 1]) {
                buckets[digit - 1] = points[i];
                used[digit - 1] = true;
            } else {
                pt_add(buckets[digit - 1], buckets[digit - 1], points[i]);
            }
        }
        // Sweep: sum_d d*bucket[d] with running suffix sums.
        pt running = PT_IDENTITY;
        pt window_sum = PT_IDENTITY;
        bool any = false;
        for (int d = N_BUCKETS - 1; d >= 0; d--) {
            if (used[d]) {
                pt_add(running, running, buckets[d]);
                any = true;
            }
            if (any) pt_add(window_sum, window_sum, running);
        }
        if (any) {
            if (acc_started) {
                pt_add(acc, acc, window_sum);
            } else {
                acc = window_sum;
                acc_started = true;
            }
        }
    }

    // Cofactored check: 8 * acc == identity.
    pt_double(acc, acc);
    pt_double(acc, acc);
    pt_double(acc, acc);
    return pt_is_identity(acc) ? 1 : 0;
}

// Signed-digit Pippenger MSM with optional pre-decompressed points.
//
// Two wins over hs_ed25519_msm_is_identity, both aimed at the per-QC
// batch-verify cost that floors committee-scale rounds:
//   - pre_xy/flags let the caller reuse committee-key decompressions
//     (decompression is ~35% of a 67-signature batch on this box; a
//     validator's committee keys are fixed per epoch — the CPU analog
//     of the device DevicePointCache);
//   - signed digits in [-2^(c-1), 2^(c-1)] halve the bucket count, and
//     the bucket sweep is the second-largest term at QC-sized batches
//     (negated addition is one fe_sub per use).
//
// pre_xy is m*64 bytes of canonical affine x|y (as written by
// hs_ed25519_decompress_check); flags[i] != 0 selects it over
// encodings+32*i. Semantics otherwise identical: 1 iff all points valid
// and 8 * sum(s_i * P_i) == identity. With cofactored == 0 the final
// multiply-by-8 is skipped (sum itself must be the identity) — the
// cofactorless equation of dalek verify_strict / OpenSSL, used for
// single-signature verification when no OpenSSL binding is installed.
int hs_ed25519_msm_signed(const uint8_t* encodings, const uint8_t* pre_xy,
                          const uint8_t* flags, const uint8_t* scalars,
                          uint64_t m, int c, int cofactored) {
    if (encodings == nullptr || scalars == nullptr || m == 0) return -1;
    g_msm_calls.fetch_add(1, std::memory_order_relaxed);
    g_msm_points.fetch_add(m, std::memory_order_relaxed);
    if (c < 1) c = 1;
    if (c > 12) c = 12;

    std::vector<pt> points(m);
    for (uint64_t i = 0; i < m; i++) {
        if (flags != nullptr && pre_xy != nullptr && flags[i]) {
            pt& p = points[i];
            fe_frombytes(p.x, pre_xy + 64 * i);
            fe_frombytes(p.y, pre_xy + 64 * i + 32);
            p.z = FE_ONE;
            fe_mul(p.t, p.x, p.y);
        } else if (!pt_decompress(points[i], encodings + 32 * i)) {
            return 0;
        }
    }

    // Signed recode: LSB-first carry pass, digits in [-2^(c-1), 2^(c-1)].
    const int N_WINDOWS = (253 + c - 1) / c + 1;  // +1 for the top carry
    const int HALF = 1 << (c - 1);
    std::vector<int16_t> digits(m * N_WINDOWS);
    for (uint64_t i = 0; i < m; i++) {
        int carry = 0;
        for (int w = 0; w < N_WINDOWS; w++) {
            int d = (w * c < 256 ? scalar_window(scalars + 32 * i, w * c, c)
                                 : 0) +
                    carry;
            if (d > HALF) {
                d -= 1 << c;
                carry = 1;
            } else {
                carry = 0;
            }
            digits[i * N_WINDOWS + w] = (int16_t)d;
        }
    }

    std::vector<pt> buckets(HALF);
    std::vector<bool> used(HALF);
    pt acc = PT_IDENTITY;
    bool acc_started = false;
    pt negp;
    for (int w = N_WINDOWS - 1; w >= 0; w--) {
        if (acc_started) {
            for (int i = 0; i < c; i++) pt_double(acc, acc);
        }
        std::fill(used.begin(), used.end(), false);
        for (uint64_t i = 0; i < m; i++) {
            int d = digits[i * N_WINDOWS + w];
            if (d == 0) continue;
            const pt* p = &points[i];
            if (d < 0) {
                pt_neg(negp, points[i]);
                p = &negp;
                d = -d;
            }
            if (!used[d - 1]) {
                buckets[d - 1] = *p;
                used[d - 1] = true;
            } else {
                pt_add(buckets[d - 1], buckets[d - 1], *p);
            }
        }
        pt running = PT_IDENTITY;
        pt window_sum = PT_IDENTITY;
        bool any = false;
        for (int d = HALF - 1; d >= 0; d--) {
            if (used[d]) {
                pt_add(running, running, buckets[d]);
                any = true;
            }
            if (any) pt_add(window_sum, window_sum, running);
        }
        if (any) {
            if (acc_started) {
                pt_add(acc, acc, window_sum);
            } else {
                acc = window_sum;
                acc_started = true;
            }
        }
    }

    if (cofactored) {
        pt_double(acc, acc);
        pt_double(acc, acc);
        pt_double(acc, acc);
    }
    return pt_is_identity(acc) ? 1 : 0;
}

// out32 = compress(scalar * B). scalar: 32 bytes little-endian, already
// reduced mod L by the caller (< 2^253). Returns 1; -1 on null args.
// Powers Ed25519 signing and public-key derivation when the environment
// has no OpenSSL-backed crypto package (the Python side does the SHA-512
// and mod-L scalar arithmetic, exactly like the batch-verify split).
int hs_ed25519_scalarmult_base(const uint8_t* scalar, uint8_t* out32) {
    if (scalar == nullptr || out32 == nullptr) return -1;
    g_scalarmult_calls.fetch_add(1, std::memory_order_relaxed);
    std::call_once(g_base_table_once, build_base_table);
    pt acc = PT_IDENTITY;
    bool started = false;
    for (int w = 0; w < BASE_WINDOWS; w++) {
        int d = (scalar[w >> 1] >> ((w & 1) * 4)) & 0xf;
        if (d == 0) continue;
        const pt& e = g_base_table[w * 15 + (d - 1)];
        if (!started) {
            acc = e;
            started = true;
        } else {
            pt_add(acc, acc, e);
        }
    }
    pt_compress(out32, acc);
    return 1;
}

// Single-point decompression probe (for tests): returns 1 if the encoding
// is a valid canonical curve point, else 0; writes the canonical x|y
// field bytes when out is non-null.
int hs_ed25519_decompress_check(const uint8_t* enc, uint8_t* out64) {
    if (enc == nullptr) return -1;
    g_decompress_calls.fetch_add(1, std::memory_order_relaxed);
    pt p;
    if (!pt_decompress(p, enc)) return 0;
    if (out64 != nullptr) {
        fe_tobytes(out64, p.x);
        fe_tobytes(out64 + 32, p.y);
    }
    return 1;
}

// Batch challenge hashing for a fused aggregate-certificate verify:
// out[64*i : 64*i+64] = SHA-512(sigs[stride*i : +32] || pubs[32*i : +32]
// || msg) for i in [0, n) — the Ed25519 challenge h_i = H(R_i||A_i||M)
// for every seat of a cert sharing one message, computed behind a single
// ctypes crossing. ``stride`` lets the caller pass the cert's packed
// signature buffer (wire-v2 QC stride 64, TC record stride 72) without
// slicing per-signature copies in Python.
int hs_ed25519_cert_challenges(const uint8_t* msg, uint64_t msg_len,
                               const uint8_t* pubs, const uint8_t* sigs,
                               uint64_t stride, uint64_t n, uint8_t* out) {
    if (msg == nullptr || pubs == nullptr || sigs == nullptr ||
        out == nullptr || n == 0 || stride < 64)
        return -1;
    g_cert_challenge_calls.fetch_add(1, std::memory_order_relaxed);
    g_cert_challenge_sigs.fetch_add(n, std::memory_order_relaxed);
    const uint8_t* parts[3];
    uint64_t lens[3] = {32, 32, msg_len};
    for (uint64_t i = 0; i < n; i++) {
        parts[0] = sigs + stride * i;  // R_i: first 32 bytes of the sig
        parts[1] = pubs + 32 * i;      // A_i
        parts[2] = msg;
        sha512_oneshot(parts, lens, 3, out + 64 * i);
    }
    return 1;
}

// Telemetry snapshot: fills up to ``cap`` slots in the order
// {msm_calls, msm_points, scalarmult_calls, decompress_calls,
// cert_challenge_calls, cert_challenge_sigs} and returns the number
// filled. One call exports every engine counter — the registry
// collector reads this once per snapshot.
int hs_ed25519_stats(uint64_t* out, int cap) {
    if (out == nullptr || cap <= 0) return 0;
    const uint64_t fields[6] = {
        g_msm_calls.load(std::memory_order_relaxed),
        g_msm_points.load(std::memory_order_relaxed),
        g_scalarmult_calls.load(std::memory_order_relaxed),
        g_decompress_calls.load(std::memory_order_relaxed),
        g_cert_challenge_calls.load(std::memory_order_relaxed),
        g_cert_challenge_sigs.load(std::memory_order_relaxed),
    };
    int n = cap < 6 ? cap : 6;
    for (int i = 0; i < n; i++) out[i] = fields[i];
    return n;
}

}  // extern "C"
