"""Multi-chip sharded batch verification over a jax.sharding.Mesh.

The MSM lanes (one per R_i/A_i/B term) are the parallel axis: each device
decompresses and accumulates its lane shard into a partial MSM accumulator
point, and the per-device partials are combined with an ``all_gather`` over
ICI followed by a log-depth point-addition tree (point addition is a group
law, not a ring sum, so this is the system's "psum" — see SURVEY.md §2.8:
the one true collective in the design).

This scales the 4096-validator vote-set target (BASELINE.json config 5):
lanes 2*4096+1 → 8 devices × ~1k lanes each.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from hotstuff_tpu.ops import curve as cv
from hotstuff_tpu.ops import field as fe

AXIS = "lanes"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def _combine_partials(acc: jnp.ndarray) -> jnp.ndarray:
    """Inside shard_map: combine per-device accumulator points. Point
    addition is the group law (not a ring op), so gather + tree-add."""
    partials = jax.lax.all_gather(acc, AXIS)  # [D, 4, 20]
    d = partials.shape[0]
    while d > 1:
        half = d // 2
        partials = cv.point_add(partials[:half], partials[half : 2 * half])
        d = half
    return partials[0]


def msm_sharded(mesh: Mesh, points: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """Like ``curve.msm`` but lanes sharded across the mesh.

    points: [m, 4, 20], digits: [N_WINDOWS, m]; m divisible by mesh size
    with a power-of-two per-device shard.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(AXIS, None, None), P(None, AXIS)),
        out_specs=P(),
        # The combine (all_gather + tree add) replicates the result on every
        # device, but that's data-dependent knowledge the static
        # varying-axes check can't infer.
        check_vma=False,
    )
    def run(pts, dg):
        return _combine_partials(cv.msm(pts, dg))

    return run(points, digits)


def build_verifier(mesh: Mesh, m: int):
    """A jitted sharded verifier for padded lane count ``m``: decompress all
    lanes, partial MSM per device, combine over ICI, cofactor-check."""
    n_dev = mesh.devices.size
    assert m % n_dev == 0, "lanes must divide the mesh"
    per_dev = m // n_dev
    assert per_dev & (per_dev - 1) == 0, "per-device lanes must be 2^k"

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(AXIS, None),),
        out_specs=P(),
        check_vma=False,  # result replicated by the explicit combine
    )
    def run(packed):
        from hotstuff_tpu.ops.verify import _kernels, _unpack_device

        root_fn, msm_fn = _kernels()
        y_limbs, signs, digits = _unpack_device(packed)
        ok, pts = cv.decompress(y_limbs, signs, root_fn=root_fn)
        acc = _combine_partials(msm_fn(pts, digits))
        all_ok = jax.lax.psum(jnp.all(ok).astype(jnp.int32), AXIS) == n_dev
        zero = cv.is_identity(cv.mul_by_cofactor(acc[None, ...]))[0]
        return all_ok & zero

    return run


def verify_batch_device_sharded(mesh: Mesh, msgs, pubs, sigs, _rng=None) -> bool:
    """Sharded variant of ``ops.verify.verify_batch_device``."""
    from hotstuff_tpu.ops import verify as v

    n = len(msgs)
    if n == 0:
        return True
    prepared = v.prepare_batch(msgs, pubs, sigs, _rng=_rng)
    if prepared is None:
        return False
    packed, m = prepared
    n_dev = mesh.devices.size
    # Round lanes up so each device gets an equal power-of-two shard.
    per_dev = max(4, -(-m // n_dev))
    while per_dev & (per_dev - 1):
        per_dev += 1
    target = per_dev * n_dev
    if target > m:
        packed = v.pad_prepared(packed, target)
    run = _sharded_cache(mesh, target)
    return bool(run(jnp.asarray(packed)))


_VERIFIERS: dict = {}


def _sharded_cache(mesh: Mesh, m: int):
    key = (id(mesh), m)
    if key not in _VERIFIERS:
        _VERIFIERS[key] = build_verifier(mesh, m)
    return _VERIFIERS[key]
