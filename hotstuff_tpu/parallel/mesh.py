"""Multi-chip sharded batch verification over a jax.sharding.Mesh.

The MSM lanes (one per R_i/A_i/B term) are the parallel axis: each device
decompresses and accumulates its lane shard into a partial MSM accumulator
point, and the per-device partials are combined with an ``all_gather`` over
ICI followed by a log-depth point-addition tree (point addition is a group
law, not a ring sum, so this is the system's "psum" — see SURVEY.md §2.8:
the one true collective in the design).

This scales the 4096-validator vote-set target (BASELINE.json config 5):
lanes 2*4096+1 → 8 devices × ~1k lanes each.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map_fn
except ImportError:  # older jax: experimental namespace, module-per-name
    from jax.experimental.shard_map import shard_map as _shard_map_fn

import inspect as _inspect

if "check_vma" in _inspect.signature(_shard_map_fn).parameters:
    shard_map = _shard_map_fn
else:
    # Older jax spells the replication-check knob ``check_rep``.
    def shard_map(f=None, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return lambda g: _shard_map_fn(g, **kwargs)
        return _shard_map_fn(f, **kwargs)

from hotstuff_tpu.ops import curve as cv
from hotstuff_tpu.ops import field as fe

AXIS = "lanes"


def make_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def _combine_partials(acc: jnp.ndarray) -> jnp.ndarray:
    """Inside shard_map: combine per-device accumulator points. Point
    addition is the group law (not a ring op), so gather + tree-add."""
    partials = jax.lax.all_gather(acc, AXIS)  # [D, 4, 20]
    d = partials.shape[0]
    while d > 1:
        half = d // 2
        partials = cv.point_add(partials[:half], partials[half : 2 * half])
        d = half
    return partials[0]


def msm_sharded(mesh: Mesh, points: jnp.ndarray, digits: jnp.ndarray) -> jnp.ndarray:
    """Like ``curve.msm`` but lanes sharded across the mesh.

    points: [m, 4, 20], digits: [N_WINDOWS, m]; m divisible by mesh size
    with a power-of-two per-device shard.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(AXIS, None, None), P(None, AXIS)),
        out_specs=P(),
        # The combine (all_gather + tree add) replicates the result on every
        # device, but that's data-dependent knowledge the static
        # varying-axes check can't infer.
        check_vma=False,
    )
    def run(pts, dg):
        return _combine_partials(cv.msm(pts, dg))

    return run(points, digits)


def build_verifier(mesh: Mesh, m: int):
    """A jitted sharded verifier for padded lane count ``m``: decompress all
    lanes, partial MSM per device, combine over ICI, cofactor-check."""
    n_dev = mesh.devices.size
    assert m % n_dev == 0, "lanes must divide the mesh"
    per_dev = m // n_dev
    assert per_dev & (per_dev - 1) == 0, "per-device lanes must be 2^k"

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(AXIS, None),),
        out_specs=P(),
        check_vma=False,  # result replicated by the explicit combine
    )
    def run(packed):
        from hotstuff_tpu.ops.verify import _kernels, _unpack_device

        root_fn, msm_fn = _kernels()
        y_limbs, signs, digits = _unpack_device(packed)
        ok, pts = cv.decompress(y_limbs, signs, root_fn=root_fn)
        acc = _combine_partials(msm_fn(pts, digits))
        all_ok = jax.lax.psum(jnp.all(ok).astype(jnp.int32), AXIS) == n_dev
        zero = cv.is_identity(cv.mul_by_cofactor(acc[None, ...]))[0]
        return all_ok & zero

    return run


def verify_batch_device_sharded(mesh: Mesh, msgs, pubs, sigs, _rng=None) -> bool:
    """Sharded variant of ``ops.verify.verify_batch_device``."""
    from hotstuff_tpu.ops import verify as v

    n = len(msgs)
    if n == 0:
        return True
    prepared = v.prepare_batch(msgs, pubs, sigs, _rng=_rng)
    if prepared is None:
        return False
    packed, m = prepared
    n_dev = mesh.devices.size
    # Round lanes up so each device gets an equal power-of-two shard.
    target = _shard_target(m, n_dev)
    if target > m:
        packed = v.pad_prepared(packed, target)
    run = _sharded_cache(mesh, target)
    return bool(run(jnp.asarray(packed)))


_VERIFIERS: dict = {}


def _sharded_cache(mesh: Mesh, m: int):
    key = (id(mesh), m)
    if key not in _VERIFIERS:
        _VERIFIERS[key] = build_verifier(mesh, m)
    return _VERIFIERS[key]


def build_cached_verifier(mesh: Mesh, mf: int, mc: int):
    """Sharded variant of ``ops.verify._compiled_cached``: the committee
    point cache (device-resident, replicated across the mesh) supplies the
    A/B points; each device decompresses its shard of the fresh R lanes and
    accumulates partial signed MSMs for both groups; one ICI combine.

    This keeps round-2's main crypto optimization on the BASELINE config-5
    path (4096-validator vote sets sharded across a pod slice), which
    previously fell back to full decompression."""
    n_dev = mesh.devices.size
    for m, nm in ((mf, "fresh"), (mc, "cached")):
        assert m % n_dev == 0, f"{nm} lanes must divide the mesh"
        per = m // n_dev
        assert per & (per - 1) == 0, f"per-device {nm} lanes must be 2^k"

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), P(None, None, None)),
        out_specs=P(),
        check_vma=False,  # result replicated by the explicit combine
    )
    def run(fresh, cached, cache_arr):
        from hotstuff_tpu.ops.verify import (
            _enc_to_y_limbs,
            _kernels,
            _signed_msm_fn,
        )

        root_fn, _ = _kernels()
        msm_signed = _signed_msm_fn()
        b_f = fresh.astype(jnp.int32)
        b_c = cached.astype(jnp.int32)
        y_limbs = _enc_to_y_limbs(b_f[:, :32])
        ok_f, pts_f = cv.decompress(y_limbs, b_f[:, 65], root_fn=root_fn)
        digits_f = b_f[:, 32:65].T - 8  # [33, mf/D] signed
        rows = b_c[:, 64] | (b_c[:, 65] << 8)
        pts_c = jnp.take(cache_arr, rows, axis=0)
        digits_c = b_c[:, :64].T - 8  # [64, mc/D] signed
        acc = cv.point_add(
            msm_signed(pts_f, digits_f), msm_signed(pts_c, digits_c)
        )
        acc = _combine_partials(acc)
        all_ok = jax.lax.psum(jnp.all(ok_f).astype(jnp.int32), AXIS) == n_dev
        zero = cv.is_identity(cv.mul_by_cofactor(acc[None, ...]))[0]
        return all_ok & zero

    return run


def _sharded_cached_cache(mesh: Mesh, mf: int, mc: int):
    key = (id(mesh), mf, mc, "cached")
    if key not in _VERIFIERS:
        _VERIFIERS[key] = build_cached_verifier(mesh, mf, mc)
    return _VERIFIERS[key]


def _shard_target(m: int, n_dev: int) -> int:
    """Smallest lane count >= m giving each device an equal 2^k shard."""
    per = max(4, -(-m // n_dev))
    while per & (per - 1):
        per += 1
    return per * n_dev


def verify_batch_device_cached_sharded(
    mesh: Mesh, msgs, pubs, sigs, cache, _rng=None
) -> bool:
    """Sharded variant of ``ops.verify.verify_batch_device_cached``."""
    from hotstuff_tpu.ops import verify as v

    if len(msgs) == 0:
        return True
    prepared = v.prepare_batch_cached(msgs, pubs, sigs, cache, _rng=_rng)
    if prepared is None:
        return False
    packed, mf, mc = prepared
    n_dev = mesh.devices.size
    mf2 = _shard_target(mf, n_dev)
    mc2 = _shard_target(mc, n_dev)
    if (mf2, mc2) != (mf, mc):
        packed = v.pad_prepared_cached(packed, mf, mc, mf2, mc2)
    run = _sharded_cached_cache(mesh, mf2, mc2)
    return bool(
        run(
            jnp.asarray(packed[:mf2]),
            jnp.asarray(packed[mf2:]),
            cache.array,
        )
    )
