"""Parallel runtimes: device-mesh sharded crypto (``mesh``) and the
process-sharded committee engine groups (``engine_groups``).

``mesh`` pulls in jax at import; the engine-group runtime is pure
stdlib (multiprocessing + shared memory) and worker processes must not
pay a jax import to boot, so the mesh exports resolve lazily (PEP 562).
"""

_MESH_EXPORTS = ("make_mesh", "msm_sharded", "verify_batch_device_sharded")


def __getattr__(name):
    if name in _MESH_EXPORTS:
        from . import mesh

        return getattr(mesh, name)
    raise AttributeError(name)


__all__ = [*_MESH_EXPORTS, "engine_groups"]
