from .mesh import make_mesh, msm_sharded, verify_batch_device_sharded

__all__ = ["make_mesh", "msm_sharded", "verify_batch_device_sharded"]
