"""Process-sharded committee engine groups.

The one-process committee testbed multiplexes every engine through one
GIL; the PR 7 profiler measured ~59% of N=200 wall as GIL delay while
verify workers idled. This runtime generalizes the native command ring's
batching discipline (``network/native``: fixed-layout LE records, one
flush per loop iteration, pricing counters) across PROCESS boundaries:
the committee is sharded into worker processes ("engine groups"), each
running its slice of consensus engines on its own event loop with its
own crypto plane, native transport and decode arena, while the parent
touches only decisions — commit events, error verdicts, and the merged
telemetry snapshot — carried over shared-memory SPSC rings.

Topology: node i lives in group ``i % n_groups``; the committee's
addresses are plain localhost TCP, so cross-group links are ordinary
socket connections (the ReliableSender's backoff reconnect absorbs boot
skew between groups). Nothing inside an engine changes: the
single-process path (``HOTSTUFF_ENGINE_GROUPS=0``, the default) is
byte-identical for tests and Simulant.

Ring layout (one producer, one consumer, same pricing discipline as the
native command ring): a 16-byte header of u64 little-endian head/tail
cursors, then a power-of-two payload arena of ``op:u8 len:u32le payload``
records. A record that would straddle the arena end is preceded by an
op=0 wrap marker. Counters (pushes, bytes, wraps, polls) mirror into the
telemetry registry as ``parallel.ring.*``.

On a one-core host this buys GIL-crossing avoidance, not parallelism —
the committed N=1000 milestone rows are measured single-process with the
fused aggregate-QC plane; the groups runtime is the architecture for
multi-core hosts and is exercised by ``tests/test_engine_groups.py``.
"""

from __future__ import annotations

import asyncio
import json
import multiprocessing
import os
import struct
import time
from multiprocessing import shared_memory

_HDR = 16  # two u64 cursors
_REC = struct.Struct("<BI")  # op, payload length

# Ring record ops (u8). 0 is the wrap marker, never a record.
OP_READY = 1  # worker booted its shard              payload: group:u32
OP_COMMIT = 2  # one engine committed a block         payload: node:u32 seq:u64
OP_TELEMETRY = 3  # final registry snapshot             payload: JSON bytes
OP_ERROR = 4  # worker died                          payload: UTF-8 message
OP_DONE = 5  # worker finished shutdown             payload: group:u32
OP_STOP = 6  # parent -> worker: shut down          payload: empty

_READY = struct.Struct("<I")
_COMMIT = struct.Struct("<IQ")


def groups_from_env(default: int = 0) -> int:
    """``HOTSTUFF_ENGINE_GROUPS``: 0 (default) disables the runtime —
    the kill-switch keeping the single-process path byte-identical."""
    try:
        return max(0, int(os.environ.get("HOTSTUFF_ENGINE_GROUPS", default)))
    except ValueError:
        return 0


class ShmRing:
    """SPSC byte ring over POSIX shared memory.

    One side constructs with ``create=True`` (owner, unlinks on close);
    the peer attaches by name. Exactly one process pushes and exactly one
    pops — cursor stores are 8-byte aligned u64 writes, and each side
    only ever writes its own cursor (producer: tail, consumer: head).
    """

    def __init__(self, name: str | None = None, capacity: int = 1 << 20,
                 create: bool = False) -> None:
        if create:
            assert capacity & (capacity - 1) == 0, "capacity must be 2^k"
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=_HDR + capacity
            )
            self._shm.buf[:_HDR] = bytes(_HDR)
        else:
            # Attach: the creator chose the capacity, so derive it from
            # the segment instead of trusting the default (the size may
            # be page-rounded, hence largest power of two that fits).
            self._shm = shared_memory.SharedMemory(name=name)
            capacity = 1 << ((self._shm.size - _HDR).bit_length() - 1)
        self.capacity = capacity
        self.name = self._shm.name
        self._owner = create
        self._cur = self._shm.buf[:_HDR].cast("Q")  # [head, tail]
        self._buf = self._shm.buf[_HDR:]
        # Pricing counters, same discipline as the native command ring
        # (each side counts its own operations; merged via telemetry).
        self.pushes = 0
        self.push_bytes = 0
        self.wraps = 0
        self.polls = 0
        self.pops = 0

    # -- producer side ------------------------------------------------------

    def try_push(self, op: int, payload: bytes = b"") -> bool:
        """Append one record; False when the ring lacks space (caller
        decides whether to spin — commit events may not be dropped)."""
        need = _REC.size + len(payload)
        if need > self.capacity - _REC.size - 1:
            raise ValueError("record exceeds ring capacity")
        head = self._cur[0]
        tail = self._cur[1]
        free = self.capacity - (tail - head)
        pos = tail % self.capacity
        room_to_end = self.capacity - pos
        wrap = room_to_end < need
        if wrap and room_to_end < _REC.size:
            # Not even space for a wrap marker before the edge: treat the
            # trailing sliver as consumed by the wrap.
            if free < room_to_end + need:
                return False
            tail += room_to_end
        elif wrap:
            if free < room_to_end + need:
                return False
            self._buf[pos : pos + _REC.size] = _REC.pack(0, 0)
            tail += room_to_end
        elif free < need:
            return False
        if wrap:
            self.wraps += 1
            pos = tail % self.capacity
        self._buf[pos : pos + _REC.size] = _REC.pack(op, len(payload))
        if payload:
            self._buf[pos + _REC.size : pos + need] = payload
        self._cur[1] = tail + need  # publish after the payload is in place
        self.pushes += 1
        self.push_bytes += need
        return True

    def push(self, op: int, payload: bytes = b"", timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while not self.try_push(op, payload):
            if time.monotonic() > deadline:
                raise TimeoutError("ring full: consumer stalled")
            time.sleep(0.0005)

    # -- consumer side ------------------------------------------------------

    def pop_all(self) -> list[tuple[int, bytes]]:
        """Drain every published record (one poll, many records — the
        command-ring flush pattern in reverse)."""
        self.polls += 1
        out: list[tuple[int, bytes]] = []
        head = self._cur[0]
        tail = self._cur[1]
        while head != tail:
            pos = head % self.capacity
            room_to_end = self.capacity - pos
            if room_to_end < _REC.size:
                head += room_to_end  # trailing sliver skipped by producer
                continue
            op, ln = _REC.unpack_from(self._buf, pos)
            if op == 0:
                head += room_to_end  # wrap marker
                continue
            payload = bytes(self._buf[pos + _REC.size : pos + _REC.size + ln])
            head += _REC.size + ln
            out.append((op, payload))
            self.pops += 1
        self._cur[0] = head  # release consumed space
        return out

    # -- lifecycle ----------------------------------------------------------

    def counters(self) -> dict[str, int]:
        return {
            "pushes": self.pushes,
            "push_bytes": self.push_bytes,
            "wraps": self.wraps,
            "polls": self.polls,
            "pops": self.pops,
        }

    def close(self) -> None:
        # Release exported memoryviews before closing the segment.
        self._cur.release()
        self._buf.release()
        self._shm.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


class EngineGroup:
    """Parent-side handle for one worker process and its two rings."""

    def __init__(self, group_id: int, node_ids: list[int]) -> None:
        self.group_id = group_id
        self.node_ids = node_ids
        self.events = ShmRing(create=True)  # worker -> parent
        self.commands = ShmRing(create=True, capacity=1 << 12)  # parent -> worker
        self.process: multiprocessing.Process | None = None
        self.ready = False
        self.done = False
        self.error: str | None = None
        self.telemetry: dict | None = None

    def close(self) -> None:
        self.events.close()
        self.commands.close()


def _worker_main(group_id, node_ids, keys, addresses, timeout_delay,
                 evt_name, cmd_name) -> None:
    """Worker entry: boot this group's engine shard, stream commit events
    to the parent, shut down on OP_STOP, post the telemetry snapshot."""
    events = ShmRing(name=evt_name)
    commands = ShmRing(name=cmd_name)
    try:
        asyncio.run(
            _worker_async(
                group_id, node_ids, keys, addresses, timeout_delay,
                events, commands,
            )
        )
        events.push(OP_DONE, _READY.pack(group_id))
    except BaseException as e:  # noqa: BLE001 - verdict must reach the parent
        try:
            events.push(OP_ERROR, f"group {group_id}: {e!r}".encode())
        except Exception:
            pass
        raise
    finally:
        events.close()
        commands.close()


async def _worker_async(group_id, node_ids, keys, addresses, timeout_delay,
                        events: ShmRing, commands: ShmRing) -> None:
    from hotstuff_tpu import telemetry
    from hotstuff_tpu.consensus import Authority, Committee, Consensus, Parameters
    from hotstuff_tpu.crypto import SignatureService
    from hotstuff_tpu.store import Store

    # BEFORE engines are constructed (they capture metric objects at
    # creation): the final snapshot each group posts is the parent's only
    # view into the shard, so the registry must be live.
    telemetry.enable()
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=addresses[i])
            for i, (pk, _) in enumerate(keys)
        }
    )
    params = Parameters(
        timeout_delay=timeout_delay, batch_vote_verification=True
    )

    engines, watchers, sinks = [], [], []
    for idx in node_ids:
        pk, sk = keys[idx]
        rx_mempool: asyncio.Queue = asyncio.Queue()
        tx_mempool: asyncio.Queue = asyncio.Queue()
        tx_commit: asyncio.Queue = asyncio.Queue()

        async def drain(q=tx_mempool):
            while True:
                await q.get()

        async def watch(q=tx_commit, node=idx):
            seq = 0
            while True:
                await q.get()
                seq += 1
                events.push(OP_COMMIT, _COMMIT.pack(node, seq))

        sinks.append(asyncio.create_task(drain()))
        watchers.append(asyncio.create_task(watch()))
        engines.append(
            await Consensus.spawn(
                pk, committee, params, SignatureService(sk), Store(),
                rx_mempool, tx_mempool, tx_commit,
            )
        )
    events.push(OP_READY, _READY.pack(group_id))

    # Poll the command ring off the loop's natural cadence; OP_STOP ends
    # the shard. The poll interval is latency of SHUTDOWN only — commit
    # events flow the other way without it.
    stopping = False
    while not stopping:
        for op, _payload in commands.pop_all():
            if op == OP_STOP:
                stopping = True
        await asyncio.sleep(0.02)

    for e in engines:
        await e.shutdown()
    for t in (*sinks, *watchers):
        t.cancel()
    snap = telemetry.get_registry().snapshot()
    snap["parallel.ring"] = events.counters()
    events.push(OP_TELEMETRY, json.dumps(snap).encode())


class EngineGroupRuntime:
    """Boot a committee sharded over ``n_groups`` worker processes and
    measure commit progress from the parent.

    The parent never constructs an engine, decodes a frame, or verifies a
    signature — it generates the committee identity, forks the groups,
    and consumes decision records (ready / commit / error / telemetry)
    from the event rings.
    """

    def __init__(self, n: int, n_groups: int, base_port: int = 18000,
                 timeout_delay: int = 30_000) -> None:
        if n_groups < 1:
            raise ValueError("n_groups must be >= 1")
        self.n = n
        self.n_groups = min(n_groups, n)
        self.base_port = base_port
        self.timeout_delay = timeout_delay
        self.groups: list[EngineGroup] = []
        self.commit_counts = [0] * n

    def start(self) -> None:
        from hotstuff_tpu.crypto import generate_keypair

        keys = [generate_keypair() for _ in range(self.n)]
        addresses = [("127.0.0.1", self.base_port + i) for i in range(self.n)]
        ctx = multiprocessing.get_context("fork")  # inherit keys, no pickling
        for g in range(self.n_groups):
            node_ids = list(range(g, self.n, self.n_groups))
            group = EngineGroup(g, node_ids)
            group.process = ctx.Process(
                target=_worker_main,
                args=(
                    g, node_ids, keys, addresses, self.timeout_delay,
                    group.events.name, group.commands.name,
                ),
                daemon=True,
            )
            group.process.start()
            self.groups.append(group)

    def _drain(self) -> None:
        for g in self.groups:
            for op, payload in g.events.pop_all():
                if op == OP_READY:
                    g.ready = True
                elif op == OP_COMMIT:
                    node, seq = _COMMIT.unpack(payload)
                    self.commit_counts[node] = seq
                elif op == OP_ERROR:
                    g.error = payload.decode(errors="replace")
                elif op == OP_TELEMETRY:
                    g.telemetry = json.loads(payload.decode())
                elif op == OP_DONE:
                    g.done = True

    def _check_failures(self) -> None:
        for g in self.groups:
            if g.error is not None:
                raise RuntimeError(g.error)
            if g.process is not None and not g.process.is_alive() and not g.done:
                raise RuntimeError(
                    f"group {g.group_id} died (exitcode "
                    f"{g.process.exitcode}) without a verdict"
                )

    def _wait(self, predicate, timeout: float, what: str) -> None:
        deadline = time.monotonic() + timeout
        while True:
            self._drain()
            if predicate():
                return
            self._check_failures()
            if time.monotonic() > deadline:
                raise TimeoutError(f"engine groups: timed out waiting for {what}")
            time.sleep(0.002)

    def measure(self, rounds_target: int, boot_timeout: float = 120.0,
                round_timeout: float = 600.0) -> float:
        """Seconds per round: wait for the first commit on every node
        (the single-process harness's measurement anchor), then time
        ``rounds_target`` more everywhere."""
        self._wait(
            lambda: all(g.ready for g in self.groups), boot_timeout, "boot"
        )
        self._wait(
            lambda: all(c >= 1 for c in self.commit_counts),
            round_timeout, "first commit",
        )
        target = 1 + rounds_target
        t0 = time.perf_counter()
        self._wait(
            lambda: all(c >= target for c in self.commit_counts),
            round_timeout, f"{rounds_target} rounds",
        )
        return (time.perf_counter() - t0) / rounds_target

    def stop(self, timeout: float = 60.0) -> dict:
        """Stop every group and merge telemetry: counter sums across the
        groups plus the parent-side ring pricing, keyed per group."""
        for g in self.groups:
            try:
                g.commands.push(OP_STOP)
            except TimeoutError:
                pass
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._drain()
            if all(g.done or g.error is not None for g in self.groups):
                break
            if all(
                g.process is None or not g.process.is_alive()
                for g in self.groups
            ):
                self._drain()
                break
            time.sleep(0.01)
        merged_counters: dict[str, int] = {}
        rings: dict[str, dict] = {}
        for g in self.groups:
            if g.process is not None:
                g.process.join(timeout=10)
                if g.process.is_alive():
                    g.process.terminate()
                    g.process.join(timeout=10)
            if g.telemetry:
                for name, value in g.telemetry.get("counters", {}).items():
                    merged_counters[name] = merged_counters.get(name, 0) + value
                rings[f"group{g.group_id}"] = g.telemetry.get(
                    "parallel.ring", {}
                )
            rings[f"group{g.group_id}.parent"] = {
                "events": g.events.counters(),
                "commands": g.commands.counters(),
            }
            g.close()
        try:
            from hotstuff_tpu import telemetry

            telemetry.gauge("parallel.groups").set(self.n_groups)
            for name, value in merged_counters.items():
                telemetry.counter("parallel.merged." + name).inc(value)
        except Exception:
            pass
        return {"counters": merged_counters, "rings": rings}


def run_grouped_committee(n: int, rounds_target: int, n_groups: int,
                          base_port: int = 18000,
                          timeout_delay: int = 30_000) -> tuple[float, dict]:
    """Convenience wrapper: boot, measure, stop. Returns
    (seconds_per_round, merged telemetry)."""
    rt = EngineGroupRuntime(
        n, n_groups, base_port=base_port, timeout_delay=timeout_delay
    )
    rt.start()
    try:
        per_round = rt.measure(rounds_target)
    finally:
        merged = rt.stop()
    return per_round, merged
