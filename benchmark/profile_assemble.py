"""Function-level attribution: join sampling-profiler records onto the
cross-node trace edges.

``benchmark/trace_assemble.py`` answers "which EDGE of the round eats
the milliseconds" (ingress, vote_wire, qc_to_commit, ...);
``telemetry/profiler.py`` records folded stacks tagged with the stage
active when each sample was taken — and the stages are NAMED AFTER the
trace edges, so the join is a group-by: for every edge, the top-k
functions by self (leaf) samples inside it, with sample counts converted
to estimated milliseconds via the sampling interval. The report is the
"which decode path, which ctypes call" answer ROADMAP items 2-3 need
before the shared decode arena / command ring are built.

Also emits speedscope-format flamegraphs (one sampled profile per
stage, https://www.speedscope.app) so the full stacks stay explorable,
and surfaces the sampler's boundary accounts: per-``hs_net_*``/
``hs_ed25519_*`` ctypes call counts + wall time, and the GIL-delay
proxy.

    python -m benchmark.profile_assemble .bench/logs --committee 200 \
        --output results/profile-attribution-200.json \
        --speedscope results/profile-200.speedscope.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import Counter, defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402
from benchmark.logs import ParseError, read_stream_records  # noqa: E402
from benchmark.trace_assemble import EDGES, assemble  # noqa: E402

ATTRIBUTION_SCHEMA = "hotstuff-profile-attribution-v1"


def load_profiles(
    paths: list[str], skipped_streams: list[str] | None = None
) -> list[dict]:
    """All ``hotstuff-profile-v1`` records across streams; unusable
    streams are skipped with a warning (same contract as the trace
    assembler — partial attribution beats none)."""
    records: list[dict] = []
    for path in paths:
        try:
            records.extend(read_stream_records(path).profiles)
        except (ParseError, OSError) as e:
            print(f"WARN: skipping stream {path}: {e}", file=sys.stderr)
            if skipped_streams is not None:
                skipped_streams.append(os.path.basename(path))
    return records


def aggregate(records: list[dict]) -> tuple[dict[str, Counter], dict]:
    """(per-stage folded-stack counters, sampler meta). Stage counters
    sum across records/nodes; meta keeps the session totals the report
    surfaces (samples, interval, GIL delay, ctypes accounts — cumulative
    per record, so the LAST record per (node, pid) wins)."""
    stages: dict[str, Counter] = defaultdict(Counter)
    last: dict[tuple, dict] = {}
    interval_ms = None
    for rec in records:
        interval_ms = rec.get("interval_ms", interval_ms)
        for stage_name, folded, count in rec.get("stacks", []):
            stages[stage_name][folded] += count
        key = (rec.get("node", ""), rec.get("pid", 0))
        if key not in last or rec.get("seq", 0) >= last[key].get("seq", 0):
            last[key] = rec
    samples = sum(r.get("samples", 0) for r in last.values())
    gil_delay_ns = sum(r.get("gil_delay_ns", 0) for r in last.values())
    truncated = sum(r.get("truncated", 0) for r in last.values())
    ctypes_totals: dict[str, list[int]] = defaultdict(lambda: [0, 0])
    for rec in last.values():
        for name, (calls, ns) in (rec.get("ctypes") or {}).items():
            ctypes_totals[name][0] += calls
            ctypes_totals[name][1] += ns
    meta = {
        "interval_ms": interval_ms,
        "samples": samples,
        "truncated": truncated,
        "gil_delay_ms": round(gil_delay_ns / 1e6, 3),
        "sessions": len(last),
        "ctypes": {
            name: {
                "calls": calls,
                "ms": round(ns / 1e6, 3),
                "us_per_call": round(ns / 1e3 / calls, 3) if calls else None,
            }
            for name, (calls, ns) in sorted(
                ctypes_totals.items(), key=lambda kv: -kv[1][1]
            )
        },
    }
    return dict(stages), meta


def top_functions(
    stacks: Counter, interval_ms: float | None, k: int
) -> list[dict]:
    """Top-k by self (leaf) samples inside one stage, with cumulative
    (anywhere-on-stack) counts alongside."""
    self_c: Counter[str] = Counter()
    cum_c: Counter[str] = Counter()
    total = 0
    for folded, count in stacks.items():
        frames = folded.split(";")
        self_c[frames[-1]] += count
        total += count
        for name in set(frames):
            cum_c[name] += count
    out = []
    for fn, n in self_c.most_common(k):
        entry = {
            "fn": fn,
            "self_samples": n,
            "self_share": round(n / total, 4) if total else 0.0,
            "cum_samples": cum_c[fn],
        }
        if interval_ms:
            entry["self_ms_est"] = round(n * interval_ms, 1)
        out.append(entry)
    return out


def attribute(
    paths: list[str], *, top_k: int = 10, align: bool = True
) -> dict:
    """The joined report: trace edge attribution (ms) + per-edge top
    functions (samples/estimated ms) + sampler/boundary accounts."""
    skipped: list[str] = []
    trace_report = assemble(paths, align=align)
    stages, meta = aggregate(load_profiles(paths, skipped_streams=skipped))
    interval_ms = meta["interval_ms"]
    total_samples = sum(sum(c.values()) for c in stages.values())

    edges: dict[str, dict] = {}
    for edge in EDGES:
        stacks = stages.get(edge, Counter())
        n = sum(stacks.values())
        trace_edge = trace_report["edges"].get(edge)
        edges[edge] = {
            "trace_mean_ms": trace_edge["mean_ms"] if trace_edge else None,
            "trace_p90_ms": trace_edge["p90_ms"] if trace_edge else None,
            "samples": n,
            "sample_share": (
                round(n / total_samples, 4) if total_samples else 0.0
            ),
            "thread_ms_est": round(n * interval_ms, 1) if interval_ms else None,
            "top_functions": top_functions(stacks, interval_ms, top_k),
        }
    other = {}
    for stage_name in sorted(set(stages) - set(EDGES)):
        stacks = stages[stage_name]
        n = sum(stacks.values())
        other[stage_name or "(untagged)"] = {
            "samples": n,
            "sample_share": (
                round(n / total_samples, 4) if total_samples else 0.0
            ),
            "top_functions": top_functions(stacks, interval_ms, top_k),
        }
    return {
        "schema": ATTRIBUTION_SCHEMA,
        "host": host_meta(),
        "streams": trace_report["streams"],
        "skipped_streams": sorted(
            set(skipped) | set(trace_report["skipped_streams"])
        ),
        "rounds": trace_report["rounds"],
        "round_total_ms": trace_report["total_ms"],
        "top_cost_centers": trace_report["top_cost_centers"],
        "sampler": {k: v for k, v in meta.items() if k != "ctypes"},
        "ctypes": meta["ctypes"],
        "edges": edges,
        "other_stages": other,
    }


# -- speedscope export -------------------------------------------------------


def to_speedscope(
    stages: dict[str, Counter], interval_ms: float | None, name: str
) -> dict:
    """Speedscope file: one *sampled* profile per stage over a shared
    frame table (https://www.speedscope.app/file-format-schema.json).
    Weights are milliseconds (samples x interval)."""
    frame_index: dict[str, int] = {}
    frames: list[dict] = []

    def idx(fn: str) -> int:
        i = frame_index.get(fn)
        if i is None:
            i = frame_index[fn] = len(frames)
            frames.append({"name": fn})
        return i

    weight = interval_ms or 1.0
    profiles = []
    for stage_name in sorted(stages, key=lambda s: -sum(stages[s].values())):
        stacks = stages[stage_name]
        samples = []
        weights = []
        total = 0.0
        for folded, count in sorted(stacks.items()):
            samples.append([idx(fn) for fn in folded.split(";")])
            w = count * weight
            weights.append(w)
            total += w
        profiles.append(
            {
                "type": "sampled",
                "name": stage_name or "(untagged)",
                "unit": "milliseconds",
                "startValue": 0,
                "endValue": round(total, 3),
                "samples": samples,
                "weights": weights,
            }
        )
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "name": name,
        "exporter": "hotstuff_tpu profile_assemble",
        "shared": {"frames": frames},
        "profiles": profiles,
    }


def _human(report: dict, top: int = 3) -> str:
    lines = [
        f"{report['rounds']} rounds, {report['sampler']['samples']} samples "
        f"@ {report['sampler']['interval_ms']} ms, "
        f"GIL delay {report['sampler']['gil_delay_ms']} ms"
        + (
            f", {len(report['skipped_streams'])} stream(s) skipped"
            if report["skipped_streams"]
            else ""
        ),
        f"{'edge':<14} {'trace ms':>9} {'thr ms':>9}  top functions by self time",
    ]
    for edge, e in sorted(
        report["edges"].items(), key=lambda kv: -(kv[1]["samples"])
    ):
        tops = ", ".join(
            f"{f['fn'].rsplit(':', 1)[-1]} {f['self_share']:.0%}"
            for f in e["top_functions"][:top]
        )
        lines.append(
            f"{edge:<14} {e['trace_mean_ms'] if e['trace_mean_ms'] is not None else '-':>9} "
            f"{e['thread_ms_est'] if e['thread_ms_est'] is not None else '-':>9}  {tops}"
        )
    if report["ctypes"]:
        worst = next(iter(report["ctypes"].items()))
        lines.append(
            f"ctypes boundary: {len(report['ctypes'])} entry points; "
            f"heaviest {worst[0]} ({worst[1]['calls']} calls, "
            f"{worst[1]['ms']} ms)"
        )
    return "\n".join(lines)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "paths", nargs="+",
        help="telemetry stream files, or directories containing "
        "telemetry-*.jsonl",
    )
    p.add_argument("--committee", type=int, help="committee size (recorded)")
    p.add_argument("--top", type=int, default=10, help="functions per edge")
    p.add_argument("--no-align", action="store_true")
    p.add_argument("--output", help="write the JSON attribution report here")
    p.add_argument(
        "--speedscope", metavar="PATH",
        help="also write a speedscope flamegraph file (one profile per stage)",
    )
    args = p.parse_args()

    paths: list[str] = []
    for path in args.paths:
        if os.path.isdir(path):
            paths.extend(
                sorted(glob.glob(os.path.join(path, "telemetry-*.jsonl")))
            )
        else:
            paths.append(path)
    if not paths:
        print("no telemetry streams found", file=sys.stderr)
        sys.exit(2)

    report = attribute(paths, top_k=args.top, align=not args.no_align)
    if args.committee is not None:
        report["committee"] = args.committee
    print(_human(report))
    if args.output:
        os.makedirs(
            os.path.dirname(os.path.abspath(args.output)), exist_ok=True
        )
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"attribution report written to {args.output}")
    if args.speedscope:
        stages, meta = aggregate(load_profiles(paths))
        scope = to_speedscope(
            stages, meta["interval_ms"],
            os.path.basename(args.speedscope),
        )
        os.makedirs(
            os.path.dirname(os.path.abspath(args.speedscope)), exist_ok=True
        )
        with open(args.speedscope, "w") as f:
            json.dump(scope, f)
            f.write("\n")
        print(f"speedscope profile written to {args.speedscope}")
    if not report["sampler"]["samples"]:
        print("no profile records were found in the streams", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
