"""Testbed settings (reference ``benchmark/benchmark/settings.py``):
same ``settings.json`` schema — testbed name, ssh key, port layout
(consensus/mempool/front), repo, instance type, AWS regions."""

from __future__ import annotations

import json


class SettingsError(Exception):
    pass


class Settings:
    def __init__(
        self,
        testbed: str,
        key_name: str,
        key_path: str,
        base_port: int,
        repo_name: str,
        repo_url: str,
        branch: str,
        instance_type: str,
        aws_regions: list[str],
    ) -> None:
        self.testbed = testbed
        self.key_name = key_name
        self.key_path = key_path
        self.base_port = base_port
        self.repo_name = repo_name
        self.repo_url = repo_url
        self.branch = branch
        self.instance_type = instance_type
        self.aws_regions = aws_regions

    @property
    def consensus_port(self) -> int:
        return self.base_port

    @property
    def mempool_port(self) -> int:
        return self.base_port + 1_000

    @property
    def front_port(self) -> int:
        return self.base_port + 2_000

    @classmethod
    def load(cls, filename: str = "settings.json") -> "Settings":
        try:
            with open(filename) as f:
                data = json.load(f)
            return cls(
                testbed=data["testbed"],
                key_name=data["key"]["name"],
                key_path=data["key"]["path"],
                base_port=int(data["ports"]["consensus"]),
                repo_name=data["repo"]["name"],
                repo_url=data["repo"]["url"],
                branch=data["repo"]["branch"],
                instance_type=data["instances"]["type"],
                aws_regions=list(data["instances"]["regions"]),
            )
        except (OSError, KeyError, ValueError) as e:
            raise SettingsError(f"failed to load settings '{filename}': {e}") from e
