"""Testbed settings (reference ``benchmark/benchmark/settings.py``):
same ``settings.json`` schema — testbed name, ssh key, port layout
(consensus/mempool/front), repo, instance type, AWS regions."""

from __future__ import annotations

import json


class SettingsError(Exception):
    pass


class Settings:
    def __init__(
        self,
        testbed: str,
        key_name: str,
        key_path: str,
        consensus_port: int,
        mempool_port: int,
        front_port: int,
        repo_name: str,
        repo_url: str,
        branch: str,
        instance_type: str,
        aws_regions: list[str],
    ) -> None:
        self.testbed = testbed
        self.key_name = key_name
        self.key_path = key_path
        self.consensus_port = consensus_port
        self.mempool_port = mempool_port
        self.front_port = front_port
        self.repo_name = repo_name
        self.repo_url = repo_url
        self.branch = branch
        self.instance_type = instance_type
        self.aws_regions = aws_regions

    @classmethod
    def load(cls, filename: str = "settings.json") -> "Settings":
        try:
            with open(filename) as f:
                data = json.load(f)
            return cls(
                testbed=data["testbed"],
                key_name=data["key"]["name"],
                key_path=data["key"]["path"],
                consensus_port=int(data["ports"]["consensus"]),
                mempool_port=int(data["ports"]["mempool"]),
                front_port=int(data["ports"]["front"]),
                repo_name=data["repo"]["name"],
                repo_url=data["repo"]["url"],
                branch=data["repo"]["branch"],
                instance_type=data["instances"]["type"],
                aws_regions=list(data["instances"]["regions"]),
            )
        except (OSError, KeyError, ValueError) as e:
            raise SettingsError(f"failed to load settings '{filename}': {e}") from e
