"""Local benchmark: boot a committee of real node processes plus load-
generating clients on localhost, then parse their logs into the SUMMARY
block (reference ``benchmark/benchmark/local.py``).

Differences from the reference: processes are supervised directly (no tmux)
and there is no cargo build step (Python nodes launch as subprocesses with
stderr redirected to per-role log files, like the reference's
``local.py:25-28``).
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import sys
import time

from hotstuff_tpu.consensus import Authority as CAuth
from hotstuff_tpu.consensus import Committee as CCommittee
from hotstuff_tpu.consensus import Parameters as CParams
from hotstuff_tpu.mempool import Authority as MAuth
from hotstuff_tpu.mempool import Committee as MCommittee
from hotstuff_tpu.mempool import Parameters as MParams
from hotstuff_tpu.mempool import WorkerEntry
from hotstuff_tpu.node.config import Committee, Parameters, Secret

from .logs import LogParser

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class BenchError(Exception):
    pass


class LocalBench:
    """Reference flow (``local.py:37-121``): clean state, generate N key
    files + committee json, start each client & node with stderr->logfile,
    sleep for the duration, kill, parse logs."""

    def __init__(
        self,
        nodes: int = 4,
        rate: int = 1_000,
        tx_size: int = 512,
        duration: int = 20,
        faults: int = 0,
        base_port: int = 9000,
        timeout_delay: int = 1_000,
        batch_size: int = 15_000,
        max_batch_delay: int = 10,
        work_dir: str = ".bench",
        crypto_backend: str = "cpu",
        telemetry: bool = False,
        chaos: str | None = None,
        workers: int = 0,
        retention_rounds: int = 0,
        client_extra: list[str] | None = None,
    ) -> None:
        self.nodes = nodes
        self.rate = rate
        self.tx_size = tx_size
        self.duration = duration
        self.faults = faults
        self.base_port = base_port
        self.timeout_delay = timeout_delay
        self.batch_size = batch_size
        self.max_batch_delay = max_batch_delay
        self.work_dir = os.path.abspath(work_dir)
        self.crypto_backend = crypto_backend
        self.telemetry = telemetry
        # Chaos mode: path to a faultline scenario JSON. Partition/link/
        # byzantine events run INSIDE each node process (the env-armed
        # FaultPlane); crash/restart events are enacted HERE by killing
        # and relaunching real node processes. After the run the
        # faultline checker judges the logs; the verdict lands in
        # ``self.chaos_verdict``.
        self.chaos = chaos
        self.chaos_verdict: dict | None = None
        # Conveyor data plane: worker shards per node. Port layout
        # extends the reference blocks — worker w of node i listens on
        # base + (3 + 2w) * n + i (client ingress) and
        # base + (4 + 2w) * n + i (peer port). Clients switch to the
        # sharded bundle generator targeting their node's ingress ports.
        self.workers = workers
        # Lazarus: snapshot/truncate retention depth in rounds (0 =
        # unbounded store, the historic behavior).
        self.retention_rounds = retention_rounds
        # Extra argv appended to every client (e.g. ``--fleet``/
        # ``--coalesce-bytes`` knobs from the fleet/sweep harnesses).
        self.client_extra = list(client_extra or [])
        self._procs: list[subprocess.Popen] = []
        self._node_procs: dict[int, subprocess.Popen] = {}
        self._node_cmds: dict[int, tuple[list, str]] = {}  # i -> (cmd, log)

    def _cleanup(self) -> None:
        # SIGTERM first: nodes flush their final telemetry snapshot +
        # trace tail from the signal handler (telemetry.arm_shutdown_flush)
        # — without this the last interval of every stream was lost.
        # SIGKILL after a short grace bounds the teardown; a node that
        # missed the window just loses its final line (the lenient stream
        # reader tolerates a truncated tail).
        procs = [*self._procs, *self._node_procs.values()]
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    pass
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        self._procs.clear()
        self._node_procs.clear()

    @staticmethod
    def _wait_for_ports(addresses, timeout: float) -> None:
        import socket

        deadline = time.monotonic() + timeout
        for host, port in addresses:
            while True:
                try:
                    with socket.create_connection((host, port), timeout=1):
                        break
                except OSError:
                    if time.monotonic() > deadline:
                        raise BenchError(
                            f"node on {host}:{port} did not come up in time"
                        ) from None
                    time.sleep(0.5)

    def run(self, debug: bool = False) -> LogParser:
        shutil.rmtree(self.work_dir, ignore_errors=True)
        os.makedirs(self.work_dir, exist_ok=True)
        logs_dir = os.path.join(self.work_dir, "logs")
        os.makedirs(logs_dir)

        # Keys + committee (reference port layout: consensus, front, mempool
        # blocks of N ports each, ``config.py:81-90``).
        secrets = [Secret.new() for _ in range(self.nodes)]
        n = self.nodes
        consensus = CCommittee(
            authorities={
                s.name: CAuth(stake=1, address=("127.0.0.1", self.base_port + i))
                for i, s in enumerate(secrets)
            }
        )
        mempool = MCommittee(
            authorities={
                s.name: MAuth(
                    stake=1,
                    transactions_address=("127.0.0.1", self.base_port + n + i),
                    mempool_address=("127.0.0.1", self.base_port + 2 * n + i),
                    workers=[
                        WorkerEntry(
                            transactions_address=(
                                "127.0.0.1",
                                self.base_port + (3 + 2 * w) * n + i,
                            ),
                            worker_address=(
                                "127.0.0.1",
                                self.base_port + (4 + 2 * w) * n + i,
                            ),
                        )
                        for w in range(self.workers)
                    ],
                )
                for i, s in enumerate(secrets)
            }
        )
        committee_file = os.path.join(self.work_dir, "committee.json")
        Committee(consensus, mempool).write(committee_file)
        params_file = os.path.join(self.work_dir, "parameters.json")
        Parameters(
            CParams(
                timeout_delay=self.timeout_delay,
                retention_rounds=self.retention_rounds,
            ),
            MParams(
                batch_size=self.batch_size,
                max_batch_delay=self.max_batch_delay,
                workers=self.workers,
            ),
        ).write(params_file)

        key_files = []
        for i, s in enumerate(secrets):
            kf = os.path.join(self.work_dir, f"node_{i}.json")
            s.write(kf)
            key_files.append(kf)

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["HOTSTUFF_CRYPTO_BACKEND"] = self.crypto_backend
        schedule = None
        if self.chaos:
            from hotstuff_tpu.faultline import Scenario

            scenario = Scenario.load(self.chaos)
            schedule = scenario.compile([f"n{i:03d}" for i in range(n)])
            # Arm every node process's in-process fault plane; telemetry
            # rides along so the faultline.* counters exist in the
            # emitted snapshots.
            env["HOTSTUFF_FAULTLINE"] = os.path.abspath(self.chaos)
            self.telemetry = True
        if self.telemetry:
            # Nodes stream telemetry-<name>.jsonl next to their logs; the
            # SIGTERM-first teardown lets each node's signal handler flush
            # its final snapshot + trace tail. A short interval still
            # bounds the loss for nodes the chaos supervisor SIGKILLs.
            env["HOTSTUFF_TELEMETRY_DIR"] = logs_dir
            env.setdefault("HOTSTUFF_TELEMETRY_INTERVAL", "1")

        booted = self.nodes - self.faults  # faults = don't boot the last f
        try:
            # Boot clients first (they wait for node ports), then nodes
            # (reference ``remote.py:177-219`` order).
            for i in range(booted):
                front = f"127.0.0.1:{self.base_port + n + i}"
                node_addrs = [
                    f"127.0.0.1:{self.base_port + n + j}" for j in range(booted)
                ]
                shard_args = []
                if self.workers:
                    shards = ",".join(
                        f"127.0.0.1:{self.base_port + (3 + 2 * w) * n + i}"
                        for w in range(self.workers)
                    )
                    shard_args = ["--shards", shards]
                log_file = open(os.path.join(logs_dir, f"client-{i}.log"), "w")
                self._procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-m",
                            "hotstuff_tpu.node.client",
                            front,
                            "--size",
                            str(self.tx_size),
                            "--rate",
                            str(self.rate // booted),
                            "--timeout",
                            str(self.timeout_delay),
                            *shard_args,
                            *self.client_extra,
                            "--nodes",
                            *node_addrs,
                        ],
                        stderr=log_file,
                        env=env,
                        cwd=REPO_ROOT,
                    )
                )
            for i in range(booted):
                log_path = os.path.join(logs_dir, f"node-{i}.log")
                cmd = [
                    sys.executable,
                    "-m",
                    "hotstuff_tpu.node",
                    # default verbosity is INFO; -v adds DEBUG, which
                    # would skew the measured window.
                    *(["-v"] if debug else []),
                    "run",
                    "--keys",
                    key_files[i],
                    "--committee",
                    committee_file,
                    "--store",
                    os.path.join(self.work_dir, f"db_{i}"),
                    "--parameters",
                    params_file,
                ]
                self._node_cmds[i] = (cmd, log_path)
                self._node_procs[i] = subprocess.Popen(
                    cmd,
                    stderr=open(log_path, "a"),
                    env=env,
                    cwd=REPO_ROOT,
                )

            # Python interpreter startup is expensive (~2s CPU each on this
            # class of machine) and all processes compete for cores: don't
            # start the measurement clock until every node actually listens.
            self._wait_for_ports(
                [("127.0.0.1", self.base_port + i) for i in range(booted)],
                timeout=30 * booted,
            )
            time.sleep(2 * self.timeout_delay / 1000)
            if schedule is None:
                time.sleep(self.duration)
            else:
                heal_counts = self._supervise_chaos(schedule, env)
        finally:
            self._cleanup()

        parser = LogParser.process(logs_dir, faults=self.faults)
        if schedule is not None:
            self.chaos_verdict = self._judge_chaos(
                logs_dir, schedule, heal_counts
            )
        return parser

    # -- chaos supervision ---------------------------------------------------

    def _restart_node(self, i: int, env: dict) -> None:
        cmd, log_path = self._node_cmds[i]
        self._node_procs[i] = subprocess.Popen(
            cmd, stderr=open(log_path, "a"), env=env, cwd=REPO_ROOT
        )

    @staticmethod
    def _commit_lines(logs_dir: str, i: int) -> list[tuple[int, str]]:
        import re

        path = os.path.join(logs_dir, f"node-{i}.log")
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            return []
        return [
            (int(r), d)
            for r, d in re.findall(r"FaultlineCommit r=(\d+) d=([0-9a-f]+)", text)
        ]

    def _supervise_chaos(self, schedule, env: dict) -> dict[int, int]:
        """Enact the schedule's crash/restart events against real node
        processes (partition/link/byzantine run inside the nodes via the
        env-armed planes) and snapshot per-node commit-line counts at the
        last heal — the liveness baseline ``_judge_chaos`` compares
        against. Virtual t=0 is the moment the committee finished
        booting, matching each node plane's process-boot anchor to within
        interpreter-startup skew."""
        logs_dir = os.path.join(self.work_dir, "logs")
        actions = sorted(
            (
                (e.at, e.kind, e.params["node"])
                for e in schedule.events
                if e.kind in ("crash", "restart")
            ),
        )
        heal_t = schedule.last_heal_time()
        heal_counts: dict[int, int] = {}
        t0 = time.monotonic()
        while True:
            elapsed = time.monotonic() - t0
            while actions and actions[0][0] <= elapsed:
                _, kind, node = actions.pop(0)
                i = int(node.lstrip("n"))
                proc = self._node_procs.get(i)
                if kind == "crash":
                    if proc is not None and proc.poll() is None:
                        proc.send_signal(signal.SIGKILL)
                        print(f"chaos: crashed node {i} at t={elapsed:.1f}s")
                elif proc is None or proc.poll() is not None:
                    self._restart_node(i, env)
                    print(f"chaos: restarted node {i} at t={elapsed:.1f}s")
            if not heal_counts and elapsed >= heal_t:
                heal_counts = {
                    i: len(self._commit_lines(logs_dir, i))
                    for i in range(self.nodes - self.faults)
                }
            if elapsed >= self.duration:
                # Recovery tail: a restarted node may still be walking a
                # long sync catch-up when the measurement window closes.
                # Give the committee a bounded extra window to prove
                # post-heal commit growth before the SIGKILL teardown —
                # the same grace the in-process harness grants.
                recovered = heal_counts and all(
                    len(self._commit_lines(logs_dir, i)) >= base + 3
                    for i, base in heal_counts.items()
                    if self._node_procs.get(i) is not None
                    and self._node_procs[i].poll() is None
                )
                if recovered or elapsed >= self.duration + 45:
                    break
            time.sleep(0.2)
        return heal_counts

    def _judge_chaos(self, logs_dir: str, schedule, heal_counts) -> dict:
        """Feed the scraped commit streams to the faultline checker.
        Per-line virtual times aren't in the logs; what liveness needs is
        only pre/post-heal attribution, which the heal-time count
        snapshot gives exactly."""
        from hotstuff_tpu.faultline import CommitRecord, check

        heal_t = schedule.last_heal_time()
        commits = {}
        for i in range(self.nodes - self.faults):
            lines = self._commit_lines(logs_dir, i)
            cut = heal_counts.get(i, len(lines))
            commits[f"n{i:03d}"] = [
                CommitRecord(r, bytes.fromhex(d), 0.0 if k < cut else heal_t + 1.0)
                for k, (r, d) in enumerate(lines)
            ]
        verdict = check(schedule, commits)
        if self.workers:
            verdict["availability"] = self._audit_availability(
                logs_dir, schedule
            )
        return verdict

    def _audit_availability(self, logs_dir: str, schedule) -> dict:
        """The Conveyor invariant, audited end to end: every batch digest
        any node COMMITTED must resolve from at least f+1 honest nodes'
        on-disk stores after the run — the availability the certificate
        promised at ordering time, checked against reality."""
        import asyncio
        import base64
        import re

        from hotstuff_tpu.faultline import check_availability
        from hotstuff_tpu.store import Store

        booted = self.nodes - self.faults
        committed: set[bytes] = set()
        for i in range(booted):
            try:
                with open(os.path.join(logs_dir, f"node-{i}.log")) as f:
                    text = f.read()
            except OSError:
                continue
            for b64 in re.findall(r"Committed B\d+ -> ([^ \n]+=)", text):
                try:
                    raw = base64.standard_b64decode(b64)
                except ValueError:
                    continue
                if len(raw) == 32:
                    committed.add(raw)

        holders: dict[str, set[str]] = {d.hex(): set() for d in committed}

        async def scan() -> None:
            for i in range(booted):
                path = os.path.join(self.work_dir, f"db_{i}")
                if not os.path.isdir(path):
                    continue
                store = Store(path)
                try:
                    for d in committed:
                        if await store.read(d) is not None:
                            holders[d.hex()].add(f"n{i:03d}")
                finally:
                    store.close()

        asyncio.run(scan())
        return check_availability(schedule, set(holders), holders)
