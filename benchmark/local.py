"""Local benchmark: boot a committee of real node processes plus load-
generating clients on localhost, then parse their logs into the SUMMARY
block (reference ``benchmark/benchmark/local.py``).

Differences from the reference: processes are supervised directly (no tmux)
and there is no cargo build step (Python nodes launch as subprocesses with
stderr redirected to per-role log files, like the reference's
``local.py:25-28``).
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import sys
import time

from hotstuff_tpu.consensus import Authority as CAuth
from hotstuff_tpu.consensus import Committee as CCommittee
from hotstuff_tpu.consensus import Parameters as CParams
from hotstuff_tpu.mempool import Authority as MAuth
from hotstuff_tpu.mempool import Committee as MCommittee
from hotstuff_tpu.mempool import Parameters as MParams
from hotstuff_tpu.node.config import Committee, Parameters, Secret

from .logs import LogParser

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class BenchError(Exception):
    pass


class LocalBench:
    """Reference flow (``local.py:37-121``): clean state, generate N key
    files + committee json, start each client & node with stderr->logfile,
    sleep for the duration, kill, parse logs."""

    def __init__(
        self,
        nodes: int = 4,
        rate: int = 1_000,
        tx_size: int = 512,
        duration: int = 20,
        faults: int = 0,
        base_port: int = 9000,
        timeout_delay: int = 1_000,
        batch_size: int = 15_000,
        max_batch_delay: int = 10,
        work_dir: str = ".bench",
        crypto_backend: str = "cpu",
        telemetry: bool = False,
    ) -> None:
        self.nodes = nodes
        self.rate = rate
        self.tx_size = tx_size
        self.duration = duration
        self.faults = faults
        self.base_port = base_port
        self.timeout_delay = timeout_delay
        self.batch_size = batch_size
        self.max_batch_delay = max_batch_delay
        self.work_dir = os.path.abspath(work_dir)
        self.crypto_backend = crypto_backend
        self.telemetry = telemetry
        self._procs: list[subprocess.Popen] = []

    def _cleanup(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        self._procs.clear()

    @staticmethod
    def _wait_for_ports(addresses, timeout: float) -> None:
        import socket

        deadline = time.monotonic() + timeout
        for host, port in addresses:
            while True:
                try:
                    with socket.create_connection((host, port), timeout=1):
                        break
                except OSError:
                    if time.monotonic() > deadline:
                        raise BenchError(
                            f"node on {host}:{port} did not come up in time"
                        ) from None
                    time.sleep(0.5)

    def run(self, debug: bool = False) -> LogParser:
        shutil.rmtree(self.work_dir, ignore_errors=True)
        os.makedirs(self.work_dir, exist_ok=True)
        logs_dir = os.path.join(self.work_dir, "logs")
        os.makedirs(logs_dir)

        # Keys + committee (reference port layout: consensus, front, mempool
        # blocks of N ports each, ``config.py:81-90``).
        secrets = [Secret.new() for _ in range(self.nodes)]
        n = self.nodes
        consensus = CCommittee(
            authorities={
                s.name: CAuth(stake=1, address=("127.0.0.1", self.base_port + i))
                for i, s in enumerate(secrets)
            }
        )
        mempool = MCommittee(
            authorities={
                s.name: MAuth(
                    stake=1,
                    transactions_address=("127.0.0.1", self.base_port + n + i),
                    mempool_address=("127.0.0.1", self.base_port + 2 * n + i),
                )
                for i, s in enumerate(secrets)
            }
        )
        committee_file = os.path.join(self.work_dir, "committee.json")
        Committee(consensus, mempool).write(committee_file)
        params_file = os.path.join(self.work_dir, "parameters.json")
        Parameters(
            CParams(timeout_delay=self.timeout_delay),
            MParams(batch_size=self.batch_size, max_batch_delay=self.max_batch_delay),
        ).write(params_file)

        key_files = []
        for i, s in enumerate(secrets):
            kf = os.path.join(self.work_dir, f"node_{i}.json")
            s.write(kf)
            key_files.append(kf)

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["HOTSTUFF_CRYPTO_BACKEND"] = self.crypto_backend
        if self.telemetry:
            # Nodes stream telemetry-<name>.jsonl next to their logs. A
            # short interval keeps the stream's tail close to the SIGKILL
            # teardown (nodes never get to write a final snapshot here).
            env["HOTSTUFF_TELEMETRY_DIR"] = logs_dir
            env.setdefault("HOTSTUFF_TELEMETRY_INTERVAL", "1")

        booted = self.nodes - self.faults  # faults = don't boot the last f
        try:
            # Boot clients first (they wait for node ports), then nodes
            # (reference ``remote.py:177-219`` order).
            for i in range(booted):
                front = f"127.0.0.1:{self.base_port + n + i}"
                node_addrs = [
                    f"127.0.0.1:{self.base_port + n + j}" for j in range(booted)
                ]
                log_file = open(os.path.join(logs_dir, f"client-{i}.log"), "w")
                self._procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-m",
                            "hotstuff_tpu.node.client",
                            front,
                            "--size",
                            str(self.tx_size),
                            "--rate",
                            str(self.rate // booted),
                            "--timeout",
                            str(self.timeout_delay),
                            "--nodes",
                            *node_addrs,
                        ],
                        stderr=log_file,
                        env=env,
                        cwd=REPO_ROOT,
                    )
                )
            for i in range(booted):
                log_file = open(os.path.join(logs_dir, f"node-{i}.log"), "w")
                self._procs.append(
                    subprocess.Popen(
                        [
                            sys.executable,
                            "-m",
                            "hotstuff_tpu.node",
                            # default verbosity is INFO; -v adds DEBUG, which
                            # would skew the measured window.
                            *(["-v"] if debug else []),
                            "run",
                            "--keys",
                            key_files[i],
                            "--committee",
                            committee_file,
                            "--store",
                            os.path.join(self.work_dir, f"db_{i}"),
                            "--parameters",
                            params_file,
                        ],
                        stderr=log_file,
                        env=env,
                        cwd=REPO_ROOT,
                    )
                )

            # Python interpreter startup is expensive (~2s CPU each on this
            # class of machine) and all processes compete for cores: don't
            # start the measurement clock until every node actually listens.
            self._wait_for_ports(
                [("127.0.0.1", self.base_port + i) for i in range(booted)],
                timeout=30 * booted,
            )
            time.sleep(2 * self.timeout_delay / 1000)
            time.sleep(self.duration)
        finally:
            self._cleanup()

        return LogParser.process(logs_dir, faults=self.faults)
