"""Detector ground-truth bench: run seeded faultline schedules with the
watchtower attached and score precision / recall / time-to-detection —
the fault plan IS the label set.

    python -m benchmark.detector_bench --seeds 3,7 --controls 2 \
        --nodes 4 --duration 24 --output results --gate

Each seeded run boots the in-process faultline committee
(``hotstuff_tpu.faultline.harness``) with telemetry streaming to a
temp directory, attaches a live :class:`benchmark.watchtower
.DirectoryWatch` (tail-follow over the stream as it is written — the
exact production ingest path, not a post-hoc batch), arms alert-
triggered capture, and afterwards joins the fired alerts against the
compiled fault schedule:

- an **incident** is one faulted (peer, kind) interval from the
  schedule: a crash until its restart, a byzantine behavior while
  armed, a partition's minority members while cut, a lossy link's
  source while degraded;
- an alert is a **true positive** when an accused peer has an incident
  whose interval (extended by ``--slack`` seconds: post-heal lag and
  withholding are real incidents that OUTLIVE their injection — the
  committed chaos3/chaos7 findings are exactly that) covers the alert;
- **time-to-detection** is first-matching-alert wall time minus the
  incident's activation wall time (``FaultPlane.started_wall`` anchors
  virtual time);
- **controls** are fault-free schedules: every alert on a control is a
  false positive, and the gate requires zero.

``--gate`` additionally asserts the two committed incident signatures:
chaos-seed-3's crash victim (the "laggard commits nothing" finding)
and chaos-seed-7's silent leader (the "withholding" finding) must each
be detected with the correct peer accused. The verdict artifact
(``results/watchtower-detect-*.json``) is the committed evidence.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402
from benchmark.watchtower import DirectoryWatch  # noqa: E402

BENCH_SCHEMA = "hotstuff-watchtower-detect-v1"

#: detectors considered compatible with each fault kind when judging
#: recall (any-detector accusation still counts as a true positive for
#: precision — a laggard alert on a crashed node is correct evidence).
EXPECTED_DETECTORS = {
    "crash": ("laggard", "silent_voter", "partitioned_clique"),
    # partitioned_clique is expected for byzantine too: a silent leader
    # (or vote-withholding actor) stops appearing in anyone's committing
    # set, which the clique detector reports as a singleton component —
    # correct peer, correct window, same rationale as laggard.
    "byzantine": (
        "grinding_leader", "silent_voter", "equivocation", "laggard",
        "partitioned_clique",
    ),
    # grinding_leader is expected for partition for the same reason it
    # is for link: an isolated node is alive-but-unseen — its own
    # stream keeps reporting timeouts while no proposal of its ever
    # reaches an observer, which is exactly the silent-leader shape.
    "partition": (
        "partitioned_clique", "silent_voter", "laggard", "grinding_leader",
    ),
    "link": (
        "grinding_leader", "partitioned_clique", "silent_voter", "laggard",
    ),
}


def _incidents(schedule, duration_s: float) -> list[dict]:
    """Flatten the compiled schedule into labeled (peer, kind) intervals
    in VIRTUAL time. Crash intervals run to the node's restart (or the
    scenario end); partitions label every minority-group member."""
    restarts: dict[str, list[float]] = {}
    for e in schedule.events:
        if e.kind == "restart":
            restarts.setdefault(e.params["node"], []).append(e.at)
    out: list[dict] = []
    for e in schedule.events:
        end = e.until if e.until is not None else duration_s
        if e.kind == "crash":
            node = e.params["node"]
            later = [t for t in restarts.get(node, []) if t >= e.at]
            out.append(
                {
                    "peer": node,
                    "kind": "crash",
                    "t": e.at,
                    "until": min(later) if later else duration_s,
                }
            )
        elif e.kind == "byzantine":
            out.append(
                {
                    "peer": e.params["node"],
                    "kind": "byzantine",
                    "behavior": e.params["behavior"],
                    "t": e.at,
                    "until": end,
                }
            )
        elif e.kind == "partition":
            groups = sorted(e.params["groups"], key=len, reverse=True)
            for group in groups[1:]:
                for node in group:
                    out.append(
                        {
                            "peer": node,
                            "kind": "partition",
                            "t": e.at,
                            "until": end,
                        }
                    )
        elif e.kind == "link":
            src = e.params.get("src")
            if src and src != "*":
                out.append(
                    {"peer": src, "kind": "link", "t": e.at, "until": end}
                )
    out.sort(key=lambda i: (i["t"], i["peer"]))
    return out


async def _drive(run, stream_path: str) -> dict:
    """Execute the scenario with a telemetry emitter streaming the whole
    committee's snapshots + trace events (the watchtower's food)."""
    from hotstuff_tpu import telemetry

    emitter = telemetry.TelemetryEmitter(
        telemetry.get_registry(),
        stream_path,
        node="harness",
        interval_s=0.5,
        trace=telemetry.trace_buffer(),
    )
    emitter.emit()
    emitter.spawn()
    try:
        return await run.execute()
    finally:
        await emitter.shutdown()


def run_labeled(
    scenario,
    nodes: int,
    *,
    base_port: int,
    timeout_delay: int,
    config=None,
    capture_dir: str | None = None,
    slack_s: float = 45.0,
    recovery_timeout_s: float = 30.0,
) -> dict:
    """One seeded run end to end: boot, watch live, score vs labels."""
    from hotstuff_tpu import telemetry
    from hotstuff_tpu.faultline.harness import ScenarioRun
    from hotstuff_tpu.telemetry.watchtower import AlertCapture

    telemetry.reset_for_tests()
    telemetry.enable()
    work = tempfile.mkdtemp(prefix="hotstuff_detector_bench_")
    stream = os.path.join(work, "telemetry-harness.jsonl")
    try:
        run = ScenarioRun(
            scenario,
            nodes,
            base_port=base_port,
            timeout_delay=timeout_delay,
            recovery_timeout_s=recovery_timeout_s,
        )
        alias = {repr(eng.pk): eng.name for eng in run.engines}
        capture = None
        if capture_dir:
            capture = AlertCapture(
                capture_dir,
                trace=telemetry.trace_buffer(),
                registry=telemetry.get_registry(),
                profile_s=1.0,
            )
        watch = DirectoryWatch(
            work,
            config=config,
            alias=alias,
            on_alert=capture,
            alerts_path=os.path.join(work, "watchtower-alerts.jsonl"),
        )
        if capture is not None:
            capture.watchtower = watch.watch
        watch.start()
        t_begin = time.time()
        try:
            result = asyncio.run(_drive(run, stream))
        finally:
            watch.stop()
        anchor = run.plane.started_wall or t_begin
        alerts = watch.alerts()
        incidents = _incidents(run.schedule, scenario.duration_s)
        for inc in incidents:
            inc["t_wall"] = anchor + inc["t"]
            inc["until_wall"] = anchor + inc["until"]

        matched_alerts = 0
        for alert in alerts:
            alert["matches"] = [
                i
                for i, inc in enumerate(incidents)
                if inc["peer"] in alert["accused"]
                and inc["t_wall"] - 1.0 <= alert["ts"] <= inc["until_wall"] + slack_s
            ]
            if alert["matches"]:
                matched_alerts += 1
        for i, inc in enumerate(incidents):
            hits = [
                a
                for a in alerts
                if i in a["matches"]
                and a["detector"] in EXPECTED_DETECTORS.get(inc["kind"], ())
            ]
            inc["detected"] = bool(hits)
            if hits:
                first = min(hits, key=lambda a: a["ts"])
                inc["detected_by"] = first["detector"]
                inc["ttd_s"] = round(first["ts"] - inc["t_wall"], 2)

        per_detector: dict[str, dict] = {}
        for alert in alerts:
            d = per_detector.setdefault(
                alert["detector"], {"alerts": 0, "true_positive": 0}
            )
            d["alerts"] += 1
            d["true_positive"] += 1 if alert["matches"] else 0
        for d in per_detector.values():
            d["precision"] = (
                round(d["true_positive"] / d["alerts"], 3) if d["alerts"] else None
            )

        verdict = result["verdict"]
        return {
            "scenario": scenario.name,
            "seed": scenario.seed,
            "nodes": nodes,
            "duration_s": scenario.duration_s,
            "checker": {
                "safety_ok": verdict["safety"]["ok"],
                "recovered": verdict["liveness"]["recovered"],
            },
            "incidents": incidents,
            "alerts": [
                {k: v for k, v in a.items() if k != "matches"}
                | {"matched": bool(a["matches"])}
                for a in alerts
            ],
            "detectors": per_detector,
            "recall": (
                round(
                    sum(1 for i in incidents if i["detected"]) / len(incidents),
                    3,
                )
                if incidents
                else None
            ),
            "precision": (
                round(matched_alerts / len(alerts), 3) if alerts else None
            ),
            "scoreboard": watch.scoreboard(),
            "stream_stats": watch.stats(),
            "captures": capture.paths if capture is not None else [],
        }
    finally:
        telemetry.reset_for_tests()
        shutil.rmtree(work, ignore_errors=True)


def main() -> None:
    from hotstuff_tpu.faultline import Scenario, chaos_scenario
    from hotstuff_tpu.telemetry.watchtower import (
        DETECTOR_CATALOG_VERSION,
        WatchtowerConfig,
    )

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--seeds", default="3,7",
        help="comma-separated chaos seeds to run as labeled storms",
    )
    p.add_argument(
        "--controls", type=int, default=1,
        help="number of fault-free control runs (zero-alert gate)",
    )
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument(
        "--duration", type=float, default=24.0,
        help="scenario virtual duration (s)",
    )
    p.add_argument("--timeout", type=int, default=1_000, help="consensus ms")
    p.add_argument("--base-port", type=int, default=23000)
    p.add_argument(
        "--slack", type=float, default=45.0,
        help="post-interval seconds an incident's effects may outlive its "
        "injection (post-heal laggards/grinds are real incidents)",
    )
    p.add_argument("--config", help="JSON WatchtowerConfig overrides")
    p.add_argument(
        "--capture-dir",
        help="keep alert-triggered captures here (default: discarded "
        "with the temp workdir)",
    )
    p.add_argument("--output", help="directory for the verdict artifact")
    p.add_argument(
        "--gate", action="store_true",
        help="exit nonzero unless the chaos-3 and chaos-7 incident "
        "signatures are detected with the correct peers and the "
        "controls fire zero alerts",
    )
    args = p.parse_args()

    config = None
    if args.config:
        with open(args.config) as f:
            config = WatchtowerConfig.from_dict(json.load(f))

    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    runs: list[dict] = []
    port = args.base_port
    for seed in seeds:
        scenario = chaos_scenario(seed, duration_s=args.duration)
        print(f"== chaos seed {seed} ({args.duration:.0f}s, "
              f"n={args.nodes}) ==", flush=True)
        runs.append(
            run_labeled(
                scenario,
                args.nodes,
                base_port=port,
                timeout_delay=args.timeout,
                config=config,
                capture_dir=args.capture_dir,
                slack_s=args.slack,
            )
        )
        port += args.nodes + 16
        r = runs[-1]
        labels = [i["kind"] + ":" + i["peer"] for i in r["incidents"]]
        print(
            f"   recall={r['recall']} precision={r['precision']} "
            f"alerts={len(r['alerts'])} incidents={labels}",
            flush=True,
        )
    controls: list[dict] = []
    for i in range(args.controls):
        scenario = Scenario(
            name=f"control-{i}",
            seed=1_000 + i,
            duration_s=min(args.duration, 15.0),
            events=[],
        )
        print(f"== control {i} (fault-free) ==", flush=True)
        controls.append(
            run_labeled(
                scenario,
                args.nodes,
                base_port=port,
                timeout_delay=args.timeout,
                config=config,
                slack_s=args.slack,
                recovery_timeout_s=10.0,
            )
        )
        port += args.nodes + 16
        print(f"   alerts={len(controls[-1]['alerts'])}", flush=True)

    # -- gate ----------------------------------------------------------------
    problems: list[str] = []
    for c in controls:
        if c["alerts"]:
            problems.append(
                f"control {c['scenario']} fired "
                f"{len(c['alerts'])} alert(s) — false positives"
            )
    by_seed = {r["seed"]: r for r in runs}
    signatures = {
        # The two committed incident signatures: chaos-seed-3's crash
        # victim goes dark / lags (soak-slo-n4-60s-chaos3.json), chaos-
        # seed-7's silent leader grinds the committee
        # (soak-slo-n4-60s-chaos7.json). Peers per the compiled n=4
        # schedules (policy.py is seed-deterministic).
        3: ("n000", ("laggard", "silent_voter", "partitioned_clique")),
        7: ("n003", ("grinding_leader", "silent_voter", "equivocation")),
    }
    for seed, (peer, detectors) in signatures.items():
        r = by_seed.get(seed)
        if r is None:
            continue
        hit = [
            a
            for a in r["alerts"]
            if peer in a["accused"] and a["detector"] in detectors
        ]
        if not hit:
            problems.append(
                f"seed {seed}: expected an alert accusing {peer} from "
                f"{detectors}, got "
                f"{[(a['detector'], a['accused']) for a in r['alerts']]}"
            )

    effective_config = config or WatchtowerConfig()
    report = {
        "schema": BENCH_SCHEMA,
        "host": host_meta(),
        "ok": not problems,
        "detector_catalog": DETECTOR_CATALOG_VERSION,
        "config": {
            "nodes": args.nodes,
            "duration_s": args.duration,
            "timeout_ms": args.timeout,
            "slack_s": args.slack,
            "watchtower": effective_config.__dict__,
            "watchtower_hash": effective_config.fingerprint(),
        },
        "runs": runs,
        "controls": controls,
        "problems": problems,
    }
    print(json.dumps(
        {k: v for k, v in report.items() if k not in ("runs", "controls")},
        indent=2, sort_keys=True,
    ))
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        tag = "-".join(str(s) for s in seeds)
        path = os.path.join(
            args.output,
            f"watchtower-detect-n{args.nodes}-seeds{tag}.json",
        )
        with open(path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"report written to {path}")
    if args.gate and problems:
        print(f"FAIL: {problems}", file=sys.stderr)
        sys.exit(1)
    print("detector bench " + ("PASS" if not problems else "(problems noted)"))


if __name__ == "__main__":
    main()
