"""Network-namespace testbed: real multi-host benchmarking on one machine.

The reference proves its remote flow on AWS (``benchmark/benchmark/
remote.py`` + boto3); this environment has neither ssh nor cloud access,
so the multi-host flow runs against kernel network namespaces instead:
every "host" gets its own network stack (netns) with an IP on a shared
bridge, its own home directory with its own git clone of the repo, and
its own node/client processes. Everything the ssh flow exercises is real
here — TCP between distinct stacks over veth/bridge, process boot by
command, log download, crash-fault host skipping — except the transport
used to reach the host (``ip netns exec`` instead of ssh) and the
underlying filesystem (shared, so "upload" is a copy).

Topology: bridge ``hsbr0`` at 10.99.0.254/24; host i = netns ``hs<i>``
with eth0 = 10.99.0.<i>/24. Requires root (this testbed runs as root).

    python -m benchmark.netns --hosts 4 --rate 1000 --duration 20
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BRIDGE = "hsbr0"
SUBNET = "10.99.0"
WORK_ROOT = "/tmp/hs-netns-hosts"


def _run(cmd: list[str], check: bool = True, **kw):
    return subprocess.run(cmd, check=check, capture_output=True, text=True, **kw)


def host_ip(i: int) -> str:
    return f"{SUBNET}.{i + 1}"


def ns_name(ip: str) -> str:
    return "hs" + ip.rsplit(".", 1)[1]


def setup(n: int) -> list[str]:
    """Create the bridge and n namespaces; returns their IPs."""
    teardown()
    _run(["ip", "link", "add", BRIDGE, "type", "bridge"])
    _run(["ip", "addr", "add", f"{SUBNET}.254/24", "dev", BRIDGE])
    _run(["ip", "link", "set", BRIDGE, "up"])
    hosts = []
    for i in range(n):
        ip = host_ip(i)
        ns = ns_name(ip)
        veth = f"hsv{i}"
        _run(["ip", "netns", "add", ns])
        _run(
            ["ip", "link", "add", veth, "type", "veth", "peer", "name",
             "eth0", "netns", ns]
        )
        _run(["ip", "link", "set", veth, "master", BRIDGE])
        _run(["ip", "link", "set", veth, "up"])
        _run(["ip", "netns", "exec", ns, "ip", "addr", "add", f"{ip}/24",
              "dev", "eth0"])
        _run(["ip", "netns", "exec", ns, "ip", "link", "set", "eth0", "up"])
        _run(["ip", "netns", "exec", ns, "ip", "link", "set", "lo", "up"])
        hosts.append(ip)
    return hosts


def apply_netem(
    hosts: list[str], rtt_ms: float, jitter_ms: float = 0.0,
    loss_pct: float = 0.0,
) -> bool:
    """WAN shaping: attach ``tc netem`` to every namespace's egress.
    Each side delays its own egress by rtt/2, so any A<->B round trip
    pays the full RTT — the standard symmetric-WAN emulation. Loss is
    per-direction. Returns False when the kernel lacks ``sch_netem``
    (container kernels often do) — the caller then falls back to
    faultline's app-layer link delay."""
    if rtt_ms <= 0 and loss_pct <= 0:
        return True
    for ip in hosts:
        ns = ns_name(ip)
        cmd = ["ip", "netns", "exec", ns, "tc", "qdisc", "add", "dev",
               "eth0", "root", "netem"]
        if rtt_ms > 0:
            cmd += ["delay", f"{rtt_ms / 2:.1f}ms"]
            if jitter_ms > 0:
                cmd += [f"{jitter_ms / 2:.1f}ms"]
        if loss_pct > 0:
            cmd += ["loss", f"{loss_pct}%"]
        res = _run(cmd, check=False)
        if res.returncode != 0:
            print(
                f"tc netem unavailable ({res.stderr.strip() or 'unknown'}); "
                "falling back to faultline app-layer WAN shaping"
            )
            return False
    return True


def teardown() -> None:
    out = _run(["ip", "netns", "list"], check=False).stdout
    for line in out.splitlines():
        name = line.split()[0] if line.split() else ""
        if name.startswith("hs"):
            _run(["ip", "netns", "del", name], check=False)
    _run(["ip", "link", "del", BRIDGE], check=False)


class NetnsRunner:
    """``RemoteBench`` transport backed by ``ip netns exec``.

    Each host's commands run inside its namespace with HOME and CWD set
    to a private per-host directory, so ``~``-relative paths and process
    match patterns (``pkill -f``) naturally scope per host even though
    all hosts share one pid namespace.
    """

    def __init__(self, repo_path: str | None = None) -> None:
        self.repo_path = repo_path or os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )

    def _home(self, host: str) -> str:
        return os.path.join(WORK_ROOT, host)

    def exec(self, host: str, command: str, check: bool = True):
        home = self._home(host)
        os.makedirs(home, exist_ok=True)
        env = dict(os.environ, HOME=home)
        return subprocess.run(
            ["ip", "netns", "exec", ns_name(host), "bash", "-c", command],
            check=check,
            capture_output=True,
            text=True,
            cwd=home,
            env=env,
        )

    def _map(self, host: str, remote: str) -> str:
        if remote.startswith("~"):
            remote = self._home(host) + remote[1:]
        if not os.path.isabs(remote):
            remote = os.path.join(self._home(host), remote)
        return remote

    def put(self, host: str, local: str, remote: str) -> None:
        dst = self._map(host, remote)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copy(local, dst)

    def get(self, host: str, remote: str, local: str) -> None:
        shutil.copy(self._map(host, remote), local)

    def provision(self, host: str) -> None:
        """Real clone per host (the install step, sans apt: the base
        image is the machine we are on)."""
        home = self._home(host)
        os.makedirs(home, exist_ok=True)
        repo_name = os.path.basename(self.repo_path.rstrip("/")) or "repo"
        dst = os.path.join(home, repo_name)
        if not os.path.isdir(os.path.join(dst, ".git")):
            _run(["git", "clone", "--depth", "1",
                  f"file://{self.repo_path}", dst])


def main() -> None:
    from benchmark.remote import RemoteBench
    from benchmark.settings import Settings

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hosts", type=int, default=4)
    p.add_argument("--rate", type=int, default=1_000)
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--duration", type=int, default=20)
    p.add_argument("--faults", type=int, default=0)
    p.add_argument("--timeout", type=int, default=5_000)
    p.add_argument(
        "--rtt", type=float, default=0.0,
        help="tc netem WAN shaping: full round-trip time in ms between "
        "any two hosts (each namespace delays its egress by rtt/2)",
    )
    p.add_argument(
        "--jitter", type=float, default=0.0,
        help="tc netem delay jitter in ms (full-RTT scale, split per side)",
    )
    p.add_argument(
        "--loss", type=float, default=0.0,
        help="tc netem per-direction packet loss percentage",
    )
    p.add_argument(
        "--partition", metavar="GROUPS",
        help="partition mode: host-index groups separated by '|' (e.g. "
        "'0,1|2,3'), cut at --partition-at and healed at "
        "--partition-heal seconds into the run. Enacted by each node's "
        "env-armed faultline plane (scheduled, deterministic, and "
        "kernel-agnostic — unlike tc, which cannot time a cut)",
    )
    p.add_argument(
        "--partition-at", type=float, default=5.0,
        help="seconds into the run the partition cuts (with --partition)",
    )
    p.add_argument(
        "--partition-heal", type=float, default=10.0,
        help="seconds into the run the partition heals (with --partition)",
    )
    p.add_argument("--output", help="directory to append the SUMMARY to")
    p.add_argument("--keep", action="store_true", help="skip teardown")
    args = p.parse_args()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_name = os.path.basename(repo.rstrip("/")) or "repo"
    settings = Settings(
        testbed="netns",
        key_name="-",
        key_path="-",
        consensus_port=8000,
        mempool_port=7000,
        front_port=6000,
        repo_name=repo_name,
        repo_url=f"file://{repo}",
        branch="main",
        instance_type="-",
        aws_regions=[],
    )

    hosts = setup(args.hosts)
    netem_ok = apply_netem(hosts, args.rtt, args.jitter, args.loss)
    events: list[dict] = []
    if not netem_ok and (args.rtt > 0 or args.loss > 0):
        # Kernel without sch_netem: emulate the WAN in the nodes
        # themselves via a permanent faultline all-links rule (each
        # side delays its egress by rtt/2; loss maps to per-frame drop).
        events.append(
            {
                "kind": "link",
                "src": "*",
                "dst": "*",
                "at": 0.0,
                "delay_ms": [args.rtt / 2, args.rtt / 2 + args.jitter / 2],
                "drop": args.loss / 100.0,
            }
        )
    if args.partition:
        # Partition mode: host-index groups (committee node names are
        # positional — n000… in consensus-address order, which setup()
        # makes identical to host order).
        groups = [
            [int(x) for x in group.split(",") if x != ""]
            for group in args.partition.split("|")
        ]
        events.append(
            {
                "kind": "partition",
                "groups": groups,
                "at": args.partition_at,
                "until": args.partition_heal,
            }
        )
    node_env = ""
    if events:
        from hotstuff_tpu.faultline import Scenario

        label = f"wan-rtt{int(args.rtt)}" if args.rtt else "partition"
        chaos = Scenario(
            name=f"netns-{label}",
            seed=0,
            duration_s=float(args.duration + 3600),
            events=events,
        )
        wan_file = "/tmp/hs-netns-wan.json"
        chaos.save(wan_file)
        node_env = "HOTSTUFF_FAULTLINE=~/bench/chaos.json"
    try:
        from hotstuff_tpu.consensus import Parameters as CParams
        from hotstuff_tpu.mempool import Parameters as MParams
        from hotstuff_tpu.node.config import Parameters as NodeParams

        bench = RemoteBench(settings, hosts, runner=NetnsRunner(repo))
        bench.install()
        bench.config(
            node_params=NodeParams(
                CParams(timeout_delay=args.timeout), MParams()
            )
        )
        if node_env:
            for host in hosts:
                bench.runner.put(host, "/tmp/hs-netns-wan.json", "bench/chaos.json")
        parser = bench.run(
            rate=args.rate,
            tx_size=args.tx_size,
            duration=args.duration,
            faults=args.faults,
            timeout_delay=args.timeout,
            node_env=node_env,
        )
        summary = parser.result()
        print(summary)
        if args.output:
            os.makedirs(args.output, exist_ok=True)
            shaped = f"-rtt{int(args.rtt)}" if args.rtt else ""
            if args.partition:
                shaped += "-part"
            name = (
                f"remote-netns{shaped}-{args.faults}-{args.hosts}-"
                f"{args.rate}-{args.tx_size}.txt"
            )
            with open(os.path.join(args.output, name), "a") as f:
                if args.rtt or args.loss:
                    f.write(
                        f"netem: rtt={args.rtt}ms jitter={args.jitter}ms "
                        f"loss={args.loss}%\n"
                    )
                if args.partition:
                    f.write(
                        f"partition: {args.partition} cut at "
                        f"{args.partition_at}s healed at "
                        f"{args.partition_heal}s\n"
                    )
                f.write(summary + "\n")
    finally:
        if not args.keep:
            bench_kill_stragglers()
            teardown()


def bench_kill_stragglers() -> None:
    _run(["pkill", "-f", WORK_ROOT], check=False)
    time.sleep(0.5)


if __name__ == "__main__":
    main()
