"""Device-side profile of the QC batch-verify pipeline.

Decomposes the per-batch device time into stages (decompress root /
kernel A partials / kernel B combine / full pipeline) by timing each
jitted stage on device-resident inputs as a pipelined stream, which
cancels the tunnel round-trip latency the same way bench.py does.

Usage: python benchmark/profile_device.py [n_sigs]
"""

from __future__ import annotations

import random
import sys
import time

import numpy as np

from hotstuff_tpu.utils.jaxcache import enable_persistent_cache

enable_persistent_cache()

import jax
import jax.numpy as jnp


def timed(fn, *args, iters: int = 16) -> float:
    """Median-of-3 rounds of `iters` overlapped calls on device-resident
    args; returns seconds per call."""
    outs = [fn(*args) for _ in range(2)]  # warm-up
    jax.block_until_ready(outs)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(iters)]
        jax.block_until_ready(outs)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main() -> None:
    n_sigs = int(sys.argv[1]) if len(sys.argv) > 1 else 1343

    sys.path.insert(0, ".")
    from bench import make_batch

    from hotstuff_tpu.ops import curve as cv
    from hotstuff_tpu.ops import field as fe
    from hotstuff_tpu.ops.verify import _compiled, _kernels, _unpack_device, prepare_batch

    msgs, pubs, sigs = make_batch(n_sigs)
    packed, m = prepare_batch(msgs, pubs, sigs, _rng=random.Random(7))
    print(f"n_sigs={n_sigs} lanes={m}")
    root_fn, msm_fn = _kernels()

    dev_packed = jnp.asarray(packed)

    # Stage jits.
    @jax.jit
    def unpack(p):
        return _unpack_device(p)

    @jax.jit
    def decomp(p):
        y, s, d = _unpack_device(p)
        ok, pts = cv.decompress(y, s, root_fn=root_fn)
        return ok, pts

    y_limbs, signs, digits = unpack(dev_packed)
    _, pts = decomp(dev_packed)
    pts, digits = jax.block_until_ready((pts, digits))

    @jax.jit
    def sqrt_only(y):
        yy = fe.square(y)
        u = fe.sub(yy, fe.fe_from_int(1, yy.shape[:-1]))
        v = fe.add(fe.mul(yy, jnp.asarray(fe.D_LIMBS)), fe.fe_from_int(1, yy.shape[:-1]))
        return root_fn(u, v) if root_fn is not None else fe.sqrt_ratio(u, v)[1]

    @jax.jit
    def msm_only(p, d):
        return msm_fn(p, d)

    @jax.jit
    def check_only(a):
        return cv.is_identity(cv.mul_by_cofactor(a[None, ...]))[0]

    acc = jax.block_until_ready(msm_only(pts, digits))

    full = _compiled(m)
    stages = {
        "full_pipeline": (full, (dev_packed,)),
        "unpack": (unpack, (dev_packed,)),
        "decompress(all)": (decomp, (dev_packed,)),
        "sqrt_pow_only": (sqrt_only, (y_limbs,)),
        "msm": (msm_only, (pts, digits)),
        "cofactor_check": (check_only, (acc,)),
    }
    results = {}
    for name, (fn, args) in stages.items():
        s = timed(fn, *args)
        results[name] = s
        print(f"{name:18s} {s * 1e3:9.3f} ms/batch  {s / n_sigs * 1e6:7.2f} us/sig")

    # Kernel A vs B split (pallas only).
    if jax.default_backend() == "tpu":
        from hotstuff_tpu.ops import pallas_msm as pm

        block = min(pm.DEFAULT_BLOCK, m)
        if block != m and block % 128 != 0:
            block = m
        grid = m // block
        partials_call = pm._build_partials(m, block)
        combine_call = pm._build_combine()

        @jax.jit
        def partials_only(p, d):
            coords = jnp.moveaxis(p, 0, -1)
            return partials_call(
                jnp.asarray(pm.CONSTS_CM), coords[0], coords[1], coords[2], coords[3], d
            )

        wsums = jax.block_until_ready(partials_only(pts, digits))

        @jax.jit
        def combine_only(wx, wy, wz, wt):
            return combine_call(jnp.asarray(pm.CONSTS_LM), wx, wy, wz, wt)

        for name, (fn, args) in {
            "kernelA_partials": (partials_only, (pts, digits)),
            "kernelB_combine": (combine_only, tuple(wsums)),
        }.items():
            s = timed(fn, *args)
            print(f"{name:18s} {s * 1e3:9.3f} ms/batch  {s / n_sigs * 1e6:7.2f} us/sig")
        print(f"(pallas block={block} grid={grid})")


if __name__ == "__main__":
    main()


