"""Cross-node round-trace assembly: merge every node's telemetry trace
stream into one causal timeline per committed block and attribute
milliseconds to each edge of the propose→vote→QC→commit path.

Input: the ``hotstuff-trace-v1`` lines interleaved in telemetry streams
(``telemetry-*.jsonl``) — per-node protocol events ``(seq, node, round,
stage, t_mono)`` with a wall-clock anchor per emitting process. The
stages a round leaves behind:

- ``propose_send`` (leader): proposal broadcast — t=0 of the timeline
- ``propose`` (every node): proposal seen (wire + receiver decode +
  core queue wait behind it)
- ``verified`` (every node): certificates verified (the crypto edge)
- ``vote_send`` (every node): vote created and dispatched
- ``first_vote`` / ``qc`` (the round's collector — the NEXT leader):
  fan-in window endpoints
- ``commit`` (every node): 2-chain commit of the round's block

Per committed round the assembler computes the **critical path**
``propose_send → first_vote → qc → commit`` and sub-attributes its first
leg through the fastest replica's marks, plus per-node distributions
(median/p90/max) for the fan-out edges — which is exactly the
decomposition that separates serde/queueing (``ingress``) from the
crypto plane (``verify``) from vote fan-in (``fanin``) at committee
scale.

Clock model: events are monotonic timestamps mapped to wall time via
each stream's anchor (``wall = anchor.wall + (t - anchor.mono)``). For
multi-host runs with skewed wall clocks, ``--align`` (default on)
estimates a per-node offset from causality — a replica cannot receive a
proposal before its leader sent it — and shifts each node by the
smallest offset restoring non-negative wire times.

    python -m benchmark.trace_assemble .bench/logs --committee 100 \
        --output results/trace-critical-path-100.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict
from statistics import median

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402
from benchmark.logs import ParseError, read_stream_records  # noqa: E402

REPORT_SCHEMA = "hotstuff-trace-critical-path-v1"

# The cross-node edges, in causal order. "ingress" is wire + receiver
# decode + core queue; "verify" the certificate verification; "vote" the
# vote make/persist/dispatch; "vote_wire" dispatch to first arrival at
# the collector; "fanin" first vote to assembled QC (the 2f+1 straggler
# wait); "qc_to_commit" certificate to 2-chain commit (two follow-on
# rounds by construction).
EDGES = ("ingress", "verify", "vote", "vote_wire", "fanin", "qc_to_commit")


def load_events(
    paths: list[str], skipped_streams: list[str] | None = None
) -> list[dict]:
    """All trace events across streams as dicts with wall-mapped times.
    Events are re-sorted by (node, seq): a stream's lines can land
    interleaved/out of order when processes share a file.

    A stream that cannot contribute — unreadable/corrupt, or trace
    records missing the wall-clock **anchor** that maps their monotonic
    timestamps onto the shared timeline — is skipped with a warning and
    recorded in ``skipped_streams`` (when a list is given) instead of
    aborting the whole assembly or vanishing silently: one crashed
    node's stream must not cost the other N-1 nodes' timeline, but the
    report has to say the attribution is partial."""
    events: list[dict] = []
    for path in paths:
        try:
            records = read_stream_records(path)
        except (ParseError, OSError) as e:
            print(f"WARN: skipping stream {path}: {e}", file=sys.stderr)
            if skipped_streams is not None:
                skipped_streams.append(os.path.basename(path))
            continue
        bad_anchor = False
        for rec in records.traces:
            anchor = rec.get("anchor") or {}
            if not all(
                isinstance(anchor.get(k), (int, float)) for k in ("mono", "wall")
            ):
                bad_anchor = True
                continue
            off = anchor["wall"] - anchor["mono"]
            for ev in rec["events"]:
                # Events are 5-tuples, or 6 with a detail payload (vote
                # author/digest, commit height — the watchtower's fields);
                # edge attribution only needs the first five.
                seq, node, round_, stage, t = ev[:5]
                events.append(
                    {
                        "seq": seq,
                        "node": node,
                        "round": round_,
                        "stage": stage,
                        "t": t + off,
                        "stream": path,
                    }
                )
        if bad_anchor:
            print(
                f"WARN: {path}: trace record(s) without a wall-clock "
                "anchor skipped (cannot place on the shared timeline)",
                file=sys.stderr,
            )
            if skipped_streams is not None:
                skipped_streams.append(os.path.basename(path))
    events.sort(key=lambda e: (e["stream"], e["node"], e["seq"]))
    return events


def estimate_offsets(events: list[dict]) -> dict[str, float]:
    """Per-node clock offsets restoring send→receive causality.

    For every round with a ``propose_send``, each node's ``propose``
    must not precede it. A node whose earliest observed wire delta is
    negative gets shifted forward by exactly that amount — the minimal
    correction, assuming near-zero minimum network delay. Leaders anchor
    the timeline; nodes that never receive relative to a known send
    keep offset 0."""
    sends: dict[int, float] = {}
    for e in events:
        if e["stage"] == "propose_send":
            r = e["round"]
            if r not in sends or e["t"] < sends[r]:
                sends[r] = e["t"]
    offsets: dict[str, float] = defaultdict(float)
    worst: dict[str, float] = {}
    for e in events:
        if e["stage"] != "propose" or e["round"] not in sends:
            continue
        delta = e["t"] - sends[e["round"]]
        node = e["node"]
        if node not in worst or delta < worst[node]:
            worst[node] = delta
    for node, delta in worst.items():
        if delta < 0:
            offsets[node] = -delta
    return dict(offsets)


def _pct(sorted_values: list[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1, int(round(q * (len(sorted_values) - 1))))
    return sorted_values[idx]


def _stats_ms(values: list[float]) -> dict:
    vs = sorted(values)
    return {
        "n": len(vs),
        "median_ms": round(median(vs) * 1e3, 3) if vs else None,
        "p90_ms": round(_pct(vs, 0.9) * 1e3, 3) if vs else None,
        "max_ms": round(vs[-1] * 1e3, 3) if vs else None,
    }


def assemble_rounds(
    events: list[dict], offsets: dict[str, float] | None = None
) -> list[dict]:
    """Per committed round: the merged timeline and edge attribution."""
    offsets = offsets or {}

    def t_of(e):
        return e["t"] + offsets.get(e["node"], 0.0)

    by_round: dict[int, dict[str, list[dict]]] = defaultdict(
        lambda: defaultdict(list)
    )
    for e in events:
        by_round[e["round"]][e["stage"]].append(e)

    rounds: list[dict] = []
    for r in sorted(by_round):
        stages = by_round[r]
        if not stages.get("commit"):
            continue  # only committed blocks get a full timeline
        commits = sorted(t_of(e) for e in stages["commit"])
        send = min(
            (t_of(e) for e in stages.get("propose_send", [])), default=None
        )
        recvs = {e["node"]: t_of(e) for e in stages.get("propose", [])}
        if send is None:
            # Leader's stream missing: fall back to the earliest sighting
            # (the leader's own loopback propose is within µs of its
            # broadcast in-process).
            send = min(recvs.values(), default=None)
        if send is None:
            continue
        verifieds = {e["node"]: t_of(e) for e in stages.get("verified", [])}
        vote_sends = {e["node"]: t_of(e) for e in stages.get("vote_send", [])}
        first_vote = min(
            (t_of(e) for e in stages.get("first_vote", [])), default=None
        )
        qc = min((t_of(e) for e in stages.get("qc", [])), default=None)
        first_commit = commits[0]

        ingress = [max(0.0, t - send) for t in recvs.values()]
        verify = [
            max(0.0, verifieds[n] - recvs[n]) for n in verifieds if n in recvs
        ]
        vote = [
            max(0.0, vote_sends[n] - verifieds[n])
            for n in vote_sends
            if n in verifieds
        ]

        # Critical-path legs (they sum to total by construction when all
        # marks exist): send→first_vote decomposed through the fastest
        # voter, then the fan-in window, then qc→commit.
        edges: dict[str, float | None] = dict.fromkeys(EDGES)
        if first_vote is not None and vote_sends:
            fastest_vote_send = min(vote_sends.values())
            edges["vote_wire"] = max(0.0, first_vote - fastest_vote_send)
            # Sub-attribute through the fastest FULLY-marked replica (the
            # leader votes via loopback and carries no receive/verify
            # marks, so it would otherwise always win and void these
            # edges). The table is attribution along representative fast
            # paths, not an exact decomposition — "unattributed" absorbs
            # the difference against the true total.
            full = [
                n for n in vote_sends if n in recvs and n in verifieds
            ]
            if full:
                fast_voter = min(full, key=vote_sends.get)
                edges["ingress"] = max(0.0, recvs[fast_voter] - send)
                edges["verify"] = max(
                    0.0, verifieds[fast_voter] - recvs[fast_voter]
                )
                edges["vote"] = max(
                    0.0, vote_sends[fast_voter] - verifieds[fast_voter]
                )
        if first_vote is not None and qc is not None:
            edges["fanin"] = max(0.0, qc - first_vote)
        if qc is not None:
            edges["qc_to_commit"] = max(0.0, first_commit - qc)

        total = first_commit - send
        attributed = sum(v for v in edges.values() if v is not None)
        rounds.append(
            {
                "round": r,
                "total_ms": round(total * 1e3, 3),
                "unattributed_ms": round(max(0.0, total - attributed) * 1e3, 3),
                "edges_ms": {
                    k: (None if v is None else round(v * 1e3, 3))
                    for k, v in edges.items()
                },
                "fanout": {
                    "ingress": _stats_ms(ingress),
                    "verify": _stats_ms(verify),
                    "vote": _stats_ms(vote),
                },
                "nodes_observed": len(recvs),
                "commit_spread_ms": round((commits[-1] - commits[0]) * 1e3, 3),
            }
        )
    return rounds


def summarize(rounds: list[dict], top: int = 5) -> dict:
    """Aggregate edge attribution + top-k slowest rounds + ranked cost
    centers (the committed "what eats the time" answer)."""
    per_edge: dict[str, list[float]] = defaultdict(list)
    for rd in rounds:
        for edge, v in rd["edges_ms"].items():
            if v is not None:
                per_edge[edge].append(v)
        per_edge["unattributed"].append(rd["unattributed_ms"])
    totals = sorted(rd["total_ms"] for rd in rounds)
    edge_summary = {}
    for edge, values in per_edge.items():
        vs = sorted(values)
        edge_summary[edge] = {
            "n": len(vs),
            "mean_ms": round(sum(vs) / len(vs), 3),
            "median_ms": round(median(vs), 3),
            "p90_ms": round(_pct(vs, 0.9), 3),
            "max_ms": round(vs[-1], 3),
        }
    cost_centers = sorted(
        (
            {"edge": e, "mean_ms": s["mean_ms"]}
            for e, s in edge_summary.items()
        ),
        key=lambda c: -c["mean_ms"],
    )
    mean_total = sum(totals) / len(totals) if totals else 0.0
    for c in cost_centers:
        c["share"] = round(c["mean_ms"] / mean_total, 4) if mean_total else 0.0
    slowest = sorted(rounds, key=lambda rd: -rd["total_ms"])[:top]
    return {
        "rounds": len(rounds),
        "total_ms": {
            "mean": round(mean_total, 3),
            "median": round(median(totals), 3) if totals else None,
            "p90": round(_pct(totals, 0.9), 3) if totals else None,
            "max": round(totals[-1], 3) if totals else None,
        },
        "edges": edge_summary,
        "cost_centers": cost_centers,
        "top_cost_centers": [c["edge"] for c in cost_centers[:3]],
        "slowest_rounds": slowest,
    }


def assemble(
    paths: list[str], *, align: bool = True, top: int = 5
) -> dict:
    skipped: list[str] = []
    events = load_events(paths, skipped_streams=skipped)
    offsets = estimate_offsets(events) if align else {}
    rounds = assemble_rounds(events, offsets)
    report = {
        "schema": REPORT_SCHEMA,
        "host": host_meta(),
        "streams": [os.path.basename(p) for p in paths],
        "events": len(events),
        "skipped_streams": sorted(set(skipped)),
        "clock_offsets_s": {
            n: round(o, 6) for n, o in sorted(offsets.items())
        },
        **summarize(rounds, top=top),
        "per_round": rounds,
    }
    return report


def _human(report: dict) -> str:
    lines = [
        f"assembled {report['rounds']} committed rounds from "
        f"{report['events']} events across {len(report['streams'])} stream(s)"
        + (
            f" ({len(report['skipped_streams'])} skipped: no usable anchor)"
            if report.get("skipped_streams")
            else ""
        ),
        f"round total: mean {report['total_ms']['mean']} ms, "
        f"p90 {report['total_ms']['p90']} ms, max {report['total_ms']['max']} ms",
        f"{'edge':<14} {'mean ms':>9} {'p90 ms':>9} {'max ms':>9} {'share':>7}",
    ]
    shares = {c["edge"]: c["share"] for c in report["cost_centers"]}
    for edge, s in sorted(
        report["edges"].items(), key=lambda kv: -kv[1]["mean_ms"]
    ):
        lines.append(
            f"{edge:<14} {s['mean_ms']:>9} {s['p90_ms']:>9} {s['max_ms']:>9} "
            f"{shares.get(edge, 0):>6.1%}"
        )
    lines.append(
        "top cost centers: " + ", ".join(report["top_cost_centers"])
    )
    return "\n".join(lines)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "paths", nargs="+",
        help="telemetry stream files, or directories containing "
        "telemetry-*.jsonl",
    )
    p.add_argument("--top", type=int, default=5, help="slowest rounds kept")
    p.add_argument("--committee", type=int, help="committee size (recorded)")
    p.add_argument(
        "--no-align", action="store_true",
        help="skip causality-based clock-offset estimation",
    )
    p.add_argument("--output", help="write the JSON report here")
    args = p.parse_args()

    paths: list[str] = []
    for path in args.paths:
        if os.path.isdir(path):
            paths.extend(sorted(glob.glob(os.path.join(path, "telemetry-*.jsonl"))))
        else:
            paths.append(path)
    if not paths:
        print("no telemetry streams found", file=sys.stderr)
        sys.exit(2)

    report = assemble(paths, align=not args.no_align, top=args.top)
    if args.committee is not None:
        report["committee"] = args.committee
    print(_human(report))
    if args.output:
        os.makedirs(
            os.path.dirname(os.path.abspath(args.output)), exist_ok=True
        )
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"report written to {args.output}")
    if not report["rounds"]:
        print("no committed rounds were assembled", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
