"""Open-loop fleet bench: the front door under connection-scale load.

Boots the sharded-ingest local bench (real node processes, worker
shards) but replaces the per-node saturating loader with the client's
``--fleet`` mode: many concurrent connections per client, Poisson
(exponential-gap) arrivals of small bundles, optional square-wave burst
windows and connection churn. Because arrivals never wait for
back-pressure, overload shows up where it should: shed notifications,
worker ingress watermarks, and the p99.9 e2e tail — the three numbers a
closed-loop sweep structurally cannot measure.

The artifact (``results/fleet-*.json``) records, per run: committed e2e
TPS, mean/p99/p99.9 e2e latency, total sheds, connection churns, and
the max ``mempool.worker.ingress_depth`` watermark observed across
every node's telemetry stream (host class stamped via
``benchmark.hostinfo``).

    python -m benchmark.fleet_bench --nodes 4 --workers 2 --rate 20000 \
        --fleet 256 --bundle-txs 8 --duration 30 --output results
    python -m benchmark.fleet_bench --nodes 4 --workers 1 --rate 10000 \
        --fleet 512 --burst-every 10 --burst-len 2 --burst-x 4 --churn 0.5
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402
from benchmark.local import BenchError, LocalBench  # noqa: E402
from benchmark.logs import ParseError, read_telemetry_stream  # noqa: E402

FLEET_SCHEMA = "hotstuff-fleet-v1"


def _churn_total(logs_dir: str) -> int:
    total = 0
    for fn in sorted(glob.glob(os.path.join(logs_dir, "client-*.log"))):
        with open(fn) as f:
            matches = re.findall(r"Connection churns: (\d+)", f.read())
        if matches:
            total += int(matches[-1])
    return total


def _ingress_watermark(logs_dir: str) -> int:
    """Max ``mempool.worker.ingress_depth`` gauge across all snapshots of
    all node streams — the high-water mark the fleet actually reached."""
    peak = 0
    for fn in sorted(glob.glob(os.path.join(logs_dir, "telemetry-*.jsonl"))):
        try:
            stream = read_telemetry_stream(fn)
        except ParseError:
            continue
        for snap in stream:
            for name, value in snap.get("gauges", {}).items():
                if name.endswith("ingress_depth"):
                    peak = max(peak, int(value))
    return peak


def run_fleet(args: argparse.Namespace) -> dict:
    per_client_fleet = max(args.fleet // args.nodes, 1)
    extra = [
        "--fleet", str(per_client_fleet),
        "--bundle-txs", str(args.bundle_txs),
    ]
    if args.burst_every > 0:
        extra += [
            "--burst-every", str(args.burst_every),
            "--burst-len", str(args.burst_len),
            "--burst-x", str(args.burst_x),
        ]
    if args.churn > 0:
        extra += ["--churn", str(args.churn)]
    bench = LocalBench(
        nodes=args.nodes,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        base_port=args.base_port,
        timeout_delay=args.timeout,
        batch_size=args.batch_size,
        max_batch_delay=args.max_batch_delay,
        work_dir=args.work_dir,
        workers=args.workers,
        telemetry=True,
        client_extra=extra,
    )
    parser = bench.run()
    e2e_tps, e2e_bps, dur = parser._end_to_end_throughput()
    logs_dir = os.path.join(os.path.abspath(args.work_dir), "logs")
    return {
        "e2e_tps": round(e2e_tps),
        "e2e_bps": round(e2e_bps),
        "e2e_latency_ms": round(parser._end_to_end_latency() * 1e3),
        "e2e_latency_p99_ms": round(parser.e2e_latency_tail(0.99) * 1e3),
        "e2e_latency_p999_ms": round(parser.e2e_latency_tail(0.999) * 1e3),
        "consensus_latency_ms": round(parser._consensus_latency() * 1e3),
        "duration_s": round(dur, 1),
        "shed": parser.sheds,
        "churns": _churn_total(logs_dir),
        "ingress_depth_peak": _ingress_watermark(logs_dir),
        "rate_misses": parser.misses,
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--rate", type=int, default=10_000, help="total tx/s")
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--duration", type=int, default=30)
    p.add_argument("--timeout", type=int, default=2_000)
    p.add_argument("--batch-size", type=int, default=250_000)
    p.add_argument("--max-batch-delay", type=int, default=50, help="ms")
    p.add_argument("--base-port", type=int, default=13000)
    p.add_argument("--work-dir", default=".fleet-bench")
    p.add_argument(
        "--fleet", type=int, default=256,
        help="total concurrent connections across all clients",
    )
    p.add_argument(
        "--bundle-txs", type=int, default=8,
        help="transactions per bundle (arrival granularity)",
    )
    p.add_argument("--burst-every", type=float, default=0.0)
    p.add_argument("--burst-len", type=float, default=0.0)
    p.add_argument("--burst-x", type=float, default=1.0)
    p.add_argument(
        "--churn", type=float, default=0.0,
        help="per-client: redial one connection every N seconds",
    )
    p.add_argument("--output", help="directory for the fleet artifact")
    args = p.parse_args()
    if args.workers < 1:
        p.error("--workers must be >= 1 (fleet mode targets worker shards)")

    try:
        results = run_fleet(args)
    except (BenchError, ParseError) as e:
        print(f"fleet bench failed: {e}")
        sys.exit(1)
    report = {
        "schema": FLEET_SCHEMA,
        "ts": time.time(),
        "host": host_meta(),
        "config": {
            "nodes": args.nodes,
            "workers": args.workers,
            "rate": args.rate,
            "tx_size": args.tx_size,
            "duration_s": args.duration,
            "fleet": args.fleet,
            "bundle_txs": args.bundle_txs,
            "burst_every_s": args.burst_every,
            "burst_len_s": args.burst_len,
            "burst_x": args.burst_x,
            "churn_s": args.churn,
        },
        "results": results,
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        path = os.path.join(
            args.output,
            f"fleet-n{args.nodes}-w{args.workers}-c{args.fleet}-"
            f"{args.tx_size}B.json",
        )
        with open(path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"artifact written to {path}")


if __name__ == "__main__":
    main()
