"""Plot aggregated benchmark series (reference
``benchmark/benchmark/plot.py``): matplotlib errorbar L-graphs
(latency vs throughput) with a tx/s <-> MB/s twin axis, and scalability
plots (best TPS vs committee size)."""

from __future__ import annotations

import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import matplotlib.ticker as ticker  # noqa: E402

from .aggregate import LogAggregator
from .utils import PathMaker


class Ploter:
    def __init__(self, results_dir: str | None = None) -> None:
        self.agg = LogAggregator(results_dir)

    @staticmethod
    def _tx_to_mb(rate: float, tx_size: int) -> float:
        return rate * tx_size / 1e6

    def plot_latency(
        self, faults: list[int], nodes: list[int], tx_size: int, out: str | None = None
    ) -> str:
        """Latency vs throughput, one curve per (faults, committee size)."""
        fig, ax = plt.subplots(figsize=(6.4, 3.6))
        for f in faults:
            for n in nodes:
                rows = self.agg.latency_vs_rate(f, n, tx_size)
                if not rows:
                    continue
                xs = [r[1] for r in rows]  # achieved tps
                ys = [r[3] for r in rows]
                yerr = [r[4] for r in rows]
                label = f"{n} nodes" + (f" ({f} faulty)" if f else "")
                ax.errorbar(xs, ys, yerr=yerr, marker="o", capsize=3, label=label)
        ax.set_xlabel("Throughput (tx/s)")
        ax.set_ylabel("Latency (ms)")
        ax.xaxis.set_major_formatter(ticker.StrMethodFormatter("{x:,.0f}"))
        ax.legend(loc="upper left", fontsize=8)

        # Twin axis in MB/s (reference ``plot.py:56-88``).
        sec = ax.secondary_xaxis(
            "top",
            functions=(
                lambda x: x * tx_size / 1e6,
                lambda x: x * 1e6 / tx_size,
            ),
        )
        sec.set_xlabel("Throughput (MB/s)")
        out = out or PathMaker.plot_file(f"latency-{tx_size}")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        fig.tight_layout()
        fig.savefig(out)
        plt.close(fig)
        return out

    def plot_tps(
        self,
        faults: list[int],
        tx_size: int,
        max_latency: float | None = None,
        out: str | None = None,
    ) -> str:
        """Best TPS vs committee size (scalability)."""
        fig, ax = plt.subplots(figsize=(6.4, 3.6))
        for f in faults:
            rows = self.agg.tps_vs_nodes(f, tx_size, max_latency)
            if not rows:
                continue
            xs = [r[0] for r in rows]
            ys = [r[1] for r in rows]
            yerr = [r[2] for r in rows]
            label = f"{f} faulty" if f else "no faults"
            ax.errorbar(xs, ys, yerr=yerr, marker="s", capsize=3, label=label)
        ax.set_xlabel("Committee size")
        ax.set_ylabel("Throughput (tx/s)")
        ax.yaxis.set_major_formatter(ticker.StrMethodFormatter("{x:,.0f}"))
        ax.legend(loc="upper right", fontsize=8)
        out = out or PathMaker.plot_file(f"tps-{tx_size}")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        fig.tight_layout()
        fig.savefig(out)
        plt.close(fig)
        return out
