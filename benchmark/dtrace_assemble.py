"""Lifeline assembly: merge every node's ``hotstuff-dtrace-v1`` batch
lifecycle events (plus the round traces they join onto) into one causal
timeline per committed batch, and attribute milliseconds to each edge of
the data-plane path the consensus-round trace cannot see.

Input: telemetry streams (``telemetry-*.jsonl``) carrying interleaved
``hotstuff-dtrace-v1`` and ``hotstuff-trace-v1`` records. The lifecycle
stages a batch leaves behind (see ``hotstuff_tpu/telemetry/dtrace.py``):
``ingress`` → ``seal`` → ``disseminate`` → ``ack``* → ``cert`` →
``enqueue`` → ``proposed`` → ``committed`` → ``resolved``.

Per committed batch the assembler computes the seven-edge attribution:

- ``ingress_wait``: earliest contributing bundle arrival → seal
- ``seal``:        seal → dissemination handoff (encode+hash+store+sign)
- ``disseminate``: handoff → FIRST peer ack verified (wire + peer store)
- ``ack_fanin``:   first ack → 2f+1 stake (the straggler wait)
- ``queue_wait``:  proposer enqueue → drained into a block
- ``ordering``:    proposed → first commit anywhere (joined to the
  round trace: the ``r<round>`` detail keys the round's own
  propose→vote→QC→commit breakdown onto the batch)
- ``resolve``:     first commit → commit-path bytes materialized

A batch that died mid-pipeline (sealed but never certified, committed
but never resolved) is reported with its reached stage and the OPEN
edge named — partial lifelines are the diagnostic, not an error.

Clock model: each record's wall anchor maps its monotonic timestamps
onto the shared timeline; ``--align`` additionally applies the round
trace's causality-estimated per-node offsets (a replica cannot receive
a proposal before its leader sent it) to the dtrace events of the same
nodes — multi-process engine-group streams merge the same way.

    python -m benchmark.dtrace_assemble .dataplane-bench/logs \
        --clients .dataplane-bench/logs --output results/dtrace.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict
from statistics import median

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402
from benchmark.logs import ParseError, _to_posix, read_stream_records  # noqa: E402
from benchmark.trace_assemble import (  # noqa: E402
    _pct,
    assemble_rounds,
    estimate_offsets,
    load_events,
)

REPORT_SCHEMA = "hotstuff-dtrace-lifeline-v1"

#: the seven per-batch lifecycle edges, in causal order.
EDGES = (
    "ingress_wait", "seal", "disseminate", "ack_fanin", "queue_wait",
    "ordering", "resolve",
)

#: (edge, its opening stage, its closing stage) — an edge is OPEN when
#: the opening stage was observed but the closing one never arrived.
_EDGE_STAGES = (
    ("ingress_wait", "ingress", "seal"),
    ("seal", "seal", "disseminate"),
    ("disseminate", "disseminate", "first_ack"),
    ("ack_fanin", "first_ack", "cert"),
    ("queue_wait", "enqueue", "proposed"),
    ("ordering", "proposed", "committed"),
    ("resolve", "committed", "resolved"),
)


def load_dtrace_events(
    paths: list[str], skipped_streams: list[str] | None = None
) -> list[dict]:
    """All batch-lifecycle events across streams with wall-mapped times
    (same skip semantics as ``trace_assemble.load_events``: a stream
    that cannot contribute is warned about and recorded, not fatal)."""
    events: list[dict] = []
    for path in paths:
        try:
            records = read_stream_records(path)
        except (ParseError, OSError) as e:
            print(f"WARN: skipping stream {path}: {e}", file=sys.stderr)
            if skipped_streams is not None:
                skipped_streams.append(os.path.basename(path))
            continue
        bad_anchor = False
        for rec in records.dtraces:
            anchor = rec.get("anchor") or {}
            if not all(
                isinstance(anchor.get(k), (int, float)) for k in ("mono", "wall")
            ):
                bad_anchor = True
                continue
            off = anchor["wall"] - anchor["mono"]
            for ev in rec["events"]:
                seq, node, batch, stage, t = ev[:5]
                events.append(
                    {
                        "seq": seq,
                        "node": node,
                        "batch": batch,
                        "stage": stage,
                        "t": t + off,
                        "detail": ev[5] if len(ev) > 5 else None,
                        "stream": path,
                    }
                )
        if bad_anchor:
            print(
                f"WARN: {path}: dtrace record(s) without a wall-clock "
                "anchor skipped (cannot place on the shared timeline)",
                file=sys.stderr,
            )
            if skipped_streams is not None:
                skipped_streams.append(os.path.basename(path))
    events.sort(key=lambda e: (e["stream"], e["node"], e["seq"]))
    return events


def load_client_sends(paths: list[str]) -> dict[int, float]:
    """sample id -> earliest wall send time, from the clients' "Sending
    sample transaction N" measurement lines (the regex contract)."""
    from re import findall

    sends: dict[int, float] = {}
    for path in paths:
        try:
            with open(path) as f:
                log_text = f.read()
        except OSError:
            continue
        for ts, s in findall(
            r"\[(.*Z) .* sample transaction (\d+)", log_text
        ):
            t = _to_posix(ts)
            sid = int(s)
            if sid not in sends or t < sends[sid]:
                sends[sid] = t
    return sends


def _parse_round(detail) -> int | None:
    if isinstance(detail, str) and detail.startswith("r"):
        try:
            return int(detail[1:])
        except ValueError:
            return None
    return None


def _seal_samples(detail) -> list[int]:
    """Sample ids from a seal detail ``w0|8tx|4096B|s42,43``."""
    if not isinstance(detail, str):
        return []
    for part in detail.split("|"):
        if part.startswith("s") and part[1:].replace(",", "").isdigit():
            return [int(x) for x in part[1:].split(",") if x]
    return []


def assemble_batches(
    events: list[dict],
    offsets: dict[str, float] | None = None,
    round_edges: dict[int, dict] | None = None,
    client_sends: dict[int, float] | None = None,
) -> list[dict]:
    """Per batch: merged timeline, seven-edge attribution, round join."""
    offsets = offsets or {}
    round_edges = round_edges or {}

    def t_of(e):
        return e["t"] + offsets.get(e["node"], 0.0)

    by_batch: dict[str, dict[str, list[dict]]] = defaultdict(
        lambda: defaultdict(list)
    )
    for e in events:
        by_batch[e["batch"]][e["stage"]].append(e)

    batches: list[dict] = []
    for label in sorted(by_batch):
        stages = by_batch[label]
        marks: dict[str, float] = {}
        # Single-producer stages: the sealing worker's own marks.
        for st in ("ingress", "seal", "disseminate", "cert"):
            if stages.get(st):
                marks[st] = min(t_of(e) for e in stages[st])
        acks = sorted(t_of(e) for e in stages.get("ack", []))
        if acks:
            marks["first_ack"] = acks[0]
        proposed_evs = stages.get("proposed", [])
        proposer = None
        round_ = None
        if proposed_evs:
            first_prop = min(proposed_evs, key=t_of)
            marks["proposed"] = t_of(first_prop)
            proposer = first_prop["node"]
            round_ = _parse_round(first_prop["detail"])
        # queue_wait wants the enqueue on the PROPOSING node (that is
        # the queue the digest waited in); fall back to the earliest.
        enq = stages.get("enqueue", [])
        if enq:
            own = [e for e in enq if proposer is None or e["node"] == proposer]
            marks["enqueue"] = min(t_of(e) for e in (own or enq))
        commits = sorted(t_of(e) for e in stages.get("committed", []))
        if commits:
            marks["committed"] = commits[0]
            if round_ is None:
                round_ = _parse_round(
                    min(stages["committed"], key=t_of)["detail"]
                )
        resolves = sorted(t_of(e) for e in stages.get("resolved", []))
        if resolves:
            marks["resolved"] = resolves[0]

        edges: dict[str, float | None] = dict.fromkeys(EDGES)
        open_edges: list[str] = []
        last_stage = None
        for edge, lo, hi in _EDGE_STAGES:
            a, b = marks.get(lo), marks.get(hi)
            if a is not None:
                last_stage = lo
            if a is not None and b is not None:
                edges[edge] = max(0.0, b - a)
            elif a is not None and b is None:
                open_edges.append(edge)
        if marks.get("resolved") is not None:
            last_stage = "resolved"
        elif marks.get("committed") is not None:
            last_stage = "committed"

        t_first = min(marks.values(), default=None)
        t_last = max(marks.values(), default=None)
        if t_first is None:
            continue
        total = t_last - t_first
        attributed = sum(v for v in edges.values() if v is not None)
        row = {
            "batch": label,
            "round": round_,
            "stage_reached": last_stage,
            "total_ms": round(total * 1e3, 3),
            "unattributed_ms": round(max(0.0, total - attributed) * 1e3, 3),
            "edges_ms": {
                k: (None if v is None else round(v * 1e3, 3))
                for k, v in edges.items()
            },
            "open_edges": open_edges,
            "acks": len(acks),
            "commit_nodes": len(commits),
        }
        # Round-trace join: the batch's ordering edge decomposed through
        # the round's own critical path (propose wire, verify, vote
        # fan-in, qc→commit) when that round assembled.
        if round_ is not None and round_ in round_edges:
            row["round_edges_ms"] = round_edges[round_]
        # Client join: earliest sampled client send → worker ingress
        # (only sampled txs carry ids; absence is not an open edge).
        if client_sends and stages.get("seal"):
            sids = _seal_samples(min(stages["seal"], key=t_of)["detail"])
            sent = min(
                (client_sends[s] for s in sids if s in client_sends),
                default=None,
            )
            anchor_t = marks.get("ingress", marks.get("seal"))
            if sent is not None and anchor_t is not None:
                row["client_submit_ms"] = round(
                    max(0.0, anchor_t - sent) * 1e3, 3
                )
        batches.append(row)
    return batches


def summarize(batches: list[dict], top: int = 5) -> dict:
    """Aggregate edge attribution + cost-center ranking + top-k slowest
    COMPLETE batches + a census of where incomplete lifelines stopped."""
    per_edge: dict[str, list[float]] = defaultdict(list)
    complete = [b for b in batches if not b["open_edges"]]
    for b in batches:
        for edge, v in b["edges_ms"].items():
            if v is not None:
                per_edge[edge].append(v)
    edge_summary = {}
    for edge, values in per_edge.items():
        vs = sorted(values)
        edge_summary[edge] = {
            "n": len(vs),
            "mean_ms": round(sum(vs) / len(vs), 3),
            "median_ms": round(median(vs), 3),
            "p90_ms": round(_pct(vs, 0.9), 3),
            "max_ms": round(vs[-1], 3),
        }
    cost_centers = sorted(
        (
            {"edge": e, "mean_ms": s["mean_ms"]}
            for e, s in edge_summary.items()
        ),
        key=lambda c: -c["mean_ms"],
    )
    totals = sorted(b["total_ms"] for b in complete)
    mean_total = sum(totals) / len(totals) if totals else 0.0
    for c in cost_centers:
        c["share"] = round(c["mean_ms"] / mean_total, 4) if mean_total else 0.0
    stage_census: dict[str, int] = defaultdict(int)
    for b in batches:
        if b["open_edges"]:
            stage_census[b["stage_reached"] or "none"] += 1
    slowest = sorted(complete, key=lambda b: -b["total_ms"])[:top]
    return {
        "batches": len(batches),
        "complete": len(complete),
        "incomplete_by_stage_reached": dict(sorted(stage_census.items())),
        "total_ms": {
            "mean": round(mean_total, 3),
            "median": round(median(totals), 3) if totals else None,
            "p90": round(_pct(totals, 0.9), 3) if totals else None,
            "max": round(totals[-1], 3) if totals else None,
        },
        "edges": edge_summary,
        "cost_centers": cost_centers,
        "top_cost_centers": [c["edge"] for c in cost_centers[:3]],
        "slowest_batches": slowest,
    }


def assemble(
    paths: list[str],
    *,
    align: bool = True,
    top: int = 5,
    client_paths: list[str] | None = None,
) -> dict:
    skipped: list[str] = []
    devents = load_dtrace_events(paths, skipped_streams=skipped)
    # The round traces ride the same streams: they give the per-node
    # clock offsets (causality anchored on propose_send) AND the ordering
    # edge's internal breakdown for the round join.
    revents = load_events(paths)
    offsets = estimate_offsets(revents) if align else {}
    rounds = assemble_rounds(revents, offsets)
    round_edges = {rd["round"]: rd["edges_ms"] for rd in rounds}
    client_sends = (
        load_client_sends(client_paths) if client_paths else None
    )
    batches = assemble_batches(
        devents, offsets, round_edges=round_edges, client_sends=client_sends
    )
    report = {
        "schema": REPORT_SCHEMA,
        "host": host_meta(),
        "streams": [os.path.basename(p) for p in paths],
        "events": len(devents),
        "round_trace_rounds": len(rounds),
        "skipped_streams": sorted(set(skipped)),
        "clock_offsets_s": {
            n: round(o, 6) for n, o in sorted(offsets.items())
        },
        **summarize(batches, top=top),
        "per_batch": batches,
    }
    if client_sends is not None:
        joined = [
            b["client_submit_ms"]
            for b in batches
            if "client_submit_ms" in b
        ]
        report["client_submit_ms"] = (
            {
                "n": len(joined),
                "median_ms": round(median(joined), 3),
                "max_ms": round(max(joined), 3),
            }
            if joined
            else {"n": 0}
        )
    return report


def _human(report: dict) -> str:
    lines = [
        f"assembled {report['batches']} batch lifelines "
        f"({report['complete']} complete) from {report['events']} events "
        f"across {len(report['streams'])} stream(s); "
        f"{report['round_trace_rounds']} round traces joined"
        + (
            f" ({len(report['skipped_streams'])} stream(s) skipped)"
            if report.get("skipped_streams")
            else ""
        ),
    ]
    if report["incomplete_by_stage_reached"]:
        lines.append(
            "incomplete lifelines stopped at: "
            + ", ".join(
                f"{st}={n}"
                for st, n in report["incomplete_by_stage_reached"].items()
            )
        )
    if report["total_ms"]["mean"] is not None and report["complete"]:
        lines.append(
            f"batch e2e (ingress→resolved): mean {report['total_ms']['mean']} ms, "
            f"p90 {report['total_ms']['p90']} ms, max {report['total_ms']['max']} ms"
        )
    lines.append(
        f"{'edge':<14} {'mean ms':>9} {'p90 ms':>9} {'max ms':>9} {'share':>7}"
    )
    shares = {c["edge"]: c["share"] for c in report["cost_centers"]}
    for edge, s in sorted(
        report["edges"].items(), key=lambda kv: -kv[1]["mean_ms"]
    ):
        lines.append(
            f"{edge:<14} {s['mean_ms']:>9} {s['p90_ms']:>9} {s['max_ms']:>9} "
            f"{shares.get(edge, 0):>6.1%}"
        )
    lines.append(
        "top cost centers: " + ", ".join(report["top_cost_centers"])
    )
    return "\n".join(lines)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "paths", nargs="+",
        help="telemetry stream files, or directories containing "
        "telemetry-*.jsonl",
    )
    p.add_argument("--top", type=int, default=5, help="slowest batches kept")
    p.add_argument(
        "--clients", nargs="*", default=None,
        help="client log files or directories (joins the sampled client "
        "submit timestamps as an extra leading edge)",
    )
    p.add_argument(
        "--no-align", action="store_true",
        help="skip causality-based clock-offset estimation",
    )
    p.add_argument("--output", help="write the JSON report here")
    args = p.parse_args()

    paths: list[str] = []
    for path in args.paths:
        if os.path.isdir(path):
            paths.extend(sorted(glob.glob(os.path.join(path, "telemetry-*.jsonl"))))
        else:
            paths.append(path)
    if not paths:
        print("no telemetry streams found", file=sys.stderr)
        sys.exit(2)
    client_paths: list[str] | None = None
    if args.clients is not None:
        client_paths = []
        for path in args.clients:
            if os.path.isdir(path):
                client_paths.extend(
                    sorted(glob.glob(os.path.join(path, "client-*.log")))
                )
            else:
                client_paths.append(path)

    report = assemble(
        paths, align=not args.no_align, top=args.top,
        client_paths=client_paths,
    )
    print(_human(report))
    if args.output:
        os.makedirs(
            os.path.dirname(os.path.abspath(args.output)), exist_ok=True
        )
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"report written to {args.output}")
    if not report["batches"]:
        print("no batch lifelines were assembled", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
