"""Diagnostic for the in-process committee protocol benchmark.

Boots the same N-validator committee as ``committee_scale --mode protocol``
but keeps handles on every Core and samples progress every few seconds:
per-node round spread, merged-queue depths, commit counts, and asyncio task
count. Used to triage the N=40 stall (round-2 ROADMAP OPEN item).

    python -m benchmark.diag_protocol --nodes 40 --seconds 60
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def run(n: int, seconds: float, base_port: int, timeout_delay: int):
    from hotstuff_tpu.consensus import Authority, Committee, Parameters
    from hotstuff_tpu.consensus.consensus import Consensus
    from hotstuff_tpu.crypto import SignatureService, generate_keypair
    from hotstuff_tpu.store import Store

    keys = [generate_keypair() for _ in range(n)]
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", base_port + i))
            for i, (pk, _) in enumerate(keys)
        }
    )
    params = Parameters(timeout_delay=timeout_delay, batch_vote_verification=True)

    engines, commit_counts, sinks, cores = [], [], [], []
    t_spawn0 = time.perf_counter()
    for idx, (pk, sk) in enumerate(keys):
        rx_mempool: asyncio.Queue = asyncio.Queue()
        tx_mempool: asyncio.Queue = asyncio.Queue()
        tx_commit: asyncio.Queue = asyncio.Queue()
        counter = [0]

        async def drain_mem(q=tx_mempool):
            while True:
                await q.get()

        async def drain_commit(q=tx_commit, c=counter):
            while True:
                await q.get()
                c[0] += 1

        sinks.append(asyncio.create_task(drain_mem()))
        sinks.append(asyncio.create_task(drain_commit()))
        eng = await Consensus.spawn(
            pk, committee, params, SignatureService(sk), Store(),
            rx_mempool, tx_mempool, tx_commit,
        )
        engines.append(eng)
        commit_counts.append(counter)
    print(f"spawned {n} engines in {time.perf_counter() - t_spawn0:.1f}s", flush=True)

    # Reach into the Core objects via the coro frames of their tasks.
    for eng in engines:
        core_task = eng.tasks[0]
        core = core_task.get_coro().cr_frame.f_locals.get("self")
        cores.append(core)

    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        await asyncio.sleep(5)
        rounds = [c.round if c is not None else -1 for c in cores]
        queues = [c.rx_message.qsize() if c is not None else -1 for c in cores]
        commits = [c[0] for c in commit_counts]
        print(
            f"t={time.perf_counter() - t0:5.1f}s "
            f"round min/med/max={min(rounds)}/{sorted(rounds)[n // 2]}/{max(rounds)} "
            f"queue max={max(queues)} sum={sum(queues)} "
            f"commits min/max={min(commits)}/{max(commits)} "
            f"tasks={len(asyncio.all_tasks())}",
            flush=True,
        )

    for e in engines:
        await e.shutdown()
    for s in sinks:
        s.cancel()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=40)
    p.add_argument("--seconds", type=float, default=60)
    p.add_argument("--base-port", type=int, default=19000)
    p.add_argument("--timeout", type=int, default=30_000)
    args = p.parse_args()
    asyncio.run(run(args.nodes, args.seconds, args.base_port, args.timeout))


if __name__ == "__main__":
    main()
