"""Watchtower smoke + overhead gate (the live-detection sibling of
``benchmark/profile_smoke.py``).

Runs the one-process committee bench twice per repeat — telemetry
streaming in BOTH legs (that budget is already paid and gated by
``telemetry_smoke``), watchtower DETACHED vs ATTACHED (a
:class:`benchmark.watchtower.DirectoryWatch` tail-following the stream
and scoring every peer while the committee runs) — and gates:

1. the attached leg actually ingested the stream (records > 0) and
   scored rounds (frontier advanced);
2. **zero alerts on the fault-free run** — the detectors' false-positive
   gate at the exact config the soaks run with;
3. measured overhead within ``--budget`` (default 1%): min-over-repeats
   with alternating order, the same noise-robust estimator the other
   smoke lanes use. Each leg runs in a FRESH subprocess (the native
   transport accumulates process-wide state; see profile_smoke).

Exit 0 on pass, 1 on ingest/alert failure, 2 on budget failure.

    python -m benchmark.watchtower_smoke --nodes 10 --rounds 20
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402


def _run_once(
    n: int,
    rounds: int,
    base_port: int,
    with_watch: bool,
    snap_path: str,
):
    from benchmark.committee_scale import run_committee
    from benchmark.watchtower import DirectoryWatch
    from hotstuff_tpu import telemetry

    telemetry.reset_for_tests()
    telemetry.enable()
    watch = None
    if with_watch:
        watch = DirectoryWatch(
            os.path.dirname(os.path.abspath(snap_path)),
            pattern=os.path.basename(snap_path),
            alerts_path=snap_path + ".alerts.jsonl",
        )
        watch.start()
    try:
        per_round, _ = asyncio.run(
            run_committee(
                n, rounds, base_port, timeout_delay=30_000,
                telemetry_path=snap_path,
            )
        )
    finally:
        if watch is not None:
            watch.stop()
        telemetry.disable()
    result = {"per_round": per_round, "alerts": 0, "records": 0, "rounds": 0}
    if watch is not None:
        board = watch.scoreboard()
        result.update(
            alerts=len(watch.alerts()),
            records=watch.stats()["records"],
            rounds=board["rounds"],
            frontier=board["frontier"],
        )
    return result


def _spawn_once(
    n: int, rounds: int, base_port: int, with_watch: bool, snap_path: str
):
    """One measurement leg in a fresh subprocess (see profile_smoke for
    why in-process repeats bias the estimator)."""
    cmd = [
        sys.executable, "-m", "benchmark.watchtower_smoke", "--one-shot",
        "--nodes", str(n), "--rounds", str(rounds),
        "--base-port", str(base_port), "--snap", snap_path,
    ]
    if with_watch:
        cmd.append("--watch-on")
    proc = subprocess.run(
        cmd, capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"one-shot leg failed (rc={proc.returncode}):\n{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=10)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument(
        "--budget",
        type=float,
        default=float(os.environ.get("HOTSTUFF_WATCHTOWER_BUDGET", "0.01")),
        help="max allowed relative overhead (default 0.01 = 1%%)",
    )
    p.add_argument("--base-port", type=int, default=20500)
    p.add_argument("--output", help="file to append the result summary to")
    # Internal: one measurement leg (see _spawn_once).
    p.add_argument("--one-shot", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--watch-on", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--snap", help=argparse.SUPPRESS)
    args = p.parse_args()

    os.environ.setdefault("HOTSTUFF_TELEMETRY_INTERVAL", "1")
    os.environ.setdefault("HOTSTUFF_CRYPTO_WORKERS", "32")

    if args.one_shot:
        print(
            json.dumps(
                _run_once(
                    args.nodes, args.rounds, args.base_port,
                    args.watch_on, args.snap,
                )
            )
        )
        return

    snap_dir = tempfile.mkdtemp(prefix="hotstuff_watchtower_smoke_")
    off_times: list[float] = []
    on_times: list[float] = []
    total_alerts = 0
    total_records = 0
    scored_rounds = 0
    port = args.base_port

    # Discarded warm-up (one-time costs must not land on either side).
    _spawn_once(
        args.nodes, max(2, args.rounds // 4), port, False,
        os.path.join(snap_dir, "telemetry-warmup.jsonl"),
    )
    port += 2 * args.nodes

    for rep in range(args.repeats):
        order = (False, True) if rep % 2 == 0 else (True, False)
        for with_watch in order:
            snap_path = os.path.join(
                snap_dir,
                f"telemetry-{'on' if with_watch else 'off'}-{rep}.jsonl",
            )
            result = _spawn_once(
                args.nodes, args.rounds, port, with_watch, snap_path
            )
            port += 2 * args.nodes
            if with_watch:
                on_times.append(result["per_round"])
                total_alerts += result["alerts"]
                total_records += result["records"]
                scored_rounds += result["rounds"]
            else:
                off_times.append(result["per_round"])

    problems: list[str] = []
    if total_records == 0:
        problems.append("attached watchtower ingested zero stream records")
    if scored_rounds == 0:
        problems.append("attached watchtower scored zero rounds")
    if total_alerts:
        problems.append(
            f"{total_alerts} alert(s) fired on fault-free runs — "
            "false positives"
        )

    best_off = min(off_times)
    best_on = min(on_times)
    overhead = (best_on - best_off) / best_off

    result = {
        "metric": f"watchtower_overhead_n{args.nodes}",
        "host": host_meta(),
        "off_ms_per_round": round(best_off * 1e3, 2),
        "on_ms_per_round": round(best_on * 1e3, 2),
        "overhead": round(overhead, 4),
        "budget": args.budget,
        "alerts": total_alerts,
        "records": total_records,
        "scored_rounds": scored_rounds,
        "problems": problems,
    }
    print(json.dumps(result))

    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
        with open(args.output, "a") as f:
            f.write(json.dumps(result) + "\n")

    if problems:
        print(f"FAIL: {problems}", file=sys.stderr)
        sys.exit(1)
    if overhead > args.budget:
        print(
            f"FAIL: watchtower overhead {overhead:.2%} exceeds the "
            f"{args.budget:.2%} budget",
            file=sys.stderr,
        )
        sys.exit(2)
    print(
        f"PASS: watchtower overhead {overhead:+.2%} within "
        f"{args.budget:.2%}; {total_records} record(s) ingested, "
        f"{scored_rounds} round(s) scored, 0 alerts"
    )


if __name__ == "__main__":
    main()
