"""Remote (multi-host) benchmark orchestration (reference
``benchmark/benchmark/remote.py``).

The reference drives AWS hosts over Fabric SSH; this environment has no
fabric/boto3, so orchestration uses plain ``ssh``/``scp`` subprocesses with
the same flow (``remote.py:58-235``):

  install -> update -> config (generate keys/committee locally, upload)
  -> run (boot clients then nodes, sleep, kill) -> logs (download, parse)

Hosts come from ``Settings`` + an explicit host list (or the AWS
InstanceManager when boto3 is available). Crash-fault runs skip booting
the last ``faults`` hosts (``remote.py:273-275``).
"""

from __future__ import annotations

import os
import subprocess
import time

from hotstuff_tpu.consensus import Authority as CAuth
from hotstuff_tpu.consensus import Committee as CCommittee
from hotstuff_tpu.consensus import Parameters as CParams
from hotstuff_tpu.mempool import Authority as MAuth
from hotstuff_tpu.mempool import Committee as MCommittee
from hotstuff_tpu.mempool import Parameters as MParams
from hotstuff_tpu.node.config import Committee, Parameters, Secret

from .logs import LogParser
from .settings import Settings
from .utils import PathMaker, Print


class BenchError(Exception):
    pass


class SshRunner:
    """Host access over plain ``ssh``/``scp`` subprocesses — the
    real-cluster transport (reference drives Fabric SSH the same way)."""

    def __init__(self, settings: Settings) -> None:
        self.settings = settings

    def exec(self, host: str, command: str, check: bool = True):
        return subprocess.run(
            [
                "ssh",
                "-i",
                self.settings.key_path,
                "-o",
                "StrictHostKeyChecking=no",
                f"ubuntu@{host}",
                command,
            ],
            check=check,
            capture_output=True,
            text=True,
        )

    def put(self, host: str, local: str, remote: str) -> None:
        subprocess.run(
            [
                "scp",
                "-i",
                self.settings.key_path,
                "-o",
                "StrictHostKeyChecking=no",
                local,
                f"ubuntu@{host}:{remote}",
            ],
            check=True,
            capture_output=True,
        )

    def get(self, host: str, remote: str, local: str) -> None:
        subprocess.run(
            [
                "scp",
                "-i",
                self.settings.key_path,
                "-o",
                "StrictHostKeyChecking=no",
                f"ubuntu@{host}:{remote}",
                local,
            ],
            check=True,
            capture_output=True,
        )

    def provision(self, host: str) -> None:
        """python + a clone of the repo (reference ``remote.py:58-83``
        installs rust; we install the python package)."""
        cmd = " && ".join(
            [
                "sudo apt-get update",
                "sudo apt-get -y install python3 python3-pip git",
                f"(git clone {self.settings.repo_url} || true)",
            ]
        )
        self.exec(host, cmd)


class RemoteBench:
    def __init__(
        self, settings: Settings, hosts: list[str], runner=None
    ) -> None:
        self.settings = settings
        self.hosts = hosts
        # Pluggable host transport: SshRunner for real clusters;
        # benchmark.netns.NetnsRunner gives each "host" its own kernel
        # network stack on one machine (real TCP over veth/bridge, real
        # process boot/kill, real log collection) when no ssh exists.
        self.runner = runner if runner is not None else SshRunner(settings)

    # -- ssh plumbing (kept as thin aliases; flow code reads better) --------

    def _ssh(self, host: str, command: str, check: bool = True):
        return self.runner.exec(host, command, check=check)

    def _upload(self, host: str, local: str, remote: str) -> None:
        self.runner.put(host, local, remote)

    def _download(self, host: str, remote: str, local: str) -> None:
        self.runner.get(host, remote, local)

    # -- benchmark flow -----------------------------------------------------

    def install(self) -> None:
        """Provision every host (reference ``remote.py:58-83``)."""
        for host in self.hosts:
            self.runner.provision(host)
            Print.info(f"installed on {host}")

    def update(self) -> None:
        """git pull on every host (reference ``remote.py:117-128``)."""
        repo = self.settings.repo_name
        cmd = f"cd {repo} && git fetch && git checkout {self.settings.branch} && git pull"
        for host in self.hosts:
            self._ssh(host, cmd)

    def config(self, work_dir: str = ".remote-bench", node_params: Parameters | None = None):
        """Generate keys + committee locally, upload to every host
        (reference ``remote.py:130-175``)."""
        os.makedirs(work_dir, exist_ok=True)
        secrets = [Secret.new() for _ in self.hosts]
        consensus = CCommittee(
            authorities={
                s.name: CAuth(stake=1, address=(h, self.settings.consensus_port))
                for s, h in zip(secrets, self.hosts)
            }
        )
        mempool = MCommittee(
            authorities={
                s.name: MAuth(
                    stake=1,
                    transactions_address=(h, self.settings.front_port),
                    mempool_address=(h, self.settings.mempool_port),
                )
                for s, h in zip(secrets, self.hosts)
            }
        )
        committee_file = os.path.join(work_dir, "committee.json")
        Committee(consensus, mempool).write(committee_file)
        params_file = os.path.join(work_dir, "parameters.json")
        (node_params or Parameters(CParams(), MParams())).write(params_file)

        key_files = []
        for i, s in enumerate(secrets):
            kf = os.path.join(work_dir, f"node_{i}.json")
            s.write(kf)
            key_files.append(kf)

        for i, host in enumerate(self.hosts):
            self._ssh(host, "mkdir -p bench", check=False)
            self._upload(host, committee_file, "bench/committee.json")
            self._upload(host, params_file, "bench/parameters.json")
            self._upload(host, key_files[i], "bench/key.json")
        return committee_file

    def kill(self) -> None:
        for host in self.hosts:
            self._ssh(host, "pkill -f hotstuff_tpu || true", check=False)

    def run(
        self,
        rate: int,
        tx_size: int,
        duration: int,
        faults: int = 0,
        timeout_delay: int = 5_000,
        node_env: str = "",
    ) -> LogParser:
        """Boot clients then nodes, sleep for the duration, kill, download
        and parse logs (reference ``remote.py:177-235``). ``node_env`` is
        a shell ``VAR=value ...`` prefix applied to the node processes
        (e.g. ``HOTSTUFF_FAULTLINE=~/bench/chaos.json`` arms fault
        injection on every host)."""
        self.kill()
        repo = self.settings.repo_name
        booted = self.hosts[: len(self.hosts) - faults]
        node_addrs = " ".join(
            f"{h}:{self.settings.front_port}" for h in booted
        )
        env_prefix = f"{node_env} " if node_env else ""
        for host in booted:
            client = (
                f"cd {repo} && nohup python3 -m hotstuff_tpu.node.client "
                f"{host}:{self.settings.front_port} --size {tx_size} "
                f"--rate {rate // len(booted)} --timeout {timeout_delay} "
                f"--nodes {node_addrs} > /dev/null 2> ~/bench/client.log &"
            )
            self._ssh(host, client)
        for host in booted:
            node = (
                f"cd {repo} && {env_prefix}nohup python3 -m hotstuff_tpu.node run "
                f"--keys ~/bench/key.json --committee ~/bench/committee.json "
                f"--store ~/bench/db --parameters ~/bench/parameters.json "
                f"> /dev/null 2> ~/bench/node.log &"
            )
            self._ssh(host, node)

        time.sleep(2 * timeout_delay / 1000 + duration)
        self.kill()

        logs_dir = PathMaker.logs_path()
        os.makedirs(logs_dir, exist_ok=True)
        for i, host in enumerate(booted):
            self._download(host, "~/bench/client.log", PathMaker.client_log_file(i))
            self._download(host, "~/bench/node.log", PathMaker.node_log_file(i))
        return LogParser.process(logs_dir, faults=faults)
