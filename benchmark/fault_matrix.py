"""Fault matrix: the acceptance sweep behind the Robustness claims.

Runs the three canonical fault classes against a live in-process
committee — **f crash faults** (kill f nodes uncleanly, restart them on
their stores), **minority partition + heal** (isolate f nodes; the
majority must keep committing, the minority must catch up), and
**delay+duplicate+reorder** (every link impaired at once) — on BOTH
transport planes (asyncio and the native C++ engine), gating each run on
the invariant checker: safety=ok and liveness=recovered. One JSON
artifact records every verdict, the injected-fault counts, and the
measured post-heal recovery cost (``liveness.recovery_s``).

Plane selection must happen before ``hotstuff_tpu.network`` first
imports (``HOTSTUFF_NET`` is read at import time), so the matrix
re-executes itself per plane as a subprocess.

    python -m benchmark.fault_matrix --nodes 20 --output results
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402


def build_scenarios(n: int, duration: float):
    """The three acceptance scenarios, parameterized by committee size
    (f = (n-1)//3). Fixed seeds: the schedules — and therefore the whole
    runs' fault timelines — are reproducible artifacts."""
    from hotstuff_tpu.faultline import Scenario

    f = max(1, (n - 1) // 3)
    crash_events = []
    for k in range(f):
        # Stagger the kills across the middle of the run; every victim
        # restarts before 0.8*duration so liveness is judged fault-free.
        at = round(0.2 * duration + k * (0.4 * duration / f), 3)
        crash_events.append({"kind": "crash", "node": k, "at": at})
        crash_events.append(
            {"kind": "restart", "node": k, "at": round(min(at + 0.25 * duration, 0.8 * duration), 3)}
        )
    return [
        Scenario(
            name=f"crash-f{f}", seed=501, duration_s=duration,
            events=crash_events,
        ),
        Scenario(
            name="minority-partition", seed=502, duration_s=duration,
            events=[
                {
                    "kind": "partition",
                    "groups": [list(range(f)), list(range(f, n))],
                    "at": round(0.3 * duration, 3),
                    "until": round(0.6 * duration, 3),
                }
            ],
        ),
        Scenario(
            name="delay-dup-reorder", seed=503, duration_s=duration,
            events=[
                {
                    "kind": "link", "src": "*", "dst": "*",
                    "at": round(0.2 * duration, 3),
                    "until": round(0.7 * duration, 3),
                    "drop": 0.05, "delay_ms": [5, 40],
                    "duplicate": 0.1, "reorder": 0.1,
                }
            ],
        ),
    ]


def run_plane(args) -> dict:
    """Worker: run the matrix on the CURRENT plane (this process's
    already-imported transport) and return {scenario: verdict}."""
    from hotstuff_tpu import telemetry
    from hotstuff_tpu.faultline import run_scenario
    from hotstuff_tpu.telemetry import slo as slo_mod

    telemetry.enable()
    # Chaos-appropriate SLOs evaluated on each run's final cumulative
    # snapshot: round latency p99 (clean rounds only — faulted rounds
    # have their own histogram) and the whole-run view-change rate.
    # Thresholds are deliberately loose: the matrix's hard gate stays the
    # invariant checker; the SLO section quantifies degradation.
    chaos_specs = [
        slo_mod.SloSpec(
            "p99_round_commit_ms", "quantile",
            "consensus.span.propose_to_commit_ms", q=0.99, max=15_000.0,
        ),
        slo_mod.SloSpec(
            "timeouts_per_round", "ratio",
            "consensus.timeouts_fired", per="consensus.rounds_advanced",
            max=2.0,
        ),
    ]
    out: dict[str, dict] = {}
    base = args.base_port
    for scenario in build_scenarios(args.nodes, args.duration):
        import time as _time

        # Window the registry around THIS scenario: the process registry
        # is cumulative across the matrix's scenarios, and each verdict
        # must judge only its own run.
        before = dict(telemetry.get_registry().snapshot(), ts=_time.time())
        result = asyncio.run(
            run_scenario(
                scenario,
                args.nodes,
                base_port=base,
                timeout_delay=args.timeout,
                recovery_timeout_s=90.0,
            )
        )
        after = dict(result["telemetry"], ts=_time.time())
        base += args.nodes + 16
        verdict = result["verdict"]
        verdict["slo"] = slo_mod.evaluate(
            [before, after], chaos_specs, source=scenario.name
        )
        verdict["flight_record"] = result.get("flight_record")
        out[scenario.name] = verdict
        status = (
            "ok"
            if verdict["safety"]["ok"] and verdict["liveness"]["recovered"]
            else "FAILED"
        )
        print(
            f"[{args.plane}] {scenario.name}: {status} "
            f"recovery_s={verdict['liveness']['recovery_s']} "
            f"injections={verdict['injections']['counts']}",
            file=sys.stderr,
        )
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=20)
    p.add_argument("--duration", type=float, default=12.0)
    p.add_argument("--timeout", type=int, default=1_000)
    p.add_argument("--base-port", type=int, default=23000)
    p.add_argument(
        "--planes", default="asyncio,native",
        help="comma-separated transport planes to sweep",
    )
    p.add_argument("--output", help="directory for the JSON artifact")
    p.add_argument(
        "--plane", help=argparse.SUPPRESS  # worker mode: a single plane
    )
    args = p.parse_args()

    if args.plane:
        json.dump(run_plane(args), sys.stdout)
        return

    report: dict = {"nodes": args.nodes, "host": host_meta(), "planes": {}}
    ok = True
    for plane in args.planes.split(","):
        env = dict(os.environ)
        env["HOTSTUFF_NET"] = "native" if plane == "native" else ""
        proc = subprocess.run(
            [
                sys.executable, "-m", "benchmark.fault_matrix",
                "--plane", plane,
                "--nodes", str(args.nodes),
                "--duration", str(args.duration),
                "--timeout", str(args.timeout),
                "--base-port", str(args.base_port),
            ],
            env=env,
            capture_output=True,
            text=True,
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            print(f"plane {plane} worker failed:\n{proc.stdout}")
            ok = False
            continue
        verdicts = json.loads(proc.stdout)
        report["planes"][plane] = verdicts
        for name, v in verdicts.items():
            if not (v["safety"]["ok"] and v["liveness"]["recovered"]):
                ok = False
                print(f"FAILED: {plane}/{name}: {json.dumps(v, indent=2)}")

    print(
        f"fault matrix N={args.nodes}: "
        + ("all scenarios safe + recovered" if ok else "FAILURES (see above)")
    )
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        path = os.path.join(args.output, f"fault-matrix-n{args.nodes}.json")
        with open(path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"artifact written to {path}")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
