"""Seeded fault-schedule sweep on the deterministic simulation plane.

The search the asyncio planes cannot afford: every seed is a full
chaos schedule (crash/restart, partition, link impairment, byzantine
behavior) executed in virtual time on the sans-io core
(``hotstuff_tpu/sim``), checker-gated, at >=1,000 seeds per minute at
N=4 on one CPU core. Any safety/liveness violation is shrunk to a
minimal pinned reproducer (``hotstuff_tpu/sim/shrink``) and written as
a replayable artifact.

Usage:
    python -m benchmark.sim_sweep --seeds 0:1000                # search
    python -m benchmark.sim_sweep --seeds 0:500 --twins 24 --gate
    python -m benchmark.sim_sweep --seeds 0:50 --jitter 3       # 3 interleavings/seed
    python -m benchmark.sim_sweep --inject-wedge                # shrink-pipeline demo

``--gate`` exits non-zero on any genuine violation (the CI contract).
``--inject-wedge`` adds a deliberately wedged schedule (two permanent
crashes at N=4) to validate the violation->shrink->artifact pipeline
end to end; its expected violation never trips the gate.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time

from benchmark.hostinfo import host_meta
from hotstuff_tpu.faultline.policy import Scenario, chaos_scenario
from hotstuff_tpu.sim import SimWorld
from hotstuff_tpu.sim.shrink import shrink, sim_failure_probe, write_reproducer
from hotstuff_tpu.sim.twins import enumerate_twins

SCHEMA = "sim-sweep-v1"

#: the injected-violation demo: two permanent crashes wedge an N=4
#: committee below quorum; the trailing link fault extends the
#: checker's heal horizon past the crashes so the liveness window
#: actually judges the wedged tail (see docs/faultline.md).
WEDGE = {
    "name": "injected-wedge",
    "seed": 3,
    "duration_s": 8.0,
    "events": [
        {"kind": "link", "src": "?", "dst": "*", "at": 1.0, "until": 3.0,
         "drop": 0.2, "delay_ms": [5.0, 40.0]},
        {"kind": "partition", "at": 2.0, "until": 4.0},
        {"kind": "crash", "node": 1, "at": 2.5},
        {"kind": "byzantine", "node": 0, "behavior": "stale_vote_flood",
         "at": 3.0, "until": 5.0},
        {"kind": "crash", "node": 2, "at": 3.5},
        {"kind": "link", "src": "*", "dst": "?", "at": 4.0, "until": 5.5,
         "drop": 0.1, "delay_ms": [1.0, 10.0]},
    ],
}


def _violation(verdict: dict) -> str | None:
    if not verdict["safety"]["ok"]:
        return "safety"
    if not verdict["liveness"]["recovered"]:
        return "liveness"
    return None


def _run_one(scenario, n, world_kwargs, twins=None):
    world = SimWorld(scenario, n, twins=twins, **world_kwargs)
    result = world.run()
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seeds", default="0:200",
                   help="seed range lo:hi (half-open) for chaos schedules")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--duration", type=float, default=8.0,
                   help="virtual seconds per schedule")
    p.add_argument("--timeout-delay", type=int, default=1_000, help="ms")
    p.add_argument("--elector", default="",
                   help="leader elector ('' = round-robin, or 'reputation')")
    p.add_argument("--link-delay", default="25:75",
                   help="per-hop latency draw lo:hi in ms")
    p.add_argument("--jitter", type=int, default=1,
                   help="interleavings per seed (re-drawn link latencies)")
    p.add_argument("--twins", type=int, default=0,
                   help="also run this many systematic Twins scenarios")
    p.add_argument("--inject-wedge", action="store_true",
                   help="add the known-wedged demo schedule (expected "
                        "violation; exercises shrink+artifact)")
    p.add_argument("--no-shrink", action="store_true")
    p.add_argument("--max-shrink", type=int, default=5,
                   help="shrink at most this many distinct failures")
    p.add_argument("--artifacts", default="results",
                   help="directory for shrunk reproducer artifacts")
    p.add_argument("--out", default=None, help="summary JSON path")
    p.add_argument("--gate", action="store_true",
                   help="exit non-zero on any genuine violation")
    p.add_argument("--verbose", action="store_true",
                   help="keep per-round protocol warnings (timeouts, "
                        "rejected byzantine traffic) on stderr")
    args = p.parse_args(argv)

    if not args.verbose:
        # Chaos schedules make the cores warn constantly (timeouts,
        # rejected byzantine frames) — per-event noise at sweep rates.
        for name in ("consensus", "network", "faultline", "sim"):
            logging.getLogger(name).setLevel(logging.ERROR)

    lo, hi = (int(x) for x in args.seeds.split(":"))
    dlo, dhi = (float(x) for x in args.link_delay.split(":"))
    world_kwargs = dict(
        timeout_delay=args.timeout_delay,
        leader_elector=args.elector,
        link_delay_ms=(dlo, dhi),
    )

    runs = []
    failures = []
    injected_failures = []
    t0 = time.perf_counter()
    events_total = 0

    def record(scenario, n, result, *, twins=None, jitter=0, injected=False):
        nonlocal events_total
        verdict = result["verdict"]
        violation = _violation(verdict)
        events_total += result["events"]
        runs.append(
            {
                "name": scenario.name,
                "seed": scenario.seed,
                "jitter": jitter,
                "twins": bool(twins),
                "violation": violation,
                "commits": verdict["commits"],
                "recovery_s": verdict["liveness"]["recovery_s"],
            }
        )
        if violation is None:
            return
        entry = {
            "name": scenario.name,
            "seed": scenario.seed,
            "jitter": jitter,
            "violation": violation,
            "injected": injected,
            "artifact": None,
        }
        (injected_failures if injected else failures).append(entry)
        budget = args.max_shrink - len(
            [f for f in failures + injected_failures if f["artifact"]]
        )
        if args.no_shrink or budget <= 0:
            return
        probe_kwargs = dict(world_kwargs)
        probe_kwargs["jitter"] = jitter
        if twins:
            # Shrink under the same twin topology.
            def probe(sc, _tw=twins, _kw=probe_kwargs):
                v = SimWorld(sc, n, twins=_tw, **_kw).run()["verdict"]
                return _violation(v), v
        else:
            probe = sim_failure_probe(n, **probe_kwargs)
        res = shrink(scenario, probe)
        entry["artifact"] = write_reproducer(
            args.artifacts,
            res.scenario,
            n,
            res.verdict,
            trace=result["trace"],
            world={**probe_kwargs, "twins": twins or {}},
            steps=res.steps,
            tag="sim-shrunk",
        )
        entry["shrink_runs"] = res.runs
        entry["shrunk_events"] = len(res.scenario.events)
        print(
            f"  shrunk {scenario.name}: {len(scenario.events)} -> "
            f"{len(res.scenario.events)} events ({res.runs} probe runs) "
            f"-> {entry['artifact']}"
        )

    for seed in range(lo, hi):
        scenario = chaos_scenario(seed, duration_s=args.duration)
        for jitter in range(args.jitter):
            kwargs = dict(world_kwargs)
            kwargs["jitter"] = jitter
            result = _run_one(scenario, args.nodes, kwargs)
            record(scenario, args.nodes, result, jitter=jitter)

    twins_runs = 0
    for scenario, twins_map in enumerate_twins(
        args.nodes, duration_s=args.duration, limit=args.twins or None
    ):
        if args.twins <= 0:
            break
        result = _run_one(scenario, args.nodes, world_kwargs, twins=twins_map)
        record(scenario, args.nodes, result, twins=twins_map)
        twins_runs += 1

    if args.inject_wedge:
        scenario = Scenario.from_json({**WEDGE, "schema": None})
        result = _run_one(scenario, args.nodes, world_kwargs)
        record(scenario, args.nodes, result, injected=True)

    wall = time.perf_counter() - t0
    n_runs = len(runs)
    per_min = n_runs / wall * 60.0 if wall > 0 else 0.0
    summary = {
        "schema": SCHEMA,
        "host": host_meta(),
        "config": {
            "seeds": [lo, hi],
            "nodes": args.nodes,
            "duration_s": args.duration,
            "timeout_delay_ms": args.timeout_delay,
            "leader_elector": args.elector or "round-robin",
            "link_delay_ms": [dlo, dhi],
            "jitter": args.jitter,
            "twins": args.twins,
            "inject_wedge": args.inject_wedge,
        },
        "totals": {
            "runs": n_runs,
            "chaos_seeds": hi - lo,
            "twins_runs": twins_runs,
            "ok": sum(1 for r in runs if r["violation"] is None),
            "violations": len(failures),
            "injected_violations": len(injected_failures),
            "events_simulated": events_total,
            "wall_s": round(wall, 3),
            "schedules_per_min": round(per_min, 1),
        },
        "failures": failures,
        "injected": injected_failures,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
            f.write("\n")
    print(
        f"sim-sweep: {n_runs} schedules ({twins_runs} twins) in {wall:.1f}s "
        f"= {per_min:.0f}/min; {len(failures)} violations"
        + (f", {len(injected_failures)} injected" if args.inject_wedge else "")
    )
    if failures:
        for f_ in failures:
            print(f"  VIOLATION {f_['violation']}: {f_['name']} "
                  f"seed={f_['seed']} jitter={f_['jitter']} "
                  f"artifact={f_['artifact']}")
    if args.gate and failures:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
