"""CLI entry for the local benchmark (the ``fab local`` equivalent,
reference ``benchmark/fabfile.py:11-38``): boots N nodes + clients on
localhost and prints the SUMMARY block."""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.local import LocalBench  # noqa: E402


def main() -> None:
    p = argparse.ArgumentParser(description="Run a local hotstuff_tpu benchmark.")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--rate", type=int, default=1_000, help="total input rate tx/s")
    p.add_argument("--tx-size", type=int, default=512, help="transaction bytes")
    p.add_argument("--duration", type=int, default=20, help="benchmark seconds")
    p.add_argument("--faults", type=int, default=0, help="crash-faulted nodes")
    p.add_argument("--timeout", type=int, default=1_000, help="consensus timeout ms")
    p.add_argument("--batch-size", type=int, default=15_000, help="mempool batch B")
    p.add_argument("--max-batch-delay", type=int, default=10, help="ms")
    p.add_argument("--base-port", type=int, default=9000)
    p.add_argument("--work-dir", default=".bench")
    p.add_argument(
        "--workers", type=int, default=0,
        help="Conveyor worker shards per node (0 = legacy mempool only); "
        "clients switch to the sharded bundle load generator",
    )
    p.add_argument(
        "--crypto-backend",
        default="cpu",
        choices=["cpu", "tpu", "cpu-batched", "tpu-batched"],
    )
    p.add_argument(
        "--telemetry",
        action="store_true",
        help="nodes stream JSON-lines telemetry snapshots next to their "
        "logs; prints the telemetry-derived SUMMARY alongside the regex one",
    )
    p.add_argument(
        "--chaos",
        metavar="SCENARIO",
        help="faultline scenario: a JSON file, or chaos:<seed> for a "
        "seeded generated storm. Crash/restart events kill and relaunch "
        "real node processes; partition/link/byzantine events run inside "
        "each node via its env-armed fault plane. Prints the checker "
        "verdict and exits nonzero on a safety violation or liveness "
        "stall.",
    )
    args = p.parse_args()

    chaos_path = args.chaos
    if chaos_path and chaos_path.startswith("chaos:"):
        from hotstuff_tpu.faultline import chaos_scenario

        scenario = chaos_scenario(
            int(chaos_path.split(":", 1)[1]), duration_s=float(args.duration)
        )
        # NOT inside work_dir: LocalBench.run() wipes that tree before
        # loading the scenario.
        chaos_path = os.path.abspath(args.work_dir).rstrip("/") + "-scenario.json"
        scenario.save(chaos_path)

    bench = LocalBench(
        nodes=args.nodes,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        faults=args.faults,
        base_port=args.base_port,
        timeout_delay=args.timeout,
        batch_size=args.batch_size,
        max_batch_delay=args.max_batch_delay,
        work_dir=args.work_dir,
        crypto_backend=args.crypto_backend,
        telemetry=args.telemetry,
        chaos=chaos_path,
        workers=args.workers,
    )
    parser = bench.run()
    print(parser.result())
    if args.telemetry or chaos_path:
        from benchmark.logs import TelemetryParser

        print(
            TelemetryParser.process(
                os.path.join(os.path.abspath(args.work_dir), "logs"),
                tx_size=args.tx_size,
            ).result()
        )
    if bench.chaos_verdict is not None:
        import json

        v = bench.chaos_verdict
        avail = v.get("availability")
        print(
            f"chaos verdict: safety="
            f"{'ok' if v['safety']['ok'] else 'VIOLATED'} liveness="
            f"{'recovered' if v['liveness']['recovered'] else 'STALLED'}"
            + (
                f" availability={'ok' if avail['ok'] else 'VIOLATED'}"
                f" ({avail['checked']} digests @ f+1={avail['required_holders']})"
                if avail is not None
                else ""
            )
            + f" commits={v['commits']}"
        )
        out = os.path.join(os.path.abspath(args.work_dir), "chaos-verdict.json")
        with open(out, "w") as f:
            json.dump(v, f, indent=2, sort_keys=True)
        print(f"verdict written to {out}")
        if not (
            v["safety"]["ok"]
            and v["liveness"]["recovered"]
            and v.get("availability", {}).get("ok", True)
        ):
            sys.exit(1)


if __name__ == "__main__":
    main()
