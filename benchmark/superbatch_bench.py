"""Superbatching cost/benefit measurement (round-2 weak #6).

Two questions, answered with numbers:

1. **Lone-QC latency**: what does the superbatch wrapper add to a single
   isolated QC verification? (Round 2's fixed 2 ms collection window made
   this the reason the wrapper was off by default; the back-pressure
   design should make it ~zero.)
2. **Contended throughput**: committee-1000 vote-rate regime — many
   concurrent QC verifications from worker threads (the crypto bridge's
   executor). How much does fusion amortize, and what fusion ratio is
   achieved?

Appends to ``results/superbatch-bench-<backend>.txt`` with ``--output``.

    python -m benchmark.superbatch_bench --output results
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_qc_batches(n_qcs: int, qc_size: int, seed: int = 5):
    from hotstuff_tpu.crypto import ed25519_ref as ref

    rng = random.Random(seed)
    out = []
    for _ in range(n_qcs):
        msgs, pubs, sigs = [], [], []
        digest = rng.randbytes(32)
        for _ in range(qc_size):
            sk = rng.randbytes(32)
            pubs.append(ref.secret_to_public(sk))
            msgs.append(digest)
            sigs.append(ref.sign(sk, digest))
        out.append((msgs, pubs, sigs))
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", help="directory to append the result file to")
    p.add_argument("--qc-size", type=int, default=67, help="2f+1 at N=100")
    p.add_argument("--qcs", type=int, default=24)
    p.add_argument("--threads", type=int, default=8)
    args = p.parse_args()

    from hotstuff_tpu.crypto import get_backend, set_backend
    from hotstuff_tpu.crypto.batching import BatchingBackend

    set_backend(os.environ.get("HOTSTUFF_CRYPTO_BACKEND", "cpu"))
    inner = get_backend()
    wrapped = BatchingBackend(inner)

    lines = [f"qc_size={args.qc_size} qcs={args.qcs} threads={args.threads} inner={inner.name}"]

    # 1. Lone-QC latency, plain vs wrapped (median of 30).
    (lone,) = make_qc_batches(1, args.qc_size, seed=7)
    for name, backend in (("plain", inner), ("superbatch", wrapped)):
        backend.verify_batch(*lone)  # warm
        samples = []
        for _ in range(30):
            t0 = time.perf_counter()
            backend.verify_batch(*lone)
            samples.append(time.perf_counter() - t0)
        med = sorted(samples)[len(samples) // 2]
        lines.append(f"lone-QC {name}: {med * 1e3:.3f} ms median")
        print(lines[-1], flush=True)

    # 2. Contended throughput: N concurrent QC verifications.
    qcs = make_qc_batches(args.qcs, args.qc_size, seed=8)
    for name, backend in (("plain", inner), ("superbatch", wrapped)):
        with ThreadPoolExecutor(args.threads) as ex:
            list(ex.map(lambda q: backend.verify_batch(*q), qcs))  # warm
            t0 = time.perf_counter()
            list(ex.map(lambda q: backend.verify_batch(*q), qcs))
            dt = time.perf_counter() - t0
        total_sigs = args.qcs * args.qc_size
        line = (
            f"contended {name}: {dt * 1e3:.1f} ms for {args.qcs} QCs "
            f"({dt / total_sigs * 1e6:.2f} us/sig)"
        )
        if name == "superbatch":
            line += (
                f" fusion: {wrapped.fused_requests} requests in "
                f"{wrapped.inner_calls} inner calls"
            )
        lines.append(line)
        print(line, flush=True)

    if args.output:
        os.makedirs(args.output, exist_ok=True)
        path = os.path.join(args.output, f"superbatch-bench-{inner.name}.txt")
        with open(path, "a") as f:
            f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
