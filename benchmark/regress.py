"""Perf-regression gate: fresh telemetry-derived measurements vs the
committed baseline corpus, with noise-aware thresholds.

Two checks, each min-over-repeats (the CI-stable estimator — scheduler
noise inflates individual runs, a real regression shifts the minimum):

- **protocol**: one-process committee round rate at ``--nodes`` under
  the CURRENT backend/transport selection, compared against the best
  committed ``results/committee-protocol-*.txt`` row with the same
  (committee, backend, transport) key. The run streams telemetry and the
  artifact records the registry-derived context (rounds advanced, QCs
  formed, votes batched) alongside the wall number, so a regression
  comes with its first diagnostic attached.
- **crypto**: CPU batch-verify µs/sig at ``--sigs`` (the committed
  BENCH_r0*.json shape: RLC + MSM through the native engine), compared
  against the best committed ``cpu_batch_us``.

A check fails when ``fresh_min > baseline_min * (1 + tolerance)``.
``--tolerance`` defaults to 0.5: the committed corpus was measured on an
idle box, CI shares cores — the gate catches the silent 2× rots, not 5%
drift. Exit 0 green / 1 regression / 2 usage error.

    python -m benchmark.regress --output results
    HOTSTUFF_NET=native HOTSTUFF_CRYPTO_BACKEND=cpu-batched \
        python -m benchmark.regress --nodes 100 --tolerance 0.35
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
from benchmark.hostinfo import host_meta  # noqa: E402

REGRESS_SCHEMA = "hotstuff-regress-v1"

_PROTOCOL_LINE = re.compile(
    r"committee=(\d+) .*mode=protocol.*backend=(\S+?)"
    r"(?: transport=(\w+))?: ([\d.]+) ms/round"
)


def load_protocol_baselines(results_dir: str) -> list[dict]:
    """Every committed protocol row: {nodes, backend, transport, ms}."""
    rows: list[dict] = []
    for fn in sorted(
        glob.glob(os.path.join(results_dir, "committee-protocol-*.txt"))
    ):
        with open(fn) as f:
            for line in f:
                m = _PROTOCOL_LINE.search(line)
                if m:
                    rows.append(
                        {
                            "nodes": int(m.group(1)),
                            "backend": m.group(2),
                            "transport": m.group(3),  # None on old rows
                            "ms_per_round": float(m.group(4)),
                            "source": os.path.basename(fn),
                        }
                    )
    return rows


def best_protocol_baseline(
    rows: list[dict], nodes: int, backend: str, transport: str
) -> dict | None:
    """Best committed row for this config. Rows predating the transport
    tag match any transport (they were measured before the tag existed —
    better a loose baseline than none)."""
    matches = [
        r
        for r in rows
        if r["nodes"] == nodes
        and r["backend"] == backend
        and r["transport"] in (transport, None)
    ]
    exact = [r for r in matches if r["transport"] == transport]
    pool = exact or matches
    return min(pool, key=lambda r: r["ms_per_round"]) if pool else None


def load_crypto_baseline(repo_root: str) -> dict | None:
    """Best committed CPU batch µs/sig across the BENCH_r0*.json rounds."""
    best = None
    for fn in sorted(glob.glob(os.path.join(repo_root, "BENCH_r0*.json"))):
        try:
            with open(fn) as f:
                parsed = json.load(f).get("parsed") or {}
        except (OSError, json.JSONDecodeError):
            continue
        us = parsed.get("cpu_batch_us")
        if us is None:
            continue
        if best is None or us < best["cpu_batch_us"]:
            best = {"cpu_batch_us": us, "source": os.path.basename(fn)}
    return best


def measure_protocol(
    nodes: int, rounds: int, repeats: int, base_port: int, pyprof: bool = False
):
    """(min ms/round, telemetry context) for the current stack. With
    ``pyprof`` the sampling profiler runs across the repeats and the
    context gains the top self-time functions — a regression artifact
    then carries its own first function-level diagnosis."""
    from benchmark.committee_scale import run_committee
    from hotstuff_tpu import telemetry
    from hotstuff_tpu.telemetry import profiler as pyprof_mod

    telemetry.enable()
    registry = telemetry.get_registry()
    profiler = None
    if pyprof:
        profiler = pyprof_mod.SamplingProfiler()
        profiler.start(mode="auto")
    best = float("inf")
    port = base_port
    before = registry.snapshot()["counters"]
    try:
        for _ in range(repeats):
            per_round, _ = asyncio.run(
                run_committee(nodes, rounds, port, timeout_delay=30_000)
            )
            best = min(best, per_round)
            port += 2 * nodes
    finally:
        if profiler is not None:
            profiler.stop()
    deltas = telemetry.diff_counters(before, registry.snapshot()["counters"])
    context = {
        k: v
        for k, v in deltas.items()
        if k in (
            "consensus.rounds_advanced",
            "consensus.qcs_formed",
            "consensus.votes_received",
            "consensus.blocks_committed",
            "consensus.span.evicted_rounds",
        )
    }
    if profiler is not None:
        self_c, _cum, _ = profiler.self_cum()
        total = sum(self_c.values()) or 1
        context["profile_top"] = [
            {"fn": fn, "self_share": round(n / total, 4)}
            for fn, n in self_c.most_common(10)
        ]
        context["profile_samples"] = profiler.samples
    return best * 1e3, context


def measure_crypto(sigs: int, repeats: int) -> float:
    """Min µs/sig of the CPU batch verify at the committed bench shape."""
    import bench as headline_bench

    msgs, pubs, sigs_ = headline_bench.make_batch(sigs)
    best = float("inf")
    for _ in range(repeats):
        best = min(best, headline_bench.bench_cpu_batch(msgs, pubs, sigs_))
    return best / sigs * 1e6


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=40)
    p.add_argument("--rounds", type=int, default=12)
    p.add_argument("--repeats", type=int, default=2)
    p.add_argument("--sigs", type=int, default=1343)
    p.add_argument(
        "--tolerance",
        type=float,
        default=float(os.environ.get("HOTSTUFF_REGRESS_TOLERANCE", "0.5")),
        help="allowed relative slowdown vs baseline (0.5 = +50%%)",
    )
    p.add_argument("--base-port", type=int, default=25000)
    p.add_argument("--skip-protocol", action="store_true")
    p.add_argument("--skip-crypto", action="store_true")
    p.add_argument(
        "--dataplane", type=int, default=0, metavar="RATE",
        help="also gate the Conveyor sharded-ingest e2e TPS at this "
        "offered rate (tx/s) against the committed dataplane sweep "
        "artifact (offered-rate-aware floor)",
    )
    p.add_argument("--dataplane-workers", type=int, default=1)
    p.add_argument("--dataplane-duration", type=int, default=15)
    p.add_argument(
        "--dataplane-parity", type=int, default=0, metavar="RATE",
        help="paired small-frame legs at this offered rate: one asyncio, "
        "one native (subprocesses inherit HOTSTUFF_NET per leg); fails "
        "when native e2e TPS drops below asyncio's minus the tolerance",
    )
    p.add_argument(
        "--parity-size", type=int, default=1024,
        help="tx size (B) for the --dataplane-parity legs",
    )
    p.add_argument(
        "--pyprof", action="store_true",
        help="sample the protocol measurement and attach the top "
        "self-time functions to the artifact (a red gate then names "
        "its own suspects)",
    )
    p.add_argument("--output", help="directory for the JSON artifact")
    args = p.parse_args()

    if (
        args.skip_protocol
        and args.skip_crypto
        and not args.dataplane
        and not args.dataplane_parity
    ):
        print("nothing to check", file=sys.stderr)
        sys.exit(2)

    checks: list[dict] = []

    if not args.skip_protocol:
        os.environ.setdefault("HOTSTUFF_CRYPTO_WORKERS", "32")
        from hotstuff_tpu import network as _network
        from hotstuff_tpu.crypto import get_backend

        backend = get_backend().name
        transport = (
            "native" if "Native" in _network.Receiver.__name__ else "asyncio"
        )
        rows = load_protocol_baselines(os.path.join(REPO_ROOT, "results"))
        baseline = best_protocol_baseline(rows, args.nodes, backend, transport)
        fresh_ms, context = measure_protocol(
            args.nodes, args.rounds, args.repeats, args.base_port,
            pyprof=args.pyprof,
        )
        check = {
            "metric": f"protocol_ms_per_round_n{args.nodes}",
            "backend": backend,
            "transport": transport,
            "fresh": round(fresh_ms, 1),
            "telemetry": context,
        }
        if baseline is None:
            check.update(status="no-baseline", ok=True)
        else:
            limit = baseline["ms_per_round"] * (1 + args.tolerance)
            check.update(
                status="compared",
                baseline=baseline["ms_per_round"],
                baseline_source=baseline["source"],
                limit=round(limit, 1),
                ratio=round(fresh_ms / baseline["ms_per_round"], 3),
                ok=fresh_ms <= limit,
            )
        checks.append(check)

    if not args.skip_crypto:
        baseline = load_crypto_baseline(REPO_ROOT)
        fresh_us = measure_crypto(args.sigs, max(2, args.repeats))
        check = {
            "metric": f"crypto_cpu_batch_us_per_sig_{args.sigs}sigs",
            "fresh": round(fresh_us, 2),
        }
        if baseline is None:
            check.update(status="no-baseline", ok=True)
        else:
            limit = baseline["cpu_batch_us"] * (1 + args.tolerance)
            check.update(
                status="compared",
                baseline=baseline["cpu_batch_us"],
                baseline_source=baseline["source"],
                limit=round(limit, 2),
                ratio=round(fresh_us / baseline["cpu_batch_us"], 3),
                ok=fresh_us <= limit,
            )
        checks.append(check)

    if args.dataplane:
        from benchmark.dataplane_sweep import best_committed_tps, run_point

        row = run_point(
            args.dataplane,
            nodes=4,
            workers=args.dataplane_workers,
            tx_size=512,
            duration=args.dataplane_duration,
            base_port=args.base_port + 5_000,
            work_dir=".regress-dataplane",
            batch_size=250_000,
            max_batch_delay=50,
            timeout=5_000,
        )
        check = {
            "metric": f"dataplane_e2e_tps_{args.dataplane}offered",
            "fresh": row["e2e_tps"],
            "e2e_latency_ms": row["e2e_latency_ms"],
            "shed": row["shed"],
        }
        baseline = best_committed_tps(os.path.join(REPO_ROOT, "results"))
        if baseline is None:
            check.update(status="no-baseline", ok=True)
        else:
            # A run cannot commit more than it offered: floor against
            # min(committed peak, offered rate).
            reachable = min(baseline["e2e_tps"], args.dataplane)
            floor = reachable * (1 - args.tolerance)
            check.update(
                status="compared",
                baseline=baseline["e2e_tps"],
                baseline_source=baseline["source"],
                floor=round(floor),
                ratio=round(row["e2e_tps"] / reachable, 3),
                ok=row["e2e_tps"] >= floor,
            )
        checks.append(check)

    if args.dataplane_parity:
        from benchmark.dataplane_sweep import run_point

        # Same offered load through both transports, back to back on the
        # same host. The bench subprocesses read HOTSTUFF_NET from the
        # inherited environment, so each leg swaps the whole plane —
        # receiver, senders, and worker ingress — not just the parent.
        legs: dict[str, dict] = {}
        for i, plane in enumerate(("asyncio", "native")):
            saved = os.environ.get("HOTSTUFF_NET")
            os.environ["HOTSTUFF_NET"] = plane
            try:
                legs[plane] = run_point(
                    args.dataplane_parity,
                    nodes=4,
                    workers=args.dataplane_workers,
                    tx_size=args.parity_size,
                    duration=args.dataplane_duration,
                    base_port=args.base_port + 7_000 + i * 1_000,
                    work_dir=f".regress-parity-{plane}",
                    batch_size=250_000,
                    max_batch_delay=50,
                    timeout=5_000,
                )
            finally:
                if saved is None:
                    os.environ.pop("HOTSTUFF_NET", None)
                else:
                    os.environ["HOTSTUFF_NET"] = saved
        floor = legs["asyncio"]["e2e_tps"] * (1 - args.tolerance)
        checks.append(
            {
                "metric": (
                    f"dataplane_parity_tps_{args.parity_size}B"
                    f"_{args.dataplane_parity}offered"
                ),
                "status": "compared",
                "fresh": legs["native"]["e2e_tps"],
                "baseline": legs["asyncio"]["e2e_tps"],
                "baseline_source": "paired asyncio leg (same run)",
                "floor": round(floor),
                "ratio": round(
                    legs["native"]["e2e_tps"]
                    / max(legs["asyncio"]["e2e_tps"], 1),
                    3,
                ),
                "native_latency_ms": legs["native"]["e2e_latency_ms"],
                "asyncio_latency_ms": legs["asyncio"]["e2e_latency_ms"],
                "ok": legs["native"]["e2e_tps"] >= floor,
            }
        )

    ok = all(c["ok"] for c in checks)
    report = {
        "schema": REGRESS_SCHEMA,
        "ok": ok,
        "host": host_meta(),
        "tolerance": args.tolerance,
        "ts": time.time(),
        "checks": checks,
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        path = os.path.join(args.output, "regress-gate.json")
        with open(path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"artifact written to {path}")
    print(f"regression gate: {'GREEN' if ok else 'RED'}")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
