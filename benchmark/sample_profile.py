"""Sampling profiler CLI for the in-process committee (1-core box).

Thin wrapper over ``hotstuff_tpu.telemetry.profiler.SamplingProfiler`` —
the one sampler implementation in the tree (this script used to carry
its own main-thread-only SIGPROF walker; the telemetry profiler walks
ALL threads via ``sys._current_frames`` and tags samples with the
active round-trace stage). cProfile's tracing overhead multiplies
asyncio's per-event cost so much that an N=40 committee cannot even
form its mesh inside a CI window; a SIGPROF sampler costs one stack
walk per interval (~0.3% at 2 ms) and leaves the timing honest.

    python -m benchmark.sample_profile --nodes 40 --rounds 15

For per-trace-edge attribution (which functions inside which edge), use
``committee_scale --pyprof --telemetry`` + ``profile_assemble`` instead.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=40)
    p.add_argument("--rounds", type=int, default=15)
    p.add_argument("--base-port", type=int, default=22000)
    p.add_argument("--interval-ms", type=float, default=2.0)
    p.add_argument("--top", type=int, default=35)
    p.add_argument(
        "--by-stage", action="store_true",
        help="break the table down by round-trace stage tag "
        "(requires telemetry marks; enabled automatically)",
    )
    args = p.parse_args()

    from benchmark.committee_scale import run_committee
    from hotstuff_tpu import telemetry
    from hotstuff_tpu.telemetry import profiler as pyprof

    telemetry.enable()  # the stage tags come from RoundTrace marks
    profiler = pyprof.SamplingProfiler(interval_ms=args.interval_ms)
    profiler.start(mode="auto")
    try:
        per_round, _ = asyncio.run(
            run_committee(args.nodes, args.rounds, args.base_port, 30_000)
        )
    finally:
        profiler.stop()

    print(
        f"\ncommittee={args.nodes} protocol: {per_round * 1e3:.1f} ms/round; "
        f"{profiler.samples} samples @ {args.interval_ms} ms "
        f"({profiler.mode} mode, whole run incl. boot); "
        f"GIL delay {profiler.gil_delay_ns / 1e6:.1f} ms"
    )

    if args.by_stage:
        per_stage = {
            stage or "(untagged)": n
            for stage, n in profiler.stage_totals().items()
        }
        total = sum(per_stage.values()) or 1
        print("\nsamples by round-trace stage:")
        for stage, n in sorted(per_stage.items(), key=lambda kv: -kv[1]):
            print(f"  {stage:<14} {n:>8} ({100 * n / total:5.1f}%)")

    self_c, cum_c, _ = profiler.self_cum()
    total = sum(self_c.values()) or 1
    print(f"\n{'SELF%':>6} {'CUM%':>6}  function")
    for name, n in self_c.most_common(args.top):
        print(
            f"{100 * n / total:6.2f} {100 * cum_c[name] / total:6.2f}  {name}"
        )


if __name__ == "__main__":
    main()
