"""Sampling profiler for the in-process committee (1-core box).

cProfile's tracing overhead multiplies asyncio's per-event cost so much
that an N=40 committee cannot even form its mesh inside a CI window; a
SIGPROF sampler costs one stack walk per interval (~0.3% at 2 ms) and
leaves the timing honest. Aggregates leaf-ward self time and rolled-up
cumulative time per function.

    python -m benchmark.sample_profile --nodes 40 --rounds 15
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_samples: collections.Counter[tuple[str, ...]] = collections.Counter()
_self: collections.Counter[str] = collections.Counter()
_cum: collections.Counter[str] = collections.Counter()
_nsamples = 0


def _frame_id(frame) -> str:
    code = frame.f_code
    fn = code.co_filename
    # Compress to repo-relative / stdlib-basename names.
    for marker in ("/hotstuff_tpu/", "/benchmark/"):
        if marker in fn:
            fn = marker.strip("/") + "/" + fn.split(marker, 1)[1]
            break
    else:
        fn = os.path.basename(fn)
    return f"{fn}:{code.co_firstlineno}:{code.co_name}"


def _on_prof(signum, frame) -> None:
    global _nsamples
    if frame is None:  # delivered with no Python frame current
        return
    _nsamples += 1
    stack = []
    f = frame
    while f is not None:
        stack.append(_frame_id(f))
        f = f.f_back
    _self[stack[0]] += 1
    for name in set(stack):
        _cum[name] += 1


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=40)
    p.add_argument("--rounds", type=int, default=15)
    p.add_argument("--base-port", type=int, default=22000)
    p.add_argument("--interval-ms", type=float, default=2.0)
    p.add_argument("--top", type=int, default=35)
    args = p.parse_args()

    from benchmark.committee_scale import run_committee

    signal.signal(signal.SIGPROF, _on_prof)
    signal.setitimer(
        signal.ITIMER_PROF, args.interval_ms / 1e3, args.interval_ms / 1e3
    )
    per_round, _ = asyncio.run(
        run_committee(args.nodes, args.rounds, args.base_port, 30_000)
    )
    signal.setitimer(signal.ITIMER_PROF, 0)

    print(
        f"\ncommittee={args.nodes} protocol: {per_round * 1e3:.1f} ms/round; "
        f"{_nsamples} samples @ {args.interval_ms} ms (whole run incl. boot)"
    )
    print(f"\n{'SELF%':>6} {'CUM%':>6}  function")
    for name, n in _self.most_common(args.top):
        print(
            f"{100 * n / _nsamples:6.2f} {100 * _cum[name] / _nsamples:6.2f}"
            f"  {name}"
        )


if __name__ == "__main__":
    main()
