"""Harness entry points (reference ``benchmark/fabfile.py``): the same task
set — local, remote, create, destroy, kill, plot, aggregate, logs — exposed
both as plain functions (wrappable by fabric if present) and as a CLI:

    python -m benchmark.fabfile local --nodes 4 --rate 1000
    python -m benchmark.fabfile plot
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.local import LocalBench  # noqa: E402
from benchmark.logs import LogParser  # noqa: E402
from benchmark.utils import PathMaker, Print  # noqa: E402


def local(
    nodes: int = 4,
    rate: int = 1_000,
    tx_size: int = 512,
    duration: int = 20,
    faults: int = 0,
    timeout: int = 1_000,
    batch_size: int = 15_000,
    save: bool = False,
):
    """Local benchmark (reference defaults: 4 nodes, 1k tx/s, 512 B, 20 s,
    1 s timeout, 15 kB batches — ``fabfile.py:12-38``)."""
    bench = LocalBench(
        nodes=nodes,
        rate=rate,
        tx_size=tx_size,
        duration=duration,
        faults=faults,
        timeout_delay=timeout,
        batch_size=batch_size,
    )
    parser = bench.run()
    print(parser.result())
    if save:
        os.makedirs(PathMaker.results_path(), exist_ok=True)
        parser.print_to(PathMaker.result_file(faults, nodes, rate, tx_size))
    return parser


def remote(hosts: list[str], rate: int = 10_000, tx_size: int = 512, duration: int = 60, faults: int = 0):
    """Remote benchmark over SSH hosts (reference ``fabfile.py:96-122``)."""
    from benchmark.remote import RemoteBench
    from benchmark.settings import Settings

    settings = Settings.load()
    bench = RemoteBench(settings, hosts)
    parser = bench.run(rate=rate, tx_size=tx_size, duration=duration, faults=faults)
    print(parser.result())
    return parser


def create(instances: int = 2):
    """Create AWS testbed instances (requires boto3)."""
    from benchmark.instance import InstanceManager
    from benchmark.settings import Settings

    InstanceManager(Settings.load()).create(instances)


def destroy():
    from benchmark.instance import InstanceManager
    from benchmark.settings import Settings

    InstanceManager(Settings.load()).terminate()


def kill(hosts: list[str]):
    from benchmark.remote import RemoteBench
    from benchmark.settings import Settings

    RemoteBench(Settings.load(), hosts).kill()


def logs(directory: str = "logs", faults: int = 0):
    """Parse an existing log directory into a SUMMARY."""
    parser = LogParser.process(directory, faults=faults)
    print(parser.result())
    return parser


def aggregate(results_dir: str | None = None):
    from benchmark.aggregate import LogAggregator

    agg = LogAggregator(results_dir)
    for path in agg.print_series():
        Print.info(f"wrote {path}")


def plot(results_dir: str | None = None, tx_size: int = 512):
    from benchmark.plot import Ploter

    ploter = Ploter(results_dir)
    Print.info(f"wrote {ploter.plot_latency([0, 1, 3], [4, 10, 20, 50], tx_size)}")
    Print.info(f"wrote {ploter.plot_tps([0], tx_size)}")


def main() -> None:
    p = argparse.ArgumentParser(prog="benchmark.fabfile")
    sub = p.add_subparsers(dest="task", required=True)

    pl = sub.add_parser("local")
    pl.add_argument("--nodes", type=int, default=4)
    pl.add_argument("--rate", type=int, default=1_000)
    pl.add_argument("--tx-size", type=int, default=512)
    pl.add_argument("--duration", type=int, default=20)
    pl.add_argument("--faults", type=int, default=0)
    pl.add_argument("--timeout", type=int, default=1_000)
    pl.add_argument("--save", action="store_true")

    pr = sub.add_parser("remote")
    pr.add_argument("--hosts", nargs="+", required=True)
    pr.add_argument("--rate", type=int, default=10_000)
    pr.add_argument("--tx-size", type=int, default=512)
    pr.add_argument("--duration", type=int, default=60)
    pr.add_argument("--faults", type=int, default=0)

    pk = sub.add_parser("kill")
    pk.add_argument("--hosts", nargs="+", required=True)

    plog = sub.add_parser("logs")
    plog.add_argument("--dir", default="logs")
    plog.add_argument("--faults", type=int, default=0)

    sub.add_parser("aggregate")
    pp = sub.add_parser("plot")
    pp.add_argument("--tx-size", type=int, default=512)

    args = p.parse_args()
    if args.task == "local":
        local(
            nodes=args.nodes,
            rate=args.rate,
            tx_size=args.tx_size,
            duration=args.duration,
            faults=args.faults,
            timeout=args.timeout,
            save=args.save,
        )
    elif args.task == "remote":
        remote(args.hosts, args.rate, args.tx_size, args.duration, args.faults)
    elif args.task == "kill":
        kill(args.hosts)
    elif args.task == "logs":
        logs(args.dir, args.faults)
    elif args.task == "aggregate":
        aggregate()
    elif args.task == "plot":
        plot(tx_size=args.tx_size)


if __name__ == "__main__":
    main()
