"""Committee-scale consensus benchmark (BASELINE.json configs 2-4).

Two modes:

``--mode protocol`` (default) boots an N-validator committee of full
consensus engines IN ONE PROCESS over real localhost TCP (mempool channels
sunk, like the reference's `node deploy` testbed) with
``batch_vote_verification`` on, and measures round rate under the selected
crypto backend. Socket count scales as N^2, so this mode tops out around
N=100 on one host.

``--mode crypto`` measures the per-round *certificate verification* load at
committees where the protocol cannot be materialized on one box (N=400,
N=1000 — BASELINE configs 3-4): each round verifies one proposal the way a
validator does (block signature + embedded 2f+1-vote QC batch verification,
``consensus/messages.py`` — the same code the node runs), and with
``--tc-heavy`` additionally verifies a (2f+1)-signature TimeoutCertificate
per round (the f=333 view-change regime; reference ``messages.rs:283-320``).

    python -m benchmark.committee_scale --nodes 20 --rounds 20
    HOTSTUFF_CRYPTO_BACKEND=tpu python -m benchmark.committee_scale \
        --nodes 1000 --mode crypto --tc-heavy --output results

Results are appended to ``results/committee-<mode>[-tc]-<backend>-<N>.txt``
when ``--output`` is given (the committed corpus under ``results/``).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

from benchmark.hostinfo import host_meta

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def run_committee(
    n: int,
    rounds_target: int,
    base_port: int,
    timeout_delay: int,
    profile: bool = False,
    telemetry_path: str | None = None,
    profiler=None,
):
    """Returns ``(seconds_per_round, stage_profile | None)`` where the
    stage profile — measured-window deltas of the registry's
    ``consensus.stage.<kind>.{ns,calls}`` counters — covers EVERY
    engine's core (the whole committee's per-round handler cost)."""
    from hotstuff_tpu import telemetry
    from hotstuff_tpu.consensus import Authority, Committee, Consensus, Parameters
    from hotstuff_tpu.crypto import SignatureService, generate_keypair
    from hotstuff_tpu.store import Store

    emitter = None
    if telemetry_path:
        emitter = telemetry.TelemetryEmitter(
            telemetry.get_registry(),
            telemetry_path,
            node=f"committee-{n}",
            interval_s=telemetry.env_interval_s(),
            # Cross-node trace events ride the same stream: every
            # engine's RoundTrace labels its events with its key, so one
            # in-process stream carries the whole committee's timelines
            # (benchmark/trace_assemble.py merges them per round).
            trace=telemetry.trace_buffer(),
            # --pyprof: folded-stack profile records interleave too
            # (benchmark/profile_assemble.py joins them onto the edges).
            profiler=profiler,
        )

    keys = [generate_keypair() for _ in range(n)]
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", base_port + i))
            for i, (pk, _) in enumerate(keys)
        }
    )
    params = Parameters(
        timeout_delay=timeout_delay, batch_vote_verification=True
    )

    engines, commits, sinks = [], [], []
    for pk, sk in keys:
        rx_mempool: asyncio.Queue = asyncio.Queue()
        tx_mempool: asyncio.Queue = asyncio.Queue()
        tx_commit: asyncio.Queue = asyncio.Queue()

        async def drain(q=tx_mempool):
            while True:
                await q.get()

        sinks.append(asyncio.create_task(drain()))
        engines.append(
            await Consensus.spawn(
                pk,
                committee,
                params,
                SignatureService(sk),
                Store(),
                rx_mempool,
                tx_mempool,
                tx_commit,
                profile=profile,
            )
        )
        commits.append(tx_commit)

    # Wait for the first commit everywhere, then time rounds_target more.
    await asyncio.gather(*[q.get() for q in commits])
    if emitter is not None:
        # Stream from the measurement anchor, not process start: the N^2
        # dial-in boot phase would otherwise dominate the stream with
        # zero-progress windows and boot-skew timeouts, and SLO verdicts
        # must judge the measured regime (boot counters still appear —
        # cumulatively — in the first snapshot's totals, just never as a
        # window delta).
        #
        # The round-trace ring gets the same anchoring: boot-era events
        # would otherwise drain into the measured stream, and a round
        # whose timeline spans both lives (proposed during dial-in,
        # commit-straggled by a lagging engine into the measured window)
        # reports a multi-minute "critical path" that is really boot
        # skew — observed live at N=200, poisoning the committed
        # trace-edge means by two orders of magnitude.
        telemetry.trace_buffer().clear()
        emitter.emit()
        emitter.spawn()
    registry = telemetry.get_registry()
    warmup = registry.snapshot()["counters"] if profile else None
    t0 = time.perf_counter()
    for _ in range(rounds_target):
        await asyncio.gather(*[q.get() for q in commits])
    elapsed = time.perf_counter() - t0

    stage_profile: dict[str, tuple[int, int]] | None = None
    if profile:
        # Measured-window deltas only (warm-up handlers excluded).
        deltas = telemetry.diff_counters(warmup, registry.snapshot()["counters"])
        stage_profile = {}
        prefix = "consensus.stage."
        for name, value in deltas.items():
            if not name.startswith(prefix):
                continue
            kind, field = name[len(prefix):].rsplit(".", 1)
            ns, calls = stage_profile.get(kind, (0, 0))
            if field == "ns":
                ns += value
            elif field == "calls":
                calls += value
            stage_profile[kind] = (ns, calls)

    if emitter is not None:
        await emitter.shutdown()
    for e in engines:
        await e.shutdown()
    for s in sinks:
        s.cancel()
    return elapsed / rounds_target, stage_profile


def run_crypto_rounds(n: int, rounds: int, tc_heavy: bool) -> float:
    """Per-round certificate-verification time at committee size n: one
    proposal verification (block sig + QC batch over 2f+1 votes) and, with
    ``tc_heavy``, one (2f+1)-vote TC verification — the exact
    ``Block.verify``/``TC.verify`` code paths a validator runs per round."""
    import struct

    from hotstuff_tpu.consensus import Authority, Committee
    from hotstuff_tpu.consensus.messages import QC, TC, Block
    from hotstuff_tpu.crypto import Signature, generate_keypair, sha512_digest

    keys = [generate_keypair() for _ in range(n)]
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", 0))
            for pk, _ in keys
        }
    )
    f = (n - 1) // 3
    quorum = 2 * f + 1

    # Genesis-parented block signed by the round-2 leader, with a real QC
    # over round 1 and (optionally) a TC for the view change into round 2.
    genesis = Block.genesis()
    qc = QC(hash=genesis.digest(), round=1, votes=[])
    qc.votes = [
        (pk, Signature.new(qc.digest(), sk)) for pk, sk in keys[:quorum]
    ]

    tc = None
    if tc_heavy:
        u64 = struct.Struct("<Q")
        tc_votes = [
            (pk, Signature.new(sha512_digest(u64.pack(2), u64.pack(1)), sk), 1)
            for pk, sk in keys[:quorum]
        ]
        tc = TC(round=2, votes=tc_votes)

    author_pk, author_sk = keys[0]
    block = Block.new_from_key(
        qc=qc, tc=tc, author=author_pk, round_=2, payload=[], secret=author_sk
    )

    block.verify(committee)  # warm-up (device compile / native lib load)
    t0 = time.perf_counter()
    for _ in range(rounds):
        block.verify(committee)
    return (time.perf_counter() - t0) / rounds


def run_faults(args) -> None:
    """``--faults``: run a faultline scenario end-to-end on the
    in-process committee and gate on the checker verdict. The scenario is
    a JSON file or the ``chaos:<seed>`` shorthand; with ``--replay`` the
    scenario runs TWICE and the two compiled fault schedules must be
    byte-identical (the seed-reproducibility contract)."""
    import json

    from hotstuff_tpu import telemetry
    from hotstuff_tpu.faultline import Scenario, chaos_scenario, run_scenario

    telemetry.enable()  # faultline.* counters + RoundTrace annotations
    if args.faults.startswith("chaos:"):
        scenario = chaos_scenario(
            int(args.faults.split(":", 1)[1]), duration_s=args.faults_duration
        )
    elif args.faults == "split":
        # The view-change/recovery probe: cut the committee into two
        # EVEN halves (neither holds 2f+1) for the middle 30% of the
        # run. All progress stops, both sides burn timeout rounds; on
        # heal the committee must timeout-sync, re-elect, and resume —
        # the verdict's liveness.recovery_s IS the measured view-change
        # + recovery cost.
        d = args.faults_duration
        half = args.nodes // 2
        scenario = Scenario(
            name="split",
            seed=0,
            duration_s=d,
            events=[
                {
                    "kind": "partition",
                    "groups": [
                        list(range(half)), list(range(half, args.nodes))
                    ],
                    "at": round(0.3 * d, 3),
                    "until": round(0.6 * d, 3),
                }
            ],
        )
    else:
        scenario = Scenario.load(args.faults)

    async def one_run(base_port: int) -> dict:
        return await run_scenario(
            scenario,
            args.nodes,
            base_port=base_port,
            timeout_delay=args.timeout,
            leader_elector=args.leader_elector,
            retention_rounds=args.retention_rounds,
            # Committee-size-aware recovery bound: post-heal the whole
            # committee must timeout-sync and re-quorum; at N=100 that
            # is minutes of real work on one core, not the N=4 seconds.
            recovery_timeout_s=max(30.0, 1.2 * args.nodes),
        )

    result = asyncio.run(one_run(args.base_port))
    traces = [result["trace"]]
    if args.replay:
        replay = asyncio.run(one_run(args.base_port + args.nodes + 16))
        traces.append(replay["trace"])
        assert traces[0] == traces[1], "replay trace diverged for equal seeds"
        result["replay_verdict"] = replay["verdict"]
    verdict = result["verdict"]
    fault_counters = {
        k: v
        for k, v in result["telemetry"]["counters"].items()
        if k.startswith("faultline.")
    }
    report = {
        "verdict": verdict,
        "host": host_meta(),
        # None (not true) when --replay didn't run: absence of evidence.
        "replay_trace_match": (
            traces[0] == traces[1] if len(traces) == 2 else None
        ),
        "trace": json.loads(traces[0]),
        "faultline_counters": fault_counters,
    }
    frontier = verdict.get("frontier_availability")
    ok = (
        verdict["safety"]["ok"]
        and verdict["liveness"]["recovered"]
        and (frontier is None or frontier["ok"])
    )
    print(
        f"faultline scenario={scenario.name} seed={scenario.seed} "
        f"nodes={args.nodes}: safety={'ok' if verdict['safety']['ok'] else 'VIOLATED'} "
        f"liveness={'recovered' if verdict['liveness']['recovered'] else 'STALLED'} "
        + (
            f"frontier={'ok' if frontier['ok'] else 'UNSERVABLE'} "
            f"floors={frontier['floors']} "
            if frontier is not None
            else ""
        )
        + f"commits={verdict['commits']} "
        f"injections={verdict['injections']['counts']}"
    )
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        path = os.path.join(
            args.output, f"chaos-{scenario.name}-{args.nodes}.json"
        )
        with open(path, "w") as out:
            json.dump(report, out, indent=2, sort_keys=True)
            out.write("\n")
        print(f"verdict written to {path}")
    if not ok:
        sys.exit(1)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=20)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--base-port", type=int, default=17000)
    p.add_argument("--timeout", type=int, default=30_000)
    p.add_argument("--mode", choices=["protocol", "crypto"], default="protocol")
    p.add_argument("--tc-heavy", action="store_true")
    p.add_argument(
        "--groups",
        type=int,
        default=None,
        help="protocol mode: shard the committee across this many worker "
        "processes (engine groups, hotstuff_tpu/parallel/engine_groups.py). "
        "Default: HOTSTUFF_ENGINE_GROUPS (0 = single-process, the "
        "byte-identical classic path)",
    )
    p.add_argument(
        "--faults",
        metavar="SCENARIO",
        help="run a faultline scenario (a JSON file, chaos:<seed> for a "
        "seeded storm, or 'split' for the even two-way partition "
        "view-change probe) on the in-process committee and exit nonzero "
        "unless the checker reports safety=ok and liveness=recovered",
    )
    p.add_argument(
        "--faults-duration",
        type=float,
        default=15.0,
        help="chaos:<seed> scenario duration in virtual seconds",
    )
    p.add_argument(
        "--replay",
        action="store_true",
        help="with --faults: run the scenario twice and assert the two "
        "compiled fault schedules (replay traces) are identical",
    )
    p.add_argument(
        "--leader-elector",
        default="",
        help="with --faults: consensus leader elector (e.g. reputation)",
    )
    p.add_argument(
        "--retention-rounds",
        type=int,
        default=0,
        help="with --faults: arm snapshot/truncate log compaction at this "
        "retention depth (rounds; 0 = disabled) — the verdict then also "
        "gates on the frontier-availability invariant",
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="protocol mode: print per-stage µs/round (aggregated over "
        "every engine's core — the whole committee's per-round handler "
        "cost on this core; sourced from the telemetry registry's "
        "consensus.stage.* counters)",
    )
    p.add_argument(
        "--telemetry",
        metavar="PATH",
        help="protocol mode: enable the telemetry plane and stream "
        "JSON-lines snapshots + cross-node trace events to PATH (final "
        "snapshot at shutdown; interval via HOTSTUFF_TELEMETRY_INTERVAL)",
    )
    p.add_argument(
        "--pyprof",
        nargs="?",
        const=2.0,
        type=float,
        metavar="INTERVAL_MS",
        help="protocol mode: run the all-thread sampling profiler for "
        "the whole run (default 2 ms). With --telemetry the folded-stack "
        "records ride the stream as hotstuff-profile-v1 lines and the "
        "per-edge function attribution is printed after the run "
        "(benchmark/profile_assemble.py joins them onto the trace "
        "edges); without it the top self-time functions are printed.",
    )
    p.add_argument(
        "--slo",
        nargs="?",
        const="default",
        metavar="SPEC.json",
        help="with --telemetry: evaluate SLOs over the emitted snapshot "
        "stream after the run (default spec set, or a JSON spec file) and "
        "exit nonzero on violation",
    )
    p.add_argument(
        "--watch",
        action="store_true",
        help="with --telemetry: attach the live watchtower (tail-follows "
        "the stream while the committee runs, scores every peer, prints "
        "hotstuff-alert-v1 alerts as they fire, and — in-process — dumps "
        "a flight record + bounded profile at the moment of detection)",
    )
    p.add_argument(
        "--watch-capture",
        metavar="DIR",
        help="with --watch: directory for alert-triggered captures "
        "(default: alongside the telemetry stream)",
    )
    p.add_argument("--output", help="directory to append the result file to")
    args = p.parse_args()

    if args.faults:
        # Chaos mode replaces the timing benchmark: a default --timeout
        # of 30 s would let a single dead-leader round eat the whole
        # scenario, so chaos runs use a snappier view-change budget.
        if args.timeout == 30_000:
            args.timeout = 1_000
        run_faults(args)
        return

    if args.telemetry or args.pyprof is not None:
        # BEFORE actors/backends are constructed: they capture their
        # metric objects at creation time. --pyprof needs this too: the
        # RoundTrace marks that drive the sampler's stage tags only
        # exist when telemetry is enabled.
        from hotstuff_tpu import telemetry as _telemetry

        _telemetry.enable()

    profiler = None
    if args.pyprof is not None:
        if args.mode != "protocol":
            print("--pyprof requires --mode protocol", file=sys.stderr)
            sys.exit(2)
        from hotstuff_tpu.telemetry import profiler as _pyprof

        profiler = _pyprof.SamplingProfiler(interval_ms=args.pyprof)
        profiler.start(mode="auto")

    if args.mode == "protocol":
        # The one-process committee multiplexes N engines' verification
        # requests through one crypto plane: enough bridge workers must
        # exist for concurrent requests to POOL in the superbatching
        # backend (fusion+dedup collapses the N byte-identical QC
        # verifies of a round to one MSM). With the default 2 workers the
        # pool depth is 2 and fusion never happens. Explicit env wins.
        os.environ.setdefault("HOTSTUFF_CRYPTO_WORKERS", "32")

    from hotstuff_tpu.crypto import get_backend

    backend = get_backend().name
    f = (args.nodes - 1) // 3
    stage_profile = None
    watch = None
    if args.watch:
        if not args.telemetry or args.mode != "protocol":
            print(
                "--watch requires --mode protocol with --telemetry PATH",
                file=sys.stderr,
            )
            sys.exit(2)
        from benchmark.watchtower import DirectoryWatch
        from hotstuff_tpu import telemetry as _telemetry
        from hotstuff_tpu.telemetry.watchtower import AlertCapture

        stream_abs = os.path.abspath(args.telemetry)
        capture = AlertCapture(
            args.watch_capture
            or os.path.join(os.path.dirname(stream_abs), "captures"),
            # In-process: the watcher shares the engines' process, so an
            # alert dumps the live trace ring + registry and runs a
            # bounded profiler burst on the spot.
            trace=_telemetry.trace_buffer(),
            registry=_telemetry.get_registry(),
        )
        watch = DirectoryWatch(
            os.path.dirname(stream_abs),
            pattern=os.path.basename(stream_abs),
            on_alert=capture,
            alerts_path=stream_abs + ".alerts.jsonl",
        )
        capture.watchtower = watch.watch
        watch.start()
    from hotstuff_tpu.parallel.engine_groups import groups_from_env

    n_groups = args.groups if args.groups is not None else groups_from_env()
    if args.mode == "protocol" and n_groups >= 1:
        # Process-sharded committee: the parent only consumes decision
        # records from the groups' event rings (no engines, no crypto in
        # this process). Incompatible with the in-process observability
        # attachments (--profile/--telemetry/--pyprof/--watch), which
        # assume the engines share the parent's registry.
        if args.profile or args.telemetry or profiler is not None or watch:
            print(
                "--groups is incompatible with --profile/--telemetry/"
                "--pyprof/--watch (engines run in worker processes)",
                file=sys.stderr,
            )
            sys.exit(2)
        from hotstuff_tpu.parallel.engine_groups import run_grouped_committee

        per_round, _merged = run_grouped_committee(
            args.nodes, args.rounds, n_groups,
            base_port=args.base_port, timeout_delay=args.timeout,
        )
    elif args.mode == "protocol":
        try:
            per_round, stage_profile = asyncio.run(
                run_committee(
                    args.nodes, args.rounds, args.base_port, args.timeout,
                    profile=args.profile,
                    telemetry_path=args.telemetry,
                    profiler=profiler,
                )
            )
        finally:
            if profiler is not None:
                profiler.stop()
            if watch is not None:
                watch.stop()
    else:
        per_round = run_crypto_rounds(args.nodes, args.rounds, args.tc_heavy)
    # Ask the network package what it ACTUALLY selected (HOTSTUFF_NET=native
    # silently falls back to asyncio when the C++ library cannot build) so
    # committed result lines never claim a transport that didn't run.
    from hotstuff_tpu import network as _network

    transport = (
        "native" if "Native" in _network.Receiver.__name__ else "asyncio"
    )
    line = (
        f"committee={args.nodes} (f={f}, QC size {2 * f + 1}) mode={args.mode}"
        f"{' tc-heavy' if args.tc_heavy else ''}"
        f"{f' groups={n_groups}' if args.mode == 'protocol' and n_groups else ''}"
        f" backend={backend}"
        f" transport={transport}: "
        f"{per_round * 1e3:.1f} ms/round ({1 / per_round:.2f} rounds/s)"
    )
    print(line)
    profile_lines = []
    if stage_profile:
        # Aggregated over ALL engines: the committee's whole per-round
        # handler bill on this core, by stage (telemetry registry,
        # consensus.stage.* counters over the measured window).
        profile_lines.append(
            f"per-stage handler cost (all {args.nodes} engines, "
            f"{args.rounds} measured rounds, telemetry registry):"
        )
        profile_lines.append(
            f"  {'stage':<10} {'calls/round':>12} {'us/round':>12}"
        )
        for kind, (ns, calls) in sorted(
            stage_profile.items(), key=lambda kv: -kv[1][0]
        ):
            profile_lines.append(
                f"  {kind:<10} {calls / args.rounds:>12.1f} "
                f"{ns / 1e3 / args.rounds:>12.1f}"
            )
        print("\n".join(profile_lines))
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        tag = f"{args.mode}{'-tc' if args.tc_heavy else ''}"
        path = os.path.join(
            args.output, f"committee-{tag}-{backend}-{args.nodes}.txt"
        )
        with open(path, "a") as out:
            out.write(line + "\n")
            for pl in profile_lines:
                out.write(pl + "\n")

    if profiler is not None:
        print(
            f"pyprof: {profiler.samples} samples @ {profiler.interval_ms} ms "
            f"({profiler.mode} mode), GIL delay "
            f"{profiler.gil_delay_ns / 1e6:.1f} ms"
        )
        if args.telemetry:
            # The emitter drained the folded stacks into the stream:
            # join them onto the trace edges for the printed answer.
            from benchmark.profile_assemble import _human, attribute

            print(_human(attribute([args.telemetry])))
        else:
            self_c, cum_c, _samples = profiler.self_cum()
            total = sum(self_c.values())
            if total:
                print(f"{'SELF%':>6} {'CUM%':>6}  function")
                for fn, n in self_c.most_common(20):
                    print(
                        f"{100 * n / total:6.2f} {100 * cum_c[fn] / total:6.2f}"
                        f"  {fn}"
                    )

    if watch is not None:
        import json

        alerts = watch.alerts()
        board = watch.scoreboard()
        print(
            f"watchtower: {len(alerts)} alert(s), "
            f"frontier={board['frontier']}, "
            f"{board['rounds']} scored round(s), "
            f"streams={json.dumps(watch.stats())}"
        )
        for alert in alerts:
            print(
                f"  ALERT {alert['detector']}: accused={alert['accused']} "
                f"confidence={alert['confidence']} "
                f"capture={alert.get('capture', {})}"
            )

    if args.slo:
        if not args.telemetry:
            print("--slo requires --telemetry PATH", file=sys.stderr)
            sys.exit(2)
        import json

        from benchmark.logs import read_telemetry_stream
        from hotstuff_tpu.telemetry import slo as slo_mod

        specs = (
            slo_mod.default_slos()
            if args.slo == "default"
            else slo_mod.load_specs(args.slo)
        )
        verdict = slo_mod.evaluate(
            read_telemetry_stream(args.telemetry),
            specs,
            window_s=float(os.environ.get("HOTSTUFF_SLO_WINDOW_S", "30")),
            source=args.telemetry,
        )
        print(json.dumps(verdict, sort_keys=True))
        if not verdict["ok"]:
            print("SLO verdict: FAILED", file=sys.stderr)
            sys.exit(3)
        print("SLO verdict: ok")


if __name__ == "__main__":
    main()
