"""Committee-scale consensus benchmark (BASELINE.json configs 2-4).

Boots an N-validator committee of full consensus engines IN ONE PROCESS
(mempool channels sunk, like the reference's `node deploy` testbed) with
``batch_vote_verification`` on, and measures round rate and QC sizes under
the selected crypto backend:

    python -m benchmark.committee_scale --nodes 20 --rounds 20
    HOTSTUFF_CRYPTO_BACKEND=tpu python -m benchmark.committee_scale --nodes 20

At committee scale the per-round cost is dominated by QC verification
(every validator batch-verifies the 2f+1 signatures embedded in each
proposal): the point of the TPU backend. All N validators share one event
loop and one CPU core here, so absolute round rates are a lower bound; the
relevant comparison is cpu-backend vs tpu-backend at the same N.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def run_committee(n: int, rounds_target: int, base_port: int, timeout_delay: int):
    from hotstuff_tpu.consensus import Authority, Committee, Consensus, Parameters
    from hotstuff_tpu.crypto import SignatureService, generate_keypair
    from hotstuff_tpu.store import Store

    keys = [generate_keypair() for _ in range(n)]
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", base_port + i))
            for i, (pk, _) in enumerate(keys)
        }
    )
    params = Parameters(
        timeout_delay=timeout_delay, batch_vote_verification=True
    )

    engines, commits, sinks = [], [], []
    for pk, sk in keys:
        rx_mempool: asyncio.Queue = asyncio.Queue()
        tx_mempool: asyncio.Queue = asyncio.Queue()
        tx_commit: asyncio.Queue = asyncio.Queue()

        async def drain(q=tx_mempool):
            while True:
                await q.get()

        sinks.append(asyncio.create_task(drain()))
        engines.append(
            await Consensus.spawn(
                pk,
                committee,
                params,
                SignatureService(sk),
                Store(),
                rx_mempool,
                tx_mempool,
                tx_commit,
            )
        )
        commits.append(tx_commit)

    # Wait for the first commit everywhere, then time rounds_target more.
    await asyncio.gather(*[q.get() for q in commits])
    t0 = time.perf_counter()
    for _ in range(rounds_target):
        await asyncio.gather(*[q.get() for q in commits])
    elapsed = time.perf_counter() - t0

    for e in engines:
        await e.shutdown()
    for s in sinks:
        s.cancel()
    return elapsed / rounds_target


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=20)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--base-port", type=int, default=17000)
    p.add_argument("--timeout", type=int, default=30_000)
    args = p.parse_args()

    from hotstuff_tpu.crypto import get_backend

    backend = get_backend().name
    f = (args.nodes - 1) // 3
    per_round = asyncio.run(
        run_committee(args.nodes, args.rounds, args.base_port, args.timeout)
    )
    print(
        f"committee={args.nodes} (f={f}, QC size {2 * f + 1}) "
        f"backend={backend} batch_votes=on: "
        f"{per_round * 1e3:.1f} ms/round ({1 / per_round:.2f} rounds/s)"
    )


if __name__ == "__main__":
    main()
