"""Harness utilities (reference ``benchmark/benchmark/utils.py``):
file-naming conventions, colored printing, progress."""

from __future__ import annotations

import os
import sys
from datetime import datetime


class PathMaker:
    """All benchmark file-naming conventions (reference ``utils.py:57-62``)."""

    @staticmethod
    def results_path() -> str:
        return "results"

    @staticmethod
    def plots_path() -> str:
        return "plots"

    @staticmethod
    def logs_path() -> str:
        return "logs"

    @staticmethod
    def result_file(faults: int, nodes: int, rate: int, tx_size: int) -> str:
        return os.path.join(
            PathMaker.results_path(), f"bench-{faults}-{nodes}-{rate}-{tx_size}.txt"
        )

    @staticmethod
    def agg_file(kind: str, faults, nodes, rate, tx_size) -> str:
        """Aggregated-series file; 'x' marks the swept dimension (e.g. the
        L-graph sweeps rate: ``agg-l-0-4-x-512.txt``)."""
        return os.path.join(
            PathMaker.plots_path(), f"agg-{kind}-{faults}-{nodes}-{rate}-{tx_size}.txt"
        )

    @staticmethod
    def plot_file(name: str, ext: str = "pdf") -> str:
        return os.path.join(PathMaker.plots_path(), f"{name}.{ext}")

    @staticmethod
    def node_log_file(i: int) -> str:
        return os.path.join(PathMaker.logs_path(), f"node-{i}.log")

    @staticmethod
    def client_log_file(i: int) -> str:
        return os.path.join(PathMaker.logs_path(), f"client-{i}.log")


class Print:
    @staticmethod
    def heading(message: str) -> None:
        print(f"\033[1m{message}\033[0m")

    @staticmethod
    def info(message: str) -> None:
        print(message)

    @staticmethod
    def warn(message: str) -> None:
        print(f"\033[93mWARN: {message}\033[0m", file=sys.stderr)

    @staticmethod
    def error(message: str) -> None:
        print(f"\033[91mERROR: {message}\033[0m", file=sys.stderr)


def progress_bar(iterable, prefix: str = "", size: int = 30):
    total = len(iterable)
    for i, item in enumerate(iterable, 1):
        filled = size * i // total
        sys.stdout.write(
            f"\r{prefix}[{'#' * filled}{'.' * (size - filled)}] {i}/{total}"
        )
        sys.stdout.flush()
        yield item
    sys.stdout.write("\n")


def timestamp() -> str:
    return datetime.now().strftime("%Y-%m-%d %H:%M:%S")
