"""Sampling-profiler smoke + overhead gate (the profiling sibling of
``benchmark/telemetry_smoke.py``).

Runs the one-process committee bench twice per repeat — telemetry on in
BOTH legs (the baseline the <1% telemetry budget already paid for),
sampler OFF vs sampler ON (2 ms all-thread stack walks + stage tagging
+ ctypes accounting + profile-record emission) — and:

1. validates that the sampler actually produced ``hotstuff-profile-v1``
   records in the stream, that they parse back through
   ``benchmark.logs.read_stream_records``, and that stage tags joinable
   onto the trace edges are present;
2. gates the measured overhead: min-over-repeats per-round time with
   the sampler on must be within ``--budget`` (default 1%) of off —
   min-of-N with alternating order, the same noise-robust estimator the
   telemetry gate uses on a shared CI core.

Exit code 0 on pass, 1 on record/schema failure, 2 on budget failure.

    python -m benchmark.profile_smoke --nodes 10 --rounds 20
    python -m benchmark.profile_smoke --nodes 100 --rounds 20 \
        --output results/profile-overhead-100.txt
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402


def _run_once(
    n: int,
    rounds: int,
    base_port: int,
    with_sampler: bool,
    interval_ms: float,
    snap_path: str | None,
    ctypes_accounting: bool = True,
):
    from benchmark.committee_scale import run_committee
    from hotstuff_tpu import telemetry
    from hotstuff_tpu.telemetry import profiler as pyprof

    telemetry.reset_for_tests()
    telemetry.enable()
    profiler = None
    if with_sampler:
        profiler = pyprof.SamplingProfiler(interval_ms=interval_ms)
        profiler.start(mode="auto", ctypes_accounting=ctypes_accounting)
    try:
        per_round, _ = asyncio.run(
            run_committee(
                n, rounds, base_port, timeout_delay=30_000,
                telemetry_path=snap_path, profiler=profiler,
            )
        )
    finally:
        if profiler is not None:
            profiler.stop()
        telemetry.disable()
    samples = profiler.samples if profiler is not None else 0
    return per_round, samples


def _spawn_once(
    n: int,
    rounds: int,
    base_port: int,
    with_sampler: bool,
    interval_ms: float,
    snap_path: str | None,
):
    """One measurement leg in a FRESH subprocess. The native transport's
    C++ context is process-wide and keeps outbound connections for the
    process lifetime, so repeated in-process committees accumulate
    state: later legs run slower regardless of the sampler, and the
    drift lands asymmetrically on the on/off sides. A process per leg
    makes every leg identical to a standalone run."""
    cmd = [
        sys.executable, "-m", "benchmark.profile_smoke", "--one-shot",
        "--nodes", str(n), "--rounds", str(rounds),
        "--base-port", str(base_port), "--interval-ms", str(interval_ms),
    ]
    if with_sampler:
        cmd.append("--sampler-on")
    if snap_path:
        cmd += ["--snap", snap_path]
    proc = subprocess.run(
        cmd, capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"one-shot leg failed (rc={proc.returncode}):\n{proc.stderr[-2000:]}"
        )
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    return result["per_round"], result["samples"]


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=10)
    p.add_argument("--rounds", type=int, default=15)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--interval-ms", type=float, default=2.0)
    p.add_argument(
        "--budget",
        type=float,
        default=float(os.environ.get("HOTSTUFF_PYPROF_BUDGET", "0.01")),
        help="max allowed relative overhead (default 0.01 = 1%%)",
    )
    p.add_argument("--base-port", type=int, default=19000)
    p.add_argument("--output", help="file to append the result summary to")
    # Internal: one measurement leg (see _spawn_once).
    p.add_argument("--one-shot", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--sampler-on", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--no-ctypes-acct", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--snap", help=argparse.SUPPRESS)
    args = p.parse_args()

    os.environ.setdefault("HOTSTUFF_TELEMETRY_INTERVAL", "1")
    # Measurement parity with committee_scale's protocol mode: enough
    # bridge workers for the superbatching backend to fuse (the regime
    # the committed ms/round numbers were measured in).
    os.environ.setdefault("HOTSTUFF_CRYPTO_WORKERS", "32")

    if args.one_shot:
        per_round, samples = _run_once(
            args.nodes, args.rounds, args.base_port, args.sampler_on,
            args.interval_ms, args.snap,
            ctypes_accounting=not args.no_ctypes_acct,
        )
        print(json.dumps({"per_round": per_round, "samples": samples}))
        return

    from benchmark.logs import read_stream_records

    snap_dir = tempfile.mkdtemp(prefix="hotstuff_profile_smoke_")
    off_times: list[float] = []
    on_times: list[float] = []
    total_samples = 0
    port = args.base_port

    # Discarded warm-up: one-time costs (native lib builds, bytecode
    # caches) must not land on either side of the gate.
    _spawn_once(args.nodes, max(2, args.rounds // 4), port, False,
                args.interval_ms, None)
    port += 2 * args.nodes

    for rep in range(args.repeats):
        order = (False, True) if rep % 2 == 0 else (True, False)
        for with_sampler in order:
            snap_path = (
                os.path.join(snap_dir, f"telemetry-run{rep}.jsonl")
                if with_sampler
                else None
            )
            per_round, samples = _spawn_once(
                args.nodes, args.rounds, port, with_sampler,
                args.interval_ms, snap_path,
            )
            port += 2 * args.nodes
            if with_sampler:
                on_times.append(per_round)
                total_samples += samples
            else:
                off_times.append(per_round)

    # -- profile-record gate -------------------------------------------------
    problems: list[str] = []
    records = 0
    staged = 0
    for fn in sorted(os.listdir(snap_dir)):
        path = os.path.join(snap_dir, fn)
        try:
            recs = read_stream_records(path)  # raises on schema violation
        except Exception as e:  # noqa: BLE001
            problems.append(f"{fn}: {e}")
            continue
        records += len(recs.profiles)
        for rec in recs.profiles:
            staged += sum(
                c for stage, _f, c in rec["stacks"] if stage
            )
    if records == 0:
        problems.append("no hotstuff-profile-v1 records were emitted")
    if total_samples and not staged:
        problems.append("no sample carried a round-trace stage tag")

    # -- overhead gate -------------------------------------------------------
    best_off = min(off_times)
    best_on = min(on_times)
    overhead = (best_on - best_off) / best_off

    result = {
        "metric": f"pyprof_overhead_n{args.nodes}",
        "host": host_meta(),
        "off_ms_per_round": round(best_off * 1e3, 2),
        "on_ms_per_round": round(best_on * 1e3, 2),
        "overhead": round(overhead, 4),
        "budget": args.budget,
        "interval_ms": args.interval_ms,
        "samples": total_samples,
        "profile_records": records,
        "stage_tagged_samples": staged,
        "schema_problems": problems,
    }
    print(json.dumps(result))

    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
        with open(args.output, "a") as f:
            f.write(json.dumps(result) + "\n")

    if problems:
        print(f"FAIL: profile problems: {problems}", file=sys.stderr)
        sys.exit(1)
    if overhead > args.budget:
        print(
            f"FAIL: sampler overhead {overhead:.2%} exceeds the "
            f"{args.budget:.2%} budget",
            file=sys.stderr,
        )
        sys.exit(2)
    print(
        f"PASS: sampler overhead {overhead:+.2%} within {args.budget:.2%}; "
        f"{records} profile record(s), {total_samples} samples "
        f"({staged} stage-tagged)"
    )


if __name__ == "__main__":
    main()
