"""Telemetry smoke + overhead gate.

Runs the one-process committee bench (``benchmark.committee_scale``'s
protocol mode) twice per repeat — telemetry OFF and telemetry ON
(counters + round-trace spans + a 1 s snapshot emitter + per-stage
profiling) — and:

1. validates every emitted snapshot line against the schema
   (``hotstuff_tpu.telemetry.validate_snapshot``) and checks the
   per-stage profile is present and parses back through
   ``benchmark.logs.read_telemetry_stream``;
2. gates the measured overhead: min-over-repeats per-round time with
   telemetry on must be within ``--budget`` (default 1%) of off.
   Min-of-N with alternating order is the noise-robust estimator on a
   shared CI core; a genuine regression shifts the minimum, scheduler
   noise does not.

Exit code 0 on pass, 1 on schema failure, 2 on budget failure.

    python -m benchmark.telemetry_smoke --nodes 10 --rounds 15
    python -m benchmark.telemetry_smoke --nodes 100 --rounds 20 \
        --output results/telemetry-overhead-100.txt
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402


def _run_once(
    n: int, rounds: int, base_port: int, with_telemetry: bool, snap_path: str | None
):
    from hotstuff_tpu import telemetry
    from benchmark.committee_scale import run_committee

    if with_telemetry:
        telemetry.reset_for_tests()
        telemetry.enable()
    else:
        telemetry.disable()
    try:
        per_round, stage = asyncio.run(
            run_committee(
                n,
                rounds,
                base_port,
                timeout_delay=30_000,
                profile=with_telemetry,
                telemetry_path=snap_path if with_telemetry else None,
            )
        )
    finally:
        telemetry.disable()
    return per_round, stage


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=10)
    p.add_argument("--rounds", type=int, default=15)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument(
        "--budget",
        type=float,
        default=float(os.environ.get("HOTSTUFF_TELEMETRY_BUDGET", "0.01")),
        help="max allowed relative overhead (default 0.01 = 1%%)",
    )
    p.add_argument("--base-port", type=int, default=18000)
    p.add_argument("--output", help="file to append the result summary to")
    args = p.parse_args()

    os.environ.setdefault("HOTSTUFF_TELEMETRY_INTERVAL", "1")

    from benchmark.logs import read_telemetry_stream

    snap_dir = tempfile.mkdtemp(prefix="hotstuff_telemetry_smoke_")
    off_times: list[float] = []
    on_times: list[float] = []
    last_stage = None
    port = args.base_port

    # Discarded warm-up: first-run one-time costs (native lib load, key
    # interning, backend init) must not land on either side of the gate.
    _run_once(args.nodes, max(2, args.rounds // 4), port, False, None)
    port += 2 * args.nodes

    for rep in range(args.repeats):
        order = (False, True) if rep % 2 == 0 else (True, False)
        for with_telemetry in order:
            snap_path = os.path.join(snap_dir, f"telemetry-run{rep}.jsonl")
            per_round, stage = _run_once(
                args.nodes, args.rounds, port, with_telemetry, snap_path
            )
            port += 2 * args.nodes
            if with_telemetry:
                on_times.append(per_round)
                last_stage = stage
            else:
                off_times.append(per_round)

    # -- snapshot schema gate -----------------------------------------------
    problems: list[str] = []
    streams = 0
    for fn in sorted(os.listdir(snap_dir)):
        path = os.path.join(snap_dir, fn)
        try:
            snaps = read_telemetry_stream(path)  # raises on schema violation
        except Exception as e:  # noqa: BLE001
            problems.append(f"{fn}: {e}")
            continue
        streams += 1
        final = snaps[-1]
        for expected in (
            "consensus.rounds_advanced",
            "consensus.qcs_formed",
            "consensus.votes_received",
        ):
            if expected not in final["counters"]:
                problems.append(f"{fn}: missing counter {expected}")
    if streams == 0:
        problems.append("no telemetry streams were emitted")
    if not last_stage:
        problems.append("per-stage profile missing from telemetry registry")

    # -- overhead gate ------------------------------------------------------
    best_off = min(off_times)
    best_on = min(on_times)
    overhead = (best_on - best_off) / best_off

    result = {
        "metric": f"telemetry_overhead_n{args.nodes}",
        "host": host_meta(),
        "off_ms_per_round": round(best_off * 1e3, 2),
        "on_ms_per_round": round(best_on * 1e3, 2),
        "overhead": round(overhead, 4),
        "budget": args.budget,
        "snapshot_streams": streams,
        "schema_problems": problems,
        "stages": {
            k: [ns, calls] for k, (ns, calls) in (last_stage or {}).items()
        },
    }
    print(json.dumps(result))

    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
        with open(args.output, "a") as f:
            f.write(json.dumps(result) + "\n")
            if last_stage:
                f.write(
                    f"per-stage handler cost (all {args.nodes} engines, "
                    f"{args.rounds} measured rounds, telemetry registry):\n"
                )
                f.write(f"  {'stage':<10} {'calls/round':>12} {'us/round':>12}\n")
                for kind, (ns, calls) in sorted(
                    last_stage.items(), key=lambda kv: -kv[1][0]
                ):
                    f.write(
                        f"  {kind:<10} {calls / args.rounds:>12.1f} "
                        f"{ns / 1e3 / args.rounds:>12.1f}\n"
                    )

    if problems:
        print(f"FAIL: schema problems: {problems}", file=sys.stderr)
        sys.exit(1)
    if overhead > args.budget:
        print(
            f"FAIL: telemetry overhead {overhead:.2%} exceeds the "
            f"{args.budget:.2%} budget",
            file=sys.stderr,
        )
        sys.exit(2)
    print(
        f"PASS: telemetry overhead {overhead:+.2%} within {args.budget:.2%}; "
        f"{streams} snapshot stream(s) schema-valid"
    )


if __name__ == "__main__":
    main()
