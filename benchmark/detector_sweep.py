"""Oracle: detector tuning as search — score every Watchtower detector
against thousands of labeled Simulant schedules, then tune
``WatchtowerConfig`` thresholds by coordinate-descent grid search.

The fault plan IS the label set: a compiled faultline schedule says
exactly which peer misbehaved, how, and when, and the sim→stream bridge
(``hotstuff_tpu.sim.streams``) renders the run into the same telemetry
streams the real emitters write — so ``Watchtower.feed`` replays a whole
schedule in milliseconds and precision/recall/time-to-detect become
measurable at corpus scale instead of two seeded wall-clock schedules
per minute (``benchmark/detector_bench.py``).

Corpus (all virtual-clock, all labeled):

- **chaos** schedules (``chaos_scenario``): 4 overlapping incidents each
  — the precision/stress set. Overlapping faults routinely mask each
  other (a crash during another node's link fault is a global stall with
  no contrast to attribute), so chaos incidents are scored but only a
  subset is *pinned*.
- **single-fault** schedules (one seeded fault per run, duration drawn
  ≥ ``PIN_MIN_DURATION_S``): the recall floor. Every one of these is a
  pinned incident — missing any is a gate failure.
- **controls** (fault-free): any alert is a false alarm; the gate
  requires zero.

Pinned incident classes (the recall-1.0 constraint of the search):
``crash``, ``partition``, ``byzantine:silent_leader`` — when the
incident lasts ≥ ``PIN_MIN_DURATION_S`` *and* no other fault overlaps it
(contrast-based detectors cannot attribute a jointly-caused stall) — and
``byzantine:equivocate`` whenever it lasts ≥ ``PIN_MIN_DURATION_S``
(conflicting-digest evidence is direct and survives overlap).
``byzantine:stale_vote_flood`` is labeled but never pinned: the core
drops stale votes before any trace mark, so the flood is invisible to
stream detectors by design (rate-limit territory, not accountability).
``link`` faults are degradation, not misbehavior; labeled, not pinned.

Usage::

    # full tuned-vs-default scorecard + tuned preset (the committed run)
    python -m benchmark.detector_sweep --search \\
        --out results/detector-scorecard-n4.json \\
        --preset-out hotstuff_tpu/telemetry/presets/tuned-n4.json

    # CI gate: evaluate the committed preset, fail on any pinned miss
    # or control false alarm
    python -m benchmark.detector_sweep --seeds 0:500 \\
        --config preset:tuned-n4 --gate --out sweep-ci.json
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import random
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # pragma: no cover - direct invocation
    sys.path.insert(0, _REPO_ROOT)

from benchmark.detector_bench import EXPECTED_DETECTORS, _incidents  # noqa: E402
from benchmark.hostinfo import host_meta  # noqa: E402
from hotstuff_tpu.faultline.policy import Scenario, chaos_scenario  # noqa: E402
from hotstuff_tpu.sim.streams import StreamRecorder  # noqa: E402
from hotstuff_tpu.sim.world import SimWorld  # noqa: E402
from hotstuff_tpu.telemetry.watchtower import (  # noqa: E402
    DETECTOR_CATALOG_VERSION,
    Watchtower,
    WatchtowerConfig,
)

SWEEP_SCHEMA = "hotstuff-detector-sweep-v1"
PRESET_SCHEMA = "hotstuff-watchtower-preset-v1"

#: incident classes the tuned config must reach recall 1.0 on.
PINNED_CLASSES = (
    "crash",
    "partition",
    "byzantine:equivocate",
    "byzantine:silent_leader",
)
#: detectability horizon: incidents shorter than this may begin and end
#: inside one evidence window and are reported, not gated.
PIN_MIN_DURATION_S = 5.0
#: alert-to-incident matching window (virtual seconds): an alert counts
#: for an incident from just before injection to slack past heal
#: (laggard/silent evidence legitimately closes a window or two after).
MATCH_LEAD_S = 1.0
MATCH_SLACK_S = 15.0

#: single-fault scenario kinds (the pinned recall floor).
SINGLE_FAULT_KINDS = (
    "crash",
    "partition",
    "byzantine:equivocate",
    "byzantine:silent_leader",
)

#: coordinate-descent dimensions, in descent order. Window geometry
#: first (it moves recall), score cutoffs and backoffs after (they move
#: precision). The resource-slope budgets (rss/store/digest-queue) are
#: NOT searched: sim streams carry no resource gauges, so those
#: detectors never fire here — they are wall-plane detectors and keep
#: their hand-tuned defaults.
SEARCH_GRID: tuple[tuple[str, tuple], ...] = (
    ("window_s", (2.0, 3.0, 5.0)),
    ("window_rounds", (8, 12, 16)),
    ("min_rounds", (3, 4, 6)),
    ("settle_s", (0.5, 1.0)),
    ("settle_multiplier", (1.0, 1.2, 1.6)),
    ("silent_windows", (1, 2, 3)),
    ("silent_participation_max", (0.05, 0.10, 0.20)),
    ("laggard_windows", (1, 2)),
    ("laggard_min_lag", (4, 6, 8)),
    ("laggard_stale_s", (4.0, 8.0, 12.0)),
    ("grind_timeout_rate", (0.25, 0.4, 0.6)),
    ("grind_min_proposals", (2, 3)),
    ("grind_proposal_stale_s", (0.0, 2.5, 3.0, 4.0)),
    ("alert_min_confidence", (0.0, 0.55, 0.65)),
    ("cooldown_s", (10.0, 15.0, 30.0)),
)

log = logging.getLogger("benchmark.detector_sweep")


# -- corpus ----------------------------------------------------------------


def single_fault_scenario(kind: str, seed: int) -> Scenario:
    """One isolated fault of a pinned class, seeded timing, duration
    drawn comfortably above ``PIN_MIN_DURATION_S``."""
    if kind not in SINGLE_FAULT_KINDS:
        raise ValueError(f"unknown single-fault kind {kind!r}")
    rng = random.Random(f"oracle-single:{kind}:{seed}")
    at = round(rng.uniform(1.5, 2.5), 3)
    hold = round(rng.uniform(5.5, 6.5), 3)
    victim = rng.randrange(1 << 16)
    if kind == "crash":
        events = [
            {"kind": "crash", "node": victim, "at": at},
            {"kind": "restart", "node": victim, "at": round(at + hold, 3)},
        ]
    elif kind == "partition":
        events = [
            {"kind": "partition", "at": at, "until": round(at + hold, 3)}
        ]
    else:
        behavior = kind.split(":", 1)[1]
        events = [
            {
                "kind": "byzantine",
                "behavior": behavior,
                "node": victim,
                "at": at,
                "until": round(at + hold, 3),
            }
        ]
    return Scenario(
        name=f"oracle-{kind.replace(':', '-')}-{seed}",
        seed=seed,
        duration_s=round(at + hold + 3.0, 3),
        events=events,
    )


def control_scenario(seed: int, duration_s: float = 8.0) -> Scenario:
    return Scenario(
        name=f"oracle-control-{seed}",
        seed=seed,
        duration_s=duration_s,
        events=[],
    )


def incident_class(inc: dict) -> str:
    if inc["kind"] == "byzantine":
        return f"byzantine:{inc['behavior']}"
    return inc["kind"]


def _mark_pinned(incidents: list[dict]) -> None:
    """Annotate each incident with its class and pinned flag (see module
    docstring for the pinning rules)."""
    for inc in incidents:
        inc["class"] = incident_class(inc)
        dur = inc["until"] - inc["t"]
        if inc["class"] not in PINNED_CLASSES or dur < PIN_MIN_DURATION_S:
            inc["pinned"] = False
            continue
        if inc["class"] == "byzantine:equivocate":
            inc["pinned"] = True
            continue
        overlapped = any(
            other is not inc
            and other["t"] - 1.0 < inc["until"]
            and other["until"] + 1.0 > inc["t"]
            for other in incidents
        )
        inc["pinned"] = not overlapped


def run_schedule(
    scenario: Scenario,
    *,
    nodes: int = 4,
    interval_s: float = 0.5,
) -> tuple[list, list[dict], dict]:
    """Simulate one scenario with the stream bridge attached; returns
    ``(timeline, incidents, sim_result)``."""
    recorder = StreamRecorder(interval_s=interval_s)
    world = SimWorld(scenario, nodes, recorder=recorder)
    result = world.run()
    incidents = _incidents(world.schedule, scenario.duration_s)
    _mark_pinned(incidents)
    return recorder.timeline(), incidents, result


# -- scoring ---------------------------------------------------------------


def replay_config(timeline: list, config: WatchtowerConfig) -> list[dict]:
    watch = Watchtower(config, label="oracle")
    alerts = watch.feed((obj, node) for _, node, obj in timeline)
    alerts += watch.flush()
    return alerts


def match_alerts(incidents: list[dict], alerts: list[dict]) -> None:
    """Annotate incidents with detection results and alerts with their
    matched flag, in place."""
    for a in alerts:
        a["matched"] = False
    for inc in incidents:
        expected = EXPECTED_DETECTORS.get(inc["kind"], ())
        hits = [
            a
            for a in alerts
            if inc["peer"] in a["accused"]
            and a["detector"] in expected
            and inc["t"] - MATCH_LEAD_S
            <= a["ts"]
            <= inc["until"] + MATCH_SLACK_S
        ]
        for a in hits:
            a["matched"] = True
        inc["detected"] = bool(hits)
        if hits:
            first = min(hits, key=lambda a: a["ts"])
            inc["detected_by"] = first["detector"]
            inc["ttd_s"] = round(max(0.0, first["ts"] - inc["t"]), 3)


class ScoreAccumulator:
    """Streaming metrics over (incidents, alerts) pairs — one instance
    per evaluated config, fed one schedule at a time."""

    def __init__(self) -> None:
        self.schedules = 0
        self.alerts = 0
        self.matched_alerts = 0
        self.control_runs = 0
        self.control_alerts = 0
        self.per_detector: dict[str, dict] = {}
        self.per_class: dict[str, dict] = {}
        self.pinned_misses: list[dict] = []

    def add(self, tag: str, incidents: list[dict], alerts: list[dict],
            *, control: bool = False) -> None:
        self.schedules += 1
        if control:
            self.control_runs += 1
            self.control_alerts += len(alerts)
        match_alerts(incidents, alerts)
        self.alerts += len(alerts)
        self.matched_alerts += sum(a["matched"] for a in alerts)
        for a in alerts:
            d = self.per_detector.setdefault(
                a["detector"], {"alerts": 0, "true_positive": 0, "ttds": []}
            )
            d["alerts"] += 1
            d["true_positive"] += 1 if a["matched"] else 0
        for inc in incidents:
            c = self.per_class.setdefault(
                inc["class"],
                {
                    "incidents": 0,
                    "detected": 0,
                    "pinned": 0,
                    "pinned_detected": 0,
                    "ttds": [],
                    "detected_by": {},
                },
            )
            c["incidents"] += 1
            if inc["detected"]:
                c["detected"] += 1
                c["ttds"].append(inc["ttd_s"])
                by = inc["detected_by"]
                c["detected_by"][by] = c["detected_by"].get(by, 0) + 1
                d = self.per_detector.setdefault(
                    by, {"alerts": 0, "true_positive": 0, "ttds": []}
                )
                d["ttds"].append(inc["ttd_s"])
            if inc["pinned"]:
                c["pinned"] += 1
                if inc["detected"]:
                    c["pinned_detected"] += 1
                else:
                    self.pinned_misses.append(
                        {
                            "schedule": tag,
                            "class": inc["class"],
                            "peer": inc["peer"],
                            "t": round(inc["t"], 3),
                            "duration_s": round(inc["until"] - inc["t"], 3),
                        }
                    )

    # -- derived -----------------------------------------------------------

    @property
    def incidents(self) -> int:
        return sum(c["incidents"] for c in self.per_class.values())

    @property
    def pinned(self) -> int:
        return sum(c["pinned"] for c in self.per_class.values())

    @property
    def pinned_detected(self) -> int:
        return sum(c["pinned_detected"] for c in self.per_class.values())

    @property
    def recall_pinned(self) -> float:
        return self.pinned_detected / self.pinned if self.pinned else 1.0

    @property
    def recall_all(self) -> float:
        n = self.incidents
        return (
            sum(c["detected"] for c in self.per_class.values()) / n
            if n
            else 1.0
        )

    @property
    def precision(self) -> float:
        return self.matched_alerts / self.alerts if self.alerts else 1.0

    @property
    def mean_ttd(self) -> float:
        ttds = [t for c in self.per_class.values() for t in c["ttds"]]
        return sum(ttds) / len(ttds) if ttds else 0.0

    def objective(self) -> tuple:
        """Lexicographic search objective: reach pinned recall, kill
        control false alarms, then precision, recall on everything,
        and finally time-to-detect."""
        return (
            round(self.recall_pinned, 6),
            -self.control_alerts,
            round(self.precision, 6),
            round(self.recall_all, 6),
            -round(self.mean_ttd, 3),
        )

    def feasible(self) -> bool:
        return self.recall_pinned >= 1.0 and self.control_alerts == 0

    def report(self) -> dict:
        def _ttd_stats(ttds):
            if not ttds:
                return None
            s = sorted(ttds)
            return {
                "mean_s": round(sum(s) / len(s), 3),
                "p50_s": round(s[len(s) // 2], 3),
                "max_s": round(s[-1], 3),
            }

        per_detector = {}
        for name, d in sorted(self.per_detector.items()):
            per_detector[name] = {
                "alerts": d["alerts"],
                "true_positive": d["true_positive"],
                "precision": (
                    round(d["true_positive"] / d["alerts"], 3)
                    if d["alerts"]
                    else None
                ),
                "ttd": _ttd_stats(d["ttds"]),
            }
        per_class = {}
        for name, c in sorted(self.per_class.items()):
            per_class[name] = {
                "incidents": c["incidents"],
                "detected": c["detected"],
                "recall": round(c["detected"] / c["incidents"], 3),
                "pinned": c["pinned"],
                "pinned_detected": c["pinned_detected"],
                "detected_by": dict(sorted(c["detected_by"].items())),
                "ttd": _ttd_stats(c["ttds"]),
            }
        return {
            "schedules": self.schedules,
            "incidents": self.incidents,
            "alerts": self.alerts,
            "precision": round(self.precision, 4),
            "recall_all": round(self.recall_all, 4),
            "pinned_incidents": self.pinned,
            "pinned_detected": self.pinned_detected,
            "recall_pinned": round(self.recall_pinned, 4),
            "control_runs": self.control_runs,
            "control_alerts": self.control_alerts,
            "mean_ttd_s": round(self.mean_ttd, 3),
            "per_detector": per_detector,
            "per_class": per_class,
            "pinned_misses": self.pinned_misses[:32],
        }


def score_corpus(
    corpus: list[tuple[str, bool, list, list[dict]]],
    config: WatchtowerConfig,
) -> ScoreAccumulator:
    """Replay a cached corpus (``(tag, is_control, timeline, incidents)``
    tuples) against one config."""
    acc = ScoreAccumulator()
    for tag, is_control, timeline, incidents in corpus:
        alerts = replay_config(timeline, config)
        acc.add(tag, incidents, alerts, control=is_control)
    return acc


# -- search ----------------------------------------------------------------


def coordinate_descent(
    corpus: list,
    base: WatchtowerConfig,
    *,
    grid=SEARCH_GRID,
    max_passes: int = 3,
    progress=None,
) -> tuple[WatchtowerConfig, dict]:
    """Greedy per-dimension grid search: sweep each knob holding the
    rest fixed, keep the best objective, repeat until a full pass makes
    no move (or ``max_passes``)."""
    current = dict(base.__dict__)
    best = score_corpus(corpus, WatchtowerConfig(**current)).objective()
    evaluations = 1
    trajectory = []
    for sweep_pass in range(max_passes):
        moved = False
        for knob, values in grid:
            for value in values:
                if value == current[knob]:
                    continue
                trial = dict(current, **{knob: value})
                obj = score_corpus(corpus, WatchtowerConfig(**trial)).objective()
                evaluations += 1
                if obj > best:
                    best = obj
                    current = trial
                    moved = True
                    trajectory.append(
                        {"pass": sweep_pass, "set": {knob: value},
                         "objective": list(obj)}
                    )
                    if progress:
                        progress(
                            f"pass {sweep_pass}: {knob}={value} -> "
                            f"recall_pinned={obj[0]} controls={-obj[1]} "
                            f"precision={obj[2]}"
                        )
        if not moved:
            break
    return WatchtowerConfig(**current), {
        "evaluations": evaluations,
        "passes": sweep_pass + 1,
        "objective": list(best),
        "trajectory": trajectory,
        "dimensions": [k for k, _ in grid],
    }


# -- evaluation passes -----------------------------------------------------


def _parse_range(spec: str) -> range:
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return range(int(lo), int(hi))
    return range(0, int(spec))


def corpus_specs(args) -> list[tuple[str, bool, Scenario]]:
    """The full evaluation corpus as (tag, is_control, scenario)."""
    specs: list[tuple[str, bool, Scenario]] = []
    for seed in _parse_range(args.seeds):
        specs.append(
            (
                f"chaos-{seed}",
                False,
                chaos_scenario(seed=seed, duration_s=args.duration),
            )
        )
    for seed in range(args.labeled_seeds):
        for kind in SINGLE_FAULT_KINDS:
            scn = single_fault_scenario(kind, seed)
            specs.append((scn.name, False, scn))
    for seed in range(args.controls):
        specs.append(
            (f"control-{seed}", True, control_scenario(90_000 + seed))
        )
    return specs


def evaluate_streaming(
    specs: list,
    config: WatchtowerConfig,
    *,
    nodes: int,
    interval_s: float,
) -> tuple[ScoreAccumulator, dict]:
    """The timed scoring pass: simulate + render + replay + match every
    schedule, nothing cached — the honest schedules/min number."""
    acc = ScoreAccumulator()
    t0 = time.time()
    for tag, is_control, scenario in specs:
        timeline, incidents, _ = run_schedule(
            scenario, nodes=nodes, interval_s=interval_s
        )
        alerts = replay_config(timeline, config)
        acc.add(tag, incidents, alerts, control=is_control)
    wall = time.time() - t0
    timing = {
        "wall_s": round(wall, 2),
        "schedules": len(specs),
        "schedules_per_min": round(len(specs) / wall * 60.0, 1) if wall else None,
    }
    return acc, timing


def _quiet_sim_logs() -> None:
    for name in ("consensus", "network", "faultline", "sim", "store"):
        logging.getLogger(name).setLevel(logging.CRITICAL)


def _load_config(spec: str | None) -> WatchtowerConfig:
    if not spec:
        return WatchtowerConfig()
    if spec.startswith("preset:"):
        return WatchtowerConfig.preset(spec.split(":", 1)[1])
    with open(spec) as f:
        doc = json.load(f)
    return WatchtowerConfig.from_dict(doc.get("config", doc))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seeds", default="0:500",
                   help="chaos seed range lo:hi (the precision corpus)")
    p.add_argument("--labeled-seeds", type=int, default=30,
                   help="seeds per single-fault class (x4 classes: the "
                   "pinned recall floor)")
    p.add_argument("--controls", type=int, default=50,
                   help="fault-free control schedules (zero-alert gate)")
    p.add_argument("--duration", type=float, default=11.0,
                   help="chaos schedule virtual seconds (fault durations "
                   "scale with it: at 11s chaos faults run 1-4.4s, below "
                   "the pin horizon — chaos is the precision set, the "
                   "single-fault families are the recall floor)")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--interval", type=float, default=0.5,
                   help="bridge emit interval (matches the real default)")
    p.add_argument("--search", action="store_true",
                   help="coordinate-descent threshold search before the "
                   "evaluation passes (else evaluate --config only)")
    p.add_argument("--train-seeds", default="0:120",
                   help="chaos seeds for the search corpus")
    p.add_argument("--train-labeled-seeds", type=int, default=15)
    p.add_argument("--train-controls", type=int, default=20)
    p.add_argument("--config", default=None,
                   help="config to evaluate: JSON file or preset:<name>")
    p.add_argument("--out", default=None, help="scorecard JSON path")
    p.add_argument("--preset-out", default=None,
                   help="write the tuned config as a loadable preset")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 unless the evaluated config reaches "
                   "recall 1.0 on pinned incidents with zero control "
                   "alerts")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    _quiet_sim_logs()

    report: dict = {
        "schema": SWEEP_SCHEMA,
        "generated_by": "benchmark.detector_sweep",
        "host": host_meta(),
        "detector_catalog": DETECTOR_CATALOG_VERSION,
        "corpus": {
            "nodes": args.nodes,
            "chaos_seeds": args.seeds,
            "chaos_duration_s": args.duration,
            "single_fault_seeds_per_class": args.labeled_seeds,
            "single_fault_classes": list(SINGLE_FAULT_KINDS),
            "controls": args.controls,
            "emit_interval_s": args.interval,
            "pin_min_duration_s": PIN_MIN_DURATION_S,
            "pinned_classes": list(PINNED_CLASSES),
            "match_lead_s": MATCH_LEAD_S,
            "match_slack_s": MATCH_SLACK_S,
        },
    }

    tuned_cfg = _load_config(args.config)
    if args.search:
        log.info("building search corpus (train seeds %s) ...",
                 args.train_seeds)
        train_specs = corpus_specs(
            argparse.Namespace(
                seeds=args.train_seeds,
                labeled_seeds=args.train_labeled_seeds,
                controls=args.train_controls,
                duration=args.duration,
            )
        )
        t0 = time.time()
        train_corpus = []
        for tag, is_control, scenario in train_specs:
            timeline, incidents, _ = run_schedule(
                scenario, nodes=args.nodes, interval_s=args.interval
            )
            train_corpus.append((tag, is_control, timeline, incidents))
        log.info("search corpus: %d schedules in %.1fs",
                 len(train_corpus), time.time() - t0)
        t0 = time.time()
        tuned_cfg, search_meta = coordinate_descent(
            train_corpus, tuned_cfg, progress=log.info
        )
        search_meta["search_wall_s"] = round(time.time() - t0, 1)
        search_meta["train_schedules"] = len(train_corpus)
        report["search"] = search_meta
        log.info("search: %d evaluations in %.0fs",
                 search_meta["evaluations"], search_meta["search_wall_s"])

    specs = corpus_specs(args)
    default_cfg = WatchtowerConfig()

    log.info("evaluating tuned config over %d schedules ...", len(specs))
    tuned_acc, tuned_timing = evaluate_streaming(
        specs, tuned_cfg, nodes=args.nodes, interval_s=args.interval
    )
    log.info("tuned pass: %.1fs (%s schedules/min)",
             tuned_timing["wall_s"], tuned_timing["schedules_per_min"])
    log.info("evaluating default config over %d schedules ...", len(specs))
    default_acc, default_timing = evaluate_streaming(
        specs, default_cfg, nodes=args.nodes, interval_s=args.interval
    )

    report["default"] = {
        "config": dict(default_cfg.__dict__),
        "config_hash": default_cfg.fingerprint(),
        "timing": default_timing,
        **default_acc.report(),
    }
    report["tuned"] = {
        "config": dict(tuned_cfg.__dict__),
        "config_hash": tuned_cfg.fingerprint(),
        "timing": tuned_timing,
        **tuned_acc.report(),
    }
    report["gate"] = {
        "recall_pinned": round(tuned_acc.recall_pinned, 4),
        "control_alerts": tuned_acc.control_alerts,
        "precision_vs_default": [
            round(tuned_acc.precision, 4),
            round(default_acc.precision, 4),
        ],
        "ok": tuned_acc.feasible(),
    }

    if args.preset_out:
        preset = {
            "schema": PRESET_SCHEMA,
            "name": os.path.splitext(os.path.basename(args.preset_out))[0],
            "config": dict(tuned_cfg.__dict__),
            "config_hash": tuned_cfg.fingerprint(),
            "detector_catalog": DETECTOR_CATALOG_VERSION,
            "provenance": {
                "tool": "benchmark.detector_sweep",
                "corpus": report["corpus"],
                "recall_pinned": round(tuned_acc.recall_pinned, 4),
                "precision": round(tuned_acc.precision, 4),
                "control_alerts": tuned_acc.control_alerts,
            },
        }
        os.makedirs(os.path.dirname(args.preset_out) or ".", exist_ok=True)
        with open(args.preset_out, "w") as f:
            json.dump(preset, f, indent=2, sort_keys=True)
            f.write("\n")
        log.info("tuned preset written to %s", args.preset_out)

    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        log.info("scorecard written to %s", args.out)

    summary = report["gate"]
    log.info(
        "sweep: %d schedules, %d incidents (%d pinned) | tuned: "
        "precision=%.3f recall_pinned=%.3f controls=%d | default: "
        "precision=%.3f recall_pinned=%.3f",
        tuned_acc.schedules, tuned_acc.incidents, tuned_acc.pinned,
        tuned_acc.precision, tuned_acc.recall_pinned,
        tuned_acc.control_alerts, default_acc.precision,
        default_acc.recall_pinned,
    )
    if args.gate and not summary["ok"]:
        log.error("GATE FAIL: recall_pinned=%.4f control_alerts=%d "
                  "(pinned misses: %s)",
                  tuned_acc.recall_pinned, tuned_acc.control_alerts,
                  tuned_acc.pinned_misses[:5])
        sys.exit(1)


if __name__ == "__main__":
    main()
