"""Diagnostic for the reputation-elector committee: boots 4-node
committees repeatedly and, on a commit stall with advancing rounds (the
"timeout grind" signature — proposals dying silently to the
unsolicited-block gate while TCs keep rounds moving), dumps every
node's election picks and anchored windows. Used to chase the rare
(~1-in-20 pytest runs) residual liveness issue documented in ROADMAP.

    python -m benchmark.diag_reputation
"""

import asyncio
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
logging.basicConfig(level=logging.CRITICAL)
from hotstuff_tpu.consensus import Authority, Committee, Consensus, Parameters
from hotstuff_tpu.crypto import SignatureService, generate_keypair
from hotstuff_tpu.store import Store

async def run_once(run_idx):
    n = 4
    base = 27000 + (run_idx % 40) * 20
    kps = [generate_keypair() for _ in range(n)]
    committee = Committee(authorities={pk: Authority(stake=1, address=("127.0.0.1", base + i)) for i, (pk, _) in enumerate(kps)})
    params = Parameters(timeout_delay=5_000, leader_elector="reputation")
    engines, commits, sinks, cores = [], [], [], []
    for pk, sk in kps:
        rxm, txm, txc = asyncio.Queue(), asyncio.Queue(), asyncio.Queue()
        async def drain(q=txm):
            while True: await q.get()
        sinks.append(asyncio.create_task(drain()))
        eng = await Consensus.spawn(pk, committee, params, SignatureService(sk), Store(), rxm, txm, txc)
        engines.append(eng); commits.append(txc)
        cores.append(eng.tasks[0].get_coro().cr_frame.f_locals.get("self"))
    names = {pk: f"n{i}" for i, (pk, _) in enumerate(kps)}
    got = [0]*n
    async def counter(i, q):
        while True:
            await q.get(); got[i] += 1
    cnt = [asyncio.create_task(counter(i, q)) for i, q in enumerate(commits)]
    grind = False
    last = None
    stall_ticks = 0
    for t in range(60):
        await asyncio.sleep(0.5)
        if min(got) >= 12: break
        state = tuple(got)
        stall_ticks = stall_ticks + 1 if state == last else 0
        last = state
        if stall_ticks >= 12:  # 6s no commit anywhere but rounds moving?
            grind = True
            print(f"GRIND run={run_idx} commits={got} rounds={[c.round for c in cores]}")
            for i, c in enumerate(cores):
                el = c.leader_elector
                r = c.round
                picks = {rr: names.get(el.get_leader(rr), "?") for rr in range(r, r+4)}
                win = [(e[0], names.get(e[1], "gen"), tuple(sorted(names.get(s,"?") for s in e[2]))) for e in el._window]
                print(f"  n{i}: round={r} picks={picks}")
                print(f"       window={win}")
            break
    print(f"run {run_idx}: commits={got} grind={grind}")
    for e in engines: await e.shutdown()
    for s in sinks + cnt: s.cancel()
    return grind

async def main():
    for i in range(25):
        if await run_once(i): break

asyncio.run(main())
