"""Host metadata for benchmark artifacts.

Every committed perf row is meaningless without the box it ran on —
all rows before PR 17 came from an undocumented one-core container.
``host_meta()`` is the one shared helper the harnesses stamp into
their artifact metadata so a future reader (or the perf-regress gate)
can tell a real regression from a host-class change.
"""

from __future__ import annotations

import os
import platform


def host_meta() -> dict:
    """Return ``{"cpu_count": N, "cpu_model": str}`` for this host."""
    model = None
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 says "model name"; some ARM kernels say "model" or
                # "Hardware" — take the first model-ish line we find.
                if line.lower().startswith(("model name", "hardware", "cpu model")):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    if not model:
        model = platform.processor() or platform.machine() or "unknown"
    return {"cpu_count": os.cpu_count() or 1, "cpu_model": model}
