"""Aggregate benchmark result files into plot-ready series (reference
``benchmark/benchmark/aggregate.py``).

Result ``.txt`` files contain one or more SUMMARY blocks (repeated runs of
the same setup are appended to the same file); aggregation computes
mean ± stdev over the runs and emits series:

- latency-vs-rate (L-graph) per (faults, nodes, tx_size)
- tps-vs-nodes (scalability) per (faults, rate, tx_size)
"""

from __future__ import annotations

import glob
import os
from collections import defaultdict
from dataclasses import dataclass, field
from re import search
from statistics import mean, stdev

from .utils import PathMaker


@dataclass(frozen=True)
class Setup:
    faults: int
    nodes: int
    rate: int
    tx_size: int

    @classmethod
    def from_block(cls, raw: str) -> "Setup":
        return cls(
            faults=int(search(r"Faults: (\d+)", raw).group(1)),
            nodes=int(search(r"Committee size: (\d+)", raw).group(1)),
            rate=int(search(r"Input rate: ([\d,]+)", raw).group(1).replace(",", "")),
            tx_size=int(
                search(r"Transaction size: ([\d,]+)", raw).group(1).replace(",", "")
            ),
        )


@dataclass
class Measurement:
    tps: list[int] = field(default_factory=list)
    latency: list[int] = field(default_factory=list)

    def add(self, raw: str) -> None:
        self.tps.append(
            int(search(r"End-to-end TPS: ([\d,]+)", raw).group(1).replace(",", ""))
        )
        self.latency.append(
            int(
                search(r"End-to-end latency: ([\d,]+)", raw).group(1).replace(",", "")
            )
        )

    def mean_tps(self) -> float:
        return mean(self.tps) if self.tps else 0

    def std_tps(self) -> float:
        return stdev(self.tps) if len(self.tps) > 1 else 0

    def mean_latency(self) -> float:
        return mean(self.latency) if self.latency else 0

    def std_latency(self) -> float:
        return stdev(self.latency) if len(self.latency) > 1 else 0


class LogAggregator:
    def __init__(self, results_dir: str | None = None) -> None:
        self.data: dict[Setup, Measurement] = defaultdict(Measurement)
        directory = results_dir or PathMaker.results_path()
        for fn in sorted(glob.glob(os.path.join(directory, "bench-*.txt"))):
            with open(fn) as f:
                raw = f.read()
            # One SUMMARY block per run; repeated runs append to the file.
            for block in raw.split(" SUMMARY:")[1:]:
                setup = Setup.from_block(block)
                self.data[setup].add(block)

    def latency_vs_rate(self, faults: int, nodes: int, tx_size: int):
        """[(rate, mean_tps, std_tps, mean_latency, std_latency)] sorted by
        input rate — the L-graph series."""
        rows = [
            (s.rate, m.mean_tps(), m.std_tps(), m.mean_latency(), m.std_latency())
            for s, m in self.data.items()
            if s.faults == faults and s.nodes == nodes and s.tx_size == tx_size
        ]
        return sorted(rows)

    def tps_vs_nodes(self, faults: int, tx_size: int, max_latency: float | None = None):
        """Best achievable TPS per committee size (optionally under a
        latency cap) — the scalability series."""
        best: dict[int, tuple] = {}
        for s, m in self.data.items():
            if s.faults != faults or s.tx_size != tx_size:
                continue
            if max_latency is not None and m.mean_latency() > max_latency:
                continue
            cur = best.get(s.nodes)
            if cur is None or m.mean_tps() > cur[1]:
                best[s.nodes] = (s.nodes, m.mean_tps(), m.std_tps())
        return sorted(best.values())

    def print_series(self, out_dir: str | None = None) -> list[str]:
        """Write agg files per setup family; returns the paths."""
        out_dir = out_dir or PathMaker.plots_path()
        os.makedirs(out_dir, exist_ok=True)
        written = []
        families = {(s.faults, s.nodes, s.tx_size) for s in self.data}
        for faults, nodes, tx_size in sorted(families):
            path = os.path.join(
                out_dir,
                os.path.basename(PathMaker.agg_file("l", faults, nodes, "x", tx_size)),
            )
            with open(path, "w") as f:
                f.write("rate tps tps_std latency latency_std\n")
                for row in self.latency_vs_rate(faults, nodes, tx_size):
                    f.write(" ".join(str(round(x)) for x in row) + "\n")
            written.append(path)
        return written
