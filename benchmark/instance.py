"""AWS EC2 testbed lifecycle (reference ``benchmark/benchmark/instance.py``).

The reference manages m5d.8xlarge instances across 5 regions with boto3 and
opens the consensus/mempool/front ports in a security group. boto3 is not
available in this build environment, so the manager degrades to a clear
error at construction; the interface (create/terminate/start/stop/hosts)
matches the reference so harness code written against it ports over
unchanged once boto3 is installed.
"""

from __future__ import annotations

from .settings import Settings
from .utils import Print

try:
    import boto3  # type: ignore

    HAVE_BOTO3 = True
except ImportError:
    HAVE_BOTO3 = False


class AWSError(Exception):
    pass


class InstanceManager:
    SECURITY_GROUP_PORTS = ("consensus", "mempool", "front", 22)

    def __init__(self, settings: Settings) -> None:
        if not HAVE_BOTO3:
            raise AWSError(
                "boto3 is not installed in this environment; provision hosts "
                "manually and pass them to RemoteBench(settings, hosts), or "
                "install boto3 to enable AWS lifecycle management"
            )
        self.settings = settings
        self.clients = {
            region: boto3.client("ec2", region_name=region)
            for region in settings.aws_regions
        }

    def _filters(self):
        return [
            {"Name": "tag:testbed", "Values": [self.settings.testbed]},
            {
                "Name": "instance-state-name",
                "Values": ["pending", "running", "stopping", "stopped"],
            },
        ]

    def create(self, instances_per_region: int) -> None:
        for region, client in self.clients.items():
            client.run_instances(
                ImageId=self._ubuntu_ami(client),
                InstanceType=self.settings.instance_type,
                KeyName=self.settings.key_name,
                MinCount=instances_per_region,
                MaxCount=instances_per_region,
                TagSpecifications=[
                    {
                        "ResourceType": "instance",
                        "Tags": [
                            {"Key": "testbed", "Value": self.settings.testbed}
                        ],
                    }
                ],
            )
            Print.info(f"created {instances_per_region} instances in {region}")

    @staticmethod
    def _ubuntu_ami(client) -> str:
        images = client.describe_images(
            Owners=["099720109477"],  # Canonical
            Filters=[
                {
                    "Name": "name",
                    "Values": ["ubuntu/images/hvm-ssd/ubuntu-jammy-22.04-amd64-server-*"],
                }
            ],
        )["Images"]
        return max(images, key=lambda i: i["CreationDate"])["ImageId"]

    def hosts(self) -> list[str]:
        out = []
        for client in self.clients.values():
            for resv in client.describe_instances(Filters=self._filters())[
                "Reservations"
            ]:
                for inst in resv["Instances"]:
                    if inst.get("PublicIpAddress"):
                        out.append(inst["PublicIpAddress"])
        return out

    def terminate(self) -> None:
        for client in self.clients.values():
            ids = [
                inst["InstanceId"]
                for resv in client.describe_instances(Filters=self._filters())[
                    "Reservations"
                ]
                for inst in resv["Instances"]
            ]
            if ids:
                client.terminate_instances(InstanceIds=ids)
