"""Device vs host SHA-512 batch digesting (BASELINE config 3 decision).

Measures the mempool Processor's two digest paths at a drain of K batches
of S bytes each (the ``device_batch_digests`` opportunistic drain,
``mempool/processor.py``): host hashlib per batch vs one batched device
dispatch. Emits one line per configuration and a recommendation, appended
to ``results/digest-bench-<backend>.txt`` with ``--output``.

    python -m benchmark.digest_bench --output results
"""

from __future__ import annotations

import argparse
import hashlib
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hotstuff_tpu.utils.jaxcache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()


def bench(k: int, size: int, iters: int = 5) -> tuple[float, float]:
    """Returns (host_s, device_s) to digest k batches of `size` bytes."""
    from hotstuff_tpu.ops.sha512 import sha512_32_batch

    rng = random.Random(42)
    batches = [rng.randbytes(size) for _ in range(k)]

    # Correctness first: the device path must match hashlib bit-for-bit.
    dev = sha512_32_batch(batches)
    host = [hashlib.sha512(b).digest()[:32] for b in batches]
    assert list(dev) == host, "device SHA-512 diverges from hashlib"

    t0 = time.perf_counter()
    for _ in range(iters):
        [hashlib.sha512(b).digest()[:32] for b in batches]
    host_s = (time.perf_counter() - t0) / iters

    sha512_32_batch(batches)  # warm (compile cached)
    t0 = time.perf_counter()
    for _ in range(iters):
        sha512_32_batch(batches)
    device_s = (time.perf_counter() - t0) / iters
    return host_s, device_s


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", help="directory to append the result file to")
    p.add_argument("--sizes", default="512,15000,500000")
    p.add_argument("--drains", default="8,32,128")
    p.add_argument(
        "--platform",
        help="force a jax platform (e.g. cpu). NOTE: this environment "
        "pins jax_platforms to the tunneled axon TPU plugin at "
        "interpreter startup and the JAX_PLATFORMS env var does NOT "
        "override it — only jax.config (set here, before backend init) "
        "does.",
    )
    args = p.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    backend = jax.default_backend()
    lines = []
    wins = 0
    total = 0
    for size in (int(s) for s in args.sizes.split(",")):
        for k in (int(d) for d in args.drains.split(",")):
            host_s, dev_s = bench(k, size)
            total += 1
            wins += dev_s < host_s
            lines.append(
                f"digest k={k} size={size}B backend={backend}: "
                f"host {host_s * 1e3:.2f} ms, device {dev_s * 1e3:.2f} ms "
                f"({host_s / dev_s:.2f}x)"
            )
            print(lines[-1], flush=True)
    rec = (
        "RECOMMEND device_batch_digests=True"
        if wins > total / 2
        else "RECOMMEND device_batch_digests=False (host hashing wins here)"
    )
    lines.append(f"{rec} [{wins}/{total} device wins]")
    print(lines[-1])
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        path = os.path.join(args.output, f"digest-bench-{backend}.txt")
        with open(path, "a") as f:
            f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
