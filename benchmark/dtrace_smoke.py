"""Lifeline smoke + overhead gate (the dtrace sibling of
``benchmark/watchtower_smoke.py``).

Runs an in-process micro data plane per leg — one REAL Conveyor worker
sealing/certifying production-weight batches against three acking peer
doubles, telemetry streaming throughout — and gates two things:

1. **Attribution fixture check** — the attached leg's stream carries
   ``hotstuff-dtrace-v1`` records and ``benchmark/dtrace_assemble.py``
   assembles them into batch lifelines with the data-plane edges
   (ingress_wait → seal → disseminate → ack_fanin) populated. The
   consensus-side edges are covered by the full-lifecycle fixtures in
   ``tests/test_dtrace_assemble.py``. A fully env-detached leg must
   conversely leave ZERO dtrace records (the ``HOTSTUFF_DTRACE=0``
   switch works end to end).
2. **Overhead budget** (default <1%) — measured as the median of
   per-batch PAIRED differences: each measurement leg alternates the
   lifeline plane per batch (attached, detached, attached, ...) inside
   one process and reports the median attached-minus-detached CPU delta
   over adjacent pairs. Pairing spans milliseconds, so CPU-frequency
   drift, co-tenant load, and GC pressure cancel instead of swamping a
   sub-1%% signal the way whole-leg wall-clock comparison does on
   shared CI runners. Legs run in FRESH subprocesses (one leg = one
   subprocess, alternating starting parity) and the gate takes the
   median across legs.

Exit 0 on pass, 1 on stream/assembly/switch failure, 2 on budget
failure.

    python -m benchmark.dtrace_smoke --batches 48 --repeats 3
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import struct
import subprocess
import sys
import tempfile
import time

from benchmark.hostinfo import host_meta

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the edges the micro data plane can close (no consensus in the loop:
#: queue_wait and later edges stay open by construction).
DATAPLANE_EDGES = ("ingress_wait", "seal", "disseminate", "ack_fanin")


async def _acking_peer(port: int, secret):
    """A peer worker double: acks every batch frame it receives."""
    from hotstuff_tpu.crypto import Signature, sha512_digest
    from hotstuff_tpu.mempool.dataplane import ack_digest
    from hotstuff_tpu.mempool.dataplane import messages as dpm

    async def handle(reader, writer):
        try:
            while True:
                hdr = await reader.readexactly(4)
                (n,) = struct.unpack(">I", hdr)
                frame = await reader.readexactly(n)
                if frame[0] == dpm.TAG_BATCH:
                    digest = sha512_digest(frame)
                    sig = Signature.new(ack_digest(digest), secret)
                    ack = dpm.encode_ack(digest, secret.public_key(), sig)
                    writer.write(struct.pack(">I", len(ack)) + ack)
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass

    return await asyncio.start_server(handle, "127.0.0.1", port)


async def _drive(
    batches: int,
    tx_size: int,
    batch_bytes: int,
    base_port: int,
    paired: bool,
    start_attached: bool,
) -> dict:
    """Seal + certify batches of ``batch_bytes`` through one real
    worker. ``batch_bytes`` defaults near the production
    ``Parameters.batch_size`` so the overhead denominator reflects what
    a real batch costs — gating tiny toy batches would overstate the
    constant per-batch trace cost ~100x.

    In ``paired`` mode, drives ``batches`` adjacent (attached, detached)
    batch pairs toggling :func:`telemetry.set_dtrace_detached` between
    them, and reports the median paired CPU delta. Otherwise drives
    ``batches`` batches under whatever the environment configured and
    reports the median per-batch CPU."""
    from hotstuff_tpu import telemetry
    from hotstuff_tpu.crypto import SignatureService, generate_keypair
    from hotstuff_tpu.mempool import Parameters, WorkerEntry
    from hotstuff_tpu.mempool.config import Authority, Committee
    from hotstuff_tpu.mempool.dataplane import Watermark, Worker
    from hotstuff_tpu.mempool.dataplane import messages as dpm
    from hotstuff_tpu.store import Store

    ks = [generate_keypair() for _ in range(4)]
    committee = Committee(
        authorities={
            pk: Authority(
                stake=1,
                transactions_address=("127.0.0.1", base_port + i),
                mempool_address=("127.0.0.1", base_port + 20 + i),
                workers=[
                    WorkerEntry(
                        transactions_address=("127.0.0.1", base_port + 40 + i),
                        worker_address=("127.0.0.1", base_port + 60 + i),
                    )
                ],
            )
            for i, (pk, _) in enumerate(ks)
        }
    )
    name = ks[0][0]
    servers = [
        await _acking_peer(committee.worker_address(pk, 0)[1], sk)
        for pk, sk in ks[1:]
    ]
    txs_per_batch = max(1, batch_bytes // tx_size)
    tx_consensus: asyncio.Queue = asyncio.Queue()
    worker = Worker(
        name,
        0,
        committee,
        Parameters(
            batch_size=txs_per_batch * tx_size,
            max_batch_delay=5_000,
            workers=1,
        ),
        Store(),
        SignatureService(ks[0][1]),
        tx_consensus,
        Watermark(4 * batch_bytes, 2 * batch_bytes),
    )
    await worker.spawn()
    _, writer = await asyncio.open_connection(
        "127.0.0.1", committee.workers_of(name)[0].transactions_address[1]
    )
    seq = 0

    def tx() -> bytes:
        nonlocal seq
        seq += 1
        return b"\x00" + seq.to_bytes(8, "big") + bytes(tx_size - 9)

    async def one_batch() -> float:
        c0 = time.process_time()
        for start in range(0, txs_per_batch, 8):
            n = min(8, txs_per_batch - start)
            frame = dpm.encode_bundle([tx() for _ in range(n)])
            writer.write(struct.pack(">I", len(frame)) + frame)
        await writer.drain()
        await asyncio.wait_for(tx_consensus.get(), 15)
        return time.process_time() - c0

    # Warm the path end to end before the measured window.
    await one_batch()

    if paired:
        diffs: list[float] = []
        offs: list[float] = []
        for _ in range(batches):
            pair = {}
            order = (True, False) if start_attached else (False, True)
            for attached in order:
                telemetry.set_dtrace_detached(not attached)
                pair[attached] = await one_batch()
            start_attached = not start_attached
            diffs.append(pair[True] - pair[False])
            offs.append(pair[False])
        result = {
            "pair_delta": statistics.median(diffs),
            "off_cpu_per_batch": statistics.median(offs),
        }
    else:
        samples = [await one_batch() for _ in range(batches)]
        result = {"cpu_per_batch": statistics.median(samples)}

    writer.close()
    await worker.shutdown()
    for s in servers:
        s.close()
    return result


def _run_once(args) -> dict:
    from hotstuff_tpu import telemetry

    telemetry.reset_for_tests()
    telemetry.enable()
    emitter = telemetry.TelemetryEmitter(
        telemetry.get_registry(),
        args.snap,
        node="dtrace-smoke",
        interval_s=1.0,
        trace=telemetry.trace_buffer(),
        dtrace=telemetry.dtrace_buffer(),
    )
    try:
        dtrace_on = telemetry.dtrace_enabled()
        result = asyncio.run(
            _drive(
                args.batches,
                args.tx_size,
                args.batch_bytes,
                args.base_port,
                args.paired,
                args.start_attached,
            )
        )
    finally:
        emitter.emit(final=True)
        telemetry.disable()
    return dict(result, dtrace_on=dtrace_on)


def _spawn_once(
    args, *, batches: int, port: int, snap_path: str,
    paired: bool = False, attached: bool = True, start_attached: bool = True,
) -> dict:
    """One leg in a fresh subprocess. Non-paired legs configure the
    lifeline plane via ``HOTSTUFF_DTRACE`` so the end-to-end environment
    switch itself is exercised; paired legs toggle it internally."""
    cmd = [
        sys.executable, "-m", "benchmark.dtrace_smoke", "--one-shot",
        "--batches", str(batches), "--tx-size", str(args.tx_size),
        "--batch-bytes", str(args.batch_bytes),
        "--base-port", str(port), "--snap", snap_path,
    ]
    if paired:
        cmd.append("--paired")
        if start_attached:
            cmd.append("--start-attached")
    env = dict(os.environ)
    env["HOTSTUFF_DTRACE"] = "1" if (attached or paired) else "0"
    proc = subprocess.run(
        cmd, capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"one-shot leg failed (rc={proc.returncode}):\n{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _count_dtrace_records(snap_path: str) -> int:
    from hotstuff_tpu.telemetry import DTRACE_SCHEMA

    count = 0
    with open(snap_path) as f:
        for line in f:
            try:
                if json.loads(line).get("schema") == DTRACE_SCHEMA:
                    count += 1
            except json.JSONDecodeError:
                continue
    return count


def _check_attribution(snap_path: str) -> tuple[dict | None, list[str]]:
    """The fixture check: the attached stream must assemble into batch
    lifelines with every data-plane edge populated."""
    from benchmark.dtrace_assemble import assemble

    problems: list[str] = []
    try:
        report = assemble([snap_path])
    except Exception as e:  # noqa: BLE001 — a crash here IS the failure
        return None, [f"dtrace assembly crashed: {e}"]
    if report["batches"] == 0:
        problems.append("attached stream assembled zero batch lifelines")
    for edge in DATAPLANE_EDGES:
        stats = report["edges"].get(edge)
        if not stats or stats["n"] == 0:
            problems.append(f"edge {edge!r} got no attribution")
    return report, problems


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--batches", type=int, default=48,
        help="batch PAIRS per measurement leg",
    )
    p.add_argument("--tx-size", type=int, default=4096)
    p.add_argument(
        "--batch-bytes",
        type=int,
        default=500_000,
        help="sealed batch size; the production Parameters.batch_size "
        "default, so the overhead denominator is what a real batch costs",
    )
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument(
        "--budget",
        type=float,
        default=float(os.environ.get("HOTSTUFF_DTRACE_BUDGET", "0.01")),
        help="max allowed relative overhead (default 0.01 = 1%%)",
    )
    p.add_argument("--base-port", type=int, default=21500)
    p.add_argument("--output", help="file to append the result summary to")
    p.add_argument(
        "--work-dir",
        help="where the legs' telemetry streams land (default: a fresh "
        "temp dir); CI points this at the workspace so failures upload "
        "the evidence",
    )
    # Internal: one measurement leg (see _spawn_once).
    p.add_argument("--one-shot", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--snap", help=argparse.SUPPRESS)
    p.add_argument("--paired", action="store_true", help=argparse.SUPPRESS)
    p.add_argument(
        "--start-attached", action="store_true", help=argparse.SUPPRESS
    )
    args = p.parse_args()

    if args.one_shot:
        print(json.dumps(_run_once(args)))
        return

    if args.work_dir:
        snap_dir = os.path.abspath(args.work_dir)
        os.makedirs(snap_dir, exist_ok=True)
    else:
        snap_dir = tempfile.mkdtemp(prefix="hotstuff_dtrace_smoke_")
    problems: list[str] = []
    port = args.base_port
    fixture_batches = max(8, args.batches // 4)

    # Attached leg: warms every code path AND provides the fully-traced
    # stream for the attribution fixture check.
    attached_snap = os.path.join(snap_dir, "telemetry-attached.jsonl")
    leg = _spawn_once(
        args, batches=fixture_batches, port=port, snap_path=attached_snap,
        attached=True,
    )
    port += 100
    if leg["dtrace_on"] is not True:
        problems.append("HOTSTUFF_DTRACE=1 leg came up detached")
    report, attr_problems = _check_attribution(attached_snap)
    problems.extend(attr_problems)

    # Env-detached leg: the production off-switch must leave no trace.
    detached_snap = os.path.join(snap_dir, "telemetry-detached.jsonl")
    leg = _spawn_once(
        args, batches=fixture_batches, port=port, snap_path=detached_snap,
        attached=False,
    )
    port += 100
    if leg["dtrace_on"] is not False:
        problems.append("HOTSTUFF_DTRACE=0 leg came up attached")
    if (n := _count_dtrace_records(detached_snap)) != 0:
        problems.append(f"HOTSTUFF_DTRACE=0 leg streamed {n} dtrace records")

    # Measurement legs: paired per-batch alternation, fresh subprocess
    # each, starting parity alternating across legs.
    overheads: list[float] = []
    off_ms: list[float] = []
    for rep in range(args.repeats):
        leg = _spawn_once(
            args,
            batches=args.batches,
            port=port,
            snap_path=os.path.join(snap_dir, f"telemetry-paired-{rep}.jsonl"),
            paired=True,
            start_attached=rep % 2 == 0,
        )
        port += 100
        overheads.append(leg["pair_delta"] / leg["off_cpu_per_batch"])
        off_ms.append(leg["off_cpu_per_batch"] * 1e3)

    overhead = statistics.median(overheads)
    result = {
        "metric": f"dtrace_overhead_p{args.batches}x{args.repeats}",
        "host": host_meta(),
        "off_cpu_ms_per_batch": round(statistics.median(off_ms), 3),
        "overhead": round(overhead, 4),
        "leg_overheads": [round(o, 4) for o in overheads],
        "budget": args.budget,
        "batches_assembled": report["batches"] if report else 0,
        "edges": (
            {e: report["edges"][e]["mean_ms"] for e in DATAPLANE_EDGES}
            if report and not attr_problems
            else None
        ),
        "snap_dir": snap_dir,
        "problems": problems,
    }
    print(json.dumps(result))

    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
        with open(args.output, "a") as f:
            f.write(json.dumps(result) + "\n")

    if problems:
        print(f"FAIL: {problems}", file=sys.stderr)
        sys.exit(1)
    if overhead > args.budget:
        print(
            f"FAIL: dtrace overhead {overhead:.2%} exceeds the "
            f"{args.budget:.2%} budget",
            file=sys.stderr,
        )
        sys.exit(2)
    print(
        f"PASS: dtrace overhead {overhead:+.2%} within {args.budget:.2%}; "
        f"{result['batches_assembled']} lifeline(s) assembled with all "
        "data-plane edges attributed; HOTSTUFF_DTRACE switch verified "
        "both ways"
    )


if __name__ == "__main__":
    main()
