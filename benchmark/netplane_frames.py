"""Large-frame transport microbenchmark: asyncio vs native, one edge.

ROADMAP 3a: the sharded dataplane bench under ``HOTSTUFF_NET=native``
measured WORSE than asyncio at large batch frames (9.8k vs 30k tx/s at
60k offered, ~387 KB frames). This isolates exactly that edge — one
reliable sender blasting fixed-size frames at one ACKing receiver over
loopback, the batch-dissemination shape (``mempool/batch_maker.py``
broadcasts via ReliableSender; the QuorumWaiter consumes the ACKs) —
so the two transports can be profiled head-to-head without the rest of
the committee attached.

Usage:
    python -m benchmark.netplane_frames --sizes 1024,65536,396288 \
        --frames 200 --window 32 [--json results/netplane-frames.json]

Prints frames/s and MB/s per (transport, size) and, for the native
plane, the engine's own counter deltas (writev calls, poll/dispatch ns,
drain bytes) so a regression localizes to a stage instead of a vibe.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

from benchmark.hostinfo import host_meta
from hotstuff_tpu.network.receiver import MessageHandler, Receiver
from hotstuff_tpu.network.reliable_sender import ReliableSender


class _AckHandler(MessageHandler):
    """The mempool helper's shape: store (here: count) then ACK."""

    def __init__(self) -> None:
        self.frames = 0
        self.bytes = 0

    async def dispatch(self, writer, message: bytes) -> None:
        self.frames += 1
        self.bytes += len(message)
        await writer.send(b"Ack")


async def _pump(sender, addr, payload: bytes, frames: int, window: int) -> None:
    """Windowed reliable pipeline: keep ``window`` frames in flight,
    await ACKs as they land (the QuorumWaiter consumes handlers the same
    way; PENDING_CAP back-pressure engages above the window)."""
    inflight: set[asyncio.Future] = set()
    for _ in range(frames):
        handler = await sender.send(addr, payload)
        inflight.add(asyncio.ensure_future(handler))
        if len(inflight) >= window:
            done, inflight = await asyncio.wait(
                inflight, return_when=asyncio.FIRST_COMPLETED
            )
    if inflight:
        await asyncio.wait(inflight)


async def _run_one(transport: str, size: int, frames: int, window: int,
                   port: int) -> dict:
    if transport == "native":
        from hotstuff_tpu.network import native

        receiver_cls, sender_cls = native.NativeReceiver, native.NativeReliableSender
        t = native.NativeTransport.get()
        stats0 = t.stats()
    else:
        receiver_cls, sender_cls = Receiver, ReliableSender
        stats0 = {}
    handler = _AckHandler()
    addr = ("127.0.0.1", port)
    receiver = await receiver_cls.spawn(addr, handler)
    sender = sender_cls()
    payload = b"\xab" * size
    # Warmup (connection establishment, JIT-ish paths) outside the clock.
    await _pump(sender, addr, payload, min(8, frames), window)
    warm = handler.frames
    t0 = time.perf_counter()
    await _pump(sender, addr, payload, frames, window)
    # The clock stops when every ACK is back — ingest AND egress priced.
    elapsed = time.perf_counter() - t0
    result = {
        "transport": transport,
        "host": host_meta(),
        "size": size,
        "frames": frames,
        "window": window,
        "elapsed_s": elapsed,
        "frames_per_s": frames / elapsed,
        "mb_per_s": frames * size / elapsed / 1e6,
        "received": handler.frames - warm,
    }
    if transport == "native":
        stats1 = t.stats()
        result["native_delta"] = {
            k: stats1.get(k, 0) - stats0.get(k, 0)
            for k in (
                "frames_tx", "bytes_tx", "frames_rx", "bytes_rx",
                "writev_calls", "loop_polls", "poll_ns", "dispatch_ns",
                "cmds_serviced", "cmd_service_ns",
            )
        }
    sender.shutdown()
    await receiver.shutdown()
    await asyncio.sleep(0.05)  # let the listener close before reuse
    return result


async def _main(args) -> list[dict]:
    rows = []
    port = args.base_port
    for size in args.sizes:
        for transport in args.transports:
            port += 1
            row = await _run_one(
                transport, size, args.frames, args.window, port
            )
            rows.append(row)
            line = (
                f"{transport:>7} size={size:>8,}B frames={args.frames} "
                f"window={args.window}: {row['frames_per_s']:>9,.1f} fr/s "
                f"{row['mb_per_s']:>9,.1f} MB/s"
            )
            nd = row.get("native_delta")
            if nd:
                per_frame_polls = nd["loop_polls"] / max(1, args.frames)
                line += (
                    f"  [writev={nd['writev_calls']} polls/frame="
                    f"{per_frame_polls:.1f} dispatch_ms="
                    f"{nd['dispatch_ns'] / 1e6:.1f}]"
                )
            print(line, flush=True)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--sizes", default="1024,65536,396288",
        help="comma-separated frame payload sizes in bytes",
    )
    ap.add_argument("--frames", type=int, default=200)
    ap.add_argument("--window", type=int, default=32)
    ap.add_argument(
        "--transports", default="asyncio,native",
        help="comma-separated subset of asyncio,native",
    )
    ap.add_argument("--base-port", type=int, default=17480)
    ap.add_argument("--json", default=None, help="write rows to this path")
    args = ap.parse_args(argv)
    args.sizes = [int(s) for s in args.sizes.split(",") if s]
    args.transports = [t for t in args.transports.split(",") if t]
    rows = asyncio.run(_main(args))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(
                {"bench": "netplane_frames", "rows": rows}, f, indent=2
            )
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
