"""Live watchtower: tail the telemetry streams of a running committee,
score every peer, and fire alerts WHILE the run is going.

    # live: follow a local bench's logs directory until Ctrl-C
    python -m benchmark.watchtower .bench/logs --capture .bench/captures

    # replay: analyze finished streams (same code path, no tailing)
    python -m benchmark.watchtower results-run/logs --once

Per stream file this multiplexes a :class:`benchmark.logs.StreamFollower`
(tail-follow with partial-line and truncation handling) into one
:class:`hotstuff_tpu.telemetry.Watchtower`; new ``telemetry-*.jsonl``
files appearing mid-run (a node booting late, a restart) are picked up
by periodic rescans. Alerts print as they fire and are appended to
``watchtower-alerts.jsonl`` (one ``hotstuff-alert-v1`` line each) next
to the streams — machine-consumable by the soak verdict and the
detector bench. ``--capture DIR`` arms :class:`AlertCapture`.

:class:`DirectoryWatch` is the embeddable form — ``benchmark/soak.py``
runs one in a thread for the live soak verdict, and
``benchmark/watchtower_smoke.py`` measures its attached overhead.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.logs import StreamFollower  # noqa: E402
from hotstuff_tpu.telemetry import watchtower as wt_mod  # noqa: E402


class DirectoryWatch(threading.Thread):
    """Follow every ``telemetry-*.jsonl`` in a directory through one
    Watchtower. Start it, run the workload, then ``stop()`` (which
    performs a final drain + flush so end-of-run evidence is judged).

    The thread is the single ingest writer; ``alerts()`` /
    ``scoreboard()`` are safe to call from other threads at any time.
    """

    def __init__(
        self,
        directory: str,
        *,
        config: wt_mod.WatchtowerConfig | None = None,
        alias: dict[str, str] | None = None,
        on_alert=None,
        alerts_path: str | None = None,
        pattern: str = "telemetry-*.jsonl",
        poll_s: float = 0.2,
        rescan_s: float = 1.0,
        tick_with_wall_clock: bool = True,
    ) -> None:
        super().__init__(name="watchtower", daemon=True)
        self.directory = directory
        self.pattern = pattern
        self.poll_s = poll_s
        self.rescan_s = rescan_s
        self.alerts_path = alerts_path
        self.tick_with_wall_clock = tick_with_wall_clock
        self._stop_evt = threading.Event()
        self._followers: dict[str, StreamFollower] = {}
        self.watch = wt_mod.Watchtower(
            config, alias=alias, on_alert=self._on_alert, label="watchtower"
        )
        self._user_on_alert = on_alert
        self._alerts_fh = None

    # -- alert sink ----------------------------------------------------------

    def _on_alert(self, alert: dict) -> None:
        if self.alerts_path is not None:
            try:
                if self._alerts_fh is None:
                    os.makedirs(
                        os.path.dirname(os.path.abspath(self.alerts_path)),
                        exist_ok=True,
                    )
                    self._alerts_fh = open(self.alerts_path, "a")
                self._alerts_fh.write(
                    json.dumps(alert, separators=(",", ":")) + "\n"
                )
                self._alerts_fh.flush()
            except OSError:
                pass  # monitoring must not die on a full disk
        if self._user_on_alert is not None:
            self._user_on_alert(alert)

    # -- lifecycle -----------------------------------------------------------

    def _rescan(self) -> None:
        for path in sorted(
            glob.glob(os.path.join(self.directory, self.pattern))
        ):
            if path not in self._followers:
                self._followers[path] = StreamFollower(
                    path, poll_s=self.poll_s
                )

    def _drain_all(self) -> int:
        # One feed() per stream: the batch path the replay/sweep planes
        # use, so live-follow and offline replay share the ingest loop.
        n = 0
        for path, follower in self._followers.items():
            batch = [(record, path) for record in follower.drain()]
            if batch:
                self.watch.feed(batch)
                n += len(batch)
        return n

    def run(self) -> None:
        last_rescan = 0.0
        while not self._stop_evt.is_set():
            now = time.time()
            if now - last_rescan >= self.rescan_s:
                self._rescan()
                last_rescan = now
            got = self._drain_all()
            if self.tick_with_wall_clock:
                self.watch.tick(time.time())
            else:
                self.watch.tick()
            if not got:
                self._stop_evt.wait(self.poll_s)
        # Final sweep: records written between the last poll and stop()
        # (teardown flushes the final snapshot + trace tail).
        self._rescan()
        self._drain_all()
        self.watch.flush()
        if self._alerts_fh is not None:
            self._alerts_fh.close()
            self._alerts_fh = None

    def stop(self, join_timeout_s: float = 10.0) -> None:
        self._stop_evt.set()
        if self.is_alive():
            self.join(join_timeout_s)

    # -- read side -----------------------------------------------------------

    def alerts(self) -> list[dict]:
        return self.watch.snapshot_alerts()

    def scoreboard(self) -> dict:
        return self.watch.scoreboard()

    def stats(self) -> dict:
        return {
            "streams": len(self._followers),
            "records": sum(
                f.records_read for f in self._followers.values()
            ),
            "skipped": sum(f.skipped for f in self._followers.values()),
            "truncations": sum(
                f.truncations for f in self._followers.values()
            ),
        }


def replay_directory(
    directory: str,
    *,
    config: wt_mod.WatchtowerConfig | None = None,
    alias: dict[str, str] | None = None,
    on_alert=None,
    pattern: str = "telemetry-*.jsonl",
) -> wt_mod.Watchtower:
    """Post-hoc analysis of finished streams through the SAME incremental
    ingest path the live follower uses (the replay = live equivalence the
    detector bench leans on). Records are globally ordered by wall time
    so cross-stream windows close the way they would have live."""
    watch = wt_mod.Watchtower(
        config, alias=alias, on_alert=on_alert, label="watchtower-replay"
    )
    timed: list[tuple[float, str, dict]] = []
    for path in sorted(glob.glob(os.path.join(directory, pattern))):
        follower = StreamFollower(path)
        anchor_off = None
        for record in follower.drain():
            schema = record.get("schema")
            ts = record.get("ts")
            if schema == "hotstuff-trace-v1":
                anchor = record.get("anchor") or {}
                if all(
                    isinstance(anchor.get(k), (int, float))
                    for k in ("mono", "wall")
                ):
                    anchor_off = anchor["wall"] - anchor["mono"]
                events = record.get("events") or ()
                if events and anchor_off is not None:
                    ts = events[0][4] + anchor_off
            if not isinstance(ts, (int, float)):
                ts = timed[-1][0] if timed else 0.0
            timed.append((ts, path, record))
    timed.sort(key=lambda x: x[0])
    watch.feed((record, path) for _ts, path, record in timed)
    watch.flush()
    return watch


def _fmt_alert(alert: dict) -> str:
    rounds = alert["window"].get("rounds")
    return (
        f"[watchtower] {alert['detector']}: accused={alert['accused']} "
        f"confidence={alert['confidence']}"
        + (f" rounds={rounds}" if rounds else "")
        + f" evidence={json.dumps(alert['evidence'], sort_keys=True)}"
    )


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "directory", help="directory containing telemetry-*.jsonl streams"
    )
    p.add_argument(
        "--once", action="store_true",
        help="replay the existing streams and exit (no tailing)",
    )
    p.add_argument(
        "--duration", type=float, default=None,
        help="follow for this many seconds, then report (default: Ctrl-C)",
    )
    p.add_argument(
        "--config", help="JSON file of WatchtowerConfig overrides"
    )
    p.add_argument(
        "--capture", metavar="DIR",
        help="arm alert-triggered capture (evidence + flight + bounded "
        "profile) into DIR",
    )
    p.add_argument(
        "--alerts-file", default=None,
        help="append hotstuff-alert-v1 lines here (default: "
        "<directory>/watchtower-alerts.jsonl)",
    )
    p.add_argument(
        "--scoreboard", action="store_true",
        help="print the per-peer scoreboard at exit",
    )
    args = p.parse_args()

    config = None
    if args.config:
        with open(args.config) as f:
            config = wt_mod.WatchtowerConfig.from_dict(json.load(f))

    capture = None
    if args.capture:
        capture = wt_mod.AlertCapture(args.capture)

    def on_alert(alert: dict) -> None:
        if capture is not None:
            capture(alert)
        print(_fmt_alert(alert), flush=True)

    if args.once:
        watch = replay_directory(
            args.directory, config=config, on_alert=on_alert
        )
        alerts = watch.snapshot_alerts()
        board = watch.scoreboard()
    else:
        alerts_path = args.alerts_file or os.path.join(
            args.directory, "watchtower-alerts.jsonl"
        )
        dw = DirectoryWatch(
            args.directory,
            config=config,
            on_alert=on_alert,
            alerts_path=alerts_path,
        )
        if capture is not None:
            # In-process capture gets the live trace ring + registry only
            # when the watcher shares the node process; a standalone
            # follower captures evidence windows.
            capture.watchtower = dw.watch
        dw.start()
        try:
            if args.duration is not None:
                time.sleep(args.duration)
            else:
                while True:
                    time.sleep(3600)
        except KeyboardInterrupt:
            pass
        dw.stop()
        alerts = dw.alerts()
        board = dw.scoreboard()
        print(f"[watchtower] streams: {json.dumps(dw.stats())}")

    print(
        f"[watchtower] {len(alerts)} alert(s); frontier={board['frontier']} "
        f"over {board['rounds']} scored round(s)"
    )
    if args.scoreboard:
        print(json.dumps(board, indent=2, sort_keys=True))
    sys.exit(0 if not alerts else 3)


if __name__ == "__main__":
    main()
