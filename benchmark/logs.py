"""Log parser — the measurement system (reference
``benchmark/benchmark/logs.py``).

Scrapes client + node logs with the same regex contract as the reference
harness (our nodes emit the identical line formats):

- consensus TPS/BPS: committed batch bytes over [first proposal, last commit]
- consensus latency: commit_ts - proposal_ts per batch digest
- e2e TPS: committed batch bytes over [client start, last commit]
- e2e latency: commit_ts - client_send_ts per sample transaction

Multi-node timestamps are merged keeping the earliest (``logs.py:64-71``);
the parser doubles as the correctness oracle: tracebacks/errors in any log
raise ParseError (``logs.py:74-75,91-92``).

``TelemetryParser`` is the regex path's structured sibling: it reads the
JSON-lines snapshot streams nodes emit when telemetry is enabled
(``HOTSTUFF_TELEMETRY_DIR``, see ``hotstuff_tpu/telemetry``) and computes
the consensus TPS/latency measurements from the registry's counters and
histograms instead of scraping log lines. The telemetry recorders run at
the exact code sites that emit the regex-scraped lines, so both paths
measure the same events; small deltas remain (telemetry credits a batch
at its proposer's/creator's local observations when nodes run in
separate processes, while the regex path merges earliest-across-nodes) —
see docs/telemetry.md.
"""

from __future__ import annotations

import glob
import json
import math
import os
from datetime import datetime
from re import findall, search
from statistics import mean

from hotstuff_tpu.telemetry import (
    ALERT_SCHEMA,
    DTRACE_SCHEMA,
    META_SCHEMA,
    PROFILE_SCHEMA,
    SCHEMA as SNAPSHOT_SCHEMA,
    TRACE_SCHEMA,
    validate_alert_record,
    validate_dtrace_record,
    validate_meta_record,
    validate_profile_record,
    validate_snapshot,
    validate_trace_record,
)


class ParseError(Exception):
    pass


def _to_posix(ts: str) -> float:
    return datetime.fromisoformat(ts.replace("Z", "+00:00")).timestamp()


def _merge_earliest(dicts) -> dict:
    merged: dict = {}
    for d in dicts:
        for k, v in d.items():
            if k not in merged or merged[k] > v:
                merged[k] = v
    return merged


class LogParser:
    def __init__(self, clients: list[str], nodes: list[str], faults: int = 0) -> None:
        if not clients or not nodes:
            raise ParseError("missing client or node logs")
        self.faults = faults
        self.committee_size = len(nodes) + faults

        results = [self._parse_client(log) for log in clients]
        (
            self.sizes_cfg,
            self.rate,
            self.start,
            misses,
            self.sent_samples,
            sheds,
        ) = zip(*results)
        self.misses = sum(misses)
        self.sheds = sum(sheds)

        results = [self._parse_node(log) for log in nodes]
        proposals, commits, sizes, received, timeouts, self.configs = zip(*results)
        self.proposals = _merge_earliest(proposals)
        self.commits = _merge_earliest(commits)
        self.batch_sizes = {
            k: v for x in sizes for k, v in x.items() if k in self.commits
        }
        self.received_samples = received
        self.timeouts = max(timeouts)

        if self.misses:
            print(f"WARN: clients missed their target rate {self.misses:,} time(s)")
        if self.timeouts > 2:
            print(f"WARN: nodes timed out {self.timeouts:,} time(s)")

    def _parse_client(self, log: str):
        if search(r"Traceback|ERROR", log) is not None:
            raise ParseError("client(s) panicked")
        size = int(search(r"Transactions size: (\d+)", log).group(1))
        rate = int(search(r"Transactions rate: (\d+)", log).group(1))
        start = _to_posix(search(r"\[(.*Z) .* Start ", log).group(1))
        misses = len(findall(r"rate too high", log))
        samples = {
            int(s): _to_posix(t)
            for t, s in findall(r"\[(.*Z) .* sample transaction (\d+)", log)
        }
        # Cumulative counter: the last line per client is its total.
        shed_lines = findall(r"Shed notifications: (\d+)", log)
        sheds = int(shed_lines[-1]) if shed_lines else 0
        return size, rate, start, misses, samples, sheds

    def _parse_node(self, log: str):
        if search(r"Traceback|panic", log) is not None:
            raise ParseError("node(s) panicked")

        proposals = _merge_earliest(
            [
                {d: _to_posix(t)}
                for t, d in findall(r"\[(.*Z) .* Created B\d+ -> ([^ ]+=)", log)
            ]
        )
        commits = _merge_earliest(
            [
                {d: _to_posix(t)}
                for t, d in findall(r"\[(.*Z) .* Committed B\d+ -> ([^ ]+=)", log)
            ]
        )
        sizes = {
            d: int(s) for d, s in findall(r"Batch ([^ ]+) contains (\d+) B", log)
        }
        samples = {
            int(s): d
            for d, s in findall(r"Batch ([^ ]+) contains sample tx (\d+)", log)
        }
        timeouts = len(findall(r".* WARN .* Timeout", log))

        configs = {
            "consensus": {
                "timeout_delay": int(search(r"Timeout delay .* (\d+)", log).group(1)),
                "sync_retry_delay": int(
                    search(r"consensus.* Sync retry delay .* (\d+)", log).group(1)
                ),
            },
            "mempool": {
                "gc_depth": int(search(r"Garbage collection .* (\d+)", log).group(1)),
                "sync_retry_delay": int(
                    search(r"mempool.* Sync retry delay .* (\d+)", log).group(1)
                ),
                "sync_retry_nodes": int(
                    search(r"Sync retry nodes .* (\d+)", log).group(1)
                ),
                "batch_size": int(search(r"Batch size .* (\d+)", log).group(1)),
                "max_batch_delay": int(
                    search(r"Max batch delay .* (\d+)", log).group(1)
                ),
            },
        }
        return proposals, commits, sizes, samples, timeouts, configs

    # -- measurements -------------------------------------------------------

    def _consensus_throughput(self):
        if not self.commits:
            return 0, 0, 0
        start, end = min(self.proposals.values()), max(self.commits.values())
        duration = end - start
        nbytes = sum(self.batch_sizes.values())
        bps = nbytes / duration if duration else 0
        tps = bps / self.sizes_cfg[0]
        return tps, bps, duration

    def _consensus_latency(self):
        lat = [c - self.proposals[d] for d, c in self.commits.items() if d in self.proposals]
        return mean(lat) if lat else 0

    def _end_to_end_throughput(self):
        if not self.commits:
            return 0, 0, 0
        start, end = min(self.start), max(self.commits.values())
        duration = end - start
        nbytes = sum(self.batch_sizes.values())
        bps = nbytes / duration if duration else 0
        tps = bps / self.sizes_cfg[0]
        return tps, bps, duration

    def _e2e_latency_samples(self) -> list[float]:
        lat = []
        for sent, received in zip(self.sent_samples, self.received_samples):
            for tx_id, batch_id in received.items():
                if batch_id in self.commits and tx_id in sent:
                    lat.append(self.commits[batch_id] - sent[tx_id])
        return lat

    def _end_to_end_latency(self):
        lat = self._e2e_latency_samples()
        return mean(lat) if lat else 0

    def e2e_latency_tail(self, q: float) -> float:
        """Order-statistic percentile (q in (0,1]) of sample-tx e2e
        latency in seconds. With one sample per 50 ms burst a p99.9
        needs a multi-minute run to be meaningful; shorter runs degrade
        toward the max, which is still the honest tail bound."""
        lat = sorted(self._e2e_latency_samples())
        if not lat:
            return 0.0
        return lat[max(0, math.ceil(q * len(lat)) - 1)]

    def result(self) -> str:
        consensus_latency = self._consensus_latency() * 1000
        consensus_tps, consensus_bps, _ = self._consensus_throughput()
        e2e_tps, e2e_bps, duration = self._end_to_end_throughput()
        e2e_latency = self._end_to_end_latency() * 1000
        cfg_c = self.configs[0]["consensus"]
        cfg_m = self.configs[0]["mempool"]
        return (
            "\n"
            "-----------------------------------------\n"
            " SUMMARY:\n"
            "-----------------------------------------\n"
            " + CONFIG:\n"
            f" Faults: {self.faults} nodes\n"
            f" Committee size: {self.committee_size} nodes\n"
            f" Input rate: {sum(self.rate):,} tx/s\n"
            f" Transaction size: {self.sizes_cfg[0]:,} B\n"
            f" Execution time: {round(duration):,} s\n"
            "\n"
            f" Consensus timeout delay: {cfg_c['timeout_delay']:,} ms\n"
            f" Consensus sync retry delay: {cfg_c['sync_retry_delay']:,} ms\n"
            f" Mempool GC depth: {cfg_m['gc_depth']:,} rounds\n"
            f" Mempool sync retry delay: {cfg_m['sync_retry_delay']:,} ms\n"
            f" Mempool sync retry nodes: {cfg_m['sync_retry_nodes']:,} nodes\n"
            f" Mempool batch size: {cfg_m['batch_size']:,} B\n"
            f" Mempool max batch delay: {cfg_m['max_batch_delay']:,} ms\n"
            "\n"
            " + RESULTS:\n"
            f" Consensus TPS: {round(consensus_tps):,} tx/s\n"
            f" Consensus BPS: {round(consensus_bps):,} B/s\n"
            f" Consensus latency: {round(consensus_latency):,} ms\n"
            "\n"
            f" End-to-end TPS: {round(e2e_tps):,} tx/s\n"
            f" End-to-end BPS: {round(e2e_bps):,} B/s\n"
            f" End-to-end latency: {round(e2e_latency):,} ms\n"
            f" End-to-end latency p99: "
            f"{round(self.e2e_latency_tail(0.99) * 1000):,} ms\n"
            f" End-to-end latency p99.9: "
            f"{round(self.e2e_latency_tail(0.999) * 1000):,} ms\n"
            f" Shed notifications: {self.sheds:,}\n"
            "-----------------------------------------\n"
        )

    def print_to(self, filename: str) -> None:
        with open(filename, "a") as f:
            f.write(self.result())

    @classmethod
    def process(cls, directory: str, faults: int = 0) -> "LogParser":
        clients, nodes = [], []
        for fn in sorted(glob.glob(os.path.join(directory, "client-*.log"))):
            with open(fn) as f:
                clients.append(f.read())
        for fn in sorted(glob.glob(os.path.join(directory, "node-*.log"))):
            with open(fn) as f:
                nodes.append(f.read())
        return cls(clients, nodes, faults)


# ---------------------------------------------------------------------------
# Telemetry-stream reader (the structured path).
# ---------------------------------------------------------------------------


class StreamRecords:
    """One parsed telemetry stream, by record schema.

    ``snapshots`` are the ``hotstuff-telemetry-v1`` lines, ``traces`` the
    interleaved ``hotstuff-trace-v1`` lines, ``dtraces`` the
    ``hotstuff-dtrace-v1`` batch-lifecycle lines, ``profiles`` the
    ``hotstuff-profile-v1`` sampling-profiler lines, ``meta`` the
    ``hotstuff-meta-v1`` stream self-descriptions (one per writer; a
    restart of the same node appends another), ``alerts`` any
    ``hotstuff-alert-v1`` watchtower records, ``skipped`` counts lines
    that could not be used: a truncated FINAL line (a node crashed or
    was SIGKILLed mid-write — expected under chaos, never fatal) and
    lines of unknown schema (forward compatibility). Malformed JSON
    anywhere but the last line still raises — mid-file corruption is a
    real bug, not crash fallout."""

    __slots__ = (
        "snapshots", "traces", "dtraces", "profiles", "meta", "alerts",
        "skipped",
    )

    def __init__(self) -> None:
        self.snapshots: list[dict] = []
        self.traces: list[dict] = []
        self.dtraces: list[dict] = []
        self.profiles: list[dict] = []
        self.meta: list[dict] = []
        self.alerts: list[dict] = []
        self.skipped = 0


def read_stream_records(path: str) -> StreamRecords:
    with open(path) as f:
        lines = [
            (i, line.strip()) for i, line in enumerate(f, 1) if line.strip()
        ]
    records = StreamRecords()
    for pos, (lineno, line) in enumerate(lines):
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            if pos == len(lines) - 1:
                # Truncated final line: the writer died mid-append.
                records.skipped += 1
                continue
            raise ParseError(f"{path}:{lineno}: bad JSON: {e}") from e
        schema = obj.get("schema") if isinstance(obj, dict) else None
        if schema == SNAPSHOT_SCHEMA:
            problems = validate_snapshot(obj)
            if problems:
                raise ParseError(f"{path}:{lineno}: {'; '.join(problems)}")
            records.snapshots.append(obj)
        elif schema == TRACE_SCHEMA:
            problems = validate_trace_record(obj)
            if problems:
                raise ParseError(f"{path}:{lineno}: {'; '.join(problems)}")
            records.traces.append(obj)
        elif schema == DTRACE_SCHEMA:
            problems = validate_dtrace_record(obj)
            if problems:
                raise ParseError(f"{path}:{lineno}: {'; '.join(problems)}")
            records.dtraces.append(obj)
        elif schema == PROFILE_SCHEMA:
            problems = validate_profile_record(obj)
            if problems:
                raise ParseError(f"{path}:{lineno}: {'; '.join(problems)}")
            records.profiles.append(obj)
        elif schema == META_SCHEMA:
            problems = validate_meta_record(obj)
            if problems:
                raise ParseError(f"{path}:{lineno}: {'; '.join(problems)}")
            records.meta.append(obj)
        elif schema == ALERT_SCHEMA:
            problems = validate_alert_record(obj)
            if problems:
                raise ParseError(f"{path}:{lineno}: {'; '.join(problems)}")
            records.alerts.append(obj)
        else:
            records.skipped += 1
    return records


class StreamFollower:
    """Tail-follow reader for one live telemetry stream: yields each
    record (validated, any known schema) as the file grows — the
    watchtower's ingestion primitive, and independently useful for any
    ``--telemetry`` consumer that wants records before the run ends.

    Live-stream realities it handles:

    - the file may not exist yet (a node still booting): polls quietly;
    - a **partial final line** (writer mid-append): buffered until its
      newline arrives — a record is only parsed once complete;
    - **rotation by truncation** (file size shrinks): reopens from the
      start and counts ``truncations``;
    - malformed or unknown-schema lines: counted in ``skipped`` and
      skipped — a live follower cannot tell mid-file corruption from a
      crash tail, and dying on it would kill monitoring exactly when
      something is going wrong (the post-hoc ``read_stream_records``
      stays strict).

    Iterate it directly (blocking, ``poll_s`` between growth checks)
    until ``stop()`` is called or ``stop_when`` returns True — both
    finish with one final drain so nothing already on disk is lost —
    or call :meth:`drain` for a non-blocking sweep of what's new.
    """

    def __init__(
        self,
        path: str,
        *,
        poll_s: float = 0.2,
        stop_when=None,
    ) -> None:
        self.path = path
        self.poll_s = poll_s
        self.stop_when = stop_when
        self.skipped = 0
        self.truncations = 0
        self.records_read = 0
        self._offset = 0
        self._buf = b""
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    def _should_stop(self) -> bool:
        return self._stopped or (
            self.stop_when is not None and self.stop_when()
        )

    def drain(self) -> list[dict]:
        """Non-blocking: parse and return every complete new record."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []  # not created yet (or vanished): keep polling
        if size < self._offset:
            # Rotation by truncation: the writer started the file over.
            self._offset = 0
            self._buf = b""
            self.truncations += 1
        if size == self._offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read()
            self._offset = f.tell()
        self._buf += chunk
        out: list[dict] = []
        while True:
            nl = self._buf.find(b"\n")
            if nl < 0:
                break  # partial final line: wait for the newline
            line, self._buf = self._buf[:nl], self._buf[nl + 1:]
            line = line.strip()
            if not line:
                continue
            record = self._parse(line)
            if record is not None:
                self.records_read += 1
                out.append(record)
        return out

    def _parse(self, line: bytes) -> dict | None:
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            self.skipped += 1
            return None
        schema = obj.get("schema") if isinstance(obj, dict) else None
        validator = {
            SNAPSHOT_SCHEMA: validate_snapshot,
            TRACE_SCHEMA: validate_trace_record,
            DTRACE_SCHEMA: validate_dtrace_record,
            PROFILE_SCHEMA: validate_profile_record,
            META_SCHEMA: validate_meta_record,
            ALERT_SCHEMA: validate_alert_record,
        }.get(schema)
        if validator is None or validator(obj):
            self.skipped += 1
            return None
        return obj

    def __iter__(self):
        import time as _time

        while not self._should_stop():
            got = self.drain()
            if got:
                yield from got
            else:
                _time.sleep(self.poll_s)
        # Final drain: records appended between the last poll and the
        # stop signal (e.g. a final snapshot flushed at teardown).
        yield from self.drain()


class SnapshotStream(list):
    """A list of snapshots that remembers how many lines were skipped
    (kept a list subclass so existing callers stay source-compatible)."""

    skipped = 0


def read_telemetry_stream(path: str) -> SnapshotStream:
    """Parse one JSON-lines stream; returns the snapshot lines (trace
    lines are separated out — use ``read_stream_records`` for those),
    tolerating a truncated final line. Raises ParseError on mid-stream
    corruption or schema-invalid records."""
    records = read_stream_records(path)
    stream = SnapshotStream(records.snapshots)
    stream.skipped = records.skipped
    return stream


class TelemetryParser:
    """Consensus TPS/latency from telemetry snapshot streams.

    ``streams`` is one list of parsed snapshots per source (file / node);
    only each stream's LAST snapshot matters (counters are cumulative).
    Cross-stream merge mirrors the regex parser's: the measurement window
    is [min first-proposal, max last-commit] across streams, committed
    bytes sum (each batch is credited exactly once, by its creator), and
    latency histograms merge by bucket addition.
    """

    def __init__(self, streams: list[list[dict]], tx_size: int | None = None):
        finals = [s[-1] for s in streams if s]
        if not finals:
            raise ParseError("no telemetry snapshots")
        self.snapshots = finals
        self.tx_size = tx_size
        # Lines the lenient reader had to drop (truncated final writes of
        # crashed nodes); surfaced so measurements know their provenance.
        self.skipped_lines = sum(
            getattr(s, "skipped", 0) for s in streams
        )

        def gauge(snap, name):
            return snap["gauges"].get(name)

        starts = [
            g
            for s in finals
            if (g := gauge(s, "consensus.first_proposal_ts")) is not None
        ]
        ends = [
            g
            for s in finals
            if (g := gauge(s, "consensus.last_commit_ts")) is not None
        ]
        self.start = min(starts) if starts else None
        self.end = max(ends) if ends else None
        self.committed_bytes = sum(
            s["counters"].get("consensus.committed_bytes", 0) for s in finals
        )
        self.committed_batches = sum(
            s["counters"].get("consensus.batches_committed", 0) for s in finals
        )
        self.latency_sum_ms = 0.0
        self.latency_count = 0
        for s in finals:
            h = s["histograms"].get("consensus.commit_latency_ms")
            if h is not None:
                self.latency_sum_ms += h["sum"]
                self.latency_count += h["count"]

    def counter_total(self, name: str) -> int:
        return sum(s["counters"].get(name, 0) for s in self.snapshots)

    def consensus_throughput(self) -> tuple[float, float, float]:
        """(tps, bps, duration_s); tps is 0 unless ``tx_size`` was given."""
        if self.start is None or self.end is None or self.end <= self.start:
            return 0.0, 0.0, 0.0
        duration = self.end - self.start
        bps = self.committed_bytes / duration
        tps = bps / self.tx_size if self.tx_size else 0.0
        return tps, bps, duration

    def consensus_latency_ms(self) -> float:
        if not self.latency_count:
            return 0.0
        return self.latency_sum_ms / self.latency_count

    def result(self) -> str:
        tps, bps, duration = self.consensus_throughput()
        return (
            "\n"
            "-----------------------------------------\n"
            " TELEMETRY SUMMARY:\n"
            "-----------------------------------------\n"
            f" Snapshot streams: {len(self.snapshots)}\n"
            f" Measured window: {duration:.1f} s\n"
            f" Committed batches: {self.committed_batches:,}\n"
            "\n"
            f" Consensus TPS: {round(tps):,} tx/s\n"
            f" Consensus BPS: {round(bps):,} B/s\n"
            f" Consensus latency: {round(self.consensus_latency_ms()):,} ms\n"
            "-----------------------------------------\n"
        )

    @classmethod
    def process(cls, directory: str, tx_size: int | None = None) -> "TelemetryParser":
        streams = [
            read_telemetry_stream(fn)
            for fn in sorted(
                glob.glob(os.path.join(directory, "telemetry-*.jsonl"))
            )
        ]
        if not streams:
            raise ParseError(f"no telemetry-*.jsonl streams in {directory}")
        return cls(streams, tx_size=tx_size)
