"""Conveyor rate/latency sweep: the data plane's committed throughput
curve, and the noise-aware gate the CI smoke compares against.

Sweeps the sharded-ingest local bench (real node processes, worker
shards, bundle-mode clients) across offered rates and records, per
point: committed end-to-end TPS/BPS/latency, consensus TPS/latency, and
the clients' shed counts (the back-pressure contract made visible — at
overload the curve should PLATEAU with rising shed counts, not
collapse). The artifact (``results/dataplane-sweep-*.json``) is the
throughput claim the README cites.

Gate mode (``--gate``): the fresh peak e2e TPS must stay within
``tolerance`` of the best committed sweep artifact (min-over-noise
semantics borrowed from ``benchmark/regress.py``: CI shares cores, the
gate catches silent multiples, not drift). ``--min-tps`` adds an
absolute floor. Exit 0 green / 1 regression.

    python -m benchmark.dataplane_sweep --nodes 4 --workers 2 \
        --rates 10000,20000,40000,80000 --duration 20 --output results
    HOTSTUFF_REGRESS_TOLERANCE=0.5 python -m benchmark.dataplane_sweep \
        --nodes 4 --workers 1 --rates 20000 --duration 15 --gate
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402
from benchmark.local import BenchError, LocalBench  # noqa: E402
from benchmark.logs import ParseError  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SWEEP_SCHEMA = "hotstuff-dataplane-sweep-v1"


def _shed_total(logs_dir: str) -> int:
    """Final shed count across clients (the counter is cumulative, so
    the last 'Shed notifications: N' line per client is the total)."""
    total = 0
    for fn in sorted(glob.glob(os.path.join(logs_dir, "client-*.log"))):
        with open(fn) as f:
            matches = re.findall(r"Shed notifications: (\d+)", f.read())
        if matches:
            total += int(matches[-1])
    return total


#: per-point cap on per-batch lifeline rows kept in the artifact (the
#: aggregate edge stats always cover every batch; only the raw rows trim).
DTRACE_BATCH_CAP = 200


def run_point(
    rate: int,
    *,
    nodes: int,
    workers: int,
    tx_size: int,
    duration: int,
    base_port: int,
    work_dir: str,
    batch_size: int,
    max_batch_delay: int,
    timeout: int,
    dtrace: bool = False,
    client_extra: list[str] | None = None,
) -> dict:
    bench = LocalBench(
        nodes=nodes,
        rate=rate,
        tx_size=tx_size,
        duration=duration,
        base_port=base_port,
        timeout_delay=timeout,
        batch_size=batch_size,
        max_batch_delay=max_batch_delay,
        work_dir=work_dir,
        workers=workers,
        telemetry=dtrace,
        client_extra=client_extra,
    )
    parser = bench.run()
    e2e_tps, e2e_bps, dur = parser._end_to_end_throughput()
    c_tps, c_bps, _ = parser._consensus_throughput()
    logs_dir = os.path.join(os.path.abspath(work_dir), "logs")
    row = {
        "rate": rate,
        "e2e_tps": round(e2e_tps),
        "e2e_bps": round(e2e_bps),
        "e2e_latency_ms": round(parser._end_to_end_latency() * 1e3),
        "consensus_tps": round(c_tps),
        "consensus_latency_ms": round(parser._consensus_latency() * 1e3),
        "duration_s": round(dur, 1),
        "shed": _shed_total(logs_dir),
        "rate_misses": parser.misses,
    }
    if dtrace:
        # Per-batch edge attribution assembled from this point's streams
        # (joined to round traces and the clients' sampled submit lines).
        from benchmark.dtrace_assemble import assemble

        streams = sorted(
            glob.glob(os.path.join(logs_dir, "telemetry-*.jsonl"))
        )
        try:
            report = assemble(
                streams,
                client_paths=sorted(
                    glob.glob(os.path.join(logs_dir, "client-*.log"))
                ),
            )
            if len(report["per_batch"]) > DTRACE_BATCH_CAP:
                report["per_batch"] = report["per_batch"][:DTRACE_BATCH_CAP]
                report["per_batch_truncated"] = True
            row["dtrace"] = report
        except Exception as e:  # noqa: BLE001 — attribution is advisory
            row["dtrace"] = {"error": str(e)}
    return row


def best_committed_tps(results_dir: str) -> dict | None:
    """Best peak e2e TPS across committed sweep artifacts."""
    best = None
    for fn in sorted(
        glob.glob(os.path.join(results_dir, "dataplane-sweep-*.json"))
    ):
        try:
            with open(fn) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        peak = data.get("peak", {}).get("e2e_tps")
        if peak is None:
            continue
        if best is None or peak > best["e2e_tps"]:
            best = {"e2e_tps": peak, "source": os.path.basename(fn)}
    return best


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument(
        "--rates", default="10000,20000,40000",
        help="comma-separated offered rates (total tx/s)",
    )
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--duration", type=int, default=20)
    p.add_argument("--timeout", type=int, default=2_000)
    p.add_argument("--batch-size", type=int, default=250_000)
    p.add_argument("--max-batch-delay", type=int, default=50, help="ms")
    p.add_argument("--base-port", type=int, default=11000)
    p.add_argument("--work-dir", default=".dataplane-bench")
    p.add_argument("--output", help="directory for the sweep artifact")
    p.add_argument(
        "--client-extra", default="",
        help="extra args appended to every client command line, e.g. "
        "'--coalesce-bytes 8192 --coalesce-ms 5' to enable small-bundle "
        "write coalescing",
    )
    p.add_argument(
        "--dtrace", action="store_true",
        help="stream telemetry from every node and attach the assembled "
        "per-batch lifeline attribution (seven-edge) to each point; also "
        "writes a dataplane-dtrace-*.json artifact under --output",
    )
    p.add_argument(
        "--gate", action="store_true",
        help="compare the peak against the committed baseline artifact",
    )
    p.add_argument(
        "--min-tps", type=float, default=None,
        help="absolute floor for the fresh peak e2e TPS",
    )
    p.add_argument(
        "--tolerance",
        type=float,
        default=float(os.environ.get("HOTSTUFF_REGRESS_TOLERANCE", "0.5")),
        help="allowed relative shortfall vs the committed peak",
    )
    args = p.parse_args()

    rates = [int(r) for r in args.rates.split(",") if r]
    rows = []
    port = args.base_port
    for rate in rates:
        print(f"--- sweep point: {rate:,} tx/s offered ---", flush=True)
        try:
            row = run_point(
                rate,
                nodes=args.nodes,
                workers=args.workers,
                tx_size=args.tx_size,
                duration=args.duration,
                base_port=port,
                work_dir=args.work_dir,
                batch_size=args.batch_size,
                max_batch_delay=args.max_batch_delay,
                timeout=args.timeout,
                dtrace=args.dtrace,
                client_extra=args.client_extra.split() or None,
            )
        except (BenchError, ParseError) as e:
            row = {"rate": rate, "error": str(e)}
        rows.append(row)
        # Per-point console line stays one line: the lifeline report (if
        # any) is summarized to its cost-center ranking here and kept in
        # full in the report/artifact.
        preview = {k: v for k, v in row.items() if k != "dtrace"}
        if isinstance(row.get("dtrace"), dict):
            preview["dtrace_top"] = row["dtrace"].get(
                "top_cost_centers", row["dtrace"].get("error")
            )
        print(json.dumps(preview), flush=True)
        # Fresh port block per point: TIME_WAIT sockets from the last
        # point must not collide with the next committee.
        port += 20 * args.nodes * (args.workers + 3)

    good = [r for r in rows if "error" not in r]
    peak = max(good, key=lambda r: r["e2e_tps"], default=None)
    report = {
        "schema": SWEEP_SCHEMA,
        "ts": time.time(),
        "host": host_meta(),
        "config": {
            "nodes": args.nodes,
            "workers": args.workers,
            "tx_size": args.tx_size,
            "duration_s": args.duration,
            "batch_size": args.batch_size,
            "max_batch_delay_ms": args.max_batch_delay,
            "client_extra": args.client_extra or None,
        },
        "rows": rows,
        "peak": peak,
    }

    ok = True
    if args.gate:
        gate: dict = {"tolerance": args.tolerance}
        fresh = peak["e2e_tps"] if peak else 0
        baseline = best_committed_tps(os.path.join(REPO_ROOT, "results"))
        gate["fresh_peak_tps"] = fresh
        if args.min_tps is not None:
            gate["min_tps"] = args.min_tps
            ok = ok and fresh >= args.min_tps
        if baseline is not None:
            # A run cannot commit more than it offered: the floor is set
            # by the committed peak OR this sweep's highest offered rate,
            # whichever is lower — so a cheap CI point (one mid rate)
            # still gates against silent multiples without demanding the
            # committed box's full curve.
            reachable = min(baseline["e2e_tps"], max(rates))
            floor = reachable * (1 - args.tolerance)
            gate.update(
                baseline=baseline["e2e_tps"],
                baseline_source=baseline["source"],
                reachable=reachable,
                floor=round(floor),
            )
            ok = ok and fresh >= floor
        else:
            gate["status"] = "no-baseline"
        gate["ok"] = ok
        report["gate"] = gate

    print(
        json.dumps(
            {
                **report,
                "rows": [
                    {k: v for k, v in r.items() if k != "dtrace"}
                    for r in report["rows"]
                ],
                "peak": (
                    {k: v for k, v in peak.items() if k != "dtrace"}
                    if peak
                    else None
                ),
            },
            indent=2,
            sort_keys=True,
        )
    )
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        path = os.path.join(
            args.output,
            f"dataplane-sweep-n{args.nodes}-w{args.workers}-"
            f"{args.tx_size}B.json",
        )
        with open(path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"artifact written to {path}")
        if args.dtrace and peak and isinstance(peak.get("dtrace"), dict):
            # The lifeline attribution stands alone too: the per-batch
            # edge breakdown at the sweep's peak point, the artifact the
            # latency profile doc cites.
            dpath = os.path.join(
                args.output,
                f"dataplane-dtrace-n{args.nodes}-w{args.workers}-"
                f"{args.tx_size}B.json",
            )
            with open(dpath, "w") as f:
                json.dump(
                    {
                        "config": report["config"],
                        "host": report["host"],
                        "rate": peak["rate"],
                        "lifeline": peak["dtrace"],
                    },
                    f, indent=2, sort_keys=True,
                )
                f.write("\n")
            print(f"lifeline artifact written to {dpath}")
    if args.gate:
        print(f"dataplane gate: {'GREEN' if ok else 'RED'}")
        if not ok:
            sys.exit(1)


if __name__ == "__main__":
    main()
