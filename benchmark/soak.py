"""SLO-gated soak harness: sustained load under faultline chaos, judged
by machine verdicts instead of eyeballed summaries.

Drives the multi-process local bench (real node processes + load
clients) for ``--duration`` seconds with a seeded faultline chaos
scenario armed and telemetry streaming per node, then judges THREE ways
and passes only if all agree:

1. the faultline **invariant checker** (safety: no conflicting commits;
   liveness: post-heal commit growth) — correctness under chaos;
2. the **SLO engine** over every node's snapshot stream in sliding
   windows (p99 commit latency, ms/round, mempool queue depth,
   timeout/view-change rate) — sustained service quality, with a bounded
   tolerated fraction of degraded windows while faults are open;
3. the **regex log parse** (tracebacks in any log fail the run).

The verdict (one JSON artifact) is the machine contract ROADMAP item 3
asks for: long runs gated on telemetry SLOs. Thresholds and the chaos
seed are CLI knobs so CI smokes (60 s) and overnight soaks share this
entry point.

    python -m benchmark.soak --nodes 4 --rate 500 --duration 60 \
        --chaos-seed 7 --output results
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.hostinfo import host_meta  # noqa: E402
from benchmark.local import LocalBench  # noqa: E402
from benchmark.logs import ParseError, TelemetryParser, read_telemetry_stream  # noqa: E402
from benchmark.watchtower import DirectoryWatch  # noqa: E402
from hotstuff_tpu.telemetry import slo as slo_mod  # noqa: E402
from hotstuff_tpu.telemetry.watchtower import AlertCapture, WatchtowerConfig  # noqa: E402

SOAK_SCHEMA = "hotstuff-soak-verdict-v1"


def run_soak(args) -> dict:
    work_dir = os.path.abspath(args.work_dir)
    if args.pyprof:
        # Child node processes arm the all-thread sampling profiler and
        # their hotstuff-profile-v1 records ride the telemetry streams
        # (joined below into the verdict's attribution section).
        os.environ["HOTSTUFF_PYPROF"] = "1"
    chaos_path = None
    if getattr(args, "chaos_scenario", None):
        chaos_path = os.path.abspath(args.chaos_scenario)
    elif args.chaos_seed is not None:
        from hotstuff_tpu.faultline import chaos_scenario

        scenario = chaos_scenario(
            args.chaos_seed, duration_s=float(args.duration)
        )
        # NOT inside work_dir: LocalBench.run() wipes that tree.
        chaos_path = work_dir.rstrip("/") + "-soak-scenario.json"
        scenario.save(chaos_path)

    bench = LocalBench(
        nodes=args.nodes,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        base_port=args.base_port,
        timeout_delay=args.timeout,
        work_dir=args.work_dir,
        telemetry=True,
        chaos=chaos_path,
        workers=args.workers,
        retention_rounds=args.retention_rounds,
    )
    logs_dir = os.path.join(work_dir, "logs")

    # Live watchtower: tail every node's stream WHILE the soak runs, so
    # an SLO breach mid-run carries a named suspect in the verdict and
    # the capture evidence is written at the moment of detection, not at
    # teardown. (bench.run() wipes work_dir first; the watch's rescan
    # picks the fresh streams up as the nodes create them.)
    watch = None
    if not args.no_watch:
        capture = AlertCapture(
            os.path.join(work_dir, "captures"),
            profile_s=0.0,  # nodes are other processes: evidence-only
        )
        watch_cfg = None
        if args.watch_config:
            if args.watch_config.startswith("preset:"):
                watch_cfg = WatchtowerConfig.preset(
                    args.watch_config.split(":", 1)[1]
                )
            else:
                # Accept both a bare config dict and a committed preset
                # document ({"schema": ..., "config": {...}, ...}).
                doc = json.load(open(args.watch_config))
                watch_cfg = WatchtowerConfig.from_dict(
                    doc.get("config", doc) if isinstance(doc, dict) else doc
                )
        watch = DirectoryWatch(
            logs_dir,
            config=watch_cfg,
            on_alert=capture,
            alerts_path=os.path.join(logs_dir, "watchtower-alerts.jsonl"),
        )
        capture.watchtower = watch.watch
        watch.start()

    parse_error = None
    summary = None
    try:
        parser = bench.run()
        summary = parser.result()
    except ParseError as e:
        parse_error = str(e)
    finally:
        if watch is not None:
            watch.stop()
    streams: dict[str, list[dict]] = {}
    skipped = 0
    for fn in sorted(glob.glob(os.path.join(logs_dir, "telemetry-*.jsonl"))):
        stream = read_telemetry_stream(fn)
        skipped += stream.skipped
        streams[os.path.basename(fn)] = list(stream)

    specs = (
        slo_mod.load_specs(args.slo_spec)
        if args.slo_spec
        else slo_mod.default_slos(
            p99_commit_latency_ms=args.p99_commit_ms,
            ms_per_round=args.ms_per_round,
            mempool_queue_depth=args.queue_depth,
            timeouts_per_round=args.timeouts_per_round,
            allow_violation_fraction=args.allow_violation_fraction,
        )
        + (
            # Conveyor gate set: bounded worker store depth (the
            # back-pressure contract) and zero commit-path resolution
            # timeouts (the availability contract). Streams without the
            # worker metrics skip these, so the flag is always safe.
            slo_mod.dataplane_slos(
                allow_violation_fraction=args.allow_violation_fraction
            )
            if args.workers
            else []
        )
        + slo_mod.memory_slos(
            # The unbounded-growth gate (ROADMAP item 4): RSS and store
            # disk must grow slower than the bound in every window. The
            # resource gauges come from each node's resource collector;
            # streams without them skip these specs. With retention
            # armed, the bounded-store contract additionally caps the
            # ABSOLUTE store size (compaction must plateau it).
            rss_growth_bytes_per_s=args.rss_growth_mb_s * 1024 * 1024,
            store_growth_bytes_per_s=args.store_growth_mb_s * 1024 * 1024,
            store_bytes_max=(
                args.store_max_mb * 1024 * 1024
                if args.store_max_mb is not None
                else None
            ),
            allow_violation_fraction=args.allow_violation_fraction,
        )
    )
    slo_verdict = slo_mod.evaluate_streams(
        streams, specs, window_s=args.window
    )

    chaos_ok = True
    if bench.chaos_verdict is not None:
        chaos_ok = (
            bench.chaos_verdict["safety"]["ok"]
            and bench.chaos_verdict["liveness"]["recovered"]
            # Conveyor availability invariant (present when workers > 0):
            # every committed digest resolvable at f+1 honest stores.
            and bench.chaos_verdict.get("availability", {}).get("ok", True)
        )

    # Resource + commit trajectory per node (first → last snapshot): the
    # human-readable face of what the memory-growth SLOs judged, plus
    # each node's commit height so a laggard that commits nothing in the
    # tail is visible in the verdict itself — the chaos3 finding took
    # diffing flight records to see; now it is one row here.
    resources: dict[str, dict] = {}
    commit_heights: dict[str, dict] = {}
    for name, snaps in streams.items():
        if not snaps:
            continue
        first, last_snap = snaps[0], snaps[-1]
        row = {}
        for gauge_name, label in (
            ("resource.rss_bytes", "rss_bytes"),
            ("resource.store_bytes", "store_bytes"),
            ("resource.open_fds", "open_fds"),
        ):
            a = first.get("gauges", {}).get(gauge_name)
            b = last_snap.get("gauges", {}).get(gauge_name)
            if b is not None:
                row[label] = {"first": a, "last": b}
        h_first = first.get("gauges", {}).get("consensus.last_committed_round")
        h_last = last_snap.get("gauges", {}).get(
            "consensus.last_committed_round"
        )
        if h_last is not None:
            heights = {"first": h_first, "last": h_last}
            row["commit_height"] = heights
            commit_heights[name] = dict(heights)
        if row:
            resources[name] = row
    frontier = max(
        (h["last"] for h in commit_heights.values()), default=None
    )
    commit_section = None
    if commit_heights:
        for h in commit_heights.values():
            h["lag"] = frontier - h["last"]
            h["advanced"] = (h["last"] - (h["first"] or 0)) > 0
        commit_section = {
            "frontier": frontier,
            "nodes": commit_heights,
            "laggards": sorted(
                name
                for name, h in commit_heights.items()
                if not h["advanced"] or h["lag"] >= 8
            ),
        }

    # Per-batch lifeline attribution from the nodes' dtrace records
    # (only present under --dtrace; absence is not an error). The soak
    # keeps just the aggregate face — edge stats, cost centers, and the
    # incomplete-lifeline census (a batch stuck mid-pipeline during
    # chaos is exactly what this section is for).
    dtrace_attr = None
    if args.dtrace:
        try:
            from benchmark.dtrace_assemble import assemble

            report = assemble(
                sorted(glob.glob(os.path.join(logs_dir, "telemetry-*.jsonl")))
            )
            dtrace_attr = {
                "batches": report["batches"],
                "complete": report["complete"],
                "incomplete_by_stage_reached": report[
                    "incomplete_by_stage_reached"
                ],
                "total_ms": report["total_ms"],
                "edges": report["edges"],
                "top_cost_centers": report["top_cost_centers"],
                "slowest_batches": report["slowest_batches"][:3],
            }
        except Exception as e:  # noqa: BLE001 — attribution is advisory
            dtrace_attr = {"error": str(e)}

    # Function-level attribution from the nodes' profile records (only
    # present under --pyprof; absence is not an error).
    profile_attr = None
    if args.pyprof:
        try:
            from benchmark.profile_assemble import attribute

            report = attribute(
                sorted(glob.glob(os.path.join(logs_dir, "telemetry-*.jsonl")))
            )
            profile_attr = {
                "samples": report["sampler"]["samples"],
                "gil_delay_ms": report["sampler"]["gil_delay_ms"],
                "ctypes": report["ctypes"],
                "edges": {
                    e: {
                        "samples": v["samples"],
                        "top_functions": v["top_functions"][:3],
                    }
                    for e, v in report["edges"].items()
                },
            }
        except Exception as e:  # noqa: BLE001 — attribution is advisory
            profile_attr = {"error": str(e)}

    telemetry_summary = None
    try:
        tele = TelemetryParser.process(logs_dir, tx_size=args.tx_size)
        tps, bps, duration = tele.consensus_throughput()
        telemetry_summary = {
            "consensus_tps": round(tps),
            "consensus_bps": round(bps),
            "consensus_latency_ms": round(tele.consensus_latency_ms()),
            "measured_window_s": round(duration, 1),
            "skipped_stream_lines": tele.skipped_lines,
        }
    except ParseError:
        pass

    # Watchtower verdict section: what the ONLINE plane concluded while
    # the run was still going — every alert (with its accused peers and
    # capture paths) plus the per-peer scoreboard, so an SLO breach has
    # a named suspect without any post-hoc assembly.
    alerts_section = None
    if watch is not None:
        alerts = watch.alerts()
        alerts_section = {
            "count": len(alerts),
            "alerts": alerts,
            "suspects": sorted(
                {p for a in alerts for p in a["accused"]}
            ),
            "scoreboard": watch.scoreboard(),
            "streams": watch.stats(),
        }

    ok = slo_verdict["ok"] and chaos_ok and parse_error is None
    return {
        "schema": SOAK_SCHEMA,
        "ok": ok,
        "host": host_meta(),
        "config": {
            "nodes": args.nodes,
            "rate": args.rate,
            "tx_size": args.tx_size,
            "duration_s": args.duration,
            "chaos_seed": args.chaos_seed,
            "chaos_scenario": getattr(args, "chaos_scenario", None),
            "workers": args.workers,
            "retention_rounds": args.retention_rounds,
            "store_max_mb": args.store_max_mb,
            "slo_window_s": args.window,
        },
        "slo": slo_verdict,
        "chaos": bench.chaos_verdict,
        "telemetry": telemetry_summary,
        "resources": resources,
        "commit": commit_section,
        "alerts": alerts_section,
        "profile": profile_attr,
        "dtrace": dtrace_attr,
        "parse_error": parse_error,
        "skipped_stream_lines": skipped,
        "summary": summary,
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--rate", type=int, default=500, help="total input tx/s")
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--duration", type=int, default=60, help="soak seconds")
    p.add_argument(
        "--hours", type=float, default=None,
        help="convenience: soak length in hours (overrides --duration); "
        "the ROADMAP 3c long-soak artifacts use --hours 1",
    )
    p.add_argument("--timeout", type=int, default=1_000, help="consensus ms")
    p.add_argument("--base-port", type=int, default=9400)
    p.add_argument("--work-dir", default=".soak")
    p.add_argument(
        "--chaos-seed", type=int, default=None,
        help="arm a seeded faultline chaos storm for the whole run",
    )
    p.add_argument(
        "--chaos-scenario", default=None,
        help="explicit faultline scenario JSON (overrides --chaos-seed); "
        "e.g. benchmark/scenarios/worker-crash.json",
    )
    p.add_argument(
        "--workers", type=int, default=0,
        help="Conveyor worker shards per node; adds the dataplane SLO "
        "set and the availability invariant to the verdict",
    )
    p.add_argument(
        "--window", type=float, default=15.0, help="SLO sliding window (s)"
    )
    p.add_argument("--slo-spec", help="JSON SLO spec file (overrides knobs)")
    p.add_argument("--p99-commit-ms", type=float, default=5_000.0)
    p.add_argument("--ms-per-round", type=float, default=2_000.0)
    p.add_argument("--queue-depth", type=float, default=50_000.0)
    p.add_argument("--timeouts-per-round", type=float, default=1.0)
    p.add_argument(
        "--rss-growth-mb-s", type=float, default=8.0,
        help="memory-growth SLO: max RSS growth (MiB/s) per window",
    )
    p.add_argument(
        "--store-growth-mb-s", type=float, default=32.0,
        help="memory-growth SLO: max on-disk store growth (MiB/s)",
    )
    p.add_argument(
        "--retention-rounds", type=int, default=0,
        help="Lazarus: arm snapshot/truncate log compaction in every "
        "node at this retention depth (rounds; 0 = unbounded store)",
    )
    p.add_argument(
        "--store-max-mb", type=float, default=None,
        help="absolute on-disk store cap per node (gauge_max SLO on "
        "resource.store_bytes); defaults to 512 MiB when "
        "--retention-rounds is armed, off otherwise",
    )
    p.add_argument(
        "--dtrace", action="store_true",
        help="join the per-batch lifeline attribution (edge stats, cost "
        "centers, stuck-batch census) into the verdict",
    )
    p.add_argument(
        "--pyprof", action="store_true",
        help="arm the sampling profiler in every node process and join "
        "the function-level attribution into the verdict",
    )
    p.add_argument(
        "--no-watch", action="store_true",
        help="disable the live watchtower (alerts section absent)",
    )
    p.add_argument(
        "--watch-config",
        help="WatchtowerConfig for the live tower: a JSON file (bare "
        "config or committed preset document) or preset:<name> "
        "(e.g. preset:tuned-n4, Oracle's sweep-tuned preset)",
    )
    p.add_argument(
        "--allow-violation-fraction", type=float, default=0.34,
        help="tolerated fraction of degraded windows per SLO (chaos "
        "scenarios legitimately stall while a partition is open)",
    )
    p.add_argument("--output", help="directory for the verdict artifact")
    args = p.parse_args()
    if args.hours is not None:
        args.duration = int(args.hours * 3600)
    if args.store_max_mb is None and args.retention_rounds > 0:
        # Bounded-store contract: a retention-armed soak gates on an
        # absolute store cap by default (compaction must plateau it).
        args.store_max_mb = 512.0

    verdict = run_soak(args)
    print(json.dumps({k: v for k, v in verdict.items() if k != "summary"},
                     indent=2, sort_keys=True))
    if verdict["summary"]:
        print(verdict["summary"])
    if args.output:
        os.makedirs(args.output, exist_ok=True)
        if getattr(args, "chaos_scenario", None):
            tag = os.path.splitext(os.path.basename(args.chaos_scenario))[0]
        elif args.chaos_seed is not None:
            tag = f"chaos{args.chaos_seed}"
        else:
            tag = "clean"
        if args.workers:
            tag = f"w{args.workers}-{tag}"
        if args.retention_rounds:
            tag = f"r{args.retention_rounds}-{tag}"
        path = os.path.join(
            args.output,
            f"soak-slo-n{args.nodes}-{args.duration}s-{tag}.json",
        )
        with open(path, "w") as f:
            json.dump(verdict, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"verdict written to {path}")
    print(f"soak verdict: {'PASS' if verdict['ok'] else 'FAIL'}")
    if not verdict["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
