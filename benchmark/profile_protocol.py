"""CPU profile of the protocol plane: run a 4-node in-process committee
plus an in-process load generator under cProfile and print the hottest
functions. This is the latency diagnosis tool for the single-core regime
(every node shares the core, so CPU-per-round IS the round latency).

    python -m benchmark.profile_protocol --seconds 20 --rate 1000
"""

from __future__ import annotations

import argparse
import asyncio
import cProfile
import io
import os
import pstats
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def _run(seconds: int, rate: int, tx_size: int, base_port: int) -> None:
    from hotstuff_tpu.consensus import Authority as CAuth
    from hotstuff_tpu.consensus import Committee as CCommittee
    from hotstuff_tpu.consensus import Parameters as CParams
    from hotstuff_tpu.mempool import Authority as MAuth
    from hotstuff_tpu.mempool import Committee as MCommittee
    from hotstuff_tpu.mempool import Parameters as MParams
    from hotstuff_tpu.node.config import Committee, Parameters, Secret
    from hotstuff_tpu.node.node import Node

    nodes = 4
    secrets = [Secret.new() for _ in range(nodes)]
    consensus = CCommittee(
        authorities={
            s.name: CAuth(stake=1, address=("127.0.0.1", base_port + i))
            for i, s in enumerate(secrets)
        }
    )
    mempool = MCommittee(
        authorities={
            s.name: MAuth(
                stake=1,
                transactions_address=("127.0.0.1", base_port + 100 + i),
                mempool_address=("127.0.0.1", base_port + 200 + i),
            )
            for i, s in enumerate(secrets)
        }
    )
    tmp = tempfile.mkdtemp(prefix="hotstuff_prof_")
    committee_file = f"{tmp}/committee.json"
    Committee(consensus, mempool).write(committee_file)
    params_file = f"{tmp}/parameters.json"
    Parameters(
        CParams(timeout_delay=2_000),
        MParams(batch_size=15_000, max_batch_delay=10),
    ).write(params_file)

    started = []
    for i, s in enumerate(secrets):
        key_file = f"{tmp}/node_{i}.json"
        s.write(key_file)
        node = await Node.new(
            committee_file, key_file, f"{tmp}/db_{i}", params_file
        )
        started.append(node)
    sinks = [asyncio.create_task(n.analyze_block()) for n in started]

    # In-process open-loop load generator against every front port.
    async def load(i: int) -> None:
        import random
        import struct

        _, writer = await asyncio.open_connection("127.0.0.1", base_port + 100 + i)
        counter = 0
        per_burst = max(1, rate // nodes // 20)
        while True:
            for _ in range(per_burst):
                tx = struct.pack(">BQ", 1, random.getrandbits(63)).ljust(
                    tx_size, b"\x00"
                )
                writer.write(len(tx).to_bytes(4, "big") + tx)
                counter += 1
            await writer.drain()
            await asyncio.sleep(0.05)

    loaders = [asyncio.create_task(load(i)) for i in range(nodes)]
    await asyncio.sleep(seconds)
    for t in [*loaders, *sinks]:
        t.cancel()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seconds", type=int, default=20)
    p.add_argument("--rate", type=int, default=1_000)
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--base-port", type=int, default=21000)
    p.add_argument("--top", type=int, default=35)
    p.add_argument("--sort", default="cumulative", choices=["cumulative", "tottime"])
    args = p.parse_args()

    prof = cProfile.Profile()
    prof.enable()
    try:
        asyncio.run(_run(args.seconds, args.rate, args.tx_size, args.base_port))
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    prof.disable()
    out = io.StringIO()
    stats = pstats.Stats(prof, stream=out)
    stats.strip_dirs().sort_stats(args.sort).print_stats(args.top)
    print(out.getvalue())


if __name__ == "__main__":
    main()
