"""Headline benchmark: Ed25519 quorum-certificate batch verification on TPU.

Measures µs per signature for the device RLC batch verifier (decompress +
shared-doubling MSM, one device call) at a committee-1000-scale vote set,
against the CPU per-signature baseline (OpenSSL, the stand-in for
ed25519-dalek's CPU batch verify — BASELINE.md's baseline-to-beat).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "us/sig", "vs_baseline": N}
"""

from __future__ import annotations

import json
import random
import sys
import time


def make_batch(n_sigs: int, seed: int = 2024):
    from hotstuff_tpu.crypto import ed25519_ref as ref

    rng = random.Random(seed)
    msgs, pubs, sigs = [], [], []
    for _ in range(n_sigs):
        seed_bytes = rng.randbytes(32)
        pubs.append(ref.secret_to_public(seed_bytes))
        msgs.append(rng.randbytes(32))
        sigs.append(ref.sign(seed_bytes, msgs[-1]))
    return msgs, pubs, sigs


def bench_device(msgs, pubs, sigs, iters: int = 5) -> float:
    """End-to-end per-batch seconds (host prep + device verify)."""
    from hotstuff_tpu.ops.verify import verify_batch_device

    rng = random.Random(1)
    assert verify_batch_device(msgs, pubs, sigs, _rng=rng)  # warm-up/compile
    t0 = time.perf_counter()
    for _ in range(iters):
        assert verify_batch_device(msgs, pubs, sigs, _rng=rng)
    return (time.perf_counter() - t0) / iters


def bench_cpu(msgs, pubs, sigs, iters: int = 2) -> float:
    from hotstuff_tpu.crypto import CpuBackend

    backend = CpuBackend()
    backend.verify_batch(msgs, pubs, sigs)  # warm-up
    t0 = time.perf_counter()
    for _ in range(iters):
        backend.verify_batch(msgs, pubs, sigs)
    return (time.perf_counter() - t0) / iters


def main() -> None:
    # Committee-1000 regime: a QC carries 2f+1 = 667 votes; batching two
    # in-flight QCs ~ 1343 signatures -> 2687 MSM lanes -> 4096 padded.
    n_sigs = int(sys.argv[1]) if len(sys.argv) > 1 else 1343

    msgs, pubs, sigs = make_batch(n_sigs)
    cpu_s = bench_cpu(msgs, pubs, sigs)
    dev_s = bench_device(msgs, pubs, sigs)

    us_per_sig = dev_s / n_sigs * 1e6
    cpu_us_per_sig = cpu_s / n_sigs * 1e6
    print(
        json.dumps(
            {
                "metric": f"ed25519_qc_batch_verify_{n_sigs}sigs",
                "value": round(us_per_sig, 3),
                "unit": "us/sig",
                "vs_baseline": round(cpu_us_per_sig / us_per_sig, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
