"""Headline benchmark: Ed25519 quorum-certificate batch verification on TPU.

Measures µs per signature for the device RLC batch verifier (decompress +
shared-doubling MSM, one device call) at a committee-1000-scale vote set,
against the CPU per-signature baseline (OpenSSL, the stand-in for
ed25519-dalek's CPU batch verify — BASELINE.md's baseline-to-beat).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "us/sig", "vs_baseline": N}
"""

from __future__ import annotations

import json
import random
import sys
import time

import numpy as np

from hotstuff_tpu.utils.jaxcache import enable_persistent_cache

# Must run before the first jit compile: cold-compiling the mega-kernels
# costs tens of seconds; the persistent cache drops later runs to a disk
# read, which is what lets this bench fit its budget even after a process
# restart or a flaky first attempt.
enable_persistent_cache()


class TunnelDown(Exception):
    """The device-aliveness probe failed: an outage, not a code defect."""


def probe_device(attempts: int = 4, backoff_s: float = 5.0) -> None:
    """Cheap device-aliveness check with bounded retry.

    A trivial op round-trip (no custom kernels) distinguishes "tunnel is
    down" from "compile is slow" in seconds instead of burning the whole
    budget on a doomed warm-up. Raises ``TunnelDown`` (wrapping the last
    error) if all attempts fail, so callers classify it as an outage
    rather than a device-code defect.
    """
    import jax
    import jax.numpy as jnp

    last: Exception | None = None
    for attempt in range(attempts):
        try:
            jnp.zeros(8).block_until_ready()
            return
        except Exception as exc:  # noqa: BLE001 — any device error retries
            last = exc
            print(
                f"device probe attempt {attempt + 1}/{attempts} failed: {exc!r}",
                file=sys.stderr,
                flush=True,
            )
            if attempt + 1 < attempts:  # no pointless sleep before raising
                time.sleep(backoff_s * (2**attempt))
    raise TunnelDown(repr(last))


def make_batch(n_sigs: int, seed: int = 2024):
    from hotstuff_tpu.crypto import ed25519_ref as ref

    rng = random.Random(seed)
    msgs, pubs, sigs = [], [], []
    for _ in range(n_sigs):
        seed_bytes = rng.randbytes(32)
        pubs.append(ref.secret_to_public(seed_bytes))
        msgs.append(rng.randbytes(32))
        sigs.append(ref.sign(seed_bytes, msgs[-1]))
    return msgs, pubs, sigs


def bench_device_cached(msgs, pubs, sigs, iters: int = 8, threads: int = 4) -> float:
    """Steady-state node path: committee keys are device-resident (decompressed
    once per epoch — committees are static), so each batch pays host prep
    (hashing, strictness, signed-digit recode), ONE packed transfer, fresh-R
    decompression and the split signed MSM. Pipelined like ``bench_device``."""
    from concurrent.futures import ThreadPoolExecutor

    import jax.numpy as jnp

    from hotstuff_tpu.ops.verify import (
        DevicePointCache,
        _compiled_cached,
        prepare_batch_cached,
        verify_batch_device_cached,
    )

    probe_device()
    cache = DevicePointCache()
    rng = random.Random(2)
    assert verify_batch_device_cached(msgs, pubs, sigs, cache, _rng=rng)  # warm

    def one_batch(seed: int):
        r = random.Random(seed)
        packed, mf, mc = prepare_batch_cached(msgs, pubs, sigs, cache, _rng=r)
        return _compiled_cached(mf, mc, cache.capacity)(jnp.asarray(packed), cache.array)

    with ThreadPoolExecutor(threads) as ex:
        warm = [ex.submit(one_batch, 1000 + i) for i in range(threads)]
        assert np.asarray(jnp.stack([f.result() for f in warm])).all()
        elapsed = float("inf")
        for _round in range(3):
            t0 = time.perf_counter()
            futures = [ex.submit(one_batch, i) for i in range(iters)]
            ok = np.asarray(jnp.stack([f.result() for f in futures]))
            elapsed = min(elapsed, (time.perf_counter() - t0) / iters)
            assert ok.all()
    return elapsed


def bench_device(msgs, pubs, sigs, iters: int = 8, threads: int = 4) -> float:
    """End-to-end per-batch seconds: full host prep per batch (hashing,
    strictness checks, RLC scalars, byte packing) + one host->device
    transfer + device verify, measured as a pipelined stream of independent
    batches. A small thread pool overlaps the synchronous transfer round
    trips with device execution and the next batch's host prep — exactly
    how the node's async crypto bridge feeds the device. Results are
    fetched in one round trip at the end."""
    from concurrent.futures import ThreadPoolExecutor

    import jax.numpy as jnp

    from hotstuff_tpu.ops.verify import _compiled, prepare_batch, verify_batch_device

    probe_device()
    rng = random.Random(1)
    assert verify_batch_device(msgs, pubs, sigs, _rng=rng)  # warm-up/compile

    def one_batch(seed: int):
        r = random.Random(seed)
        packed, m = prepare_batch(msgs, pubs, sigs, _rng=r)
        return _compiled(m)(jnp.asarray(packed))

    with ThreadPoolExecutor(threads) as ex:
        # Warm the pool: each worker thread pays one-time device-context
        # setup on its first jax call.
        warm = [ex.submit(one_batch, 1000 + i) for i in range(threads)]
        assert np.asarray(jnp.stack([f.result() for f in warm])).all()

        # Tunnel latency to the device varies run to run; best-of-rounds is
        # the stable estimator of the pipeline's true throughput.
        elapsed = float("inf")
        for _round in range(3):
            t0 = time.perf_counter()
            futures = [ex.submit(one_batch, i) for i in range(iters)]
            ok = np.asarray(jnp.stack([f.result() for f in futures]))
            elapsed = min(elapsed, (time.perf_counter() - t0) / iters)
            assert ok.all()
    return elapsed


def bench_cpu(msgs, pubs, sigs, iters: int = 2) -> float:
    """Serial per-signature CPU verification (OpenSSL)."""
    from hotstuff_tpu.crypto import CpuBackend

    backend = CpuBackend(use_rlc=False)
    backend.verify_batch(msgs, pubs, sigs)  # warm-up
    t0 = time.perf_counter()
    for _ in range(iters):
        backend.verify_batch(msgs, pubs, sigs)
    return (time.perf_counter() - t0) / iters


def bench_cpu_batch(msgs, pubs, sigs) -> float:
    """Batched CPU verification: dalek ``verify_batch`` semantics AND
    algorithm (RLC + MSM, reference ``crypto/src/lib.rs:206-219``).

    Uses the fastest batch implementation available on this host: the
    native C++ engine when built, else the pure-Python Pippenger."""
    from hotstuff_tpu.crypto import cpu_batch

    verify = cpu_batch.best_verify_batch()
    rng = random.Random(11)
    assert verify(msgs, pubs, sigs, rng=rng)  # warm-up + correctness
    t0 = time.perf_counter()
    assert verify(msgs, pubs, sigs, rng=rng)
    return time.perf_counter() - t0


def main() -> None:
    # Committee-1000 regime: a QC carries 2f+1 = 667 votes; batching two
    # in-flight QCs ~ 1343 signatures -> 2687 MSM lanes -> 4096 padded.
    n_sigs = int(sys.argv[1]) if len(sys.argv) > 1 else 1343

    msgs, pubs, sigs = make_batch(n_sigs)
    cpu_s = bench_cpu(msgs, pubs, sigs)
    cpu_us_per_sig = cpu_s / n_sigs * 1e6
    cpu_batch_s = bench_cpu_batch(msgs, pubs, sigs)
    cpu_batch_us_per_sig = cpu_batch_s / n_sigs * 1e6
    # The HONEST baseline is the fastest CPU option on this host: serial
    # native (OpenSSL) vs batched (RLC+MSM). vs_serial and vs_batch are
    # reported separately alongside it.
    best_cpu_us = min(cpu_us_per_sig, cpu_batch_us_per_sig)

    # The TPU is reached through a tunnel that can go down; a hung device
    # call must not wedge the benchmark forever. Run the device benchmark
    # under a hard timeout and report the honest CPU-only fallback if the
    # device is unreachable (15 min covers a full cold compile).
    import os
    from concurrent.futures import ThreadPoolExecutor
    from concurrent.futures import TimeoutError as FutTimeout

    # Covers a full cold compile (~400 s worst observed) with margin, while
    # staying comfortably inside typical harness timeouts.
    budget = float(os.environ.get("HOTSTUFF_BENCH_TIMEOUT", "600"))

    def device_both():
        """(warm_cached_s, cold_s): the committee-cached steady-state path
        (headline) and the cold full-decompress path (reported alongside)."""
        warm = bench_device_cached(msgs, pubs, sigs)
        cold = bench_device(msgs, pubs, sigs)
        return warm, cold

    def device_with_retry():
        # A transient tunnel error (reset connection, lost heartbeat) often
        # clears in seconds; one bounded retry converts those runs from a
        # fallback artifact into a real number. Hangs are still handled by
        # the outer budget timeout.
        try:
            return device_both()
        except Exception as exc:  # noqa: BLE001
            print(f"device bench attempt 1 failed, retrying: {exc!r}", file=sys.stderr, flush=True)
            time.sleep(10)
            probe_device()
            return device_both()

    with ThreadPoolExecutor(1) as ex:
        fut = ex.submit(device_with_retry)
        def fallback(reason_suffix: str, code: int = 0) -> None:
            # Always emit the one promised JSON line (honest CPU-only
            # numbers, explicitly labeled) and exit immediately — a hung
            # device call cannot be cancelled and would otherwise block
            # the executor's shutdown join forever.
            print(
                json.dumps(
                    {
                        "metric": f"ed25519_qc_batch_verify_{n_sigs}sigs_{reason_suffix}_cpu_only",
                        "value": round(best_cpu_us, 3),
                        "unit": "us/sig",
                        "vs_baseline": 1.0,
                        "fallback": reason_suffix,
                        "cpu_serial_us": round(cpu_us_per_sig, 3),
                        "cpu_batch_us": round(cpu_batch_us_per_sig, 3),
                    }
                ),
                flush=True,
            )
            os._exit(code)

        try:
            dev_s, dev_cold_s = fut.result(timeout=budget)
        except FutTimeout:
            # rc=0: an unreachable device is an ENVIRONMENT condition,
            # not a benchmark failure — the emitted metric line is valid
            # (honest CPU-only numbers) and tagged TPU_UNREACHABLE +
            # fallback:true so downstream readers can tell it apart from
            # a real device run. Nonzero codes are reserved for real
            # failures (DEVICE_ERROR rc=1: fast-failing device code or a
            # correctness regression).
            fallback("TPU_UNREACHABLE", code=0)
        except TunnelDown:
            fallback("TPU_UNREACHABLE", code=0)
        except KeyboardInterrupt:
            fallback("INTERRUPTED", code=130)
        except Exception:
            # A fast-failing device error or a verification-correctness
            # regression is NOT an outage: keep the one-line contract but
            # label it distinctly, preserve the diagnostic, and exit
            # nonzero so exit-status checks see the failure.
            import traceback

            traceback.print_exc(file=sys.stderr)
            fallback("DEVICE_ERROR", code=1)

    us_per_sig = dev_s / n_sigs * 1e6
    print(
        json.dumps(
            {
                "metric": f"ed25519_qc_batch_verify_{n_sigs}sigs",
                "value": round(us_per_sig, 3),
                "unit": "us/sig",
                "vs_baseline": round(best_cpu_us / us_per_sig, 3),
                "vs_serial": round(cpu_us_per_sig / us_per_sig, 3),
                "vs_batch": round(cpu_batch_us_per_sig / us_per_sig, 3),
                "cpu_serial_us": round(cpu_us_per_sig, 3),
                "cpu_batch_us": round(cpu_batch_us_per_sig, 3),
                "device_cold_us": round(dev_cold_s / n_sigs * 1e6, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
