"""Headline benchmark: Ed25519 quorum-certificate batch verification on TPU.

Measures µs per signature for the device RLC batch verifier (decompress +
shared-doubling MSM, one device call) at a committee-1000-scale vote set,
against the CPU per-signature baseline (OpenSSL, the stand-in for
ed25519-dalek's CPU batch verify — BASELINE.md's baseline-to-beat).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "us/sig", "vs_baseline": N}
"""

from __future__ import annotations

import json
import random
import sys
import time

import numpy as np


def make_batch(n_sigs: int, seed: int = 2024):
    from hotstuff_tpu.crypto import ed25519_ref as ref

    rng = random.Random(seed)
    msgs, pubs, sigs = [], [], []
    for _ in range(n_sigs):
        seed_bytes = rng.randbytes(32)
        pubs.append(ref.secret_to_public(seed_bytes))
        msgs.append(rng.randbytes(32))
        sigs.append(ref.sign(seed_bytes, msgs[-1]))
    return msgs, pubs, sigs


def bench_device(msgs, pubs, sigs, iters: int = 8, threads: int = 4) -> float:
    """End-to-end per-batch seconds: full host prep per batch (hashing,
    strictness checks, RLC scalars, byte packing) + one host->device
    transfer + device verify, measured as a pipelined stream of independent
    batches. A small thread pool overlaps the synchronous transfer round
    trips with device execution and the next batch's host prep — exactly
    how the node's async crypto bridge feeds the device. Results are
    fetched in one round trip at the end."""
    from concurrent.futures import ThreadPoolExecutor

    import jax.numpy as jnp

    from hotstuff_tpu.ops.verify import _compiled, prepare_batch, verify_batch_device

    rng = random.Random(1)
    assert verify_batch_device(msgs, pubs, sigs, _rng=rng)  # warm-up/compile

    def one_batch(seed: int):
        r = random.Random(seed)
        packed, m = prepare_batch(msgs, pubs, sigs, _rng=r)
        return _compiled(m)(jnp.asarray(packed))

    with ThreadPoolExecutor(threads) as ex:
        # Warm the pool: each worker thread pays one-time device-context
        # setup on its first jax call.
        warm = [ex.submit(one_batch, 1000 + i) for i in range(threads)]
        assert np.asarray(jnp.stack([f.result() for f in warm])).all()

        # Tunnel latency to the device varies run to run; best-of-rounds is
        # the stable estimator of the pipeline's true throughput.
        elapsed = float("inf")
        for _round in range(3):
            t0 = time.perf_counter()
            futures = [ex.submit(one_batch, i) for i in range(iters)]
            ok = np.asarray(jnp.stack([f.result() for f in futures]))
            elapsed = min(elapsed, (time.perf_counter() - t0) / iters)
            assert ok.all()
    return elapsed


def bench_cpu(msgs, pubs, sigs, iters: int = 2) -> float:
    from hotstuff_tpu.crypto import CpuBackend

    backend = CpuBackend()
    backend.verify_batch(msgs, pubs, sigs)  # warm-up
    t0 = time.perf_counter()
    for _ in range(iters):
        backend.verify_batch(msgs, pubs, sigs)
    return (time.perf_counter() - t0) / iters


def main() -> None:
    # Committee-1000 regime: a QC carries 2f+1 = 667 votes; batching two
    # in-flight QCs ~ 1343 signatures -> 2687 MSM lanes -> 4096 padded.
    n_sigs = int(sys.argv[1]) if len(sys.argv) > 1 else 1343

    msgs, pubs, sigs = make_batch(n_sigs)
    cpu_s = bench_cpu(msgs, pubs, sigs)
    cpu_us_per_sig = cpu_s / n_sigs * 1e6

    # The TPU is reached through a tunnel that can go down; a hung device
    # call must not wedge the benchmark forever. Run the device benchmark
    # under a hard timeout and report the honest CPU-only fallback if the
    # device is unreachable (15 min covers a full cold compile).
    import os
    from concurrent.futures import ThreadPoolExecutor
    from concurrent.futures import TimeoutError as FutTimeout

    # Covers a full cold compile (~400 s worst observed) with margin, while
    # staying comfortably inside typical harness timeouts.
    budget = float(os.environ.get("HOTSTUFF_BENCH_TIMEOUT", "600"))
    with ThreadPoolExecutor(1) as ex:
        fut = ex.submit(bench_device, msgs, pubs, sigs)
        def fallback(reason_suffix: str, code: int = 0) -> None:
            # Always emit the one promised JSON line (honest CPU-only
            # numbers, explicitly labeled) and exit immediately — a hung
            # device call cannot be cancelled and would otherwise block
            # the executor's shutdown join forever.
            print(
                json.dumps(
                    {
                        "metric": f"ed25519_qc_batch_verify_{n_sigs}sigs_{reason_suffix}_cpu_only",
                        "value": round(cpu_us_per_sig, 3),
                        "unit": "us/sig",
                        "vs_baseline": 1.0,
                    }
                ),
                flush=True,
            )
            os._exit(code)

        try:
            dev_s = fut.result(timeout=budget)
        except FutTimeout:
            fallback("TPU_UNREACHABLE")
        except KeyboardInterrupt:
            fallback("INTERRUPTED", code=130)
        except Exception:
            # A fast-failing device error or a verification-correctness
            # regression is NOT an outage: keep the one-line contract but
            # label it distinctly, preserve the diagnostic, and exit
            # nonzero so exit-status checks see the failure.
            import traceback

            traceback.print_exc(file=sys.stderr)
            fallback("DEVICE_ERROR", code=1)

    us_per_sig = dev_s / n_sigs * 1e6
    print(
        json.dumps(
            {
                "metric": f"ed25519_qc_batch_verify_{n_sigs}sigs",
                "value": round(us_per_sig, 3),
                "unit": "us/sig",
                "vs_baseline": round(cpu_us_per_sig / us_per_sig, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
