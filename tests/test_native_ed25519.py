"""Property tests for the native C++ Ed25519 batch-verification engine
against the pure-Python RFC 8032 oracle (``crypto/ed25519_ref``) — the
same oracle the device kernels are tested against, so all three verifier
planes (TPU, native CPU, Python) are pinned to one semantics
(dalek ``verify_batch``, reference ``crypto/src/lib.rs:206-219``)."""

import random

import pytest

from hotstuff_tpu.crypto import CpuBackend, CryptoError
from hotstuff_tpu.crypto import ed25519_ref as ref
from hotstuff_tpu.crypto.cpu_batch import verify_batch_rlc_pippenger
from hotstuff_tpu.crypto.native_ed25519 import (
    decompress_check,
    native_available,
    verify_batch_native,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ toolchain unavailable"
)


def _batch(n, rng):
    msgs, pubs, sigs = [], [], []
    for _ in range(n):
        seed = rng.randbytes(32)
        pubs.append(ref.secret_to_public(seed))
        msgs.append(rng.randbytes(32))
        sigs.append(ref.sign(seed, msgs[-1]))
    return msgs, pubs, sigs


def test_decompress_agrees_with_oracle_on_random_encodings():
    rng = random.Random(31)
    for _ in range(200):
        enc = rng.randbytes(32)
        assert decompress_check(enc) == (ref.point_decompress(enc) is not None)


def test_decompress_accepts_known_points_rejects_noncanonical():
    assert decompress_check(ref.point_compress(ref.G))
    assert decompress_check(ref.point_compress(ref.point_mul(987654321, ref.G)))
    # y = p is a non-canonical encoding of 0 and must be rejected.
    assert not decompress_check(ref.P.to_bytes(32, "little"))
    # -0 (y=1... actually x=0 with sign bit set) must be rejected.
    assert not decompress_check((1 | 1 << 255).to_bytes(32, "little"))


def test_valid_batch_accepts():
    rng = random.Random(32)
    msgs, pubs, sigs = _batch(16, rng)
    assert verify_batch_native(msgs, pubs, sigs, rng=rng)


@pytest.mark.parametrize("which", ["sig_s", "sig_r", "msg", "pub"])
def test_tampered_batch_rejects(which):
    rng = random.Random(33)
    msgs, pubs, sigs = _batch(8, rng)
    i = 3
    if which == "sig_s":
        s = int.from_bytes(sigs[i][32:], "little") ^ 2
        sigs[i] = sigs[i][:32] + s.to_bytes(32, "little")
    elif which == "sig_r":
        sigs[i] = ref.point_compress(ref.point_mul(7, ref.G)) + sigs[i][32:]
    elif which == "msg":
        msgs[i] = b"\x99" * 32
    else:
        pubs[i] = ref.secret_to_public(rng.randbytes(32))
    assert not verify_batch_native(msgs, pubs, sigs, rng=rng)


def test_noncanonical_s_rejected():
    rng = random.Random(34)
    msgs, pubs, sigs = _batch(4, rng)
    s = int.from_bytes(sigs[0][32:], "little") + ref.L
    sigs[0] = sigs[0][:32] + s.to_bytes(32, "little")
    assert not verify_batch_native(msgs, pubs, sigs, rng=rng)


def test_cofactored_semantics_match_python_batch_verifiers():
    """A signature with a torsion component in R verifies under the
    cofactored equation but not the strict one; all three batch verifiers
    must AGREE (accept), or a committee mixing backends would split."""
    rng = random.Random(35)
    msgs, pubs, sigs = _batch(3, rng)
    t = ref.torsion_generator()
    r_pt = ref.point_decompress(sigs[0][:32])
    sigs0_torsioned = ref.point_compress(ref.point_add(r_pt, t)) + sigs[0][32:]
    # The torsioned R changes the challenge hash, so re-sign around it:
    # build a fresh signature whose equation holds cofactored-only.
    # 8(sB) == 8(R' + hA) where R' = R + torsion.
    msgs2 = [msgs[0]]
    pubs2 = [pubs[0]]
    seed = b"\x42" * 32
    pub = ref.secret_to_public(seed)
    a, prefix = ref.secret_expand(seed)
    r = int.from_bytes(ref._sha512(prefix + msgs2[0]), "little") % ref.L
    big_r = ref.point_mul(r, ref.G)
    big_r_enc = ref.point_compress(ref.point_add(big_r, t))  # torsioned R
    h = ref.compute_challenge(big_r_enc, pub, msgs2[0])
    s = (r + h * a) % ref.L
    sig = big_r_enc + s.to_bytes(32, "little")
    pubs2 = [pub]
    items = (msgs2, pubs2, [sig])
    assert not ref.verify(pub, msgs2[0], sig, strict=True)
    assert ref.verify(pub, msgs2[0], sig, strict=False)
    assert verify_batch_native(*items, rng=random.Random(1))
    assert verify_batch_rlc_pippenger(*items, rng=random.Random(1))
    del sigs0_torsioned


def test_python_pippenger_agrees_with_native():
    rng = random.Random(36)
    msgs, pubs, sigs = _batch(6, rng)
    assert verify_batch_rlc_pippenger(msgs, pubs, sigs, rng=random.Random(2))
    assert verify_batch_native(msgs, pubs, sigs, rng=random.Random(2))
    msgs[2] = b"\x01" * 32
    assert not verify_batch_rlc_pippenger(msgs, pubs, sigs, rng=random.Random(2))
    assert not verify_batch_native(msgs, pubs, sigs, rng=random.Random(2))


def test_cpu_backend_uses_rlc_and_rejects_bad_batches():
    rng = random.Random(37)
    msgs, pubs, sigs = _batch(5, rng)
    backend = CpuBackend()
    assert backend._rlc is not None  # native engine picked up
    backend.verify_batch(msgs, pubs, sigs)  # no raise
    msgs[1] = b"\x00" * 32
    with pytest.raises(CryptoError):
        backend.verify_batch(msgs, pubs, sigs)


def test_window_choice_is_sane():
    from hotstuff_tpu.crypto.native_ed25519 import _pippenger_window

    assert 1 <= _pippenger_window(3) <= 4
    assert 4 <= _pippenger_window(201) <= 6
    assert 6 <= _pippenger_window(2687) <= 9
