"""Telemetry plane tests: registry primitives (thread-shard merge,
histogram bucketing), snapshot schema round-trips, round-trace spans, and
the measurement-parity contract — a real in-process 4-node run whose
telemetry stream must agree with the regex log parser on TPS/latency."""

from __future__ import annotations

import asyncio
import io
import json
import logging
import threading
import time
from datetime import datetime, timezone

import pytest

from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry import (
    Registry,
    RoundTrace,
    TelemetryEmitter,
    build_snapshot,
    validate_snapshot,
)

from .common import async_test

BASE = 15400


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


# -- registry primitives ----------------------------------------------------


def test_counter_thread_shard_merge():
    r = Registry()
    c = r.counter("t.hits")
    n_threads, per_thread = 8, 10_000

    def worker():
        for _ in range(per_thread):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == n_threads * per_thread


def test_histogram_bucketing_and_shard_merge():
    r = Registry()
    h = r.histogram("t.lat", buckets=(1, 10, 100))
    # Edges are upper-INCLUSIVE; above the last edge goes to overflow.
    observations = {0.5: 0, 1.0: 0, 1.5: 1, 10.0: 1, 99.0: 2, 100.5: 3}

    def worker(items):
        for v in items:
            h.observe(v)

    items = list(observations)
    threads = [
        threading.Thread(target=worker, args=(items,)) for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    counts, total, n = h.merged()
    assert n == 4 * len(items)
    assert total == pytest.approx(4 * sum(items))
    expected = [0] * 4
    for bucket in observations.values():
        expected[bucket] += 4
    assert counts == expected
    assert h.mean() == pytest.approx(sum(items) / len(items))


def test_histogram_rejects_unsorted_buckets():
    with pytest.raises(ValueError):
        Registry().histogram("t.bad", buckets=(10, 1))


def test_gauge_watermarks():
    r = Registry()
    g = r.gauge("t.g")
    assert g.value() is None
    g.set_min(5.0)
    g.set_min(7.0)  # not smaller: ignored
    assert g.value() == 5.0
    g2 = r.gauge("t.g2")
    g2.set_max(5.0)
    g2.set_max(3.0)
    assert g2.value() == 5.0


def test_registry_name_type_conflicts():
    r = Registry()
    r.counter("t.x")
    with pytest.raises(TypeError):
        r.gauge("t.x")
    with pytest.raises(ValueError):
        r.counter("bad name!")


def test_counter_identity_is_stable():
    r = Registry()
    assert r.counter("t.same") is r.counter("t.same")


def test_collector_values_appear_as_gauges():
    r = Registry()
    r.register_collector("engine", lambda: {"alpha": 3, "beta": 4.5})
    gauges = r.snapshot()["gauges"]
    assert gauges["engine.alpha"] == 3
    assert gauges["engine.beta"] == 4.5
    # A failing collector degrades to absence, never an exception.
    r.register_collector("engine", lambda: 1 / 0)
    assert "engine.alpha" not in r.snapshot()["gauges"]


# -- snapshot schema --------------------------------------------------------


def test_snapshot_schema_roundtrip(tmp_path):
    r = Registry()
    r.counter("c.events").inc(7)
    r.gauge("g.depth").set(3)
    r.histogram("h.ms", buckets=(1, 10)).observe(5)
    emitter = TelemetryEmitter(r, str(tmp_path / "telemetry-x.jsonl"), node="x")
    emitter.emit()
    r.counter("c.events").inc()
    emitter.emit(final=True)

    from benchmark.logs import TelemetryParser, read_telemetry_stream

    snaps = read_telemetry_stream(str(tmp_path / "telemetry-x.jsonl"))
    assert [s["seq"] for s in snaps] == [0, 1]
    assert snaps[-1]["final"] is True
    assert snaps[-1]["counters"]["c.events"] == 8
    for s in snaps:
        assert validate_snapshot(s) == []
    parser = TelemetryParser.process(str(tmp_path))
    assert parser.counter_total("c.events") == 8


def test_validate_snapshot_rejects_malformed():
    good = build_snapshot(Registry(), node="n")
    assert validate_snapshot(good) == []
    assert validate_snapshot([]) != []
    bad = dict(good, schema="other")
    assert any("schema" in p for p in validate_snapshot(bad))
    bad = json.loads(json.dumps(good))
    bad["histograms"]["h"] = {"le": [1, 2], "counts": [1, 2], "sum": 0, "count": 3}
    problems = validate_snapshot(bad)
    assert problems, "edges+1 counts invariant not enforced"


def test_read_telemetry_stream_raises_on_garbage(tmp_path):
    from benchmark.logs import ParseError, read_telemetry_stream

    # Mid-stream corruption still raises (a real bug, not crash fallout):
    # the garbage line is followed by a valid snapshot.
    good = json.dumps(build_snapshot(Registry(), node="n"))
    path = tmp_path / "telemetry-bad.jsonl"
    path.write_text(f"not json\n{good}\n")
    with pytest.raises(ParseError):
        read_telemetry_stream(str(path))


def test_read_telemetry_stream_tolerates_truncated_final_line(tmp_path):
    """A node SIGKILLed mid-write leaves a truncated last line; the
    reader must keep the valid prefix and count the loss."""
    from benchmark.logs import TelemetryParser, read_telemetry_stream

    r = Registry()
    r.counter("c.events").inc(3)
    path = tmp_path / "telemetry-crash.jsonl"
    emitter = TelemetryEmitter(r, str(path), node="crash")
    emitter.emit()
    emitter.emit()
    with open(path, "a") as f:
        f.write('{"schema": "hotstuff-telemetry-v1", "node": "crash", "coun')
    snaps = read_telemetry_stream(str(path))
    assert len(snaps) == 2
    assert snaps.skipped == 1
    parser = TelemetryParser([list(snaps)])
    assert parser.counter_total("c.events") == 3
    parser = TelemetryParser([snaps])
    assert parser.skipped_lines == 1


def test_stream_interleaves_trace_records(tmp_path):
    """Trace lines ride the same stream; the snapshot reader separates
    them and read_stream_records hands both out."""
    from benchmark.logs import read_stream_records, read_telemetry_stream

    telemetry.enable()
    r = telemetry.get_registry()
    buf = telemetry.trace_buffer()
    path = tmp_path / "telemetry-t.jsonl"
    emitter = TelemetryEmitter(r, str(path), node="t", trace=buf)
    telemetry.trace_event("n0", 1, "propose")
    telemetry.trace_event("n0", 1, "commit")
    emitter.emit()
    telemetry.trace_event("n0", 2, "propose")
    emitter.emit(final=True)

    records = read_stream_records(str(path))
    assert len(records.snapshots) == 2
    assert len(records.traces) == 2
    # Delta semantics: each trace line carries only NEW events.
    assert len(records.traces[0]["events"]) == 2
    assert len(records.traces[1]["events"]) == 1
    assert records.traces[0]["anchor"]["wall"] > 0
    snaps = read_telemetry_stream(str(path))  # trace lines separated out
    assert len(snaps) == 2 and snaps.skipped == 0


def test_emitter_final_flush_is_idempotent(tmp_path):
    """arm_shutdown_flush's atexit/SIGTERM paths and a graceful shutdown
    can all race to emit the final snapshot; exactly one must land."""
    from benchmark.logs import read_telemetry_stream

    r = Registry()
    path = tmp_path / "telemetry-f.jsonl"
    emitter = TelemetryEmitter(r, str(path), node="f")
    emitter.emit(final=True)
    emitter.emit(final=True)  # duplicate flush: swallowed
    snaps = read_telemetry_stream(str(path))
    assert len(snaps) == 1
    assert snaps[0]["final"] is True


def test_superbatch_per_sig_histogram_resolves_microseconds():
    """The fine buckets must separate a 25 µs/sig flush from a 60 µs one
    (both sat in DURATION_MS_BUCKETS' first 0.1 ms bucket)."""
    from hotstuff_tpu.telemetry import FINE_DURATION_MS_BUCKETS

    r = Registry()
    h = r.histogram("crypto.superbatch.per_sig_ms", FINE_DURATION_MS_BUCKETS)
    h.observe(0.025)
    h.observe(0.060)
    counts, _, n = h.merged()
    assert n == 2
    assert sum(1 for c in counts if c) == 2, "µs regimes share a bucket"


# -- round-trace spans ------------------------------------------------------


def test_round_trace_spans_record_and_gc():
    r = Registry()
    trace = RoundTrace(r)
    trace.mark_propose(5)
    trace.mark_vote(5)
    trace.mark_qc(5)
    trace.mark_commit(5)
    for name, want in (
        ("consensus.span.propose_to_first_vote_ms", 1),
        ("consensus.span.first_vote_to_qc_ms", 1),
        ("consensus.span.qc_to_commit_ms", 1),
        ("consensus.span.propose_to_commit_ms", 1),
    ):
        _, _, n = r.histogram(name).merged()
        assert n == want, name
    assert trace.open_rounds() == 0  # commit GC'd the round

    # Partial marks never crash and never record bogus spans.
    trace.mark_qc(9)
    trace.mark_commit(9)
    _, _, n = r.histogram("consensus.span.qc_to_commit_ms").merged()
    assert n == 2
    _, _, n = r.histogram("consensus.span.propose_to_commit_ms").merged()
    assert n == 1  # round 9 had no propose mark

    # Bounded table: far more rounds than the cap never grow state.
    for round_ in range(10_000):
        trace.mark_propose(round_)
    assert trace.open_rounds() <= 512


def test_round_trace_none_when_disabled():
    assert telemetry.round_trace() is None
    telemetry.enable()
    assert telemetry.round_trace() is not None


# -- benchmark-interface tables --------------------------------------------


def test_record_tables_join_on_first_commit():
    telemetry.enable()
    r = telemetry.get_registry()
    telemetry.record_sealed(b"d1", 1_000)
    telemetry.record_created(b"d1", ts=100.0)
    telemetry.record_commit(b"d1", ts=100.5)
    telemetry.record_commit(b"d1", ts=107.0)  # later duplicate: no effect
    snap = r.snapshot()
    assert snap["counters"]["consensus.committed_bytes"] == 1_000
    assert snap["counters"]["consensus.batches_committed"] == 1
    assert snap["gauges"]["consensus.first_proposal_ts"] == 100.0
    assert snap["gauges"]["consensus.last_commit_ts"] == 100.5
    h = snap["histograms"]["consensus.commit_latency_ms"]
    assert h["count"] == 1 and h["sum"] == pytest.approx(500.0)


def test_record_tables_noop_when_disabled():
    telemetry.record_sealed(b"d1", 1_000)
    telemetry.record_commit(b"d1")
    assert "consensus.committed_bytes" not in telemetry.get_registry().snapshot()["counters"]


# -- native ed25519 engine counters ----------------------------------------


def test_native_ed25519_stats_export():
    from hotstuff_tpu.crypto import native_ed25519

    if not native_ed25519.native_available():
        pytest.skip("native ed25519 engine unavailable")
    before = native_ed25519.native_stats()
    from hotstuff_tpu.crypto import ed25519_ref as ref

    seed = bytes(range(32))
    pub = ref.secret_to_public(seed)
    msg = b"m" * 32
    sig = ref.sign(seed, msg)
    assert native_ed25519.verify_batch_native([msg] * 2, [pub] * 2, [sig] * 2)
    after = native_ed25519.native_stats()
    assert after["msm_calls"] > before["msm_calls"]
    assert after["msm_points"] >= before["msm_points"] + 5  # 2n+1 lanes


# -- measurement parity: telemetry stream vs regex log scrape ---------------


def _iso(ts: float) -> str:
    dt = datetime.fromtimestamp(ts, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


@async_test(timeout=120)
async def test_telemetry_agrees_with_regex_parser(tmp_path):
    """Boot the 4-node in-process testbed with benchmark logging AND
    telemetry enabled, drive real transactions, then compute TPS/latency
    twice — regex-scraping the captured logs (LogParser) and reading the
    telemetry snapshot (TelemetryParser) — and require agreement."""
    from benchmark.logs import LogParser, TelemetryParser
    from hotstuff_tpu.node import Node
    from hotstuff_tpu.network.receiver import write_frame
    from hotstuff_tpu.utils.logging import _EnvLoggerFormatter

    from .test_node import _write_testbed

    telemetry.enable()

    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    handler.setFormatter(_EnvLoggerFormatter())
    handler.setLevel(logging.INFO)
    root = logging.getLogger()
    old_level = root.level
    root.addHandler(handler)
    root.setLevel(logging.INFO)

    nodes = []
    writer = None
    tx_size = 512
    n_txs = 20
    try:
        committee_file, params_file, key_files = _write_testbed(
            tmp_path, BASE, n=4
        )
        for i, kf in enumerate(key_files):
            nodes.append(
                await Node.new(
                    committee_file,
                    kf,
                    str(tmp_path / f"db_{i}"),
                    parameters_file=params_file,
                    benchmark=True,
                )
            )

        _, writer = await asyncio.open_connection("127.0.0.1", BASE + 100)
        start_ts = time.time()
        for i in range(n_txs):
            # 0x01 lead byte: standard transaction (not a latency sample).
            write_frame(writer, b"\x01" + i.to_bytes(8, "big") + b"\xab" * (tx_size - 9))
            await writer.drain()
            await asyncio.sleep(0.1)

        # Drain commits until the committee went quiet — no PAYLOAD commit
        # anywhere for a while (empty blocks keep flowing forever; only
        # payload commits move the measured window).
        async def drain_until_quiet(node):
            last_payload = time.monotonic()
            while time.monotonic() - last_payload < 1.5:
                try:
                    blk = await asyncio.wait_for(node.commit.get(), timeout=0.5)
                    if blk.payload:
                        last_payload = time.monotonic()
                except asyncio.TimeoutError:
                    pass

        await asyncio.gather(*[drain_until_quiet(n) for n in nodes])
    finally:
        if writer is not None:
            writer.close()
        for node in nodes:
            await node.shutdown()
        root.removeHandler(handler)
        root.setLevel(old_level)

    node_log = buf.getvalue()
    assert "Committed B" in node_log, f"no commits in captured log:\n{node_log[-2000:]}"
    client_log = (
        f"[{_iso(start_ts)} INFO client] Transactions size: {tx_size} B\n"
        f"[{_iso(start_ts)} INFO client] Transactions rate: 10 tx/s\n"
        f"[{_iso(start_ts)} INFO client] Start sending transactions\n"
    )
    regex = LogParser([client_log], [node_log])
    tele = TelemetryParser(
        [[build_snapshot(telemetry.get_registry(), node="testbed", final=True)]],
        tx_size=tx_size,
    )

    # Committed bytes must agree EXACTLY: both paths credit each batch
    # once, at the same seal-site size.
    assert tele.committed_bytes == sum(regex.batch_sizes.values())

    r_tps, r_bps, r_duration = regex._consensus_throughput()
    t_tps, t_bps, t_duration = tele.consensus_throughput()
    assert t_duration == pytest.approx(r_duration, abs=0.05)
    assert t_tps == pytest.approx(r_tps, rel=0.10)

    r_latency_ms = regex._consensus_latency() * 1e3
    t_latency_ms = tele.consensus_latency_ms()
    assert t_latency_ms == pytest.approx(r_latency_ms, abs=10.0)

    # The parity run doubles as wiring coverage: every plane recorded.
    snap = tele.snapshots[0]
    assert snap["counters"]["consensus.qcs_formed"] > 0
    assert snap["counters"]["mempool.batches_sealed"] > 0
    assert snap["counters"]["net.frames_in"] > 0
    assert snap["histograms"]["consensus.span.propose_to_commit_ms"]["count"] > 0
