"""Benchmark harness tests: aggregation and plotting from result files
(the LocalBench E2E flow is exercised by the driver/verify runs — booting
real process committees is too heavy for the unit suite)."""

import os

from benchmark.aggregate import LogAggregator, Setup
from benchmark.logs import LogParser

SUMMARY_TEMPLATE = """
-----------------------------------------
 SUMMARY:
-----------------------------------------
 + CONFIG:
 Faults: {faults} nodes
 Committee size: {nodes} nodes
 Input rate: {rate:,} tx/s
 Transaction size: 512 B
 Execution time: 20 s

 Consensus timeout delay: 1,000 ms
 Consensus sync retry delay: 10,000 ms
 Mempool GC depth: 50 rounds
 Mempool sync retry delay: 5,000 ms
 Mempool sync retry nodes: 3 nodes
 Mempool batch size: 15,000 B
 Mempool max batch delay: 10 ms

 + RESULTS:
 Consensus TPS: {tps:,} tx/s
 Consensus BPS: 495,294 B/s
 Consensus latency: 2 ms

 End-to-end TPS: {tps:,} tx/s
 End-to-end BPS: 491,691 B/s
 End-to-end latency: {latency:,} ms
-----------------------------------------
"""


def _write_results(tmp_path):
    cases = [
        (0, 4, 1_000, 960, 31),
        (0, 4, 1_000, 940, 35),  # second run of the same setup
        (0, 4, 2_000, 1_800, 60),
        (1, 4, 1_000, 600, 1_000),
    ]
    for faults, nodes, rate, tps, latency in cases:
        path = tmp_path / f"bench-{faults}-{nodes}-{rate}-512.txt"
        with open(path, "a") as f:
            f.write(
                SUMMARY_TEMPLATE.format(
                    faults=faults, nodes=nodes, rate=rate, tps=tps, latency=latency
                )
            )
    return str(tmp_path)


def test_aggregator_mean_std(tmp_path):
    agg = LogAggregator(_write_results(tmp_path))
    series = agg.latency_vs_rate(faults=0, nodes=4, tx_size=512)
    assert len(series) == 2
    rate, tps, tps_std, lat, lat_std = series[0]
    assert rate == 1_000 and tps == 950 and lat == 33
    assert tps_std > 0
    assert series[1][0] == 2_000


def test_aggregator_tps_vs_nodes(tmp_path):
    agg = LogAggregator(_write_results(tmp_path))
    rows = agg.tps_vs_nodes(faults=0, tx_size=512)
    assert rows == [(4, 1800.0, 0)]
    capped = agg.tps_vs_nodes(faults=0, tx_size=512, max_latency=50)
    assert capped[0][1] == 950.0  # 2k-rate point excluded by latency cap


def test_plots_render(tmp_path):
    from benchmark.plot import Ploter

    results = _write_results(tmp_path)
    ploter = Ploter(results)
    out1 = ploter.plot_latency([0, 1], [4], 512, out=str(tmp_path / "lat.pdf"))
    out2 = ploter.plot_tps([0], 512, out=str(tmp_path / "tps.pdf"))
    assert os.path.getsize(out1) > 1_000
    assert os.path.getsize(out2) > 1_000


def test_log_parser_rejects_panics(tmp_path):
    import pytest

    from benchmark.logs import ParseError

    with pytest.raises(ParseError):
        LogParser(["Traceback (most recent call last):"], ["x"], 0)
