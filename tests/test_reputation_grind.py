"""The reputation-elector "timeout grind" regression (closes the round-4
heisenbug; replaces the always-clean ``benchmark/diag_reputation.py``).

Root cause, pinned with faultline's deterministic scheduling: when honest
nodes' committed windows transiently DIVERGE (a straggler that
TC-advanced past its commit progress, or the boot transition from
round-robin to window election under a vote split), the committee can
enter rounds where no candidate is both self-elected and endorsed by a
quorum. Nothing commits in a timeout round, so the windows that caused
the disagreement stay FROZEN — convergence waited on a hash(round)
coincidence, burning a full ``timeout_delay`` per miss (multi-second
stalls with rounds advancing; ~2/30 e2e reproductions).

Fix under test: a round entered via TimeoutCertificate elects by
ROUND-ROBIN (``ReputationLeaderElector.note_round_entry``) — window-free
and therefore identical on every node that saw the timeout, so the grind
is bounded at one wasted timeout regardless of window divergence.
"""

import pytest

from hotstuff_tpu.consensus.leader import ReputationLeaderElector, RRLeaderElector
from hotstuff_tpu.faultline import Scenario

from .common import async_test, chain, consensus_committee, keys

BASE = 25600


def _divergent_electors():
    """Two electors over the SAME chain but with one node lagging two
    commits — the exact transient the commit-batching skew produces."""
    committee = consensus_committee(BASE)
    blocks = chain(12)
    ahead = ReputationLeaderElector(committee)
    behind = ReputationLeaderElector(committee)
    for blk in blocks:
        ahead.update(blk)
    for blk in blocks[:-2]:
        behind.update(blk)
    return committee, ahead, behind, blocks


def test_divergent_windows_disagree_without_tc_fallback():
    """The root cause, demonstrated: a two-commit lag makes the electors
    disagree on at least one upcoming round's leader — each such round
    under a frozen window burns a full timeout."""
    _, ahead, behind, blocks = _divergent_electors()
    start = blocks[-1].round + ReputationLeaderElector.LAG
    picks = [
        (ahead.get_leader(r), behind.get_leader(r))
        for r in range(start - 3, start + 6)
    ]
    assert any(a != b for a, b in picks), (
        "fixture no longer produces divergent elections; rebuild it "
        "with a different lag"
    )


def test_tc_entered_round_elects_round_robin_on_every_node():
    """The fix: marking a round TC-entered flips BOTH electors to the
    same deterministic round-robin leader, whatever their windows say."""
    committee, ahead, behind, blocks = _divergent_electors()
    rr = RRLeaderElector(committee)
    start = blocks[-1].round + ReputationLeaderElector.LAG
    for r in range(start - 3, start + 6):
        ahead.note_round_entry(r, via_tc=True)
        behind.note_round_entry(r, via_tc=True)
        assert ahead.get_leader(r) == behind.get_leader(r) == rr.get_leader(r)
    # Rounds NOT entered via TC keep window-based election.
    far = start + 100
    ahead.note_round_entry(far, via_tc=False)
    assert far not in ahead._tc_set


def test_tc_memory_is_bounded():
    committee = consensus_committee(BASE)
    rep = ReputationLeaderElector(committee)
    for r in range(10_000):
        rep.note_round_entry(r, via_tc=True)
    assert len(rep._tc_set) <= ReputationLeaderElector.TC_MEMORY
    assert len(rep._tc_rounds) <= ReputationLeaderElector.TC_MEMORY
    # Oldest marks expired; newest retained.
    assert 9_999 in rep._tc_set and 0 not in rep._tc_set


def test_rr_elector_accepts_round_entry_feed():
    committee = consensus_committee(BASE)
    rr = RRLeaderElector(committee)
    rr.note_round_entry(7, via_tc=True)  # must be a no-op, not an error
    assert rr.get_leader(7) == committee.sorted_keys()[7 % 4]


@async_test(timeout=150)
async def test_reputation_committee_survives_grind_scenario():
    """Seeded e2e regression: the grind-inducing storm — a silent leader
    (every election of that seat burns a timeout round, forcing repeated
    TC entries) plus a partition straggler (TC-advanced window
    divergence) — on a live reputation-elector committee. The checker
    must report safety=ok and post-heal commit recovery. Pre-fix this
    scenario ground through hash-coincidence timeouts; post-fix every
    TC round re-converges on the round-robin leader."""
    from hotstuff_tpu.faultline import run_scenario

    scenario = Scenario(
        name="reputation-grind", seed=413, duration_s=8.0,
        events=[
            # The committee builds full windows, then one node is cut
            # away while the rest keep committing (its window goes
            # stale), and a silent leader forces timeout rounds right as
            # the partition heals.
            {"kind": "partition", "groups": [[3], [0, 1, 2]],
             "at": 1.0, "until": 4.0},
            {"kind": "byzantine", "node": 0, "behavior": "silent_leader",
             "at": 3.5, "until": 6.0},
        ],
    )
    result = await run_scenario(
        scenario, 4, base_port=BASE + 20, timeout_delay=500,
        leader_elector="reputation", recovery_timeout_s=60.0,
    )
    verdict = result["verdict"]
    assert verdict["safety"]["ok"], verdict["safety"]
    assert verdict["liveness"]["recovered"], verdict["liveness"]


@pytest.mark.slow
@async_test(timeout=300)
async def test_reputation_grind_seed_sweep():
    """The captured reproductions: chaos seeds 11 and 12 ground a
    pre-fix reputation committee to a TOTAL post-heal stall (zero
    commits in 25 s of recovery window, rounds still advancing) in the
    seeded hunt that pinned this bug. With the TC round-robin fallback
    both recover. Keep these seeds verbatim — they are the only known
    deterministic schedules that reached the frozen-divergent-window
    regime at N=4."""
    from hotstuff_tpu.faultline import chaos_scenario, run_scenario

    for i, seed in enumerate((11, 12)):
        scenario = chaos_scenario(
            seed, duration_s=8.0, crashes=1, partitions=1, byzantine=1,
            links=1,
        )
        result = await run_scenario(
            scenario, 4, base_port=BASE + 40 + i * 8, timeout_delay=500,
            leader_elector="reputation", recovery_timeout_s=60.0,
        )
        verdict = result["verdict"]
        assert verdict["safety"]["ok"], (seed, verdict["safety"])
        assert verdict["liveness"]["recovered"], (seed, verdict["liveness"])
