"""Equivalence tests for the native (C++) vote pre-stage.

The pre-stage is a FILTER in front of the consensus core: it may only
drop vote frames the core would provably drop cheaply (unknown seats,
stale/far-future rounds, byte-identical resends), and everything it
admits must reach the core byte-for-byte. These tests drive a fuzzed
vote stream through a real native listener and assert the admitted set
matches a model of the core's own cheap-drop gate — including the
duplicate-vote ejection path, where a genuine re-send after a spoofed
seat MUST pass the filter for the core's re-seat logic to restore
liveness.

Skipped wholesale if the toolchain cannot build the native library.
"""

import asyncio
import random

import pytest

from hotstuff_tpu.network import native as hsnative
from hotstuff_tpu.network.receiver import write_frame
from hotstuff_tpu.consensus.messages import (
    Vote,
    decode_vote_frame,
    encode_vote,
)
from hotstuff_tpu.crypto import Signature, generate_keypair, sha512_digest

from .common import async_test, keys

pytestmark = pytest.mark.skipif(
    not hsnative.available(), reason="native transport toolchain unavailable"
)

BASE_PORT = 18600
LOOKAHEAD = 1000  # == Core.MAX_ROUND_LOOKAHEAD == netcore VOTE_ROUND_LOOKAHEAD


class _CollectingHandler:
    """Records exactly what the pre-stage delivers, in order."""

    def __init__(self):
        self.votes: list[bytes] = []  # raw frames via dispatch_votes
        self.frames: list[bytes] = []  # anything else via dispatch

    async def dispatch_votes(self, frames):
        self.votes.extend(frames)

    async def dispatch(self, writer, message):
        self.frames.append(message)


def _model_filter(stream, committee_keys, current_round):
    """The documented pre-stage contract, in pure Python: admit exactly
    the frames the core's cheap pre-verification gate would not drop.
    ``stream`` is a list of wire frames; returns the admitted subset."""
    seats = {pk.data for pk in committee_keys}
    latest: dict[tuple[int, bytes], bytes] = {}  # (round, author) -> frame
    admitted = []
    for frame in stream:
        if len(frame) != 137 or frame[0] != 1:
            continue  # not a fixed-layout vote: flows through EV_RECV
        round_ = int.from_bytes(frame[33:41], "little")
        author = frame[41:73]
        if author not in seats:
            continue
        if round_ < current_round or round_ > current_round + LOOKAHEAD:
            continue
        key = (round_, author)
        if latest.get(key) == frame:
            continue  # byte-identical resend of the seat's latest vote
        latest[key] = frame
        admitted.append(frame)
    return admitted


async def _run_stream(port, committee_keys, current_round, stream):
    """Push ``stream`` through a native listener with the pre-stage on;
    return (admitted vote frames, passthrough frames) as Python saw them."""
    handler = _CollectingHandler()
    receiver = await hsnative.NativeReceiver.spawn(
        ("127.0.0.1", port), handler, auto_ack=True
    )
    try:
        receiver.configure_vote_prestage([pk.data for pk in committee_keys])
        receiver.set_round(current_round)
        await asyncio.sleep(0.05)
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        for frame in stream:
            write_frame(writer, frame)
        await writer.drain()
        # Wait for the stream to fully drain through the loop thread.
        expected_total = None
        for _ in range(200):
            await asyncio.sleep(0.02)
            total = len(handler.votes) + len(handler.frames)
            if total == expected_total:
                break
            expected_total = total
        writer.close()
        return list(handler.votes), list(handler.frames)
    finally:
        await receiver.shutdown()


@async_test(timeout=120)
async def test_prestage_equivalence_fuzzed_stream():
    """A fuzzed mix of valid votes, unknown-seat votes, stale/future
    rounds, identical resends, conflicting re-signs, and non-vote frames:
    the native filter must admit exactly the model's set, in order, and
    route every non-vote frame through the normal path untouched."""
    committee = keys(4)
    outsider = generate_keypair(seed=b"\xee" * 32)
    rng = random.Random(1234)
    current_round = 50

    digests = [sha512_digest(b"block-%d" % i) for i in range(3)]
    stream: list[bytes] = []
    for i in range(300):
        roll = rng.random()
        if roll < 0.35:
            # Honest vote at a live round.
            pk, sk = committee[rng.randrange(4)]
            round_ = current_round + rng.randrange(3)
            stream.append(
                encode_vote(
                    Vote.new_from_key(digests[rng.randrange(3)], round_, pk, sk)
                )
            )
        elif roll < 0.45 and stream:
            # Identical resend of a random earlier frame.
            stream.append(stream[rng.randrange(len(stream))])
        elif roll < 0.55:
            # Same seat+round+digest, different signature (spoof shape):
            # MUST pass the filter (core arbitrates via re-seat logic).
            pk, _ = committee[rng.randrange(4)]
            fake = Vote(
                digests[rng.randrange(3)],
                current_round + rng.randrange(3),
                pk,
                Signature(rng.randbytes(64)),
            )
            stream.append(encode_vote(fake))
        elif roll < 0.65:
            # Unknown seat (not in the committee table): dropped.
            round_ = current_round + rng.randrange(3)
            stream.append(
                encode_vote(
                    Vote.new_from_key(
                        digests[0], round_, outsider[0], outsider[1]
                    )
                )
            )
        elif roll < 0.75:
            # Stale or far-future round: dropped.
            pk, sk = committee[rng.randrange(4)]
            round_ = rng.choice(
                [
                    rng.randrange(current_round),
                    current_round + LOOKAHEAD + 1 + rng.randrange(1000),
                ]
            )
            stream.append(
                encode_vote(Vote.new_from_key(digests[0], round_, pk, sk))
            )
        elif roll < 0.9:
            # Garbage that is NOT vote-shaped: must flow through EV_RECV.
            stream.append(rng.randbytes(rng.choice([5, 64, 136, 138, 200])))
        else:
            # Vote-tagged frame of exactly 137 bytes with random bytes:
            # the filter decodes offsets; unknown author bytes drop it.
            stream.append(b"\x01" + rng.randbytes(136))

    expected = _model_filter(stream, [pk for pk, _ in committee], current_round)
    expected_passthrough = [
        f for f in stream if not (len(f) == 137 and f[0] == 1)
    ]

    admitted, passthrough = await _run_stream(
        BASE_PORT, [pk for pk, _ in committee], current_round, stream
    )
    assert admitted == expected
    assert passthrough == expected_passthrough
    # Every admitted frame decodes as the vote that was sent.
    for frame in admitted:
        decode_vote_frame(frame)


@async_test(timeout=120)
async def test_prestage_duplicate_vote_ejection_equivalence():
    """The ejection liveness contract end-to-end through the filter: a
    spoofed signature occupies a seat, the identical spoof resend is
    dropped natively (the core would drop it via its bad-signature cache
    anyway), and the author's GENUINE vote — different bytes, same seat —
    passes the filter so the core can verify it individually and re-seat
    it. The batch path must accept the same final vote set as the
    per-vote path."""
    committee = keys(4)
    digest = sha512_digest(b"the-block")
    round_ = 7
    pk0, sk0 = committee[0]

    spoof = Vote(digest, round_, pk0, Signature(b"\x5a" * 64))
    genuine = Vote.new_from_key(digest, round_, pk0, sk0)
    stream = [
        encode_vote(spoof),
        encode_vote(spoof),  # identical resend: native drop
        encode_vote(genuine),  # different bytes: MUST pass for re-seat
        encode_vote(genuine),  # identical resend of the genuine: drop
    ]
    admitted, _ = await _run_stream(
        BASE_PORT + 1, [pk for pk, _ in committee], round_, stream
    )
    assert admitted == [encode_vote(spoof), encode_vote(genuine)]

    # Feed the admitted set to a real batched-verification core path:
    # aggregator seats the spoof, the genuine vote is individually
    # verified and re-seated — identical to what the per-vote path does
    # with the same admitted frames.
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.config import Committee as CCommittee
    from hotstuff_tpu.consensus import Authority

    ccommittee = CCommittee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", 0))
            for pk, _ in committee
        }
    )
    agg = Aggregator(ccommittee)
    votes = [decode_vote_frame(f) for f in admitted]
    agg.add_vote(votes[0])  # spoof takes the seat (batched mode: unverified)
    assert agg.stored_signature(round_, votes[0].digest(), pk0) == spoof.signature
    # The genuine vote conflicts; individual verification succeeds and
    # re-seats it (core._handle_vote_batched's arbitration).
    votes[1].verify(ccommittee)
    agg.reseat_vote(votes[1])
    assert (
        agg.stored_signature(round_, votes[1].digest(), pk0)
        == genuine.signature
    )
