"""Native (C++ epoll) transport tests — the same behavioral contract as
the asyncio suite (`tests/test_network.py`, modeled on the reference
network crate tests), plus cross-implementation interop: the two
transports share one wire format, so either side may be native.

Skipped wholesale if the toolchain cannot build the library.
"""

import asyncio

import pytest

from hotstuff_tpu.network import MessageHandler
from hotstuff_tpu.network import native as hsnative
from hotstuff_tpu.network.receiver import (
    Receiver as AsyncioReceiver,
    read_frame,
    write_frame,
)
from hotstuff_tpu.network.simple_sender import SimpleSender as AsyncioSimpleSender

from .common import async_test, listener

pytestmark = pytest.mark.skipif(
    not hsnative.available(), reason="native transport toolchain unavailable"
)

BASE_PORT = 18200


class _EchoHandler(MessageHandler):
    def __init__(self):
        self.received = []

    async def dispatch(self, writer, message: bytes) -> None:
        self.received.append(message)
        await writer.send(b"Ack")


@async_test
async def test_native_receiver_dispatch_and_reply():
    handler = _EchoHandler()
    receiver = await hsnative.NativeReceiver.spawn(
        ("127.0.0.1", BASE_PORT), handler
    )
    await asyncio.sleep(0.05)
    reader, writer = await asyncio.open_connection("127.0.0.1", BASE_PORT)
    write_frame(writer, b"hello")
    await writer.drain()
    assert await asyncio.wait_for(read_frame(reader), 5) == b"Ack"
    write_frame(writer, b"again")
    await writer.drain()
    assert await asyncio.wait_for(read_frame(reader), 5) == b"Ack"
    assert handler.received == [b"hello", b"again"]
    writer.close()
    await receiver.shutdown()


@async_test
async def test_native_simple_send_to_asyncio_listener():
    port = BASE_PORT + 1
    task = asyncio.create_task(listener(port, expected=b"payload"))
    await asyncio.sleep(0.05)
    sender = hsnative.NativeSimpleSender()
    sender.send(("127.0.0.1", port), b"payload")
    assert await asyncio.wait_for(task, 5) == b"payload"
    sender.shutdown()


@async_test
async def test_native_reliable_send_resolves_with_ack():
    port = BASE_PORT + 2
    task = asyncio.create_task(listener(port, expected=b"important"))
    await asyncio.sleep(0.05)
    sender = hsnative.NativeReliableSender()
    handler = await sender.send(("127.0.0.1", port), b"important")
    assert await asyncio.wait_for(handler, 5) == b"Ack"
    await task
    sender.shutdown()


@async_test
async def test_native_reliable_broadcast():
    ports = [BASE_PORT + 3 + i for i in range(3)]
    tasks = [asyncio.create_task(listener(p, expected=b"bcast")) for p in ports]
    await asyncio.sleep(0.05)
    sender = hsnative.NativeReliableSender()
    handlers = await sender.broadcast(
        [("127.0.0.1", p) for p in ports], b"bcast"
    )
    acks = await asyncio.wait_for(asyncio.gather(*handlers), 5)
    assert acks == [b"Ack"] * 3
    await asyncio.gather(*tasks)
    sender.shutdown()


@async_test(timeout=90)
async def test_native_reliable_retry_before_listener_exists():
    """Reference reliable_sender_tests.rs:50-67: send first, listener
    appears later, ACK still arrives (backoff reconnect + replay)."""
    port = BASE_PORT + 10
    sender = hsnative.NativeReliableSender()
    handler = await sender.send(("127.0.0.1", port), b"patience")
    await asyncio.sleep(0.5)  # let a few connect attempts fail
    task = asyncio.create_task(listener(port, expected=b"patience"))
    assert await asyncio.wait_for(handler, 30) == b"Ack"
    await task
    sender.shutdown()


@async_test
async def test_native_cancellation_skips_replay():
    """A cancelled handler's message is not replayed once the peer comes
    up: only the live message arrives."""
    port = BASE_PORT + 11
    sender = hsnative.NativeReliableSender()
    doomed = await sender.send(("127.0.0.1", port), b"doomed")
    await asyncio.sleep(0.2)
    doomed.cancel()
    live = await sender.send(("127.0.0.1", port), b"live")
    await asyncio.sleep(0.1)

    received = []

    class Collect(MessageHandler):
        async def dispatch(self, writer, message):
            received.append(message)
            await writer.send(b"Ack")

    receiver = await AsyncioReceiver.spawn(("127.0.0.1", port), Collect())
    assert await asyncio.wait_for(live, 30) == b"Ack"
    assert received == [b"live"]
    await receiver.shutdown()
    sender.shutdown()


@async_test
async def test_asyncio_sender_to_native_receiver_interop():
    """Wire compatibility the other way: the asyncio SimpleSender talks
    to a native receiver."""
    port = BASE_PORT + 12
    handler = _EchoHandler()
    receiver = await hsnative.NativeReceiver.spawn(("127.0.0.1", port), handler)
    await asyncio.sleep(0.05)
    sender = AsyncioSimpleSender()
    sender.send(("127.0.0.1", port), b"cross")
    await asyncio.sleep(0.3)
    assert handler.received == [b"cross"]
    sender.shutdown()
    await receiver.shutdown()


@async_test
async def test_native_cancel_reclaims_dead_peer_backlog():
    """Cancelling reliable messages to a permanently-down peer reclaims
    their queued frames immediately (not lazily in pump_out, which never
    runs while disconnected) — the crash-fault regime must not grow
    per-round garbage without bound. Observed via the loop-thread stats
    snapshot."""
    port = BASE_PORT + 20  # nothing ever listens here
    transport = hsnative.NativeTransport.get()
    base = transport.stats()
    sender = hsnative.NativeReliableSender()
    futs = [
        await sender.send(("127.0.0.1", port), b"round-%03d" % i)
        for i in range(50)
    ]
    await asyncio.sleep(0.1)
    grown = transport.stats()
    assert grown["pending"] >= base["pending"] + 50
    for fut in futs:
        fut.cancel()
    reclaimed = None
    for _ in range(150):
        await asyncio.sleep(0.02)
        s = transport.stats()
        if (
            s["pending"] <= base["pending"]
            and s["cancelled"] <= base["cancelled"]
        ):
            reclaimed = s
            break
    assert reclaimed is not None, f"backlog not reclaimed: {transport.stats()}"
    sender.shutdown()


@async_test
async def test_native_unresolvable_peer_fails_loudly_not_silently():
    """A hostname the resolver rejects is logged and dropped; a reliable
    send to it behaves like a permanently-down peer (future pending until
    cancelled) instead of retrying a bogus address forever."""
    sender = hsnative.NativeSimpleSender()
    sender.send(("no-such-host.invalid", 1), b"void")  # must not raise
    rsender = hsnative.NativeReliableSender()
    fut = await rsender.send(("no-such-host.invalid", 1), b"void")
    await asyncio.sleep(0.1)
    assert not fut.done()
    fut.cancel()
    sender.shutdown()
    rsender.shutdown()


@async_test
async def test_native_hostname_resolution():
    """Committee files may name peers by hostname: the native transport
    resolves them (AF_INET) instead of silently dropping every send the
    way a raw inet_pton-only path would."""
    port = BASE_PORT + 21
    task = asyncio.create_task(listener(port, expected=b"named"))
    await asyncio.sleep(0.05)
    sender = hsnative.NativeSimpleSender()
    sender.send(("localhost", port), b"named")
    assert await asyncio.wait_for(task, 5) == b"named"
    sender.shutdown()


@async_test(timeout=120)
async def test_native_receiver_flood_is_bounded_and_lossless():
    """A flooding peer must not grow the Python dispatch queue without
    bound: past the high-water mark the C++ loop stops reading (TCP
    back-pressure), and resuming later delivers every frame."""
    port = BASE_PORT + 22
    high, low = hsnative.RECV_HIGH_WATER, hsnative.RECV_LOW_WATER
    hsnative.RECV_HIGH_WATER, hsnative.RECV_LOW_WATER = 64, 16
    gate = asyncio.Event()
    seen = []

    class Block(MessageHandler):
        async def dispatch(self, writer, message):
            seen.append(message)
            await gate.wait()

    receiver = None
    try:
        receiver = await hsnative.NativeReceiver.spawn(
            ("127.0.0.1", port), Block()
        )
        await asyncio.sleep(0.05)
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        total = 600
        payload = b"x" * 4096

        async def pump():
            for i in range(total):
                write_frame(writer, payload)
                if i % 20 == 0:
                    await writer.drain()
            await writer.drain()

        send_task = asyncio.create_task(pump())
        max_q = 0
        for _ in range(100):
            await asyncio.sleep(0.02)
            max_q = max(max_q, receiver._queue.qsize())
        # The pause command races one or two 256 KiB read batches; the
        # bound is high-water plus that slack, far under the full flood.
        assert max_q < 300, max_q
        gate.set()
        await asyncio.wait_for(send_task, 30)
        for _ in range(400):
            await asyncio.sleep(0.05)
            if len(seen) >= total:
                break
        assert len(seen) == total  # paused, resumed, nothing lost
        writer.close()
    finally:
        hsnative.RECV_HIGH_WATER, hsnative.RECV_LOW_WATER = high, low
        if receiver is not None:
            await receiver.shutdown()


@async_test
async def test_native_throughput_many_frames():
    """Batched event delivery: thousands of small frames arrive intact
    and in order per connection."""
    port = BASE_PORT + 13
    handler = _EchoHandler()
    receiver = await hsnative.NativeReceiver.spawn(("127.0.0.1", port), handler)
    await asyncio.sleep(0.05)
    sender = hsnative.NativeSimpleSender()
    n = 2000
    for i in range(n):
        sender.send(("127.0.0.1", port), b"m%06d" % i)
        if i % 400 == 399:
            # Pace the burst under the best-effort sender's 1000-frame
            # queue cap (reference simple_sender.rs channel capacity —
            # both transports drop past it): the real client paces its
            # bursts too. Unpaced, the test races the drain thread.
            await asyncio.sleep(0.01)
    for _ in range(100):
        await asyncio.sleep(0.05)
        if len(handler.received) >= n:
            break
    assert len(handler.received) == n
    assert handler.received == [b"m%06d" % i for i in range(n)]
    sender.shutdown()
    await receiver.shutdown()


def test_resolve_negative_cache_has_ttl(monkeypatch):
    """A transient getaddrinfo failure must not blacklist a peer for the
    process lifetime (advisor finding r4): after the retry window the
    next lookup re-resolves and succeeds. Lookups run on the resolver
    worker, so the backoff CAP can be short — a recovered name is usable
    again within a minute."""
    import socket as socket_mod
    import time as time_mod

    assert hsnative._RESOLVE_RETRY_MAX_S == 60.0  # advisor finding r5

    transport = hsnative.NativeTransport.__new__(hsnative.NativeTransport)
    transport._resolved = {}
    transport._resolve_retry_at = {}

    calls = {"n": 0}

    def flaky_getaddrinfo(host, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("resolver not up yet")
        return [(socket_mod.AF_INET, socket_mod.SOCK_STREAM, 6, "",
                 ("10.0.0.7", 0))]

    monkeypatch.setattr(socket_mod, "getaddrinfo", flaky_getaddrinfo)

    assert transport._resolve_blocking("node7.example") is None
    # Within the retry window: cached negative, no new lookup.
    assert transport._resolve_blocking("node7.example") is None
    assert calls["n"] == 1

    # Consecutive failures back off exponentially, so a persistently-bad
    # name is not looked up on every send.
    _, next_backoff = transport._resolve_retry_at["node7.example"]
    assert next_backoff == 2 * hsnative._RESOLVE_RETRY_S

    # Past the window: re-resolves and recovers.
    monkeypatch.setattr(
        time_mod, "monotonic",
        lambda base=time_mod.monotonic(): base + hsnative._RESOLVE_RETRY_S + 1,
    )
    assert transport._resolve_blocking("node7.example") == "10.0.0.7"
    assert calls["n"] == 2
    # Positive result cached; failure backoff state reset.
    assert transport._resolve_blocking("node7.example") == "10.0.0.7"
    assert calls["n"] == 2
    assert "node7.example" not in transport._resolve_retry_at


def test_resolver_worker_flushes_parked_sends():
    """A send to a not-yet-resolved hostname must not block the event
    loop on getaddrinfo: it parks behind the worker lookup and is
    flushed once the name resolves."""
    import asyncio as _asyncio

    async def run():
        port = BASE_PORT + 30
        task = _asyncio.create_task(listener(port, expected=b"parked"))
        await _asyncio.sleep(0.05)
        transport = hsnative.NativeTransport.get()
        # "localhost" may already be cached from other tests: use an alias
        # that only the real resolver knows, monkeypatch-free.
        transport._resolved.pop("localhost", None)
        sender = hsnative.NativeSimpleSender()
        sender.send(("localhost", port), b"parked")
        assert await _asyncio.wait_for(task, 10) == b"parked"
        sender.shutdown()

    _asyncio.run(run())


# ---------------------------------------------------------------------------
# Command ring (hs_net_cmds_flush): batched Python->loop command delivery.
# ---------------------------------------------------------------------------


@async_test
async def test_cmd_ring_batches_send_round_and_consumed_commands():
    """Best-effort sends, round advances and dispatch-progress reports
    appended within one event-loop iteration ship as ONE native crossing
    and are serviced in order — frames arrive intact, the pre-stage
    cutoff moves, and nothing is lost."""
    port = BASE_PORT + 60
    handler = _EchoHandler()
    receiver = await hsnative.NativeReceiver.spawn(("127.0.0.1", port), handler)
    await asyncio.sleep(0.05)
    transport = hsnative.NativeTransport.get()
    if not transport._ring_enabled:
        pytest.skip("command ring disabled via HOTSTUFF_CMD_RING=0")
    flushes_before = transport.ring_flushes
    records_before = transport.ring_total_records
    sender = hsnative.NativeSimpleSender()
    n = 64
    for i in range(n):  # all in one loop iteration: one flush for the lot
        sender.send(("127.0.0.1", port), b"r%03d" % i)
    receiver.set_round(7)
    for _ in range(100):
        await asyncio.sleep(0.05)
        if len(handler.received) >= n:
            break
    assert handler.received == [b"r%03d" % i for i in range(n)]
    assert transport.ring_total_records - records_before >= n + 1
    # The whole burst rode far fewer crossings than commands (the send
    # loop above plus set_round is a single-iteration batch; dispatch
    # progress reports append a few more flushes afterwards).
    assert 0 < transport.ring_flushes - flushes_before < n
    await receiver.shutdown()


@async_test
async def test_cmd_ring_broadcast_and_fallback_equivalence():
    """A ring-delivered broadcast behaves exactly like the direct
    hs_net_broadcast call (one frame build, per-peer queues), and
    disabling the ring mid-process falls back to direct calls without
    behavior change."""
    ports = [BASE_PORT + 61, BASE_PORT + 62]
    handlers = [_EchoHandler(), _EchoHandler()]
    receivers = [
        await hsnative.NativeReceiver.spawn(("127.0.0.1", p), h)
        for p, h in zip(ports, handlers)
    ]
    await asyncio.sleep(0.05)
    transport = hsnative.NativeTransport.get()
    sender = hsnative.NativeSimpleSender()
    addresses = [("127.0.0.1", p) for p in ports]
    sender.broadcast(addresses, b"ringed")
    # Ring records flush at the NEXT loop iteration; yield so the ringed
    # broadcast is enqueued before the direct one (cross-path ordering
    # within one iteration is intentionally unspecified — all consensus
    # best-effort traffic rides the same path).
    await asyncio.sleep(0.05)
    ring_was = transport._ring_enabled
    transport._ring_enabled = False
    try:
        sender.broadcast(addresses, b"direct")
    finally:
        transport._ring_enabled = ring_was
    for _ in range(100):
        await asyncio.sleep(0.05)
        if all(len(h.received) >= 2 for h in handlers):
            break
    for h in handlers:
        assert h.received == [b"ringed", b"direct"]
    for r in receivers:
        await r.shutdown()


@async_test
async def test_cmd_ring_vote_filter_and_round_cutoff_apply():
    """Ring-delivered SET_VOTE_FILTER + SET_ROUND program the pre-stage
    exactly like the direct calls: stale votes drop loop-side, admitted
    votes arrive as one aggregated batch."""
    import struct as _struct

    port = BASE_PORT + 63

    class _BatchHandler(MessageHandler):
        def __init__(self):
            self.batches = []
            self.frames = []

        async def dispatch(self, writer, message: bytes) -> None:
            self.frames.append(message)

        async def dispatch_votes(self, frames):
            self.batches.append(list(frames))

    handler = _BatchHandler()
    receiver = await hsnative.NativeReceiver.spawn(
        ("127.0.0.1", port), handler, auto_ack=True
    )
    await asyncio.sleep(0.05)
    author = b"\xaa" * 32
    receiver.configure_vote_prestage([author])  # rides the ring
    receiver.set_round(5)  # rides the ring

    def vote_frame(round_: int) -> bytes:
        return (
            bytes([1]) + b"\x11" * 32 + _struct.pack("<Q", round_)
            + author + b"\x22" * 64
        )

    await asyncio.sleep(0.1)  # let the ring flush + commands service
    sender = hsnative.NativeSimpleSender()
    sender.send(("127.0.0.1", port), vote_frame(4))  # below cutoff: drops
    sender.send(("127.0.0.1", port), vote_frame(6))  # admitted
    for _ in range(100):
        await asyncio.sleep(0.05)
        if handler.batches:
            break
    assert handler.batches and handler.batches[0] == [vote_frame(6)]
    assert handler.frames == []  # nothing leaked down the per-frame path
    stats = transport_stats()
    assert stats["votes_dropped"] >= 1
    await receiver.shutdown()


def transport_stats():
    return hsnative.NativeTransport.get().stats()
