"""Fuzz the wire decoders: byzantine peers control every byte on the wire,
so malformed input must produce exactly SerdeError/MalformedMessage (the
errors the receiver handlers catch) — never any other exception type."""

import random

import pytest

from hotstuff_tpu.consensus import errors as consensus_errors
from hotstuff_tpu.consensus.messages import Block, decode_message, encode_propose
from hotstuff_tpu.mempool import messages as mempool_messages
from hotstuff_tpu.utils.serde import SerdeError

from .common import chain

ALLOWED = (SerdeError, consensus_errors.MalformedMessage)

rng = random.Random(31337)


def test_random_bytes_consensus_decoder():
    for length in [0, 1, 5, 33, 100, 500]:
        for _ in range(300):
            buf = rng.randbytes(length)
            try:
                decode_message(buf)
            except ALLOWED:
                pass  # the only acceptable failure mode


def test_random_bytes_mempool_decoder():
    for length in [0, 1, 5, 33, 100, 500]:
        for _ in range(300):
            buf = rng.randbytes(length)
            try:
                mempool_messages.decode(buf)
            except ALLOWED:
                pass


def test_truncations_and_bitflips_of_valid_messages():
    """Every truncation and single-byte corruption of a real message must
    decode, or fail with exactly the allowed errors."""
    block = chain(3)[2]
    wire = encode_propose(block)
    for cut in range(0, len(wire), 7):
        try:
            decode_message(wire[:cut])
        except ALLOWED:
            pass
    for pos in range(0, len(wire), 11):
        corrupted = bytearray(wire)
        corrupted[pos] ^= 0xFF
        try:
            decode_message(bytes(corrupted))
        except ALLOWED:
            pass


def test_block_deserialize_fuzz():
    data = chain(2)[1].serialize()
    for _ in range(500):
        buf = bytearray(data)
        for _ in range(rng.randrange(1, 6)):
            buf[rng.randrange(len(buf))] = rng.randrange(256)
        try:
            Block.deserialize(bytes(buf))
        except ALLOWED:
            pass


def test_huge_length_prefixes_bounded():
    """Length/count prefixes near MAX_LEN must fail fast, not allocate."""
    from hotstuff_tpu.utils.serde import MAX_LEN, Encoder

    evil = Encoder().u8(0).u32(MAX_LEN + 1).finish()  # batch with 64M+1 txs
    with pytest.raises(SerdeError):
        mempool_messages.decode(evil)
    evil2 = Encoder().u8(0).u32(1).u32(MAX_LEN + 1).finish()  # giant tx
    with pytest.raises(SerdeError):
        mempool_messages.decode(evil2)
