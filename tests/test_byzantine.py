"""Byzantine fault injection — beyond the reference's subtractive crash
faults (SURVEY.md §4: "Byzantine behavior is covered only at the
message-verification unit level" in the reference).

A 4-node committee runs with one seat held by an active byzantine actor
that sprays garbage frames, malformed messages, equivocating votes, and
forged-leader proposals at the honest nodes. The three honest nodes
(2f+1 = 3 of stake 4... quorum 3) must keep committing identical blocks.
"""

import asyncio
import random

from hotstuff_tpu.consensus import Consensus, Parameters
from hotstuff_tpu.consensus.messages import (
    Block,
    QC,
    Vote,
    encode_propose,
    encode_timeout,
    encode_vote,
)
from hotstuff_tpu.consensus.messages import Timeout as TimeoutMsg
from hotstuff_tpu.crypto import Signature, SignatureService, sha512_digest
from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store

from .common import async_test, consensus_committee, keys

BASE = 15800


async def _byzantine_actor(committee, my_index: int, stop: asyncio.Event):
    """The byzantine member: floods honest peers with adversarial traffic."""
    my_pk, my_sk = keys()[my_index]
    sender = SimpleSender()
    rng = random.Random(666)
    peers = [a for pk, a in committee.broadcast_addresses(my_pk)]
    digest_a = sha512_digest(b"equivocation-a")
    digest_b = sha512_digest(b"equivocation-b")
    round_ = 1
    while not stop.is_set():
        # 1. Raw garbage frames.
        sender.broadcast(peers, rng.randbytes(rng.randrange(1, 200)))
        # 2. Equivocating votes: two conflicting votes for the same round.
        va = Vote.new_from_key(digest_a, round_, my_pk, my_sk)
        vb = Vote.new_from_key(digest_b, round_, my_pk, my_sk)
        sender.broadcast(peers, encode_vote(va))
        sender.broadcast(peers, encode_vote(vb))
        # 3. A forged proposal claiming leadership with a garbage QC.
        fake_qc = QC(hash=digest_a, round=round_, votes=[])
        fake = Block.new_from_key(fake_qc, None, my_pk, round_ + 1, [], my_sk)
        sender.broadcast(peers, encode_propose(fake))
        # 4. Timeouts with bogus signatures.
        t = TimeoutMsg(QC.genesis(), round_, my_pk, Signature(b"\x0b" * 64))
        sender.broadcast(peers, encode_timeout(t))
        round_ += 1
        await asyncio.sleep(0.02)
    sender.shutdown()


async def _honest_committee(base_port: int, byzantine_index: int, params: Parameters):
    committee = consensus_committee(base_port)
    engines, commits, sinks = [], [], []
    for i, (pk, sk) in enumerate(keys()):
        if i == byzantine_index:
            continue
        rx_mempool: asyncio.Queue = asyncio.Queue()
        tx_mempool: asyncio.Queue = asyncio.Queue()
        tx_commit: asyncio.Queue = asyncio.Queue()

        async def drain(q=tx_mempool):
            while True:
                await q.get()

        sinks.append(asyncio.create_task(drain()))
        engines.append(
            await Consensus.spawn(
                pk,
                committee,
                params,
                SignatureService(sk),
                Store(),
                rx_mempool,
                tx_mempool,
                tx_commit,
            )
        )
        commits.append(tx_commit)
    return committee, engines, commits, sinks


async def _run_byzantine_case(base_port: int, params: Parameters):
    byzantine_index = 3
    committee, engines, commits, sinks = await _honest_committee(
        base_port, byzantine_index, params
    )
    stop = asyncio.Event()
    attacker = asyncio.create_task(_byzantine_actor(committee, byzantine_index, stop))

    # Under active attack, all honest nodes must agree on a prefix of
    # committed blocks.
    seen = []
    for _ in range(4):
        blocks = await asyncio.wait_for(
            asyncio.gather(*[q.get() for q in commits]), 60
        )
        assert len({b.digest() for b in blocks}) == 1, "honest nodes diverged"
        seen.append(blocks[0])
    rounds = [b.round for b in seen]
    assert rounds == sorted(rounds), "commit order regressed"

    stop.set()
    await attacker
    for e in engines:
        await e.shutdown()
    for s in sinks:
        s.cancel()


@async_test
async def test_honest_nodes_commit_under_byzantine_attack():
    await _run_byzantine_case(BASE, Parameters(timeout_delay=3_000))


@async_test
async def test_honest_nodes_commit_under_attack_with_batched_votes():
    """The batched-vote path faces the same attack: equivocating votes and
    garbage signatures from the byzantine seat must not stall it."""
    await _run_byzantine_case(
        BASE + 20,
        Parameters(timeout_delay=3_000, batch_vote_verification=True),
    )


def test_honest_nodes_commit_under_attack_native_prestage(monkeypatch):
    """Full-stack equivalence of the native vote pre-stage under active
    byzantine attack: the consensus receivers run on the C++ transport
    (votes length-validated, seat-filtered, deduped and batch-delivered
    in C++; egress broadcasts coalesced), with the attack mix including
    equivocating votes and garbage signatures — the exact inputs the
    duplicate-vote ejection path arbitrates. Honest nodes must commit the
    same chain they commit on the asyncio transport."""
    from hotstuff_tpu.network import native as hsnative
    import pytest as _pytest

    if not hsnative.available():
        _pytest.skip("native transport toolchain unavailable")

    import hotstuff_tpu.consensus.consensus as consensus_mod
    import hotstuff_tpu.consensus.core as core_mod

    monkeypatch.setattr(consensus_mod, "Receiver", hsnative.NativeReceiver)
    monkeypatch.setattr(core_mod, "SimpleSender", hsnative.NativeSimpleSender)

    async def run():
        await _run_byzantine_case(
            BASE + 40,
            Parameters(timeout_delay=3_000, batch_vote_verification=True),
        )

    asyncio.run(asyncio.wait_for(run(), timeout=60))
