"""Faultline: scenario policy determinism, FaultPlane link semantics,
checker verdicts, and end-to-end seeded chaos runs on a live 4-node
committee (crash + partition + heal; a two-minority-group split — the
CI fault-matrix surface)."""

import asyncio
import time

import pytest

from hotstuff_tpu.faultline import (
    CommitRecord,
    FaultPlane,
    Scenario,
    chaos_scenario,
    check,
    hooks,
)
from hotstuff_tpu.faultline import runtime as fl_runtime

from .common import async_test

BASE = 25200

NODES = ["n000", "n001", "n002", "n003"]
ADDRS = {("127.0.0.1", 40000 + i): NODES[i] for i in range(4)}
ADDR = {name: addr for addr, name in ADDRS.items()}


# ---------------------------------------------------------------------------
# policy: seed determinism + serialization
# ---------------------------------------------------------------------------


def test_same_seed_identical_schedule():
    a = chaos_scenario(1234, duration_s=20).compile(NODES)
    b = chaos_scenario(1234, duration_s=20).compile(NODES)
    assert a.trace() == b.trace()


def test_different_seed_different_schedule():
    a = chaos_scenario(1234, duration_s=20).compile(NODES)
    b = chaos_scenario(1235, duration_s=20).compile(NODES)
    assert a.trace() != b.trace()


def test_scenario_json_roundtrip_preserves_schedule():
    s = chaos_scenario(7, duration_s=12)
    restored = Scenario.from_json(s.to_json())
    assert restored.compile(NODES).trace() == s.compile(NODES).trace()


def test_chaos_crash_and_restart_pair_same_node():
    for seed in range(20):
        schedule = chaos_scenario(seed, duration_s=20).compile(NODES)
        crashes = [e for e in schedule.events if e.kind == "crash"]
        restarts = [e for e in schedule.events if e.kind == "restart"]
        assert {e.params["node"] for e in crashes} == {
            e.params["node"] for e in restarts
        }
        assert not schedule.crashed_forever()


def test_heal_time_covers_interval_faults():
    s = Scenario(
        name="t", seed=0, duration_s=10,
        events=[
            {"kind": "partition", "at": 2.0, "until": 6.0},
            {"kind": "crash", "node": 0, "at": 1.0},
            {"kind": "restart", "node": 0, "at": 7.5},
        ],
    )
    schedule = s.compile(NODES)
    assert schedule.last_heal_time() == 7.5
    assert schedule.crashed_forever() == set()


# ---------------------------------------------------------------------------
# runtime: link filter semantics
# ---------------------------------------------------------------------------


def _armed_plane(events, elapsed: float = 100.0) -> FaultPlane:
    """A plane whose virtual clock already sits ``elapsed`` seconds in —
    every event with at <= elapsed is active."""
    schedule = Scenario(
        name="unit", seed=9, duration_s=1e6, events=events
    ).compile(NODES)
    plane = FaultPlane(schedule, ADDRS)
    plane.start(time.monotonic() - elapsed)
    return plane


def _as(node: str):
    return hooks.NODE.set(node)


def test_partition_drops_cross_group_only():
    plane = _armed_plane(
        [{"kind": "partition", "groups": [[0, 1], [2, 3]], "at": 0.0}]
    )
    token = _as("n000")
    try:
        assert plane.filter_send(ADDR["n002"], b"\x01x") == ("drop", 0.0, 0)
        assert plane.filter_send(ADDR["n001"], b"\x01x") is None
    finally:
        hooks.NODE.reset(token)
    assert plane.counts["send_drops"] == 1


def test_unknown_sender_and_peer_unaffected():
    plane = _armed_plane(
        [{"kind": "partition", "groups": [[0, 1], [2, 3]], "at": 0.0}]
    )
    # No node identity (e.g. a benchmark client): never filtered.
    assert plane.filter_send(ADDR["n002"], b"x") is None
    token = _as("n000")
    try:  # an address outside the committee map: never filtered
        assert plane.filter_send(("127.0.0.1", 55555), b"x") is None
    finally:
        hooks.NODE.reset(token)


def test_silent_leader_suppresses_only_proposals():
    plane = _armed_plane(
        [{"kind": "byzantine", "node": 0, "behavior": "silent_leader", "at": 0.0}]
    )
    token = _as("n000")
    try:
        # TAG_PROPOSE = 0 is the first payload byte of proposal frames.
        assert plane.filter_send(ADDR["n001"], b"\x00rest") == ("drop", 0.0, 0)
        assert plane.filter_send(ADDR["n001"], b"\x01vote") is None
        # Framed variant (length prefix skipped via payload_off).
        assert plane.filter_send(
            ADDR["n001"], b"\x00\x00\x00\x04\x00abc", payload_off=4
        ) == ("drop", 0.0, 0)
    finally:
        hooks.NODE.reset(token)
    token = _as("n001")  # other nodes' proposals flow
    try:
        assert plane.filter_send(ADDR["n002"], b"\x00rest") is None
    finally:
        hooks.NODE.reset(token)
    assert plane.counts["proposals_suppressed"] == 2


def test_link_drop_decisions_replay_with_seed():
    events = [{"kind": "link", "src": 0, "dst": "*", "at": 0.0, "drop": 0.5}]

    def decisions():
        plane = _armed_plane(events)
        token = _as("n000")
        try:
            return [
                plane.filter_send(ADDR["n002"], b"\x01x") is None
                for _ in range(200)
            ]
        finally:
            hooks.NODE.reset(token)

    first, second = decisions(), decisions()
    assert first == second  # same seed => same per-message coin flips
    assert any(first) and not all(first)  # p=0.5 actually drops and passes


def test_link_delay_and_duplicate():
    plane = _armed_plane(
        [
            {
                "kind": "link", "src": 0, "dst": 2, "at": 0.0,
                "delay_ms": [5, 10], "duplicate": 1.0,
            }
        ]
    )
    token = _as("n000")
    try:
        action, delay, copies = plane.filter_send(ADDR["n002"], b"\x01x")
    finally:
        hooks.NODE.reset(token)
    assert action == "deliver"
    assert 0.005 <= delay <= 0.010
    assert copies == 2
    assert plane.counts["delays"] == 1 and plane.counts["duplicates"] == 1


def test_recv_side_rule_applies_at_receiver():
    plane = _armed_plane(
        [
            {
                "kind": "link", "src": "*", "dst": 2, "at": 0.0,
                "drop": 1.0, "side": "recv",
            }
        ]
    )
    assert plane.filter_recv(ADDR["n002"]) == ("drop", 0.0)
    assert plane.filter_recv(ADDR["n001"]) is None
    token = _as("n000")  # send side ignores recv rules
    try:
        assert plane.filter_send(ADDR["n002"], b"\x01x") is None
    finally:
        hooks.NODE.reset(token)


def test_heal_restores_clean_links():
    plane = _armed_plane(
        [{"kind": "partition", "groups": [[0, 1], [2, 3]], "at": 0.0,
          "until": 50.0}],
        elapsed=60.0,  # past the heal
    )
    token = _as("n000")
    try:
        assert plane.filter_send(ADDR["n002"], b"\x01x") is None
    finally:
        hooks.NODE.reset(token)
    phases = [(a["kind"], a["phase"]) for a in plane.applied]
    assert phases == [("partition", "inject"), ("partition", "heal")]


def test_injected_event_log_replays_identically():
    """Satellite: the injected-fault EVENT LOG (``FaultPlane.applied``:
    what fired, in which phase, against whom, at which scheduled time)
    is byte-identical across two runs of the same seed. Both transport
    planes consume this one plane object, so log determinism here is
    plane determinism everywhere the schedule is concerned; the
    per-frame coin-flip replays are covered per plane by
    ``test_link_drop_decisions_replay_with_seed`` (asyncio) and
    ``test_native_fault_drop_pattern_replays_with_seed`` (native)."""
    import json

    scenario = chaos_scenario(77, duration_s=20, crashes=2, partitions=2,
                              byzantine=1, links=2)

    def one_run():
        plane = FaultPlane(scenario.compile(NODES), ADDRS)
        plane.start(time.monotonic() - 1e6)  # whole schedule elapsed
        actions = plane.poll_actions()
        return json.dumps(plane.applied, sort_keys=True), actions

    (log_a, actions_a), (log_b, actions_b) = one_run(), one_run()
    assert log_a == log_b
    assert actions_a == actions_b
    assert json.loads(log_a)  # the storm is not empty


def test_supervised_actions_surface_in_order():
    plane = _armed_plane(
        [
            {"kind": "crash", "node": 1, "at": 1.0},
            {"kind": "restart", "node": 1, "at": 2.0},
            {"kind": "byzantine", "node": 2, "behavior": "stale_vote_flood",
             "at": 3.0, "until": 4.0},
        ]
    )
    actions = plane.poll_actions()
    assert [a["action"] for a in actions] == [
        "crash", "restart", "byzantine_on", "byzantine_off"
    ]


# ---------------------------------------------------------------------------
# checker
# ---------------------------------------------------------------------------


def _schedule(events=None, duration=10.0):
    return Scenario(
        name="chk", seed=3, duration_s=duration, events=events or []
    ).compile(NODES)


def test_checker_flags_conflicting_commits():
    schedule = _schedule()
    commits = {
        "n000": [CommitRecord(5, b"a" * 32, 1.0)],
        "n001": [CommitRecord(5, b"b" * 32, 1.0)],
    }
    verdict = check(schedule, commits, min_recovery_commits=0)
    assert not verdict["safety"]["ok"]
    assert verdict["safety"]["violations"][0]["type"] == "conflicting_commit"


def test_checker_flags_intra_node_conflict():
    schedule = _schedule()
    commits = {
        "n000": [CommitRecord(5, b"a" * 32, 1.0), CommitRecord(5, b"c" * 32, 2.0)]
    }
    verdict = check(schedule, commits, min_recovery_commits=0)
    assert not verdict["safety"]["ok"]
    assert verdict["safety"]["violations"][0]["type"] == "intra_node_conflict"


def test_checker_tolerates_crash_recovery_replay():
    """Commit progress persists lazily (with the vote state), so a node
    restarted between a commit and its next vote REPLAYS recent commits.
    Identical-digest repeats — in any order — are legitimate
    at-least-once delivery, not a safety violation."""
    schedule = _schedule()
    stream = [
        CommitRecord(4, b"d" * 32, 1.0),
        CommitRecord(5, b"a" * 32, 1.1),
        # crash + restart: rounds 4..5 re-delivered with the same digests
        CommitRecord(4, b"d" * 32, 2.0),
        CommitRecord(5, b"a" * 32, 2.1),
        CommitRecord(6, b"b" * 32, 2.2),
    ]
    commits = {n: list(stream) for n in NODES}
    verdict = check(schedule, commits, min_recovery_commits=0)
    assert verdict["safety"]["ok"], verdict["safety"]


def test_checker_liveness_requires_post_heal_growth():
    schedule = _schedule(
        [{"kind": "partition", "at": 1.0, "until": 5.0}]
    )
    pre = [CommitRecord(r, bytes([r]) * 32, 0.5) for r in range(1, 4)]
    post = [CommitRecord(r, bytes([r]) * 32, 6.0 + r) for r in range(4, 8)]
    commits = {n: pre + post for n in NODES}
    ok = check(schedule, commits, min_recovery_commits=3)
    assert ok["liveness"]["recovered"]
    stalled = {n: list(pre) for n in NODES}
    bad = check(schedule, stalled, min_recovery_commits=3)
    assert not bad["liveness"]["recovered"]
    assert bad["liveness"]["laggards"] == NODES


def test_checker_excludes_byzantine_and_dead_nodes():
    schedule = _schedule(
        [
            {"kind": "crash", "node": 0, "at": 1.0},  # never restarted
            {"kind": "byzantine", "node": 1, "behavior": "equivocate",
             "at": 1.0, "until": 2.0},
        ]
    )
    good = [CommitRecord(r, bytes([r]) * 32, 3.0 + r) for r in range(1, 6)]
    commits = {"n002": list(good), "n003": list(good)}
    verdict = check(schedule, commits, min_recovery_commits=3)
    assert verdict["safety"]["ok"]
    assert verdict["liveness"]["recovered"]
    assert set(verdict["liveness"]["post_heal_commits"]) == {"n002", "n003"}


# ---------------------------------------------------------------------------
# end to end: seeded crash + partition + heal on a live committee
# ---------------------------------------------------------------------------


@async_test(timeout=150)
async def test_chaos_smoke_crash_partition_heal():
    """The canonical chaos smoke: a 4-node committee survives a
    supervised crash/restart and a 2-2 partition with healing; the
    checker must report safety=ok and liveness=recovered, and the
    injection counters must show the faults actually fired."""
    from hotstuff_tpu.faultline import run_scenario

    scenario = Scenario(
        name="smoke-4", seed=20260804, duration_s=6.0,
        events=[
            {"kind": "crash", "node": 1, "at": 0.5},
            {"kind": "restart", "node": 1, "at": 2.0},
            {"kind": "partition", "at": 3.0, "until": 4.5},
        ],
    )
    result = await run_scenario(
        scenario, 4, base_port=BASE, timeout_delay=500,
        recovery_timeout_s=60.0,
    )
    verdict = result["verdict"]
    assert verdict["safety"]["ok"], verdict["safety"]
    assert verdict["liveness"]["recovered"], verdict["liveness"]
    counts = verdict["injections"]["counts"]
    assert counts["events_applied"] == 4
    assert counts["send_drops"] > 0  # the partition really cut links
    # Replay contract: recompiling the same scenario yields the identical
    # fault schedule byte for byte.
    assert result["trace"] == scenario.compile(
        [f"n{i:03d}" for i in range(4)]
    ).trace()
    # The plane uninstalled cleanly (no leakage into later tests).
    assert hooks.plane is None
    assert fl_runtime.uninstall() is None


@async_test(timeout=150)
async def test_minority_partition_halts_then_recovers():
    """Satellite: cut the committee into TWO MINORITY groups (2+2 of 4 —
    neither side holds 2f+1 = 3), at a fixed seed. Safety demands the
    commit stream goes silent for the partition's duration (no quorum
    anywhere ⇒ no QC ⇒ no commit); liveness demands commit progress
    resumes within k timeout periods of the heal."""
    from hotstuff_tpu.faultline import run_scenario

    cut_at, heal_at = 2.0, 4.0
    scenario = Scenario(
        name="minority-split", seed=424242, duration_s=5.0,
        events=[
            {"kind": "partition", "groups": [[0, 1], [2, 3]],
             "at": cut_at, "until": heal_at},
        ],
    )
    timeout_delay_ms = 500
    result = await run_scenario(
        scenario, 4, base_port=BASE + 80, timeout_delay=timeout_delay_ms,
        recovery_timeout_s=60.0,
    )
    verdict = result["verdict"]
    assert verdict["safety"]["ok"], verdict["safety"]
    # No commits during the cut: allow a 1 s drain for blocks already
    # QC'd in flight when the partition lands, then demand silence. A
    # healthy committee here commits many times per second, so a quorum
    # that somehow survived the cut would certainly show up.
    silent_from = cut_at + 1.0
    during = [
        (name, round_, t)
        for name, recs in result["commit_streams"].items()
        for round_, t in recs
        if silent_from < t < heal_at
    ]
    assert during == [], f"commits flowed inside a minority-only split: {during}"
    # Progress DID happen before the cut and resumed after the heal.
    for name, recs in result["commit_streams"].items():
        assert any(t < cut_at for _, t in recs), f"{name} never committed pre-cut"
    assert verdict["liveness"]["recovered"], verdict["liveness"]
    # Recovery within k timeout periods of the heal (k = 40 is generous
    # for a loaded CI box; the regression this guards was a TOTAL stall).
    k = 40
    recovery_s = verdict["liveness"]["recovery_s"]
    assert recovery_s is not None
    assert recovery_s <= k * (timeout_delay_ms / 1e3), verdict["liveness"]
    # The partition really cut links both ways.
    assert verdict["injections"]["counts"]["send_drops"] > 0


@pytest.mark.slow
@async_test(timeout=240)
async def test_chaos_byzantine_storm_n8():
    """Heavier seeded storm: 8 nodes, crash + partition + byzantine
    actor + lossy links, all drawn from one seed. Safety must hold under
    active adversarial traffic and liveness must recover post-heal."""
    from hotstuff_tpu.faultline import run_scenario

    scenario = chaos_scenario(
        991, duration_s=10.0, crashes=1, partitions=1, byzantine=1, links=1
    )
    result = await run_scenario(
        scenario, 8, base_port=BASE + 40, timeout_delay=1_000,
        recovery_timeout_s=90.0,
    )
    verdict = result["verdict"]
    assert verdict["safety"]["ok"], verdict["safety"]
    assert verdict["liveness"]["recovered"], verdict["liveness"]
    assert verdict["injections"]["counts"]["events_applied"] >= 6
