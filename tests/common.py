"""Shared test fixtures, modeled on the reference's per-crate
``src/tests/common.rs`` (deterministic keys from a seeded RNG, localhost
committees with per-test base ports, one-shot ACKing listener doubles —
reference ``consensus/src/tests/common.rs:17-46,182-198``)."""

from __future__ import annotations

import asyncio
import functools
import random
import struct

from hotstuff_tpu.crypto import PublicKey, SecretKey, generate_keypair


def async_test(fn=None, *, timeout: float = 60):
    """Run an ``async def`` test on a fresh event loop (no pytest-asyncio in
    this environment). Use ``@async_test(timeout=N)`` for long scenarios —
    inner wait_for budgets must fit under this outer cap."""

    def decorate(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return asyncio.run(asyncio.wait_for(f(*args, **kwargs), timeout=timeout))

        return wrapper

    return decorate(fn) if fn is not None else decorate


async def next_payload_commit(node):
    """Drain a node's commit stream until a block carrying payload arrives."""
    while True:
        block = await node.commit.get()
        if block.payload:
            return block


def keys(n: int = 4) -> list[tuple[PublicKey, SecretKey]]:
    """n deterministic keypairs (seeded RNG, like StdRng::from_seed([0;32]))."""
    rng = random.Random(0)
    return [generate_keypair(seed=rng.randbytes(32))[0:2] for _ in range(n)]


def mempool_committee(base_port: int, n: int = 4):
    """4-node localhost mempool committee with a per-test base port
    (reference ``mempool/src/tests/common.rs``)."""
    from hotstuff_tpu.mempool import Authority, Committee

    return Committee(
        authorities={
            pk: Authority(
                stake=1,
                transactions_address=("127.0.0.1", base_port + i),
                mempool_address=("127.0.0.1", base_port + 100 + i),
            )
            for i, (pk, _) in enumerate(keys(n))
        }
    )


def consensus_committee(base_port: int, n: int = 4):
    """4-node localhost consensus committee (reference
    ``consensus/src/tests/common.rs:23-46``)."""
    from hotstuff_tpu.consensus import Authority, Committee

    return Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", base_port + i))
            for i, (pk, _) in enumerate(keys(n))
        }
    )


def chain(n_blocks: int, key_list=None):
    """A valid block chain rooted at genesis: block r is authored by the
    round-r leader and carries a full QC over its parent (reference
    ``consensus/src/tests/common.rs:147-179``)."""
    from hotstuff_tpu.consensus.messages import QC, Block
    from hotstuff_tpu.crypto import Signature

    key_list = key_list or keys()
    by_pk = dict(key_list)
    sorted_pks = sorted(by_pk.keys())

    def leader(r):
        return sorted_pks[r % len(sorted_pks)]

    blocks = []
    qc = QC.genesis()
    for r in range(1, n_blocks + 1):
        author = leader(r)
        block = Block.new_from_key(qc, None, author, r, [], by_pk[author])
        blocks.append(block)
        qc = QC(hash=block.digest(), round=r, votes=[])
        qc.votes = [(pk, Signature.new(qc.digest(), sk)) for pk, sk in key_list]
    return blocks


def qc_vote_digest(block_digest, round_: int):
    """The digest each QC vote signs (== QC.digest() of the certified
    block)."""
    from hotstuff_tpu.consensus.messages import QC

    return QC(hash=block_digest, round=round_, votes=[]).digest()


async def listener(port: int, expected: bytes | None = None, reply: bytes = b"Ack"):
    """One-shot TCP server: accept, read one length-delimited frame, reply
    ``Ack``, optionally assert the payload. Returns the received frame.

    The key network test double (reference ``consensus/src/tests/common.rs:182-198``).
    """
    received: asyncio.Future[bytes] = asyncio.get_running_loop().create_future()

    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            hdr = await reader.readexactly(4)
            (n,) = struct.unpack(">I", hdr)
            payload = await reader.readexactly(n)
            writer.write(struct.pack(">I", len(reply)) + reply)
            await writer.drain()
            if not received.done():
                received.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionError):
            if not received.done():
                received.set_exception(ConnectionError("listener connection died"))
        finally:
            # One-shot: close our side so Server.wait_closed() (which waits
            # for client transports on Python 3.12) cannot hang on senders
            # that keep their connection open.
            writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", port)
    try:
        payload = await asyncio.wait_for(received, timeout=10)
    finally:
        server.close()
        await server.wait_closed()
    if expected is not None:
        assert payload == expected, f"listener got unexpected payload"
    return payload
