"""Shared test fixtures, modeled on the reference's per-crate
``src/tests/common.rs`` (deterministic keys from a seeded RNG, localhost
committees with per-test base ports, one-shot ACKing listener doubles —
reference ``consensus/src/tests/common.rs:17-46,182-198``)."""

from __future__ import annotations

import asyncio
import functools
import random
import struct

from hotstuff_tpu.crypto import PublicKey, SecretKey, generate_keypair


def async_test(fn):
    """Run an ``async def`` test on a fresh event loop (no pytest-asyncio in
    this environment)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=60))

    return wrapper


def keys(n: int = 4) -> list[tuple[PublicKey, SecretKey]]:
    """n deterministic keypairs (seeded RNG, like StdRng::from_seed([0;32]))."""
    rng = random.Random(0)
    return [generate_keypair(seed=rng.randbytes(32))[0:2] for _ in range(n)]


async def listener(port: int, expected: bytes | None = None, reply: bytes = b"Ack"):
    """One-shot TCP server: accept, read one length-delimited frame, reply
    ``Ack``, optionally assert the payload. Returns the received frame.

    The key network test double (reference ``consensus/src/tests/common.rs:182-198``).
    """
    received: asyncio.Future[bytes] = asyncio.get_running_loop().create_future()

    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            hdr = await reader.readexactly(4)
            (n,) = struct.unpack(">I", hdr)
            payload = await reader.readexactly(n)
            writer.write(struct.pack(">I", len(reply)) + reply)
            await writer.drain()
            if not received.done():
                received.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionError):
            if not received.done():
                received.set_exception(ConnectionError("listener connection died"))
        finally:
            # One-shot: close our side so Server.wait_closed() (which waits
            # for client transports on Python 3.12) cannot hang on senders
            # that keep their connection open.
            writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", port)
    try:
        payload = await asyncio.wait_for(received, timeout=10)
    finally:
        server.close()
        await server.wait_closed()
    if expected is not None:
        assert payload == expected, f"listener got unexpected payload"
    return payload
