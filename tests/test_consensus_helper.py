"""Consensus Helper: serves SyncRequests with full Propose replies
(reference ``consensus/src/tests/helper_tests.rs``)."""

import asyncio

from hotstuff_tpu.consensus.helper import Helper
from hotstuff_tpu.consensus.messages import decode_message
from hotstuff_tpu.store import Store

from .common import async_test, chain, consensus_committee, keys, listener

BASE = 15500


@async_test
async def test_helper_serves_stored_block():
    committee = consensus_committee(BASE)
    store = Store()
    block = chain(1)[0]
    await store.write(block.digest().data, block.serialize())

    rx: asyncio.Queue = asyncio.Queue()
    Helper.spawn(committee, store, rx)

    requestor = keys()[1][0]
    task = asyncio.create_task(listener(committee.address(requestor)[1]))
    await asyncio.sleep(0.05)
    await rx.put((block.digest(), requestor))
    frame = await asyncio.wait_for(task, 5)
    kind, replied = decode_message(frame)
    assert kind == "propose"
    assert replied.digest() == block.digest()


@async_test
async def test_helper_survives_corrupt_stored_block():
    """A corrupt stored block must not kill the helper task: later requests
    for healthy blocks are still served."""
    committee = consensus_committee(BASE + 20)
    store = Store()
    block = chain(1)[0]
    await store.write(block.digest().data, block.serialize())
    from hotstuff_tpu.crypto import sha512_digest

    corrupt = sha512_digest(b"corrupt")
    await store.write(corrupt.data, b"\xff garbage not a block")

    rx: asyncio.Queue = asyncio.Queue()
    helper_task = Helper.spawn(committee, store, rx)
    requestor = keys()[1][0]
    await rx.put((corrupt, requestor))  # deserialization fails
    await asyncio.sleep(0.1)
    assert not helper_task.done(), "helper died on a corrupt stored block"

    task = asyncio.create_task(listener(committee.address(requestor)[1]))
    await asyncio.sleep(0.05)
    await rx.put((block.digest(), requestor))
    frame = await asyncio.wait_for(task, 5)
    kind, replied = decode_message(frame)
    assert kind == "propose" and replied.digest() == block.digest()


@async_test
async def test_helper_ignores_unknown_digest_and_stranger():
    from hotstuff_tpu.crypto import generate_keypair, sha512_digest

    committee = consensus_committee(BASE + 10)
    store = Store()
    rx: asyncio.Queue = asyncio.Queue()
    Helper.spawn(committee, store, rx)
    stranger, _ = generate_keypair(seed=b"\x55" * 32)
    await rx.put((sha512_digest(b"unknown"), stranger))  # unknown requestor
    await rx.put((sha512_digest(b"unknown"), keys()[1][0]))  # unknown block
    await asyncio.sleep(0.2)  # nothing to assert beyond "no crash/no send"


@async_test
async def test_helper_rate_limits_snapshot_replies_per_origin():
    """Regression: the request's origin field is unsigned and spoofable,
    and a snapshot reply is heavy (two blocks + a 2f+1-signature QC) —
    spraying unknown digests with a victim's origin must not have the
    helper amplify traffic at the victim. At most one snapshot reply per
    origin per half retry window, checked BEFORE the meta read."""
    from hotstuff_tpu.consensus.statesync import SNAPSHOT_KEY, encode_snapshot

    committee = consensus_committee(BASE + 30)
    blocks = chain(4)
    snapshot = encode_snapshot(blocks[1], blocks[2], blocks[3].qc)

    class _CountingStore(Store):
        def __init__(self):
            super().__init__()
            self.meta_reads = 0

        async def read_meta(self, key):
            self.meta_reads += 1
            return await super().read_meta(key)

    store = _CountingStore()
    await store.write_meta(SNAPSHOT_KEY, snapshot)
    rx: asyncio.Queue = asyncio.Queue()
    # sync_retry_delay=10s -> 5s window: the burst below fits inside it.
    Helper.spawn(committee, store, rx, sync_retry_delay=10_000)
    from hotstuff_tpu.crypto import sha512_digest

    victim = keys()[1][0]
    for i in range(5):
        await rx.put((sha512_digest(b"unknown%d" % i), victim))
    await asyncio.sleep(0.2)
    assert store.meta_reads == 1  # one snapshot reply, 4 requests shed
    # A different origin is NOT throttled by the victim's bucket.
    other = keys()[2][0]
    await rx.put((sha512_digest(b"unknown-other"), other))
    await asyncio.sleep(0.1)
    assert store.meta_reads == 2
