"""StreamFollower (tail-follow) tests: incremental growth, partial final
lines, rotation-by-truncation, stop semantics, and the stream
self-description (meta record + validate CLI) it feeds on."""

from __future__ import annotations

import json
import threading
import time

import pytest

from benchmark.logs import StreamFollower, read_stream_records
from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry import (
    META_SCHEMA,
    TelemetryEmitter,
    build_meta_record,
    validate_meta_record,
)
from hotstuff_tpu.telemetry.registry import Registry
from hotstuff_tpu.telemetry.validate import validate_stream


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


def _snap_line(seq=0, node="n", counters=None):
    from hotstuff_tpu.telemetry import build_snapshot

    r = Registry()
    for name, v in (counters or {}).items():
        r.counter(name).inc(v)
    snap = build_snapshot(r, node=node, seq=seq)
    return json.dumps(snap)


# -- incremental growth ------------------------------------------------------


def test_follower_yields_records_as_file_grows(tmp_path):
    path = tmp_path / "telemetry-x.jsonl"
    follower = StreamFollower(str(path), poll_s=0.01)
    assert follower.drain() == []  # not created yet: no error, no data

    path.write_text(_snap_line(0) + "\n")
    got = follower.drain()
    assert [g["seq"] for g in got] == [0]

    with open(path, "a") as f:
        f.write(_snap_line(1) + "\n" + _snap_line(2) + "\n")
    got = follower.drain()
    assert [g["seq"] for g in got] == [1, 2]
    assert follower.drain() == []  # no growth, no records
    assert follower.records_read == 3


def test_follower_buffers_partial_final_line(tmp_path):
    """A record is only parsed once its newline lands — the writer may
    be mid-append at any poll."""
    path = tmp_path / "telemetry-x.jsonl"
    line = _snap_line(0)
    cut = len(line) // 2
    path.write_text(line[:cut])
    follower = StreamFollower(str(path))
    assert follower.drain() == []  # incomplete: buffered, not parsed
    assert follower.skipped == 0
    with open(path, "a") as f:
        f.write(line[cut:] + "\n")
    got = follower.drain()
    assert len(got) == 1 and got[0]["seq"] == 0


def test_follower_handles_rotation_by_truncation(tmp_path):
    path = tmp_path / "telemetry-x.jsonl"
    path.write_text(_snap_line(0) + "\n" + _snap_line(1) + "\n")
    follower = StreamFollower(str(path))
    assert len(follower.drain()) == 2
    # The writer starts the file over (log rotation by truncation).
    path.write_text(_snap_line(0, node="fresh") + "\n")
    got = follower.drain()
    assert len(got) == 1 and got[0]["node"] == "fresh"
    assert follower.truncations == 1


def test_follower_skips_malformed_and_unknown_lines(tmp_path):
    path = tmp_path / "telemetry-x.jsonl"
    path.write_text(
        "not json\n"
        + json.dumps({"schema": "hotstuff-future-v9"}) + "\n"
        + _snap_line(0) + "\n"
    )
    follower = StreamFollower(str(path))
    got = follower.drain()
    assert len(got) == 1 and got[0]["seq"] == 0
    assert follower.skipped == 2


def test_follower_iter_stops_with_final_drain(tmp_path):
    """stop() finishes the iteration AFTER one last drain, so records
    appended between the last poll and the stop signal are not lost."""
    path = tmp_path / "telemetry-x.jsonl"
    path.write_text(_snap_line(0) + "\n")
    follower = StreamFollower(str(path), poll_s=0.01)
    got: list[dict] = []

    def consume():
        got.extend(follower)

    t = threading.Thread(target=consume)
    t.start()
    deadline = time.time() + 5.0
    while not got and time.time() < deadline:
        time.sleep(0.01)
    with open(path, "a") as f:
        f.write(_snap_line(1) + "\n")
    follower.stop()
    t.join(5.0)
    assert not t.is_alive()
    assert [g["seq"] for g in got] == [0, 1]


def test_follower_stop_when_predicate(tmp_path):
    path = tmp_path / "telemetry-x.jsonl"
    path.write_text(_snap_line(0) + "\n")
    follower = StreamFollower(
        str(path), poll_s=0.01, stop_when=lambda: True
    )
    assert [g["seq"] for g in follower] == [0]


# -- stream self-description -------------------------------------------------


def test_emitter_writes_meta_record_first(tmp_path):
    path = tmp_path / "telemetry-x.jsonl"
    r = Registry()
    emitter = TelemetryEmitter(r, str(path), node="x", interval_s=1.0)
    emitter.emit()
    emitter.emit(final=True)
    with open(path) as f:
        first = json.loads(f.readline())
    assert first["schema"] == META_SCHEMA
    assert validate_meta_record(first) == []
    assert first["node"] == "x"
    assert "hotstuff-telemetry-v1" in first["schemas"]
    records = read_stream_records(str(path))
    assert len(records.meta) == 1
    assert len(records.snapshots) == 2
    assert records.skipped == 0  # meta is a known schema, not "skipped"


def test_validate_meta_record_rejects_malformed():
    good = build_meta_record(node="n", interval_s=1.0)
    assert validate_meta_record(good) == []
    assert validate_meta_record([]) != []
    assert validate_meta_record(dict(good, schema="other")) != []
    bad = dict(good)
    bad.pop("anchor")
    assert any("anchor" in p for p in validate_meta_record(bad))


def test_validate_cli_counts_and_flags_problems(tmp_path):
    path = tmp_path / "telemetry-x.jsonl"
    r = Registry()
    telemetry.enable()
    emitter = TelemetryEmitter(
        r, str(path), node="x", trace=telemetry.trace_buffer()
    )
    telemetry.trace_event("n0", 1, "propose")
    emitter.emit(final=True)
    report = validate_stream(str(path))
    assert report["ok"] is True
    assert report["counts"][META_SCHEMA] == 1
    assert report["counts"]["hotstuff-telemetry-v1"] == 1
    assert report["counts"]["hotstuff-trace-v1"] == 1
    assert report["self_described"] is True

    # A truncated tail is reported, not fatal; mid-file garbage is.
    with open(path, "a") as f:
        f.write('{"schema": "hotstuff-telemetry-v1", "trunca')
    report = validate_stream(str(path))
    assert report["ok"] is True and report["truncated_tail"] is True

    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        json.dumps({"schema": "hotstuff-telemetry-v1", "node": 3}) + "\n"
    )
    report = validate_stream(str(bad))
    assert report["ok"] is False
    assert report["problems"][0]["line"] == 1


def test_validate_cli_main_exit_codes(tmp_path, capsys):
    from hotstuff_tpu.telemetry.validate import main

    good = tmp_path / "good.jsonl"
    good.write_text(_snap_line(0) + "\n")
    assert main([str(good)]) == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text("garbage\n" + _snap_line(0) + "\n")
    assert main([str(bad), "--json"]) == 1
    out = capsys.readouterr().out
    assert "bad JSON" in out
