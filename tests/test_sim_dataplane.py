"""Simulant model check of the Conveyor availability invariant (the
sim-first gate for the data plane): certified ordering keeps every
committed digest resolvable at f+1 honest nodes across seeded fault
schedules, and the naive (no-cert) ordering rule is CAUGHT violating it
— proof the checker can find the bug class it exists for."""

from hotstuff_tpu.faultline.policy import Scenario, chaos_scenario
from hotstuff_tpu.sim.dataplane import DataPlaneSim, run_dataplane_sim


def _with_withholding(scenario: Scenario) -> Scenario:
    """Layer a batch-withholding byzantine node onto a seeded storm."""
    events = list(scenario.events) + [
        {
            "kind": "byzantine",
            "node": "?",
            "behavior": "batch_withhold",
            "at": 0.2 * scenario.duration_s,
            "until": 0.8 * scenario.duration_s,
        }
    ]
    return Scenario(
        name=scenario.name + "+withhold",
        seed=scenario.seed,
        duration_s=scenario.duration_s,
        events=events,
    )


def test_certified_ordering_holds_availability_across_seeded_storms():
    """Hundreds of seeded chaos schedules (crash/restart, partitions,
    lossy links, plus an explicit batch-withholding byzantine): with the
    Conveyor rule, zero availability violations."""
    total_committed = 0
    for seed in range(40):
        scenario = _with_withholding(
            chaos_scenario(seed, duration_s=4.0, byzantine=0)
        )
        result = run_dataplane_sim(scenario, 4, workers=2)
        v = result["verdict"]
        assert v["ok"], (seed, v["violations"][:3])
        total_committed += result["committed"]
    assert total_committed > 500  # the sweep actually ordered real work


def test_naive_ordering_is_caught_by_the_checker():
    """Order-on-send (no availability proof) + a partitioned author that
    crashes forever => committed digests held by nobody reachable. The
    checker MUST find these — otherwise the invariant gate is theater."""
    scenario = Scenario(
        name="naive-violation",
        seed=7,
        duration_s=2.0,
        events=[
            # Author n000 cut off from everyone from the start...
            {
                "kind": "partition",
                "groups": [["n000"], ["n001", "n002", "n003"]],
                "at": 0.0,
            },
            # ...seals and (naively) orders in isolation, then dies.
            {"kind": "crash", "node": "n000", "at": 1.5},
        ],
    )
    result = run_dataplane_sim(scenario, 4, require_certs=False)
    v = result["verdict"]
    assert not v["ok"]
    assert any(
        viol["type"] == "unresolvable_commit" for viol in v["violations"]
    )


def test_certified_ordering_survives_the_naive_counterexample():
    """The exact schedule that breaks order-on-send is harmless under
    certified ordering: the isolated author never reaches 2f+1 acks, so
    its batches are never ordered at all."""
    scenario = Scenario(
        name="cert-survives",
        seed=7,
        duration_s=2.0,
        events=[
            {
                "kind": "partition",
                "groups": [["n000"], ["n001", "n002", "n003"]],
                "at": 0.0,
            },
            {"kind": "crash", "node": "n000", "at": 1.5},
        ],
    )
    result = run_dataplane_sim(scenario, 4, require_certs=True)
    v = result["verdict"]
    assert v["ok"]
    # The majority side kept certifying and ordering throughout; the
    # isolated author's batches never earned a certificate.
    assert result["committed"] > 0
    assert all(not d.startswith("n000/") for d in result["digests"])


def test_dataplane_sim_is_deterministic():
    scenario = _with_withholding(chaos_scenario(11, duration_s=3.0))
    a = DataPlaneSim(scenario, 4, workers=2).run()
    b = DataPlaneSim(scenario, 4, workers=2).run()
    assert a["trace"] == b["trace"]
    assert a["committed"] == b["committed"]
    assert a["events"] == b["events"]
    assert a["verdict"] == b["verdict"]
