"""Fused aggregate-certificate verification: one RLC MSM per cert.

Covers the whole chain the wire-v2 raw path rides: deterministic RLC
coefficients (``cpu_batch.cert_rlc_coefficients``), the pure-Python fused
reference (``verify_cert_rlc``), the native engine
(``verify_cert_native`` + the C challenge-hash entry point), backend
dispatch (``backend_verify_cert`` with the ``HOTSTUFF_AGG_QC=0``
kill-switch), super-batch cert-identity dedup and bad-cert isolation
(``BatchingBackend.verify_cert``), the process-wide cert arena, and
end-to-end QC/TC verification through both wire formats — including the
acceptance criterion that a cert with ANY corrupted signature slice is
rejected.
"""

import random
import struct
import threading

import pytest

from hotstuff_tpu.consensus import Authority, Committee, errors
from hotstuff_tpu.consensus import cert_arena
from hotstuff_tpu.consensus.messages import (
    QC,
    TC,
    Block,
    CertificateCache,
    SeatTable,
    decode_message,
    encode_propose,
    encode_tc,
)
from hotstuff_tpu.crypto import (
    CpuBackend,
    CryptoError,
    Signature,
    backend_verify_cert,
    generate_keypair,
    set_backend,
    sha512_digest,
)
from hotstuff_tpu.crypto import ed25519_ref as ref
from hotstuff_tpu.crypto.batching import BatchingBackend
from hotstuff_tpu.crypto.cpu_batch import (
    cert_rlc_coefficients,
    verify_cert_rlc,
)
from hotstuff_tpu.crypto.native_ed25519 import native_available

_U64 = struct.Struct("<Q")

_native = pytest.mark.skipif(
    not native_available(), reason="g++ toolchain unavailable"
)


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Fresh arena + default env + cpu backend around every test."""
    monkeypatch.delenv("HOTSTUFF_AGG_QC", raising=False)
    monkeypatch.delenv("HOTSTUFF_CERT_ARENA", raising=False)
    cert_arena.reset()
    yield
    set_backend("cpu")
    cert_arena.reset()


# ---------------------------------------------------------------------------
# Raw packed-cert fixtures (no consensus objects)
# ---------------------------------------------------------------------------


def _packed_cert(n, rng, stride=64, shared=True):
    """A valid packed cert: n keys, one sig per record at ``stride``.

    ``shared=True`` mirrors a QC (every seat signs the same statement);
    otherwise per-seat messages bind each record's trailing bytes, like a
    TC's high_qc_round.
    """
    seeds = [rng.randbytes(32) for _ in range(n)]
    pubs = [ref.secret_to_public(s) for s in seeds]
    if shared:
        msg = rng.randbytes(32)
        recs = [
            ref.sign(s, msg) + rng.randbytes(stride - 64) for s in seeds
        ]
        return msg, pubs, b"".join(recs)
    msgs, recs = [], []
    for s in seeds:
        extra = rng.randbytes(stride - 64)
        m = sha512_digest(rng.randbytes(8), extra).data
        msgs.append(m)
        recs.append(ref.sign(s, m) + extra)
    return msgs, pubs, b"".join(recs)


def _corrupt(sig_buf, pos):
    b = bytearray(sig_buf)
    b[pos] ^= 0x01
    return bytes(b)


# ---------------------------------------------------------------------------
# Pure-Python fused reference
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("stride,shared", [(64, True), (72, False)])
def test_rlc_reference_accepts_valid_cert(stride, shared):
    rng = random.Random(101)
    for n in (1, 4, 7):
        msgs, pubs, buf = _packed_cert(n, rng, stride=stride, shared=shared)
        assert verify_cert_rlc(msgs, pubs, buf, stride=stride)


@pytest.mark.parametrize("stride,shared", [(64, True), (72, False)])
def test_rlc_reference_rejects_any_corrupted_slice(stride, shared):
    """Acceptance criterion: corrupting any single signature slice of the
    packed buffer — property over every seat — must fail the fused check."""
    rng = random.Random(102)
    n = 5
    msgs, pubs, buf = _packed_cert(n, rng, stride=stride, shared=shared)
    for seat in range(n):
        # One bit anywhere in the seat's 64-byte signature slice.
        pos = seat * stride + rng.randrange(64)
        assert not verify_cert_rlc(msgs, pubs, _corrupt(buf, pos), stride=stride)


def test_rlc_coefficients_deterministic_and_content_bound():
    rng = random.Random(103)
    msg, pubs, buf = _packed_cert(4, rng)
    a = cert_rlc_coefficients(msg, pubs, buf, 64, 4)
    b = cert_rlc_coefficients(msg, pubs, buf, 64, 4)
    assert a == b  # reproducible per verify (Fiat-Shamir derandomized)
    assert all(z >> 127 == 1 for z in a)  # full 128-bit coefficients
    # Any change to the statement re-randomizes the coefficients, so an
    # adversary cannot pick content against known coefficients.
    c = cert_rlc_coefficients(msg, pubs, _corrupt(buf, 0), 64, 4)
    assert a != c
    d = cert_rlc_coefficients(_corrupt(msg, 0), pubs, buf, 64, 4)
    assert a != d


# ---------------------------------------------------------------------------
# Native engine equivalence
# ---------------------------------------------------------------------------


@_native
@pytest.mark.parametrize("stride,shared", [(64, True), (72, False)])
def test_native_fused_matches_pure_reference(stride, shared):
    from hotstuff_tpu.crypto.native_ed25519 import verify_cert_native

    rng = random.Random(104)
    for n in (1, 3, 8):
        msgs, pubs, buf = _packed_cert(n, rng, stride=stride, shared=shared)
        assert verify_cert_native(msgs, pubs, buf, stride=stride)
        pos = rng.randrange(n) * stride + rng.randrange(64)
        bad = _corrupt(buf, pos)
        assert not verify_cert_native(msgs, pubs, bad, stride=stride)
        assert not verify_cert_rlc(msgs, pubs, bad, stride=stride)


@_native
def test_native_challenge_hashing_matches_hashlib():
    """The C challenge-hash entry (one ctypes crossing per cert) computes
    SHA-512(R || A || M) per seat exactly as the Python loop does."""
    import ctypes
    import hashlib

    from hotstuff_tpu.crypto.native_ed25519 import _load

    lib = _load()
    rng = random.Random(105)
    for n, stride in ((1, 64), (5, 64), (3, 72)):
        msg = rng.randbytes(32)
        pubs = rng.randbytes(32 * n)
        sigs = rng.randbytes(stride * n)
        out = ctypes.create_string_buffer(64 * n)
        rc = lib.hs_ed25519_cert_challenges(
            msg, len(msg), pubs, sigs, stride, n, out
        )
        assert rc == 1  # success convention shared by the engine's entries
        for i in range(n):
            want = hashlib.sha512(
                sigs[i * stride : i * stride + 32]
                + pubs[i * 32 : (i + 1) * 32]
                + msg
            ).digest()
            assert out.raw[i * 64 : (i + 1) * 64] == want


# ---------------------------------------------------------------------------
# Backend dispatch + kill-switch
# ---------------------------------------------------------------------------


class RecordingBackend(CpuBackend):
    """Counts fused vs exploded arrivals at the inner backend."""

    def __init__(self):
        super().__init__()
        self.batch_calls = 0
        self.cert_calls = 0

    def verify_batch(self, msgs, pubs, sigs):
        self.batch_calls += 1
        super().verify_batch(msgs, pubs, sigs)

    def verify_cert(self, msgs, pubs, sig_buf, stride=64, key=None):
        self.cert_calls += 1
        super().verify_cert(msgs, pubs, sig_buf, stride, key=key)


class ExplodedOnlyBackend(CpuBackend):
    """A backend with no fused entry point (models pre-aggregate planes)."""

    verify_cert = None

    def __init__(self):
        super().__init__()
        self.batch_calls = 0

    def verify_batch(self, msgs, pubs, sigs):
        self.batch_calls += 1
        super().verify_batch(msgs, pubs, sigs)


def test_backend_dispatch_fused_by_default():
    rng = random.Random(106)
    msg, pubs, buf = _packed_cert(4, rng)
    backend = RecordingBackend()
    set_backend(backend)
    backend_verify_cert(msg, pubs, buf, 64)
    assert backend.cert_calls == 1 and backend.batch_calls == 0
    with pytest.raises(CryptoError):
        backend_verify_cert(msg, pubs, _corrupt(buf, 3), 64)


def test_backend_dispatch_kill_switch_explodes(monkeypatch):
    """HOTSTUFF_AGG_QC=0: certs take the pre-aggregate per-signature batch
    path — same acceptance, no fused entry touched."""
    rng = random.Random(107)
    msg, pubs, buf = _packed_cert(4, rng)
    backend = RecordingBackend()
    set_backend(backend)
    monkeypatch.setenv("HOTSTUFF_AGG_QC", "0")
    backend_verify_cert(msg, pubs, buf, 64)
    assert backend.cert_calls == 0 and backend.batch_calls == 1
    with pytest.raises(CryptoError):
        backend_verify_cert(msg, pubs, _corrupt(buf, 70), 64)


def test_backend_without_fused_entry_falls_back():
    rng = random.Random(108)
    msgs, pubs, buf = _packed_cert(3, rng, stride=72, shared=False)
    backend = ExplodedOnlyBackend()
    set_backend(backend)
    backend_verify_cert(msgs, pubs, buf, 72)
    assert backend.batch_calls == 1
    with pytest.raises(CryptoError):
        backend_verify_cert(msgs, pubs, _corrupt(buf, 72 * 2 + 10), 72)


# ---------------------------------------------------------------------------
# Super-batching: cert-identity dedup + bad-cert isolation
# ---------------------------------------------------------------------------


class GatedBackend(RecordingBackend):
    """First verify_batch call blocks until released — pools later
    requests behind an 'in-flight device call' deterministically."""

    def __init__(self):
        super().__init__()
        self.first_entered = threading.Event()
        self.release_first = threading.Event()
        self._first = True

    def verify_batch(self, msgs, pubs, sigs):
        gate = self._first
        self._first = False
        if gate:
            self.first_entered.set()
            assert self.release_first.wait(timeout=30)
        super().verify_batch(msgs, pubs, sigs)


def _spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t


def test_superbatch_dedups_same_cert_to_one_msm():
    rng = random.Random(109)
    msg, pubs, buf = _packed_cert(4, rng)
    inner = GatedBackend()
    b = BatchingBackend(inner)

    # Occupy the flusher with a plain triple request at the gate.
    pk, sk = generate_keypair(seed=rng.randbytes(32))
    d = sha512_digest(b"gate")
    sig = Signature.new(d, sk)
    t0 = _spawn(lambda: b.verify_batch([d.data], [pk.data], [sig.data]))
    assert inner.first_entered.wait(timeout=30)

    # Three copies of the SAME cert (one proposal fanned to N in-process
    # validators) pool behind it.
    errs = []

    def one():
        try:
            b.verify_cert(msg, pubs, buf, 64, key=b"cert-identity")
        except CryptoError as e:  # pragma: no cover - failure reporting
            errs.append(e)

    ts = [_spawn(one) for _ in range(3)]
    deadline = 30.0
    while len(b._pending) < 3 and deadline > 0:
        threading.Event().wait(0.005)
        deadline -= 0.005
    inner.release_first.set()
    for t in (t0, *ts):
        t.join(timeout=30)
    assert not errs
    assert inner.cert_calls == 1  # one MSM for the three requests
    assert b.cert_requests == 3
    assert b.cert_deduped_sigs == len(pubs) * 2


def test_superbatch_bad_cert_fails_only_its_own_waiters():
    rng = random.Random(110)
    msg, pubs, buf = _packed_cert(4, rng)
    bad_buf = _corrupt(buf, 5)
    inner = GatedBackend()
    b = BatchingBackend(inner)

    pk, sk = generate_keypair(seed=rng.randbytes(32))
    d = sha512_digest(b"gate2")
    sig = Signature.new(d, sk)
    t0 = _spawn(lambda: b.verify_batch([d.data], [pk.data], [sig.data]))
    assert inner.first_entered.wait(timeout=30)

    results = {}

    def run(tag, sbuf, key):
        try:
            b.verify_cert(msg, pubs, sbuf, 64, key=key)
            results[tag] = None
        except CryptoError as e:
            results[tag] = e

    ts = [
        _spawn(lambda: run("good", buf, b"good")),
        _spawn(lambda: run("bad", bad_buf, b"bad")),
    ]
    deadline = 30.0
    while len(b._pending) < 2 and deadline > 0:
        threading.Event().wait(0.005)
        deadline -= 0.005
    inner.release_first.set()
    for t in (t0, *ts):
        t.join(timeout=30)
    assert results["good"] is None
    assert isinstance(results["bad"], CryptoError)


# ---------------------------------------------------------------------------
# End-to-end: wire v1/v2 interop through QC/TC.verify
# ---------------------------------------------------------------------------


def _committee(n, rng):
    kps = [generate_keypair(seed=rng.randbytes(32)) for _ in range(n)]
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", 0)) for pk, _ in kps
        }
    )
    return committee, kps


def _signed_block(kps, quorum, with_tc=True):
    genesis = Block.genesis()
    qc = QC(hash=genesis.digest(), round=1, votes=[])
    qc.votes = [(pk, Signature.new(qc.digest(), sk)) for pk, sk in kps[:quorum]]
    tc = None
    if with_tc:
        tc = TC(
            round=2,
            votes=[
                (
                    pk,
                    Signature.new(
                        sha512_digest(_U64.pack(2), _U64.pack(1)), sk
                    ),
                    1,
                )
                for pk, sk in kps[:quorum]
            ],
        )
    pk, sk = kps[0]
    return Block.new_from_key(
        qc=qc, tc=tc, author=pk, round_=2, payload=[], secret=sk
    )


def _lazy_qc_with_buf(template, sig_buf):
    """Clone a lazily-decoded v2 QC with a substituted signature buffer."""
    seat_list, _buf, seats = template.__dict__["_raw_votes"]
    q = QC.__new__(QC)
    q.hash = template.hash
    q.round = template.round
    q.__dict__["_raw_votes"] = (seat_list, sig_buf, seats)
    return q


def _lazy_tc_with_buf(template, buf):
    seat_list, _buf, seats = template.__dict__["_raw_votes"]
    t = TC.__new__(TC)
    t.round = template.round
    t.__dict__["_raw_votes"] = (seat_list, buf, seats)
    return t


@pytest.mark.parametrize("agg", ["1", "0"])
def test_wire_interop_and_corrupted_slice_rejection(agg, monkeypatch):
    """v1 (materialized) and v2 (raw) decodes of the same block both
    verify, with fused verification on and off — and the v2 raw path
    rejects a cert whose buffer has any one corrupted slice."""
    monkeypatch.setenv("HOTSTUFF_AGG_QC", agg)
    monkeypatch.setenv("HOTSTUFF_CERT_ARENA", "0")  # count every verify
    rng = random.Random(111)
    committee, kps = _committee(7, rng)
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, committee.quorum_threshold())

    _, b1 = decode_message(encode_propose(block), seats)
    _, b2 = decode_message(encode_propose(block, seats), seats)
    b1.verify(committee)  # v1: materialized votes
    b2.verify(committee)  # v2: raw slices through backend_verify_cert

    raw = b2.qc.__dict__["_raw_votes"]
    seat_list, sig_buf, _ = raw
    for seat in range(len(seat_list)):
        pos = seat * 64 + rng.randrange(64)
        bad = _lazy_qc_with_buf(b2.qc, _corrupt(sig_buf, pos))
        with pytest.raises(errors.InvalidSignature):
            bad.verify(committee)

    # TC: 72-byte records; corrupting the signature OR the signed
    # high_qc_round bytes must both reject.
    _, tc2 = decode_message(encode_tc(block.tc, seats), seats)
    tc2.verify(committee)
    t_seats, t_buf, _ = tc2.__dict__["_raw_votes"]
    for pos in (0 * 72 + 10, 1 * 72 + 66):
        bad_tc = _lazy_tc_with_buf(tc2, _corrupt(t_buf, pos))
        with pytest.raises(errors.InvalidSignature):
            bad_tc.verify(committee)


def test_v1_and_v2_share_cache_and_arena_identity():
    """The canonical cert key is wire-format independent: a v1 and a v2
    copy of one QC hit the same CertificateCache and arena entries."""
    rng = random.Random(112)
    committee, kps = _committee(4, rng)
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, committee.quorum_threshold(), with_tc=False)
    _, b1 = decode_message(encode_propose(block), seats)
    _, b2 = decode_message(encode_propose(block, seats), seats)
    assert CertificateCache.key_of(b1.qc) == CertificateCache.key_of(b2.qc)

    backend = RecordingBackend()
    set_backend(backend)
    b2.qc.verify(committee)  # miss: pays the fused MSM
    b1.qc.verify(committee)  # arena hit via the shared canonical key
    arena = cert_arena.get_arena()
    assert arena is not None
    assert arena.hits == 1 and arena.misses == 1
    assert backend.cert_calls + backend.batch_calls == 1


# ---------------------------------------------------------------------------
# Cert arena semantics
# ---------------------------------------------------------------------------


def test_arena_kill_switch(monkeypatch):
    monkeypatch.setenv("HOTSTUFF_CERT_ARENA", "0")
    cert_arena.reset()
    assert cert_arena.get_arena() is None


def test_arena_never_caches_failures():
    """A byzantine cert re-raises on EVERY arrival — success-only arena."""
    rng = random.Random(113)
    committee, kps = _committee(4, rng)
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, committee.quorum_threshold(), with_tc=False)
    _, b2 = decode_message(encode_propose(block, seats), seats)
    _, sig_buf, _ = b2.qc.__dict__["_raw_votes"]
    bad = _lazy_qc_with_buf(b2.qc, _corrupt(sig_buf, 7))
    for _ in range(2):
        with pytest.raises(errors.InvalidSignature):
            bad.verify(committee)
    arena = cert_arena.get_arena()
    assert arena.hits == 0 and arena.misses == 2


def test_arena_isolates_committees():
    """Same cert bytes under a different committee must not alias: the
    arena key includes the committee fingerprint."""
    rng = random.Random(114)
    committee, kps = _committee(4, rng)
    # Same keys, different stake distribution -> different fingerprint.
    committee2 = Committee(
        authorities={
            pk: Authority(stake=2, address=("127.0.0.1", 0)) for pk, _ in kps
        }
    )
    assert cert_arena.committee_fp(committee) != cert_arena.committee_fp(
        committee2
    )
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, committee.quorum_threshold(), with_tc=False)
    _, b2 = decode_message(encode_propose(block, seats), seats)
    backend = RecordingBackend()
    set_backend(backend)
    b2.qc.verify(committee)
    before = backend.cert_calls + backend.batch_calls
    b2.qc.verify(committee2)  # different committee: pays its own verify
    assert backend.cert_calls + backend.batch_calls == before + 1
