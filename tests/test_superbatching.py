"""Super-batching backend: concurrent verification requests fuse into one
inner call; byzantine requests are isolated; the Signature API routes
through it via the "-batched" backend variants."""

import threading

import pytest

from hotstuff_tpu.crypto import (
    CpuBackend,
    CryptoError,
    Signature,
    get_backend,
    set_backend,
    sha512_digest,
)
from hotstuff_tpu.crypto.batching import BatchingBackend

from .common import keys


@pytest.fixture(autouse=True)
def restore_backend():
    yield
    set_backend("cpu")


class CountingBackend(CpuBackend):
    def __init__(self):
        super().__init__()
        self.calls = []

    def verify_batch(self, msgs, pubs, sigs):
        self.calls.append(len(msgs))
        super().verify_batch(msgs, pubs, sigs)


def make_request(n=3, tag=b"m"):
    d = sha512_digest(tag)
    msgs, pubs, sigs = [], [], []
    for pk, sk in keys(4)[:n]:
        msgs.append(d.data)
        pubs.append(pk.data)
        sigs.append(Signature.new(d, sk).data)
    return msgs, pubs, sigs


def _run_threads(backend, requests):
    errors = [None] * len(requests)

    def worker(i, req):
        try:
            backend.verify_batch(*req)
        except CryptoError as e:
            errors[i] = e

    threads = [
        threading.Thread(target=worker, args=(i, r)) for i, r in enumerate(requests)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return errors


class GatedBackend(CountingBackend):
    """First call blocks until released — models an in-flight device call
    so tests can deterministically pool requests behind it."""

    def __init__(self):
        super().__init__()
        self.first_entered = threading.Event()
        self.release_first = threading.Event()
        self._first = True

    def verify_batch(self, msgs, pubs, sigs):
        gate = self._first
        self._first = False
        if gate:
            self.first_entered.set()
            assert self.release_first.wait(10)
        super().verify_batch(msgs, pubs, sigs)


def test_requests_pool_behind_inflight_call_and_fuse():
    """Back-pressure batching: requests arriving while an inner call is
    in flight fuse into ONE follow-up call when the device frees."""
    inner = GatedBackend()
    backend = BatchingBackend(inner)
    opener = threading.Thread(
        target=backend.verify_batch, args=make_request(tag=b"opener")
    )
    opener.start()
    assert inner.first_entered.wait(10)  # device now "busy"
    requests = [make_request(tag=b"r%d" % i) for i in range(5)]
    threads = [
        threading.Thread(target=backend.verify_batch, args=r) for r in requests
    ]
    for t in threads:
        t.start()
    # Give all five time to pool behind the in-flight call.
    for _ in range(100):
        with backend._lock:
            if len(backend._pending) == 5:
                break
        threading.Event().wait(0.01)
    inner.release_first.set()
    opener.join(10)
    for t in threads:
        t.join(10)
    assert inner.calls == [3, 15], f"expected opener + one fused call, got {inner.calls}"
    assert backend.fused_requests == 6 and backend.inner_calls == 2


def test_identical_requests_dedup_inside_fused_flush():
    """Byte-identical (msg, pub, sig) triples fused into one flush are
    verified ONCE: the N copies of a rebroadcast QC (or of a proposal's
    author signature fanned to N in-process validators) collapse to one
    — verifying the distinct set decides the multiset. Verdicts stay
    per-request."""
    inner = GatedBackend()
    backend = BatchingBackend(inner)
    same = make_request(tag=b"same-qc")
    opener = threading.Thread(
        target=backend.verify_batch, args=make_request(tag=b"opener")
    )
    opener.start()
    assert inner.first_entered.wait(10)
    threads = [
        threading.Thread(target=backend.verify_batch, args=same)
        for _ in range(5)
    ]
    for t in threads:
        t.start()
    for _ in range(100):
        with backend._lock:
            if len(backend._pending) == 5:
                break
        threading.Event().wait(0.01)
    inner.release_first.set()
    opener.join(10)
    for t in threads:
        t.join(10)
    # Five identical 3-sig requests fused into ONE 3-sig inner call.
    assert inner.calls == [3, 3], inner.calls
    assert backend.deduped_sigs == 12


def test_identical_bad_requests_still_reject_each_caller():
    """Dedup must not launder rejections: every caller of an identical
    INVALID triple gets its own CryptoError (per-request fallback)."""
    inner = GatedBackend()
    backend = BatchingBackend(inner)
    msgs, pubs, sigs = make_request(tag=b"bad")
    sigs = [b"\x07" * 64 for _ in sigs]  # garbage signatures
    bad = (msgs, pubs, sigs)
    opener = threading.Thread(
        target=backend.verify_batch, args=make_request(tag=b"opener2")
    )
    opener.start()
    assert inner.first_entered.wait(10)
    errors = [None, None, None]

    def worker(i):
        try:
            backend.verify_batch(*bad)
        except CryptoError as e:
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for _ in range(100):
        with backend._lock:
            if len(backend._pending) == 3:
                break
        threading.Event().wait(0.01)
    inner.release_first.set()
    opener.join(10)
    for t in threads:
        t.join(10)
    assert all(isinstance(e, CryptoError) for e in errors)


def test_lone_request_flushes_immediately():
    """An idle device means zero added latency: a lone QC goes straight
    through (round 2 charged it a fixed 2 ms collection window)."""
    import time

    inner = CountingBackend()
    backend = BatchingBackend(inner)
    t0 = time.perf_counter()
    backend.verify_batch(*make_request(tag=b"lone"))
    elapsed = time.perf_counter() - t0
    assert inner.calls == [3] and backend.inner_calls == 1
    # Generous bound: the old 2 ms window alone would eat most of this.
    assert elapsed < 1.0


def test_byzantine_request_isolated():
    inner = GatedBackend()
    backend = BatchingBackend(inner)
    opener = threading.Thread(
        target=backend.verify_batch, args=make_request(tag=b"opener")
    )
    opener.start()
    assert inner.first_entered.wait(10)
    good = [make_request(tag=b"g%d" % i) for i in range(3)]
    bad_msgs, bad_pubs, bad_sigs = make_request(tag=b"bad")
    bad_sigs[1] = bytes(64)
    pooled = good + [(bad_msgs, bad_pubs, bad_sigs)]
    errors = [None] * len(pooled)

    def worker(i, req):
        try:
            backend.verify_batch(*req)
        except CryptoError as e:
            errors[i] = e

    threads = [
        threading.Thread(target=worker, args=(i, r)) for i, r in enumerate(pooled)
    ]
    for t in threads:
        t.start()
    for _ in range(100):
        with backend._lock:
            if len(backend._pending) == 4:
                break
        threading.Event().wait(0.01)
    inner.release_first.set()
    opener.join(10)
    for t in threads:
        t.join(10)
    assert errors[:3] == [None] * 3, "good requests poisoned by the bad one"
    assert isinstance(errors[3], CryptoError)
    # Opener + one fused attempt + one isolation pass per pooled request.
    assert inner.calls[0] == 3 and inner.calls[1] == 12 and len(inner.calls) == 6


def test_sequential_requests_still_work():
    backend = BatchingBackend(CountingBackend(), window_ms=1)
    for i in range(3):
        backend.verify_batch(*make_request(tag=b"s%d" % i))
    with pytest.raises(CryptoError):
        m, p, s = make_request(tag=b"x")
        backend.verify_batch(m, p, [bytes(64)] * len(s))


def test_backend_variant_names():
    set_backend("cpu-batched")
    backend = get_backend()
    assert isinstance(backend, BatchingBackend)
    assert backend.name == "cpu+superbatch"
    # The public Signature API routes through it.
    d = sha512_digest(b"qc")
    votes = [(pk, Signature.new(d, sk)) for pk, sk in keys(4)]
    Signature.verify_batch(d, votes)
    with pytest.raises(ValueError):
        set_backend("cpu-bogus")
    # A failed set_backend must leave the active backend unchanged.
    assert get_backend() is backend
    with pytest.raises(ValueError):
        set_backend("tpu-")  # trailing dash = malformed, not bare tpu
    assert get_backend() is backend


def test_device_failure_does_not_wedge_waiters():
    """A NON-crypto exception from the inner backend (JAX RuntimeError,
    device/tunnel death) must release every fused waiter with a CryptoError
    — not propagate into one caller while the rest block forever."""

    class DyingBackend(CpuBackend):
        def verify_batch(self, msgs, pubs, sigs):
            raise RuntimeError("device tunnel died")

    backend = BatchingBackend(DyingBackend(), window_ms=50)
    requests = [make_request(tag=b"w%d" % i) for i in range(4)]
    errors = _run_threads(backend, requests)
    assert all(isinstance(e, CryptoError) for e in errors), errors
    assert all("backend failure" in str(e) for e in errors)


def test_partial_device_failure_isolates_to_healthy_path():
    """Fused call dies with a non-crypto error, but per-request retries
    succeed: every waiter must be released with the correct verdict."""

    class FlakyBackend(CpuBackend):
        def __init__(self):
            super().__init__()
            self.first = True

        def verify_batch(self, msgs, pubs, sigs):
            if self.first:
                self.first = False
                raise RuntimeError("transient device error")
            super().verify_batch(msgs, pubs, sigs)

    backend = BatchingBackend(FlakyBackend(), window_ms=50)
    requests = [make_request(tag=b"f%d" % i) for i in range(3)]
    errors = _run_threads(backend, requests)
    assert errors == [None] * 3, errors


def test_enable_superbatching_idempotent():
    from hotstuff_tpu.crypto.batching import enable_superbatching

    set_backend("cpu")
    b1 = enable_superbatching()
    b2 = enable_superbatching()
    assert b1 is b2
