"""Multi-device (mesh) TPU backend tests — the sharded and
sharded+cached verifier graphs are separate heavy XLA compiles, so they
get their own cold-compile slice (split from test_tpu_backend.py)."""

import pytest

pytest.importorskip("jax")

pytestmark = pytest.mark.device

from hotstuff_tpu.crypto import CryptoError  # noqa: E402
from .test_tpu_backend import make_batch  # noqa: E402
from hotstuff_tpu.crypto import (  # noqa: E402
    Signature,
    set_backend,
    sha512_digest,
)
from .common import chain, consensus_committee, keys  # noqa: E402


@pytest.fixture(autouse=True)
def reset_backend():
    yield
    set_backend("cpu")



def test_tpu_backend_auto_shards_on_multidevice():
    """On a multi-device platform (the conftest's virtual 8-CPU mesh) the
    backend must select the lane-sharded mesh verifier automatically
    (BASELINE config 5 wiring) — and both polarities must flow through it."""
    import jax

    from hotstuff_tpu.crypto.tpu_backend import TpuBackend

    backend = TpuBackend()
    assert jax.device_count() > 1
    assert backend._mesh is not None, "multi-device must auto-select the mesh"

    msgs, pubs, sigs = make_batch(5, seed=21)
    backend.verify_batch(msgs, pubs, sigs)  # must not raise
    bad = bytearray(sigs[2])
    bad[7] ^= 0x20
    with pytest.raises(CryptoError):
        backend.verify_batch(msgs, pubs, [*sigs[:2], bytes(bad), *sigs[3:]])


def test_tpu_backend_sharded_override_off():
    from hotstuff_tpu.crypto.tpu_backend import TpuBackend

    assert TpuBackend(sharded=False)._mesh is None


def test_tpu_backend_mesh_uses_committee_cache():
    """BASELINE config 5: the sharded mesh path must consult the committee
    point cache (round-2 weak #7 — it used to fall back to full
    decompression exactly where the cache matters most). Pins both
    acceptance polarities through the sharded+cached path and steady-state
    row reuse. (Unsharded cached-vs-v1 acceptance parity is pinned in
    test_verify_cached / test_verify_cache_shapes; compiling the unsharded
    graph HERE too would blow this slice's cold window.)"""
    import random

    from hotstuff_tpu.crypto.tpu_backend import TpuBackend
    from hotstuff_tpu.ops.verify import DevicePointCache
    from hotstuff_tpu.parallel import make_mesh
    from hotstuff_tpu.parallel.mesh import verify_batch_device_cached_sharded

    backend = TpuBackend()
    assert backend._mesh is not None and backend._cache is not None, (
        "multi-device backend must keep the committee cache"
    )

    msgs, pubs, sigs = make_batch(5, seed=33)
    mesh = make_mesh()
    cache_a = DevicePointCache()  # default capacity: shares the backend graphs' cache-array shape
    ok_sharded = verify_batch_device_cached_sharded(
        mesh, msgs, pubs, sigs, cache_a, _rng=random.Random(7)
    )
    assert ok_sharded is True

    bad = bytearray(sigs[1])
    bad[3] ^= 0x10
    bad_sigs = [sigs[0], bytes(bad), *sigs[2:]]
    assert (
        verify_batch_device_cached_sharded(
            mesh, msgs, pubs, bad_sigs, cache_a, _rng=random.Random(8)
        )
        is False
    )
    # Steady state: repeat batches reuse the cached rows (no growth).
    rows_before = cache_a._next_row
    assert verify_batch_device_cached_sharded(
        mesh, msgs, pubs, sigs, cache_a, _rng=random.Random(9)
    )
    assert cache_a._next_row == rows_before


# Backend-routed paths: on a multi-device platform these flow through
# the sharded mesh verifier, sharing its compiled graph.
def test_tpu_backend_through_signature_api():
    set_backend("tpu")
    d = sha512_digest(b"quorum certificate")
    votes = [(pk, Signature.new(d, sk)) for pk, sk in keys(4)]
    Signature.verify_batch(d, votes)  # must not raise
    votes[1] = (votes[1][0], Signature(bytes(64)))
    with pytest.raises(CryptoError):
        Signature.verify_batch(d, votes)


def test_tpu_backend_qc_verify():
    set_backend("tpu")
    committee = consensus_committee(14000)
    blocks = chain(2)
    blocks[1].verify(committee)  # embedded QC batch-verifies on device
