"""Committee-scale batched vote verification: unverified votes accumulate,
the assembled QC is verified in one batch call, and byzantine signatures
are identified and ejected without halting aggregation."""

import asyncio

from hotstuff_tpu.consensus.leader import LeaderElector
from hotstuff_tpu.consensus.messages import Vote
from hotstuff_tpu.consensus.proposer import Make
from hotstuff_tpu.crypto import Signature

from .common import async_test, chain, consensus_committee, keys
from .test_consensus_core import leader_index, spawn_core

BASE = 13400


@async_test
async def test_batched_votes_make_verified_qc():
    committee = consensus_committee(BASE)
    blocks = chain(1)
    me = leader_index(committee, 2)
    node = spawn_core(me, committee, batch_vote_verification=True)
    votes = [
        Vote.new_from_key(blocks[0].digest(), 1, pk, sk) for pk, sk in keys()[:3]
    ]
    for v in votes:
        await node["rx"].put(("vote", v))
    while True:
        msg = await asyncio.wait_for(node["proposer"].get(), 5)
        if isinstance(msg, Make) and msg.round == 2:
            assert msg.qc.hash == blocks[0].digest()
            break
    node["task"].cancel()
    node["sync"].shutdown()


@async_test
async def test_spoofed_vote_cannot_displace_honest_vote():
    """A garbage signature under an honest author's key arrives FIRST; the
    genuine vote must still land (individual verify + replacement) and the
    QC must form — the anti-displacement liveness property."""
    committee = consensus_committee(BASE + 20)
    blocks = chain(1)
    me = leader_index(committee, 2)
    node = spawn_core(me, committee, batch_vote_verification=True)

    spoof = Vote(blocks[0].digest(), 1, keys()[0][0], Signature(b"\x09" * 64))
    await node["rx"].put(("vote", spoof))  # occupies author 0's slot
    await asyncio.sleep(0.05)
    good = [
        Vote.new_from_key(blocks[0].digest(), 1, pk, sk) for pk, sk in keys()
    ]
    await node["rx"].put(("vote", good[0]))  # the genuine vote: must replace
    await node["rx"].put(("vote", good[1]))
    await node["rx"].put(("vote", good[2]))
    while True:
        msg = await asyncio.wait_for(node["proposer"].get(), 5)
        if isinstance(msg, Make) and msg.round == 2:
            assert msg.qc.hash == blocks[0].digest()
            break
    node["task"].cancel()
    node["sync"].shutdown()


@async_test
async def test_future_round_votes_bounded():
    """Votes absurdly far in the future are dropped, not aggregated."""
    committee = consensus_committee(BASE + 30)
    blocks = chain(1)
    node = spawn_core(0, committee, batch_vote_verification=True)
    core = None
    pk, sk = keys()[1]
    far = Vote.new_from_key(blocks[0].digest(), 10_000_000, pk, sk)
    await node["rx"].put(("vote", far))
    await asyncio.sleep(0.1)
    # Reach into the running core to check no state was allocated.
    frame_self = node["task"].get_coro().cr_frame.f_locals["self"]
    assert 10_000_000 not in frame_self.aggregator.votes_aggregators
    node["task"].cancel()
    node["sync"].shutdown()


def test_rebuild_emits_qc_when_good_votes_meet_quorum():
    """Unequal stakes: if the ejected signature was not load-bearing, the
    surviving votes already form a quorum and rebuild must emit the QC
    instead of stalling (regression for the stake-weighted case)."""
    from hotstuff_tpu.consensus import Authority, Committee
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.messages import Vote

    ks = keys(3)
    # Stakes A=1, B=1, C=3 -> total 5, quorum = 2*5//3+1 = 4.
    committee = Committee(
        authorities={
            ks[0][0]: Authority(stake=1, address=("127.0.0.1", 1)),
            ks[1][0]: Authority(stake=1, address=("127.0.0.1", 2)),
            ks[2][0]: Authority(stake=3, address=("127.0.0.1", 3)),
        }
    )
    agg = Aggregator(committee)
    block = chain(1)[0]
    v_a = Vote.new_from_key(block.digest(), 1, ks[0][0], ks[0][1])
    v_c = Vote.new_from_key(block.digest(), 1, ks[2][0], ks[2][1])
    bad_b = Vote(block.digest(), 1, ks[1][0], Signature(b"\x03" * 64))

    assert agg.add_vote(bad_b) is None  # stake 1
    assert agg.add_vote(v_a) is None  # stake 2
    qc = agg.add_vote(v_c)  # stake 5 >= 4 -> QC (contains the bad sig)
    assert qc is not None
    # Ejection keeps A (1) + C (3) = 4 >= quorum: rebuild must emit.
    good = [(pk, sig) for pk, sig in qc.votes if pk != ks[1][0]]
    rebuilt = agg.rebuild_votes(qc.round, qc.digest(), good, qc.hash)
    assert rebuilt is not None
    rebuilt.verify(committee)


def test_aggregator_per_round_digest_bound():
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.crypto import sha512_digest

    committee = consensus_committee(BASE + 40)
    agg = Aggregator(committee)
    pk, sk = keys()[0]
    cap = Aggregator.MAX_DIGESTS_PER_ROUND_FACTOR * committee.size()
    for i in range(cap + 5):
        v = Vote(sha512_digest(b"digest%d" % i), 3, pk, Signature(b"\x01" * 64))
        agg.add_vote(v)
    assert len(agg.votes_aggregators[3]) == cap


@async_test
async def test_byzantine_vote_ejected_and_quorum_recovers():
    committee = consensus_committee(BASE + 10)
    blocks = chain(1)
    me = leader_index(committee, 2)
    node = spawn_core(me, committee, batch_vote_verification=True)

    good = [
        Vote.new_from_key(blocks[0].digest(), 1, pk, sk) for pk, sk in keys()
    ]
    # keys()[2] is byzantine: garbage signature.
    bad = Vote(blocks[0].digest(), 1, keys()[2][0], Signature(b"\x07" * 64))
    await node["rx"].put(("vote", good[0]))
    await node["rx"].put(("vote", good[1]))
    await node["rx"].put(("vote", bad))  # completes 2f+1 -> batch fails
    await asyncio.sleep(0.3)
    assert node["proposer"].empty()  # no QC from the poisoned batch
    # The byzantine author's slot is free again; an honest 3rd vote follows.
    await node["rx"].put(("vote", good[3]))
    while True:
        msg = await asyncio.wait_for(node["proposer"].get(), 5)
        if isinstance(msg, Make) and msg.round == 2:
            qc = msg.qc
            assert qc.hash == blocks[0].digest()
            break
    node["task"].cancel()
    node["sync"].shutdown()
