"""Committee-scale batched vote verification: unverified votes accumulate,
the assembled QC is verified in one batch call, and byzantine signatures
are identified and ejected without halting aggregation."""

import asyncio

from hotstuff_tpu.consensus.leader import LeaderElector
from hotstuff_tpu.consensus.messages import Vote
from hotstuff_tpu.consensus.proposer import Make
from hotstuff_tpu.crypto import Signature

from .common import async_test, chain, consensus_committee, keys
from .test_consensus_core import leader_index, spawn_core

BASE = 13400


@async_test
async def test_batched_votes_make_verified_qc():
    committee = consensus_committee(BASE)
    blocks = chain(1)
    me = leader_index(committee, 2)
    node = spawn_core(me, committee, batch_vote_verification=True)
    votes = [
        Vote.new_from_key(blocks[0].digest(), 1, pk, sk) for pk, sk in keys()[:3]
    ]
    for v in votes:
        await node["rx"].put(("vote", v))
    while True:
        msg = await asyncio.wait_for(node["proposer"].get(), 5)
        if isinstance(msg, Make) and msg.round == 2:
            assert msg.qc.hash == blocks[0].digest()
            break
    node["task"].cancel()
    node["sync"].shutdown()


@async_test
async def test_spoofed_vote_cannot_displace_honest_vote():
    """A garbage signature under an honest author's key arrives FIRST; the
    genuine vote must still land (individual verify + replacement) and the
    QC must form — the anti-displacement liveness property."""
    committee = consensus_committee(BASE + 20)
    blocks = chain(1)
    me = leader_index(committee, 2)
    node = spawn_core(me, committee, batch_vote_verification=True)

    spoof = Vote(blocks[0].digest(), 1, keys()[0][0], Signature(b"\x09" * 64))
    await node["rx"].put(("vote", spoof))  # occupies author 0's slot
    await asyncio.sleep(0.05)
    good = [
        Vote.new_from_key(blocks[0].digest(), 1, pk, sk) for pk, sk in keys()
    ]
    await node["rx"].put(("vote", good[0]))  # the genuine vote: must replace
    await node["rx"].put(("vote", good[1]))
    await node["rx"].put(("vote", good[2]))
    while True:
        msg = await asyncio.wait_for(node["proposer"].get(), 5)
        if isinstance(msg, Make) and msg.round == 2:
            assert msg.qc.hash == blocks[0].digest()
            break
    node["task"].cancel()
    node["sync"].shutdown()


@async_test
async def test_spoofed_bogus_digest_cannot_block_honest_vote():
    """Cross-bucket displacement: a garbage signature under an honest
    author's key voting for a FABRICATED digest arrives first; the genuine
    vote for the real proposal must still be re-seated and the QC form."""
    from hotstuff_tpu.crypto import sha512_digest

    committee = consensus_committee(BASE + 60)
    blocks = chain(1)
    me = leader_index(committee, 2)
    node = spawn_core(me, committee, batch_vote_verification=True)

    spoof = Vote(sha512_digest(b"bogus"), 1, keys()[0][0], Signature(b"\x09" * 64))
    await node["rx"].put(("vote", spoof))  # binds author 0 to a bogus bucket
    await asyncio.sleep(0.05)
    good = [
        Vote.new_from_key(blocks[0].digest(), 1, pk, sk) for pk, sk in keys()
    ]
    await node["rx"].put(("vote", good[1]))
    await node["rx"].put(("vote", good[2]))
    await node["rx"].put(("vote", good[0]))  # must evict the bogus entry
    while True:
        msg = await asyncio.wait_for(node["proposer"].get(), 5)
        if isinstance(msg, Make) and msg.round == 2:
            assert msg.qc.hash == blocks[0].digest()
            break
    node["task"].cancel()
    node["sync"].shutdown()


@async_test
async def test_future_round_votes_bounded():
    """Votes absurdly far in the future are dropped, not aggregated."""
    committee = consensus_committee(BASE + 30)
    blocks = chain(1)
    node = spawn_core(0, committee, batch_vote_verification=True)
    core = None
    pk, sk = keys()[1]
    far = Vote.new_from_key(blocks[0].digest(), 10_000_000, pk, sk)
    await node["rx"].put(("vote", far))
    await asyncio.sleep(0.1)
    # Reach into the running core to check no state was allocated.
    frame_self = node["task"].get_coro().cr_frame.f_locals["self"]
    assert 10_000_000 not in frame_self.aggregator.votes_aggregators
    node["task"].cancel()
    node["sync"].shutdown()


def test_rebuild_emits_qc_when_good_votes_meet_quorum():
    """Unequal stakes: if the ejected signature was not load-bearing, the
    surviving votes already form a quorum and rebuild must emit the QC
    instead of stalling (regression for the stake-weighted case)."""
    from hotstuff_tpu.consensus import Authority, Committee
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.messages import Vote

    ks = keys(3)
    # Stakes A=1, B=1, C=3 -> total 5, quorum = 2*5//3+1 = 4.
    committee = Committee(
        authorities={
            ks[0][0]: Authority(stake=1, address=("127.0.0.1", 1)),
            ks[1][0]: Authority(stake=1, address=("127.0.0.1", 2)),
            ks[2][0]: Authority(stake=3, address=("127.0.0.1", 3)),
        }
    )
    agg = Aggregator(committee)
    block = chain(1)[0]
    v_a = Vote.new_from_key(block.digest(), 1, ks[0][0], ks[0][1])
    v_c = Vote.new_from_key(block.digest(), 1, ks[2][0], ks[2][1])
    bad_b = Vote(block.digest(), 1, ks[1][0], Signature(b"\x03" * 64))

    assert agg.add_vote(bad_b) is None  # stake 1
    assert agg.add_vote(v_a) is None  # stake 2
    qc = agg.add_vote(v_c)  # stake 5 >= 4 -> QC (contains the bad sig)
    assert qc is not None
    # Ejection keeps A (1) + C (3) = 4 >= quorum: it must emit a QC.
    bad = [(pk, sig) for pk, sig in qc.votes if pk == ks[1][0]]
    rebuilt, ejected = agg.eject_votes(qc.round, qc.digest(), bad, qc.hash)
    assert ejected == {ks[1][0]}
    assert rebuilt is not None
    rebuilt.verify(committee)


def test_eject_votes_keeps_replaced_genuine_signature():
    """Ejection is keyed by (author, signature): if an author's spoofed
    signature from a stale QC snapshot was already swapped for their
    individually-verified genuine one, ejecting the stale pair must keep
    the genuine vote seated (and not report the author ejected)."""
    from hotstuff_tpu.consensus import Authority, Committee
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.messages import Vote

    ks = keys(3)
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", 1 + i))
            for i, (pk, _) in enumerate(ks)
        }
    )
    agg = Aggregator(committee)
    block = chain(1)[0]
    spoofed = Vote(block.digest(), 1, ks[1][0], Signature(b"\x07" * 64))
    genuine = Vote.new_from_key(block.digest(), 1, ks[1][0], ks[1][1])
    v_a = Vote.new_from_key(block.digest(), 1, ks[0][0], ks[0][1])
    v_c = Vote.new_from_key(block.digest(), 1, ks[2][0], ks[2][1])

    assert agg.add_vote(spoofed) is None
    assert agg.add_vote(v_a) is None
    stale_qc = agg.add_vote(v_c)  # quorum met; snapshot holds the spoof
    assert stale_qc is not None
    agg.replace_vote(genuine)  # core verified the genuine resend

    bad = [(pk, sig) for pk, sig in stale_qc.votes if pk == ks[1][0]]
    fixed, ejected = agg.eject_votes(
        stale_qc.round, stale_qc.digest(), bad, stale_qc.hash
    )
    assert ejected == set()  # the genuine replacement survived
    assert fixed is not None
    fixed.verify(committee)  # all three signatures now genuine


def test_aggregator_one_bucket_per_author():
    """A byzantine member signing votes for many fabricated digests can
    occupy at most ONE digest bucket per round — honest votes for the real
    proposal are never displaced (liveness-DoS fix)."""
    import pytest

    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.errors import AuthorityReuse
    from hotstuff_tpu.crypto import sha512_digest

    committee = consensus_committee(BASE + 40)
    agg = Aggregator(committee)
    pk, sk = keys()[0]
    agg.add_vote(Vote(sha512_digest(b"digest0"), 3, pk, Signature(b"\x01" * 64)))
    for i in range(1, 10):
        v = Vote(sha512_digest(b"digest%d" % i), 3, pk, Signature(b"\x01" * 64))
        with pytest.raises(AuthorityReuse):
            agg.add_vote(v)
    assert len(agg.votes_aggregators[3]) == 1
    # Honest votes for the real digest still aggregate to a QC.
    block = chain(1)[0]
    qc = None
    for hpk, hsk in keys()[1:4]:
        qc = agg.add_vote(Vote.new_from_key(block.digest(), 3, hpk, hsk))
    assert qc is not None and qc.hash == block.digest()


def test_reseat_vote_moves_author_across_buckets():
    """Cross-bucket conflict: a (spoofed or equivocating) entry under an
    author's key in a bogus-digest bucket is evicted when the author's
    verified vote for the real digest is re-seated; the empty bogus bucket
    is garbage-collected and the re-seat can complete a quorum."""
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.crypto import sha512_digest

    committee = consensus_committee(BASE + 50)
    agg = Aggregator(committee)
    block = chain(1)[0]
    ks = keys()
    bogus = Vote(sha512_digest(b"bogus"), 1, ks[0][0], Signature(b"\x02" * 64))
    agg.add_vote(bogus)
    for pk, sk in ks[1:3]:
        assert agg.add_vote(Vote.new_from_key(block.digest(), 1, pk, sk)) is None
    genuine = Vote.new_from_key(block.digest(), 1, ks[0][0], ks[0][1])
    qc = agg.reseat_vote(genuine)  # 3rd vote: completes 2f+1
    assert qc is not None and qc.hash == block.digest()
    qc.verify(committee)
    assert bogus.digest() not in agg.votes_aggregators[1]  # bucket GC'd


@async_test
async def test_backend_outage_does_not_blacklist_honest_votes():
    """A transient device/tunnel failure during QC batch verification must
    NOT classify the honest signatures as byzantine: after the backend
    recovers, a resend of one vote completes the quorum and the QC forms.

    The process-wide cert arena is dropped first: earlier tests in this
    module verify the byte-identical QC (keys and chain() are
    deterministic), and an arena hit would let the QC form without ever
    consulting the dead backend — hiding the outage path under test."""
    from hotstuff_tpu import crypto as crypto_mod
    from hotstuff_tpu.consensus import cert_arena
    from hotstuff_tpu.crypto import BackendUnavailable, get_backend

    cert_arena.reset()
    committee = consensus_committee(BASE + 70)
    blocks = chain(1)
    me = leader_index(committee, 2)

    real = get_backend()

    class OutageBackend:
        name = "outage"
        fail = True

        def verify_batch(self, msgs, pubs, sigs):
            if OutageBackend.fail:
                raise BackendUnavailable("tunnel died")
            real.verify_batch(msgs, pubs, sigs)

        def __getattr__(self, item):
            return getattr(real, item)

    try:
        crypto_mod._BACKEND = OutageBackend()
        node = spawn_core(me, committee, batch_vote_verification=True)
        good = [
            Vote.new_from_key(blocks[0].digest(), 1, pk, sk) for pk, sk in keys()
        ]
        for v in good[:3]:
            await node["rx"].put(("vote", v))  # 3rd completes 2f+1 -> outage
        await asyncio.sleep(0.1)
        assert node["proposer"].empty()
        assert not node["task"].done(), "core died on backend outage"
        OutageBackend.fail = False  # tunnel recovers; bounded retry fires
        while True:
            msg = await asyncio.wait_for(node["proposer"].get(), 5)
            if isinstance(msg, Make) and msg.round == 2:
                assert msg.qc.hash == blocks[0].digest()
                break
        node["task"].cancel()
        node["sync"].shutdown()
    finally:
        crypto_mod._BACKEND = real


@async_test
async def test_byzantine_vote_ejected_and_quorum_recovers():
    committee = consensus_committee(BASE + 10)
    blocks = chain(1)
    me = leader_index(committee, 2)
    node = spawn_core(me, committee, batch_vote_verification=True)

    good = [
        Vote.new_from_key(blocks[0].digest(), 1, pk, sk) for pk, sk in keys()
    ]
    # keys()[2] is byzantine: garbage signature.
    bad = Vote(blocks[0].digest(), 1, keys()[2][0], Signature(b"\x07" * 64))
    await node["rx"].put(("vote", good[0]))
    await node["rx"].put(("vote", good[1]))
    await node["rx"].put(("vote", bad))  # completes 2f+1 -> batch fails
    await asyncio.sleep(0.3)
    assert node["proposer"].empty()  # no QC from the poisoned batch
    # The byzantine author's slot is free again; an honest 3rd vote follows.
    await node["rx"].put(("vote", good[3]))
    while True:
        msg = await asyncio.wait_for(node["proposer"].get(), 5)
        if isinstance(msg, Make) and msg.round == 2:
            qc = msg.qc
            assert qc.hash == blocks[0].digest()
            break
    node["task"].cancel()
    node["sync"].shutdown()


@async_test
async def test_verify_off_loop_gates_inline_on_batch_size():
    """CPU-backend verifications run inline only below INLINE_SIG_LIMIT;
    committee-scale batches (8-38 ms at N=400-1000) go to the worker pool so
    they cannot head-of-line-block timers and network reads (advisor
    finding, round 2)."""
    import threading

    from hotstuff_tpu.consensus import crypto_bridge as cb

    loop_thread = threading.get_ident()
    seen = {}

    def probe():
        seen["thread"] = threading.get_ident()
        return 42

    assert await cb.verify_off_loop(probe) == 42
    assert seen["thread"] == loop_thread, "single-sig CPU verify must inline"
    assert await cb.verify_off_loop(probe, n_sigs=cb.INLINE_SIG_LIMIT) == 42
    assert seen["thread"] != loop_thread, "large CPU batch must use the pool"
