"""SLO engine tests: window arithmetic over cumulative snapshot streams
(empty window, single snapshot, counter reset after restart), histogram
quantiles, and verdict semantics."""

from __future__ import annotations

import pytest

from hotstuff_tpu.telemetry import slo


def _snap(ts, counters=None, histograms=None, gauges=None):
    return {
        "ts": ts,
        "seq": int(ts),
        "counters": counters or {},
        "histograms": histograms or {},
        "gauges": gauges or {},
    }


def _hist(le, counts):
    return {
        "le": list(le),
        "counts": list(counts),
        "sum": 0.0,
        "count": sum(counts),
    }


# -- primitives --------------------------------------------------------------


def test_counter_delta_and_reset():
    b = _snap(0, {"c": 100})
    a = _snap(10, {"c": 150})
    assert slo.counter_delta(b, a, "c") == 50
    # Restart mid-window: cumulative value went DOWN => counted from
    # zero again, the delta is the after-value, never negative.
    a_reset = _snap(10, {"c": 30})
    assert slo.counter_delta(b, a_reset, "c") == 30
    assert slo.counter_delta(None, a, "c") == 150
    assert slo.counter_delta(b, _snap(10, {}), "c") == 0


def test_histogram_delta_and_reset():
    le = (1, 10, 100)
    b = _snap(0, histograms={"h": _hist(le, [5, 3, 0, 0])})
    a = _snap(10, histograms={"h": _hist(le, [8, 4, 1, 0])})
    d = slo.histogram_delta(b, a, "h")
    assert d["counts"] == [3, 1, 1, 0]
    # Reset: any negative bucket falls back to the after-histogram.
    a_reset = _snap(10, histograms={"h": _hist(le, [2, 0, 0, 0])})
    d = slo.histogram_delta(b, a_reset, "h")
    assert d["counts"] == [2, 0, 0, 0]
    assert slo.histogram_delta(b, _snap(10), "h") is None


def test_histogram_quantile_interpolation():
    h = _hist((10, 20, 40), [0, 100, 0, 0])  # all mass in (10, 20]
    assert slo.histogram_quantile(h, 0.5) == pytest.approx(15.0)
    assert slo.histogram_quantile(h, 0.99) == pytest.approx(19.9)
    # Overflow bucket resolves to the last edge (conservative).
    h = _hist((10, 20), [0, 0, 5])
    assert slo.histogram_quantile(h, 0.99) == 20
    assert slo.histogram_quantile(_hist((10,), [0, 0]), 0.5) is None


def test_windows_empty_single_and_sliding():
    assert slo.windows([], 30.0) == []
    s0 = _snap(0)
    assert slo.windows([s0], 30.0) == [(None, s0)]  # cumulative-from-zero
    snaps = [_snap(t) for t in (0, 10, 20, 30, 40)]
    wins = slo.windows(snaps, 30.0)
    assert len(wins) == 4  # one per snapshot past the first
    # The last window spans [10, 40] (>= 30 s back), the second [0, 10]
    # (clamped to the stream head during warm-up).
    assert wins[-1][0]["ts"] == 10 and wins[-1][1]["ts"] == 40
    assert wins[0][0]["ts"] == 0 and wins[0][1]["ts"] == 10


# -- evaluation --------------------------------------------------------------


def test_evaluate_empty_stream_fails_closed():
    verdict = slo.evaluate([], slo.default_slos())
    assert verdict["ok"] is False
    assert verdict["reason"] == "no snapshots"


def test_evaluate_single_snapshot_uses_cumulative_window():
    snap = _snap(
        100,
        counters={"consensus.timeouts_fired": 1,
                  "consensus.rounds_advanced": 100},
    )
    specs = [
        slo.SloSpec(
            "timeouts_per_round", "ratio", "consensus.timeouts_fired",
            per="consensus.rounds_advanced", max=0.5,
        )
    ]
    verdict = slo.evaluate([snap], specs)
    assert verdict["ok"] is True
    assert verdict["slos"][0]["windows"] == 1
    assert verdict["slos"][0]["worst"] == pytest.approx(0.01)


def test_evaluate_ms_per_round_flags_stall():
    snaps = [
        _snap(0, {"consensus.rounds_advanced": 10}),
        _snap(10, {"consensus.rounds_advanced": 110}),  # 100 ms/round: ok
        _snap(20, {"consensus.rounds_advanced": 110}),  # stall: inf
    ]
    specs = [
        slo.SloSpec(
            "ms_per_round", "ms_per_count",
            "consensus.rounds_advanced", max=500.0,
        )
    ]
    verdict = slo.evaluate(snaps, specs, window_s=5.0)
    res = verdict["slos"][0]
    assert res["windows"] == 2
    assert res["violated_windows"] == 1
    assert res["worst"] == "inf"
    assert verdict["ok"] is False
    # A bounded tolerated degradation fraction flips it green.
    specs[0].allow_violation_fraction = 0.5
    assert slo.evaluate(snaps, specs, window_s=5.0)["ok"] is True


def test_evaluate_counter_reset_is_not_a_violation():
    # A node restart resets the counter; the reset-aware delta keeps the
    # window positive and the rate sane.
    snaps = [
        _snap(0, {"consensus.rounds_advanced": 500}),
        _snap(10, {"consensus.rounds_advanced": 40}),  # restarted
    ]
    specs = [
        slo.SloSpec(
            "ms_per_round", "ms_per_count",
            "consensus.rounds_advanced", max=500.0,
        )
    ]
    verdict = slo.evaluate(snaps, specs, window_s=5.0)
    assert verdict["ok"] is True
    assert verdict["slos"][0]["worst"] == pytest.approx(250.0)


def test_evaluate_quantile_and_gauge():
    hist = _hist((100, 500, 1000), [90, 10, 0, 0])
    snaps = [
        _snap(0, histograms={"consensus.commit_latency_ms": _hist(
            (100, 500, 1000), [0, 0, 0, 0])}),
        _snap(
            30,
            histograms={"consensus.commit_latency_ms": hist},
            gauges={"mempool.tx_queue_depth": 120.0},
        ),
    ]
    specs = [
        slo.SloSpec(
            "p99", "quantile", "consensus.commit_latency_ms",
            q=0.99, max=450.0,
        ),
        slo.SloSpec(
            "queue", "gauge_max", "mempool.tx_queue_depth", max=100.0,
        ),
    ]
    verdict = slo.evaluate(snaps, specs, window_s=10.0)
    by_name = {r["spec"]["name"]: r for r in verdict["slos"]}
    # 90 of 100 observations ≤ 100 ms, the rest in (100, 500]: the
    # interpolated p99 is 100 + 400*(9/10) = 460 ms > the 450 budget.
    assert by_name["p99"]["ok"] is False
    assert by_name["p99"]["worst"] == pytest.approx(460.0)
    assert by_name["queue"]["ok"] is False
    assert by_name["queue"]["worst"] == 120.0


def test_metric_absent_is_not_a_violation():
    snaps = [_snap(0), _snap(30)]
    verdict = slo.evaluate(snaps, slo.default_slos(), window_s=10.0)
    # No metric ever appeared: every spec reports zero windows and the
    # verdict stays green (absence of a plane ≠ violation) — but the
    # stream itself carried windows, so ok is True.
    assert verdict["ok"] is True
    assert all(r["windows"] == 0 for r in verdict["slos"])


def test_evaluate_streams_aggregates_per_node():
    good = [
        _snap(0, {"consensus.rounds_advanced": 0}),
        _snap(10, {"consensus.rounds_advanced": 100}),
    ]
    stalled = [
        _snap(0, {"consensus.rounds_advanced": 0}),
        _snap(10, {"consensus.rounds_advanced": 0}),
    ]
    specs = [
        slo.SloSpec(
            "ms_per_round", "ms_per_count",
            "consensus.rounds_advanced", max=500.0,
        )
    ]
    verdict = slo.evaluate_streams(
        {"n0": good, "n1": stalled}, specs, window_s=5.0
    )
    assert verdict["ok"] is False  # a wedged straggler fails the cluster
    assert verdict["nodes"]["n0"]["ok"] is True
    assert verdict["nodes"]["n1"]["ok"] is False


def test_spec_validation_and_io(tmp_path):
    with pytest.raises(ValueError):
        slo.SloSpec("x", "nope", "m", max=1)
    with pytest.raises(ValueError):
        slo.SloSpec("x", "quantile", "m", q=1.5, max=1)
    with pytest.raises(ValueError):
        slo.SloSpec("x", "ratio", "m", max=1)  # missing per
    with pytest.raises(ValueError):
        slo.SloSpec("x", "rate", "m")  # no threshold
    import json

    specs = slo.default_slos()
    path = tmp_path / "slos.json"
    path.write_text(json.dumps([s.to_dict() for s in specs]))
    loaded = slo.load_specs(str(path))
    assert [s.to_dict() for s in loaded] == [s.to_dict() for s in specs]


# -- gauge_growth (memory-growth SLOs) ---------------------------------------


def test_gauge_growth_judges_per_second_slope():
    spec = slo.SloSpec(
        "rss_growth", "gauge_growth", "resource.rss_bytes", max=1_000.0
    )
    snaps = [
        _snap(0, gauges={"resource.rss_bytes": 100_000}),
        _snap(10, gauges={"resource.rss_bytes": 105_000}),  # 500 B/s: ok
        _snap(20, gauges={"resource.rss_bytes": 205_000}),  # 10 kB/s: bad
    ]
    verdict = slo.evaluate(snaps, [spec], window_s=5.0)
    assert verdict["ok"] is False
    result = verdict["slos"][0]
    assert result["windows"] == 2
    assert result["violated_windows"] == 1
    assert result["worst"] == pytest.approx(10_000.0)


def test_gauge_growth_negative_growth_passes():
    # Compaction/GC shrinks the gauge: a max bound never fires.
    spec = slo.SloSpec(
        "store_growth", "gauge_growth", "resource.store_bytes", max=100.0
    )
    snaps = [
        _snap(0, gauges={"resource.store_bytes": 1_000_000}),
        _snap(10, gauges={"resource.store_bytes": 200_000}),
    ]
    verdict = slo.evaluate(snaps, [spec], window_s=5.0)
    assert verdict["ok"] is True


def test_gauge_growth_absent_gauge_skips_windows():
    # A node without the resource collector: no data, not a violation.
    spec = slo.SloSpec(
        "rss_growth", "gauge_growth", "resource.rss_bytes", max=1.0
    )
    snaps = [_snap(0), _snap(10), _snap(20)]
    verdict = slo.evaluate(snaps, [spec], window_s=5.0)
    assert verdict["ok"] is True
    assert verdict["slos"][0]["windows"] == 0


def test_memory_slos_default_set():
    specs = slo.memory_slos()
    names = [s.name for s in specs]
    assert names == ["rss_growth_bytes_per_s", "store_growth_bytes_per_s"]
    assert all(s.kind == "gauge_growth" for s in specs)


def test_dataplane_slos_gate_depth_and_unresolved():
    specs = slo.dataplane_slos(worker_store_depth=100.0)
    assert [s.name for s in specs] == [
        "worker_store_depth", "resolver_unresolved",
        "digest_queue_growth_per_s",
    ]
    # Bounded depth + zero resolution timeouts: green.
    ok_snaps = [
        _snap(0, counters={"mempool.resolver.unresolved": 0},
              gauges={"mempool.worker.store_depth": 10}),
        _snap(10, counters={"mempool.resolver.unresolved": 0},
              gauges={"mempool.worker.store_depth": 40}),
    ]
    assert slo.evaluate(ok_snaps, specs, window_s=5.0)["ok"] is True
    # Depth breach: the back-pressure failure mode is flagged.
    deep = [
        _snap(0, gauges={"mempool.worker.store_depth": 10}),
        _snap(10, gauges={"mempool.worker.store_depth": 500}),
    ]
    verdict = slo.evaluate(deep, specs, window_s=5.0)
    assert verdict["ok"] is False
    assert verdict["slos"][0]["ok"] is False
    # A single resolution timeout is an availability violation.
    timeouts = [
        _snap(0, counters={"mempool.resolver.unresolved": 0}),
        _snap(10, counters={"mempool.resolver.unresolved": 1}),
    ]
    verdict = slo.evaluate(timeouts, specs, window_s=5.0)
    assert verdict["ok"] is False


def test_dataplane_slos_skip_when_plane_absent():
    specs = slo.dataplane_slos()
    snaps = [_snap(0), _snap(10)]
    verdict = slo.evaluate(snaps, specs, window_s=5.0)
    assert verdict["ok"] is True
    assert all(s["windows"] == 0 for s in verdict["slos"])
