"""Crash-fault liveness and teardown regressions.

The reference only exercises crash faults subtractively (the harness
doesn't boot the last f nodes, ``local.py:75-76``); these tests kill live
engines mid-run — the regime that exposed three real bugs in round 4:

1. ``Receiver.shutdown`` hung forever in Python 3.12's
   ``Server.wait_closed()`` when a connection handler was parked in
   ``dispatch`` (e.g. awaiting a queue whose consumer was cancelled).
2. Timeout retransmissions were re-verified (full high_qc batch
   verification) before being dropped as duplicates, so committee-scale
   view changes saturated the core in redundant crypto and ground for
   many timer periods per round ("timeout grind").
3. Every node's timeout carries the same high_qc and every TC-former
   broadcasts the TC: without a verified-certificate cache each arrival
   paid the full batch verification again.
"""

import asyncio
import time

import pytest

from hotstuff_tpu.consensus import Consensus, Parameters
from hotstuff_tpu.consensus.messages import QC, Block, CertificateCache, Timeout
from hotstuff_tpu.crypto import Signature, SignatureService
from hotstuff_tpu.network import MessageHandler
from hotstuff_tpu.network.receiver import Receiver, write_frame
from hotstuff_tpu.store import Store

from .common import async_test, consensus_committee, keys

BASE = 14500


async def _spawn_committee(n: int, base_port: int, timeout_delay: int):
    committee = consensus_committee(base_port, n)
    engines, counts, aux = [], [0] * n, []
    for j, (pk, sk) in enumerate(keys(n)):
        rx_mempool: asyncio.Queue = asyncio.Queue()
        tx_mempool: asyncio.Queue = asyncio.Queue()
        tx_commit: asyncio.Queue = asyncio.Queue()

        async def drain(q=tx_mempool):
            while True:
                await q.get()

        async def count(jj=j, q=tx_commit):
            while True:
                await q.get()
                counts[jj] += 1

        aux.append(asyncio.create_task(drain()))
        aux.append(asyncio.create_task(count()))
        engines.append(
            await Consensus.spawn(
                pk,
                committee,
                Parameters(
                    timeout_delay=timeout_delay, batch_vote_verification=True
                ),
                SignatureService(sk),
                Store(),
                rx_mempool,
                tx_mempool,
                tx_commit,
            )
        )
    return engines, counts, aux


def _crash(engine) -> None:
    """Kill an engine the unclean way — cancel its tasks and yank its
    listeners — modeling a process crash, not a graceful shutdown."""
    for t in engine.tasks:
        t.cancel()
    for r in engine.receivers:
        r._server.close()
        for w in list(r._writers):
            w.transport.abort()


@async_test(timeout=90)
async def test_crash_faulted_committee_keeps_committing():
    """Kill f of N mid-run: the surviving 2f+1 must keep committing.
    Before the round-4 fixes this ground to a halt (timeout waves cost
    more crypto than a timer period at scale; dead-leader rounds never
    cleared)."""
    n, f = 10, 3
    engines, counts, aux = await _spawn_committee(n, BASE, timeout_delay=1_000)
    try:
        # Let it commit healthy first.
        for _ in range(200):
            await asyncio.sleep(0.1)
            if min(counts) >= 3:
                break
        assert min(counts) >= 3, f"healthy committee failed to commit: {counts}"

        for e in engines[:f]:
            _crash(e)

        live = counts[f:]
        before = list(live)
        # Survivors must produce NEW commits: allow several view changes
        # (3 dead leaders per 10-round rotation at 1 s timeout).
        deadline = asyncio.get_running_loop().time() + 45
        while asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.5)
            if all(c >= b + 3 for c, b in zip(counts[f:], before)):
                break
        assert all(
            c >= b + 3 for c, b in zip(counts[f:], before)
        ), f"survivors stalled after crash-fault: before={before} after={counts[f:]}"
    finally:
        for e in engines[f:]:
            await asyncio.wait_for(e.shutdown(), 10)
        for t in aux:
            t.cancel()


@async_test
async def test_receiver_shutdown_completes_with_blocked_handler():
    """Python 3.12 ``Server.wait_closed()`` waits for every connection
    handler; a handler parked in dispatch must not wedge shutdown."""
    port = BASE + 40
    gate: asyncio.Future = asyncio.get_running_loop().create_future()

    class Block_(MessageHandler):
        async def dispatch(self, writer, message):
            await gate  # never resolved — models a dead consumer

    receiver = await Receiver.spawn(("127.0.0.1", port), Block_())
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    write_frame(writer, b"stuck")
    await writer.drain()
    await asyncio.sleep(0.2)  # let dispatch park on the gate
    await asyncio.wait_for(receiver.shutdown(), 10)
    writer.close()


@async_test
async def test_timeout_duplicate_dropped_before_verification():
    """Timers retransmit timeouts every timeout_delay; a retransmission
    whose author already holds a seat must be dropped BEFORE paying the
    signature verification (the high_qc batch verify per arrival is what
    saturated committee-scale view changes)."""
    from hotstuff_tpu.consensus.core import Core
    from hotstuff_tpu.consensus.leader import RRLeaderElector

    kl = keys(4)
    committee = consensus_committee(BASE + 60)
    pk, sk = kl[0]
    core = Core.__new__(Core)  # state-only instance: no tasks
    core.name = pk
    core.committee = committee
    core.round = 5
    from hotstuff_tpu.consensus.aggregator import Aggregator

    core.aggregator = Aggregator(committee)
    core.leader_elector = RRLeaderElector(committee)
    core._cert_cache = CertificateCache()
    core.high_qc = QC.genesis()

    timeout = Timeout.new_from_key(QC.genesis(), 5, kl[1][0], kl[1][1])
    calls = 0
    orig = Timeout.verify

    def counting_verify(self, committee_, cache=None):
        nonlocal calls
        calls += 1
        return orig(self, committee_, cache)

    Timeout.verify = counting_verify
    try:
        await Core.handle_timeout(core, timeout)
        assert calls == 1
        await Core.handle_timeout(core, timeout)  # retransmission
        assert calls == 1, "duplicate timeout was re-verified"
    finally:
        Timeout.verify = orig


@async_test
async def test_timeout_amplification_rejoins_higher_round():
    """Timeout-sync regression (faultline chaos seed 11): a lost TC
    broadcast can split the committee across adjacent rounds — two nodes
    timing out at r, two at r+1 — where no round can ever gather 2f+1
    same-round timeouts again (permanent wedge). On seeing f+1 distinct
    timeouts for a round ahead of ours, the core must JOIN that view
    change: broadcast its own timeout for that round and seat it, so the
    TC forms and every node re-converges."""
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.core import Core
    from hotstuff_tpu.consensus.leader import RRLeaderElector
    from hotstuff_tpu.consensus.timer import Timer

    kl = keys(4)
    committee = consensus_committee(BASE + 100)
    pk, sk = kl[0]

    class _SpySender:
        def __init__(self):
            self.broadcasts = []

        def broadcast(self, addresses, data):
            self.broadcasts.append(data)

        def send(self, address, data):
            pass

    core = Core.__new__(Core)  # state-only instance: no tasks
    core.name = pk
    core.committee = committee
    core.round = 5
    core.last_voted_round = 4
    core.last_committed_round = 0
    core.persist_sync = False
    core.high_qc = QC.genesis()
    core.aggregator = Aggregator(committee)
    core.leader_elector = RRLeaderElector(committee)
    core._cert_cache = CertificateCache()
    core._amplified = set()
    core._bad_sigs = {}
    core._verified_seats = {}
    core.signature_service = SignatureService(sk)
    core.store = Store()
    core.timer = Timer(60_000)
    core.network = _SpySender()
    core.tx_proposer = asyncio.Queue()
    core._on_round_advance = None

    # One peer ahead at round 7: below f+1, no amplification.
    t1 = Timeout.new_from_key(QC.genesis(), 7, kl[1][0], kl[1][1])
    await Core.handle_timeout(core, t1)
    assert core.round == 5 and 7 not in core._amplified

    # Second distinct peer reaches f+1 = 2: the core must amplify —
    # sign its own round-7 timeout (persisted first), broadcast it, and
    # seat it, which completes the 2f+1 TC and advances the round.
    t2 = Timeout.new_from_key(QC.genesis(), 7, kl[2][0], kl[2][1])
    await Core.handle_timeout(core, t2)
    assert core.last_voted_round == 7  # never votes below the joined round
    assert core.round == 8, "TC(7) should have formed and advanced the round"
    from hotstuff_tpu.consensus.messages import TAG_TC, TAG_TIMEOUT

    tags = [b[0] for b in core.network.broadcasts]
    assert TAG_TIMEOUT in tags and TAG_TC in tags

    # Retransmissions must not re-amplify (one own timeout per round).
    n_broadcasts = len(core.network.broadcasts)
    t2b = Timeout.new_from_key(QC.genesis(), 9, kl[2][0], kl[2][1])
    await Core.handle_timeout(core, t2b)
    t2c = Timeout.new_from_key(QC.genesis(), 9, kl[2][0], kl[2][1])
    await Core.handle_timeout(core, t2c)  # same author again: no f+1
    assert 9 not in core._amplified
    assert len(core.network.broadcasts) == n_broadcasts


def test_certificate_cache_skips_byte_identical_and_only_those(monkeypatch):
    """A byte-identical QC that verified once skips re-verification; any
    tampered variant misses the cache and fails from scratch.

    The process-wide cert arena is disabled here: it deliberately
    memoizes byte-identical certs ACROSS caches (its whole point), which
    would hide the per-node CertificateCache contract this test pins."""
    from hotstuff_tpu.consensus import cert_arena

    monkeypatch.setenv("HOTSTUFF_CERT_ARENA", "0")
    cert_arena.reset()
    kl = keys(4)
    committee = consensus_committee(BASE + 80)
    block_digest = Block.genesis().digest()
    qc = QC(hash=block_digest, round=1, votes=[])
    qc.votes = [(pk, Signature.new(qc.digest(), sk)) for pk, sk in kl]

    cache = CertificateCache()
    calls = 0
    orig = Signature.verify_batch

    def counting_batch(digest, votes):
        nonlocal calls
        calls += 1
        return orig(digest, votes)

    Signature.verify_batch = staticmethod(counting_batch)
    try:
        qc.verify(committee, cache)
        assert calls == 1
        qc.verify(committee, cache)  # rebroadcast copy: cache hit
        assert calls == 1
        qc.verify(committee)  # no cache: verified again
        assert calls == 2

        # Tampered variant (flip one signature byte): cache miss + reject.
        bad = QC(hash=qc.hash, round=qc.round, votes=list(qc.votes))
        pk0, sig0 = bad.votes[0]
        raw = bytearray(sig0.data)
        raw[0] ^= 1
        bad.votes[0] = (pk0, Signature(bytes(raw)))
        with pytest.raises(Exception):
            bad.verify(committee, cache)
        assert calls == 3
    finally:
        Signature.verify_batch = staticmethod(orig)


@pytest.mark.slow
@async_test(timeout=240)
async def test_crash_fault_avalanche_regression_n40():
    """The committee-scale reproduction of the round-4 'timeout grind':
    kill 7 of 40 and require sustained commit progress. Pre-fix, timeout
    waves (~N² high_qc batch verifies per wave, re-verified on every
    retransmission) saturated the core and commits stopped for minutes."""
    n, k = 40, 7
    engines, counts, aux = await _spawn_committee(
        n, BASE + 120, timeout_delay=5_000
    )
    try:
        for _ in range(400):
            await asyncio.sleep(0.1)
            if min(counts) >= 2:
                break
        assert min(counts) >= 2, "healthy committee failed to commit"
        for e in engines[:k]:
            _crash(e)
        before = list(counts[k:])
        deadline = asyncio.get_running_loop().time() + 120
        while asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(1)
            if all(c >= b + 5 for c, b in zip(counts[k:], before)):
                break
        assert all(
            c >= b + 5 for c, b in zip(counts[k:], before)
        ), f"avalanche regression: survivors stalled ({before} -> {counts[k:]})"
    finally:
        for e in engines[k:]:
            await asyncio.wait_for(e.shutdown(), 15)
        for t in aux:
            t.cancel()


def test_certificate_cache_concurrent_hit_add():
    """hit() on the event loop races add()/hit() in the crypto executor
    (QC/TC.verify offload); with a tiny cap forcing constant eviction,
    the unlocked OrderedDict raised KeyError from check-then-move_to_end.
    Regression for advisor finding r4 (messages.py CertificateCache)."""
    import threading

    cache = CertificateCache(cap=4)
    keys_ = [bytes([i]) * 8 for i in range(64)]
    errors: list[BaseException] = []
    stop = threading.Event()

    def churn(offset: int) -> None:
        try:
            i = offset
            while not stop.is_set():
                k = keys_[i % len(keys_)]
                if not cache.hit(k):
                    cache.add(k)
                i += 1
        except BaseException as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=churn, args=(o,)) for o in range(4)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(cache._seen) <= cache.cap
