"""Native C++ store engine tests: interface parity with LogEngine,
cross-engine on-disk compatibility, torn-tail replay, and the Store actor
running on top of it."""

import os

import pytest

try:
    from hotstuff_tpu.store.native import NativeEngine, _ensure_built

    _ensure_built()
    HAVE_NATIVE = True
except Exception:  # toolchain unavailable
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(not HAVE_NATIVE, reason="g++ unavailable")

from hotstuff_tpu.store import LogEngine, Store  # noqa: E402

from .common import async_test  # noqa: E402


def test_put_get_roundtrip(tmp_path):
    eng = NativeEngine(str(tmp_path / "db"))
    assert eng.get(b"missing") is None
    eng.put(b"k", b"v1")
    eng.put(b"k2", b"x" * 100_000)
    eng.put(b"k", b"v2")  # overwrite
    assert eng.get(b"k") == b"v2"
    assert eng.get(b"k2") == b"x" * 100_000
    eng.close()


def test_persistence_across_reopen(tmp_path):
    path = str(tmp_path / "db")
    eng = NativeEngine(path)
    eng.put(b"a", b"1")
    eng.put(b"b", bytes(range(256)))
    eng.close()
    eng2 = NativeEngine(path)
    assert eng2.get(b"a") == b"1"
    assert eng2.get(b"b") == bytes(range(256))
    eng2.close()


def test_cross_engine_disk_compat(tmp_path):
    """Python LogEngine and the C++ engine share the on-disk format."""
    path = str(tmp_path / "db")
    py = LogEngine(path)
    py.put(b"from-python", b"hello")
    py.close()
    nat = NativeEngine(path)
    assert nat.get(b"from-python") == b"hello"
    nat.put(b"from-native", b"world")
    nat.close()
    py2 = LogEngine(path)
    assert py2.get(b"from-native") == b"world"
    assert py2.get(b"from-python") == b"hello"
    py2.close()


def test_torn_tail_replay(tmp_path):
    path = str(tmp_path / "db")
    eng = NativeEngine(path)
    eng.put(b"good", b"value")
    eng.close()
    with open(os.path.join(path, "store.log"), "ab") as f:
        f.write(b"\x10\x00\x00\x00\x10\x00")  # half a header + garbage
    eng2 = NativeEngine(path)
    assert eng2.get(b"good") == b"value"
    eng2.close()


def test_torn_tail_double_restart(tmp_path):
    """Crash -> restart -> write -> restart keeps the post-crash write
    (replay truncates the torn tail before reopening for append)."""
    path = str(tmp_path / "db")
    eng = NativeEngine(path)
    eng.put(b"good", b"value")
    eng.close()
    with open(os.path.join(path, "store.log"), "ab") as f:
        f.write(b"\x10\x00\x00\x00\x10\x00")
    eng2 = NativeEngine(path)
    eng2.put(b"after-crash", b"kept")
    eng2.close()
    eng3 = NativeEngine(path)
    assert eng3.get(b"good") == b"value"
    assert eng3.get(b"after-crash") == b"kept"
    # No garbage keys: exactly the two real records survived.
    assert eng3._lib.hs_store_size(eng3._handle) == 2
    eng3.close()


def test_torn_tail_huge_length_header(tmp_path):
    """A torn header decoding to multi-GB lengths must be truncated, not
    attempted as an allocation (bad_alloc across the C ABI aborts)."""
    path = str(tmp_path / "db")
    eng = NativeEngine(path)
    eng.put(b"good", b"value")
    eng.close()
    with open(os.path.join(path, "store.log"), "ab") as f:
        f.write(b"\xff\xff\xff\xff\xff\xff\xff\xff tail")  # klen=vlen=4GiB-1
    eng2 = NativeEngine(path)
    assert eng2.get(b"good") == b"value"
    eng2.put(b"after", b"kept")
    eng2.close()
    eng3 = NativeEngine(path)
    assert eng3.get(b"after") == b"kept"
    assert eng3._lib.hs_store_size(eng3._handle) == 2
    eng3.close()


def test_meta_records(tmp_path):
    eng = NativeEngine(str(tmp_path / "db"))
    assert eng.get_meta(b"state") is None
    eng.put_meta(b"state", b"round=5", sync=True)
    eng.put_meta(b"state", b"round=6")
    assert eng.get_meta(b"state") == b"round=6"
    eng.close()


@async_test
async def test_store_actor_on_native_engine(tmp_path):
    store = Store(engine=NativeEngine(str(tmp_path / "db")))
    await store.write(b"k", b"v")
    assert await store.read(b"k") == b"v"
    import asyncio

    waiter = asyncio.create_task(store.notify_read(b"pending"))
    await asyncio.sleep(0.01)
    await store.write(b"pending", b"arrived")
    assert await waiter == b"arrived"
    store.close()


def test_default_engine_prefers_native(tmp_path):
    """Store(path) picks the native engine when the toolchain exists."""
    store = Store(str(tmp_path / "db"))
    assert type(store._engine).__name__ == "NativeEngine"
    store.close()
