"""Resource-observability tests: RSS / on-disk size probes and the
snapshot-gauge collector the memory-growth SLOs read."""

from __future__ import annotations

import pytest

from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry import resources


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


def test_rss_bytes_is_positive():
    rss = resources.rss_bytes()
    assert rss is not None and rss > 1024 * 1024  # a CPython process


def test_dir_bytes_counts_recursively(tmp_path):
    (tmp_path / "a").write_bytes(b"x" * 100)
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "b").write_bytes(b"y" * 50)
    assert resources.dir_bytes(str(tmp_path)) == 150
    assert resources.dir_bytes(str(tmp_path / "missing")) == 0


def test_collector_surfaces_gauges_in_snapshots(tmp_path):
    (tmp_path / "wal").write_bytes(b"z" * 4096)
    telemetry.enable()
    resources.install(store_path=str(tmp_path), tracemalloc_on=False)
    snap = telemetry.get_registry().snapshot()
    assert snap["gauges"]["resource.rss_bytes"] > 0
    assert snap["gauges"]["resource.store_bytes"] == 4096
    assert snap["gauges"]["resource.open_fds"] > 0


def test_install_without_store_path_omits_store_gauge():
    telemetry.enable()
    resources.install(tracemalloc_on=False)
    snap = telemetry.get_registry().snapshot()
    assert "resource.rss_bytes" in snap["gauges"]
    assert "resource.store_bytes" not in snap["gauges"]


def test_tracemalloc_gauges_when_enabled():
    telemetry.enable()
    resources.install(tracemalloc_on=True)
    try:
        blob = [bytearray(64 * 1024) for _ in range(8)]  # noqa: F841
        snap = telemetry.get_registry().snapshot()
        assert snap["gauges"]["resource.tracemalloc_total_bytes"] > 0
        assert "resource.tracemalloc_top_growth_bytes" in snap["gauges"]
        # Second poll sees growth bounded by what we allocated since.
        blob.extend(bytearray(128 * 1024) for _ in range(4))
        snap2 = telemetry.get_registry().snapshot()
        assert (
            snap2["gauges"]["resource.tracemalloc_total_bytes"]
            > snap["gauges"]["resource.tracemalloc_total_bytes"]
        )
    finally:
        import tracemalloc

        tracemalloc.stop()
