"""Message/QC/TC verification tests incl. adversarial cases — modeled on
reference ``consensus/src/tests/messages_tests.rs:8-55`` and
``aggregator_tests.rs:5-56``."""

import pytest

from hotstuff_tpu.consensus import errors
from hotstuff_tpu.consensus.aggregator import Aggregator
from hotstuff_tpu.consensus.messages import (
    QC,
    TC,
    Block,
    Timeout,
    Vote,
    decode_message,
    encode_propose,
    encode_sync_request,
    encode_tc,
    encode_timeout,
    encode_vote,
)
from hotstuff_tpu.crypto import Signature, generate_keypair, sha512_digest

from .common import chain, consensus_committee, keys, qc_vote_digest

BASE = 13000


def make_qc(committee=None, n_votes=4):
    blocks = chain(1)
    block = blocks[0]
    votes = [
        (pk, Signature.new(qc_vote_digest(block.digest(), 1), sk))
        for pk, sk in keys()[:n_votes]
    ]
    return QC(hash=block.digest(), round=1, votes=votes)


def test_verify_valid_qc():
    make_qc().verify(consensus_committee(BASE))  # must not raise


def test_qc_authority_reuse():
    qc = make_qc()
    qc.votes[1] = qc.votes[0]
    with pytest.raises(errors.AuthorityReuse):
        qc.verify(consensus_committee(BASE))


def test_qc_unknown_authority():
    qc = make_qc()
    stranger_pk, stranger_sk = generate_keypair(seed=b"\x42" * 32)
    qc.votes[0] = (stranger_pk, qc.votes[0][1])
    with pytest.raises(errors.UnknownAuthority):
        qc.verify(consensus_committee(BASE))


def test_qc_insufficient_stake():
    qc = make_qc(n_votes=2)  # 2 < 2f+1 = 3
    with pytest.raises(errors.QCRequiresQuorum):
        qc.verify(consensus_committee(BASE))


def test_qc_bad_signature():
    qc = make_qc()
    pk0, _ = keys()[0]
    qc.votes[0] = (pk0, Signature(bytes(64)))
    with pytest.raises(errors.InvalidSignature):
        qc.verify(consensus_committee(BASE))


def test_verify_valid_block():
    blocks = chain(2)
    blocks[1].verify(consensus_committee(BASE))  # block 2 embeds a real QC


def test_block_wrong_signature():
    blocks = chain(2)
    blocks[1].signature = Signature(bytes(64))
    with pytest.raises(errors.InvalidSignature):
        blocks[1].verify(consensus_committee(BASE))


def test_valid_tc():
    committee = consensus_committee(BASE)
    import struct

    votes = []
    for pk, sk in keys()[:3]:
        digest = sha512_digest(struct.pack("<Q", 5), struct.pack("<Q", 2))
        votes.append((pk, Signature.new(digest, sk), 2))
    tc = TC(round=5, votes=votes)
    tc.verify(committee)
    assert tc.high_qc_rounds() == [2, 2, 2]


def test_tc_insufficient_stake():
    import struct

    votes = []
    for pk, sk in keys()[:2]:
        digest = sha512_digest(struct.pack("<Q", 5), struct.pack("<Q", 2))
        votes.append((pk, Signature.new(digest, sk), 2))
    with pytest.raises(errors.TCRequiresQuorum):
        TC(round=5, votes=votes).verify(consensus_committee(BASE))


def test_timeout_roundtrip_and_verify():
    committee = consensus_committee(BASE)
    pk, sk = keys()[0]
    t = Timeout.new_from_key(QC.genesis(), 3, pk, sk)
    t.verify(committee)
    kind, decoded = decode_message(encode_timeout(t))
    assert kind == "timeout"
    assert decoded.round == 3 and decoded.author == pk
    decoded.verify(committee)


def test_wire_roundtrips():
    blocks = chain(3)
    kind, b = decode_message(encode_propose(blocks[2]))
    assert kind == "propose" and b.digest() == blocks[2].digest()
    assert b.qc.votes == blocks[2].qc.votes

    pk, sk = keys()[0]
    vote = Vote.new_from_key(blocks[0].digest(), 1, pk, sk)
    kind, v = decode_message(encode_vote(vote))
    assert kind == "vote" and v.digest() == vote.digest()
    assert v.signature == vote.signature

    tc = TC(round=7, votes=[(pk, Signature.new(sha512_digest(b"x"), sk), 3)])
    kind, t = decode_message(encode_tc(tc))
    assert kind == "tc" and t.round == 7 and t.votes == tc.votes

    d = sha512_digest(b"blk")
    kind, (digest, origin) = decode_message(encode_sync_request(d, pk))
    assert kind == "sync_request" and digest == d and origin == pk


def test_block_store_roundtrip():
    blocks = chain(2)
    data = blocks[1].serialize()
    restored = Block.deserialize(data)
    assert restored.digest() == blocks[1].digest()
    assert restored.qc == blocks[1].qc
    assert restored.signature == blocks[1].signature


def test_genesis_identities():
    g = Block.genesis()
    assert g.round == 0 and g.qc == QC.genesis() and g.payload == []


# ---------------------------------------------------------------------------
# Aggregator
# ---------------------------------------------------------------------------


def test_aggregator_makes_qc_at_quorum():
    committee = consensus_committee(BASE)
    agg = Aggregator(committee)
    block = chain(1)[0]
    votes = [
        Vote.new_from_key(block.digest(), 1, pk, sk) for pk, sk in keys()
    ]
    assert agg.add_vote(votes[0]) is None
    assert agg.add_vote(votes[1]) is None
    qc = agg.add_vote(votes[2])
    assert qc is not None and qc.round == 1 and len(qc.votes) == 3
    qc.verify(committee)
    # The fourth vote does NOT produce a second QC.
    assert agg.add_vote(votes[3]) is None


def test_aggregator_rejects_authority_reuse():
    agg = Aggregator(consensus_committee(BASE))
    block = chain(1)[0]
    pk, sk = keys()[0]
    vote = Vote.new_from_key(block.digest(), 1, pk, sk)
    agg.add_vote(vote)
    with pytest.raises(errors.AuthorityReuse):
        agg.add_vote(vote)


def test_aggregator_timeouts_make_tc():
    committee = consensus_committee(BASE)
    agg = Aggregator(committee)
    touts = [
        Timeout.new_from_key(QC.genesis(), 4, pk, sk) for pk, sk in keys()
    ]
    assert agg.add_timeout(touts[0]) is None
    assert agg.add_timeout(touts[1]) is None
    tc = agg.add_timeout(touts[2])
    assert tc is not None and tc.round == 4
    tc.verify(committee)


def test_aggregator_cleanup():
    agg = Aggregator(consensus_committee(BASE))
    block = chain(1)[0]
    pk, sk = keys()[0]
    agg.add_vote(Vote.new_from_key(block.digest(), 1, pk, sk))
    assert agg.votes_aggregators
    agg.cleanup(2)
    assert not agg.votes_aggregators
