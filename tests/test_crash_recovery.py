"""Full-node crash/restart recovery: a node that dies mid-run must rejoin
from its persisted store, catch up via the sync protocols (block ancestry +
payload fetch, SURVEY §3.5), and resume committing — without equivocating
(voting state is persisted; the reference leaves this unsafe, issue #15)."""

import asyncio

from hotstuff_tpu.consensus import Parameters as CParams
from hotstuff_tpu.mempool import Parameters as MParams
from hotstuff_tpu.network.receiver import write_frame
from hotstuff_tpu.node import Node, Parameters

from .common import async_test, next_payload_commit
from .test_node import _write_testbed

BASE = 16200


@async_test(timeout=170)
async def test_node_crash_restart_catches_up(tmp_path):
    committee_file, params_file, key_files = _write_testbed(tmp_path, BASE)
    # Faster cadence for the test.
    Parameters(
        CParams(timeout_delay=1_500),
        MParams(batch_size=200, max_batch_delay=30),
    ).write(params_file)

    async def boot(i):
        return await Node.new(
            committee_file,
            key_files[i],
            str(tmp_path / f"db_{i}"),
            parameters_file=params_file,
        )

    nodes = [await boot(i) for i in range(4)]

    _, writer = await asyncio.open_connection("127.0.0.1", BASE + 100)

    async def submit(tag: int):
        tx = b"\x01" + tag.to_bytes(8, "big") + b"\xcd" * 300
        write_frame(writer, tx)
        await writer.drain()
        return tx

    # Phase 1: all four commit a payload block.
    tx1 = await submit(1)
    blocks = await asyncio.wait_for(
        asyncio.gather(*[next_payload_commit(n) for n in nodes]), 30
    )
    assert len({b.digest() for b in blocks}) == 1

    # Phase 2: node 3 crashes (f=1 tolerated); the rest keep committing.
    await nodes[3].shutdown()
    await asyncio.sleep(0.1)
    await submit(2)
    blocks = await asyncio.wait_for(
        asyncio.gather(*[next_payload_commit(n) for n in nodes[:3]]), 30
    )
    assert len({b.digest() for b in blocks}) == 1
    survivor_round = blocks[0].round

    # Phase 3: node 3 restarts from its own store and must catch up to
    # payload commits at rounds at/beyond where it died. Commit
    # re-delivery of pre-crash blocks is legitimate (last_committed_round
    # persists on vote, not per commit) — drain past it.
    nodes[3] = await boot(3)
    await submit(3)

    async def catch_up():
        while True:
            b = await next_payload_commit(nodes[3])
            if b.round >= survivor_round:
                return b

    restarted_block = await asyncio.wait_for(catch_up(), 60)
    # Prefix consistency at the crash boundary: if the restarted node
    # re-committed the survivors' block at survivor_round, it must be
    # byte-identical to what the survivors committed in phase 2.
    if restarted_block.round == survivor_round:
        assert restarted_block.digest() == blocks[0].digest()

    # And the other nodes eventually commit the same block at the
    # restarted node's round (drain until there, compare when aligned).
    async def reach(node, round_):
        while True:
            b = await node.commit.get()
            if b.round >= round_:
                return b

    others = await asyncio.wait_for(
        asyncio.gather(*[reach(n, restarted_block.round) for n in nodes[:3]]), 60
    )
    for b in others:
        if b.round == restarted_block.round:
            assert b.digest() == restarted_block.digest()

    writer.close()
    for n in nodes:
        await n.shutdown()
