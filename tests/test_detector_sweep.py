"""Oracle's scoring loop: labeled schedules → streams → Watchtower →
scorecard. Pins the properties CI leans on — deterministic scoring,
honest incident labeling, and a committed tuned preset that round-trips
to the exact config hash the scorecard stamped.
"""

import json
import os

import pytest

from benchmark.detector_sweep import (
    MATCH_LEAD_S,
    MATCH_SLACK_S,
    PINNED_CLASSES,
    ScoreAccumulator,
    control_scenario,
    match_alerts,
    replay_config,
    run_schedule,
    single_fault_scenario,
)
from hotstuff_tpu.faultline.policy import chaos_scenario
from hotstuff_tpu.telemetry.watchtower import (
    DETECTOR_CATALOG_VERSION,
    WatchtowerConfig,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCORECARD = os.path.join(REPO, "results", "detector-scorecard-n4.json")
PRESET = os.path.join(
    REPO, "hotstuff_tpu", "telemetry", "presets", "tuned-n4.json"
)


def _score(config, specs):
    acc = ScoreAccumulator()
    for tag, is_control, scenario in specs:
        timeline, incidents, _ = run_schedule(scenario)
        alerts = replay_config(timeline, config)
        match_alerts(incidents, alerts)
        acc.add(tag, incidents, alerts, control=is_control)
    return acc


def _small_specs():
    specs = []
    for kind in ("crash", "byzantine:equivocate"):
        specs.append((f"single:{kind}:0", False, single_fault_scenario(kind, 0)))
    specs.append(("control:0", True, control_scenario(0)))
    return specs


def test_scoring_is_deterministic():
    """Same corpus, same config → identical report dict, twice. The
    committed scorecard's numbers are only meaningful if re-running the
    sweep cannot wobble them."""
    cfg = WatchtowerConfig()
    a = _score(cfg, _small_specs()).report()
    b = _score(cfg, _small_specs()).report()
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_single_fault_scenarios_are_pinned_and_isolated():
    """Every single-fault schedule must contribute exactly one pinned
    incident of its class — the recall-floor denominators CI gates on."""
    for kind in PINNED_CLASSES:
        scenario = single_fault_scenario(kind, 1)
        _, incidents, _ = run_schedule(scenario)
        pinned = [i for i in incidents if i.get("pinned")]
        assert len(pinned) == 1, (kind, incidents)
        assert pinned[0]["class"] == kind
        assert pinned[0]["until"] - pinned[0]["t"] >= 5.0


def test_match_window_attributes_alerts_to_incidents():
    """An alert matches an incident iff it accuses the victim with an
    expected detector inside [t - lead, until + slack] — pin the
    window edges so a silent widening can't inflate recall."""
    incidents = [{
        "class": "crash", "kind": "crash", "peer": "n001",
        "t": 10.0, "until": 17.0, "duration_s": 7.0, "pinned": True,
    }]
    inside = {
        "detector": "silent_voter", "accused": ["n001"],
        "ts": 10.0 - MATCH_LEAD_S, "confidence": 0.9,
    }
    outside = dict(inside, ts=17.0 + MATCH_SLACK_S + 0.1)
    wrong_peer = dict(inside, accused=["n002"])
    alerts = [dict(inside), dict(outside), dict(wrong_peer)]
    match_alerts(incidents, alerts)
    assert incidents[0]["detected"]
    assert alerts[0]["matched"]
    assert not alerts[1]["matched"]
    assert not alerts[2]["matched"]


def test_control_alerts_count_as_false_alarms():
    acc = ScoreAccumulator()
    acc.add("control:x", [], [
        {"detector": "laggard", "accused": ["n000"], "ts": 3.0,
         "confidence": 0.8, "matched": False},
    ], control=True)
    assert acc.control_alerts == 1
    assert not acc.feasible()


def test_chaos_schedule_yields_labeled_incidents():
    _, incidents, _ = run_schedule(chaos_scenario(seed=0, duration_s=11.0))
    assert len(incidents) >= 4
    kinds = {i["class"] for i in incidents}
    assert any(k.startswith("byzantine") for k in kinds)


@pytest.mark.skipif(
    not (os.path.exists(PRESET) and os.path.exists(SCORECARD)),
    reason="tuned preset / scorecard not committed yet",
)
def test_tuned_preset_round_trips_to_committed_hash():
    """`WatchtowerConfig.preset('tuned-n4')` must reconstruct exactly
    the config the sweep scored: fingerprint == the preset's own
    config_hash == the scorecard's tuned config_hash, at the same
    detector-catalog version."""
    cfg = WatchtowerConfig.preset("tuned-n4")
    with open(PRESET) as f:
        preset_doc = json.load(f)
    assert cfg.fingerprint() == preset_doc["config_hash"]
    assert preset_doc["detector_catalog"] == DETECTOR_CATALOG_VERSION
    with open(SCORECARD) as f:
        scorecard = json.load(f)
    assert scorecard["tuned"]["config_hash"] == preset_doc["config_hash"]
    assert scorecard["detector_catalog"] == DETECTOR_CATALOG_VERSION


@pytest.mark.skipif(
    not os.path.exists(SCORECARD),
    reason="scorecard not committed yet",
)
def test_committed_scorecard_meets_the_gate():
    """The committed numbers ARE the acceptance claim: tuned recall
    1.0 on pinned classes, zero control alerts, precision strictly
    above the default config's."""
    with open(SCORECARD) as f:
        scorecard = json.load(f)
    gate = scorecard["gate"]
    assert gate["ok"], gate
    assert gate["recall_pinned"] == 1.0
    assert gate["control_alerts"] == 0
    tuned_p, default_p = gate["precision_vs_default"]
    assert tuned_p > default_p
    assert scorecard["tuned"]["incidents"] >= 2000
