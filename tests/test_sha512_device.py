"""Device SHA-512 vs hashlib (bit-exactness property tests)."""

import hashlib
import random

import pytest

pytest.importorskip("jax")

pytestmark = pytest.mark.device

from hotstuff_tpu.ops.sha512 import sha512_32_batch, sha512_batch  # noqa: E402

rng = random.Random(99)


@pytest.mark.parametrize("length", [0, 1, 32, 96, 111, 112, 127, 128, 300])
def test_matches_hashlib(length):
    msgs = [rng.randbytes(length) for _ in range(4)]
    got = sha512_batch(msgs)
    for m, d in zip(msgs, got):
        assert d == hashlib.sha512(m).digest(), f"length {length}"


def test_protocol_digest_truncation():
    msgs = [b"batch-bytes" * 10] * 3
    got = sha512_32_batch(msgs)
    assert got[0] == hashlib.sha512(msgs[0]).digest()[:32]


def test_challenge_hash_shape():
    """The verifier's h = SHA512(R||A||M) input is 96 bytes — one block."""
    msgs = [rng.randbytes(96) for _ in range(8)]
    got = sha512_batch(msgs)
    assert all(
        d == hashlib.sha512(m).digest() for m, d in zip(msgs, got)
    )
