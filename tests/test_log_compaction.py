"""Log-compaction and MetaLog crash-hardening tests: atomic store.log
rewrite (Python and native engines), stale compaction temps, torn tails
written after the atomic-replace window, and the legacy per-key fallback
interacting with the snapshot record (ISSUE 16 satellite)."""

from __future__ import annotations

import asyncio
import os
import struct

import pytest

from hotstuff_tpu.store import _HDR, LogEngine, MemEngine, MetaLog, Store

from .common import async_test


def _fill(engine, n=50, vlen=64):
    for i in range(n):
        engine.put(b"k%04d" % i, bytes([i % 256]) * vlen)


# -- LogEngine.compact -------------------------------------------------------


def test_log_compact_drops_keys_and_reclaims_bytes(tmp_path):
    eng = LogEngine(str(tmp_path))
    _fill(eng)
    before = eng.size_bytes()
    freed = eng.compact([b"k%04d" % i for i in range(40)])
    assert freed > 0 and eng.size_bytes() == before - freed
    assert eng.get(b"k0000") is None and eng.get(b"k0045") is not None
    eng.close()


def test_log_compact_squeezes_superseded_duplicates(tmp_path):
    eng = LogEngine(str(tmp_path))
    for _ in range(10):
        eng.put(b"hot", b"x" * 100)  # 10 versions on disk, 1 live
    freed = eng.compact([])  # nothing dropped — duplicates alone shrink it
    assert freed > 0
    assert eng.get(b"hot") == b"x" * 100
    eng.close()


def test_log_phased_compaction_mirrors_concurrent_puts(tmp_path):
    # Regression: the rewrite runs off the event loop (Store.compact sends
    # compact_write to an executor), so puts can land while it is in
    # flight. They must be mirrored into the tmp file at commit or the
    # atomic replace silently discards records the index already holds.
    eng = LogEngine(str(tmp_path))
    _fill(eng, n=20)
    drop = [b"k%04d" % i for i in range(10)]
    state = eng.compact_begin(drop)
    assert state is not None
    assert eng.compact_begin(drop) is None  # one compaction at a time
    eng.put(b"mid", b"written-during-rewrite")
    assert eng.compact_write(state)
    eng.put(b"late", b"written-after-rewrite-before-commit")
    assert eng.compact_commit(state) > 0
    assert eng.get(b"mid") == b"written-during-rewrite"
    assert eng.get(b"late") == b"written-after-rewrite-before-commit"
    assert eng.get(b"k0003") is None and eng.get(b"k0015") is not None
    eng.close()
    # The mirrored records must be IN the swapped file, not only the index.
    eng2 = LogEngine(str(tmp_path))
    assert eng2.get(b"mid") == b"written-during-rewrite"
    assert eng2.get(b"late") == b"written-after-rewrite-before-commit"
    assert eng2.get(b"k0003") is None and eng2.get(b"k0015") is not None
    eng2.close()


def test_log_compact_commit_failure_restores_append_handle(tmp_path, monkeypatch):
    # Regression: a failed atomic swap used to leave the engine with a
    # closed append handle, poisoning every later put. The old log must
    # stay live and writable after the failure.
    eng = LogEngine(str(tmp_path))
    _fill(eng, n=10)
    state = eng.compact_begin([b"k0000"])
    assert eng.compact_write(state)
    monkeypatch.setattr(os, "replace", lambda src, dst: (_ for _ in ()).throw(OSError("no swap")))
    with pytest.raises(OSError):
        eng.compact_commit(state)
    monkeypatch.undo()
    eng.put(b"after-failure", b"v")
    assert eng.get(b"after-failure") == b"v"
    eng.close()
    eng2 = LogEngine(str(tmp_path))
    assert eng2.get(b"after-failure") == b"v"
    assert eng2.get(b"k0000") is not None  # old log survived whole
    eng2.close()


@async_test
async def test_store_compact_offloaded_with_concurrent_writes(tmp_path):
    # Store.compact runs the rewrite on the executor; a write racing it on
    # the loop must survive the swap.
    store = Store(engine=LogEngine(str(tmp_path)))
    for i in range(50):
        await store.write(b"k%04d" % i, bytes([i % 256]) * 64)
    assert store.compaction_offloaded()
    task = asyncio.create_task(store.compact([b"k%04d" % i for i in range(40)]))
    await asyncio.sleep(0)  # let the rewrite reach the executor
    await store.write(b"mid-compaction", b"v")
    assert await task > 0
    assert await store.read(b"mid-compaction") == b"v"
    assert await store.read(b"k0001") is None
    assert await store.read(b"k0045") is not None
    store.close()


def test_log_compact_survives_reopen(tmp_path):
    eng = LogEngine(str(tmp_path))
    _fill(eng, n=20)
    eng.compact([b"k%04d" % i for i in range(10)])
    eng.put(b"after", b"compaction")  # appends continue on the new log
    eng.close()
    eng2 = LogEngine(str(tmp_path))
    assert eng2.get(b"k0000") is None
    assert eng2.get(b"k0015") is not None
    assert eng2.get(b"after") == b"compaction"
    eng2.close()


def test_log_compact_unknown_keys_retained(tmp_path):
    eng = LogEngine(str(tmp_path))
    _fill(eng, n=5)
    eng.compact([b"not-present"])
    for i in range(5):
        assert eng.get(b"k%04d" % i) is not None
    eng.close()


def test_stale_compaction_tmp_discarded_on_open(tmp_path):
    eng = LogEngine(str(tmp_path))
    _fill(eng, n=5)
    eng.close()
    # Crash inside a compaction's write window: a partial tmp survives
    # beside the intact live log. It must be discarded, never adopted.
    tmp = os.path.join(str(tmp_path), "store.log.tmp")
    with open(tmp, "wb") as f:
        f.write(b"half a compaction")
    eng2 = LogEngine(str(tmp_path))
    assert not os.path.exists(tmp)
    for i in range(5):
        assert eng2.get(b"k%04d" % i) is not None
    eng2.close()


def test_native_engine_compact_parity(tmp_path):
    native = pytest.importorskip("hotstuff_tpu.store.native")
    try:
        eng = native.NativeEngine(str(tmp_path))
    except Exception:
        pytest.skip("native toolchain unavailable")
    _fill(eng, n=30)
    before = eng.size_bytes()
    freed = eng.compact([b"k%04d" % i for i in range(20)])
    assert freed > 0 and eng.size_bytes() < before
    assert eng.get(b"k0000") is None and eng.get(b"k0025") is not None
    eng.close()
    # Compacted log replays identically in the PYTHON engine: the two
    # engines stay interchangeable on disk across a truncation.
    pyeng = LogEngine(str(tmp_path))
    assert pyeng.get(b"k0000") is None and pyeng.get(b"k0025") is not None
    pyeng.close()


def test_native_engine_phased_compaction_mirrors_puts(tmp_path):
    native = pytest.importorskip("hotstuff_tpu.store.native")
    try:
        eng = native.NativeEngine(str(tmp_path))
    except Exception:
        pytest.skip("native toolchain unavailable")
    _fill(eng, n=20)
    drop = [b"k%04d" % i for i in range(10)]
    state = eng.compact_begin(drop)
    assert state is not None
    assert eng.compact_begin(drop) is None  # one compaction at a time
    eng.put(b"mid", b"written-during-rewrite")
    assert eng.compact_write(state)
    eng.put(b"late", b"written-after-rewrite-before-commit")
    assert eng.compact_commit(state) > 0
    eng.put(b"after", b"post-swap-append")  # handle restored by commit
    assert eng.get(b"mid") == b"written-during-rewrite"
    assert eng.get(b"k0003") is None and eng.get(b"k0015") is not None
    eng.close()
    # The mirrored records are IN the swapped file (replay via LogEngine:
    # same on-disk format, independent reader).
    pyeng = LogEngine(str(tmp_path))
    assert pyeng.get(b"mid") == b"written-during-rewrite"
    assert pyeng.get(b"late") == b"written-after-rewrite-before-commit"
    assert pyeng.get(b"after") == b"post-swap-append"
    assert pyeng.get(b"k0003") is None and pyeng.get(b"k0015") is not None
    pyeng.close()


def test_mem_engine_compact(tmp_path):
    eng = MemEngine()
    _fill(eng, n=10)
    assert eng.compact([b"k0000", b"missing"]) > 0
    assert eng.get(b"k0000") is None and eng.get(b"k0005") is not None


@async_test
async def test_store_compact_noop_without_engine_support():
    class Bare:
        def put(self, k, v): ...
        def get(self, k): return None
        def close(self): ...

    store = Store(engine=Bare())
    assert await store.compact([b"x"]) == 0


# -- MetaLog crash hardening -------------------------------------------------


def test_metalog_torn_tail_after_compaction_window(tmp_path):
    """A torn append landing AFTER an in-place compaction (atomic replace)
    must truncate cleanly on replay: the compacted prefix survives, the
    torn record is dropped, and subsequent appends parse."""
    ml = MetaLog(str(tmp_path))
    for i in range(8):
        ml.put(b"round", str(i).encode())
    ml.put(b"floor", b"42")
    ml._compact()  # in-place atomic replace: 2 live records remain
    ml.put(b"round", b"9")
    ml.close()
    path = os.path.join(str(tmp_path), "meta.log")
    # Crash mid-append: header promises more bytes than were written.
    with open(path, "ab") as f:
        f.write(_HDR.pack(5, 100) + b"tornk" + b"only-part")
    ml2 = MetaLog(str(tmp_path))
    assert ml2.get(b"round") == b"9"
    assert ml2.get(b"floor") == b"42"
    assert ml2.get(b"tornk") is None
    ml2.put(b"round", b"10")  # post-recovery appends must parse on replay
    ml2.close()
    ml3 = MetaLog(str(tmp_path))
    assert ml3.get(b"round") == b"10"
    ml3.close()


def test_metalog_stale_compaction_tmp_discarded(tmp_path):
    ml = MetaLog(str(tmp_path))
    ml.put(b"k", b"live")
    ml.close()
    tmp = os.path.join(str(tmp_path), "meta.log.tmp")
    with open(tmp, "wb") as f:
        f.write(_HDR.pack(1, 1) + b"kX")  # plausible but stale generation
    ml2 = MetaLog(str(tmp_path))
    assert not os.path.exists(tmp)
    assert ml2.get(b"k") == b"live"
    ml2.close()


def test_metalog_legacy_fallback_reads_snapshot_record(tmp_path):
    """A node restarted across the per-key-file -> MetaLog layout change
    must still see a snapshot record written by its previous life, and a
    new MetaLog put must shadow the legacy file from then on."""
    from hotstuff_tpu.consensus.statesync import SNAPSHOT_KEY

    legacy_value = b"snapshot-from-previous-layout"
    ml = MetaLog(str(tmp_path))
    legacy = ml._legacy_path(SNAPSHOT_KEY)
    ml.close()
    with open(legacy, "wb") as f:
        f.write(legacy_value)
    ml2 = MetaLog(str(tmp_path))
    assert ml2.get(SNAPSHOT_KEY) == legacy_value
    ml2.put(SNAPSHOT_KEY, b"new-layout-record")
    assert ml2.get(SNAPSHOT_KEY) == b"new-layout-record"
    ml2.close()
    ml3 = MetaLog(str(tmp_path))  # the shadow persists across reopen
    assert ml3.get(SNAPSHOT_KEY) == b"new-layout-record"
    ml3.close()


def test_metalog_torn_tail_with_legacy_fallback_present(tmp_path):
    """Torn tail recovery must not fall back to a STALE legacy record for
    a key whose live MetaLog record survived intact before the tear."""
    from hotstuff_tpu.consensus.statesync import SNAPSHOT_KEY

    ml = MetaLog(str(tmp_path))
    legacy = ml._legacy_path(SNAPSHOT_KEY)
    ml.put(SNAPSHOT_KEY, b"current")
    ml.close()
    with open(legacy, "wb") as f:
        f.write(b"ancient")
    path = os.path.join(str(tmp_path), "meta.log")
    with open(path, "ab") as f:
        f.write(_HDR.pack(3, 50) + b"abc")  # torn: value bytes missing
    ml2 = MetaLog(str(tmp_path))
    assert ml2.get(SNAPSHOT_KEY) == b"current"
    ml2.close()


def test_metalog_torn_header_alone(tmp_path):
    ml = MetaLog(str(tmp_path))
    ml.put(b"a", b"1")
    ml.close()
    path = os.path.join(str(tmp_path), "meta.log")
    with open(path, "ab") as f:
        f.write(struct.pack("<I", 7))  # half a header
    ml2 = MetaLog(str(tmp_path))
    assert ml2.get(b"a") == b"1"
    assert os.path.getsize(path) == _HDR.size + 2  # tear truncated away
    ml2.close()
