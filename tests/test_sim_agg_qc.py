"""Simulant model-check of the aggregate-QC verify rule.

Before the fused one-MSM certificate check is trusted on the real
planes, the deterministic simulation plane pins its acceptance set to
the per-signature oracle: over an exhaustive corruption model (every
seat, both signature halves, and individually-VALID signatures spliced
in from the wrong statement), the fused check must reject exactly the
certs the per-signature rule rejects — a cert that any seat's signature
fails must be caught. Under the sim plane's process-wide verdict memo,
fused dispatch falls back to exploded per-signature triples so the memo
keyspace stays unified across the structured and raw paths.
"""

import random

import pytest

from hotstuff_tpu import crypto
from hotstuff_tpu.crypto import (
    CpuBackend,
    CryptoError,
    backend_verify_cert,
    set_backend,
)
from hotstuff_tpu.crypto import ed25519_ref as ref
from hotstuff_tpu.crypto.cpu_batch import verify_cert_rlc
from hotstuff_tpu.crypto.native_ed25519 import native_available


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    monkeypatch.delenv("HOTSTUFF_AGG_QC", raising=False)
    crypto.enable_verify_memo(False)
    yield
    crypto.enable_verify_memo(False)
    set_backend("cpu")


def _cert(n, rng):
    seeds = [rng.randbytes(32) for _ in range(n)]
    pubs = [ref.secret_to_public(s) for s in seeds]
    msg = rng.randbytes(32)
    return msg, seeds, pubs, b"".join(ref.sign(s, msg) for s in seeds)


def _oracle(msg, pubs, sig_buf):
    """The per-signature rule the fused check must reproduce."""
    return all(
        ref.verify(pub, msg, sig_buf[i * 64 : (i + 1) * 64], strict=False)
        for i, pub in enumerate(pubs)
    )


def _fused_verdicts(msg, pubs, buf):
    """Every fused implementation's verdict on one cert."""
    verdicts = {"rlc": verify_cert_rlc(msg, pubs, buf)}
    if native_available():
        from hotstuff_tpu.crypto.native_ed25519 import verify_cert_native

        verdicts["native"] = verify_cert_native(msg, pubs, buf)
    return verdicts


def test_model_check_fused_rule_matches_per_signature_oracle():
    """Exhaustive single-seat corruption model over a 4-seat cert: for
    every mutation, every fused implementation agrees with the oracle —
    in particular, a cert containing ONE invalid signature is caught no
    matter which seat or which half of the signature is wrong."""
    rng = random.Random(201)
    msg, seeds, pubs, buf = _cert(4, rng)
    pub_bytes = [p for p in pubs]

    cases = [("valid", buf)]
    for seat in range(4):
        base = seat * 64
        for tag, pos in (("R", base + 3), ("s", base + 40)):
            b = bytearray(buf)
            b[pos] ^= 0x01
            cases.append((f"seat{seat}-{tag}", bytes(b)))
        # Individually-VALID signature of the WRONG statement spliced in:
        # passes no per-byte sanity check, only actual verification.
        alien = ref.sign(seeds[seat], rng.randbytes(32))
        b = bytearray(buf)
        b[base : base + 64] = alien
        cases.append((f"seat{seat}-alien", bytes(b)))
        # A neighbor's valid signature under seat's key: valid bytes,
        # wrong key binding.
        if seat:
            b = bytearray(buf)
            b[base : base + 64] = buf[:64]
            cases.append((f"seat{seat}-swapped", bytes(b)))

    for tag, candidate in cases:
        want = _oracle(msg, pub_bytes, candidate)
        assert want == (tag == "valid"), tag  # the model is well-formed
        for impl, got in _fused_verdicts(msg, pub_bytes, candidate).items():
            assert got == want, (tag, impl)


class CountingBackend(CpuBackend):
    def __init__(self):
        super().__init__()
        self.batch_calls = 0
        self.cert_calls = 0

    def verify_batch(self, msgs, pubs, sigs):
        self.batch_calls += 1
        super().verify_batch(msgs, pubs, sigs)

    def verify_cert(self, msgs, pubs, sig_buf, stride=64, key=None):
        self.cert_calls += 1
        super().verify_cert(msgs, pubs, sig_buf, stride, key=key)


def test_memo_unifies_fused_and_structured_keyspaces():
    """Under the sim plane's verdict memo, fused dispatch explodes into
    per-signature triples: the SAME memo entries then serve the
    structured batch path, so sim verdicts cannot diverge between a cert
    arriving raw (v2) and materialized (v1)."""
    rng = random.Random(202)
    msg, _seeds, pubs, buf = _cert(3, rng)
    backend = CountingBackend()
    set_backend(backend)
    crypto.enable_verify_memo(True)

    backend_verify_cert(msg, pubs, buf, 64)
    assert backend.cert_calls == 0  # memo active: no fused entry touched
    first = backend.batch_calls
    assert first >= 1
    # Same statements through the structured path: all memo hits.
    sigs = [buf[i * 64 : (i + 1) * 64] for i in range(3)]
    crypto.backend_verify_batch([msg] * 3, pubs, sigs)
    assert backend.batch_calls == first


def test_byzantine_cert_rejected_on_every_arrival_under_memo():
    """Failure verdicts are memoized but never flipped: a cert with one
    bad signature raises on every re-arrival in a sim run."""
    rng = random.Random(203)
    msg, _seeds, pubs, buf = _cert(3, rng)
    bad = bytearray(buf)
    bad[64 + 10] ^= 0x01
    bad = bytes(bad)
    set_backend(CountingBackend())
    crypto.enable_verify_memo(True)
    for _ in range(3):
        with pytest.raises(CryptoError):
            backend_verify_cert(msg, pubs, bad, 64)
    backend_verify_cert(msg, pubs, buf, 64)  # the honest cert still passes
