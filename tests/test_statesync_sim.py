"""Simulation-plane Lazarus tests: seeded join/truncate schedules through
the real FaultPlane on the virtual clock, the frontier-availability
invariant, determinism, and (slow) the real-plane wipe-restart scenario
end to end."""

from __future__ import annotations

import pytest

from hotstuff_tpu import telemetry
from hotstuff_tpu.sim.statesync import (
    _violation,
    rejoin_scenario,
    run_rejoin,
)


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


def _find_seed(want_wipe: bool, start: int = 0) -> int:
    for seed in range(start, start + 64):
        sc = rejoin_scenario(seed)
        restart = next(e for e in sc.events if e["kind"] == "restart")
        if bool(restart.get("wipe")) == want_wipe:
            return seed
    raise AssertionError("no matching seed in range")


def test_rejoin_scenario_shape():
    sc = rejoin_scenario(3)
    kinds = [e["kind"] for e in sc.events]
    assert "crash" in kinds and "restart" in kinds
    crash = next(e for e in sc.events if e["kind"] == "crash")
    restart = next(e for e in sc.events if e["kind"] == "restart")
    assert crash["at"] < restart["at"]
    assert crash["node"] == restart["node"]


def test_rejoin_scenario_deterministic():
    a, b = rejoin_scenario(11), rejoin_scenario(11)
    assert a.to_json() == b.to_json()
    assert rejoin_scenario(12).to_json() != a.to_json()


def test_cold_join_recovers_past_truncation():
    """A WIPED replica rejoins against truncated peer logs: it must
    state-sync (install a snapshot — it cannot replay a log it lost) and
    commit again, with no checker violation on any invariant."""
    seed = _find_seed(want_wipe=True)
    result = run_rejoin(seed)
    verdict = result["verdict"]
    assert _violation(verdict) is None, verdict
    rejoin = result["rejoin"]
    assert rejoin["wipe"] is True
    assert rejoin["post_rejoin_commits"] > 0, "victim never committed again"
    assert rejoin["victim_snapshot_round"] is not None, (
        "cold join must land via snapshot install"
    )
    assert verdict["frontier_availability"]["ok"]


def test_warm_lag_rejoin_recovers():
    seed = _find_seed(want_wipe=False)
    result = run_rejoin(seed)
    verdict = result["verdict"]
    assert _violation(verdict) is None, verdict
    assert result["rejoin"]["wipe"] is False
    assert result["rejoin"]["post_rejoin_commits"] > 0


def test_rejoin_sweep_small():
    """A handful of seeds through the full checker stack — the CI sweep
    runs 200; this keeps a canary in tier-1."""
    for seed in range(6):
        result = run_rejoin(seed)
        assert _violation(result["verdict"]) is None, (seed, result["verdict"])


def test_retention_zero_never_truncates():
    result = run_rejoin(_find_seed(want_wipe=False), retention_rounds=0)
    verdict = result["verdict"]
    assert _violation(verdict) is None
    # No compaction armed: no node may report a snapshot floor.
    assert not verdict["frontier_availability"].get("floors")


@pytest.mark.slow
def test_real_plane_wipe_restart_rejoin():
    """The committed-artifact scenario (benchmark/scenarios/rejoin.json)
    end to end on real asyncio+TCP engines: crash n1 at 2s, wipe+restart
    at 8s against retention-truncated peers, require safety + liveness +
    frontier availability."""
    import asyncio
    import pathlib

    from hotstuff_tpu.faultline import Scenario, run_scenario

    scenario = Scenario.load(
        str(
            pathlib.Path(__file__).resolve().parents[1]
            / "benchmark"
            / "scenarios"
            / "rejoin.json"
        )
    )
    result = asyncio.run(
        run_scenario(scenario, 4, base_port=9700, retention_rounds=16)
    )
    verdict = result["verdict"]
    assert verdict["safety"]["ok"], verdict["safety"]
    assert verdict["liveness"]["recovered"], verdict["liveness"]
    assert verdict["frontier_availability"]["ok"], verdict[
        "frontier_availability"
    ]
