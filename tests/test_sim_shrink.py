"""Schedule shrinking: convergence on a synthetic predicate, end-to-end
reduction of a real checker violation, and the reproducer artifact."""

import json

import pytest

from hotstuff_tpu.faultline.policy import Scenario
from hotstuff_tpu.sim.shrink import (
    shrink,
    sim_failure_probe,
    write_reproducer,
)

NOISE = [
    {"kind": "link", "src": "?", "dst": "*", "at": 1.0, "until": 2.0,
     "drop": 0.1, "delay_ms": [1.0, 5.0]},
    {"kind": "partition", "at": 1.5, "until": 2.5},
    {"kind": "byzantine", "node": 0, "behavior": "stale_vote_flood",
     "at": 2.0, "until": 3.0},
    {"kind": "crash", "node": 2, "at": 2.2},
    {"kind": "restart", "node": 2, "at": 2.8},
]

BUG = {"kind": "crash", "node": 1, "at": 2.5}


def _synthetic_probe(scenario):
    """Fails iff a crash of node 1 is present — an injected 'bug'
    predicate with a known one-event minimal core."""
    failing = any(
        e.get("kind") == "crash" and e.get("node") == 1
        for e in scenario.events
    )
    return ("liveness" if failing else None), {"synthetic": failing}


def test_shrink_converges_to_single_event_core():
    scenario = Scenario(
        name="synth", seed=1, duration_s=8.0,
        events=[*NOISE[:3], BUG, *NOISE[3:]],
    )
    res = shrink(scenario, _synthetic_probe)
    assert res.violation == "liveness"
    assert res.scenario.events == [BUG]
    assert res.runs <= 40  # greedy pass, not exponential
    assert res.scenario.duration_s < scenario.duration_s  # pass 3 fired


def test_shrink_refuses_passing_scenario():
    scenario = Scenario(name="fine", seed=1, duration_s=4.0, events=[])
    with pytest.raises(ValueError):
        shrink(scenario, _synthetic_probe)


def test_shrink_preserves_violation_class():
    """A candidate that flips the violation class (here: removing the
    bug but tripping a different synthetic failure) must be rejected."""

    def probe(scenario):
        has_bug = any(e == BUG for e in scenario.events)
        has_partition = any(e.get("kind") == "partition" for e in scenario.events)
        if has_bug:
            return "liveness", {}
        if has_partition:
            return "safety", {}  # different class: not the same bug
        return None, {}

    scenario = Scenario(
        name="classes", seed=1, duration_s=8.0,
        events=[{"kind": "partition", "at": 1.0, "until": 2.0}, BUG],
    )
    res = shrink(scenario, probe)
    assert res.violation == "liveness"
    assert BUG in res.scenario.events


def test_shrink_real_liveness_wedge_end_to_end(tmp_path):
    """The injected wedge (two permanent crashes at N=4 => below quorum
    forever) padded with noise: the shrinker must cut the schedule down
    around the crash pair while the checker keeps reporting the same
    liveness violation, and the artifact must round-trip."""
    scenario = Scenario(
        name="wedge", seed=3, duration_s=8.0,
        events=[
            NOISE[0],
            {"kind": "partition", "at": 2.0, "until": 4.0},
            {"kind": "crash", "node": 1, "at": 2.5},
            NOISE[2],
            {"kind": "crash", "node": 2, "at": 3.5},
            {"kind": "link", "src": "*", "dst": "?", "at": 4.0, "until": 5.5,
             "drop": 0.1, "delay_ms": [1.0, 10.0]},
        ],
    )
    probe = sim_failure_probe(4, recovery_timeout_s=10.0)
    res = shrink(scenario, probe)
    assert res.violation == "liveness"
    kinds = sorted(e["kind"] for e in res.scenario.events)
    assert kinds.count("crash") == 2  # the wedge core survives
    assert len(res.scenario.events) <= 4  # noise gone
    assert res.runs < 60

    path = write_reproducer(
        str(tmp_path), res.scenario, 4, res.verdict,
        steps=res.steps, tag="sim-shrunk",
    )
    with open(path) as f:
        data = json.load(f)
    assert data["schema"] == "simulant-repro-v1"
    replay = Scenario.from_json(data["scenario"])
    violation, _ = probe(replay)
    assert violation == "liveness"  # the artifact reproduces as written


@pytest.fixture(autouse=True, scope="module")
def _reset_verify_memo():
    """Sim runs enable the process-wide crypto verdict memo (kept warm
    across a sweep's seeds by design); drop it after this module so the
    rest of the suite prices crypto per-node as the real planes do."""
    yield
    from hotstuff_tpu import crypto

    crypto.enable_verify_memo(False)
