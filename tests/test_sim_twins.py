"""Twins-style systematic equivocation on the sim plane: a correct core
must keep safety with a duplicated identity split across partitions."""

import pytest

from hotstuff_tpu.faultline.policy import Scenario
from hotstuff_tpu.sim.twins import (
    TWIN_SUFFIX,
    dual_commit_config,
    enumerate_twins,
    run_twins,
    twins_round_scenario,
    twins_scenario,
)


def test_enumeration_separates_the_twin_pair():
    seen = 0
    for scenario, twins_map in enumerate_twins(4, limit=16):
        (twin_inst, base), = twins_map.items()
        assert twin_inst == base + TWIN_SUFFIX
        for event in scenario.events:
            assert event["kind"] == "partition"
            groups = event["groups"]
            sides_a = [twin_inst in g for g in groups]
            sides_b = [base in g for g in groups]
            # One copy per side, never together.
            assert sides_a.count(True) == 1 and sides_b.count(True) == 1
            assert sides_a.index(True) != sides_b.index(True)
            # At least one side can quorum (with its twin copy).
            assert max(len(g) for g in groups) >= 3
        seen += 1
    assert seen == 16


def test_twins_scenarios_are_seed_deterministic():
    a_sc, a_map = twins_scenario(7)
    b_sc, b_map = twins_scenario(7)
    assert a_sc.to_json() == b_sc.to_json()
    assert a_map == b_map
    c_sc, _ = twins_scenario(8)
    assert c_sc.to_json() != a_sc.to_json()


def test_correct_core_survives_systematic_twins():
    """The Twins gate: every enumerated configuration must preserve
    safety — the twinned seat signs on both sides of every cut, and
    honest nodes must still never commit conflicting blocks — and
    recover liveness after the last heal."""
    ran = 0
    for scenario, twins_map in enumerate_twins(4, limit=10):
        result = run_twins(scenario, twins_map, 4)
        v = result["verdict"]
        assert v["safety"]["ok"], (scenario.name, v["safety"])
        assert v["liveness"]["recovered"], (scenario.name, v["liveness"])
        # Both twin copies ran and committed (the scenario actually
        # exercised the duplicated identity).
        (twin_inst, base), = twins_map.items()
        assert len(result["commit_streams"][twin_inst]) > 0
        assert len(result["commit_streams"][base]) > 0
        ran += 1
    assert ran == 10


def test_weakened_quorum_still_cannot_dual_commit_at_n4():
    """A deliberately weakened quorum (f+1) run through Twins splits:
    at N=4 with round-robin vote routing, a 2-seat side can never chain
    two consecutive QCs (the vote for round r travels to leader(r+1),
    which cycles off-side), so even this broken configuration cannot
    dual-commit — quorum intersection is not the only line of defense
    here. Pinned as a finding: the per-round leader-assignment control
    the Twins paper uses is what makes weakened-quorum violations
    reachable, and a round-window leader schedule in the sim would
    unlock it."""
    from hotstuff_tpu.consensus.config import Committee

    original = Committee.quorum_threshold
    Committee.quorum_threshold = Committee.validity_threshold  # f+1
    try:
        for scenario, twins_map in enumerate_twins(4, limit=6):
            result = run_twins(scenario, twins_map, 4)
            assert result["verdict"]["safety"]["ok"]
    finally:
        Committee.quorum_threshold = original


def test_checker_flags_forked_commit_streams():
    """Detection-wiring control: fork one honest node's commit digest at
    one round in an otherwise-clean Twins run — the checker must flag
    exactly a conflicting_commit. If this passes silently, the sweep
    gate is blind."""
    from hotstuff_tpu.faultline.checker import CommitRecord, check
    from hotstuff_tpu.sim.world import SimWorld

    scenario, twins_map = twins_scenario(3)
    result = run_twins(scenario, twins_map, 4)
    assert result["verdict"]["safety"]["ok"]

    world = SimWorld(scenario, 4, twins=twins_map)
    streams = {
        name: [CommitRecord(r, b"same-digest", t) for r, t in stream]
        for name, stream in result["commit_streams"].items()
    }
    twinned_base = next(iter(twins_map.values()))
    victim = next(
        n for n in ("n000", "n001", "n002", "n003") if n != twinned_base
    )
    for rec in streams[victim]:
        if rec.round == 5:
            rec.digest = b"forked-digest"
    verdict = check(world.schedule, streams, honest=world._honest_set())
    assert not verdict["safety"]["ok"]
    kinds = {v["type"] for v in verdict["safety"]["violations"]}
    assert "conflicting_commit" in kinds


def test_dual_commit_boundary_is_reachable_beyond_tolerance():
    """The Twins tolerance boundary, violating side: two twinned seats
    at n=4 (faults > f=1) scripted into a split where BOTH sides hold a
    quorum of distinct seats. Per-round leader pinning keeps a twinned
    seat leading every round, proposal salting makes the two copies'
    same-round blocks conflict, and each side 2-chains its own QCs —
    honest observers commit conflicting blocks and the checker MUST
    flag it. If this starts passing safety, either the per-round
    partition routing or the salt stopped doing its job and the sim
    can no longer represent the paper's attack."""
    scenario, twins_map, sim_kwargs = dual_commit_config(pairs=2)
    result = run_twins(scenario, twins_map, 4, **sim_kwargs)
    v = result["verdict"]
    assert not v["safety"]["ok"], "beyond-tolerance split must dual-commit"
    assert v["safety"]["violations"], "violation must carry evidence"
    kinds = {viol["type"] for viol in v["safety"]["violations"]}
    assert "conflicting_commit" in kinds
    # Both sides actually committed — the violation came from genuine
    # dual commits, not a checker artifact over empty streams.
    committed = {n for n, s in result["commit_streams"].items() if s}
    assert {"n002", "n003"} <= committed


def test_dual_commit_boundary_is_unreachable_within_tolerance():
    """Same script, one twinned seat (faults == f, within tolerance):
    the twin-holding side is one distinct seat short of quorum, so it
    can never certify anything and safety provably holds. Pins the
    unreachable side of the boundary with the same machinery that
    reaches the violation at pairs=2 — the safety argument, run."""
    scenario, twins_map, sim_kwargs = dual_commit_config(pairs=1)
    result = run_twins(scenario, twins_map, 4, **sim_kwargs)
    v = result["verdict"]
    assert v["safety"]["ok"], v["safety"]
    assert v["safety"]["violations"] == []


def test_dual_commit_config_validates_inputs():
    with pytest.raises(ValueError):
        dual_commit_config(n=5)
    with pytest.raises(ValueError):
        dual_commit_config(pairs=3)


def test_round_scenarios_are_seed_deterministic_and_safe():
    """Per-round Twins sampling: deterministic per seed, and every
    drawn schedule (single twin pair — within tolerance) must preserve
    safety no matter how leaders and cuts interleave. Liveness is
    deliberately not asserted: a schedule whose leaders keep landing on
    the minority side grinds at timeout pace and may end mid-script."""
    a_sc, a_map, a_kw = twins_round_scenario(5)
    b_sc, b_map, b_kw = twins_round_scenario(5)
    assert a_sc.to_json() == b_sc.to_json()
    assert a_map == b_map
    assert a_kw == b_kw
    c_sc, _, c_kw = twins_round_scenario(6)
    assert (c_sc.to_json(), c_kw) != (a_sc.to_json(), a_kw)
    for seed in range(3):
        scenario, twins_map, sim_kwargs = twins_round_scenario(seed)
        result = run_twins(scenario, twins_map, 4, **sim_kwargs)
        assert result["verdict"]["safety"]["ok"], (seed, result["verdict"])


@pytest.fixture(autouse=True, scope="module")
def _reset_verify_memo():
    """Sim runs enable the process-wide crypto verdict memo (kept warm
    across a sweep's seeds by design); drop it after this module so the
    rest of the suite prices crypto per-node as the real planes do."""
    yield
    from hotstuff_tpu import crypto

    crypto.enable_verify_memo(False)
