"""Twins-style systematic equivocation on the sim plane: a correct core
must keep safety with a duplicated identity split across partitions."""

import pytest

from hotstuff_tpu.faultline.policy import Scenario
from hotstuff_tpu.sim.twins import (
    TWIN_SUFFIX,
    enumerate_twins,
    run_twins,
    twins_scenario,
)


def test_enumeration_separates_the_twin_pair():
    seen = 0
    for scenario, twins_map in enumerate_twins(4, limit=16):
        (twin_inst, base), = twins_map.items()
        assert twin_inst == base + TWIN_SUFFIX
        for event in scenario.events:
            assert event["kind"] == "partition"
            groups = event["groups"]
            sides_a = [twin_inst in g for g in groups]
            sides_b = [base in g for g in groups]
            # One copy per side, never together.
            assert sides_a.count(True) == 1 and sides_b.count(True) == 1
            assert sides_a.index(True) != sides_b.index(True)
            # At least one side can quorum (with its twin copy).
            assert max(len(g) for g in groups) >= 3
        seen += 1
    assert seen == 16


def test_twins_scenarios_are_seed_deterministic():
    a_sc, a_map = twins_scenario(7)
    b_sc, b_map = twins_scenario(7)
    assert a_sc.to_json() == b_sc.to_json()
    assert a_map == b_map
    c_sc, _ = twins_scenario(8)
    assert c_sc.to_json() != a_sc.to_json()


def test_correct_core_survives_systematic_twins():
    """The Twins gate: every enumerated configuration must preserve
    safety — the twinned seat signs on both sides of every cut, and
    honest nodes must still never commit conflicting blocks — and
    recover liveness after the last heal."""
    ran = 0
    for scenario, twins_map in enumerate_twins(4, limit=10):
        result = run_twins(scenario, twins_map, 4)
        v = result["verdict"]
        assert v["safety"]["ok"], (scenario.name, v["safety"])
        assert v["liveness"]["recovered"], (scenario.name, v["liveness"])
        # Both twin copies ran and committed (the scenario actually
        # exercised the duplicated identity).
        (twin_inst, base), = twins_map.items()
        assert len(result["commit_streams"][twin_inst]) > 0
        assert len(result["commit_streams"][base]) > 0
        ran += 1
    assert ran == 10


def test_weakened_quorum_still_cannot_dual_commit_at_n4():
    """A deliberately weakened quorum (f+1) run through Twins splits:
    at N=4 with round-robin vote routing, a 2-seat side can never chain
    two consecutive QCs (the vote for round r travels to leader(r+1),
    which cycles off-side), so even this broken configuration cannot
    dual-commit — quorum intersection is not the only line of defense
    here. Pinned as a finding: the per-round leader-assignment control
    the Twins paper uses is what makes weakened-quorum violations
    reachable, and a round-window leader schedule in the sim would
    unlock it."""
    from hotstuff_tpu.consensus.config import Committee

    original = Committee.quorum_threshold
    Committee.quorum_threshold = Committee.validity_threshold  # f+1
    try:
        for scenario, twins_map in enumerate_twins(4, limit=6):
            result = run_twins(scenario, twins_map, 4)
            assert result["verdict"]["safety"]["ok"]
    finally:
        Committee.quorum_threshold = original


def test_checker_flags_forked_commit_streams():
    """Detection-wiring control: fork one honest node's commit digest at
    one round in an otherwise-clean Twins run — the checker must flag
    exactly a conflicting_commit. If this passes silently, the sweep
    gate is blind."""
    from hotstuff_tpu.faultline.checker import CommitRecord, check
    from hotstuff_tpu.sim.world import SimWorld

    scenario, twins_map = twins_scenario(3)
    result = run_twins(scenario, twins_map, 4)
    assert result["verdict"]["safety"]["ok"]

    world = SimWorld(scenario, 4, twins=twins_map)
    streams = {
        name: [CommitRecord(r, b"same-digest", t) for r, t in stream]
        for name, stream in result["commit_streams"].items()
    }
    twinned_base = next(iter(twins_map.values()))
    victim = next(
        n for n in ("n000", "n001", "n002", "n003") if n != twinned_base
    )
    for rec in streams[victim]:
        if rec.round == 5:
            rec.digest = b"forked-digest"
    verdict = check(world.schedule, streams, honest=world._honest_set())
    assert not verdict["safety"]["ok"]
    kinds = {v["type"] for v in verdict["safety"]["violations"]}
    assert "conflicting_commit" in kinds


@pytest.fixture(autouse=True, scope="module")
def _reset_verify_memo():
    """Sim runs enable the process-wide crypto verdict memo (kept warm
    across a sweep's seeds by design); drop it after this module so the
    rest of the suite prices crypto per-node as the real planes do."""
    yield
    from hotstuff_tpu import crypto

    crypto.enable_verify_memo(False)
