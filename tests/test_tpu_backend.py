"""TPU crypto backend tests: acceptance-set equality with the CPU backend
(cofactored semantics) and wiring through Signature.verify_batch/QC.verify."""

import random

import pytest

pytest.importorskip("jax")

pytestmark = pytest.mark.device

from hotstuff_tpu.crypto import set_backend  # noqa: E402
from hotstuff_tpu.crypto import ed25519_ref as ref  # noqa: E402
from hotstuff_tpu.ops.verify import verify_batch_device  # noqa: E402


@pytest.fixture(autouse=True)
def reset_backend():
    yield
    set_backend("cpu")


def make_batch(n=3, seed=5):
    rng = random.Random(seed)
    msgs, pubs, sigs = [], [], []
    for _ in range(n):
        seed_bytes = rng.randbytes(32)
        pub = ref.secret_to_public(seed_bytes)
        msg = rng.randbytes(32)
        msgs.append(msg)
        pubs.append(pub)
        sigs.append(ref.sign(seed_bytes, msg))
    return msgs, pubs, sigs


def test_device_accepts_valid_batch():
    msgs, pubs, sigs = make_batch(4)
    assert verify_batch_device(msgs, pubs, sigs, _rng=random.Random(1))


def test_device_rejects_tampered_message():
    msgs, pubs, sigs = make_batch(4)
    msgs[2] = b"\x00" * 32
    assert not verify_batch_device(msgs, pubs, sigs, _rng=random.Random(1))


def test_device_rejects_tampered_signature():
    msgs, pubs, sigs = make_batch(3)
    bad = bytearray(sigs[1])
    bad[3] ^= 1
    sigs[1] = bytes(bad)
    assert not verify_batch_device(msgs, pubs, sigs, _rng=random.Random(1))


def test_device_rejects_noncanonical_s():
    msgs, pubs, sigs = make_batch(1)
    s = int.from_bytes(sigs[0][32:], "little") + ref.L
    sigs[0] = sigs[0][:32] + s.to_bytes(32, "little")
    assert not verify_batch_device(msgs, pubs, sigs, _rng=random.Random(1))


def test_device_accepts_torsioned_signature_like_cpu():
    """Cofactored acceptance parity: a signature whose R carries an
    8-torsion component must be ACCEPTED, matching CpuBackend (see
    test_crypto.test_cofactored_batch_semantics_unified)."""
    rng = random.Random(9)
    seed = rng.randbytes(32)
    a, _ = ref.secret_expand(seed)
    pub = ref.point_compress(ref.point_mul(a, ref.G))
    msg = rng.randbytes(32)
    t8 = ref.torsion_generator()
    r = rng.getrandbits(250) % ref.L
    r_enc = ref.point_compress(ref.point_add(ref.point_mul(r, ref.G), t8))
    h = ref.compute_challenge(r_enc, pub, msg)
    s = (r + h * a) % ref.L
    sig = r_enc + int.to_bytes(s, 32, "little")
    assert ref.verify(pub, msg, sig, strict=False)
    # Pad with two honest signatures so the lane count matches the other
    # tests' compiled shape (m=8) — each distinct shape is a separate
    # ~150-250 s cold XLA compile on this box.
    msgs, pubs, sigs = make_batch(2, seed=10)
    assert verify_batch_device(
        [msg, *msgs], [pub, *pubs], [sig, *sigs], _rng=random.Random(1)
    )
