"""TPU crypto backend tests: acceptance-set equality with the CPU backend
(cofactored semantics) and wiring through Signature.verify_batch/QC.verify."""

import random

import pytest

pytest.importorskip("jax")

pytestmark = pytest.mark.device

from hotstuff_tpu.crypto import (  # noqa: E402
    CryptoError,
    Digest,
    Signature,
    set_backend,
    sha512_digest,
)
from hotstuff_tpu.crypto import ed25519_ref as ref  # noqa: E402
from hotstuff_tpu.ops.verify import verify_batch_device  # noqa: E402

from .common import chain, consensus_committee, keys


@pytest.fixture(autouse=True)
def reset_backend():
    yield
    set_backend("cpu")


def make_batch(n=3, seed=5):
    rng = random.Random(seed)
    msgs, pubs, sigs = [], [], []
    for _ in range(n):
        seed_bytes = rng.randbytes(32)
        pub = ref.secret_to_public(seed_bytes)
        msg = rng.randbytes(32)
        msgs.append(msg)
        pubs.append(pub)
        sigs.append(ref.sign(seed_bytes, msg))
    return msgs, pubs, sigs


def test_device_accepts_valid_batch():
    msgs, pubs, sigs = make_batch(4)
    assert verify_batch_device(msgs, pubs, sigs, _rng=random.Random(1))


def test_device_rejects_tampered_message():
    msgs, pubs, sigs = make_batch(4)
    msgs[2] = b"\x00" * 32
    assert not verify_batch_device(msgs, pubs, sigs, _rng=random.Random(1))


def test_device_rejects_tampered_signature():
    msgs, pubs, sigs = make_batch(3)
    bad = bytearray(sigs[1])
    bad[3] ^= 1
    sigs[1] = bytes(bad)
    assert not verify_batch_device(msgs, pubs, sigs, _rng=random.Random(1))


def test_device_rejects_noncanonical_s():
    msgs, pubs, sigs = make_batch(1)
    s = int.from_bytes(sigs[0][32:], "little") + ref.L
    sigs[0] = sigs[0][:32] + s.to_bytes(32, "little")
    assert not verify_batch_device(msgs, pubs, sigs, _rng=random.Random(1))


def test_device_accepts_torsioned_signature_like_cpu():
    """Cofactored acceptance parity: a signature whose R carries an
    8-torsion component must be ACCEPTED, matching CpuBackend (see
    test_crypto.test_cofactored_batch_semantics_unified)."""
    rng = random.Random(9)
    seed = rng.randbytes(32)
    a, _ = ref.secret_expand(seed)
    pub = ref.point_compress(ref.point_mul(a, ref.G))
    msg = rng.randbytes(32)
    t8 = ref.torsion_generator()
    r = rng.getrandbits(250) % ref.L
    r_enc = ref.point_compress(ref.point_add(ref.point_mul(r, ref.G), t8))
    h = ref.compute_challenge(r_enc, pub, msg)
    s = (r + h * a) % ref.L
    sig = r_enc + int.to_bytes(s, 32, "little")
    assert ref.verify(pub, msg, sig, strict=False)
    assert verify_batch_device([msg], [pub], [sig], _rng=random.Random(1))


def test_tpu_backend_through_signature_api():
    set_backend("tpu")
    d = sha512_digest(b"quorum certificate")
    votes = [(pk, Signature.new(d, sk)) for pk, sk in keys(4)]
    Signature.verify_batch(d, votes)  # must not raise
    votes[1] = (votes[1][0], Signature(bytes(64)))
    with pytest.raises(CryptoError):
        Signature.verify_batch(d, votes)


def test_tpu_backend_qc_verify():
    set_backend("tpu")
    committee = consensus_committee(14000)
    blocks = chain(2)
    blocks[1].verify(committee)  # embedded QC batch-verifies on device


def test_tpu_backend_auto_shards_on_multidevice():
    """On a multi-device platform (the conftest's virtual 8-CPU mesh) the
    backend must select the lane-sharded mesh verifier automatically
    (BASELINE config 5 wiring) — and both polarities must flow through it."""
    import jax

    from hotstuff_tpu.crypto.tpu_backend import TpuBackend

    backend = TpuBackend()
    assert jax.device_count() > 1
    assert backend._mesh is not None, "multi-device must auto-select the mesh"

    msgs, pubs, sigs = make_batch(5, seed=21)
    backend.verify_batch(msgs, pubs, sigs)  # must not raise
    bad = bytearray(sigs[2])
    bad[7] ^= 0x20
    with pytest.raises(CryptoError):
        backend.verify_batch(msgs, pubs, [*sigs[:2], bytes(bad), *sigs[3:]])


def test_tpu_backend_sharded_override_off():
    from hotstuff_tpu.crypto.tpu_backend import TpuBackend

    assert TpuBackend(sharded=False)._mesh is None
