"""Signed-digit recode + signed MSM bit-exactness vs the pure-Python
oracle (split from test_verify_cached.py so each cold-compile slice fits
one 10-minute CI/judging window)."""

import random

import numpy as np
import pytest

pytest.importorskip("jax")

pytestmark = pytest.mark.device

from hotstuff_tpu.crypto import ed25519_ref as ref  # noqa: E402
from hotstuff_tpu.ops import curve as cv  # noqa: E402

# -- signed digit recode ----------------------------------------------------


def test_signed_digits_reconstruct_scalar():
    rng = random.Random(1)
    scalars = [rng.getrandbits(253) for _ in range(9)] + [0, 1, ref.L - 1]
    digits = cv.scalars_to_signed_digits(scalars, 64)
    assert digits.min() >= -8 and digits.max() <= 8
    for j, s in enumerate(scalars):
        val = 0
        for w in range(64):
            val = val * 16 + int(digits[w, j])
        assert val == s


def test_signed_digits_narrow_windows():
    rng = random.Random(2)
    scalars = [rng.getrandbits(128) | (1 << 127) for _ in range(7)]
    digits = cv.scalars_to_signed_digits(scalars, 33)
    for j, s in enumerate(scalars):
        val = 0
        for w in range(33):
            val = val * 16 + int(digits[w, j])
        assert val == s


def test_signed_digits_from_bytes_matches_int_version():
    rng = random.Random(3)
    scalars = [rng.getrandbits(252) for _ in range(11)]
    sb = np.frombuffer(
        b"".join(s.to_bytes(32, "little") for s in scalars), dtype=np.uint8
    ).reshape(-1, 32)
    a = cv.signed_digits_from_bytes(sb, 64)
    b = cv.scalars_to_signed_digits(scalars, 64)
    assert (a == b).all()


# -- signed MSM vs oracle ---------------------------------------------------


def _random_points(rng, m):
    pts, ints = [], []
    for _ in range(m):
        k = rng.getrandbits(250) % ref.L
        p_int = ref.point_mul(k, ref.G)
        ints.append(p_int)
        enc = ref.point_compress(p_int)
        import numpy as _np

        from hotstuff_tpu.ops import field as fe

        y = fe.fe_from_bytes(
            _np.frombuffer(bytes([b & (0x7F if i == 31 else 0xFF) for i, b in enumerate(enc)]), dtype=_np.uint8)[None]
        )[0]
        sign = enc[31] >> 7
        ok, pt = cv.decompress(np.asarray(y)[None], np.asarray([sign]))
        assert bool(ok[0])
        pts.append(np.asarray(pt[0]))
    return np.stack(pts), ints


def test_msm_signed_matches_oracle():
    rng = random.Random(7)
    m = 4
    pts, p_ints = _random_points(rng, m)
    scalars = [rng.getrandbits(250) % ref.L for _ in range(m)]
    digits = cv.scalars_to_signed_digits(scalars, 64)
    acc = cv.msm_signed(np.asarray(pts), np.asarray(digits))
    expected = None
    for s, p in zip(scalars, p_ints):
        term = ref.point_mul(s, p)
        expected = term if expected is None else ref.point_add(expected, term)
    got = cv.to_affine_bytes(acc)
    assert got == ref.point_compress(expected)


def test_msm_signed_narrow_windows_matches_oracle():
    rng = random.Random(8)
    m = 4
    pts, p_ints = _random_points(rng, m)
    scalars = [rng.getrandbits(128) | (1 << 127) for _ in range(m)]
    digits = cv.scalars_to_signed_digits(scalars, 33)
    acc = cv.msm_signed(np.asarray(pts), np.asarray(digits))
    expected = None
    for s, p in zip(scalars, p_ints):
        term = ref.point_mul(s, p)
        expected = term if expected is None else ref.point_add(expected, term)
    assert cv.to_affine_bytes(acc) == ref.point_compress(expected)


