"""Store tests — coverage modeled on reference
``store/src/tests/store_tests.rs:4-73`` (create, read/write, unknown key,
notify_read blocking contract) plus persistence/crash-replay cases."""

import asyncio
import os

from hotstuff_tpu.store import Store, LogEngine

from .common import async_test


@async_test
async def test_create_store(tmp_path):
    Store(str(tmp_path / "db")).close()


@async_test
async def test_read_write_value(tmp_path):
    store = Store(str(tmp_path / "db"))
    await store.write(b"key", b"value")
    assert await store.read(b"key") == b"value"
    store.close()


@async_test
async def test_read_unknown_key():
    store = Store()
    assert await store.read(b"missing") is None


@async_test
async def test_notify_read_after_write():
    store = Store()
    await store.write(b"k", b"v")
    assert await store.notify_read(b"k") == b"v"


@async_test
async def test_notify_read_blocks_until_write():
    store = Store()
    waiter = asyncio.create_task(store.notify_read(b"pending"))
    await asyncio.sleep(0.02)
    assert not waiter.done()
    await store.write(b"pending", b"arrived")
    assert await waiter == b"arrived"


@async_test
async def test_notify_read_many_waiters():
    store = Store()
    waiters = [asyncio.create_task(store.notify_read(b"k")) for _ in range(5)]
    await asyncio.sleep(0)
    await store.write(b"k", b"v")
    assert await asyncio.gather(*waiters) == [b"v"] * 5


@async_test
async def test_notify_read_cancellation_drops_obligation():
    store = Store()
    waiter = asyncio.create_task(store.notify_read(b"k"))
    await asyncio.sleep(0)
    waiter.cancel()
    await asyncio.sleep(0)
    assert store._obligations == {}


@async_test
async def test_persistence_across_reopen(tmp_path):
    path = str(tmp_path / "db")
    store = Store(path)
    await store.write(b"a", b"1")
    await store.write(b"b", b"22")
    await store.write(b"a", b"333")  # overwrite keeps last value
    store.close()
    store2 = Store(path)
    assert await store2.read(b"a") == b"333"
    assert await store2.read(b"b") == b"22"
    store2.close()


def test_torn_tail_replay(tmp_path):
    path = str(tmp_path / "db")
    eng = LogEngine(path)
    eng.put(b"good", b"value")
    eng.close()
    # Simulate a crash mid-append: garbage half-record at the tail.
    with open(os.path.join(path, "store.log"), "ab") as f:
        f.write(b"\x10\x00\x00\x00\x10")
    eng2 = LogEngine(path)
    assert eng2.get(b"good") == b"value"
    eng2.close()


def test_torn_tail_double_restart(tmp_path):
    """Crash -> restart -> write -> restart must keep the post-crash write:
    replay truncates the torn tail so new records never land behind garbage."""
    path = str(tmp_path / "db")
    eng = LogEngine(path)
    eng.put(b"good", b"value")
    eng.close()
    with open(os.path.join(path, "store.log"), "ab") as f:
        f.write(b"\x10\x00\x00\x00\x10")  # torn half-record
    eng2 = LogEngine(path)
    eng2.put(b"after-crash", b"kept")
    eng2.close()
    eng3 = LogEngine(path)
    assert eng3.get(b"good") == b"value"
    assert eng3.get(b"after-crash") == b"kept"
    assert set(eng3._index) == {b"good", b"after-crash"}  # no garbage keys
    eng3.close()
