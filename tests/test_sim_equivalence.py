"""Real-vs-sim equivalence: the same scenario executed on the asyncio
plane (real TCP, real time — faultline/harness.py) and on the simulation
plane (virtual time — hotstuff_tpu/sim) must tell the same protocol
story.

The contract, precisely: wall-clock interleavings differ between planes
(and between runs of the real plane), so byte-level commit equality is
not the claim — certificate vote-sets depend on arrival order. What
must agree is (a) the commit ROUND structure (fault-free: every node
commits the exact consecutive round sequence on both planes) and (b)
the checker verdict (safety + post-heal recovery) for the same compiled
fault schedule, which is itself byte-identical across planes (same
seed, same node names, same policy compiler)."""

import pytest

from hotstuff_tpu.faultline import Scenario, chaos_scenario, run_scenario
from hotstuff_tpu.faultline.policy import Schedule
from hotstuff_tpu.sim import run_sim

from .common import async_test

BASE = 27400


def test_compiled_schedule_is_plane_independent():
    """Both planes enact the SAME schedule object: trace equality is the
    precondition for any cross-plane comparison."""
    scenario = chaos_scenario(12, duration_s=8.0)
    names = [f"n{i:03d}" for i in range(4)]
    a: Schedule = scenario.compile(names)
    b: Schedule = scenario.compile(names)
    sim_trace = run_sim(scenario, 4)["trace"]
    assert a.trace() == b.trace() == sim_trace


@async_test(timeout=150)
async def test_fault_free_pinned_seed_matches_across_planes():
    scenario = Scenario(name="equiv-ff", seed=31, duration_s=3.0, events=[])
    sim = run_sim(scenario, 4, recovery_timeout_s=10.0)
    real = await run_scenario(
        scenario, 4, base_port=BASE, timeout_delay=1_000,
        recovery_timeout_s=30.0,
    )
    for result, plane in ((sim, "sim"), (real, "real")):
        v = result["verdict"]
        assert v["safety"]["ok"], (plane, v["safety"])
        assert v["liveness"]["recovered"], (plane, v["liveness"])
    # Fault-free, both planes commit the exact consecutive round
    # sequence on every node — compare the common prefix per node.
    for name in ("n000", "n001", "n002", "n003"):
        sim_rounds = [r for r, _ in sim["commit_streams"][name]]
        real_rounds = [r for r, _ in real["commit_streams"][name]]
        depth = min(len(sim_rounds), len(real_rounds))
        assert depth > 5, (name, depth)
        assert sim_rounds[:depth] == real_rounds[:depth] == list(
            range(1, depth + 1)
        ), name


@async_test(timeout=200)
async def test_pinned_chaos_seed_verdict_matches_across_planes():
    """Chaos seed 12 — one of the two pinned schedules that exposed the
    committed reputation-elector liveness bugs (tests/
    test_reputation_grind.py) — must produce the same checker verdict on
    both planes: safe, and recovered after the last heal."""
    scenario = chaos_scenario(
        12, duration_s=8.0, crashes=1, partitions=1, byzantine=1, links=1
    )
    sim = run_sim(
        scenario, 4, timeout_delay=500, leader_elector="reputation",
        recovery_timeout_s=60.0,
    )
    real = await run_scenario(
        scenario, 4, base_port=BASE + 20, timeout_delay=500,
        leader_elector="reputation", recovery_timeout_s=60.0,
    )
    assert sim["trace"] == real["trace"]  # identical fault schedule
    sim_v, real_v = sim["verdict"], real["verdict"]
    for key in ("safety", "liveness"):
        assert sim_v[key]["ok"] == real_v[key]["ok"] is True, (
            key, sim_v[key], real_v[key],
        )
    assert sim_v["byzantine"] == real_v["byzantine"]
    # Every expected-alive node commits on both planes.
    for name, count in sim_v["commits"].items():
        if name in sim_v["byzantine"]:
            continue
        assert count > 0, (name, "sim")
        assert real_v["commits"][name] > 0, (name, "real")


@pytest.fixture(autouse=True, scope="module")
def _reset_verify_memo():
    """Sim runs enable the process-wide crypto verdict memo (kept warm
    across a sweep's seeds by design); drop it after this module so the
    rest of the suite prices crypto per-node as the real planes do."""
    yield
    from hotstuff_tpu import crypto

    crypto.enable_verify_memo(False)
