"""Profile⇄trace join tests: per-edge function attribution from
synthetic streams, speedscope export, and the trace assembler's
missing-anchor warn-and-continue contract."""

from __future__ import annotations

import json

import pytest

from benchmark.profile_assemble import (
    aggregate,
    attribute,
    load_profiles,
    to_speedscope,
    top_functions,
)
from benchmark.trace_assemble import assemble, load_events
from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry import TraceBuffer, build_trace_record
from hotstuff_tpu.telemetry.profiler import PROFILE_SCHEMA


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


def _trace_record(node, events, anchor_mono=0.0, anchor_wall=1000.0):
    buf = TraceBuffer(capacity=1024)
    buf.anchor_mono = anchor_mono
    buf.anchor_wall = anchor_wall
    return build_trace_record(buf, events, node=node)


def _profile_record(node, stacks, seq=0, samples=None, ctypes=None):
    return {
        "schema": PROFILE_SCHEMA,
        "node": node,
        "pid": 1,
        "seq": seq,
        "ts": 1000.0,
        "mode": "thread",
        "interval_ms": 2.0,
        "samples": (
            samples if samples is not None else sum(c for _s, _f, c in stacks)
        ),
        "truncated": 0,
        "threads": 1,
        "gil_delay_ns": 1_000_000,
        "ctypes": ctypes or {},
        "stacks": stacks,
    }


def _round_events(node, r, base, *, leader=False, collector=False):
    seq = r * 100 + hash(node) % 50
    events = []
    if leader:
        events.append((seq + 1, node, r, "propose_send", base))
    events.append((seq + 2, node, r, "propose", base + 0.002))
    events.append((seq + 3, node, r, "verified", base + 0.004))
    events.append((seq + 4, node, r, "vote_send", base + 0.005))
    if collector:
        events.append((seq + 5, node, r, "first_vote", base + 0.007))
        events.append((seq + 6, node, r, "qc", base + 0.010))
    events.append((seq + 7, node, r, "commit", base + 0.030))
    return events


def _write_joined_stream(path, node, *, leader=False, collector=False):
    """A stream carrying trace AND profile records, like a real node's."""
    events = []
    for r in (1, 2):
        events += _round_events(
            node, r, r * 0.1, leader=leader, collector=collector
        )
    stacks = [
        ["ingress", "a.py:1:loop;serde.py:5:decode_message", 30],
        ["ingress", "a.py:1:loop;serde.py:9:decode_qc", 10],
        ["verify", "a.py:1:loop;crypto.py:7:verify_batch", 25],
        ["idle", "a.py:1:loop;selectors.py:2:select", 100],
    ]
    lines = [
        json.dumps(_trace_record(node, events)),
        json.dumps(
            _profile_record(
                node,
                stacks,
                ctypes={"hs_net.hs_net_send": [40, 2_000_000]},
            )
        ),
    ]
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_attribute_joins_top_functions_onto_edges(tmp_path):
    paths = [
        _write_joined_stream(tmp_path / "telemetry-n0.jsonl", "n0", leader=True),
        _write_joined_stream(
            tmp_path / "telemetry-n1.jsonl", "n1", collector=True
        ),
    ]
    report = attribute(paths)
    assert report["rounds"] == 2
    ingress = report["edges"]["ingress"]
    # Trace side of the join: the edge's measured milliseconds.
    assert ingress["trace_mean_ms"] == pytest.approx(2.0, abs=0.5)
    # Profile side: top functions by self samples, both nodes summed.
    assert ingress["samples"] == 80
    top = ingress["top_functions"]
    assert top[0]["fn"] == "serde.py:5:decode_message"
    assert top[0]["self_samples"] == 60
    assert top[0]["self_share"] == pytest.approx(0.75)
    assert top[0]["self_ms_est"] == pytest.approx(120.0)
    verify = report["edges"]["verify"]
    assert verify["top_functions"][0]["fn"] == "crypto.py:7:verify_batch"
    # Stages without a trace edge are reported, not joined.
    assert report["other_stages"]["idle"]["samples"] == 200
    # Boundary accounts survive the merge (per-session cumulative).
    assert report["ctypes"]["hs_net.hs_net_send"]["calls"] == 80
    assert report["sampler"]["gil_delay_ms"] == pytest.approx(2.0)


def test_aggregate_keeps_last_record_per_session():
    recs = [
        _profile_record("n0", [["verify", "a;b", 5]], seq=0, samples=5),
        # Same session later: cumulative samples grow; stacks are deltas.
        _profile_record("n0", [["verify", "a;b", 3]], seq=1, samples=8),
    ]
    stages, meta = aggregate(recs)
    assert stages["verify"]["a;b"] == 8  # deltas sum
    assert meta["samples"] == 8  # cumulative: last record wins


def test_top_functions_orders_by_self_time():
    from collections import Counter

    stacks = Counter({"a;b;c": 10, "a;b": 5, "a;d": 1})
    top = top_functions(stacks, 2.0, 2)
    assert [t["fn"] for t in top] == ["c", "b"]
    assert top[0]["cum_samples"] == 10
    assert top[1]["cum_samples"] == 15  # b is on two stacks


def test_speedscope_export_shape(tmp_path):
    paths = [
        _write_joined_stream(tmp_path / "telemetry-n0.jsonl", "n0", leader=True)
    ]
    stages, meta = aggregate(load_profiles(paths))
    scope = to_speedscope(stages, meta["interval_ms"], "test")
    assert scope["$schema"].startswith("https://www.speedscope.app")
    names = {p["name"] for p in scope["profiles"]}
    assert {"ingress", "verify", "idle"} <= names
    frames = scope["shared"]["frames"]
    for profile in scope["profiles"]:
        assert profile["type"] == "sampled"
        assert len(profile["samples"]) == len(profile["weights"])
        assert profile["endValue"] == pytest.approx(sum(profile["weights"]))
        for sample in profile["samples"]:
            for idx in sample:
                assert 0 <= idx < len(frames)
    idle = next(p for p in scope["profiles"] if p["name"] == "idle")
    assert sum(idle["weights"]) == pytest.approx(100 * 2.0)


def test_attribute_without_profiles_reports_zero_samples(tmp_path):
    path = tmp_path / "telemetry-n0.jsonl"
    path.write_text(
        json.dumps(
            _trace_record("n0", _round_events("n0", 1, 0.1, leader=True))
        )
        + "\n"
    )
    report = attribute([str(path)])
    assert report["sampler"]["samples"] == 0
    assert all(e["samples"] == 0 for e in report["edges"].values())


# -- trace assembler: missing-anchor warn-and-continue ------------------------


def test_missing_anchor_stream_is_skipped_and_counted(tmp_path, capsys):
    good = _write_joined_stream(
        tmp_path / "telemetry-n0.jsonl", "n0", leader=True
    )
    # n1's record lost its anchor (e.g. a hand-rolled emitter): the node
    # is skipped with a warning, the rest of the committee assembles.
    rec = _trace_record("n1", _round_events("n1", 1, 0.1, collector=True))
    del rec["anchor"]
    bad = tmp_path / "telemetry-n1.jsonl"
    bad.write_text(json.dumps(rec) + "\n")

    report = assemble([good, str(bad)])
    assert report["rounds"] == 2  # n0's rounds still assembled
    assert report["skipped_streams"] == ["telemetry-n1.jsonl"]
    err = capsys.readouterr().err
    assert "telemetry-n1" in err and "anchor" in err


def test_anchorless_record_skips_only_that_stream(tmp_path):
    rec = _trace_record("n2", _round_events("n2", 1, 0.1))
    rec["anchor"] = {"mono": "not-a-number", "wall": None}
    path = tmp_path / "telemetry-n2.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    skipped: list[str] = []
    events = load_events([str(path)], skipped_streams=skipped)
    assert events == []
    assert skipped == ["telemetry-n2.jsonl"]


def test_corrupt_stream_warns_and_continues(tmp_path):
    good = _write_joined_stream(
        tmp_path / "telemetry-n0.jsonl", "n0", leader=True
    )
    bad = tmp_path / "telemetry-n1.jsonl"
    bad.write_text('{"schema": "hotstuff-trace-v1"}\nnot json at all\n')
    report = assemble([good, str(bad)])
    assert report["rounds"] == 2
    assert report["skipped_streams"] == ["telemetry-n1.jsonl"]
