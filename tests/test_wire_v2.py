"""Wire-format v2 (seat-bitmap certificates): round-trip, v1↔v2
equivalence over randomized committees, malformed-frame rejection parity,
lazy-vote semantics, and the intern-table LRU bound.

v2 ships a QC as bitmap-of-seats + concatenated signatures (a TC adds a
u64 high_qc_round per signature) instead of repeated 32-byte pubkeys —
~33% smaller proposals at N=200. Decoders accept BOTH formats whenever a
seat table is known; ``wire_v2`` only selects what a node emits, which is
the whole interop story.
"""

import random
import struct

import pytest

from hotstuff_tpu.consensus import Authority, Committee, errors
from hotstuff_tpu.consensus.messages import (
    QC,
    TC,
    Block,
    CertificateCache,
    SeatTable,
    Timeout,
    _PK_INTERN,
    _PK_INTERN_CAP,
    _intern_pk,
    decode_message,
    encode_propose,
    encode_tc,
    encode_timeout,
)
from hotstuff_tpu.crypto import Signature, generate_keypair, sha512_digest
from hotstuff_tpu.utils.serde import Decoder, Encoder, SerdeError

_U64 = struct.Struct("<Q")


def _committee(n, rng):
    kps = [generate_keypair(seed=rng.randbytes(32)) for _ in range(n)]
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", 0)) for pk, _ in kps
        }
    )
    return committee, kps


def _signed_block(kps, quorum, with_tc):
    genesis = Block.genesis()
    qc = QC(hash=genesis.digest(), round=1, votes=[])
    qc.votes = [(pk, Signature.new(qc.digest(), sk)) for pk, sk in kps[:quorum]]
    tc = None
    if with_tc:
        tc = TC(
            round=2,
            votes=[
                (
                    pk,
                    Signature.new(
                        sha512_digest(_U64.pack(2), _U64.pack(1)), sk
                    ),
                    1,
                )
                for pk, sk in kps[:quorum]
            ],
        )
    pk, sk = kps[0]
    return Block.new_from_key(
        qc=qc, tc=tc, author=pk, round_=2, payload=[], secret=sk
    )


def _vote_set(qc):
    return {(pk.data, sig.data) for pk, sig in qc.votes}


def test_v2_roundtrip_byte_identical_and_semantically_equal():
    """Property: over randomized committee sizes, a v2 frame decodes to a
    certificate semantically identical to the v1 decode of the same
    block, and re-encoding the decoded view reproduces the v2 bytes."""
    rng = random.Random(7)
    for n in (4, 7, 13, 33):
        committee, kps = _committee(n, rng)
        seats = SeatTable.for_committee(committee)
        quorum = committee.quorum_threshold()
        block = _signed_block(kps, quorum, with_tc=(n % 2 == 0))

        w1 = encode_propose(block)
        w2 = encode_propose(block, seats)
        assert len(w2) < len(w1)  # the point of the exercise

        k1, b1 = decode_message(w1, seats)
        k2, b2 = decode_message(w2, seats)
        assert k1 == k2 == "propose"
        assert b1.digest() == b2.digest() == block.digest()
        assert _vote_set(b1.qc) == _vote_set(b2.qc) == _vote_set(block.qc)
        if block.tc is not None:
            assert b2.tc.high_qc_rounds() == block.tc.high_qc_rounds()
        b1.verify(committee)
        b2.verify(committee)

        # v2 re-encode of the (lazy) decoded view is byte-identical.
        assert encode_propose(b2, seats) == w2


def test_v2_timeout_and_tc_envelopes():
    rng = random.Random(11)
    committee, kps = _committee(7, rng)
    seats = SeatTable.for_committee(committee)
    quorum = 5
    genesis = Block.genesis()
    qc = QC(hash=genesis.digest(), round=1, votes=[])
    qc.votes = [(pk, Signature.new(qc.digest(), sk)) for pk, sk in kps[:quorum]]
    pk0, sk0 = kps[0]
    t = Timeout.new_from_key(qc, 3, pk0, sk0)
    wt = encode_timeout(t, seats)
    kind, t2 = decode_message(wt, seats)
    assert kind == "timeout"
    t2.verify(committee)
    assert t2.high_qc.n_votes() == quorum
    assert encode_timeout(t2, seats) == wt

    tc = TC(
        round=2,
        votes=[
            (pk, Signature.new(sha512_digest(_U64.pack(2), _U64.pack(1)), sk), 1)
            for pk, sk in kps[:quorum]
        ],
    )
    wtc = encode_tc(tc, seats)
    kind, tc2 = decode_message(wtc, seats)
    assert kind == "tc"
    tc2.verify(committee)
    assert tc2.high_qc_rounds() == [1] * quorum
    assert encode_tc(tc2, seats) == wtc


def test_v1_peer_rejects_v2_and_v2_peer_accepts_v1():
    """Interop contract: decoding WITHOUT a seat table (a v1-only peer)
    rejects v2 frames as malformed; decoding WITH a table accepts both
    formats — so emit-side negotiation can never split a committee of
    v2-capable nodes."""
    rng = random.Random(13)
    committee, kps = _committee(4, rng)
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, 3, with_tc=False)
    w1 = encode_propose(block)
    w2 = encode_propose(block, seats)

    with pytest.raises(SerdeError):
        decode_message(w2)  # v1-only peer
    decode_message(w1)  # v1-only peer, v1 frame: fine
    _, b_from_v1 = decode_message(w1, seats)  # v2-capable peer, v1 frame
    _, b_from_v2 = decode_message(w2, seats)
    assert _vote_set(b_from_v1.qc) == _vote_set(b_from_v2.qc)


def test_v2_malformed_frames_rejected():
    """Byzantine-shaped v2 sections: popcount/count mismatch, bits beyond
    the committee, counts beyond the committee, truncated signature
    buffers — all must raise, never mis-decode."""
    rng = random.Random(17)
    committee, kps = _committee(7, rng)
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, 5, with_tc=False)
    w2 = bytearray(encode_propose(block, seats))
    # Layout after tag: hash(32) round(8) count(4) bitmap(1) sigs...
    count_off = 1 + 32 + 8
    bitmap_off = count_off + 4

    bad_count = bytearray(w2)
    bad_count[count_off:count_off + 4] = struct.pack("<I", 0x80000000 | 6)
    with pytest.raises(SerdeError):
        decode_message(bytes(bad_count), seats)

    bad_bit = bytearray(w2)
    bad_bit[bitmap_off] = 0x80  # seat 7 of a 7-seat committee (bits 0-6)
    with pytest.raises(SerdeError):
        decode_message(bytes(bad_bit), seats)

    huge_count = bytearray(w2)
    huge_count[count_off:count_off + 4] = struct.pack("<I", 0x80000000 | 9999)
    with pytest.raises(SerdeError):
        decode_message(bytes(huge_count), seats)

    truncated = bytes(w2[: bitmap_off + 1 + 64 * 3])  # 3 of 5 sigs
    with pytest.raises(SerdeError):
        decode_message(truncated, seats)


def test_v2_lazy_votes_and_cache_key_parity():
    """A v2-decoded QC exposes n_votes() and its certificate-cache key
    without constructing a single Signature; the key equals the v1
    canonical encoding, so v1 and v2 arrivals of the same certificate
    share one cache entry."""
    rng = random.Random(19)
    committee, kps = _committee(7, rng)
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, 5, with_tc=False)

    _, b2 = decode_message(encode_propose(block, seats), seats)
    qc = b2.qc
    assert "_raw_votes" in qc.__dict__ and "votes" not in qc.__dict__
    assert qc.n_votes() == 5
    key_lazy = CertificateCache.key_of(qc)
    assert "votes" not in qc.__dict__  # key derivation stayed lazy

    # The same certificate decoded from a v1 frame keys identically.
    _, b1 = decode_message(encode_propose(block), seats)
    # v1 vote order is the sender's arrival order; canonicalize through
    # a seat-ordered re-encode for the comparison.
    enc = Encoder()
    qc.encode(enc)  # materializes, v1 canonical (seat order)
    assert key_lazy == enc.finish()

    # Verification works straight off the raw slices and caches.
    cache = CertificateCache()
    qc.verify(committee, cache)
    assert cache.hit(key_lazy)


def test_v2_verify_rejects_bad_signature_and_foreign_committee():
    rng = random.Random(23)
    committee, kps = _committee(7, rng)
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, 5, with_tc=False)
    w2 = bytearray(encode_propose(block, seats))
    # Corrupt one signature byte inside the v2 sig buffer.
    sig_off = 1 + 32 + 8 + 4 + seats.nbytes + 10
    w2[sig_off] ^= 0xFF
    _, bad = decode_message(bytes(w2), seats)
    with pytest.raises(errors.InvalidSignature):
        bad.qc.verify(committee)

    # Same frame judged against a DIFFERENT committee: unknown authority.
    other_committee, _ = _committee(7, random.Random(99))
    _, b2 = decode_message(encode_propose(block, seats), seats)
    with pytest.raises(errors.UnknownAuthority):
        b2.qc.verify(other_committee)


def test_store_format_stays_v1_canonical():
    """serialize() of a v2-decoded block re-encodes to v1 (seat order) so
    restores and sync replies never need a seat table."""
    rng = random.Random(29)
    committee, kps = _committee(7, rng)
    seats = SeatTable.for_committee(committee)
    block = _signed_block(kps, 5, with_tc=False)
    _, b2 = decode_message(encode_propose(block, seats), seats)
    restored = Block.deserialize(b2.serialize())
    assert restored.digest() == block.digest()
    restored.verify(committee)


def test_genesis_qc_stays_v1():
    """An empty vote set never pays bitmap bytes (and genesis blocks stay
    byte-identical across wire settings)."""
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", 0))
            for pk, _ in [generate_keypair(seed=bytes([i]) * 32) for i in range(4)]
        }
    )
    seats = SeatTable.for_committee(committee)
    enc_v1, enc_v2 = Encoder(), Encoder()
    QC.genesis().encode(enc_v1)
    QC.genesis().encode(enc_v2, seats)
    assert enc_v1.finish() == enc_v2.finish()


def test_signer_outside_seat_table_falls_back_to_v1():
    rng = random.Random(31)
    committee, kps = _committee(4, rng)
    seats = SeatTable.for_committee(committee)
    stranger_pk, stranger_sk = generate_keypair(seed=b"\x55" * 32)
    qc = QC(hash=Block.genesis().digest(), round=1, votes=[])
    qc.votes = [(pk, Signature.new(qc.digest(), sk)) for pk, sk in kps[:3]]
    qc.votes.append((stranger_pk, Signature.new(qc.digest(), stranger_sk)))
    enc = Encoder()
    qc.encode(enc, seats)
    dec = Decoder(enc.finish())
    decoded = QC.decode(dec, seats)  # must be a v1 section
    dec.finish()
    assert "_raw_votes" not in decoded.__dict__
    assert _vote_set(decoded) == _vote_set(qc)


def test_intern_pk_lru_bounds_and_keeps_hot_keys():
    """The pubkey intern table is a bounded LRU: a byzantine key spray
    evicts only the coldest entries — keys touched during the spray
    (committee keys on every decode) survive, and evictions are counted."""
    from hotstuff_tpu.consensus import messages as msgs

    _PK_INTERN.clear()
    before_evictions = msgs.intern_evictions
    hot = _intern_pk(b"\x01" * 32)
    for i in range(_PK_INTERN_CAP + 100):
        _intern_pk(i.to_bytes(32, "big"))
        if i % 97 == 0:
            assert _intern_pk(b"\x01" * 32) is hot  # touched: stays hot
    assert len(_PK_INTERN) <= _PK_INTERN_CAP
    assert msgs.intern_evictions > before_evictions
    assert _intern_pk(b"\x01" * 32) is hot  # survived the whole spray
    _PK_INTERN.clear()
