"""The deterministic simulation plane: scheduler determinism, protocol
correctness under simulated faults, virtual-clock seams, and the crypto
memo's purity (hotstuff_tpu/sim)."""

import pytest

from hotstuff_tpu.consensus.timer import Timer
from hotstuff_tpu.faultline.policy import Scenario, chaos_scenario
from hotstuff_tpu.sim import EventHeap, SimWorld, VirtualClock, run_sim


def _ok(result):
    v = result["verdict"]
    return v["safety"]["ok"] and v["liveness"]["recovered"]


# -- scheduler primitives ----------------------------------------------------


def test_event_heap_ties_break_in_push_order():
    heap = EventHeap()
    heap.push(1.0, "late")
    heap.push(0.5, "a")
    heap.push(0.5, "b")
    heap.push(0.5, "c")
    heap.push(0.2, "first")
    order = [heap.pop() for _ in range(len(heap))]
    assert order == [
        (0.2, "first"), (0.5, "a"), (0.5, "b"), (0.5, "c"), (1.0, "late"),
    ]


def test_event_heap_unorderable_payloads_never_compared():
    heap = EventHeap()
    heap.push(1.0, {"dict": "is not orderable"})
    heap.push(1.0, object())
    heap.push(1.0, ("tuple", object()))
    assert len(heap) == 3
    for _ in range(3):
        heap.pop()  # would raise TypeError if payloads were compared


def test_virtual_clock_monotonic():
    clock = VirtualClock()
    clock.advance_to(1.5)
    clock.advance_to(1.5)  # equal is fine
    assert clock() == 1.5
    with pytest.raises(ValueError):
        clock.advance_to(1.0)


def test_timer_over_virtual_clock():
    clock = VirtualClock(10.0)
    timer = Timer(500, clock=clock)
    assert timer.deadline == pytest.approx(10.5)
    clock.advance_to(12.0)
    timer.reset()
    assert timer.deadline == pytest.approx(12.5)


# -- protocol on the sim plane ----------------------------------------------


def test_fault_free_run_commits_consecutive_rounds():
    result = run_sim(
        Scenario(name="ff", seed=1, duration_s=3.0, events=[]), 4,
        recovery_timeout_s=5.0,
    )
    assert _ok(result), result["verdict"]
    for name, stream in result["commit_streams"].items():
        rounds = [r for r, _ in stream]
        assert rounds == list(range(1, len(rounds) + 1)), name
        assert len(rounds) > 10  # virtual seconds, real progress


def test_same_seed_same_world_is_byte_deterministic():
    def one():
        return SimWorld(chaos_scenario(42, duration_s=6.0), 4).run()

    a, b = one(), one()
    assert a["commit_streams"] == b["commit_streams"]
    assert a["trace"] == b["trace"]
    assert a["events"] == b["events"]
    assert a["verdict"] == b["verdict"]


def test_jitter_changes_interleaving_not_verdict():
    scenario = chaos_scenario(43, duration_s=6.0)
    base = run_sim(scenario, 4, jitter=0)
    other = run_sim(scenario, 4, jitter=1)
    assert _ok(base) and _ok(other)
    assert base["trace"] == other["trace"]  # the fault schedule is pinned
    # The latency redraw must actually change the execution.
    assert base["commit_streams"] != other["commit_streams"]


def test_partitioned_minority_is_silent_during_cut():
    scenario = Scenario(
        name="cut", seed=5, duration_s=6.0,
        events=[{"kind": "partition", "groups": [["n003"], ["n000", "n001", "n002"]],
                 "at": 2.0, "until": 4.0}],
    )
    # timeout_delay=250ms: round-robin elects the dead seat (and routes
    # votes through it) 2 of every 4 rounds, so the majority's progress
    # during the cut comes in bursts between timeout pairs — at the
    # default 1 s timeout a 2 s cut is ALL timeout, which is correct but
    # leaves nothing to assert.
    result = run_sim(scenario, 4, timeout_delay=250)
    assert _ok(result), result["verdict"]
    # The isolated node commits nothing inside the cut (commit times are
    # virtual): allow a small delivery tail at the boundary.
    inside = [t for _, t in result["commit_streams"]["n003"] if 2.3 < t < 4.0]
    assert inside == [], inside
    # The majority side (an exact 3-of-4 quorum) keeps committing
    # through the cut, burning timeouts whenever the cycle crosses the
    # dead seat.
    majority = [t for _, t in result["commit_streams"]["n000"] if 2.3 < t < 4.0]
    assert len(majority) > 3


def test_crash_restart_recovers_from_persisted_state():
    scenario = Scenario(
        name="cr", seed=6, duration_s=6.0,
        events=[
            {"kind": "crash", "node": 1, "at": 2.0},
            {"kind": "restart", "node": 1, "at": 3.5},
        ],
    )
    result = run_sim(scenario, 4)
    assert _ok(result), result["verdict"]
    stream = result["commit_streams"]["n001"]
    gap = [t for _, t in stream if 2.0 < t < 3.5]
    post = [r for r, t in stream if t > 3.5]
    assert gap == []  # dead nodes don't commit
    assert len(post) >= 3  # restarted from its own store and caught up


def test_grind_seeds_survive_on_sim_plane():
    """Chaos seeds 11/12 (the schedules that exposed the two committed
    liveness bugs on the real plane — tests/test_reputation_grind.py)
    replayed on the sim plane with the reputation elector: the fixes
    must hold here too, at milliseconds per seed instead of minutes."""
    for seed in (11, 12):
        scenario = chaos_scenario(
            seed, duration_s=8.0, crashes=1, partitions=1, byzantine=1, links=1
        )
        result = run_sim(scenario, 4, leader_elector="reputation")
        v = result["verdict"]
        assert v["safety"]["ok"], (seed, v["safety"])
        assert v["liveness"]["recovered"], (seed, v["liveness"])


def test_sim_chaos_seed_batch():
    """A mini-sweep inline: a block of chaos seeds must all pass the
    checker — the tier-1 face of the CI sim-sweep lane."""
    for seed in range(20, 35):
        result = run_sim(chaos_scenario(seed, duration_s=6.0), 4)
        assert _ok(result), (seed, result["verdict"])


# -- the crypto memo stays semantically invisible ---------------------------


def test_verify_memo_caches_both_verdicts():
    from hotstuff_tpu import crypto

    pk, sk, *_ = crypto.generate_keypair(seed=b"m" * 32)
    digest = crypto.sha512_digest(b"memo-test")
    sig = crypto.Signature.new(digest, sk)
    bad = crypto.Signature(bytes(32) + sig.data[32:])
    crypto.enable_verify_memo(False)
    try:
        crypto.enable_verify_memo(True)
        for _ in range(2):  # second pass is served from the memo
            sig.verify(digest, pk)
            with pytest.raises(crypto.CryptoError):
                bad.verify(digest, pk)
        # Batch path, both orders (canonical key: one entry).
        crypto.Signature.verify_batch(digest, [(pk, sig)])
        crypto.Signature.verify_batch(digest, [(pk, sig)])
        with pytest.raises(crypto.CryptoError):
            crypto.Signature.verify_batch(digest, [(pk, bad)])
        with pytest.raises(crypto.CryptoError):
            crypto.Signature.verify_batch(digest, [(pk, bad)])
    finally:
        crypto.enable_verify_memo(False)


def test_byzantine_signature_rejected_under_memo():
    """A sim run that carries byzantine traffic must keep rejecting it
    with the memo enabled (failure verdicts memoized, never flipped)."""
    scenario = Scenario(
        name="byz", seed=9, duration_s=6.0,
        events=[{"kind": "byzantine", "node": 2, "behavior": "equivocate",
                 "at": 1.0, "until": 4.0}],
    )
    result = run_sim(scenario, 4)
    v = result["verdict"]
    assert v["safety"]["ok"], v["safety"]
    assert v["liveness"]["recovered"], v["liveness"]


@pytest.fixture(autouse=True, scope="module")
def _reset_verify_memo():
    """Sim runs enable the process-wide crypto verdict memo (kept warm
    across a sweep's seeds by design); drop it after this module so the
    rest of the suite prices crypto per-node as the real planes do."""
    yield
    from hotstuff_tpu import crypto

    crypto.enable_verify_memo(False)
