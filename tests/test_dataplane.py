"""Conveyor data-plane unit tests: bundle/batch wire formats, the
availability-cert quorum logic in BOTH wire formats, back-pressure
watermark transitions, worker batching + dedup against live ACKing
peers, shedding at the ingress edge, and commit-path digest→batch
resolution."""

import asyncio
import struct

import pytest

from hotstuff_tpu.crypto import (
    Signature,
    SignatureService,
    sha512_digest,
)
from hotstuff_tpu.mempool import Parameters, WorkerEntry
from hotstuff_tpu.mempool.config import Authority, Committee
from hotstuff_tpu.mempool.dataplane import (
    AvailabilityCert,
    BoundedIngress,
    CertCollector,
    CertError,
    CommitResolver,
    IngressHandler,
    Watermark,
    Worker,
    WorkerSeatTable,
    ack_digest,
    cert_key,
)
from hotstuff_tpu.mempool.dataplane import messages as dpm
from hotstuff_tpu.mempool.synchronizer import Synchronize
from hotstuff_tpu.store import Store
from hotstuff_tpu.utils.serde import SerdeError

from .common import async_test, keys

BASE = 31000


def worker_committee(base_port: int, n: int = 4, workers: int = 1) -> Committee:
    return Committee(
        authorities={
            pk: Authority(
                stake=1,
                transactions_address=("127.0.0.1", base_port + i),
                mempool_address=("127.0.0.1", base_port + 20 + i),
                workers=[
                    WorkerEntry(
                        transactions_address=(
                            "127.0.0.1",
                            base_port + 40 + 20 * w + i,
                        ),
                        worker_address=(
                            "127.0.0.1",
                            base_port + 140 + 20 * w + i,
                        ),
                    )
                    for w in range(workers)
                ],
            )
            for i, (pk, _) in enumerate(keys(n))
        }
    )


def tx(sample_id: int | None = None, size: int = 100) -> bytes:
    if sample_id is not None:
        return b"\x00" + sample_id.to_bytes(8, "big") + b"\x01" * (size - 9)
    return b"\x01" * size


# -- wire formats ------------------------------------------------------------


def test_bundle_roundtrip_and_sample_scan():
    txs = [tx(sample_id=3), tx(), tx(sample_id=9, size=50)]
    frame = dpm.encode_bundle(txs)
    n, samples, blob = dpm.decode_bundle(frame)
    assert n == 3 and samples == [3, 9]
    assert dpm.split_blob(blob) == txs
    assert dpm.batch_tx_bytes(n, blob) == sum(len(t) for t in txs)


def test_bundle_rejects_malformed():
    with pytest.raises(SerdeError):
        dpm.decode_bundle(b"")
    with pytest.raises(SerdeError):
        dpm.decode_bundle(bytes([dpm.TAG_TX_BUNDLE]) + b"\x00")
    # more samples than txs
    bad = dpm.encode_bundle([tx()], sample_ids=[1, 2])
    with pytest.raises(SerdeError):
        dpm.decode_bundle(bad)


def test_worker_batch_roundtrip():
    txs = [tx(sample_id=1), tx(size=33)]
    bundle_blob = dpm.decode_bundle(dpm.encode_bundle(txs))[2]
    frame = dpm.encode_worker_batch(2, 2, [1], bundle_blob)
    wid, n, samples, blob = dpm.decode_worker_batch(frame)
    assert (wid, n, samples) == (2, 2, [1])
    assert dpm.split_blob(blob) == txs


# -- availability certs ------------------------------------------------------


def _signed_ack(digest, pk, sk):
    return pk, Signature.new(ack_digest(digest), sk)


def test_cert_collector_quorum_crossing_exactly_once():
    committee = worker_committee(BASE)
    ks = keys()
    d = sha512_digest(b"batch")
    col = CertCollector(committee, d, own=_signed_ack(d, *ks[0]))
    assert not col.complete()
    assert col.add_ack(*_signed_ack(d, *ks[1])) is None
    cert = col.add_ack(*_signed_ack(d, *ks[2]))
    assert cert is not None and col.complete()
    # Post-quorum stragglers and retransmits never re-emit the cert.
    assert col.add_ack(*_signed_ack(d, *ks[3])) is None
    assert col.add_ack(*_signed_ack(d, *ks[1])) is None
    cert.verify(committee)


def test_cert_collector_rejects_bad_acks():
    committee = worker_committee(BASE)
    ks = keys()
    d = sha512_digest(b"batch")
    col = CertCollector(committee, d)
    # Non-member signer.
    from hotstuff_tpu.crypto import generate_keypair

    stranger_pk, stranger_sk = generate_keypair()[:2]
    with pytest.raises(CertError):
        col.add_ack(*_signed_ack(d, stranger_pk, stranger_sk))
    # Valid member, wrong digest signed.
    wrong = Signature.new(ack_digest(sha512_digest(b"other")), ks[1][1])
    with pytest.raises(CertError):
        col.add_ack(ks[1][0], wrong)
    assert col.stake == 0


def test_cert_wire_v1_and_v2_roundtrip_and_verify():
    committee = worker_committee(BASE)
    ks = keys()
    d = sha512_digest(b"batch")
    pairs = [_signed_ack(d, pk, sk) for pk, sk in ks[:3]]
    cert = AvailabilityCert(d, pairs)
    cert.verify(committee)
    seats = WorkerSeatTable.for_committee(committee)

    v1 = cert.encode()
    v2 = cert.encode(seats)
    assert v1[0] == dpm.TAG_CERT and v2[0] == dpm.TAG_CERT_V2
    assert len(v2) < len(v1)  # the bitmap drops the repeated 32B keys

    for decoded in (
        AvailabilityCert.decode(v1),
        AvailabilityCert.decode(v2, seats),
    ):
        assert decoded.digest == d
        assert sorted(map(bytes, decoded.signers())) == sorted(
            bytes(pk) for pk, _ in pairs
        )
        decoded.verify(committee)

    # v2 without a seat table is an explicit decode error, not garbage.
    with pytest.raises(SerdeError):
        AvailabilityCert.decode(v2)


def test_cert_verify_rejects_subquorum_and_forgery():
    committee = worker_committee(BASE)
    ks = keys()
    d = sha512_digest(b"batch")
    with pytest.raises(CertError):
        AvailabilityCert(d, [_signed_ack(d, *ks[0])]).verify(committee)
    # Duplicate signer padding cannot fake a quorum.
    pair = _signed_ack(d, *ks[0])
    with pytest.raises(CertError):
        AvailabilityCert(d, [pair, pair, pair]).verify(committee)
    # Tampered signature dies in verify even at quorum size.
    pairs = [_signed_ack(d, pk, sk) for pk, sk in ks[:3]]
    bad = Signature(bytes(64))
    with pytest.raises(CertError):
        AvailabilityCert(d, pairs[:2] + [(ks[2][0], bad)]).verify(committee)


# -- back-pressure -----------------------------------------------------------

def test_watermark_hysteresis_transitions():
    async def main():
        wm = Watermark(high=4, low=2)
        assert not wm.gated
        wm.update(3)
        assert not wm.gated  # below high: no transition
        wm.update(4)
        assert wm.gated and wm.transitions == 1  # ok -> high at >= high
        wm.update(3)
        assert wm.gated  # hysteresis: above low stays gated
        wm.update(2)
        assert not wm.gated and wm.transitions == 2  # high -> ok at <= low
        wm.update(10)
        assert wm.gated and wm.transitions == 3
        with pytest.raises(ValueError):
            Watermark(high=1, low=2)

    asyncio.run(main())


def test_watermark_gates_and_releases_waiters():
    async def main():
        wm = Watermark(high=2, low=0)
        wm.update(2)
        waited = []

        async def waiter():
            await wm.wait_ok()
            waited.append(True)

        task = asyncio.create_task(waiter())
        await asyncio.sleep(0.02)
        assert not waited  # parked while gated
        wm.update(0)
        await asyncio.sleep(0.02)
        assert waited
        await task

    asyncio.run(main())


def test_bounded_ingress_sheds_when_full():
    async def main():
        ingress = BoundedIngress(2)
        assert ingress.offer(b"a") and ingress.offer(b"b")
        assert not ingress.offer(b"c")
        assert ingress.shed == 1
        assert await ingress.get() == b"a"
        assert ingress.offer(b"c")

    asyncio.run(main())


class _FakeWriter:
    def __init__(self):
        self.sent = []

    async def send(self, payload):
        self.sent.append(payload)


@async_test
async def test_ingress_handler_client_visible_shedding():
    ingress = BoundedIngress(1)
    handler = IngressHandler(ingress)
    writer = _FakeWriter()
    bundle = dpm.encode_bundle([tx(), tx()])
    await handler.dispatch(writer, bundle)
    assert writer.sent == []  # accepted silently
    await handler.dispatch(writer, bundle)
    assert writer.sent == [b"Shed"]  # the client SEES the refusal


# -- worker end-to-end against live ACKing peers -----------------------------


async def _acking_peer(port: int, secret, store: dict, *, sign: bool = True):
    """A one-connection peer worker double: stores batch frames and
    replies signed acks (or stays silent when ``sign`` is False —
    the withholding peer)."""

    async def handle(reader, writer):
        try:
            while True:
                hdr = await reader.readexactly(4)
                (n,) = struct.unpack(">I", hdr)
                frame = await reader.readexactly(n)
                if frame[0] == dpm.TAG_BATCH:
                    digest = sha512_digest(frame)
                    store[digest.data] = frame
                    if sign:
                        sig = Signature.new(ack_digest(digest), secret)
                        ack = dpm.encode_ack(
                            digest, secret.public_key(), sig
                        )
                        writer.write(struct.pack(">I", len(ack)) + ack)
                        await writer.drain()
                elif frame[0] in (dpm.TAG_CERT, dpm.TAG_CERT_V2):
                    store.setdefault(b"certs", []).append(frame)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass

    return await asyncio.start_server(handle, "127.0.0.1", port)


@async_test(timeout=30)
async def test_worker_seals_certifies_and_emits_digest():
    committee = worker_committee(BASE + 200)
    ks = keys()
    name = ks[0][0]
    peer_stores = [dict() for _ in range(3)]
    servers = []
    for (pk, sk), ps in zip(ks[1:], peer_stores):
        addr = committee.worker_address(pk, 0)
        servers.append(await _acking_peer(addr[1], sk, ps))
    await asyncio.sleep(0.05)

    store = Store()
    tx_consensus = asyncio.Queue()
    params = Parameters(batch_size=150, max_batch_delay=5_000, workers=1)
    worker = Worker(
        name,
        0,
        committee,
        params,
        store,
        SignatureService(ks[0][1]),
        tx_consensus,
        Watermark(100, 50),
    )
    await worker.spawn()

    # Two bundles crossing batch_size -> immediate seal + dissemination.
    _, writer = await asyncio.open_connection(
        "127.0.0.1", committee.workers_of(name)[0].transactions_address[1]
    )
    for sample in (1, 2):
        frame = dpm.encode_bundle([tx(sample_id=sample)])
        writer.write(struct.pack(">I", len(frame)) + frame)
    await writer.drain()

    digest = await asyncio.wait_for(tx_consensus.get(), 10)
    # Batch stored locally under the digest, cert stored and valid.
    batch = await store.read(digest.data)
    assert batch is not None
    wid, n_txs, samples, blob = dpm.decode_worker_batch(batch)
    assert (wid, n_txs, sorted(samples)) == (0, 2, [1, 2])
    cert_bytes = await store.read(cert_key(digest.data))
    assert cert_bytes is not None
    seats = WorkerSeatTable.for_committee(committee)
    cert = AvailabilityCert.decode(cert_bytes, seats)
    assert cert.digest == digest
    cert.verify(committee)
    # Every live peer also holds the raw batch frame.
    await asyncio.sleep(0.2)
    for ps in peer_stores:
        assert ps.get(digest.data) == batch

    writer.close()
    await worker.shutdown()
    for s in servers:
        s.close()


@async_test(timeout=30)
async def test_worker_certifies_despite_one_withholding_peer():
    """2f+1 = 3-of-4 with own stake: one byzantine peer that stores but
    never acks cannot block certification."""
    committee = worker_committee(BASE + 400)
    ks = keys()
    name = ks[0][0]
    stores = [dict() for _ in range(3)]
    servers = []
    for i, ((pk, sk), ps) in enumerate(zip(ks[1:], stores)):
        addr = committee.worker_address(pk, 0)
        servers.append(
            await _acking_peer(addr[1], sk, ps, sign=(i != 0))
        )
    await asyncio.sleep(0.05)

    store = Store()
    tx_consensus = asyncio.Queue()
    params = Parameters(batch_size=50, max_batch_delay=5_000, workers=1)
    worker = Worker(
        name, 0, committee, params, store,
        SignatureService(ks[0][1]), tx_consensus, Watermark(100, 50),
    )
    await worker.spawn()
    _, writer = await asyncio.open_connection(
        "127.0.0.1", committee.workers_of(name)[0].transactions_address[1]
    )
    frame = dpm.encode_bundle([tx(sample_id=5)])
    writer.write(struct.pack(">I", len(frame)) + frame)
    await writer.drain()

    digest = await asyncio.wait_for(tx_consensus.get(), 10)
    cert = AvailabilityCert.decode(
        await store.read(cert_key(digest.data)),
        WorkerSeatTable.for_committee(committee),
    )
    cert.verify(committee)
    # The withholding peer is not among the signers.
    assert bytes(ks[1][0]) not in {bytes(pk) for pk in cert.signers()}

    writer.close()
    await worker.shutdown()
    for s in servers:
        s.close()


@async_test(timeout=30)
async def test_worker_dedups_retransmitted_bundles():
    committee = worker_committee(BASE + 600)
    ks = keys()
    name = ks[0][0]
    servers = []
    for pk, sk in ks[1:]:
        addr = committee.worker_address(pk, 0)
        servers.append(await _acking_peer(addr[1], sk, dict()))
    await asyncio.sleep(0.05)

    store = Store()
    tx_consensus = asyncio.Queue()
    params = Parameters(batch_size=1_000_000, max_batch_delay=100, workers=1)
    worker = Worker(
        name, 0, committee, params, store,
        SignatureService(ks[0][1]), tx_consensus, Watermark(100, 50),
    )
    await worker.spawn()
    _, writer = await asyncio.open_connection(
        "127.0.0.1", committee.workers_of(name)[0].transactions_address[1]
    )
    bundle = dpm.encode_bundle([tx(sample_id=1), tx()])
    other = dpm.encode_bundle([tx(sample_id=2)])
    for frame in (bundle, bundle, other, bundle):  # client retransmits
        writer.write(struct.pack(">I", len(frame)) + frame)
    await writer.drain()

    digest = await asyncio.wait_for(tx_consensus.get(), 10)
    _, n_txs, samples, blob = dpm.decode_worker_batch(
        await store.read(digest.data)
    )
    # One copy of the duplicated bundle, plus the distinct one.
    assert n_txs == 3 and sorted(samples) == [1, 2]

    writer.close()
    await worker.shutdown()
    for s in servers:
        s.close()


# -- commit-path resolution --------------------------------------------------


@async_test
async def test_commit_resolver_passes_local_and_fetches_missing():
    from .common import chain

    store = Store()
    rx, out, to_mempool = asyncio.Queue(), asyncio.Queue(), asyncio.Queue()
    CommitResolver.spawn(store, rx, out, to_mempool)

    present = sha512_digest(b"present-batch")
    await store.write(present.data, b"present-batch")
    missing = sha512_digest(b"missing-batch")

    block = chain(1)[0]
    block.payload = [present, missing]

    await rx.put(block)
    # The resolver asks the mempool synchronizer for the missing batch...
    sync = await asyncio.wait_for(to_mempool.get(), 5)
    assert isinstance(sync, Synchronize) and sync.digests == [missing]
    await asyncio.sleep(0.05)
    assert out.empty()  # block held until the batch materializes
    # ...and releases the block the moment the store obligation fires.
    await store.write(missing.data, b"missing-batch")
    released = await asyncio.wait_for(out.get(), 5)
    assert released is block


@async_test
async def test_commit_resolver_preserves_commit_order():
    from .common import chain

    store = Store()
    rx, out, to_mempool = asyncio.Queue(), asyncio.Queue(), asyncio.Queue()
    CommitResolver.spawn(store, rx, out, to_mempool)
    blocks = chain(3)
    d = sha512_digest(b"late")
    blocks[0].payload = [d]  # first block blocks on a fetch
    await rx.put(blocks[0])
    await rx.put(blocks[1])
    await rx.put(blocks[2])
    await asyncio.wait_for(to_mempool.get(), 5)
    await store.write(d.data, b"late")
    got = [await asyncio.wait_for(out.get(), 5) for _ in range(3)]
    assert got == blocks  # strictly in commit order


@async_test
async def test_dataplane_depth_rises_on_seal_and_falls_on_commit():
    """Regression: commit feedback must actually release watermark depth
    (a None-valued sentinel once made pop(d, None) blind to hits — every
    node gated at the high watermark forever once it sealed enough)."""
    from hotstuff_tpu.crypto import SignatureService
    from hotstuff_tpu.mempool.dataplane import DataPlane

    committee = worker_committee(BASE + 800)
    ks = keys()
    params = Parameters(
        workers=1, store_high_watermark=4, store_low_watermark=2
    )
    dp = DataPlane(
        ks[0][0], committee, params, Store(),
        SignatureService(ks[0][1]), asyncio.Queue(),
    )
    digests = [sha512_digest(f"b{i}".encode()) for i in range(5)]
    for d in digests[:4]:
        dp._note_sealed(d)
    assert dp.watermark.depth == 4 and dp.watermark.gated
    dp._note_sealed(digests[0])  # re-seal dedup: no double count
    assert dp.watermark.depth == 4
    dp.note_committed(digests[:3])
    assert dp.watermark.depth == 1 and not dp.watermark.gated
    dp.note_committed(digests[:3])  # idempotent
    assert dp.watermark.depth == 1
    dp.note_committed([digests[3], digests[4]])  # unknown digest: no-op
    assert dp.watermark.depth == 0


@async_test
async def test_peer_handler_withholds_acks_under_faultline():
    """batch_withhold: the marked node stores the batch but never signs
    an ack and never serves batch requests — and heals on schedule."""
    from hotstuff_tpu.faultline import FaultPlane, Scenario, install, uninstall
    from hotstuff_tpu.faultline import hooks as fl_hooks
    from hotstuff_tpu.mempool.dataplane.worker import PeerWorkerHandler

    committee = worker_committee(BASE + 900)
    ks = keys()
    name = ks[1][0]
    store = Store()
    handler = PeerWorkerHandler(
        name, committee, store, SignatureService(ks[1][1]), asyncio.Queue()
    )
    batch = dpm.encode_worker_batch(0, 1, [], b"\x00\x00\x00\x01x")
    digest = sha512_digest(batch)

    scenario = Scenario(
        name="withhold", seed=1, duration_s=10.0,
        events=[{
            "kind": "byzantine", "node": "n001",
            "behavior": "batch_withhold", "at": 0.0, "until": 5.0,
        }],
    )
    t = [0.0]
    plane = FaultPlane(
        scenario.compile(["n000", "n001", "n002", "n003"]),
        {}, clock=lambda: t[0],
    ).start(t0=0.0)
    install(plane)
    token = fl_hooks.NODE.set("n001")
    try:
        writer = _FakeWriter()
        await handler.dispatch(writer, batch)
        # Stored (the bytes are held) but NOT acked (no attestation).
        assert await store.read(digest.data) == batch
        assert writer.sent == []
        # Batch requests are not served while withholding either.
        req = dpm.encode_batch_request([digest], ks[0][0])
        await handler.dispatch(writer, req)
        assert writer.sent == []
        # After the heal, the same node acks normally.
        t[0] = 6.0
        writer2 = _FakeWriter()
        await handler.dispatch(writer2, batch)
        assert len(writer2.sent) == 1
        ack_d, signer, sig = dpm.decode_ack(writer2.sent[0])
        assert ack_d == digest and signer == name
        sig.verify(dpm.ack_digest(digest), name)
    finally:
        fl_hooks.NODE.reset(token)
        uninstall()
