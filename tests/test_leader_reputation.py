"""Reputation leader elector (opt-in pacemaker variant beyond the
reference's round-robin — DiemBFT-v4-style active-set election from the
committed window; ``consensus/leader.py``)."""

import asyncio

import pytest

from hotstuff_tpu.consensus import Authority, Committee, Consensus, Parameters
from hotstuff_tpu.consensus.leader import (
    ReputationLeaderElector,
    RRLeaderElector,
    make_elector,
)
from hotstuff_tpu.crypto import SignatureService, generate_keypair
from hotstuff_tpu.store import Store

from .common import async_test, chain, consensus_committee, keys

BASE = 20100


def test_make_elector_kinds():
    committee = consensus_committee(BASE)
    assert isinstance(make_elector(committee, "round-robin"), RRLeaderElector)
    assert isinstance(make_elector(committee, "rr"), RRLeaderElector)
    assert isinstance(
        make_elector(committee, "reputation"), ReputationLeaderElector
    )
    try:
        make_elector(committee, "bogus")
        raise AssertionError("unknown elector kind accepted")
    except ValueError:
        pass


def test_empty_window_falls_back_to_round_robin():
    committee = consensus_committee(BASE)
    rep = ReputationLeaderElector(committee)
    rr = RRLeaderElector(committee)
    for r in range(10):
        assert rep.get_leader(r) == rr.get_leader(r)


def test_deterministic_across_instances():
    """Two nodes feeding identical committed blocks elect identical
    leaders for every round — the agreement requirement."""
    committee = consensus_committee(BASE)
    blocks = chain(3)
    a = ReputationLeaderElector(committee)
    b = ReputationLeaderElector(committee)
    for blk in blocks:
        a.update(blk)
        b.update(blk)
    for r in range(4, 40):
        assert a.get_leader(r) == b.get_leader(r)


def test_nonparticipant_is_not_elected():
    """A validator absent from the committed window (crashed: no blocks
    authored, no QC votes) must never be chosen once the window has
    data — round-robin would keep burning a timeout on it every N
    rounds."""
    committee = consensus_committee(BASE)
    all_keys = [pk for pk, _ in keys(4)]
    rep = ReputationLeaderElector(committee)
    blocks = chain(3)  # authored/signed by a quorum subset
    participants = set()
    for blk in blocks:
        rep.update(blk)
        participants.add(blk.author)
        participants.update(pk for pk, _ in blk.qc.votes)
    absent = [pk for pk in all_keys if pk not in participants]
    # chain(3) uses 3-of-4 quorums; with a fixed vote set one validator
    # can be absent. Skip silently if the fixture happened to use all 4.
    # Elections below blocks[-1].round + LAG still use the boot fallback
    # (round-lagged anchoring), so assert from there on.
    start = blocks[-1].round + ReputationLeaderElector.LAG
    for r in range(start, start + 200):
        leader = rep.get_leader(r)
        assert leader in participants
        assert leader not in absent


def test_recent_author_excluded():
    committee = consensus_committee(BASE)
    rep = ReputationLeaderElector(committee, exclude=1)
    blocks = chain(2)
    for blk in blocks:
        rep.update(blk)
    last_author = blocks[-1].author
    start = blocks[-1].round + ReputationLeaderElector.LAG
    for r in range(start, start + 100):
        assert rep.get_leader(r) != last_author


@pytest.mark.slow
@async_test(timeout=90)
async def test_committee_commits_with_reputation_elector():
    """Liveness end-to-end: a 4-node committee running the reputation
    elector over real localhost TCP keeps committing.

    Marked slow as a belt-and-braces measure for CI determinism: the
    boot wedge this test once hit ~1-in-20 (solicited-block
    registration racing the Core's frame loop) is fixed — 40
    consecutive clean runs since — but multi-second TCP committee tests
    stay out of the quick loop by policy. The deterministic elector
    properties are covered by the unit tests above."""
    n = 4
    key_pairs = [generate_keypair() for _ in range(n)]
    committee = Committee(
        authorities={
            pk: Authority(stake=1, address=("127.0.0.1", BASE + 10 + i))
            for i, (pk, _) in enumerate(key_pairs)
        }
    )
    # Reference-default timeout: the boot round can drop best-effort
    # votes (receivers still coming up) and a window-transition round
    # can split the vote 2-2 — both heal through one timeout/TC cycle,
    # so recovery must be cheap relative to the test budget.
    params = Parameters(timeout_delay=5_000, leader_elector="reputation")
    engines, commits, sinks = [], [], []
    for pk, sk in key_pairs:
        rx_mempool: asyncio.Queue = asyncio.Queue()
        tx_mempool: asyncio.Queue = asyncio.Queue()
        tx_commit: asyncio.Queue = asyncio.Queue()

        async def drain(q=tx_mempool):
            while True:
                await q.get()

        sinks.append(asyncio.create_task(drain()))
        engines.append(
            await Consensus.spawn(
                pk, committee, params, SignatureService(sk), Store(),
                rx_mempool, tx_mempool, tx_commit,
            )
        )
        commits.append(tx_commit)

    # Every node commits a healthy prefix (well past the boot window, so
    # reputation-based election is actually in effect).
    for q in commits:
        for _ in range(12):
            await asyncio.wait_for(q.get(), 60)
    for e in engines:
        await e.shutdown()
    for s in sinks:
        s.cancel()
