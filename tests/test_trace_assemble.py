"""Cross-node trace assembly tests: multi-node stream merge (with clock
skew), missing-node streams, out-of-order sequence numbers, and the
flight recorder / trace ring primitives they stand on."""

from __future__ import annotations

import json

import pytest

from benchmark.trace_assemble import (
    assemble,
    assemble_rounds,
    estimate_offsets,
    load_events,
)
from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry import (
    TraceBuffer,
    build_trace_record,
    dump_flight_record,
    validate_trace_record,
)


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


# -- helpers: synthesize node streams ---------------------------------------


def _write_stream(path, node, events, anchor_mono=0.0, anchor_wall=1000.0):
    """One telemetry file with one trace record; ``events`` are
    (seq, node, round, stage, t_mono)."""
    buf = TraceBuffer(capacity=1024)
    buf.anchor_mono = anchor_mono
    buf.anchor_wall = anchor_wall
    record = build_trace_record(buf, events, node=node)
    with open(path, "w") as f:
        f.write(json.dumps(record) + "\n")
    return str(path)


def _round_events(node, r, base, *, leader=False, collector=False):
    """A plausible single-round timeline for one node, starting at
    ``base`` (monotonic seconds). Returns (events, next_seq_base)."""
    seq = r * 100 + hash(node) % 50
    events = []
    if leader:
        events.append((seq + 1, node, r, "propose_send", base))
    events.append((seq + 2, node, r, "propose", base + 0.002))
    events.append((seq + 3, node, r, "verified", base + 0.004))
    events.append((seq + 4, node, r, "vote_send", base + 0.005))
    if collector:
        events.append((seq + 5, node, r, "first_vote", base + 0.007))
        events.append((seq + 6, node, r, "qc", base + 0.010))
    events.append((seq + 7, node, r, "commit", base + 0.030))
    return events


def _committee_streams(tmp_path, skew: dict[str, float] | None = None):
    """Three nodes, rounds 1-3: n0 leads, n1 collects. ``skew`` shifts a
    node's wall anchor (clock skew between hosts)."""
    skew = skew or {}
    paths = []
    for node in ("n0", "n1", "n2"):
        events = []
        for r in (1, 2, 3):
            base = r * 0.1
            events += _round_events(
                node, r, base,
                leader=(node == "n0"), collector=(node == "n1"),
            )
        paths.append(
            _write_stream(
                tmp_path / f"telemetry-{node}.jsonl",
                node,
                events,
                anchor_wall=1000.0 + skew.get(node, 0.0),
            )
        )
    return paths


# -- assembly ---------------------------------------------------------------


def test_multi_node_merge_produces_round_timelines(tmp_path):
    report = assemble(_committee_streams(tmp_path))
    assert report["rounds"] == 3
    assert report["total_ms"]["mean"] == pytest.approx(30.0, abs=1.0)
    edges = report["edges"]
    # Every causal edge got attribution from the synthetic marks.
    assert edges["ingress"]["mean_ms"] == pytest.approx(2.0, abs=0.5)
    assert edges["verify"]["mean_ms"] == pytest.approx(2.0, abs=0.5)
    assert edges["fanin"]["mean_ms"] == pytest.approx(3.0, abs=0.5)
    assert edges["qc_to_commit"]["mean_ms"] == pytest.approx(20.0, abs=1.0)
    assert len(report["top_cost_centers"]) == 3
    assert report["top_cost_centers"][0] == "qc_to_commit"


def test_clock_skew_is_estimated_and_corrected(tmp_path):
    # n2's wall clock is 50 ms BEHIND: its receives would precede the
    # leader's send. Alignment must restore causality and keep the
    # attribution close to the unskewed run.
    paths = _committee_streams(tmp_path, skew={"n2": -0.050})
    events = load_events(paths)
    offsets = estimate_offsets(events)
    assert offsets.get("n2", 0.0) == pytest.approx(0.048, abs=0.005)
    rounds = assemble_rounds(events, offsets)
    assert len(rounds) == 3
    for rd in rounds:
        # No negative-wire artifacts: every per-node ingress ≥ 0 and the
        # fan-out stats stay in the synthetic range.
        assert rd["fanout"]["ingress"]["max_ms"] < 60.0


def test_missing_node_stream_degrades_gracefully(tmp_path):
    # Drop the collector's stream entirely: first_vote/qc vanish, but
    # rounds still assemble from commits, with fan-in edges unattributed.
    paths = [
        p
        for p in _committee_streams(tmp_path)
        if "telemetry-n1" not in p
    ]
    report = assemble(paths)
    assert report["rounds"] == 3
    for rd in report["per_round"]:
        assert rd["edges_ms"]["fanin"] is None
        assert rd["edges_ms"]["qc_to_commit"] is None
        assert rd["total_ms"] > 0


def test_missing_leader_stream_falls_back_to_earliest_sighting(tmp_path):
    paths = [
        p
        for p in _committee_streams(tmp_path)
        if "telemetry-n0" not in p
    ]
    report = assemble(paths)
    assert report["rounds"] == 3  # propose_send absent; earliest propose wins


def test_out_of_order_seq_events_are_resorted(tmp_path):
    events = []
    for r in (1, 2):
        events += _round_events("n0", r, r * 0.1, leader=True, collector=True)
    shuffled = list(reversed(events))
    path = _write_stream(tmp_path / "telemetry-n0.jsonl", "n0", shuffled)
    report = assemble([path])
    assert report["rounds"] == 2
    assert report["total_ms"]["mean"] == pytest.approx(30.0, abs=1.0)


def test_empty_streams_yield_empty_report(tmp_path):
    path = tmp_path / "telemetry-x.jsonl"
    path.write_text("")
    report = assemble([str(path)])
    assert report["rounds"] == 0
    assert report["events"] == 0


# -- trace ring + flight recorder -------------------------------------------


def test_trace_buffer_ring_eviction_and_since():
    buf = TraceBuffer(capacity=256)
    for i in range(300):
        buf.record("n0", i, "propose", t=float(i))
    assert buf.evicted == 300 - 256
    events = buf.snapshot_events()
    assert len(events) == 256
    assert events[0][0] == 45  # oldest surviving seq
    tail = buf.events_since(298)
    assert [e[0] for e in tail] == [299, 300]
    assert buf.events_since(400) == []


def test_trace_record_schema_roundtrip():
    buf = TraceBuffer(capacity=16)
    buf.record("n0", 1, "propose")
    rec = build_trace_record(buf, buf.snapshot_events(), node="n0")
    rec = json.loads(json.dumps(rec))
    assert validate_trace_record(rec) == []
    bad = dict(rec, events=[[1, "n0", "not-an-int", "propose", 0.0]])
    assert validate_trace_record(bad) != []


def test_flight_record_dump(tmp_path):
    telemetry.enable()
    registry = telemetry.get_registry()
    registry.counter("consensus.rounds_advanced").inc(7)
    buf = telemetry.trace_buffer()
    telemetry.trace_event("n0", 3, "propose")
    path = str(tmp_path / "flightrec-x.json")
    out = dump_flight_record(
        path, "checker_failure", buf, registry, extra={"note": "t"}
    )
    assert out == path
    with open(path) as f:
        rec = json.load(f)
    assert rec["schema"] == "hotstuff-flightrec-v1"
    assert rec["reason"] == "checker_failure"
    assert rec["events"] and rec["events"][0][1] == "n0"
    assert rec["snapshot"]["counters"]["consensus.rounds_advanced"] == 7
    assert rec["note"] == "t"


def test_trace_event_noop_when_disabled():
    telemetry.trace_event("n0", 1, "propose")
    assert telemetry.trace_buffer().snapshot_events() == []
    telemetry.enable()
    telemetry.trace_event("n0", 1, "propose")
    assert len(telemetry.trace_buffer().snapshot_events()) == 1


def test_round_trace_emits_events_and_counts_evictions():
    telemetry.enable()
    registry = telemetry.get_registry()
    trace = telemetry.round_trace(node="nX")
    assert trace is not None
    trace.mark_propose(4)
    trace.mark_verified(4)
    trace.mark_vote_send(4)
    trace.mark_vote(4)
    trace.mark_qc(4)
    trace.mark_commit(4)
    stages = [e[3] for e in telemetry.trace_buffer().snapshot_events()]
    assert stages == [
        "propose", "verified", "vote_send", "first_vote", "qc", "commit"
    ]
    # FIFO eviction (rounds that never commit) is counted, not silent.
    for r in range(10, 10 + 600):
        trace.mark_propose(r)
    assert registry.counter("consensus.span.evicted_rounds").value() >= 600 - 512
