"""Lazarus state-sync tests: snapshot codec + 2-chain proof soundness
(structural and cryptographic tamper rejection), Compactor snapshot/
truncate behavior over a real store, the frontier-availability checker
invariant, and the Watchtower ``sync_stall`` detector fixtures."""

from __future__ import annotations

import pytest

from hotstuff_tpu import telemetry
from hotstuff_tpu.consensus.messages import QC, Block
from hotstuff_tpu.consensus.statesync import (
    SNAPSHOT_KEY,
    Compactor,
    Snapshot,
    SnapshotError,
    StateSync,
    decode_snapshot,
    encode_snapshot,
    peek_frontier,
    verify_snapshot,
)
from hotstuff_tpu.crypto import Digest, Signature
from hotstuff_tpu.store import Store

from .common import async_test, chain, consensus_committee, keys


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


def _proof(n: int = 6, k: int = 2):
    """(frontier, child, cert) from a valid chain: F = block at round k+1,
    c1 its consecutive child, cert the QC certifying c1 (carried by the
    block above c1)."""
    blocks = chain(n)
    return blocks, blocks[k], blocks[k + 1], blocks[k + 2].qc


# -- codec + structural proof checks ----------------------------------------


def test_snapshot_roundtrip_and_peek():
    _, frontier, child, cert = _proof()
    raw = encode_snapshot(frontier, child, cert)
    assert peek_frontier(raw) == (frontier.round, frontier.digest())
    snap = decode_snapshot(raw)
    assert snap.frontier.digest() == frontier.digest()
    assert snap.child.digest() == child.digest()
    assert snap.cert.hash == cert.hash and snap.cert.round == cert.round


def test_snapshot_rejects_unknown_version():
    _, frontier, child, cert = _proof()
    raw = encode_snapshot(frontier, child, cert)
    with pytest.raises(SnapshotError):
        decode_snapshot(b"\xff" + raw[1:])
    with pytest.raises(SnapshotError):
        peek_frontier(b"\xff" + raw[1:])


def test_snapshot_rejects_truncated_record():
    _, frontier, child, cert = _proof()
    raw = encode_snapshot(frontier, child, cert)
    with pytest.raises(SnapshotError):
        decode_snapshot(raw[: len(raw) // 2])
    with pytest.raises(SnapshotError):
        decode_snapshot(raw + b"\x00")  # trailing garbage must not parse


def test_snapshot_rejects_header_frontier_mismatch():
    blocks, frontier, child, cert = _proof()
    # Swap the frontier block for a different one while keeping the header:
    # peek_frontier answers from the header, so the full decode must verify
    # the header actually matches the embedded block.
    honest = encode_snapshot(frontier, child, cert)
    forged = encode_snapshot(blocks[0], child, cert)
    # Splice honest header (ver + u64 round + 32B digest) onto forged body.
    with pytest.raises(SnapshotError):
        decode_snapshot(honest[:41] + forged[41:])


def test_snapshot_rejects_nonconsecutive_child():
    blocks = chain(6)
    # blocks[4].qc certifies blocks[3], not blocks[2]: child does not
    # certify the claimed frontier.
    with pytest.raises(SnapshotError):
        decode_snapshot(encode_snapshot(blocks[2], blocks[4], blocks[5].qc))


def test_snapshot_rejects_cert_for_wrong_block():
    blocks = chain(6)
    # cert certifies blocks[4], not the child blocks[3].
    with pytest.raises(SnapshotError):
        decode_snapshot(encode_snapshot(blocks[2], blocks[3], blocks[5].qc))


def test_snapshot_rejects_genesis_frontier():
    blocks = chain(3)
    fake = Snapshot(blocks[0], blocks[1], blocks[2].qc)
    raw = encode_snapshot(fake.frontier, fake.child, fake.cert)
    # Round-1 frontier is fine; a genesis (round-0) frontier can't exist in
    # a well-formed record because Block round 0 is the genesis sentinel —
    # assert decode of the valid boundary still works.
    assert decode_snapshot(raw).frontier.round == 1


# -- cryptographic verification ---------------------------------------------


@async_test
async def test_verify_snapshot_accepts_honest_proof():
    _, frontier, child, cert = _proof()
    raw = encode_snapshot(frontier, child, cert)
    committee = consensus_committee(9300)
    await verify_snapshot(decode_snapshot(raw), committee)


@async_test
async def test_verify_snapshot_rejects_forged_cert_votes():
    _, frontier, child, cert = _proof()
    # Keep the topology valid but re-sign the cert with the wrong key:
    # structural decode passes, signature verification must not.
    key_list = keys()
    wrong_sk = key_list[0][1]
    forged = QC(
        hash=cert.hash,
        round=cert.round,
        votes=[(pk, Signature.new(cert.digest(), wrong_sk)) for pk, _ in key_list],
    )
    raw = encode_snapshot(frontier, child, forged)
    committee = consensus_committee(9310)
    with pytest.raises(Exception):
        await verify_snapshot(decode_snapshot(raw), committee)


# -- Compactor: snapshot + truncate over a real store -----------------------


class _CoreStub:
    def __init__(self, store, last_committed_round):
        self.store = store
        self.last_committed_round = last_committed_round
        self.synchronizer = self

    def note_floor(self, frontier):
        self.floor = frontier


@async_test
async def test_compactor_truncates_below_frontier(tmp_path):
    blocks = chain(20)
    store = Store(str(tmp_path / "db"))
    for b in blocks:
        await store.write(b.digest().data, b.serialize())
    comp = Compactor(store, retention_rounds=4)
    for b in blocks:
        comp.note_commit(b)
    core = _CoreStub(store, last_committed_round=18)
    await comp.maybe_compact(core)
    await comp.drain()  # the log rewrite runs as a background task
    raw = await store.read_meta(SNAPSHOT_KEY)
    assert raw is not None, "snapshot record must be written"
    snap = decode_snapshot(raw)
    assert snap.frontier.round <= 18 - 4
    assert core.floor.digest() == snap.frontier.digest()
    # Everything strictly below the frontier is gone; F and above survive.
    for b in blocks:
        data = await store.read(b.digest().data)
        if b.round < snap.frontier.round:
            assert data is None, f"round {b.round} should be truncated"
        else:
            assert data is not None, f"round {b.round} should survive"
    store.close()


@async_test
async def test_compactor_hysteresis_no_op_below_threshold(tmp_path):
    blocks = chain(10)
    store = Store(str(tmp_path / "db"))
    for b in blocks:
        await store.write(b.digest().data, b.serialize())
    comp = Compactor(store, retention_rounds=8)
    for b in blocks:
        comp.note_commit(b)
    # head - snapshot(0) = 10 < 2*8: must not snapshot yet.
    await comp.maybe_compact(_CoreStub(store, last_committed_round=10))
    await comp.drain()
    assert await store.read_meta(SNAPSHOT_KEY) is None
    store.close()


@async_test
async def test_compactor_snapshot_survives_reopen(tmp_path):
    blocks = chain(20)
    path = str(tmp_path / "db")
    store = Store(path)
    for b in blocks:
        await store.write(b.digest().data, b.serialize())
    comp = Compactor(store, retention_rounds=4)
    for b in blocks:
        comp.note_commit(b)
    await comp.maybe_compact(_CoreStub(store, last_committed_round=18))
    await comp.drain()
    raw = await store.read_meta(SNAPSHOT_KEY)
    store.close()
    store2 = Store(path)
    assert await store2.read_meta(SNAPSHOT_KEY) == raw
    snap = decode_snapshot(raw)
    assert await store2.read(snap.frontier.digest().data) is not None
    for b in blocks:
        if b.round < snap.frontier.round:
            assert await store2.read(b.digest().data) is None
    store2.close()


# -- StateSync install: only certified state is adopted ----------------------


class _InstallCore:
    """Minimal core surface ``StateSync._install`` touches, recording what
    the snapshot makes it adopt."""

    def __init__(self):
        self.store = Store()  # MemEngine
        self.synchronizer = self
        self.last_committed_round = 0
        self._last_committed_digest = None
        self.last_voted_round = 0
        self.qcs = []
        self.persists = 0
        self.cached = []

    def note_floor(self, frontier):
        self.floor = frontier

    def cache_block(self, block):
        self.cached.append(block)

    def increase_last_voted_round(self, target):
        self.last_voted_round = max(self.last_voted_round, target)

    async def process_qc(self, qc):
        self.qcs.append(qc)

    async def _persist_state(self):
        self.persists += 1


@async_test
async def test_install_adopts_only_certified_voting_floor():
    # Regression: v1 records carried the creator's last_voted_round as an
    # unauthenticated hint; a byzantine peer attaching 2^64-1 to a valid
    # proof would permanently mute the installer (block.round can never
    # exceed it again). The record must carry no such field, and _install
    # must raise the voting floor only to the round the certificates
    # prove — c1's.
    _, frontier, child, cert = _proof()
    raw = encode_snapshot(frontier, child, cert)
    snap = decode_snapshot(raw)
    assert not hasattr(snap, "last_voted_round")

    ss = StateSync(keys()[0][0], consensus_committee(9320), 100)
    core = _InstallCore()
    ss._core = core
    await ss._install(snap, raw)

    assert core.last_voted_round == child.round
    assert core.floor.digest() == frontier.digest()
    assert core.last_committed_round == frontier.round
    assert [(q.hash, q.round) for q in core.qcs] == [(cert.hash, cert.round)]
    assert core.persists == 1
    assert await core.store.read_meta(SNAPSHOT_KEY) == raw
    assert await core.store.read(frontier.digest().data) == frontier.serialize()
    assert await core.store.read(child.digest().data) == child.serialize()


# -- StateSync pull cap: forged frontier claims are O(1) ---------------------


class _PullSync:
    def __init__(self):
        self.requests = []
        self.cancelled = []
        self.outstanding = set()

    def request_block(self, digest, address):
        self.requests.append(digest)
        self.outstanding.add(digest)

    def requested(self, digest):
        return digest in self.outstanding

    def cancel_request(self, digest):
        self.cancelled.append(digest)
        self.outstanding.discard(digest)


class _PullCore:
    def __init__(self):
        self.synchronizer = _PullSync()
        self.last_committed_round = 0
        self.network = self
        self.scheduled = []

    def _call_later(self, delay, item):
        self.scheduled.append(item)

    def send(self, address, data):
        pass


@async_test
async def test_forged_frontier_spray_bounded_to_one_pull():
    # Regression: the (round, digest) claim in a state_response is
    # unauthenticated. A byzantine peer spraying distinct forged digests
    # must not grow a request entry + store obligation + waiter task per
    # response — at most ONE direct pull may be in flight.
    ss = StateSync(keys()[0][0], consensus_committee(9330), 100)
    core = _PullCore()
    ss._core = core
    sync = core.synchronizer
    for i in range(8):
        await ss.handle_state_response((50 + i, Digest(bytes([i]) * 32), None))
    assert len(sync.requests) == 1


@async_test
async def test_pull_ttl_evicts_unservable_digest():
    ss = StateSync(keys()[0][0], consensus_committee(9340), 100)
    core = _PullCore()
    ss._core = core
    sync = core.synchronizer
    bogus = Digest(b"\x0b" * 32)
    await ss.handle_state_response((50, bogus, None))
    assert sync.requests == [bogus]
    # No peer ever serves it: after PULL_TTL_TICKS the slot is evicted via
    # cancel_request (releasing the synchronizer bookkeeping) ...
    for _ in range(StateSync.PULL_TTL_TICKS):
        await ss.handle_tick()
    assert sync.cancelled == [bogus]
    assert ss._pull is None
    # ... and a later (honest) claim can use the slot again.
    honest = Digest(b"\xaa" * 32)
    await ss.handle_state_response((60, honest, None))
    assert sync.requests == [bogus, honest]


@async_test
async def test_pull_slot_frees_on_resolution_without_cancel():
    ss = StateSync(keys()[0][0], consensus_committee(9350), 100)
    core = _PullCore()
    ss._core = core
    sync = core.synchronizer
    first = Digest(b"\x01" * 32)
    await ss.handle_state_response((50, first, None))
    sync.outstanding.discard(first)  # the block arrived: request resolved
    await ss.handle_tick()
    assert ss._pull is None and sync.cancelled == []
    second = Digest(b"\x02" * 32)
    await ss.handle_state_response((60, second, None))
    assert sync.requests == [first, second]


# -- StateSync server: snapshot replies rate-limited per origin --------------


class _CountingStore:
    def __init__(self, snapshot):
        self._snapshot = snapshot
        self.meta_reads = 0

    async def read_meta(self, key):
        self.meta_reads += 1
        return self._snapshot


class _ServeCore:
    def __init__(self, store, frontier_digest):
        self.store = store
        self.last_committed_round = 30
        self._last_committed_digest = frontier_digest
        self.network = self
        self.sent = []

    def send(self, address, data):
        self.sent.append(data)


@async_test
async def test_state_request_snapshot_rate_limited_per_origin():
    # Regression: the request's origin field is unsigned and spoofable,
    # and the snapshot record is heavy — a spray of forged requests must
    # not amplify snapshot traffic at the accused origin (at most one
    # attachment per origin per tick; plain frontier replies still flow).
    _, frontier, child, cert = _proof()
    raw = encode_snapshot(frontier, child, cert)
    ss = StateSync(keys()[0][0], consensus_committee(9360), 100)
    core = _ServeCore(_CountingStore(raw), frontier.digest())
    ss._core = core
    origin = keys()[1][0]
    await ss.handle_state_request((0, origin))
    await ss.handle_state_request((0, origin))
    await ss.handle_state_request((0, origin))
    assert core.store.meta_reads == 1  # snapshot attached once this tick
    assert len(core.sent) == 3  # every request still gets a frontier reply
    ss._tick_no += 1  # next probe window
    await ss.handle_state_request((0, origin))
    assert core.store.meta_reads == 2


# -- frontier-availability checker ------------------------------------------


def _schedule(nodes=("n0", "n1", "n2", "n3")):
    from hotstuff_tpu.faultline.policy import Schedule

    return Schedule(scenario="t", seed=0, nodes=list(nodes))


def test_frontier_availability_ok_via_resolvers():
    from hotstuff_tpu.faultline.checker import check_frontier_availability

    committed = {(1, b"a"), (2, b"b")}
    resolvers = {b"a": {"n0", "n1"}, b"b": {"n0", "n1", "n2"}}
    verdict = check_frontier_availability(_schedule(), committed, resolvers, {})
    assert verdict["ok"] and verdict["required_servers"] == 2
    assert verdict["checked"] == 2 and verdict["violations"] == []


def test_frontier_availability_snapshot_floor_serves_truncated_block():
    from hotstuff_tpu.faultline.checker import check_frontier_availability

    # Block at round 5 resolvable only at n0; n1 truncated it but its
    # snapshot floor (>= 5) subsumes it — still two servers.
    committed = {(5, b"x")}
    verdict = check_frontier_availability(
        _schedule(), committed, {b"x": {"n0"}}, {"n1": 7}
    )
    assert verdict["ok"]
    # A floor BELOW the block's round does not serve it.
    verdict = check_frontier_availability(
        _schedule(), committed, {b"x": {"n0"}}, {"n1": 4}
    )
    assert not verdict["ok"]
    assert verdict["violations"][0]["type"] == "unservable_commit"


def test_frontier_availability_excludes_byzantine_servers():
    from hotstuff_tpu.faultline.checker import check_frontier_availability
    from hotstuff_tpu.faultline.policy import FaultEvent

    sched = _schedule()
    sched.events.append(
        FaultEvent(at=0.0, kind="byzantine", params={"node": "n1", "behavior": "equivocate"})
    )
    committed = {(3, b"y")}
    # Only byzantine n1 plus honest n0 resolve it: one honest server < f+1.
    verdict = check_frontier_availability(
        sched, committed, {b"y": {"n0", "n1"}}, {}
    )
    assert not verdict["ok"]


# -- Watchtower sync_stall detector -----------------------------------------


def _sync_snapshot(ts, node, pid, active, gap):
    return {
        "schema": "hotstuff-telemetry-v1",
        "node": node,
        "pid": pid,
        "seq": 0,
        "ts": ts,
        "final": False,
        "counters": {},
        "gauges": {"statesync.active": active, "statesync.frontier_gap": gap},
        "histograms": {},
    }


def test_sync_stall_fires_when_gap_never_closes():
    from hotstuff_tpu.telemetry.watchtower import Watchtower, WatchtowerConfig

    watch = Watchtower(WatchtowerConfig(sync_stall_budget_s=20.0))
    fired = []
    for i in range(6):
        fired += watch.ingest_record(
            _sync_snapshot(i * 5.0, "n3", 7, active=1, gap=40), source="s"
        )
    alerts = [a for a in fired if a["detector"] == "sync_stall"]
    assert alerts and alerts[0]["accused"] == ["n3"]
    assert alerts[0]["evidence"]["frontier_gap"] == 40


def test_sync_stall_quiet_while_gap_shrinks():
    from hotstuff_tpu.telemetry.watchtower import Watchtower, WatchtowerConfig

    watch = Watchtower(WatchtowerConfig(sync_stall_budget_s=20.0))
    fired = []
    for i, gap in enumerate([64, 48, 32, 16, 9, 8]):
        fired += watch.ingest_record(
            _sync_snapshot(i * 5.0, "n3", 7, active=1, gap=gap), source="s"
        )
    assert [a for a in fired if a["detector"] == "sync_stall"] == []


def test_sync_stall_resets_on_restart_and_inactive():
    from hotstuff_tpu.telemetry.watchtower import Watchtower, WatchtowerConfig

    watch = Watchtower(WatchtowerConfig(sync_stall_budget_s=20.0))
    fired = []
    # Stalled under pid 7, but the node restarts (pid 9) before the budget:
    # the anchor must reset, not accumulate across lives.
    fired += watch.ingest_record(_sync_snapshot(0.0, "n3", 7, 1, 40), "s")
    fired += watch.ingest_record(_sync_snapshot(15.0, "n3", 9, 1, 40), "s")
    fired += watch.ingest_record(_sync_snapshot(25.0, "n3", 9, 1, 40), "s")
    assert [a for a in fired if a["detector"] == "sync_stall"] == []
    # Sync completing (active=0) clears the anchor too.
    fired += watch.ingest_record(_sync_snapshot(30.0, "n3", 9, 0, 0), "s")
    fired += watch.ingest_record(_sync_snapshot(50.0, "n3", 9, 0, 0), "s")
    assert [a for a in fired if a["detector"] == "sync_stall"] == []


def test_sync_stall_ignores_small_gaps():
    from hotstuff_tpu.telemetry.watchtower import Watchtower, WatchtowerConfig

    watch = Watchtower(WatchtowerConfig(sync_stall_budget_s=20.0, sync_stall_min_gap=8))
    fired = []
    for i in range(8):
        fired += watch.ingest_record(
            _sync_snapshot(i * 5.0, "n3", 7, active=1, gap=3), source="s"
        )
    assert [a for a in fired if a["detector"] == "sync_stall"] == []
