"""Node composition-root tests: config file roundtrips and a full 4-node
in-process system driving real client transactions through mempool +
consensus to the commit stream (the reference's `node deploy` testbed shape,
``node/src/main.rs:103-163``)."""

import asyncio

import pytest

from hotstuff_tpu.consensus import Authority as CAuth
from hotstuff_tpu.consensus import Committee as CCommittee
from hotstuff_tpu.consensus import Parameters as CParams
from hotstuff_tpu.mempool import Authority as MAuth
from hotstuff_tpu.mempool import Committee as MCommittee
from hotstuff_tpu.mempool import Parameters as MParams
from hotstuff_tpu.network.receiver import write_frame
from hotstuff_tpu.node import Committee, Node, Parameters, Secret
from hotstuff_tpu.node.config import ConfigError

from .common import async_test

BASE = 15000


def _write_testbed(tmp_path, base_port, n=4):
    secrets = [Secret.new() for _ in range(n)]
    consensus = CCommittee(
        authorities={
            s.name: CAuth(stake=1, address=("127.0.0.1", base_port + i))
            for i, s in enumerate(secrets)
        }
    )
    mempool = MCommittee(
        authorities={
            s.name: MAuth(
                stake=1,
                transactions_address=("127.0.0.1", base_port + 100 + i),
                mempool_address=("127.0.0.1", base_port + 200 + i),
            )
            for i, s in enumerate(secrets)
        }
    )
    committee_file = str(tmp_path / "committee.json")
    Committee(consensus, mempool).write(committee_file)
    params_file = str(tmp_path / "parameters.json")
    Parameters(
        CParams(timeout_delay=2_000),
        MParams(batch_size=200, max_batch_delay=50),
    ).write(params_file)
    key_files = []
    for i, s in enumerate(secrets):
        kf = str(tmp_path / f"node_{i}.json")
        s.write(kf)
        key_files.append(kf)
    return committee_file, params_file, key_files


def test_config_roundtrips(tmp_path):
    committee_file, params_file, key_files = _write_testbed(tmp_path, BASE)
    committee = Committee.read(committee_file)
    assert committee.consensus.size() == 4
    assert committee.mempool.quorum_threshold() == 3
    params = Parameters.read(params_file)
    assert params.consensus.timeout_delay == 2_000
    assert params.mempool.batch_size == 200
    secret = Secret.read(key_files[0])
    assert secret.name in committee.consensus.authorities


def test_config_errors(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    with pytest.raises(ConfigError):
        Committee.read(str(bad))
    with pytest.raises(ConfigError):
        Secret.read(str(tmp_path / "missing.json"))


@async_test
async def test_four_nodes_commit_client_transactions(tmp_path):
    """Boot 4 full nodes in-process, submit real transactions over TCP, and
    assert a block carrying them commits on every node."""
    committee_file, params_file, key_files = _write_testbed(tmp_path, BASE + 10)
    nodes = []
    for i, kf in enumerate(key_files):
        node = await Node.new(
            committee_file,
            kf,
            str(tmp_path / f"db_{i}"),
            parameters_file=params_file,
        )
        nodes.append(node)

    # Submit transactions to node 0's transactions port (size > batch_size
    # forces an immediate seal).
    _, writer = await asyncio.open_connection("127.0.0.1", BASE + 10 + 100)
    tx = b"\x01" + (7).to_bytes(8, "big") + b"\xab" * 300
    write_frame(writer, tx)
    await writer.drain()

    from .common import next_payload_commit

    blocks = await asyncio.wait_for(
        asyncio.gather(*[next_payload_commit(n) for n in nodes]), 30
    )
    digests = {b.digest() for b in blocks}
    assert len(digests) == 1, "nodes committed different payload blocks"
    assert len(blocks[0].payload) >= 1

    # The committed payload digest resolves to the stored batch containing
    # our transaction.
    from hotstuff_tpu.mempool.messages import decode

    batch_bytes = await nodes[0].store.read(blocks[0].payload[0].data)
    kind, txs = decode(batch_bytes)
    assert kind == "batch" and tx in txs

    writer.close()
    for n in nodes:
        await n.shutdown()
