"""Network tests — coverage modeled on the reference network crate tests:
receiver dispatch, simple/reliable send + broadcast, and reliable retry
(send before any listener exists; listener comes up later; ACK still
arrives — reference ``network/src/tests/reliable_sender_tests.rs:50-67``)."""

import asyncio

from hotstuff_tpu.network import (
    MessageHandler,
    Receiver,
    ReliableSender,
    SimpleSender,
)
from hotstuff_tpu.network.receiver import read_frame, write_frame

from .common import async_test, listener

BASE_PORT = 17000  # distinct per-test ports, like the reference fixtures


class _EchoHandler(MessageHandler):
    def __init__(self):
        self.received = []

    async def dispatch(self, writer, message: bytes) -> None:
        self.received.append(message)
        await writer.send(b"Ack")


@async_test
async def test_receiver_dispatch():
    handler = _EchoHandler()
    receiver = await Receiver.spawn(("127.0.0.1", BASE_PORT), handler)
    reader, writer = await asyncio.open_connection("127.0.0.1", BASE_PORT)
    write_frame(writer, b"hello")
    await writer.drain()
    assert await read_frame(reader) == b"Ack"
    write_frame(writer, b"again")
    await writer.drain()
    assert await read_frame(reader) == b"Ack"
    assert handler.received == [b"hello", b"again"]
    writer.close()
    await receiver.shutdown()


@async_test
async def test_simple_send():
    addr = ("127.0.0.1", BASE_PORT + 1)
    task = asyncio.create_task(listener(BASE_PORT + 1, expected=b"payload"))
    await asyncio.sleep(0.05)
    sender = SimpleSender()
    sender.send(addr, b"payload")
    assert await task == b"payload"
    sender.shutdown()


@async_test
async def test_simple_broadcast():
    ports = [BASE_PORT + 2 + i for i in range(3)]
    tasks = [asyncio.create_task(listener(p, expected=b"bcast")) for p in ports]
    await asyncio.sleep(0.05)
    sender = SimpleSender()
    sender.broadcast([("127.0.0.1", p) for p in ports], b"bcast")
    assert await asyncio.gather(*tasks) == [b"bcast"] * 3
    sender.shutdown()


@async_test
async def test_reliable_send_resolves_with_ack():
    port = BASE_PORT + 10
    task = asyncio.create_task(listener(port, expected=b"important"))
    await asyncio.sleep(0.05)
    sender = ReliableSender()
    handler = await sender.send(("127.0.0.1", port), b"important")
    assert await asyncio.wait_for(handler, 5) == b"Ack"
    await task
    sender.shutdown()


@async_test
async def test_reliable_broadcast():
    ports = [BASE_PORT + 11 + i for i in range(3)]
    tasks = [asyncio.create_task(listener(p)) for p in ports]
    await asyncio.sleep(0.05)
    sender = ReliableSender()
    handlers = await sender.broadcast([("127.0.0.1", p) for p in ports], b"rb")
    acks = await asyncio.gather(*handlers)
    assert acks == [b"Ack"] * 3
    await asyncio.gather(*tasks)
    sender.shutdown()


@async_test
async def test_reliable_retry_before_listener_exists():
    """The at-least-once contract: the message is sent while nobody is
    listening; the listener appears later; the ACK still arrives."""
    port = BASE_PORT + 20
    sender = ReliableSender()
    handler = await sender.send(("127.0.0.1", port), b"retry-me")
    await asyncio.sleep(0.4)  # let at least one connect attempt fail
    assert not handler.done()
    payload = await asyncio.wait_for(
        asyncio.gather(listener(port, expected=b"retry-me"), handler), 15
    )
    assert payload[1] == b"Ack"
    sender.shutdown()


@async_test
async def test_reliable_replays_unacked_on_reconnect():
    """A connection that dies before ACKing: the message must be replayed to
    the next listener on the same address."""
    port = BASE_PORT + 21

    # First listener: accepts, reads the frame, then hangs up WITHOUT acking.
    got_first = asyncio.get_running_loop().create_future()

    async def rude(reader, writer):
        frame = await read_frame(reader)
        if not got_first.done():
            got_first.set_result(frame)
        writer.close()

    server = await asyncio.start_server(rude, "127.0.0.1", port)
    sender = ReliableSender()
    handler = await sender.send(("127.0.0.1", port), b"replay-me")
    assert await asyncio.wait_for(got_first, 5) == b"replay-me"
    server.close()
    await server.wait_closed()
    assert not handler.done()

    # Second listener on the same port ACKs properly.
    result = await asyncio.wait_for(
        asyncio.gather(listener(port, expected=b"replay-me"), handler), 15
    )
    assert result[1] == b"Ack"
    sender.shutdown()


@async_test
async def test_reliable_lucky_broadcast():
    ports = [BASE_PORT + 30 + i for i in range(4)]
    tasks = [asyncio.create_task(listener(p)) for p in ports]
    await asyncio.sleep(0.05)
    sender = ReliableSender()
    handlers = await sender.lucky_broadcast(
        [("127.0.0.1", p) for p in ports], b"lucky", 2
    )
    assert len(handlers) == 2
    acks = await asyncio.gather(*[asyncio.wait_for(h, 5) for h in handlers])
    assert acks == [b"Ack"] * 2
    for t in tasks:
        t.cancel()
    sender.shutdown()


@async_test
async def test_reliable_send_backpressures_never_drops():
    """A live but SLOW peer must DELAY the sender, not lose messages
    (reference ``reliable_sender.rs:60-72`` awaits channel capacity): with
    the peer's socket stalled and the per-peer queue full, ``send`` blocks
    until the peer drains, and every message is still delivered in order."""
    import hotstuff_tpu.network.reliable_sender as rs

    port = BASE_PORT + 23
    orig = rs.QUEUE_CAPACITY
    orig_cap = rs.PENDING_CAP
    rs.QUEUE_CAPACITY = 2
    rs.PENDING_CAP = 2
    payload = bytes(4 * 1024 * 1024)  # exceeds loopback socket buffers
    try:
        start_reading = asyncio.Event()
        received: list[int] = []

        async def stalled_then_drain(reader, writer):
            await start_reading.wait()
            while True:
                frame = await read_frame(reader)
                received.append(len(frame))
                write_frame(writer, b"Ack")
                await writer.drain()

        server = await asyncio.start_server(
            stalled_then_drain, "127.0.0.1", port
        )
        sender = ReliableSender()
        addr = ("127.0.0.1", port)
        handlers = []
        # Fill the peer's TCP buffers and the per-peer queue: some send
        # must eventually block (back-pressure) instead of dropping.
        blocked_at = None
        for i in range(10):
            task = asyncio.create_task(sender.send(addr, payload))
            done, _ = await asyncio.wait({task}, timeout=0.5)
            if not done:
                blocked_at = i
                break
            handlers.append(task.result())
        assert blocked_at is not None, "sender never back-pressured"
        # The peer starts draining: the blocked send completes and every
        # message (including the back-pressured one) is ACKed.
        start_reading.set()
        handlers.append(await asyncio.wait_for(task, 30))
        acks = await asyncio.wait_for(asyncio.gather(*handlers), 60)
        assert acks == [b"Ack"] * (blocked_at + 1)
        assert received == [len(payload)] * (blocked_at + 1)
        sender.shutdown()
        server.close()
    finally:
        rs.QUEUE_CAPACITY = orig
        rs.PENDING_CAP = orig_cap


@async_test
async def test_reliable_backpressure_counts_unacked_inflight():
    """A CONNECTED peer that reads frames but withholds ACKs must still
    back-pressure the sender at PENDING_CAP live messages: capacity is
    measured in un-ACKed messages, not just not-yet-written ones."""
    import hotstuff_tpu.network.reliable_sender as rs

    port = BASE_PORT + 27
    orig_q, orig_cap = rs.QUEUE_CAPACITY, rs.PENDING_CAP
    rs.QUEUE_CAPACITY = 100  # queue must NOT be the binding constraint
    rs.PENDING_CAP = 3
    try:
        release = asyncio.Event()
        frames_before_release = 0
        unacked = 0
        peer_writer: list = []

        async def read_but_withhold_acks(reader, writer):
            nonlocal frames_before_release, unacked
            peer_writer.append(writer)
            while True:
                await read_frame(reader)  # consume eagerly: no TCP pressure
                if release.is_set():
                    write_frame(writer, b"Ack")
                    await writer.drain()
                else:
                    frames_before_release += 1
                    unacked += 1

        server = await asyncio.start_server(
            read_but_withhold_acks, "127.0.0.1", port
        )
        sender = ReliableSender()
        addr = ("127.0.0.1", port)
        tasks = [
            asyncio.create_task(sender.send(addr, b"m%d" % i)) for i in range(8)
        ]
        await asyncio.sleep(1.0)
        conn = sender._connections[addr]
        assert conn.live <= rs.PENDING_CAP, "live cap exceeded"
        assert frames_before_release <= rs.PENDING_CAP, (
            "peer received more than CAP un-ACKed frames"
        )
        # The peer flushes the withheld ACKs and ACKs everything further:
        # the stalled sends unblock and all eight messages resolve.
        release.set()
        for _ in range(unacked):
            write_frame(peer_writer[-1], b"Ack")
        await peer_writer[-1].drain()
        handlers = await asyncio.wait_for(asyncio.gather(*tasks), 30)
        acks = await asyncio.wait_for(asyncio.gather(*handlers), 30)
        assert acks == [b"Ack"] * 8
        sender.shutdown()
        server.close()
    finally:
        rs.QUEUE_CAPACITY = orig_q
        rs.PENDING_CAP = orig_cap


@async_test
async def test_reliable_send_to_stalled_peer_cancellation_frees_capacity():
    """A byzantine peer that ACCEPTS but never reads must not wedge
    senders that give up: cancelling handlers reclaims buffer capacity,
    so a back-pressured send completes once older messages are cancelled
    (this is where the design is deliberately stricter than the
    reference, whose channel only drains while disconnected)."""
    import hotstuff_tpu.network.reliable_sender as rs

    port = BASE_PORT + 25
    orig_q, orig_cap = rs.QUEUE_CAPACITY, rs.PENDING_CAP
    rs.QUEUE_CAPACITY = 2
    rs.PENDING_CAP = 2
    payload = bytes(4 * 1024 * 1024)
    try:
        server = await asyncio.start_server(
            lambda r, w: asyncio.sleep(3600), "127.0.0.1", port
        )
        sender = ReliableSender()
        addr = ("127.0.0.1", port)
        granted = []
        blocked = None
        for _ in range(10):
            task = asyncio.create_task(sender.send(addr, payload))
            done, _ = await asyncio.wait({task}, timeout=0.5)
            if not done:
                blocked = task
                break
            granted.append(task.result())
        assert blocked is not None, "stalled peer never back-pressured"
        # The proposer's pattern: quorum reached elsewhere, give up on the
        # stalled peer. Capacity must come back and unblock the send.
        for h in granted:
            h.cancel()
        handler = await asyncio.wait_for(blocked, 5)
        handler.cancel()
        later = await asyncio.wait_for(sender.send(addr, payload), 5)
        later.cancel()
        sender.shutdown()
        server.close()
    finally:
        rs.QUEUE_CAPACITY = orig_q
        rs.PENDING_CAP = orig_cap


@async_test
async def test_reliable_send_to_dead_peer_does_not_block_forever():
    """Back-pressure must come from a SLOW live peer, not a dead one: while
    disconnected the connection task drains its queue into the replay
    buffer (pruning cancelled messages), so a crashed replica cannot wedge
    the proposer's broadcast loop (reference ``reliable_sender.rs:160-177``)."""
    import hotstuff_tpu.network.reliable_sender as rs

    port = BASE_PORT + 24  # nothing ever listens here
    orig = rs.QUEUE_CAPACITY
    rs.QUEUE_CAPACITY = 2
    try:
        sender = ReliableSender()
        addr = ("127.0.0.1", port)
        # 3x the queue capacity: every send must still complete promptly.
        handlers = []
        for i in range(6):
            handlers.append(
                await asyncio.wait_for(sender.send(addr, b"m%d" % i), 10)
            )
        # Cancelling handlers must also free buffered slots for later sends.
        for h in handlers:
            h.cancel()
        await asyncio.wait_for(sender.send(addr, b"after-cancel"), 10)
        sender.shutdown()
    finally:
        rs.QUEUE_CAPACITY = orig


@async_test
async def test_cancelled_handler_skips_replay():
    port = BASE_PORT + 22
    sender = ReliableSender()
    h1 = await sender.send(("127.0.0.1", port), b"cancelled")
    h2 = await sender.send(("127.0.0.1", port), b"kept")
    h1.cancel()
    await asyncio.sleep(0.3)
    payload, ack = await asyncio.wait_for(
        asyncio.gather(listener(port, expected=b"kept"), h2), 15
    )
    assert ack == b"Ack"
    sender.shutdown()


@async_test
async def test_connection_budget_evicts_idle_simple_connections():
    """Above the process fd budget, idle SimpleSender connections are
    closed LRU-first; sends to an evicted peer transparently reconnect.
    (The N=100 one-process committee is a ~20k-connection full mesh
    against RLIMIT_NOFILE=20k — without reaping it EMFILE-storms.)"""
    from hotstuff_tpu.network.budget import BUDGET

    ports = [BASE_PORT + 40 + i for i in range(6)]
    handlers = []
    receivers = []
    for p in ports:
        h = _EchoHandler()
        handlers.append(h)
        receivers.append(await Receiver.spawn(("127.0.0.1", p), h))

    old_cap = BUDGET.cap
    BUDGET.cap = 3
    sender = SimpleSender()
    try:
        for p in ports:
            sender.send(("127.0.0.1", p), b"m1")
        await asyncio.sleep(0.3)
        assert len(BUDGET) <= 3, "budget must reap down to cap"
        assert BUDGET.evictions >= 3
        # The first (LRU) peers were evicted; a new send must still arrive.
        sender.send(("127.0.0.1", ports[0]), b"m2")
        await asyncio.sleep(0.3)
        assert handlers[0].received == [b"m1", b"m2"]
    finally:
        BUDGET.cap = old_cap
        sender.shutdown()
        for r in receivers:
            await r.shutdown()


@async_test
async def test_connection_budget_never_evicts_unacked_reliable():
    """A ReliableSender connection with an un-ACKed (live) message is
    pinned: the at-least-once contract survives budget pressure. Idle
    (fully-ACKed) reliable connections are evicted and reconnect on the
    next send."""
    from hotstuff_tpu.network.budget import BUDGET

    dead_port = BASE_PORT + 50  # no listener: message stays live forever
    live_ports = [BASE_PORT + 51 + i for i in range(4)]
    handlers_srv = []
    receivers = []
    for p in live_ports:
        h = _EchoHandler()
        handlers_srv.append(h)
        receivers.append(await Receiver.spawn(("127.0.0.1", p), h))

    old_cap = BUDGET.cap
    BUDGET.cap = 2
    sender = ReliableSender()
    try:
        pinned = await sender.send(("127.0.0.1", dead_port), b"must-not-drop")
        acked = []
        for p in live_ports:
            acked.append(await sender.send(("127.0.0.1", p), b"ok"))
        for h in acked:
            assert await asyncio.wait_for(h, 5) == b"Ack"
        await asyncio.sleep(0.2)
        conns = sender._connections
        assert not conns[("127.0.0.1", dead_port)].evicted, (
            "live (un-ACKed) connection must never be evicted"
        )
        assert not pinned.done()
        # Evicted idle peer still reachable through a fresh connection.
        evicted_port = next(
            p for p in live_ports if conns[("127.0.0.1", p)].evicted
        )
        h2 = await sender.send(("127.0.0.1", evicted_port), b"again")
        assert await asyncio.wait_for(h2, 5) == b"Ack"
        pinned.cancel()
    finally:
        BUDGET.cap = old_cap
        sender.shutdown()
        for r in receivers:
            await r.shutdown()


@async_test
async def test_connection_budget_reclaims_dead_peer_after_cancellation():
    """A connection to a crashed peer whose only message was CANCELLED
    (proposer reached 2f+1 ACKs elsewhere) must become evictable: its
    _run never executes, so only evictable() can prune the dead entry.
    Otherwise dead-peer connections are exempt from the fd budget in
    exactly the timeout-storm regime it exists for."""
    dead_port = BASE_PORT + 60  # nothing listens
    sender = ReliableSender()
    handler = await sender.send(("127.0.0.1", dead_port), b"doomed")
    await asyncio.sleep(0.3)  # pump seats it in pending; connect keeps failing
    conn = sender._connections[("127.0.0.1", dead_port)]
    assert not conn.evictable(), "un-cancelled message must pin the connection"
    handler.cancel()
    await asyncio.sleep(0.05)  # let the done-callback drop live to 0
    assert conn.evictable(), "cancelled-only pending must not pin a dead peer"
    sender.shutdown()
