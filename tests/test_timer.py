"""Resettable timer tests (reference ``consensus/src/tests/timer_tests.rs``)."""

import asyncio
import time

from hotstuff_tpu.consensus.timer import Timer

from .common import async_test


@async_test
async def test_timer_fires_after_duration():
    t = Timer(50)
    start = time.monotonic()
    await t.wait()
    assert time.monotonic() - start >= 0.045


@async_test
async def test_reset_postpones_firing():
    t = Timer(80)
    start = time.monotonic()
    task = asyncio.create_task(t.wait())
    await asyncio.sleep(0.05)
    t.reset()  # pushes deadline to start+0.05+0.08
    await task
    assert time.monotonic() - start >= 0.12
