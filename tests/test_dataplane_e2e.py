"""Conveyor e2e: a full 4-node in-process committee with worker shards —
client bundles flow worker ingress → batch dissemination → availability
cert → consensus (ordering certified digests) → commit-path resolution,
and every node ends the round-trip holding both the batch and its
certificate."""

import asyncio

from hotstuff_tpu.consensus import Authority as CAuth
from hotstuff_tpu.consensus import Committee as CCommittee
from hotstuff_tpu.consensus import Parameters as CParams
from hotstuff_tpu.mempool import Authority as MAuth
from hotstuff_tpu.mempool import Committee as MCommittee
from hotstuff_tpu.mempool import Parameters as MParams
from hotstuff_tpu.mempool import WorkerEntry
from hotstuff_tpu.mempool.dataplane import (
    AvailabilityCert,
    WorkerSeatTable,
    cert_key,
)
from hotstuff_tpu.mempool.dataplane import messages as dpm
from hotstuff_tpu.network.receiver import write_frame
from hotstuff_tpu.node import Committee, Node, Parameters, Secret

from .common import async_test, next_payload_commit

BASE = 31700


def _write_worker_testbed(tmp_path, base_port, n=4, workers=1):
    secrets = [Secret.new() for _ in range(n)]
    consensus = CCommittee(
        authorities={
            s.name: CAuth(stake=1, address=("127.0.0.1", base_port + i))
            for i, s in enumerate(secrets)
        }
    )
    mempool = MCommittee(
        authorities={
            s.name: MAuth(
                stake=1,
                transactions_address=("127.0.0.1", base_port + 20 + i),
                mempool_address=("127.0.0.1", base_port + 40 + i),
                workers=[
                    WorkerEntry(
                        transactions_address=(
                            "127.0.0.1",
                            base_port + 60 + 20 * w + i,
                        ),
                        worker_address=(
                            "127.0.0.1",
                            base_port + 160 + 20 * w + i,
                        ),
                    )
                    for w in range(workers)
                ],
            )
            for i, s in enumerate(secrets)
        }
    )
    committee_file = str(tmp_path / "committee.json")
    Committee(consensus, mempool).write(committee_file)
    params_file = str(tmp_path / "parameters.json")
    Parameters(
        CParams(timeout_delay=2_000),
        MParams(batch_size=200, max_batch_delay=50, workers=workers),
    ).write(params_file)
    key_files = []
    for i, s in enumerate(secrets):
        kf = str(tmp_path / f"node_{i}.json")
        s.write(kf)
        key_files.append(kf)
    return committee_file, params_file, key_files


def test_worker_committee_config_roundtrips(tmp_path):
    committee_file, params_file, _ = _write_worker_testbed(
        tmp_path, BASE, workers=2
    )
    committee = Committee.read(committee_file)
    for pk in committee.mempool.authorities:
        entries = committee.mempool.workers_of(pk)
        assert len(entries) == 2
        assert committee.mempool.worker_address(pk, 1) is not None
        assert len(committee.mempool.worker_peers(pk, 0)) == 3
    params = Parameters.read(params_file)
    assert params.mempool.workers == 2
    assert params.mempool.store_high_watermark == 256


@async_test(timeout=90)
async def test_four_node_committee_round_trip_over_workers(tmp_path):
    committee_file, params_file, key_files = _write_worker_testbed(
        tmp_path, BASE + 300
    )
    nodes = []
    for i, kf in enumerate(key_files):
        nodes.append(
            await Node.new(
                committee_file,
                kf,
                str(tmp_path / f"db_{i}"),
                parameters_file=params_file,
            )
        )
    assert all(n.mempool.dataplane is not None for n in nodes)
    assert all(n.resolver_task is not None for n in nodes)

    # A client bundle to node 0's worker-0 ingress (crosses batch_size
    # -> immediate seal).
    committee = Committee.read(committee_file)
    name0 = Secret.read(key_files[0]).name
    entry = committee.mempool.workers_of(name0)[0]
    _, writer = await asyncio.open_connection(
        "127.0.0.1", entry.transactions_address[1]
    )
    payload_tx = b"\x00" + (7).to_bytes(8, "big") + b"\xab" * 250
    write_frame(writer, dpm.encode_bundle([payload_tx]))
    await writer.drain()

    blocks = await asyncio.wait_for(
        asyncio.gather(*[next_payload_commit(n) for n in nodes]), 60
    )
    digests = {b.digest() for b in blocks}
    assert len(digests) == 1, "nodes committed different payload blocks"
    batch_digest = blocks[0].payload[0]

    # Commit-path resolution: after the resolver releases the block,
    # EVERY node's store materializes the batch...
    seats = WorkerSeatTable.for_committee(committee.mempool)
    for node in nodes:
        raw = await asyncio.wait_for(
            node.store.notify_read(batch_digest.data), 20
        )
        wid, n_txs, samples, blob = dpm.decode_worker_batch(raw)
        assert payload_tx in dpm.split_blob(blob)
        assert samples == [7]
    # ...and the availability certificate that let consensus order it is
    # present and valid wherever it was needed (author formed it, peers
    # received the broadcast).
    certs_seen = 0
    for node in nodes:
        cert_bytes = await node.store.read(cert_key(batch_digest.data))
        if cert_bytes is None:
            continue
        cert = AvailabilityCert.decode(cert_bytes, seats)
        assert cert.digest == batch_digest
        cert.verify(committee.mempool)
        certs_seen += 1
    assert certs_seen >= 3  # author + at least the cert-broadcast majority

    writer.close()
    for n in nodes:
        await n.shutdown()
