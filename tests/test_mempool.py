"""Mempool tests — one per actor plus a whole-mempool test, modeled on the
reference (``mempool/src/tests/``): batch sealing by size and by timer,
quorum ACK counting, processor hash+store+forward, sync request emission,
batch serving, and client txs driven through to the consensus digest
channel."""

import asyncio

from hotstuff_tpu.crypto import sha512_digest
from hotstuff_tpu.mempool import Cleanup, Mempool, Parameters, Synchronize
from hotstuff_tpu.mempool.batch_maker import BatchMaker
from hotstuff_tpu.mempool.helper import Helper
from hotstuff_tpu.mempool.messages import decode, encode_batch
from hotstuff_tpu.mempool.processor import Processor
from hotstuff_tpu.mempool.quorum_waiter import QuorumWaiter, QuorumWaiterMessage
from hotstuff_tpu.mempool.synchronizer import Synchronizer
from hotstuff_tpu.network.receiver import read_frame, write_frame
from hotstuff_tpu.store import Store

from .common import async_test, keys, listener, mempool_committee

BASE = 12000


def tx(sample_id: int | None = None, size: int = 100) -> bytes:
    """A transaction: sample txs start with 0 + u64 BE id (reference
    ``node/src/client.rs:107-121``)."""
    if sample_id is not None:
        return b"\x00" + sample_id.to_bytes(8, "big") + b"\x01" * (size - 9)
    return b"\x01" * size


@async_test
async def test_batch_maker_seals_by_size():
    committee = mempool_committee(BASE)
    name = keys()[0][0]
    rx_tx, tx_msg = asyncio.Queue(), asyncio.Queue()
    peers = committee.broadcast_addresses(name)
    listeners = [
        asyncio.create_task(listener(addr[1])) for _, addr in peers
    ]
    await asyncio.sleep(0.05)
    BatchMaker.spawn(200, 10_000, rx_tx, tx_msg, peers)
    await rx_tx.put(tx(size=150))
    await rx_tx.put(tx(size=150))  # 300 B >= 200 B -> seal now, not at timer
    msg: QuorumWaiterMessage = await asyncio.wait_for(tx_msg.get(), 2)
    kind, txs = decode(msg.batch)
    assert kind == "batch" and len(txs) == 2
    assert len(msg.handlers) == 3
    # All peers got the exact serialized batch.
    frames = await asyncio.gather(*listeners)
    assert frames == [msg.batch] * 3


@async_test
async def test_batch_maker_seals_by_timer():
    committee = mempool_committee(BASE + 10)
    name = keys()[0][0]
    rx_tx, tx_msg = asyncio.Queue(), asyncio.Queue()
    peers = committee.broadcast_addresses(name)
    listeners = [asyncio.create_task(listener(addr[1])) for _, addr in peers]
    await asyncio.sleep(0.05)
    BatchMaker.spawn(1_000_000, 50, rx_tx, tx_msg, peers)  # 50ms delay
    await rx_tx.put(tx(size=10))
    msg = await asyncio.wait_for(tx_msg.get(), 2)
    kind, txs = decode(msg.batch)
    assert kind == "batch" and len(txs) == 1
    await asyncio.gather(*listeners)


@async_test
async def test_quorum_waiter_forwards_at_threshold():
    committee = mempool_committee(BASE + 20)
    name = keys()[0][0]
    rx_msg, tx_batch = asyncio.Queue(), asyncio.Queue()
    QuorumWaiter.spawn(committee, name, rx_msg, tx_batch)
    loop = asyncio.get_running_loop()
    handlers = [(pk, loop.create_future()) for pk, _ in keys()[1:]]
    await rx_msg.put(QuorumWaiterMessage(b"serialized-batch", handlers))
    await asyncio.sleep(0.05)
    assert tx_batch.empty()  # own stake 1 < threshold 3
    handlers[0][1].set_result(b"Ack")
    await asyncio.sleep(0.05)
    assert tx_batch.empty()  # 2 < 3
    handlers[1][1].set_result(b"Ack")
    batch = await asyncio.wait_for(tx_batch.get(), 2)
    assert batch == b"serialized-batch"


@async_test
async def test_processor_hashes_stores_forwards():
    store = Store()
    rx_batch, tx_digest = asyncio.Queue(), asyncio.Queue()
    Processor.spawn(store, rx_batch, tx_digest)
    batch = encode_batch([tx(size=20)])
    await rx_batch.put(batch)
    digest = await asyncio.wait_for(tx_digest.get(), 2)
    assert digest == sha512_digest(batch)
    assert await store.read(digest.data) == batch


@async_test
async def test_processor_device_digests_drain_queue():
    """device_digests=True: concurrently-pending batches are hashed in one
    device call (bit-exact vs host SHA-512/32) and every digest/store write
    still lands (BASELINE config 3 wiring)."""
    store = Store()
    rx_batch, tx_digest = asyncio.Queue(), asyncio.Queue()
    batches = [encode_batch([tx(size=20 + i)]) for i in range(5)]
    for b in batches:
        rx_batch.put_nowait(b)
    Processor.spawn(store, rx_batch, tx_digest, device_digests=True)
    got = [await asyncio.wait_for(tx_digest.get(), 10) for _ in batches]
    assert got == [sha512_digest(b) for b in batches]
    for b, d in zip(batches, got):
        assert await store.read(d.data) == b


@async_test
async def test_processor_device_digests_single_batch_host_path():
    store = Store()
    rx_batch, tx_digest = asyncio.Queue(), asyncio.Queue()
    Processor.spawn(store, rx_batch, tx_digest, device_digests=True)
    batch = encode_batch([tx(size=33)])
    await rx_batch.put(batch)
    digest = await asyncio.wait_for(tx_digest.get(), 5)
    assert digest == sha512_digest(batch)


@async_test
async def test_synchronizer_emits_batch_request():
    committee = mempool_committee(BASE + 30)
    (name, _), (target, _) = keys()[0], keys()[1]
    store = Store()
    rx_msg = asyncio.Queue()
    Synchronizer.spawn(name, committee, store, 50, 5_000, 3, rx_msg)
    missing = sha512_digest(b"missing-batch")
    target_addr = committee.mempool_address(target)
    task = asyncio.create_task(listener(target_addr[1]))
    await asyncio.sleep(0.05)
    await rx_msg.put(Synchronize([missing], target))
    frame = await asyncio.wait_for(task, 3)
    kind, (digests, requestor) = decode(frame)
    assert kind == "batch_request"
    assert digests == [missing] and requestor == name


@async_test
async def test_synchronizer_cleanup_cancels_old_waiters():
    committee = mempool_committee(BASE + 40)
    name, target = keys()[0][0], keys()[1][0]
    store = Store()
    rx_msg = asyncio.Queue()
    sync = Synchronizer(name, committee, store, 10, 5_000, 3, rx_msg)
    task = asyncio.create_task(sync._run())
    target_addr = committee.mempool_address(target)
    lst = asyncio.create_task(listener(target_addr[1]))
    await asyncio.sleep(0.05)
    await rx_msg.put(Synchronize([sha512_digest(b"old")], target))
    await lst
    assert len(sync.pending) == 1
    await rx_msg.put(Cleanup(100))  # round 100, gc_depth 10 -> gc everything <= 90
    await asyncio.sleep(0.1)
    assert len(sync.pending) == 0
    task.cancel()


def test_synchronizer_retry_rearms_per_delay():
    """A retried request re-arms for a full sync_retry_delay: subsequent
    ticks inside the window do NOT re-broadcast (the consensus-side PR 10
    fix, aligned here)."""
    s = Synchronizer.__new__(Synchronizer)
    s.sync_retry_delay = 2.0
    d = sha512_digest(b"missing")
    s.pending = {d: (0, None, 0.0)}
    assert s._expired(1.0) == []  # not expired yet
    assert s._expired(2.5) == [d]  # expired: retry once
    # Re-armed: ticks inside the new delay window are quiet.
    assert s._expired(3.0) == []
    assert s._expired(4.0) == []
    assert s._expired(5.0) == [d]  # a full delay later


@async_test
async def test_synchronizer_idle_tick_does_zero_work():
    """With no outstanding requests the timer tick touches neither the
    clock nor the network; once a request expires, exactly one retry
    broadcast goes out per retry window."""
    import hotstuff_tpu.mempool.synchronizer as sync_mod

    committee = mempool_committee(BASE + 70)
    name = keys()[0][0]
    clock_reads = [0]

    def clock():
        clock_reads[0] += 1
        return 1000.0

    sync = Synchronizer(
        name, committee, Store(), 50, 1_000, 3, asyncio.Queue(), clock=clock
    )
    sent = []
    sync.network = type(
        "Net", (), {
            "send": lambda self, a, d: sent.append(("send", a)),
            "lucky_broadcast": lambda self, addrs, d, n: sent.append(
                ("lucky", n)
            ),
        },
    )()
    old = sync_mod.TIMER_RESOLUTION
    sync_mod.TIMER_RESOLUTION = 0.02
    task = asyncio.create_task(sync._run())
    try:
        await asyncio.sleep(0.15)  # several idle ticks
        assert sent == [] and clock_reads[0] == 0
        # One expired request: exactly one re-broadcast per retry window
        # (the clock is frozen, so the re-armed entry never re-expires).
        sync.pending[sha512_digest(b"want")] = (0, None, 0.0)
        await asyncio.sleep(0.15)
        assert sent == [("lucky", 3)], sent
    finally:
        sync_mod.TIMER_RESOLUTION = old
        task.cancel()


@async_test
async def test_helper_serves_batches():
    committee = mempool_committee(BASE + 50)
    name, requestor = keys()[0][0], keys()[1][0]
    store = Store()
    batch = encode_batch([tx(size=30)])
    digest = sha512_digest(batch)
    await store.write(digest.data, batch)
    rx_req = asyncio.Queue()
    Helper.spawn(committee, store, rx_req)
    req_addr = committee.mempool_address(requestor)
    task = asyncio.create_task(listener(req_addr[1]))
    await asyncio.sleep(0.05)
    await rx_req.put(([digest], requestor))
    assert await asyncio.wait_for(task, 3) == batch


@async_test
async def test_whole_mempool_client_tx_to_digest():
    """Drive real client transactions through a full mempool (with 3 fake
    ACKing peers) to the consensus digest channel (reference
    ``mempool_tests.rs:6-46``)."""
    committee = mempool_committee(BASE + 60)
    (name, _) = keys()[0]
    peer_listeners = [
        asyncio.create_task(listener(addr[1]))
        for _, addr in committee.broadcast_addresses(name)
    ]
    await asyncio.sleep(0.05)

    rx_consensus, tx_consensus = asyncio.Queue(), asyncio.Queue()
    params = Parameters(batch_size=100, max_batch_delay=10_000)
    mempool = Mempool(name, committee, params, Store(), rx_consensus, tx_consensus)
    await mempool.spawn()

    # A real client connection to the transactions address.
    tx_addr = committee.transactions_address(name)
    reader, writer = await asyncio.open_connection("127.0.0.1", tx_addr[1])
    payload = tx(sample_id=7, size=120)  # > batch_size -> immediate seal
    write_frame(writer, payload)
    await writer.drain()

    digest = await asyncio.wait_for(tx_consensus.get(), 5)
    batches = await asyncio.gather(*peer_listeners)
    assert all(b == batches[0] for b in batches)
    assert digest == sha512_digest(batches[0])
    kind, txs = decode(batches[0])
    assert kind == "batch" and txs == [payload]
    writer.close()
    await mempool.shutdown()
