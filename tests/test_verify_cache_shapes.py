"""Committee point-cache shape behavior: growth across capacities and
acceptance parity with the v1 (uncached) path on mixed batch sizes.
Split from test_verify_cached.py: these compile EXTRA kernel variants
(new cache capacities, the full v1 graph) and blew the cold-compile
window together with the core path tests."""

import random

import pytest

pytest.importorskip("jax")

pytestmark = pytest.mark.device

from hotstuff_tpu.crypto import ed25519_ref as ref  # noqa: E402
from hotstuff_tpu.ops import verify as v  # noqa: E402


def make_batch(n=3, seed=5):
    rng = random.Random(seed)
    msgs, pubs, sigs = [], [], []
    for _ in range(n):
        seed_bytes = rng.randbytes(32)
        pubs.append(ref.secret_to_public(seed_bytes))
        msgs.append(rng.randbytes(32))
        sigs.append(ref.sign(seed_bytes, msgs[-1]))
    return msgs, pubs, sigs


def test_cache_grows_beyond_initial_capacity():
    cache = v.DevicePointCache(capacity=16)
    msgs, pubs, sigs = make_batch(20, seed=17)
    assert v.verify_batch_device_cached(msgs, pubs, sigs, cache, _rng=random.Random(1))
    assert cache.capacity >= 21
    assert len(cache._rows) == 21


def test_cached_matches_v1_acceptance_on_mixed_batches():
    """Same accept/reject verdicts as the v1 full-decompress path across a
    spread of mutations."""
    rng = random.Random(18)
    for trial in range(4):
        cache = v.DevicePointCache(capacity=64)
        msgs, pubs, sigs = make_batch(3, seed=100 + trial)
        if trial % 2:
            bad = bytearray(sigs[trial % 3])
            bad[trial % 32] ^= 1 << (trial % 8)
            sigs[trial % 3] = bytes(bad)
        v1 = v.verify_batch_device(msgs, pubs, sigs, _rng=random.Random(42))
        v2 = v.verify_batch_device_cached(msgs, pubs, sigs, cache, _rng=random.Random(42))
        assert v1 == v2, f"trial {trial}: v1={v1} v2={v2}"
